"""Ownership-lifecycle passes: flight ops and slab leases.

Two recurring review-finding classes share one shape — an object is
*acquired* (``wf.begin(...)`` → FlightOp, ``pool.lease(...)`` →
SlabLease) and must reach exactly one *close* (``finish``/``abandon``,
``release``) on every path, unless ownership escapes to a caller or a
container.  The generic engine here is deliberately lexical-CFG-lite:
it reasons about assignments, ``with`` blocks, try/except/finally
structure, returns and raises, which is exactly the granularity the
hand reviews operated at (and what three past lease-leak fixes and two
flight-op fixes needed).  Anything subtler belongs in the allowlist
with a justification, not in a cleverer analyzer.
"""

from __future__ import annotations

import ast
from typing import Sequence

from tpubench.analysis.core import (
    AnalysisPass,
    Finding,
    SourceFile,
    call_name,
    dotted,
    iter_functions,
    parent_map,
    uses_name,
)


def _acquire_calls(node: ast.AST, attr: str) -> list[ast.Call]:
    """Every ``<expr>.<attr>(...)`` call under ``node``."""
    return [
        n for n in ast.walk(node)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == attr
    ]


def _closer_calls(fn: ast.AST, var: str, closers: set[str]) -> list[ast.Call]:
    return [
        n for n in ast.walk(fn)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr in closers
        and isinstance(n.func.value, ast.Name)
        and n.func.value.id == var
    ]


def _escapes(fn: ast.AST, var: str, assign: ast.AST) -> bool:
    """Ownership transfer: returned/yielded, stored into an attribute,
    subscript or container, or handed to another call.  After an
    escape the close obligation belongs to the new owner."""
    for n in ast.walk(fn):
        if n is assign:
            continue
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
            if n.value is not None and uses_name(n.value, var):
                return True
        elif isinstance(n, ast.Assign):
            if any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in n.targets
            ) and uses_name(n.value, var):
                return True
        elif isinstance(n, ast.Call):
            # var passed BARE as an argument (cache.put(key, lease),
            # q.put((idx, op))) transfers ownership; a derived value
            # (fill(lease.view())) does not — the lease stays ours.
            if isinstance(n.func, ast.Attribute) and isinstance(
                n.func.value, ast.Name
            ) and n.func.value.id == var:
                continue
            args = list(n.args) + [kw.value for kw in n.keywords]
            for a in args:
                if _is_bare_ref(a, var):
                    return True
    return False


def _is_bare_ref(node: ast.AST, var: str) -> bool:
    """The name itself, or a container literal holding it — NOT an
    arbitrary expression that merely mentions it."""
    if isinstance(node, ast.Name):
        return node.id == var
    if isinstance(node, ast.Starred):
        return _is_bare_ref(node.value, var)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(_is_bare_ref(e, var) for e in node.elts)
    if isinstance(node, ast.Dict):
        return any(v is not None and _is_bare_ref(v, var)
                   for v in list(node.keys) + list(node.values))
    return False


def _closed_on_raise(raise_node: ast.AST, fn: ast.AST,
                     parents: dict[int, ast.AST],
                     closer_ids: set[int], var: str,
                     closers: set[str]) -> bool:
    """Is the resource closed when this ``raise`` unwinds out of the
    function?  True when an enclosing ``finally`` closes it, when the
    raise sits in an except handler that already closed it, or when
    the raise is in a try body whose handlers close it."""
    node: ast.AST = raise_node
    while node is not fn:
        parent = parents.get(id(node))
        if parent is None:
            break
        if isinstance(parent, ast.Try):
            if any(
                id(c) in closer_ids
                for s in parent.finalbody for c in ast.walk(s)
            ):
                return True
            in_body = any(node is s or _contains(s, node)
                          for s in parent.body)
            if in_body and any(
                id(c) in closer_ids
                for h in parent.handlers for c in ast.walk(h)
            ):
                return True
        if isinstance(parent, ast.ExceptHandler):
            if any(id(c) in closer_ids for c in ast.walk(parent)):
                return True
        node = parent
    return False


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(root))


def _has_unconditional_close(fn: ast.AST, parents: dict[int, ast.AST],
                             assign: ast.AST, closer_nodes: list[ast.Call],
                             var: str) -> bool:
    """Does at least one closer run on the plain fall-through path?

    A closer guarded only by ``if <var> is not None``-style tests (the
    op-may-be-None idiom) or sitting in an ``if`` whose OTHER branch
    also closes counts as unconditional; a closer reachable only under
    an unrelated condition (``if ok: op.finish(1)``) or only inside a
    loop the acquire is not in does not — that is the classic
    happy-path-only leak."""
    acquire_anc: set[int] = set()
    node: ast.AST = assign
    while node is not fn:
        node = parents.get(id(node), fn)
        acquire_anc.add(id(node))

    def branch_closes(stmts) -> bool:
        return any(
            isinstance(c, ast.Call) and isinstance(c.func, ast.Attribute)
            and isinstance(c.func.value, ast.Name)
            and c.func.value.id == var
            and any(c is cn for cn in closer_nodes)
            for s in stmts for c in ast.walk(s)
        )

    for c in closer_nodes:
        node = c
        conditional = False
        while node is not fn and id(node) not in acquire_anc:
            parent = parents.get(id(node))
            if parent is None or parent is fn or \
                    id(parent) in acquire_anc:
                # Reached the region shared with the acquire: anything
                # above guards both sides equally.
                break
            if isinstance(parent, ast.If):
                guarded = uses_name(parent.test, var)
                both = (
                    branch_closes(parent.body)
                    and parent.orelse and branch_closes(parent.orelse)
                )
                if not guarded and not both:
                    conditional = True
                    break
            elif isinstance(parent, (ast.For, ast.While, ast.AsyncFor)) \
                    and id(parent) not in acquire_anc:
                # A close only inside a loop the acquire is outside of
                # may run zero times.
                conditional = True
                break
            elif isinstance(parent, ast.ExceptHandler):
                # A close only in an error handler never runs on the
                # fall-through path.
                conditional = True
                break
            node = parent
        if not conditional:
            return True
    return False


def ownership_findings(
    sf: SourceFile, *, pass_id: str, acquire_attr: str,
    closers: set[str], code_prefix: str, what: str,
) -> list[Finding]:
    out: list[Finding] = []
    for qual, fn in iter_functions(sf.tree):
        parents = parent_map(fn)
        # Nested defs are visited in their own iter_functions pass —
        # skip acquire sites that belong to an inner function, or every
        # finding would double-report under both qualnames.
        def _owned_here(node: ast.AST) -> bool:
            p = parents.get(id(node))
            while p is not None and p is not fn:
                if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    return False
                p = parents.get(id(p))
            return True

        # `with ...begin(...) [as x]` closes via __exit__: compliant.
        with_calls: set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    for c in _acquire_calls(item.context_expr, acquire_attr):
                        with_calls.add(id(c))

        # Every form that binds the acquire to a name: plain assign,
        # annotated assign, walrus — an annotation must not hide a
        # leak from the gate.
        bindings: list[tuple[str, ast.AST, ast.AST]] = []
        claimed: set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                bindings.append((n.targets[0].id, n, n.value))
            elif isinstance(n, ast.AnnAssign) and \
                    isinstance(n.target, ast.Name) and n.value is not None:
                bindings.append((n.target.id, n, n.value))
            elif isinstance(n, ast.NamedExpr) and \
                    isinstance(n.target, ast.Name):
                bindings.append((n.target.id, n, n.value))
        for _var, _node, value in bindings:
            for c in _acquire_calls(value, acquire_attr):
                claimed.add(id(c))

        for var, stmt, value in bindings:
            if _owned_here(stmt):
                calls = [
                    c for c in _acquire_calls(value, acquire_attr)
                    if id(c) not in with_calls
                ]
                if not calls:
                    continue
                closer_nodes = _closer_calls(fn, var, closers)
                if not closer_nodes and not _escapes(fn, var, stmt):
                    out.append(Finding(
                        pass_id, sf.path, stmt.lineno, qual,
                        f"{code_prefix}-leak:{var}",
                        f"{what} `{var}` acquired via .{acquire_attr}() "
                        f"but never reaches {'/'.join(sorted(closers))} "
                        f"and never escapes this function",
                    ))
                    continue
                if not closer_nodes:
                    continue  # escaped: new owner's obligation
                if not _has_unconditional_close(
                    fn, parents, stmt, closer_nodes, var
                ) and not _escapes(fn, var, stmt):
                    out.append(Finding(
                        pass_id, sf.path, stmt.lineno, qual,
                        f"{code_prefix}-conditional-close:{var}",
                        f"{what} `{var}` is closed only under a "
                        "condition unrelated to the handle (or only "
                        "on an error/loop path) — the fall-through "
                        "path leaks it",
                    ))
                    continue
                closer_ids = {id(c) for c in closer_nodes}
                first_close = min(c.lineno for c in closer_nodes)
                for n in ast.walk(fn):
                    if isinstance(n, ast.Raise) and _owned_here(n) \
                            and n.lineno > stmt.lineno \
                            and not _closed_on_raise(
                                n, fn, parents, closer_ids, var, closers):
                        # A raise between acquire and the last close
                        # with no finally/handler close → the unwind
                        # path leaks.  Raises after the first close on
                        # the fallthrough path are fine (already
                        # closed when control got there).
                        if n.lineno <= first_close:
                            out.append(Finding(
                                pass_id, sf.path, n.lineno, qual,
                                f"{code_prefix}-error-path:{var}",
                                f"{what} `{var}` may unwind un-closed: "
                                f"raise at line {n.lineno} has no "
                                f"finally/handler calling "
                                f"{'/'.join(sorted(closers))}",
                            ))
                            break

        # A bare-expression acquire not bound by ANY form above (with,
        # assign, walrus) is unconditionally dropped.
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Expr) and _owned_here(stmt):
                for c in _acquire_calls(stmt.value, acquire_attr):
                    if id(c) in with_calls or id(c) in claimed:
                        continue
                    out.append(Finding(
                        pass_id, sf.path, stmt.lineno, qual,
                        f"{code_prefix}-dropped",
                        f"result of .{acquire_attr}() discarded — the "
                        f"{what} can never be closed",
                    ))
    return out


# ------------------------------------------------------- flight-op pass --

_STAMPERS = {"note_phase", "annotate"}
_OP_STAMP_ATTRS = {"mark", "note"}
_ADOPTERS = {"adopt_op", "adopt_trace", "trace_scope"}


def _thread_target_names(tree: ast.AST) -> set[str]:
    """Simple names handed to ``threading.Thread(target=...)`` in this
    module (the helper-thread set the single-appender rule governs)."""
    names: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and call_name(n).endswith("Thread"):
            for kw in n.keywords:
                if kw.arg == "target":
                    d = dotted(kw.value)
                    if d:
                        names.add(d.rsplit(".", 1)[-1])
    return names


def _flight_pass(files: Sequence[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        out.extend(ownership_findings(
            sf, pass_id="flight-op", acquire_attr="begin",
            closers={"finish", "abandon"}, code_prefix="op",
            what="flight op",
        ))
        # Single-appender rule: a Thread-target function that stamps
        # phases ambently (note_phase/annotate) or on a foreign op must
        # adopt_op (or begin its own op) first — otherwise its stamps
        # land on whatever op the thread last held, or nowhere.
        targets = _thread_target_names(sf.tree)
        for qual, fn in iter_functions(sf.tree):
            if fn.name not in targets:
                continue
            calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
            stamps = [
                c for c in calls
                if call_name(c).rsplit(".", 1)[-1] in _STAMPERS
            ]
            if not stamps:
                continue
            adopts = any(
                call_name(c).rsplit(".", 1)[-1] in _ADOPTERS or
                call_name(c).endswith(".begin")
                for c in calls
            )
            if not adopts:
                out.append(Finding(
                    "flight-op", sf.path, stamps[0].lineno, qual,
                    "stamp-without-adopt",
                    "thread-target function stamps flight phases "
                    "(note_phase/annotate) without adopt_op/begin — "
                    "the single-appender rule: helper threads must "
                    "adopt the op they stamp for",
                ))
    return out


def _resource_pass(files: Sequence[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        out.extend(ownership_findings(
            sf, pass_id="resource", acquire_attr="lease",
            closers={"release"}, code_prefix="lease",
            what="slab lease",
        ))
    return out


FLIGHT_PASS = AnalysisPass(
    pass_id="flight-op",
    doc="every begun FlightOp reaches exactly one finish/abandon on all "
        "paths; helper threads adopt_op before stamping phases",
    run=_flight_pass,
)

RESOURCE_PASS = AnalysisPass(
    pass_id="resource",
    doc="SlabLease acquire/release balance across try/except/finally "
        "dataflow (the class behind three past lease-leak fixes)",
    run=_resource_pass,
)
