"""Static lock-order graph over the threaded planes.

The cache, the coop ring, the staging executor and the QoS queue each
own a lock; deadlock at pod scale comes from two planes acquiring them
in opposite orders (cache→coop on the miss path vs coop→cache on the
serve path is the classic near-miss review keeps re-checking).  This
pass builds a static acquired-while-held graph:

* lock identities are ``ClassName.attr`` for ``self.attr =
  threading.Lock()/RLock()/Condition()``; a ``Condition(self.lock)``
  aliases the lock it wraps;
* an edge A→B is recorded when ``with self.B:`` nests lexically inside
  ``with self.A:``, or when a call made while holding A can (transitively,
  through same-class methods and ``self.<attr>.<method>()`` calls on
  attributes whose class is constructed in-module) acquire B;
* any cycle in the union graph is a finding.

This is intentionally an over-approximation (it ignores conditional
paths) — a cycle it reports is an ordering the code can express, which
is exactly what the review rule rejected.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional, Sequence

from tpubench.analysis.core import (
    AnalysisPass,
    Finding,
    SourceFile,
    call_name,
    dotted,
)

# The threaded planes the review rounds audit for ordering.
LOCK_ORDER_FILES = (
    "tpubench/pipeline/cache.py",
    "tpubench/pipeline/coop.py",
    "tpubench/staging/executor.py",
    "tpubench/serve/qos.py",
    # Elastic membership composes over the coop broker/ring and the
    # serve admission queue — its lock must stay a leaf (listeners and
    # journal writes run OUTSIDE it).
    "tpubench/dist/membership.py",
    # Storage-lifecycle storm ledger: its lock stays a leaf (backend
    # calls and flight appends run OUTSIDE it).
    "tpubench/lifecycle/storm.py",
    # Replay driver: lock-free by design today; registered so any lock
    # it ever grows joins the ordering graph from day one (it composes
    # over the fake backend's fault plane and the serve planes).
    "tpubench/replay/driver.py",
    # Incident drill: its ledger lock guards restore/save byte counters
    # and stays a leaf — backend reads, cache fetches and flight
    # appends all run OUTSIDE it (it composes over the admission queue,
    # the coop broker and the storm ledger, each with locks of its own).
    "tpubench/workloads/drill.py",
    # Delta tracker: the shard-state lock is a leaf; CAS writes and
    # manifest uploads never run under it.
    "tpubench/lifecycle/delta.py",
    # gRPC wire plane: the client conn's write/stream locks and the
    # wire fake's per-conn write lock each stay leaves — backend
    # reads, fault sleeps and session mutations all run OUTSIDE them
    # (the h2 frame loop is single-threaded per conn by design).
    "tpubench/storage/grpc_wire/client.py",
    "tpubench/storage/fake_grpc_wire_server.py",
    # Fleet driver: single-threaded by design — no locks today. It
    # composes over Membership (whose lock must stay a leaf) and the
    # admission queue, so any lock it ever grows joins the ordering
    # graph from day one (the replay-driver precedent).
    "tpubench/fleet/driver.py",
)

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


@dataclasses.dataclass
class _ClassLocks:
    name: str
    path: str
    locks: dict[str, str]            # self-attr -> lock id
    attr_types: dict[str, str]       # self-attr -> ClassName
    methods: dict[str, ast.FunctionDef]
    # lock id -> underlying primitive: plain "Lock" is non-reentrant
    # (re-acquiring while held is a guaranteed self-deadlock); "RLock"
    # and bare Condition() (RLock-backed) are re-entrant.
    kinds: dict[str, str] = dataclasses.field(default_factory=dict)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _ann_name(ann: Optional[ast.AST]) -> str:
    """'ChunkCache' from ``cache: ChunkCache`` / ``Optional[ChunkCache]``."""
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Subscript):  # Optional[X] / "X | None" forms
        return _ann_name(ann.slice)
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip('"')
    return ""


def _collect_class(cls: ast.ClassDef, path: str) -> _ClassLocks:
    locks: dict[str, str] = {}
    attr_types: dict[str, str] = {}
    pending_alias: dict[str, str] = {}
    # Components usually arrive as annotated __init__ params
    # (``cache: ChunkCache``) stored onto self — type self-attrs from
    # those so cross-plane call edges resolve.
    param_types: dict[str, str] = {}
    init = next(
        (n for n in cls.body
         if isinstance(n, ast.FunctionDef) and n.name == "__init__"),
        None,
    )
    if init is not None:
        args = init.args
        for a in list(args.args) + list(args.kwonlyargs):
            t = _ann_name(a.annotation)
            if t and t[0].isupper():
                param_types[a.arg] = t
    kinds: dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.AnnAssign):
            attr = _self_attr(node.target)
            t = _ann_name(node.annotation)
            if attr and t and t[0].isupper():
                attr_types[attr] = t
            continue
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        attr = _self_attr(node.targets[0])
        if attr is None:
            continue
        if isinstance(node.value, ast.Name) and \
                node.value.id in param_types:
            attr_types[attr] = param_types[node.value.id]
            continue
        if not isinstance(node.value, ast.Call):
            continue
        ctor = call_name(node.value).rsplit(".", 1)[-1]
        if ctor in _LOCK_CTORS:
            arg_attr = None
            if node.value.args:
                arg_attr = _self_attr(node.value.args[0])
            if ctor == "Condition" and arg_attr is not None:
                pending_alias[attr] = arg_attr  # shares the wrapped lock
            else:
                lock_id = f"{cls.name}.{attr}"
                locks[attr] = lock_id
                # Bare Condition() is RLock-backed → re-entrant.
                kinds[lock_id] = "RLock" if ctor == "Condition" else ctor
        elif ctor and ctor[0].isupper():
            attr_types[attr] = ctor
    for attr, target in pending_alias.items():
        # The alias shares the wrapped lock's id AND its reentrancy.
        locks[attr] = locks.get(target, f"{cls.name}.{target}")
    methods = {
        n.name: n for n in cls.body
        if isinstance(n, ast.FunctionDef)
    }
    return _ClassLocks(cls.name, path, locks, attr_types, methods, kinds)


@dataclasses.dataclass
class LockGraph:
    edges: dict[str, set[str]]
    sites: dict[tuple[str, str], tuple[str, int]]  # edge -> first site
    # lock id -> underlying primitive ("Lock"/"RLock")
    kinds: dict[str, str] = dataclasses.field(default_factory=dict)
    # re-acquire of a non-reentrant Lock while held: (lock, path, line)
    self_deadlocks: list[tuple[str, str, int]] = dataclasses.field(
        default_factory=list
    )

    def add(self, a: str, b: str, path: str, line: int) -> None:
        if a == b:
            # RLock (and bare-Condition) re-acquire is legal; a plain
            # Lock re-acquired while held deadlocks unconditionally.
            if self.kinds.get(a, "Lock") == "Lock" and not any(
                s[0] == a for s in self.self_deadlocks
            ):
                self.self_deadlocks.append((a, path, line))
            return
        self.edges.setdefault(a, set()).add(b)
        self.sites.setdefault((a, b), (path, line))


def build_lock_graph(files: Sequence[SourceFile]) -> LockGraph:
    classes: dict[str, _ClassLocks] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = _collect_class(node, sf.path)

    graph = LockGraph(edges={}, sites={})
    for cl in classes.values():
        graph.kinds.update(cl.kinds)
    # (class, method) -> set of lock ids it may acquire, transitively.
    may_acquire: dict[tuple[str, str], set[str]] = {}
    # deferred: (held lock id, callee class, callee method, path, line)
    deferred: list[tuple[str, str, str, str, int]] = []

    def walk(cl: _ClassLocks, method: ast.FunctionDef,
             acquires: set[str], path: str) -> None:
        def rec(node: ast.AST, held: list[str]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for item in node.items:
                    # Calls inside the context expression run before
                    # THIS item's acquire but AFTER earlier items' —
                    # visit them under the accumulating inner set.
                    rec(item.context_expr, inner)
                    attr = _self_attr(item.context_expr)
                    lock = cl.locks.get(attr) if attr else None
                    if lock:
                        acquires.add(lock)
                        for h in inner:
                            graph.add(h, lock, path, node.lineno)
                        inner.append(lock)
                for stmt in node.body:
                    rec(stmt, inner)
                return
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                callee: Optional[tuple[str, str]] = None
                if d.startswith("self.") and d.count(".") == 1:
                    callee = (cl.name, d.split(".", 1)[1])
                elif d.startswith("self.") and d.count(".") == 2:
                    _, attr, meth = d.split(".")
                    target_cls = cl.attr_types.get(attr)
                    if target_cls:
                        callee = (target_cls, meth)
                if callee and held:
                    for h in held:
                        deferred.append(
                            (h, callee[0], callee[1], path, node.lineno)
                        )
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested defs (worker closures) run on other threads
                # with an empty held-set of their own.
                for child in ast.iter_child_nodes(node):
                    rec(child, [])
                return
            for child in ast.iter_child_nodes(node):
                rec(child, held)

        for child in ast.iter_child_nodes(method):
            rec(child, [])

    for cl in classes.values():
        for mname, m in cl.methods.items():
            acq: set[str] = set()
            walk(cl, m, acq, cl.path)
            may_acquire[(cl.name, mname)] = acq

    # Transitive closure of may_acquire through same-program calls.
    call_edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
    for cl in classes.values():
        for mname, m in cl.methods.items():
            outs: set[tuple[str, str]] = set()
            for n in ast.walk(m):
                if isinstance(n, ast.Call):
                    d = dotted(n.func)
                    if d.startswith("self.") and d.count(".") == 1:
                        outs.add((cl.name, d.split(".", 1)[1]))
                    elif d.startswith("self.") and d.count(".") == 2:
                        _, attr, meth = d.split(".")
                        t = cl.attr_types.get(attr)
                        if t:
                            outs.add((t, meth))
            call_edges[(cl.name, mname)] = outs
    changed = True
    while changed:
        changed = False
        for key, outs in call_edges.items():
            acc = may_acquire.setdefault(key, set())
            for callee in outs:
                extra = may_acquire.get(callee, set())
                if not extra <= acc:
                    acc |= extra
                    changed = True

    for held, ccls, cmeth, path, line in deferred:
        for lock in may_acquire.get((ccls, cmeth), set()):
            graph.add(held, lock, path, line)
    return graph


def find_cycles(graph: LockGraph) -> list[list[str]]:
    """Every elementary cycle reachable by DFS (deduped by rotation)."""
    cycles: list[list[str]] = []
    seen: set[tuple[str, ...]] = set()

    def dfs(node: str, path: list[str], on_path: set[str]) -> None:
        for nxt in sorted(graph.edges.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                core = cyc[:-1]
                rot = min(
                    tuple(core[i:] + core[:i]) for i in range(len(core))
                )
                if rot not in seen:
                    seen.add(rot)
                    cycles.append(cyc)
            elif len(path) < 32:
                dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph.edges):
        dfs(start, [start], {start})
    return cycles


def _lock_order_pass(files: Sequence[SourceFile]) -> list[Finding]:
    scoped = [sf for sf in files if sf.path in LOCK_ORDER_FILES]
    if not scoped:
        return []
    graph = build_lock_graph(scoped)
    out: list[Finding] = []
    for lock, path, line in graph.self_deadlocks:
        out.append(Finding(
            "lock-order", path, line, lock,
            f"self-deadlock:{lock}",
            f"non-reentrant {lock} re-acquired while already held "
            "(possibly through a callee) — a plain threading.Lock "
            "deadlocks here unconditionally",
        ))
    for cyc in find_cycles(graph):
        path, line = graph.sites.get(
            (cyc[0], cyc[1]), (scoped[0].path, 0)
        )
        out.append(Finding(
            "lock-order", path, line, cyc[0],
            "cycle:" + ">".join(cyc[:-1]),
            "lock-order cycle (deadlock expressible): "
            + " -> ".join(cyc),
        ))
    return out


LOCK_ORDER_PASS = AnalysisPass(
    pass_id="lock-order",
    doc="static acquired-while-held graph over cache/coop/staging/qos "
        "locks rejects ordering cycles",
    run=_lock_order_pass,
)
