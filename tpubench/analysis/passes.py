"""Pass registry: the five invariant planes behind ``tpubench check``."""

from __future__ import annotations

from tpubench.analysis.core import REPO_ROOT, AnalysisPass
from tpubench.analysis.determinism import DETERMINISM_PASS
from tpubench.analysis.drift import make_drift_pass
from tpubench.analysis.lifecycle import FLIGHT_PASS, RESOURCE_PASS
from tpubench.analysis.lockorder import LOCK_ORDER_PASS
from tpubench.analysis.threads import THREAD_PASS

STATIC_PASSES: tuple[AnalysisPass, ...] = (
    FLIGHT_PASS,
    THREAD_PASS,
    RESOURCE_PASS,
    DETERMINISM_PASS,
    LOCK_ORDER_PASS,
)


def all_passes(with_drift: bool = True,
               repo_root: str = REPO_ROOT) -> list[AnalysisPass]:
    passes = list(STATIC_PASSES)
    if with_drift:
        passes.append(make_drift_pass(repo_root))
    return passes
