"""Thread-hygiene pass.

Two mechanized review rules:

* **Named threads** — every ``threading.Thread(...)`` carries a
  ``name=`` kwarg.  Anonymous ``Thread-7`` in a stack dump or a flight
  journal is useless at pod scale; every review round renamed one.
* **The coop-serve / Ctrl-C rule** — ``except BaseException`` (and bare
  ``except:``) handlers must re-raise.  A swallowed BaseException eats
  KeyboardInterrupt/SystemExit: worker bodies that *record* errors must
  catch ``Exception`` and let cancellation unwind.  Handlers that
  legitimately route the error through recorded state re-raised
  elsewhere (WorkerGroup, the hedge out-queue, the staging reaper) are
  vetted in the allowlist with justifications.
"""

from __future__ import annotations

import ast
from typing import Sequence

from tpubench.analysis.core import (
    AnalysisPass,
    Finding,
    SourceFile,
    call_name,
    walk_scoped,
)


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    """A Raise on the handler's own unwind path — a raise inside a
    nested def/lambda registered as a callback does not re-raise for
    the handler and must not satisfy the rule."""
    def scan(node: ast.AST) -> bool:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Raise) or scan(child):
                return True
        return False

    return scan(handler)


def _catches_baseexception(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return "BaseException" in names


def _thread_pass(files: Sequence[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        for scope, node in walk_scoped(sf.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                # endswith: aliased imports (`import threading as
                # _threading`, a lazy-import pattern the tree uses)
                # must not hide an unnamed thread from the gate.
                if name == "Thread" or name.endswith(".Thread"):
                    has_name = any(kw.arg == "name" for kw in node.keywords)
                    if not has_name:
                        out.append(Finding(
                            "thread", sf.path, node.lineno, scope,
                            "unnamed-thread",
                            "threading.Thread without name= — anonymous "
                            "threads are invisible in stack dumps, "
                            "flight journals and the straggler tables",
                        ))
            elif isinstance(node, ast.ExceptHandler):
                if _catches_baseexception(node) and not \
                        _handler_reraises(node):
                    kind = "bare except" if node.type is None else \
                        "except BaseException"
                    out.append(Finding(
                        "thread", sf.path, node.lineno, scope,
                        "baseexception-swallow",
                        f"{kind} without re-raise swallows "
                        "KeyboardInterrupt/SystemExit (the coop-serve "
                        "Ctrl-C rule) — catch Exception, or re-raise, "
                        "or vet in the allowlist",
                    ))
    return out


THREAD_PASS = AnalysisPass(
    pass_id="thread",
    doc="every threading.Thread is named; BaseException/bare-except "
        "handlers re-raise (worker bodies record via Exception)",
    run=_thread_pass,
)
