"""Pure result-assembly logic for ``bench.py`` — separated so the verdict,
efficiency, gap-breakdown and note derivations are unit-testable (the
round-4 verdict's #2: a hardcoded note asserted "shaped" in the same JSON
object whose measured ``shaped_verdict`` said false; every sentence the
note now makes comes from the run's own fields).

No jax imports, no I/O: functions here map measured numbers → report
fields. ``bench.py`` owns the measuring.
"""

from __future__ import annotations

import statistics
from typing import Optional


def shaped_verdict(probe_shaped: bool, key_samples: list[float]) -> bool:
    """Shaping verdict from the union of observations: the closing probe's
    own verdict, OR a >3x spread across the bench's positionally identical
    cycles of ONE config (the probe runs last — on a drained budget it
    sees only the uniform floor and would misread the tunnel as unshaped).
    The spread test is only meaningful within one measurement kind; the
    caller passes identical-cycle samples of a single config."""
    live = [x for x in key_samples if x > 0]
    return bool(probe_shaped) or (len(live) >= 3 and max(live) > 3 * min(live))


def headline_value(key_samples: list[float], shaped: bool) -> float:
    """On a shaped tunnel the peak across identical cycles is the
    pipeline's demonstrated capability (medians are shaping noise); on an
    unshaped host the median is the honest sustained number."""
    if not key_samples:
        return 0.0
    return max(key_samples) if shaped else statistics.median(key_samples)


def live_pairs(eff_pairs: list[dict]) -> list[dict]:
    """Pairs whose tunnel half actually got a window (a floored ceiling
    under a fast-window staged sample would quotient > 1 — no honest
    efficiency exists for that pair)."""
    return [p for p in eff_pairs if p.get("tunnel", 0) > 0.5]


def pair_efficiency(
    eff_pairs: list[dict], mode: Optional[str] = None
) -> tuple[Optional[float], Optional[float]]:
    """(best, median) staged/tunnel quotient over the live same-window
    pairs — optionally restricted to one config ``mode`` (a median across
    MIXED configs would average different pipelines); (None, None) when
    every matching pair was floored."""
    lp = live_pairs(eff_pairs)
    if mode is not None:
        lp = [p for p in lp if p.get("mode", "sync") == mode]
    if not lp:
        return None, None
    qs = [p["staged"] / p["tunnel"] for p in lp]
    return max(qs), statistics.median(qs)


def serial_model_gbps(fetch_gbps: float, tunnel_gbps: float) -> float:
    """Staged bandwidth a DEPTH-1 (fully synchronous) pipeline can reach
    when each slot's fetch and transfer run serially: the harmonic
    composition 1/(1/fetch + 1/tunnel). This is the structural ceiling of
    the sync config — NOT pipeline inefficiency; the overlapped config's
    ceiling is min(fetch, tunnel)."""
    if fetch_gbps <= 0 or tunnel_gbps <= 0:
        return 0.0
    return 1.0 / (1.0 / fetch_gbps + 1.0 / tunnel_gbps)


def gap_breakdown(pair: dict, host_fetch_gbps: float) -> dict:
    """Root-cause fields for one same-window pair: where the staged-vs-
    tunnel gap goes. ``pair`` carries tunnel/staged GB/s, the staged run's
    measured phase times (wall_s, transfer_wait_s, put_submit_s) and its
    mode ('sync' | 'overlap')."""
    out = {
        "mode": pair.get("mode", "sync"),
        "efficiency": (
            round(pair["staged"] / pair["tunnel"], 4)
            if pair.get("tunnel", 0) > 0
            else None
        ),
    }
    bd = pair.get("breakdown") or {}
    wall = bd.get("wall_s", 0.0)
    if wall > 0:
        wait = bd.get("transfer_wait_s", 0.0)
        put = bd.get("put_submit_s", 0.0)
        out["wall_s"] = round(wall, 4)
        out["transfer_wait_frac"] = round(wait / wall, 4)
        if bd.get("drain") in ("thread", "overlap"):
            # The REAPER (or the legacy drainer, in pre-PR-6 result
            # files) owns submission+completion: its time runs
            # concurrently with fetch, so it gets its own name and is
            # never subtracted from the fetch thread's wall (doing so
            # would make the fractions sum past 1 and lie about fetch).
            out["drainer_submit_frac"] = round(put / wall, 4)
            out["fetch_and_overhead_frac"] = round(
                max(0.0, wall - wait) / wall, 4
            )
        else:
            out["put_submit_frac"] = round(put / wall, 4)
            out["fetch_and_overhead_frac"] = round(
                max(0.0, wall - wait - put) / wall, 4
            )
    if pair.get("mode", "sync") == "sync":
        model = serial_model_gbps(host_fetch_gbps, pair.get("tunnel", 0.0))
        out["serial_model_gbps"] = round(model, 4)
        # Efficiency of the pipeline against ITS OWN structural ceiling:
        # the sync config pays fetch serially, so staged/tunnel < 1 by
        # construction even for a perfect pipeline.
        out["vs_serial_model"] = (
            round(pair["staged"] / model, 4) if model > 0 else None
        )
    return out


def probe_divergence(
    window_median: float, probe_median: Optional[float]
) -> Optional[float]:
    """>3x divergence between the bench's own window samples and the
    closing probe's cycle median means the probe characterized a different
    regime (typically: it ran last, on a drained budget, and saw only the
    floor). Returns the factor when divergent, else None."""
    if not probe_median or probe_median <= 0 or window_median <= 0:
        return None
    factor = window_median / probe_median
    if not (factor > 3 or factor < 1 / 3):
        return None
    # Round for the report, but never TO zero: a sub-0.005x factor (the
    # windows were crushed, e.g. by host contention) must stay nonzero
    # so build_note can invert it.
    rounded = round(factor, 2)
    return rounded if rounded > 0 else factor


def build_note(f: dict) -> str:
    """Assemble the human note ONLY from measured fields, so it can never
    contradict the verdicts printed beside it. Expected keys:
    shaped_verdict (bool), staging_efficiency (float|None),
    best_pair_mode (str|None), probe_divergence_factor (float|None),
    nexec_median (float|None), sync_median (float|None),
    nexec_deconfounded (bool); optional: overlap_best (float|None),
    sync_best (float|None), overlap_put_submit_frac (float|None),
    fetch_ab (dict with native_executor_gbps/python_fetch_gbps),
    reactor_ab (dict with best_at_top/completions_per_wake/fanouts)."""
    parts: list[str] = []
    if f.get("shaped_verdict"):
        parts.append(
            "shaped_verdict=true: the host→HBM tunnel showed the shaped "
            "signature this run (>3x spread across identical cycles or "
            "probe verdict); value is the PEAK across identical cycles — "
            "medians across a granted-window/floor mix are shaping noise."
        )
    else:
        parts.append(
            "shaped_verdict=false: no shaping signature this run; value "
            "is the MEDIAN across identical cycles."
        )
    eff = f.get("staging_efficiency")
    if eff is not None:
        mode = f.get("best_pair_mode") or "sync"
        s = (
            f"vs_tunnel_ceiling={eff}: best SAME-WINDOW tunnel-first pair "
            "(all pairs disclosed in efficiency_pairs; order-swap "
            "measurements showed cross-window quotients are dominated by "
            "budget position, not pipeline cost)."
        )
        if eff > 1:
            s += (
                " A quotient >1 means the tunnel half UNDERSTATED the "
                "window's grant (within-window variance), not that the "
                "pipeline beat raw device_put — read it as ≈1.0, pipeline "
                "at the ceiling."
            )
        if mode == "sync":
            s += (
                " The best pair ran the depth-1 sync config, whose "
                "structural ceiling is the serial model "
                "1/(1/fetch+1/tunnel) — see gap_breakdown.vs_serial_model "
                "for the pipeline measured against its own ceiling."
            )
        parts.append(s)
    else:
        parts.append(
            "staging_efficiency=null: every same-window pair's tunnel "
            "half was floored — no honest quotient exists this run."
        )
    pdf = f.get("probe_divergence_factor")
    if pdf is not None:
        if pdf > 1:
            parts.append(
                f"closing probe diverges {pdf}x BELOW the bench's own "
                "windows: it ran last on a drained transfer budget and "
                "characterizes the floor regime, NOT the regime the "
                "headline was measured in — read its cells accordingly."
            )
        else:
            parts.append(
                f"closing probe diverges {round(1 / pdf, 2)}x ABOVE the "
                "bench's own windows: the probe caught a fast window the "
                "bench's cycles never got — the headline understates the "
                "pipeline's regime, not the reverse."
            )
    pb, sb0 = f.get("pallas_best"), f.get("sync_best")
    if pb is not None and sb0 is not None:
        gap_pct = round((1 - pb / sb0) * 100) if sb0 > 0 else 0
        rel = (
            f"within {gap_pct}% of" if 0 <= gap_pct <= 10
            else ("ahead of" if pb > sb0 else f"{gap_pct}% behind")
        )
        parts.append(
            f"pallas landing-path pair best {pb} vs device_put sync best "
            f"{sb0}: the fused copy+checksum landing ring measures {rel} "
            "the plain device_put config (its checksum validation is "
            "fused into the landing pass, not skipped)."
        )
    ob, sb = f.get("overlap_best"), f.get("sync_best")
    if ob is not None and sb is not None and ob < sb:
        frac = f.get("overlap_put_submit_frac")
        cores = f.get("host_cores")
        why = ""
        if frac is not None:
            why = (
                f" — the drain thread owns submission AND completion "
                f"(drainer submit frac {frac}), so the loss is not "
                "fetch-thread serialization"
            )
            if cores == 1:
                # Causal claim gated on the MEASURED core count.
                why += (
                    "; with host_cores=1 the CPU-mediated transfer and "
                    "the fetch share one core, and pipelining adds "
                    "thread-handoff cost instead of hiding transfer time"
                )
            else:
                why += (
                    f"; host_cores={cores} — see gap_breakdown for where "
                    "the overlap pairs' wall went"
                )
        parts.append(
            f"overlap (drain-thread) best pair {ob} vs sync best {sb}: "
            f"the depth-1 sync config wins on this host{why}."
        )
    nm, sm = f.get("nexec_median"), f.get("sync_median")
    if nm:
        src = (
            "an all-native C loopback server (no Python competing for "
            "the core)"
            if f.get("nexec_deconfounded")
            else "a Python loopback server (KNOWN single-core confound)"
        )
        rel = "ahead of" if sm and nm >= sm else "behind"
        parts.append(
            f"nexec (C++ fetch hot loop) median {nm} vs in-process-fetch "
            f"{sm}: measured against {src}, reporting {rel} the "
            "in-process-fetch config on this host."
        )
    ab = f.get("fetch_ab") or {}
    if ab.get("native_executor_gbps") and ab.get("python_fetch_gbps"):
        ng, pg = ab["native_executor_gbps"], ab["python_fetch_gbps"]
        rel = "ahead of" if ng >= pg else "behind"
        parts.append(
            f"fetch-only A/B (staging stubbed, quiet CPU, C server "
            f"source): executor {ng} vs Python fetch {pg} GB/s — the "
            f"native fan-out measures {rel} the Python hot loop on this "
            "single-core host"
            + (
                "; the per-completion queue handoff costs more than the "
                "native receive saves with only one core to share."
                if ng < pg
                else "."
            )
        )
    rab = f.get("reactor_ab") or {}
    bt = rab.get("best_at_top") or {}
    if bt.get("reactor") and bt.get("threads"):
        fan = (rab.get("fanouts") or ["?"])[-1]
        rcpw = (rab.get("completions_per_wake") or {}).get("reactor") or {}
        rel = "ahead of" if bt["reactor"] >= bt["threads"] else "behind"
        s = (
            f"reactor three-arm A/B at fan-out {fan} (best-of, quiet "
            f"CPU, C server source): reactor {bt['reactor']} vs "
            f"thread-pool {bt['threads']} vs python {bt.get('python')} "
            f"GB/s — the epoll loop + SPSC-ring handoff measures {rel} "
            "the legacy executor"
        )
        if rcpw.get("p50") is not None:
            s += (
                f", handing over {rcpw['p50']} completions per wake at "
                "p50 (the legacy per-completion handoff delivers ~1)."
            )
        else:
            s += "."
        parts.append(s)
    parts.append(
        "vs_baseline divides by an in-process host-RAM memcpy fetch "
        "(~7 GB/s) no NIC-attached client reaches; vs_tunnel_ceiling is "
        "the meaningful comparable on this hardware (BASELINE.md)."
    )
    return " ".join(parts)
