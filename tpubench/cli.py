"""tpubench CLI — replaces the reference's shell layer (SURVEY L5).

The reference drives everything through per-binary flags and hardcoded
shell launchers (``execute_pb.sh``, ``benchmark-script/*/*.sh``) that mount
gcsfuse, sweep file sizes and A/B the two protocols by redirecting stdout.
Here one CLI owns all of it:

* every workload is a subcommand (``read``, ``pod-ingest``, ``read-fs``,
  ``write``, ``list``, ``open``, ``ssd``);
* ``sweep`` reproduces the protocol A/B pairing of ``execute_pb.sh`` and the
  256KB/1MB/100MB/1GB file-size sweep of ``read_operations.sh:8-14`` with
  first-class JSON results instead of ``tr``-munged stdout;
* ``prepare`` generates worker-indexed data files (the reference assumes
  they already exist in the bucket/mount, README.md:9);
* ``--config`` loads/saves the full BenchConfig as JSON — no editing source
  to change the object prefix (main.go:50-53).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpubench.config import KB, MB, BenchConfig, preset
from tpubench.metrics.report import RunResult, upload_result, write_result


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", help="JSON config file (BenchConfig.to_json)")
    p.add_argument("--preset", choices=("256kb", "1mb", "100mb", "1gb", "smoke"))
    p.add_argument("--protocol", choices=("http", "grpc", "local", "fake"))
    p.add_argument("--bucket")
    p.add_argument("--project")
    p.add_argument("--endpoint", help="override API endpoint (fake servers)")
    p.add_argument("--tls-ca-file",
                   help="CA bundle to trust for https endpoints (overrides "
                        "the system store; test endpoints with a private CA)")
    p.add_argument("--tls-insecure-skip-verify", action="store_true",
                   help="skip TLS certificate verification (self-signed "
                        "test endpoints only)")
    p.add_argument("--dir", help="directory for local/FS workloads")
    p.add_argument("--workers", type=int)
    p.add_argument("--read-call-per-worker", type=int, dest="read_calls")
    p.add_argument("--threads", type=int)
    p.add_argument("--read-count", type=int)
    p.add_argument("--write-count", type=int)
    p.add_argument("--block-size", type=int, help="KB", dest="block_size_kb")
    p.add_argument("--file-size-mb", type=int)
    p.add_argument("--object-size", type=int, help="bytes (fake backend)")
    p.add_argument("--object-name-prefix")
    p.add_argument("--read-type", choices=("seq", "random"))
    p.add_argument("--open-files", type=int)
    p.add_argument("--staging", choices=("none", "device_put", "pallas"))
    p.add_argument("--no-double-buffer", action="store_true")
    p.add_argument("--staging-depth", type=int, dest="staging_depth",
                   help="in-flight staging window: how many host→HBM "
                        "transfers the overlapped executor keeps pending "
                        "at once, completed out of order (1 = fully "
                        "synchronous; default 3; live-tunable via the "
                        "staging_depth tune knob)")
    p.add_argument("--staging-drain", choices=("inline", "thread"),
                   help="DEPRECATED no-op (kept for old scripts): depth>1 "
                        "always rides the overlapped staging executor "
                        "now; use --staging-depth 1 for the serial ring")
    p.add_argument("--validate", action="store_true", help="on-device checksum")
    p.add_argument("--enable-tracing", action="store_true")
    p.add_argument("--trace-sample-rate", type=float)
    p.add_argument("--trace-exporter", choices=("console", "cloud_trace"),
                   help="span export path (with --enable-tracing)")
    p.add_argument("--profile-dir", help="capture a jax.profiler xplane trace here")
    p.add_argument("--profile-steps",
                   help="train-ingest: bound the jax.profiler capture to "
                        "steps N:M (inclusive; profiles the steady "
                        "state, not warmup); path + window stamped into "
                        "extra[\"profile\"]; no-op when jax profiling "
                        "is unavailable")
    p.add_argument("--flight-journal",
                   help="write the per-host flight-recorder journal JSON "
                        "here (per-read phase timelines; multi-host "
                        "processes suffix .p<idx>; a .gz path writes "
                        "gzip-compressed); render with "
                        "`tpubench report timeline <paths...>` or watch "
                        "live with `tpubench top <path>`")
    p.add_argument("--journal-max-bytes", type=int,
                   help="size bound for each journal write: a flush "
                        "that would exceed it drops the OLDEST records "
                        "with a counted rotation_dropped note (0 = "
                        "unbounded) — long runs streaming journals "
                        "can't fill the disk")
    p.add_argument("--telemetry-port", type=int,
                   help="serve live run telemetry over loopback HTTP: "
                        "Prometheus text exposition at /metrics + JSON "
                        "/snapshot (0 = ephemeral port, printed at "
                        "start; off by default)")
    p.add_argument("--telemetry-interval", type=float,
                   help="telemetry registry tick seconds: gauge refresh, "
                        "recorder/native-counter sampling and the "
                        "in-run journal stream cadence (default 1.0)")
    p.add_argument("--telemetry-otlp", action="store_true",
                   help="periodic OTLP-shaped JSON metric export "
                        "(dry-run capture stamped into the result "
                        "unless --telemetry-otlp-endpoint is set)")
    p.add_argument("--telemetry-otlp-endpoint",
                   help="POST OTLP/HTTP JSON metric payloads here every "
                        "telemetry.otlp_interval_s (implies "
                        "--telemetry-otlp; stdlib urllib, no SDK)")
    p.add_argument("--flight-records", type=int,
                   help="flight-recorder ring capacity per worker "
                        "(newest records kept; 0 disables the layer)")
    p.add_argument("--export", choices=("none", "json", "cloud"),
                   help="metric export: cloud = in-run periodic push of full "
                        "latency histograms (metrics_exporter.go:36-58); "
                        "dry-run capture unless --metrics-live")
    p.add_argument("--metrics-interval", type=float,
                   help="export interval seconds (reference: 30)")
    p.add_argument("--metrics-live", action="store_true",
                   help="really push to Cloud Monitoring (needs "
                        "google-cloud-monitoring + GCP creds; default is "
                        "dry-run capture stamped into the result)")
    p.add_argument("--results-dir")
    p.add_argument("--results-bucket",
                   help="also upload result JSONs to this bucket via the "
                        "configured storage protocol (execute_pb.sh:5)")
    p.add_argument("--no-abort-on-error", action="store_true",
                   help="per-worker failure domains instead of errgroup abort")
    p.add_argument("--fault-error-rate", type=float,
                   help="fake backend: P(open raises transient 503)")
    p.add_argument("--fault-read-error-rate", type=float,
                   help="fake backend: P(granule read fails mid-stream)")
    p.add_argument("--fault-latency", type=float,
                   help="fake backend: added first-byte latency (s)")
    p.add_argument("--fault-per-read-latency", type=float,
                   help="fake backend: added latency per granule read (s)")
    p.add_argument("--fault-stall-s", type=float,
                   help="chaos plane: one mid-body pause of this many "
                        "seconds per reader (very large = blackhole)")
    p.add_argument("--fault-stall-after-bytes", type=int,
                   help="chaos plane: the stall triggers after this many "
                        "delivered bytes (default 0 = at first byte)")
    p.add_argument("--fault-stall-rate", type=float,
                   help="chaos plane: P(a given reader stalls at all) — "
                        "<1 makes the stall a straggler, the shape "
                        "hedged reads race against")
    p.add_argument("--fault-drip-bps", type=float,
                   help="chaos plane: per-reader throughput cap "
                        "(bytes/s; the slow-drip the stall watchdog "
                        "detects)")
    p.add_argument("--fault-truncate-after-bytes", type=int,
                   help="chaos plane: clean EOF after N bytes, short of "
                        "the announced length")
    p.add_argument("--fault-reset-after-bytes", type=int,
                   help="chaos plane: kill the stream abruptly after N "
                        "bytes (reset/RST shape)")
    p.add_argument("--hedge", action="store_true",
                   help="tail tolerance: race a second ranged read when "
                        "the first byte is late; first winner streams, "
                        "loser cancelled (wins/losses/waste recorded)")
    p.add_argument("--hedge-delay", type=float,
                   help="seconds before the hedge launches (default 0.05)")
    p.add_argument("--hedge-from-p99", action="store_true",
                   help="derive the hedge delay from the run's rolling "
                        "p99 first-byte latency instead of the fixed "
                        "--hedge-delay (which becomes the floor)")
    p.add_argument("--watchdog", action="store_true",
                   help="tail tolerance: cancel+resume a stream whose "
                        "throughput stays below --stall-floor-bps for "
                        "--stall-window seconds")
    p.add_argument("--stall-window", type=float,
                   help="watchdog stall window seconds (default 1.0)")
    p.add_argument("--stall-floor-bps", type=float,
                   help="watchdog throughput floor bytes/s (default 1024)")
    p.add_argument("--breaker", action="store_true",
                   help="tail tolerance: per-backend circuit breaker "
                        "(closed→open→half-open) shedding a failing "
                        "endpoint instead of hammering it")
    p.add_argument("--breaker-failures", type=int,
                   help="consecutive failures that open the breaker "
                        "(default 5)")
    p.add_argument("--breaker-reset", type=float,
                   help="seconds the breaker stays open before probing "
                        "(default 5.0)")
    p.add_argument("--breaker-probes", type=int,
                   help="half-open probe successes required to close "
                        "(default 1)")
    p.add_argument("--cache-bytes", type=int,
                   help="ingest pipeline: host chunk-cache budget in bytes "
                        "(LRU, single-flight dedup; 0 disables caching)")
    p.add_argument("--readahead", type=int,
                   help="ingest pipeline: readahead depth in chunks the "
                        "prefetcher keeps scheduled ahead of the consumer "
                        "(0 = cold demand reads, the A/B baseline)")
    p.add_argument("--readahead-bytes", type=int,
                   help="ingest pipeline: prefetch byte budget (in-flight "
                        "+ unconsumed prefetched bytes; 0 = depth-bounded)")
    p.add_argument("--prefetch-workers", type=int,
                   help="ingest pipeline: prefetch worker threads")
    p.add_argument("--steps", type=int,
                   help="train-ingest: training steps per epoch")
    p.add_argument("--epochs", type=int,
                   help="train-ingest: epochs (the plan repeats; epoch 2+ "
                        "measures the warm-cache path)")
    p.add_argument("--batch-shards", type=int,
                   help="train-ingest: chunks consumed per step")
    p.add_argument("--chunk-bytes", type=int,
                   help="ingest pipeline: chunk size in bytes "
                        "(default: workload.granule_bytes)")
    p.add_argument("--step-compute-ms", type=float,
                   help="train-ingest: synthetic per-step compute window "
                        "(ms) the prefetcher hides fetch latency behind")
    p.add_argument("--stall-threshold-ms", type=float,
                   help="train-ingest: a step whose data wait exceeds this "
                        "counts as a stalled step")
    p.add_argument("--pipeline-pod", action="store_true",
                   help="train-ingest: stage each step's batch as "
                        "byte-range shards across the mesh and reassemble "
                        "over ICI (dist.shard/reassemble) instead of the "
                        "slot-ring device_put path")
    p.add_argument("--slab-bytes", type=int,
                   help="zero-copy datapath: slab size in bytes for the "
                        "pinned chunk-buffer pool (0 = one chunk per "
                        "slab; must hold at least one chunk)")
    p.add_argument("--pool-slabs", type=int,
                   help="zero-copy datapath: slab pool capacity (0 = "
                        "auto-sized from cache budget + readahead + "
                        "batch; exhaustion spills to counted overflow "
                        "leases, never blocks)")
    p.add_argument("--no-slab-pool", action="store_true",
                   help="disable the zero-copy slab datapath: chunks "
                        "materialize as bytes (2+ host-RAM copies per "
                        "chunk — the copies-per-byte A/B baseline arm)")
    p.add_argument("--coop", action="store_true",
                   help="cooperative chunk cache: consistent-hash chunk "
                        "ownership across the pod's hosts, peer-first "
                        "miss resolution, pod-wide single-flight (only "
                        "the owner fetches a chunk from origin) and "
                        "straggler-aware owner demotion")
    p.add_argument("--coop-hosts", type=int,
                   help="hosts on the ownership ring (default 0 = "
                        "--num-processes)")
    p.add_argument("--coop-host-id", type=int,
                   help="this host's ring id (default -1 = --process-id)")
    p.add_argument("--coop-vnodes", type=int,
                   help="virtual nodes per host on the consistent-hash "
                        "ring (default 64)")
    p.add_argument("--peer-budget-bytes", type=int,
                   help="serve-side byte budget: bytes concurrently "
                        "served to peers never exceed this — past it the "
                        "owner sheds and peers fall back to origin "
                        "(0 = unbounded; live-tunable)")
    p.add_argument("--coop-channel", choices=("auto", "loopback", "ici"),
                   help="peer transport: loopback = in-process "
                        "request/reply; ici = lockstep broadcast over "
                        "the pod mesh (plan-synchronized pod workloads "
                        "only); auto = loopback")
    p.add_argument("--no-coop-demote", action="store_true",
                   help="disable straggler-aware owner demotion (keep "
                        "slow-decile hosts on the ownership ring)")
    p.add_argument("--tune", action="store_true",
                   help="adaptive autotuner: run the online controller "
                        "during this run — worker fan-out, readahead "
                        "depth/bytes, prefetch workers and hedge delay "
                        "become live knobs driven by windowed goodput "
                        "under a p99 guardrail (read / train-ingest)")
    p.add_argument("--tune-window", type=float,
                   help="tune decision window seconds (default 0.5)")
    p.add_argument("--tune-warmup", type=int,
                   help="baseline windows before the first probe "
                        "(default 2)")
    p.add_argument("--tune-p99-guard", type=float,
                   help="p99 guardrail: probes whose window p99 exceeds "
                        "baseline x this revert regardless of goodput "
                        "(default 2.0)")
    p.add_argument("--tune-epsilon", type=float,
                   help="minimum relative goodput gain to accept a probe "
                        "(default 0.05)")
    p.add_argument("--tune-duration", type=float,
                   help="online read tuning session length seconds "
                        "(default 8; train-ingest stays step-bounded)")
    p.add_argument("--tune-knobs",
                   help="comma list of knobs the controller may actuate "
                        "(default: workers,readahead,readahead_bytes,"
                        "prefetch_workers,hedge_delay_s,staging_depth)")
    p.add_argument("--tune-profile",
                   help="tune profile JSON: `tpubench tune` WRITES the "
                        "recommended operating point here; every other "
                        "subcommand READS it and applies the recommended "
                        "knob values over the config")
    p.add_argument("--retry-deadline", type=float,
                   help="per-op retry deadline (s); bounds the reference's "
                        "retry-forever default — set this with --fault-* "
                        "rates near 1.0 or the run retries indefinitely")
    p.add_argument("--retry-max-attempts", type=int,
                   help="retry attempt cap (0 = unlimited, reference default)")
    p.add_argument("--native-receive", action="store_true",
                   help="C++ HTTP receive path into pre-registered buffers "
                        "(pooled keep-alive; http and https endpoints)")
    p.add_argument("--http2", action="store_true",
                   help="media GETs over the native HTTP/2 client (the "
                        "reference's ForceAttemptHTTP2 branch, "
                        "main.go:76-80); h2c on http, TLS+ALPN on https")
    p.add_argument("--fetch-executor",
                   choices=("python", "native", "native-reactor",
                            "native-threads"),
                   help="read fan-out runtime: python worker threads, or "
                        "the C++ fetch executor — 'native' runs its epoll "
                        "reactor (event loop + lock-free completion "
                        "rings); 'native-threads' pins the legacy "
                        "thread-per-connection pool; 'native-reactor' "
                        "pins the reactor (plain-http endpoints)")
    p.add_argument("--no-direct", action="store_true", help="skip O_DIRECT")
    p.add_argument("--mount-cmd",
                   help="shell template run before FS workloads; {dir} "
                        "expands (read_operations.sh:18 convention)")
    p.add_argument("--unmount-cmd",
                   help="shell template run after FS workloads; {dir} expands")
    p.add_argument("--rounds", type=int,
                   help="listing rounds (round 0 = cold, rest hot)")
    p.add_argument("--ring", action="store_true",
                   help="pod-ingest: explicit ppermute ring instead of all_gather")
    p.add_argument("--num-processes", type=int,
                   help="multi-host: total process count (jax.distributed); "
                        "also TPUBENCH_NUM_PROCESSES")
    p.add_argument("--process-id", type=int,
                   help="multi-host: this process's id; also "
                        "TPUBENCH_PROCESS_ID")
    p.add_argument("--coordinator",
                   help="multi-host: coordinator host:port (process 0's "
                        "address); also TPUBENCH_COORDINATOR")
    p.add_argument("--save-config", help="write effective config JSON and exit")


def _add_lifecycle_flags(p: argparse.ArgumentParser) -> None:
    """Flags for the storage-lifecycle subcommands (ckpt-save /
    ckpt-restore / meta-storm) — kept off the common surface; only these
    parsers carry them."""
    p.add_argument("--ckpt-objects", type=int,
                   help="checkpoint shard-objects in the manifest "
                        "(default 4; one object per parameter shard)")
    p.add_argument("--ckpt-object-bytes", type=int,
                   help="bytes per shard-object (default 8 MiB)")
    p.add_argument("--ckpt-part-bytes", type=int,
                   help="resumable-upload part size (each part is one "
                        "content-range PUT; default 1 MiB)")
    p.add_argument("--ckpt-writers", type=int,
                   help="concurrent object uploads during ckpt-save "
                        "(default 4)")
    p.add_argument("--ckpt-readers", type=int,
                   help="concurrent shard fetches during ckpt-restore "
                        "(default 4)")
    p.add_argument("--ckpt-prefix",
                   help="object-name prefix; the manifest lands at "
                        "<prefix>MANIFEST.json (default ckpt/)")
    p.add_argument("--no-ckpt-verify", action="store_true",
                   help="skip the readback crc32 verification pass "
                        "(save) / shard byte-identity check (restore)")
    p.add_argument("--no-restore-device", action="store_true",
                   help="ckpt-restore: host-RAM restore only — skip "
                        "staging shards into device arrays across the "
                        "mesh")
    p.add_argument("--meta-objects", type=int,
                   help="meta-storm: small-object population size "
                        "(default 64)")
    p.add_argument("--meta-object-bytes", type=int,
                   help="meta-storm: bytes per small object (default 4 KiB)")
    p.add_argument("--meta-rate", type=float, dest="meta_rate",
                   help="meta-storm: offered metadata ops/second "
                        "(default 200)")
    p.add_argument("--meta-duration", type=float, dest="meta_duration",
                   help="meta-storm: virtual schedule seconds (default 2; "
                        "wall time scales with TPUBENCH_BENCH_SLEEP_SCALE)")
    p.add_argument("--meta-arrival", choices=("poisson", "bursty", "diurnal"),
                   help="meta-storm: arrival process (default poisson)")
    p.add_argument("--meta-mix",
                   help="meta-storm: op mix as kind:weight pairs over "
                        "list/stat/open (default list:1,stat:2,open:2)")
    p.add_argument("--meta-page-size", type=int,
                   help="meta-storm: maxResults page bound for list ops "
                        "(multi-page listings; default 16, 0 = one page)")
    p.add_argument("--meta-workers", type=int,
                   help="meta-storm: service worker threads the knee "
                        "saturates (default 8)")
    p.add_argument("--meta-sweep", action="store_true",
                   help="meta-storm: step offered load through the "
                        "multipliers and identify the saturation knee "
                        "(the --serve-sweep of metadata)")
    p.add_argument("--meta-sweep-points",
                   help="comma list of offered-load multipliers for "
                        "--meta-sweep (default 0.5,1,2,4)")
    p.add_argument("--lifecycle-seed", type=int,
                   help="arrival/mix seed (identical seeds replay "
                        "identical storms)")


def _add_serve_flags(p: argparse.ArgumentParser) -> None:
    """The serve-plane flag surface, shared by the ``serve`` and
    ``drill`` subcommands (the drill IS a serve run with an incident
    scripted into it — the knobs must never fork)."""
    p.add_argument("--serve-duration", type=float,
                   help="virtual schedule length in seconds "
                        "(default 4; wall time scales with "
                        "TPUBENCH_BENCH_SLEEP_SCALE)")
    p.add_argument("--serve-rate", type=float,
                   help="aggregate offered load, requests/second "
                        "(default 200)")
    p.add_argument("--serve-arrival",
                   choices=("poisson", "bursty", "diurnal", "trace"),
                   help="arrival process (default poisson; bursty = "
                        "two-state MMPP, diurnal = sinusoidal-rate "
                        "Poisson, trace = replayed timestamps from "
                        "--serve-trace)")
    p.add_argument("--serve-trace",
                   help="replayed-trace arrivals: JSON list of "
                        "arrival seconds (implies "
                        "--serve-arrival trace)")
    p.add_argument("--serve-tenants", type=int,
                   help="synthetic tenant population (default 100), "
                        "expanded over the class shares")
    p.add_argument("--serve-classes",
                   help="priority-class spec: JSON list of {name, "
                        "share, weight, deadline_ms, priority} "
                        "dicts, inline or @path (default "
                        "gold/silver/best_effort)")
    p.add_argument("--serve-workers", type=int,
                   help="service worker threads (default 8)")
    p.add_argument("--no-serve-qos", action="store_true",
                   help="QoS off: FIFO admission, no shedding, no "
                        "weighted budgets — the baseline arm of "
                        "the QoS A/B")
    p.add_argument("--serve-admission-cap", type=int,
                   help="requests in service at once (default = "
                        "--serve-workers; live-tunable via the "
                        "workers tune knob)")
    p.add_argument("--serve-queue-limit", type=int,
                   help="queued requests before overload shedding "
                        "(QoS mode; default 8x workers)")
    p.add_argument("--serve-readahead", type=int,
                   help="readahead depth in chunks over the arrival "
                        "schedule (0 = demand-only, the default)")
    p.add_argument("--serve-burst-factor", type=float,
                   help="bursty: burst-to-quiet rate ratio "
                        "(default 4)")
    p.add_argument("--serve-burst-fraction", type=float,
                   help="bursty: fraction of each cycle bursting "
                        "(default 0.25)")
    p.add_argument("--serve-seed", type=int,
                   help="arrival/popularity seed (identical seeds "
                        "replay identical schedules)")
    p.add_argument("--serve-sweep-points",
                   help="comma list of offered-load multipliers for "
                        "--serve-sweep (default 0.25,0.5,1,2,4)")
    p.add_argument("--serve-hosts", type=int,
                   help="elastic pod: fan the serve plane across N "
                        "hermetic threaded hosts whose misses route "
                        "through coop-cache consistent-hash "
                        "ownership (default 1 = single-host plane)")
    p.add_argument("--membership-timeline",
                   help="elastic membership events: JSON list of "
                        "[t0, t1, {action: host}] entries (inline "
                        "or @path) in virtual schedule seconds — "
                        "actions kill_host / leave_host (warm "
                        "handoff) / pause_host (resumes at t1) / "
                        "rejoin_host")
    p.add_argument("--resize-window", type=float,
                   help="virtual seconds of resize window the "
                        "scorecard brackets each membership event "
                        "with (default 1.0)")


def _add_drill_flags(p: argparse.ArgumentParser) -> None:
    """Flags owned by the ``drill`` subcommand — the incident script
    and the delta-save cadence."""
    p.add_argument("--drill-kill-at", type=float, dest="drill_kill_at",
                   help="virtual second the victim host is KILLED at "
                        "(default 1.0)")
    p.add_argument("--drill-join-at", type=float, dest="drill_join_at",
                   help="virtual second the cold replacement joins and "
                        "starts restoring (default 1.5; >= --drill-"
                        "kill-at)")
    p.add_argument("--drill-victim", type=int, dest="drill_victim",
                   help="host id to kill (default -1 = last host)")
    p.add_argument("--restore-class", dest="restore_class",
                   help="QoS class tag restore reads carry end-to-end "
                        "(default 'restore'; must not collide with a "
                        "serving class)")
    p.add_argument("--restore-priority", type=int, dest="restore_priority",
                   help="admission priority of restore reads "
                        "(default 1 — below gold, above best-effort)")
    p.add_argument("--restore-weight", type=float, dest="restore_weight",
                   help="cache/prefetch budget weight of the restore "
                        "class (default 2.0)")
    p.add_argument("--restore-deadline", type=float,
                   dest="restore_deadline",
                   help="restore-read deadline in ms (default 500)")
    p.add_argument("--restore-inflight", type=int, dest="restore_inflight",
                   help="restore reads in flight through the shared "
                        "admission queue (default 8)")
    p.add_argument("--restore-retries", type=int, dest="restore_retries",
                   help="re-stat retries per shard on torn reads "
                        "(default 3)")
    p.add_argument("--restore-direct", action="store_true",
                   dest="restore_direct",
                   help="A/B arm: restore reads bypass the coop cache "
                        "and fetch direct from origin (still holding "
                        "admission slots and cache budget)")
    p.add_argument("--save-interval", type=float, dest="save_interval",
                   help="virtual seconds between checkpoint saves under "
                        "traffic (default 1.0; 0 = no periodic saves)")
    p.add_argument("--full-saves", action="store_true", dest="full_saves",
                   help="A/B arm: every periodic save re-uploads ALL "
                        "shards instead of only dirty ones")
    p.add_argument("--dirty-fraction", type=float, dest="dirty_fraction",
                   help="fraction of shards each save pass dirties "
                        "(default 0.25)")
    p.add_argument("--drill-meta-rate", type=float, dest="drill_meta_rate",
                   help="concurrent metadata-storm mix, ops/second "
                        "(default 0 = no storm; shares the storm "
                        "quota ledger)")
    p.add_argument("--drill-sweep", action="store_true",
                   help="step the save interval through the multipliers "
                        "and locate the save-rate-vs-latency knee")
    p.add_argument("--drill-sweep-points",
                   help="comma list of save-interval multipliers for "
                        "--drill-sweep (default 0.5,1,2)")


def _add_fleet_flags(p: argparse.ArgumentParser) -> None:
    """Flags owned by the ``fleet`` subcommand — the simulated topology,
    the membership timeline generator, and the calibration plumbing."""
    p.add_argument("--fleet-hosts", type=int, dest="fleet_hosts",
                   help="simulated pod size, 64-4096 territory "
                        "(default 64; 0 = inherit --serve-hosts, the "
                        "agreement-gate arm)")
    p.add_argument("--fleet-pods", type=int, dest="fleet_pods",
                   help="partition the hosts into N pods with a "
                        "cross-pod routing ring above the per-pod "
                        "coop rings (default 0 = one pod per 128 "
                        "hosts, minimum one)")
    p.add_argument("--fleet-workers-per-host", type=int,
                   dest="fleet_workers_per_host",
                   help="simulated service slots per host (default 2; "
                        "0 = --serve-workers pod-wide, the "
                        "agreement-gate arm)")
    p.add_argument("--fleet-objects", type=int, dest="fleet_objects",
                   help="synthetic object population the Zipf tenant "
                        "mix draws over (default 64)")
    p.add_argument("--fleet-timeline",
                   choices=("none", "correlated_failure",
                            "rolling_upgrade"),
                   dest="fleet_timeline",
                   help="generated membership timeline: "
                        "correlated_failure kills --fleet-fail-"
                        "fraction of the hosts at --fleet-fail-at, "
                        "rolling_upgrade pauses every host in "
                        "staggered windows (default none)")
    p.add_argument("--fleet-fail-at", type=float, dest="fleet_fail_at",
                   help="correlated_failure: virtual second the blast "
                        "lands (default 0.5)")
    p.add_argument("--fleet-fail-fraction", type=float,
                   dest="fleet_fail_fraction",
                   help="correlated_failure: fraction of hosts killed "
                        "together (default 0.1)")
    p.add_argument("--fleet-recover", type=float, dest="fleet_recover",
                   help="correlated_failure: seconds until the victims "
                        "rejoin cold (default 0 = they stay dead)")
    p.add_argument("--fleet-upgrade-pause", type=float,
                   dest="fleet_upgrade_pause",
                   help="rolling_upgrade: pause window per host in "
                        "virtual seconds (default 0.2)")
    p.add_argument("--fleet-seed", type=int, dest="fleet_seed",
                   help="victim-selection seed (identical seeds replay "
                        "identical blast patterns; default 20)")
    p.add_argument("--calibrate-from", nargs="+", dest="calibrate_from",
                   metavar="JOURNAL",
                   help="fit per-phase service times from flight "
                        "journal base paths (.p<idx> siblings and "
                        ".gz variants discovered like `tpubench top`); "
                        "phases with too few samples fall back to the "
                        "configured constants with a warning")
    p.add_argument("--fleet-profile", dest="fleet_profile",
                   help="service-time profile JSON: written here after "
                        "--calibrate-from, loaded from here otherwise "
                        "(the --tune-profile round-trip shape)")
    p.add_argument("--fleet-sweep", action="store_true",
                   dest="fleet_sweep",
                   help="step offered load through the serve sweep "
                        "multipliers under the virtual driver and "
                        "locate the knee (p99 inflection)")


def build_config(args) -> BenchConfig:
    if args.config:
        with open(args.config) as f:
            cfg = BenchConfig.from_json(f.read())
    elif args.preset:
        cfg = preset(args.preset)
    else:
        cfg = BenchConfig()
    w, t, s, o = cfg.workload, cfg.transport, cfg.staging, cfg.obs
    if args.preset and args.config:
        raise SystemExit("--preset and --config are mutually exclusive")
    # --tune-profile on a normal workload applies a previously-written
    # recommendation (on `tpubench tune` it is the OUTPUT path). Applied
    # BEFORE the flag folding below, so an explicit flag on the same
    # command line wins over the profile's recommendation.
    if getattr(args, "tune_profile", None) and \
            getattr(args, "cmd", None) != "tune":
        from tpubench.workloads.tune_cmd import apply_tune_profile

        apply_tune_profile(cfg, args.tune_profile)
    for attr, dest in (
        ("bucket", "bucket"), ("project", "project"), ("dir", "dir"),
        ("workers", "workers"), ("read_calls", "read_calls_per_worker"),
        ("threads", "threads"), ("read_count", "read_count"),
        ("write_count", "write_count"), ("block_size_kb", "block_size_kb"),
        ("file_size_mb", "file_size_mb"), ("object_size", "object_size"),
        ("object_name_prefix", "object_name_prefix"), ("read_type", "read_type"),
        ("open_files", "open_files"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(w, dest, v)
    if args.protocol:
        t.protocol = args.protocol
    if args.endpoint:
        t.endpoint = args.endpoint
    if args.staging:
        s.mode = args.staging
    if getattr(args, "staging_drain", None):
        s.drain = args.staging_drain
    if getattr(args, "staging_depth", None) is not None:
        if args.staging_depth < 1:
            raise SystemExit(
                f"--staging-depth {args.staging_depth}: must be >= 1 "
                "(1 = fully synchronous staging)"
            )
        s.depth = args.staging_depth
    if args.no_double_buffer:
        s.double_buffer = False
    if args.validate:
        s.validate_checksum = True
    if args.enable_tracing:
        o.enable_tracing = True
    if args.trace_sample_rate is not None:
        o.trace_sample_rate = args.trace_sample_rate
    if args.trace_exporter:
        o.trace_exporter = args.trace_exporter
    if args.profile_dir:
        o.profile_dir = args.profile_dir
    if getattr(args, "profile_steps", None):
        o.profile_steps = args.profile_steps
        # Validate the window at parse time (one-line SystemExit on a
        # malformed spec), not at step N of the run.
        from tpubench.obs.profiling import parse_profile_steps

        parse_profile_steps(o.profile_steps)
    if getattr(args, "flight_journal", None):
        o.flight_journal = args.flight_journal
    if getattr(args, "journal_max_bytes", None) is not None:
        if args.journal_max_bytes < 0:
            raise SystemExit(
                f"--journal-max-bytes {args.journal_max_bytes}: must be "
                ">= 0 (0 = unbounded)"
            )
        o.journal_max_bytes = args.journal_max_bytes
    tel = cfg.telemetry
    if getattr(args, "telemetry_port", None) is not None:
        tel.port = args.telemetry_port
        # -1 is the documented "off" value — it must not flip the master
        # switch (the registry tap sits on the hot read path).
        tel.enabled = args.telemetry_port >= 0
    if getattr(args, "telemetry_interval", None) is not None:
        tel.interval_s = args.telemetry_interval
    if getattr(args, "telemetry_otlp", False):
        tel.otlp = True
    if getattr(args, "telemetry_otlp_endpoint", None):
        tel.otlp = True
        tel.otlp_endpoint = args.telemetry_otlp_endpoint
    from tpubench.config import validate_telemetry_config

    validate_telemetry_config(tel)
    if getattr(args, "flight_records", None) is not None:
        if args.flight_records < 0:
            raise SystemExit(
                f"--flight-records {args.flight_records}: must be >= 0 "
                "(0 disables the flight recorder)"
            )
        o.flight_records = args.flight_records
    if args.export:
        o.export = args.export
    if args.metrics_interval is not None:
        o.metrics_interval_s = args.metrics_interval
    if args.metrics_live:
        if args.export and args.export != "cloud":
            raise SystemExit("--metrics-live requires --export cloud")
        o.export = "cloud"  # the flag implies the cloud path; never a no-op
        o.export_dry_run = False
    if args.results_dir:
        o.results_dir = args.results_dir
    if getattr(args, "results_bucket", None):
        o.results_bucket = args.results_bucket
    if args.no_abort_on_error:
        w.abort_on_error = False
    if getattr(args, "fetch_executor", None):
        w.fetch_executor = args.fetch_executor
    if args.fault_error_rate is not None:
        t.fault.error_rate = args.fault_error_rate
    if args.fault_read_error_rate is not None:
        t.fault.read_error_rate = args.fault_read_error_rate
    if args.fault_latency is not None:
        t.fault.latency_s = args.fault_latency
    for attr, dest in (
        ("fault_per_read_latency", "per_read_latency_s"),
        ("fault_stall_s", "stall_s"),
        ("fault_stall_after_bytes", "stall_after_bytes"),
        ("fault_stall_rate", "stall_rate"),
        ("fault_drip_bps", "drip_bps"),
        ("fault_truncate_after_bytes", "truncate_after_bytes"),
        ("fault_reset_after_bytes", "reset_after_bytes"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(t.fault, dest, v)
    tail = t.tail
    if getattr(args, "hedge", False):
        tail.hedge = True
    if getattr(args, "hedge_delay", None) is not None:
        tail.hedge_delay_s = args.hedge_delay
    if getattr(args, "hedge_from_p99", False):
        tail.hedge = True  # the adaptive delay implies hedging
        tail.hedge_from_p99 = True
    if getattr(args, "watchdog", False):
        tail.watchdog = True
    if getattr(args, "stall_window", None) is not None:
        tail.stall_window_s = args.stall_window
    if getattr(args, "stall_floor_bps", None) is not None:
        tail.stall_floor_bps = args.stall_floor_bps
    if getattr(args, "breaker", False):
        tail.breaker = True
    if getattr(args, "breaker_failures", None) is not None:
        tail.breaker_failures = args.breaker_failures
    if getattr(args, "breaker_reset", None) is not None:
        tail.breaker_reset_s = args.breaker_reset
    if getattr(args, "breaker_probes", None) is not None:
        tail.breaker_probes = args.breaker_probes
    if tail.hedge_delay_s < 0:
        raise SystemExit(
            f"--hedge-delay {tail.hedge_delay_s}: must be >= 0"
        )
    if tail.stall_window_s <= 0:
        raise SystemExit(
            f"--stall-window {tail.stall_window_s}: must be > 0"
        )
    if tail.stall_floor_bps < 0:
        raise SystemExit(
            f"--stall-floor-bps {tail.stall_floor_bps}: must be >= 0"
        )
    pl = cfg.pipeline
    for attr in (
        "cache_bytes", "readahead", "readahead_bytes", "prefetch_workers",
        "steps", "epochs", "batch_shards", "chunk_bytes",
        "step_compute_ms", "stall_threshold_ms",
        "slab_bytes", "pool_slabs",
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(pl, attr, v)
    if getattr(args, "pipeline_pod", False):
        pl.pod = True
    if getattr(args, "no_slab_pool", False):
        pl.slab_pool = False
    from tpubench.config import validate_pipeline_config

    validate_pipeline_config(pl, staging=s)
    co = cfg.coop
    if getattr(args, "coop", False):
        co.enabled = True
    for attr, dest in (
        ("coop_hosts", "hosts"), ("coop_host_id", "host_id"),
        ("coop_vnodes", "vnodes"),
        ("peer_budget_bytes", "peer_budget_bytes"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(co, dest, v)
    if getattr(args, "coop_channel", None):
        co.channel = args.coop_channel
    if getattr(args, "no_coop_demote", False):
        co.demote = False
    from tpubench.config import validate_coop_config

    validate_coop_config(co)
    sv = cfg.serve
    for attr, dest in (
        ("serve_duration", "duration_s"), ("serve_rate", "rate_rps"),
        ("serve_tenants", "tenants"), ("serve_workers", "workers"),
        ("serve_admission_cap", "admission_cap"),
        ("serve_queue_limit", "queue_limit"),
        ("serve_readahead", "readahead"),
        ("serve_burst_factor", "burst_factor"),
        ("serve_burst_fraction", "burst_fraction"),
        ("serve_seed", "seed"),
        ("serve_hosts", "hosts"),
        ("resize_window", "resize_window_s"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(sv, dest, v)
    if getattr(args, "membership_timeline", None):
        raw = args.membership_timeline
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        try:
            sv.membership_timeline = json.loads(raw)
        except json.JSONDecodeError as e:
            raise SystemExit(
                f"--membership-timeline: invalid JSON: {e}"
            ) from None
    if getattr(args, "serve_arrival", None):
        sv.arrival = args.serve_arrival
    if getattr(args, "serve_trace", None):
        sv.trace_path = args.serve_trace
        sv.arrival = "trace"
    if getattr(args, "no_serve_qos", False):
        sv.qos = False
    if getattr(args, "serve_classes", None):
        raw = args.serve_classes
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        try:
            sv.classes = json.loads(raw)
        except json.JSONDecodeError as e:
            raise SystemExit(
                f"--serve-classes: invalid JSON: {e}"
            ) from None
    if getattr(args, "serve_sweep_points", None):
        try:
            sv.sweep_points = [
                float(x) for x in args.serve_sweep_points.split(",") if x
            ]
        except ValueError:
            raise SystemExit(
                f"--serve-sweep-points "
                f"{args.serve_sweep_points!r}: expected a comma list "
                "of positive numbers"
            ) from None
    fc = cfg.fleet
    for attr, dest in (
        ("fleet_hosts", "hosts"), ("fleet_pods", "pods"),
        ("fleet_workers_per_host", "workers_per_host"),
        ("fleet_objects", "objects"),
        ("fleet_timeline", "timeline"),
        ("fleet_fail_at", "fail_at_s"),
        ("fleet_fail_fraction", "fail_fraction"),
        ("fleet_recover", "recover_s"),
        ("fleet_upgrade_pause", "upgrade_pause_s"),
        ("fleet_seed", "seed"),
        ("fleet_profile", "profile_path"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(fc, dest, v)
    if getattr(args, "calibrate_from", None):
        fc.calibrate_from = list(args.calibrate_from)
    if getattr(args, "fleet_sweep", False):
        fc.sweep = True
    from tpubench.config import validate_serve_config

    validate_serve_config(sv)
    if getattr(args, "cmd", None) == "fleet":
        # Only the fleet command pays fleet validation — any other
        # command carrying a config file with default fleet values must
        # not be refused (the drill-gating precedent above).
        from tpubench.config import validate_fleet_config

        validate_fleet_config(fc, sv)
    lc = cfg.lifecycle
    for attr, dest in (
        ("ckpt_objects", "objects"), ("ckpt_object_bytes", "object_bytes"),
        ("ckpt_part_bytes", "part_bytes"), ("ckpt_writers", "writers"),
        ("ckpt_readers", "readers"),
        ("meta_objects", "meta_objects"),
        ("meta_object_bytes", "meta_object_bytes"),
        ("meta_rate", "meta_rate_rps"), ("meta_duration", "meta_duration_s"),
        ("meta_page_size", "meta_page_size"),
        ("meta_workers", "meta_workers"),
        ("lifecycle_seed", "seed"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(lc, dest, v)
    if getattr(args, "ckpt_prefix", None):
        lc.prefix = args.ckpt_prefix
    if getattr(args, "no_ckpt_verify", False):
        lc.verify = False
    if getattr(args, "no_restore_device", False):
        lc.restore_device = False
    if getattr(args, "meta_arrival", None):
        lc.meta_arrival = args.meta_arrival
    if getattr(args, "meta_mix", None):
        lc.meta_mix = args.meta_mix
    if getattr(args, "meta_sweep_points", None):
        try:
            lc.sweep_points = [
                float(x) for x in args.meta_sweep_points.split(",") if x
            ]
        except ValueError:
            raise SystemExit(
                f"--meta-sweep-points {args.meta_sweep_points!r}: "
                "expected a comma list of positive numbers"
            ) from None
    from tpubench.config import validate_lifecycle_config

    validate_lifecycle_config(lc)
    dc = cfg.drill
    for attr, dest in (
        ("drill_kill_at", "kill_at_s"), ("drill_join_at", "join_at_s"),
        ("drill_victim", "victim"),
        ("restore_class", "restore_class"),
        ("restore_priority", "restore_priority"),
        ("restore_weight", "restore_weight"),
        ("restore_deadline", "restore_deadline_ms"),
        ("restore_inflight", "restore_inflight"),
        ("restore_retries", "restore_retries"),
        ("save_interval", "save_interval_s"),
        ("dirty_fraction", "dirty_fraction"),
        ("drill_meta_rate", "meta_rate_rps"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(dc, dest, v)
    if getattr(args, "restore_direct", False):
        dc.restore_via_coop = False
    if getattr(args, "full_saves", False):
        dc.delta_saves = False
    if getattr(args, "drill_sweep_points", None):
        try:
            dc.sweep_points = [
                float(x) for x in args.drill_sweep_points.split(",") if x
            ]
        except ValueError:
            raise SystemExit(
                f"--drill-sweep-points {args.drill_sweep_points!r}: "
                "expected a comma list of positive numbers"
            ) from None
    if getattr(args, "cmd", None) == "drill":
        # Only the drill command pays the drill's cross-plane
        # constraints (hosts >= 2, class collision) — a serve run with
        # default drill config must not be refused.
        from tpubench.config import validate_drill_config

        validate_drill_config(dc, sv)
    tn = cfg.tune
    if getattr(args, "tune", False):
        tn.enabled = True
    for attr, dest in (
        ("tune_window", "window_s"), ("tune_warmup", "warmup_windows"),
        ("tune_p99_guard", "p99_guard"), ("tune_epsilon", "epsilon"),
        ("tune_duration", "duration_s"),
    ):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(tn, dest, v)
    if getattr(args, "tune_knobs", None):
        tn.knobs = [k.strip() for k in args.tune_knobs.split(",") if k.strip()]
    from tpubench.config import validate_tune_config

    validate_tune_config(tn)
    if args.retry_deadline is not None:
        t.retry.deadline_s = args.retry_deadline
    if args.retry_max_attempts is not None:
        t.retry.max_attempts = args.retry_max_attempts
    if args.native_receive:
        t.native_receive = True
    if getattr(args, "http2", False):
        t.http2 = True
    if getattr(args, "tls_ca_file", None):
        t.tls_ca_file = args.tls_ca_file
    if getattr(args, "tls_insecure_skip_verify", False):
        t.tls_insecure_skip_verify = True
    if getattr(args, "mount_cmd", None):
        w.mount_cmd = args.mount_cmd
    if getattr(args, "unmount_cmd", None):
        w.unmount_cmd = args.unmount_cmd
    if getattr(args, "rounds", None) is not None:
        w.list_rounds = args.rounds
    # Multi-host bring-up knobs: flags win over env autodetect, so one
    # launch template works on every VM of a pod (reference property: the
    # same binary is launchable everywhere, main.go:158).
    d = cfg.dist
    env = os.environ
    if env.get("TPUBENCH_NUM_PROCESSES"):
        d.num_processes = int(env["TPUBENCH_NUM_PROCESSES"])
    if env.get("TPUBENCH_PROCESS_ID"):
        d.process_id = int(env["TPUBENCH_PROCESS_ID"])
    if env.get("TPUBENCH_COORDINATOR"):
        d.coordinator_address = env["TPUBENCH_COORDINATOR"]
    pid_given = bool(env.get("TPUBENCH_PROCESS_ID"))
    if getattr(args, "num_processes", None) is not None:
        d.num_processes = args.num_processes
    if getattr(args, "process_id", None) is not None:
        d.process_id = args.process_id
        pid_given = True
    if getattr(args, "coordinator", None):
        d.coordinator_address = args.coordinator
    if d.num_processes <= 1 and (pid_given or d.coordinator_address):
        # A pod member that dropped --num-processes must not silently run a
        # standalone bench while the rest of the pod hangs waiting for it
        # (including the explicit --process-id 0 host).
        raise SystemExit(
            "--process-id/--coordinator set but --num-processes is 1: "
            "pass the pod's total process count on every host"
        )
    # Fault-config sanity (rates in [0,1], non-negative durations, sane
    # phase windows) fails HERE, at parse time — not an hour into a run.
    from tpubench.config import validate_fault_config

    validate_fault_config(t.fault, "transport.fault")
    if o.results_bucket and t.protocol not in ("http", "grpc"):
        # Fail at parse time, not after an hour-long run: upload_result
        # needs an object-store protocol ('local' roots at workload.dir,
        # 'fake' drops the bytes in a throwaway in-process store).
        raise SystemExit(
            f"--results-bucket requires --protocol http|grpc, "
            f"not {t.protocol!r}"
        )
    return cfg


# Workloads whose RunResult is already pod-global (collectives / DCN
# aggregation inside the workload): process 0 owns the one report. Per-host
# workloads (read, FS paths) measure THIS host — every process reports,
# tagged by process index.
POD_COLLECTIVE_CMDS = {"pod-ingest", "stream", "gather-bench"}


def _finish(res: RunResult, cfg: BenchConfig, quiet: bool = False,
            pod_collective: bool = True) -> None:
    topo = res.extra.get("topology")
    tag = ""
    if topo and topo.get("process_count", 1) > 1:
        idx = topo.get("process_index", 0)
        if pod_collective:
            if idx != 0:
                # This process participated in the collectives; the pod-level
                # numbers live in process 0's report — don't race N files.
                print(f"process {idx}/{topo['process_count']} done "
                      f"(report at process 0)")
                return
        else:
            # Per-host measurement: EVERY process reports its own host,
            # uniformly tagged (p0, p1, …) so one glob collects the pod.
            tag = f"p{idx}"
    path = write_result(res, cfg.obs.results_dir, tag=tag)
    if not quiet:
        print(res.format())
        print(f"result: {path}")
    if cfg.obs.results_bucket:
        obj = upload_result(cfg, path)
        if not quiet:
            print(f"uploaded: {cfg.obs.results_bucket}/{obj}")


def _bringup(cfg: BenchConfig) -> dict:
    """Multi-host control-plane bring-up (jax.distributed over DCN) when
    configured; returns topology facts stamped into the run result."""
    from tpubench.dist.bringup import initialize

    return initialize(cfg.dist)


def cmd_read(cfg: BenchConfig, args) -> RunResult:
    from tpubench.obs.tracing import tracer_session
    from tpubench.staging.device import make_sink_factory
    from tpubench.workloads.read import run_read

    # Flush-on-exit (trace_exporter.go:55-60) via the ONE shared
    # discipline: without the session's finally-shutdown, batched spans
    # (console/cloud_trace exporters) are dropped at process exit — the
    # reference's lost-final-flush bug class. chaos and tune ride the
    # same context manager (the shutdown-coverage audit in
    # tests/test_trace_plane.py pins all three).
    with tracer_session(cfg) as tracer:
        return run_read(
            cfg, tracer=tracer, sink_factory=make_sink_factory(cfg)
        )


def cmd_pod_ingest(cfg: BenchConfig, args) -> RunResult:
    from tpubench.workloads.pod_ingest import run_pod_ingest

    return run_pod_ingest(cfg, ring=args.ring)


def chaos_timeline_from_args(args) -> list:
    """The ``tpubench chaos`` fault timeline: explicit JSON
    (``--chaos-timeline``, inline or ``@file``), or the single-phase
    shorthand built from ``--chaos-fault``/``--chaos-start``/
    ``--chaos-duration`` with fault parameters from the ``--fault-*``
    flags (sensible defaults per kind)."""
    if args.chaos_timeline:
        raw = args.chaos_timeline
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        try:
            timeline = json.loads(raw)
        except json.JSONDecodeError as e:
            raise SystemExit(f"--chaos-timeline: invalid JSON: {e}") from None
        if not isinstance(timeline, list):
            raise SystemExit(
                "--chaos-timeline: expected a JSON list of "
                "[t0, t1, {fault fields}] entries"
            )
        return timeline
    t0 = args.chaos_start
    t1 = t0 + args.chaos_duration

    def pick(attr, default):
        v = getattr(args, attr, None)
        return default if v is None else v

    kind = args.chaos_fault
    if kind == "stall":
        plan = {
            "stall_s": pick("fault_stall_s", 0.4),
            "stall_rate": pick("fault_stall_rate", 1.0),
            "stall_after_bytes": pick("fault_stall_after_bytes", 0),
        }
    elif kind == "blackhole":
        # Bytes stop and never resume within any sane window; hedges and
        # the watchdog are the only way out.
        plan = {
            "stall_s": 3600.0,
            "stall_rate": pick("fault_stall_rate", 1.0),
            "stall_after_bytes": pick("fault_stall_after_bytes", 0),
        }
    elif kind == "drip":
        plan = {"drip_bps": pick("fault_drip_bps", 64 * KB)}
    elif kind == "truncate":
        plan = {"truncate_after_bytes": pick("fault_truncate_after_bytes", 64 * KB)}
    elif kind == "reset":
        plan = {"reset_after_bytes": pick("fault_reset_after_bytes", 64 * KB)}
    elif kind == "error":
        plan = {"error_rate": pick("fault_error_rate", 0.5)}
    else:  # latency
        plan = {"latency_s": pick("fault_latency", 0.2)}
    return [[t0, t1, plan]]


def cmd_prepare(cfg: BenchConfig, args) -> None:
    from tpubench.workloads.fsbench import prepare_files

    w = cfg.workload
    if args.layout == "flat":
        prepare_files(w.dir, max(w.threads, w.open_files), w.file_size_mb * MB)
    else:  # ssd_test layout: Workload.<i>/0
        import os

        from tpubench.storage.base import deterministic_bytes

        for i in range(w.threads):
            d = os.path.join(w.dir, f"Workload.{i}")
            os.makedirs(d, exist_ok=True)
            p = os.path.join(d, "0")
            size = w.file_size_mb * MB
            if not (os.path.exists(p) and os.path.getsize(p) == size):
                with open(p, "wb") as f:
                    f.write(deterministic_bytes(f"Workload.{i}/0", size).tobytes())
    print(f"prepared files under {w.dir}")


def cmd_sweep(cfg: BenchConfig, args, topo=None) -> None:
    """Protocol A/B × size sweep (execute_pb.sh + read_operations.sh:8-14).

    A per-host measurement: under multi-host config every process runs and
    writes its own rows, tagged with its process index."""
    tag = ""
    if topo and topo.get("process_count", 1) > 1:
        tag = f"p{topo['process_index']}"

    protocols = args.sweep_protocols.split(",")
    sizes = {
        "256kb": (256 * KB, 1000),
        "1mb": (1 * MB, 100),
        "100mb": (100 * MB, 10),
        "1gb": (1024 * MB, 1),
    }
    chosen = args.sweep_sizes.split(",") if args.sweep_sizes else list(sizes)
    # --sweep-native adds a receive-path axis: every protocol × size cell
    # runs once through the Python client and once through the C++ engine
    # (same pooled keep-alive discipline on both, so the A/B isolates the
    # receive loop — the comparison the native path exists for).
    native_axis = [False, True] if getattr(args, "sweep_native", False) else [None]
    if native_axis[0] is not None:
        # Fail in milliseconds, not after the Python-path cells have run
        # (same fail-at-start rule as the --results-bucket check).
        from tpubench.native.engine import get_engine

        if get_engine() is None:
            raise SystemExit(
                "--sweep-native: the native engine is unavailable "
                "(C++ toolchain missing?)"
            )
    rows = []
    for proto in protocols:
        for sz in chosen:
            for native in native_axis:
                if native and proto not in ("http", "grpc"):
                    # No Python-vs-native axis for fake/local protocols,
                    # nor for http2 (the h2 client IS the native engine —
                    # its cell measures the protocol, not the runtime).
                    continue
                size, count = sizes[sz]
                c = BenchConfig.from_dict(cfg.to_dict())
                # "http2" = the reference's ForceAttemptHTTP2 branch
                # (main.go:76-80): same JSON endpoint, h2 transport — the
                # h1-vs-h2 A/B the reference could run (main.go:64).
                c.transport.protocol = "http" if proto == "http2" else proto
                if proto == "http2":
                    c.transport.http2 = True
                c.workload.object_size = size
                c.workload.read_calls_per_worker = min(
                    count, c.workload.read_calls_per_worker
                )
                if native is not None:
                    c.transport.native_receive = native
                res = cmd_read(c, args)
                res.extra["sweep"] = {"protocol": proto, "size": sz}
                if native is not None:
                    res.extra["sweep"]["native_receive"] = native
                path = write_result(res, cfg.obs.results_dir, tag=tag)
                if cfg.obs.results_bucket:
                    upload_result(cfg, path)
                row = {
                    "protocol": proto,
                    "size": sz,
                    "gbps": res.gbps,
                    "p50_ms": res.summaries["read"].p50_ms,
                    "p99_ms": res.summaries["read"].p99_ms,
                    "result": path,
                }
                if native is not None:
                    row["native_receive"] = native
                rows.append(row)
    print(json.dumps(rows, indent=2))


def main(argv=None) -> int:
    top = argparse.ArgumentParser(prog="tpubench", description=__doc__)
    sub = top.add_subparsers(dest="cmd", required=True)

    def add(name, help_):
        p = sub.add_parser(name, help=help_)
        _add_common(p)
        return p

    add("read", "root GCS read bench (reference main.go)")
    add("train-ingest", "step-paced training-loop ingest: chunk cache + "
                        "readahead prefetch + data-stall accounting "
                        "(see --cache-bytes/--readahead/--steps/"
                        "--step-compute-ms)")
    add("pod-ingest", "sharded object → pod HBM with ICI all-gather")
    stream = add("stream", "pipelined multi-object pod ingest (fetch ∥ stage+gather)")
    stream.add_argument("--objects", type=int, default=8)
    stream.add_argument("--snapshot", help="periodic progress snapshot JSON path")
    stream.add_argument("--resume-from",
                        help="resume a stream from a prior run's snapshot "
                             "JSON: already-delivered objects are skipped")
    gb = add("gather-bench", "ICI collective bandwidth vs mesh size")
    gb.add_argument("--shard-mb", type=float, default=4.0)
    gb.add_argument("--reps", type=int, default=5)
    gb.add_argument("--collective",
                    choices=("all_gather", "ring", "reduce_scatter", "psum"),
                    default="",
                    help="which collective to benchmark (default "
                         "all_gather; --ring is shorthand for ring)")
    mcs = sub.add_parser(
        "multichip-sweep",
        help="pod-ingest + collective sweep over simulated meshes "
             "(one subprocess per size; writes MULTICHIP_SWEEP.json)",
    )
    # Flags/defaults/parsing live in ONE place: tpubench.dist.sweep.main
    # (this subcommand forwards only what the user typed).
    mcs.add_argument("--sizes")
    mcs.add_argument("--shard-mb")
    mcs.add_argument("--reps")
    mcs.add_argument("--out")
    chaos = add("chaos", "scripted fault timeline + resilience scorecard "
                         "(hermetic: fake backend or in-process fake "
                         "server; see --chaos-*)")
    chaos.add_argument("--chaos-workload",
                       choices=("read", "pod-ingest", "train-ingest",
                                "serve"),
                       default="read",
                       help="workload the fault timeline runs against "
                            "(train-ingest: the fault schedule exercises "
                            "the prefetcher — a blackhole shows up as "
                            "data-stall time, never a hang; serve: the "
                            "open-loop plane — with --serve-hosts >= 2 "
                            "the timeline may also carry host-level "
                            "kill_host/leave_host/pause_host/rejoin_host "
                            "entries that resize the pod under load)")
    chaos.add_argument("--chaos-timeline",
                       help="JSON [[t0,t1,{fault fields}],...] (seconds "
                            "from run start), or @path to a JSON file; "
                            "overrides the --chaos-fault trio")
    chaos.add_argument("--chaos-fault",
                       choices=("stall", "blackhole", "drip", "truncate",
                                "reset", "error", "latency"),
                       default="stall",
                       help="single-phase shorthand: which fault the "
                            "window injects (parameters from --fault-*)")
    chaos.add_argument("--chaos-start", type=float, default=2.0,
                       help="fault window start, seconds from run start")
    chaos.add_argument("--chaos-duration", type=float, default=2.0,
                       help="fault window length in seconds")
    # Elastic-pod knobs for chaos serve runs (--chaos-workload serve):
    # the host-level kill/leave/pause/rejoin entries ride
    # --chaos-timeline; these size the pod they act on.
    for flag, kw in (
        ("--serve-hosts", dict(type=int, dest="serve_hosts")),
        ("--serve-duration", dict(type=float, dest="serve_duration")),
        ("--serve-rate", dict(type=float, dest="serve_rate")),
        ("--serve-workers", dict(type=int, dest="serve_workers")),
        ("--resize-window", dict(type=float, dest="resize_window")),
    ):
        chaos.add_argument(flag, help=argparse.SUPPRESS, **kw)
    serve = add("serve", "open-loop multi-tenant traffic plane: arrival "
                         "processes (poisson/bursty/diurnal/trace) drive "
                         "thousands of Zipf-hot tenants with per-class "
                         "QoS — priority admission, weighted cache/"
                         "prefetch budgets, deadline-aware shedding — "
                         "through the full backend/cache stack; "
                         "--serve-sweep steps offered load to the "
                         "saturation knee")
    serve.add_argument("--serve-sweep", action="store_true",
                       help="step offered load through the configured "
                            "multipliers of --serve-rate and emit the "
                            "latency-vs-load curve with the knee "
                            "identified (p99 inflection)")
    _add_serve_flags(serve)
    drill = add("drill", "production incident drill: the elastic pod "
                         "serves open-loop multi-tenant traffic while a "
                         "scripted kill takes a host down and a cold "
                         "replacement joins and ckpt-restores THROUGH "
                         "the shared coop-cache/admission stack, with "
                         "periodic delta checkpoint saves riding under "
                         "the same traffic; scorecard: gold SLO during "
                         "the restore window vs steady state, "
                         "time-to-restore vs time-to-rewarm, origin-"
                         "byte amplification, per-phase blame")
    _add_serve_flags(drill)
    _add_lifecycle_flags(drill)
    _add_drill_flags(drill)
    fleet = add("fleet", "virtual-time fleet simulation: the SAME serve/"
                         "qos/membership/coop code under a discrete-"
                         "event driver instead of worker threads — "
                         "64-4096 simulated hosts, multi-pod topologies "
                         "with cross-pod routing, diurnal multi-tenant "
                         "mixes and correlated-failure / rolling-"
                         "upgrade membership timelines, scored by the "
                         "real serve + membership scorecards; service "
                         "times calibrate from flight journals via "
                         "--calibrate-from")
    _add_serve_flags(fleet)
    _add_fleet_flags(fleet)
    for name, help_ in (
        ("ckpt-save", "storage lifecycle: save a sharded checkpoint "
                      "through resumable multi-part uploads (session -> "
                      "content-range parts -> finalize, part-level "
                      "retry/resume through the fault plane); scorecard: "
                      "save goodput, part p50/p99, resumed parts, zero "
                      "corrupt finalizes"),
        ("ckpt-restore", "storage lifecycle: restore the saved manifest "
                         "into sharded device arrays across the mesh "
                         "(per-host shard ranges via dist.shard); "
                         "time-to-restore is the headline metric, bytes "
                         "verified against the manifest crc32s"),
        ("meta-storm", "storage lifecycle: open-loop list/stat/open "
                       "storms over many small objects, driven by the "
                       "arrivals plane (poisson/bursty/diurnal); "
                       "--meta-sweep steps offered load to the "
                       "saturation knee"),
    ):
        _add_lifecycle_flags(add(name, help_))
    tune = add("tune", "adaptive ingest autotuner: offline coordinate "
                       "sweep or online AIMD session over read/"
                       "train-ingest; emits a convergence trace + a "
                       "recommended-config block (reusable via "
                       "--tune-profile)")
    tune.add_argument("--tune-mode", choices=("sweep", "online", "ab"),
                      default="online",
                      help="sweep = offline coordinate sweep; online = "
                           "one adaptive session; ab = both plus the "
                           "static-vs-adaptive comparison")
    tune.add_argument("--tune-workload", choices=("read", "train-ingest"),
                      default="read",
                      help="workload the tuning session drives")
    probe = add("probe", "host→HBM transfer-physics probe (fixed cost, "
                         "size sweep, burst/floor shaping, slow start)")
    probe.add_argument("--cycles", type=int, default=8,
                       help="identical measure cycles for burst/floor detection")
    probe.add_argument("--cycle-sleep", type=float, default=2.0)
    rpl = add("replay", "re-drive a recorded scenario bundle through the "
                        "CURRENT transport/cache/QoS/coop/membership "
                        "config: arrivals ride the trace schedule, faults "
                        "re-arm via FaultPlan, membership feeds the "
                        "elastic serve plane; prints the replay-vs-"
                        "original scorecard (hermetic: fake backend or "
                        "in-process fake server)")
    rpl.add_argument("bundle",
                     help="replay bundle path from `tpubench record` "
                          "(tpubench-bundle/1 JSON, .gz transparent)")
    # Only the SYSTEM half of the serve knobs (the fingerprint's
    # serve_system set): the scenario half — duration, rate, arrival,
    # tenants, classes, seed, membership — comes from the bundle.
    rpl.add_argument("--serve-workers", type=int,
                     help="service worker threads for the replay arm")
    rpl.add_argument("--no-serve-qos", action="store_true",
                     help="replay the scenario with QoS off (an A/B arm "
                          "against the recorded baseline)")
    rpl.add_argument("--serve-admission-cap", type=int,
                     help="requests in service at once")
    rpl.add_argument("--serve-queue-limit", type=int,
                     help="queued requests before overload shedding")
    rpl.add_argument("--serve-readahead", type=int,
                     help="readahead depth in chunks over the replayed "
                          "schedule")
    recp = sub.add_parser(
        "record",
        help="distill a serve run's flight journal(s) into a portable, "
             "versioned replay bundle (tpubench-bundle/1): arrival "
             "timeline, object population, fault plan, membership "
             "timeline, tenant/class map, config fingerprint — "
             "re-drivable via `tpubench replay`, diffable via "
             "`tpubench report --fail-on`",
    )
    recp.add_argument("journals", nargs="+",
                      help="flight-journal path(s) from ONE serve run "
                           "(per-host .p<idx> siblings merge; sweep "
                           ".pt<i> points are different runs — record "
                           "them separately)")
    recp.add_argument("--out", required=True,
                      help="bundle output path; a .gz suffix gzips "
                           "(canonical JSON either way, byte-stable "
                           "across re-records)")
    recp.add_argument("--name", default="",
                      help="scenario name stamped into the bundle "
                           "(default: the source bundle's name when "
                           "recording a replay journal, else derived "
                           "from the --out basename)")
    fs = {
        "read-fs": "sequential FS read (read_operation)",
        "write": "durable write (write_operations)",
        "list": "listing bench (list_operation)",
        "open": "open/FD-hold bench (open_file)",
        "ssd": "block-latency percentiles (ssd_test)",
    }
    for name, help_ in fs.items():
        add(name, help_)
    prep = add("prepare", "generate worker-indexed data files")
    prep.add_argument("--layout", choices=("flat", "ssd"), default="flat")
    sweep = add("sweep", "protocol A/B × size sweep (execute_pb.sh)")
    sweep.add_argument("--sweep-protocols", default="http,grpc",
                       help="comma list of http,http2,grpc,fake — http2 is "
                            "the reference's ForceAttemptHTTP2 branch "
                            "(main.go:76-80) on the native h2 client")
    sweep.add_argument("--sweep-sizes", default="")
    sweep.add_argument("--sweep-native", action="store_true",
                       help="add a receive-path axis: every cell runs with "
                            "the Python client AND the C++ native receive "
                            "(same keep-alive discipline; isolates the "
                            "receive loop)")
    add("info", "print effective config and environment")
    add("preflight", "validate auth/bucket/DirectPath/engine before a run")
    topp = sub.add_parser(
        "top",
        help="live terminal dashboard over streaming flight journals: "
             "rolling goodput GB/s(/chip), per-phase p50/p99, cache hit "
             "ratio, staging/hedge/breaker/tune counters, straggler-host "
             "highlighting; tails <journal>(.p<idx>)(.gz) files as the "
             "run flushes them (--telemetry-port streams every tick)",
    )
    topp.add_argument("journals", nargs="+",
                      help="flight-journal base path(s); per-host "
                           ".p<idx> siblings are discovered "
                           "automatically")
    topp.add_argument("--interval", type=float, default=2.0,
                      help="refresh seconds (default 2)")
    topp.add_argument("--once", action="store_true",
                      help="print a single plain frame and exit "
                           "(tests/CI)")
    topp.add_argument("--window", type=float, default=10.0,
                      help="rolling-goodput window seconds (default 10)")
    topp.add_argument("--no-color", action="store_true",
                      help="plain frames (no ANSI highlighting)")
    topp.add_argument("--frames", type=int,
                      help="exit after N refreshes (default: run until "
                           "Ctrl-C)")
    chk = sub.add_parser(
        "check",
        help="invariant-analysis plane: AST passes mechanizing the "
             "recurring review findings (flight-op lifecycle, thread "
             "hygiene, slab-lease balance, determinism & bounds, "
             "catalog-drift guards, lock-order graph); nonzero exit "
             "on findings; vetted allowlist entries require "
             "justifications (see README 'Static analysis & "
             "sanitizers')",
    )
    chk.add_argument("--json", action="store_true",
                     help="machine output (tpubench-check/1 schema)")
    chk.add_argument("--allowlist",
                     help="override the checked-in allowlist path "
                          "(tpubench/analysis/allowlist.json)")
    chk.add_argument("--no-drift", action="store_true",
                     help="skip the runtime catalog-drift guards (pure "
                          "AST passes only — faster, no engine probe)")
    chk.add_argument("paths", nargs="*",
                     help="restrict analysis to these files (default: "
                          "the whole tpubench tree)")
    rep = sub.add_parser(
        "report",
        help="summarize/compare result JSONs (percentile blocks, A/B "
             "deltas, sweep tables — replaces the reference's matplotlib "
             "recipe, README.md:15-36); `report timeline <journals...>` "
             "merges flight journals into the pod-level per-phase "
             "p50/p99 + straggler report; `report trace <journals...>` "
             "stitches them into cross-host span trees with tail-based "
             "sampling, critical-path attribution and the p99 blame "
             "table",
    )
    rep.add_argument("results", nargs="+",
                     help="result/sweep JSON paths — or `timeline`/"
                          "`trace` followed by flight-journal paths")
    rep.add_argument("--head-sample", type=float, default=0.05,
                     help="report trace: unbiased per-trace head-sample "
                          "rate kept IN ADDITION to the slowest decile "
                          "(default 0.05; decided from the trace id, so "
                          "every host and re-run keeps the same traces)")
    rep.add_argument("--slow-keep", type=int, default=512,
                     help="report trace: memory bound on kept trees "
                          "(slowest win; default 512 — the EXACT_SAMPLE_"
                          "CAP discipline)")
    rep.add_argument("--show-traces", type=int, default=3,
                     help="report trace: how many slowest span trees to "
                          "print in full (default 3)")
    rep.add_argument("--fail-on", action="append", default=[],
                     metavar="EXPR",
                     help="regression gate <metric><op><threshold>, e.g. "
                          "'gold_slo<0.95' or 'goodput_retention<0.9'; "
                          "repeatable — exit 1 when any gate trips on "
                          "any document, 2 when the metric exists in "
                          "none (a typo'd gate must fail CI loudly)")

    args = top.parse_args(argv)
    if args.cmd == "check":
        # Static analysis: jax-free, device-free — runnable on any CI
        # box or coordinator VM, same policy as report/top.
        from tpubench.analysis import run_cli_check

        return run_cli_check(
            json_out=args.json, paths=args.paths or None,
            allowlist_path=args.allowlist,
            with_drift=not args.no_drift,
        )
    if args.cmd == "top":
        # Live dashboard: jax-free, no common config (like report) —
        # runnable on a coordinator VM that never touches a device.
        from tpubench.obs.live import run_top

        return run_top(
            args.journals, interval_s=args.interval, once=args.once,
            window_s=args.window,
            color=False if args.no_color else None,
            iterations=args.frames,
        )
    if args.cmd == "report":
        # Offline post-processing: no jax, no common config needed.
        from tpubench.workloads.report_cmd import (
            run_report,
            run_timeline,
            run_trace,
        )

        if args.results and args.results[0] in ("timeline", "trace"):
            mode = args.results[0]
            if len(args.results) < 2:
                raise SystemExit(
                    f"report {mode}: at least one flight-journal path "
                    "required (workload runs write one under "
                    "--flight-journal)"
                )
            if mode == "timeline":
                print(run_timeline(args.results[1:]))
            else:
                print(run_trace(
                    args.results[1:], head_rate=args.head_sample,
                    max_keep=args.slow_keep, show=args.show_traces,
                ))
            return 0
        print(run_report(args.results))
        if not args.fail_on:
            return 0
        # Regression gates run over a second load of the same documents:
        # run_report already failed loudly on anything unreadable, so
        # every path here parses.
        from tpubench.replay.gate import run_fail_on

        docs, labels = [], []
        for p in args.results:
            with open(p) as f:
                doc = json.load(f)
            if isinstance(doc, list):  # a sweep cells file
                for i, cell in enumerate(doc):
                    docs.append(cell)
                    labels.append(f"{p}[{i}]")
            elif isinstance(doc.get("parsed"), dict):
                docs.append(doc["parsed"])  # driver BENCH_rN wrapper
                labels.append(p)
            else:
                docs.append(doc)
                labels.append(p)
        rc, lines = run_fail_on(args.fail_on, docs, paths=labels)
        for line in lines:
            print(line)
        return rc
    if args.cmd == "record":
        # Journal distillation: jax-free, no common config — the same
        # coordinator-VM policy as report/top.
        from tpubench.replay.bundle import record_bundle

        bundle = record_bundle(args.journals, args.out, name=args.name)
        print(
            f"bundle written: {args.out} ({bundle['name']}: "
            f"{len(bundle['arrivals'])} arrivals, "
            f"{len(bundle['objects'])} objects, fingerprint "
            f"{bundle['config_fingerprint']})"
        )
        return 0
    if args.cmd == "multichip-sweep":
        # Parent needs no jax (children bring their own simulated mesh)
        # and no common config — handled before build_config, which
        # requires the common flag set this subcommand doesn't carry.
        # Delegated so the flag surface exists in one place.
        from tpubench.dist.sweep import main as sweep_main

        fwd = []
        for flag in ("sizes", "shard_mb", "reps", "out"):
            v = getattr(args, flag)
            if v is not None:
                fwd += [f"--{flag.replace('_', '-')}", str(v)]
        return sweep_main(fwd)
    cfg = build_config(args)

    def pin_platform() -> None:
        # Honor JAX_PLATFORMS even when a device plugin rewrites it at
        # import (this image's TPU plugin does) — shared discipline in
        # config.pin_jax_platform (bench.py uses the same one). Called
        # only on jax-using paths — save-config/prepare stay jax-free.
        from tpubench.config import pin_jax_platform

        pin_jax_platform()

    if args.save_config:
        with open(args.save_config, "w") as f:
            f.write(cfg.to_json())
        print(f"config written: {args.save_config}")
        return 0

    if args.cmd == "fleet":
        # Pure simulation: jax-free, device-free — the point is a
        # 1024-host fleet on one CPU in seconds, so it dispatches before
        # pin_platform/_bringup like check/top/record.
        from tpubench.fleet.calibrate import (
            fit_profile,
            load_profile,
            save_profile,
        )
        from tpubench.fleet.driver import (
            format_fleet_block,
            run_fleet,
            run_fleet_sweep,
        )
        from tpubench.workloads.serve import (
            format_membership_scorecard,
            format_serve_scorecard,
        )

        fc = cfg.fleet
        if fc.calibrate_from:
            profile = fit_profile(fc.calibrate_from, defaults={
                "hit": fc.hit_service_ms, "peer": fc.peer_service_ms,
                "origin": fc.origin_service_ms,
                "cross_pod": fc.cross_pod_ms,
            })
            fc.profile = profile.to_dict()
            if fc.profile_path:
                print("fleet profile written: "
                      f"{save_profile(profile, fc.profile_path)}")
        elif fc.profile_path and not fc.profile:
            fc.profile = load_profile(fc.profile_path).to_dict()
        res = run_fleet_sweep(cfg) if fc.sweep else run_fleet(cfg)
        print(format_serve_scorecard(res.extra["serve"]))
        if res.extra.get("membership"):
            print(format_membership_scorecard(res.extra["membership"]))
        print(format_fleet_block(res.extra["fleet"]))
        _finish(res, cfg)
        return 0

    if args.cmd == "info":
        print(cfg.to_json())
        # Report engine capabilities WITHOUT triggering the first-use
        # compile: a read-only diagnostic must not spawn g++ or write the
        # .so (fresh checkouts, read-only installs, CI config inspection).
        from tpubench.native.build import library_path

        lib = library_path()
        src = os.path.join(os.path.dirname(lib), "engine.cc")
        lib_fresh = os.path.exists(lib) and (
            not os.path.exists(src)
            or os.path.getmtime(lib) >= os.path.getmtime(src)
        )
        if lib_fresh:
            from tpubench.native.engine import get_engine

            eng = get_engine()
            caps = {
                "native_engine": eng is not None,
                "native_tls": bool(eng and eng.tls_available()),
            }
        else:
            caps = {"native_engine": "unbuilt (compiles on first use)"}
        print(f"capabilities: {caps}", file=sys.stderr)
        try:
            pin_platform()
            import jax

            print(f"devices: {jax.devices()}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"jax unavailable: {e}", file=sys.stderr)
        return 0
    if args.cmd == "preflight":
        # Deliberately jax-free: a misconfigured VM should fail this in
        # seconds, before any device bringup.
        from tpubench.workloads.preflight import format_preflight, run_preflight

        result = run_preflight(cfg)
        print(format_preflight(result))
        print(json.dumps(result))
        return 0 if result["ok"] else 1
    if args.cmd == "prepare":
        # Prepare writes THROUGH the mount when hooks are configured —
        # writing into the unmounted shadow directory would hide the files
        # from every subsequent mounted run.
        from tpubench.workloads.fsbench import maybe_mounted

        with maybe_mounted(cfg):
            cmd_prepare(cfg, args)
        return 0
    if args.cmd == "sweep":
        pin_platform()
        topo = _bringup(cfg)
        from tpubench.obs.profiling import maybe_profile

        with maybe_profile(cfg.obs.profile_dir):
            cmd_sweep(cfg, args, topo)
        if cfg.obs.profile_dir:
            print(f"profile trace: {cfg.obs.profile_dir}", file=sys.stderr)
        return 0

    direct = not args.no_direct
    pin_platform()
    topo = _bringup(cfg)
    from tpubench.obs.profiling import maybe_profile

    # train-ingest owns its capture (StepProfiler: step-windowed trace,
    # extra["profile"] stamp) — wrapping it here too would nest two
    # jax.profiler traces, which the runtime rejects.
    outer_profile = "" if args.cmd == "train-ingest" else cfg.obs.profile_dir
    with maybe_profile(outer_profile):
        if args.cmd == "read":
            res = cmd_read(cfg, args)
        elif args.cmd == "train-ingest":
            from tpubench.workloads.train_ingest import (
                format_pipeline_scorecard,
                run_train_ingest,
            )

            res = run_train_ingest(cfg)
            print(format_pipeline_scorecard(res.extra["pipeline"]))
            if res.extra.get("staging"):
                from tpubench.staging.stats import format_staging_block

                print(format_staging_block(res.extra["staging"]))
        elif args.cmd == "pod-ingest":
            res = cmd_pod_ingest(cfg, args)
        elif args.cmd == "stream":
            from tpubench.workloads.pod_ingest_stream import run_pod_ingest_stream

            res = run_pod_ingest_stream(
                cfg, n_objects=args.objects, verify=args.validate,
                snapshot_path=args.snapshot,
                resume_from=getattr(args, "resume_from", None),
            )
        elif args.cmd in ("read-fs", "write", "list", "open", "ssd"):
            from tpubench.workloads import fsbench

            fs_runner = {
                "read-fs": lambda: fsbench.run_read_fs(cfg, direct=direct),
                "write": lambda: fsbench.run_write(cfg, direct=direct),
                "list": lambda: fsbench.run_listing(cfg),
                "open": lambda: fsbench.run_open_file(cfg, direct=direct),
                "ssd": lambda: fsbench.run_ssd_compare(cfg, direct=direct),
            }[args.cmd]
            # Launcher convention: bracket the run with mount/unmount
            # (read_operations.sh:18-21); no-op without configured hooks.
            with fsbench.maybe_mounted(cfg):
                res = fs_runner()
        elif args.cmd == "gather-bench":
            from tpubench.workloads.gather_bench import run_gather_bench

            res = run_gather_bench(
                cfg, shard_mb=args.shard_mb, reps=args.reps, ring=args.ring,
                collective=args.collective,
            )
        elif args.cmd == "chaos":
            from tpubench.config import FaultConfig
            from tpubench.workloads.chaos import format_scorecard, run_chaos

            timeline = chaos_timeline_from_args(args)
            if not args.chaos_timeline:
                # Shorthand mode: the --fault-* values parameterized the
                # PHASE — reset them on the base plan, or the "fault"
                # would run every second of the timeline and the
                # baseline/recovery segments would measure nothing.
                defaults = FaultConfig()
                for fname in timeline[0][2]:
                    setattr(cfg.transport.fault, fname,
                            getattr(defaults, fname))
            from tpubench.obs.tracing import tracer_session

            # Same flush-on-exit coverage as the primary workloads: a
            # chaos run with --enable-tracing must not drop its batched
            # spans at process exit.
            with tracer_session(cfg) as tracer:
                res = run_chaos(
                    cfg,
                    timeline=timeline,
                    chaos_workload=args.chaos_workload,
                    tracer=tracer,
                )
            print(format_scorecard(res.extra["chaos"]))
            if res.extra.get("membership"):
                from tpubench.workloads.serve import (
                    format_membership_scorecard,
                )

                print(format_membership_scorecard(res.extra["membership"]))
        elif args.cmd == "serve":
            from tpubench.obs.tracing import tracer_session
            from tpubench.workloads.serve import (
                format_membership_scorecard,
                format_serve_scorecard,
                run_serve,
                run_serve_sweep,
            )

            with tracer_session(cfg) as tracer:
                if args.serve_sweep:
                    res = run_serve_sweep(cfg, tracer=tracer)
                else:
                    res = run_serve(cfg, tracer=tracer)
            print(format_serve_scorecard(res.extra["serve"]))
            if res.extra.get("membership"):
                print(format_membership_scorecard(res.extra["membership"]))
        elif args.cmd == "drill":
            from tpubench.obs.tracing import tracer_session
            from tpubench.workloads.drill import (
                format_drill_scorecard,
                format_drill_sweep,
                run_drill,
                run_drill_sweep,
            )
            from tpubench.workloads.chaos import hermetic_target
            from tpubench.workloads.serve import (
                format_membership_scorecard,
                format_serve_scorecard,
            )

            with tracer_session(cfg) as tracer, hermetic_target(cfg):
                if getattr(args, "drill_sweep", False):
                    res = run_drill_sweep(cfg, tracer=tracer)
                else:
                    res = run_drill(cfg, tracer=tracer)
            print(format_serve_scorecard(res.extra["serve"]))
            if res.extra.get("membership"):
                print(format_membership_scorecard(res.extra["membership"]))
            print(format_drill_scorecard(res.extra["drill"]))
            if res.extra.get("drill_sweep"):
                print(format_drill_sweep(res.extra["drill_sweep"]))
        elif args.cmd == "replay":
            from tpubench.obs.tracing import tracer_session
            from tpubench.replay.bundle import (
                format_replay_block,
                load_bundle,
                validate_bundle,
            )
            from tpubench.replay.driver import run_replay
            from tpubench.workloads.serve import (
                format_membership_scorecard,
                format_serve_scorecard,
            )

            bundle = load_bundle(args.bundle)
            if bundle is None:
                raise SystemExit(
                    f"replay: no usable bundle at {args.bundle!r} "
                    "(missing, unreadable, or truncated — see warnings "
                    "above)"
                )
            validate_bundle(bundle, args.bundle)
            with tracer_session(cfg) as tracer:
                res = run_replay(cfg, bundle, tracer=tracer)
            print(format_serve_scorecard(res.extra["serve"]))
            if res.extra.get("membership"):
                print(format_membership_scorecard(res.extra["membership"]))
            if res.extra.get("drill"):
                from tpubench.workloads.drill import format_drill_scorecard

                print(format_drill_scorecard(res.extra["drill"]))
            print(format_replay_block(res.extra["replay"]))
        elif args.cmd == "tune":
            from tpubench.obs.tracing import tracer_session
            from tpubench.workloads.tune_cmd import format_tune_block, run_tune

            with tracer_session(cfg) as tracer:
                res = run_tune(
                    cfg,
                    mode=args.tune_mode,
                    workload=args.tune_workload,
                    profile_path=args.tune_profile or "",
                    tracer=tracer,
                )
            print(format_tune_block(res.extra["tune"]))
        elif args.cmd in ("ckpt-save", "ckpt-restore"):
            from tpubench.lifecycle import format_lifecycle_scorecard
            from tpubench.workloads.chaos import hermetic_target
            from tpubench.workloads.ckpt import (
                run_ckpt_restore,
                run_ckpt_save,
            )

            runner = (
                run_ckpt_save if args.cmd == "ckpt-save" else run_ckpt_restore
            )
            # http/grpc with no endpoint = hermetic: the write path runs
            # against the matching in-process fake server, transport.fault
            # injected on the wire.
            with hermetic_target(cfg):
                res = runner(cfg)
            print(format_lifecycle_scorecard(res.extra["lifecycle"]))
        elif args.cmd == "meta-storm":
            from tpubench.lifecycle import format_lifecycle_scorecard
            from tpubench.workloads.meta_storm import run_meta_storm

            res = run_meta_storm(cfg, sweep=args.meta_sweep)
            print(format_lifecycle_scorecard(res.extra["lifecycle"]))
        elif args.cmd == "probe":
            from tpubench.workloads.probe import run_probe

            res = run_probe(cfg, cycles=args.cycles, sleep_s=args.cycle_sleep)
        else:  # pragma: no cover
            raise SystemExit(f"unknown cmd {args.cmd}")
    if cfg.obs.profile_dir:
        print(f"profile trace: {cfg.obs.profile_dir}", file=sys.stderr)
    if topo["process_count"] > 1:
        res.extra["topology"] = topo
    _finish(res, cfg, pod_collective=args.cmd in POD_COLLECTIVE_CMDS)
    return 0


if __name__ == "__main__":
    sys.exit(main())
