"""Unified configuration for tpubench.

The reference scatters its knobs across per-binary ``flag`` globals and
hardcoded constants (SURVEY.md §5.6): e.g. ``GrpcConnPoolSize``,
``MaxConnsPerHost``, ``MaxIdleConnsPerHost`` and the retry params are consts
(``main.go:30-42``), and the object name prefix is a "change me in source"
constant (``main.go:50-53``, ``README.md:9``). Here every one of those is a
first-class config field, grouped by subsystem, with the reference defaults
preserved so a reference user finds the same dials.

All sizes are bytes unless the field name says otherwise.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any

# Captured at import of this (jax-free, imported-early) module: the TPU
# plugin in some images REWRITES the env var during `import jax`, so the
# value must be read before any jax import to honor the user's intent.
_JAX_PLATFORMS_AT_IMPORT = os.environ.get("JAX_PLATFORMS", "")


def pin_jax_platform() -> None:
    """Make JAX_PLATFORMS win over a device plugin that rewrites it at
    import (the one pin discipline, shared by cli.py and bench.py): env
    var captured before jax import, applied via jax.config so the knob
    reliably yields e.g. the simulated CPU mesh the README documents.
    No-op when the env var was unset."""
    if _JAX_PLATFORMS_AT_IMPORT:
        import jax

        jax.config.update("jax_platforms", _JAX_PLATFORMS_AT_IMPORT)

KB = 1024
MB = 1024 * 1024
GB = 1024 * 1024 * 1024


@dataclass
class RetryConfig:
    """Request-retry policy.

    Mirrors the reference's gax policy: exponential backoff capped at 30 s,
    multiplier 2.0, retry-always (``main.go:40-42,179-184``).
    """

    initial_backoff_s: float = 1.0
    max_backoff_s: float = 30.0  # main.go:41 (RetryMaxAttempt... actually backoff cap)
    multiplier: float = 2.0  # main.go:42
    policy: str = "always"  # "always" | "idempotent" | "never"; main.go:182
    # The reference retries without an attempt cap; 0 means unbounded here.
    max_attempts: int = 0
    # Total per-op deadline (0 = none). Not in the reference; a safety valve so
    # hermetic tests and fault-injection runs terminate.
    deadline_s: float = 0.0
    jitter: bool = True  # gax randomizes within [1, delay]; we keep that shape


@dataclass
class FaultConfig:
    """Fault injection for the fake backend and fake servers (SURVEY §5.3
    prescription: error %, latency injection — the resilience-testing mode
    the reference lacked). Ignored by real backends.

    Beyond the rate/latency knobs, the chaos plane adds *shaped* faults
    (stall, slow-drip, truncation, connection reset) and **time-phased
    schedules**: ``phases`` is a list of ``[t0, t1, {fault fields}]``
    windows (seconds relative to the run start) during which the phase's
    plan replaces the base one — the scripted fault timeline behind
    ``tpubench chaos``."""

    error_rate: float = 0.0  # P(read-open raises transient 503)
    read_error_rate: float = 0.0  # P(granule read raises mid-stream)
    latency_s: float = 0.0  # added first-byte latency per open
    per_read_latency_s: float = 0.0  # added latency per granule read
    seed: int = 0
    # --- shaped faults (the chaos plane) ---
    # Stall: one mid-body pause of stall_s once a reader has delivered
    # stall_after_bytes; stall_rate is P(a given reader stalls at all) —
    # <1.0 makes the fault a straggler (some streams stall, some don't),
    # the shape hedged reads exist for. A very large stall_s is the
    # blackhole: bytes stop flowing but the stream never errors.
    stall_after_bytes: int = 0
    stall_s: float = 0.0
    stall_rate: float = 1.0
    # Slow-drip: per-reader throughput cap (bytes/second); 0 = off.
    drip_bps: float = 0.0
    # Truncation: clean EOF after this many bytes, SHORT of the announced
    # length (the proxy-died shape a correct client must detect); 0 = off.
    truncate_after_bytes: int = 0
    # Connection reset: the stream dies abruptly after this many bytes
    # (transient error / RST / closed socket depending on the surface).
    reset_after_bytes: int = 0
    # --- upload-side faults (the ckpt-save chaos surface) ---
    # P(a resumable-upload part append fails with a transient 503).
    upload_error_rate: float = 0.0
    # One mid-upload pause per session (upload_stall_rate = P(a given
    # session stalls at all) — the upload twin of the read straggler).
    upload_stall_s: float = 0.0
    upload_stall_rate: float = 1.0
    # Truncate-then-reset: once a session has committed this many bytes,
    # the in-flight part commits only a PREFIX and the connection dies —
    # the mid-part shape resumable uploads exist to survive (one-shot
    # per session, so a resumed upload makes progress past it). 0 = off.
    upload_reset_after_bytes: int = 0
    # Time-phased schedule: [[t0, t1, {fault fields}], ...] — see class doc.
    phases: list = field(default_factory=list)

    @property
    def active(self) -> bool:
        return bool(
            self.error_rate
            or self.read_error_rate
            or self.latency_s
            or self.per_read_latency_s
            or self.stall_s
            or self.drip_bps
            or self.truncate_after_bytes
            or self.reset_after_bytes
            or self.upload_error_rate
            or self.upload_stall_s
            or self.upload_reset_after_bytes
            or self.phases
        )


# Fields a fault phase dict may set (everything but the schedule itself:
# nested phases would have no defined epoch).
_FAULT_PHASE_FIELDS = (
    "error_rate", "read_error_rate", "latency_s", "per_read_latency_s",
    "seed", "stall_after_bytes", "stall_s", "stall_rate", "drip_bps",
    "truncate_after_bytes", "reset_after_bytes",
    "upload_error_rate", "upload_stall_s", "upload_stall_rate",
    "upload_reset_after_bytes",
)


def validate_fault_config(fc: "FaultConfig", where: str = "fault") -> None:
    """Reject malformed fault configs with a clear one-line ``SystemExit``
    (the TPUBENCH_BENCH_SLEEP_SCALE validation style): probabilities
    outside [0, 1], negative latencies/durations/byte counts, and
    malformed or negative phase windows all fail at config-load time, not
    an hour into a run."""

    def _num(label: str, name: str, v):
        try:
            return float(v)
        except (TypeError, ValueError):
            raise SystemExit(
                f"{label}.{name}={v!r}: must be a number"
            ) from None

    def _check_fields(d: dict, label: str) -> None:
        for name in ("error_rate", "read_error_rate", "stall_rate",
                     "upload_error_rate", "upload_stall_rate"):
            v = d.get(name)
            if v is not None and not (0.0 <= _num(label, name, v) <= 1.0):
                raise SystemExit(
                    f"{label}.{name}={v!r}: must be a probability in [0, 1]"
                )
        for name in (
            "latency_s", "per_read_latency_s", "stall_s", "drip_bps",
            "stall_after_bytes", "truncate_after_bytes", "reset_after_bytes",
            "upload_stall_s", "upload_reset_after_bytes",
        ):
            v = d.get(name)
            if v is not None and _num(label, name, v) < 0:
                raise SystemExit(f"{label}.{name}={v!r}: must be >= 0")

    base = {f: getattr(fc, f) for f in _FAULT_PHASE_FIELDS}
    _check_fields(base, where)
    for i, ph in enumerate(fc.phases or ()):
        label = f"{where}.phases[{i}]"
        if not isinstance(ph, (list, tuple)) or len(ph) != 3:
            raise SystemExit(
                f"{label}: expected [t0, t1, {{fault fields}}], got {ph!r}"
            )
        t0, t1, plan = ph
        try:
            t0, t1 = float(t0), float(t1)
        except (TypeError, ValueError):
            raise SystemExit(
                f"{label}: phase window [{ph[0]!r}, {ph[1]!r}] must be numeric"
            ) from None
        if t0 < 0 or t1 < t0:
            raise SystemExit(
                f"{label}: phase window [{t0}, {t1}] must satisfy "
                "0 <= t0 <= t1"
            )
        if not isinstance(plan, dict):
            raise SystemExit(
                f"{label}: third element must be a fault-field dict, "
                f"got {plan!r}"
            )
        unknown = sorted(set(plan) - set(_FAULT_PHASE_FIELDS))
        if unknown:
            raise SystemExit(
                f"{label}: unknown fault field(s) {unknown}; "
                f"valid: {sorted(_FAULT_PHASE_FIELDS)}"
            )
        _check_fields(plan, label)


def parse_sleep_scale(purpose: str = "refill sleeps") -> float:
    """Validated ``TPUBENCH_BENCH_SLEEP_SCALE``: one definition shared by
    bench.py (refill sleeps) and the chaos workload (timeline durations),
    so the two surfaces can never drift on what the env var accepts. A
    clear one-line rejection for non-numeric/negative/NaN values instead
    of a ValueError traceback; empty/unset = 1.0."""
    raw = os.environ.get("TPUBENCH_BENCH_SLEEP_SCALE", "")
    if not raw:
        return 1.0
    try:
        v = float(raw)
    except ValueError:
        raise SystemExit(
            f"TPUBENCH_BENCH_SLEEP_SCALE={raw!r}: expected a non-negative "
            f"number (0 disables {purpose}; 1 keeps them full-length)"
        ) from None
    if v < 0 or v != v:  # reject negatives and NaN alike
        raise SystemExit(
            f"TPUBENCH_BENCH_SLEEP_SCALE={raw!r}: must be >= 0 "
            f"(0 disables {purpose}; got a negative/NaN value)"
        )
    return v


@dataclass
class TailConfig:
    """Tail-tolerance (storage/tail.py): hedged reads, the stall watchdog
    and the per-backend circuit breaker. All off by default — the
    reference has none of this (it retries-after-failure only); turning
    them on is the resilience A/B the chaos workload measures."""

    # Hedged reads: if the first byte hasn't arrived hedge_delay_s after
    # open, race a second ranged read for the same bytes and take the
    # winner (loser cancelled; wins/losses/wasted bytes recorded).
    hedge: bool = False
    hedge_delay_s: float = 0.05
    # Derive the hedge delay from the run's rolling p99 first-byte latency
    # (x hedge_p99_scale, floored at hedge_delay_s) instead of the fixed
    # delay — self-tuning to the endpoint's actual tail.
    hedge_from_p99: bool = False
    hedge_p99_scale: float = 1.5
    # Stall watchdog: a stream whose throughput stays below
    # stall_floor_bps for at least stall_window_s is cancelled with a
    # transient StallError — the resume path reopens it at offset.
    watchdog: bool = False
    stall_window_s: float = 1.0
    stall_floor_bps: float = 1024.0
    # Circuit breaker (closed → open → half-open): breaker_failures
    # consecutive failures open it; after breaker_reset_s one probe
    # (breaker_probes successes) closes it again. While open, opens are
    # shed with a transient CircuitOpenError instead of hammering the
    # endpoint.
    breaker: bool = False
    breaker_failures: int = 5
    breaker_reset_s: float = 5.0
    breaker_probes: int = 1

    @property
    def active(self) -> bool:
        return self.hedge or self.watchdog or self.breaker


@dataclass
class PipelineConfig:
    """Ingest pipeline (tpubench/pipeline/): host chunk cache + readahead
    prefetcher + the step-paced ``train-ingest`` workload.

    The reference (and every other tpubench workload) issues cold,
    demand-driven reads — no overlap between fetch and consumption. This
    subsystem is the input pipeline that hides storage latency behind
    compute and *measures how well it does so*: per-step data-stall time,
    cache hit ratio, prefetch efficiency (used vs wasted bytes).
    """

    # Host-RAM chunk cache budget (bytes). Entries are keyed by
    # (bucket, object, generation, range); byte-budgeted LRU eviction with
    # single-flight dedup of concurrent misses. 0 disables caching (every
    # chunk access is a backend read — the cold baseline).
    cache_bytes: int = 256 * MB
    # Readahead depth in CHUNKS: how far ahead of the consumer the
    # prefetcher walks the access plan. 0 disables prefetch entirely
    # (the cold A/B arm).
    readahead: int = 8
    # Prefetch byte budget: in-flight + cached-but-unconsumed prefetched
    # bytes never exceed this (0 = bounded by readahead depth alone).
    readahead_bytes: int = 0
    # Worker threads issuing prefetch reads through the backend stack
    # (hedging/watchdog/breaker/retry compose underneath, like any read).
    prefetch_workers: int = 2
    # --- train-ingest step loop ---
    steps: int = 8  # training steps per epoch
    epochs: int = 1  # plan repeats; epoch 2+ re-reads (cache hit path)
    batch_shards: int = 4  # chunks consumed per step
    # Chunk size (bytes); 0 = workload.granule_bytes.
    chunk_bytes: int = 0
    # Synthetic per-step compute time (ms): the window prefetch has to
    # hide the next batch's fetch latency behind.
    step_compute_ms: float = 0.0
    # A step whose data-wait exceeds this is a *stalled step* (the
    # stalled-step fraction the scorecard reports).
    stall_threshold_ms: float = 1.0
    # Pod path: stage each step's batch as byte-range shards across the
    # mesh and reassemble over ICI (dist.shard / dist.reassemble), instead
    # of the per-host slot-ring device_put path.
    pod: bool = False
    # --- zero-copy slab datapath (tpubench/mem/) ---
    # Lease chunks from a refcounted pinned-slab pool: the transport
    # readinto()s wire bytes straight into a leased slab, the cache
    # stores the lease, and the consumer stages the slab view in place —
    # one host-RAM write per chunk byte. False = the legacy bytes path
    # (the copies-per-byte A/B baseline arm).
    slab_pool: bool = True
    # Slab size in bytes; 0 = the effective chunk size (chunk_bytes or
    # workload.granule_bytes). Must be >= one chunk.
    slab_bytes: int = 0
    # Pool capacity in slabs; 0 = auto-sized so the cache budget plus the
    # readahead window plus one step's batch fit without overflow.
    pool_slabs: int = 0


def validate_pipeline_config(pc: "PipelineConfig",
                             where: str = "pipeline",
                             staging: "StagingConfig" = None) -> None:
    """Parse-time sanity for the pipeline knobs (same one-line SystemExit
    style as validate_fault_config). With ``staging`` supplied, also
    cross-checks the overlapped staging window against the slab pool."""
    for name, lo in (
        ("cache_bytes", 0), ("readahead", 0), ("readahead_bytes", 0),
        ("prefetch_workers", 1), ("steps", 1), ("epochs", 1),
        ("batch_shards", 1), ("chunk_bytes", 0),
        ("slab_bytes", 0), ("pool_slabs", 0),
    ):
        v = getattr(pc, name)
        if v < lo:
            raise SystemExit(f"{where}.{name}={v!r}: must be >= {lo}")
    for name in ("step_compute_ms", "stall_threshold_ms"):
        v = getattr(pc, name)
        if not (v >= 0):  # also rejects NaN
            raise SystemExit(f"{where}.{name}={v!r}: must be >= 0")
    if (
        staging is not None and staging.mode == "device_put"
        and staging.double_buffer and not staging.validate_checksum
        and not pc.pod and pc.slab_pool
        and pc.pool_slabs > 0 and pc.slab_bytes > 0
    ):
        # Scope: only the device_put overlapped window holds leases past
        # submit — pallas stages synchronously, validation forces the
        # serial ring, and the pod path never builds a stager at all.
        # The overlapped executor holds one chunk lease per in-flight
        # transfer until the bytes LAND (not until submit returns), so an
        # explicitly-sized pool must have room for the in-flight window
        # on top of the cache's working set. Without this check the
        # misconfiguration only surfaces as counted overflow leases
        # mid-run — an hour in, as pool-pressure noise, not as the
        # config error it is.
        depth = max(1, staging.depth)
        inflight = depth * pc.slab_bytes
        budget = pc.pool_slabs * pc.slab_bytes
        if inflight > budget:
            raise SystemExit(
                f"staging.depth={depth} × {where}.slab_bytes="
                f"{pc.slab_bytes} = {inflight} B of in-flight leases "
                f"exceeds the slab-pool budget ({where}.pool_slabs="
                f"{pc.pool_slabs} × {pc.slab_bytes} = {budget} B): every "
                "overlapped transfer would overflow-lease — raise "
                "--pool-slabs or lower --staging-depth"
            )
    # The cross-field readahead/cache/chunk checks live in
    # run_train_ingest, where the effective chunk size is known AND only
    # the workload that actually constructs the pipeline pays them —
    # `tpubench read --cache-bytes 0` must not fail on the pipeline's
    # default readahead.


@dataclass
class CoopConfig:
    """Pod-scale cooperative chunk cache (tpubench/pipeline/coop.py):
    consistent-hash chunk ownership across the pod's hosts, peer-first
    miss resolution over a peer channel, pod-wide single-flight (only
    the owner ever fetches a chunk from origin), and straggler-aware
    owner demotion fed by the flight recorder's per-host tables.

    Off by default — the per-host cache is the baseline arm of the
    coop-vs-per-host A/B the scorecard reports (origin GCS bytes per
    POD, not per host)."""

    enabled: bool = False
    # Pod membership: number of hosts on the ring (0 = dist.num_processes)
    # and this host's id (-1 = dist.process_id). Explicit values exist
    # for embedding harnesses (the hermetic multi-"host" sim).
    hosts: int = 0
    host_id: int = -1
    # Virtual nodes per host: more = smoother key balance, identical
    # rehash-minimality (~1/N of keys move on a join/leave either way).
    vnodes: int = 64
    # Serve-side byte budget: bytes concurrently being served to peers
    # never exceed this — past it the owner sheds (peers fall back to
    # origin) instead of queueing unboundedly. 0 = unbounded. Live: the
    # `peer_budget_bytes` tune knob actuates it.
    peer_budget_bytes: int = 0
    # Peer transport: "loopback" = in-process request/reply (hermetic
    # tests, single-host dev); "ici" = lockstep broadcast over the pod
    # mesh (dist/peer.py — plan-synchronized pod workloads only);
    # "auto" = loopback.
    channel: str = "auto"
    # Straggler demotion: owners whose per-host flight table places them
    # in the slowest decile (tail_share >= demote_share) leave the ring
    # until a later table clears them; the recorder scan runs at most
    # once per demote_interval_s.
    demote: bool = True
    demote_share: float = 0.5
    demote_interval_s: float = 2.0


def validate_coop_config(cc: "CoopConfig", where: str = "coop") -> None:
    """Parse-time sanity for the coop knobs (one-line SystemExit at
    config load — the validate_fault_config style)."""
    if cc.hosts < 0:
        raise SystemExit(f"{where}.hosts={cc.hosts!r}: must be >= 0 "
                         "(0 = dist.num_processes)")
    if cc.host_id < -1:
        raise SystemExit(f"{where}.host_id={cc.host_id!r}: must be >= -1 "
                         "(-1 = dist.process_id)")
    if cc.hosts and cc.host_id >= cc.hosts:
        raise SystemExit(
            f"{where}.host_id={cc.host_id} is outside the pod "
            f"({where}.hosts={cc.hosts})"
        )
    if cc.vnodes < 1:
        raise SystemExit(f"{where}.vnodes={cc.vnodes!r}: must be >= 1")
    if cc.peer_budget_bytes < 0:
        raise SystemExit(
            f"{where}.peer_budget_bytes={cc.peer_budget_bytes!r}: must be "
            ">= 0 (0 = unbounded)"
        )
    if cc.channel not in ("auto", "loopback", "ici"):
        raise SystemExit(
            f"{where}.channel={cc.channel!r}: must be auto|loopback|ici"
        )
    if not (0.0 < cc.demote_share <= 1.0):  # also rejects NaN
        raise SystemExit(
            f"{where}.demote_share={cc.demote_share!r}: must be in (0, 1]"
        )
    if not (cc.demote_interval_s > 0):
        raise SystemExit(
            f"{where}.demote_interval_s={cc.demote_interval_s!r}: "
            "must be > 0"
        )


@dataclass
class ServeConfig:
    """Open-loop multi-tenant traffic plane (``tpubench serve``,
    tpubench/serve/ + workloads/serve.py).

    Every other workload is closed-loop — a fixed pool pulls as fast as
    it can. ``serve`` drives OPEN-LOOP arrivals (requests land on their
    own schedule whether or not the system keeps up) from many synthetic
    tenants in weighted priority classes, through the full
    open_backend → chunk cache → prefetcher → staging stack, with QoS
    enforced at the choke points: priority admission with a live cap
    (the PR-5 runnable-queue admission hook), weighted per-class cache/
    prefetch byte budgets, and deadline-aware shedding under overload.
    ``serve-sweep`` steps offered load and emits the latency-vs-load
    curve to the saturation knee (the Pulsar-study methodology)."""

    # Run length (seconds of VIRTUAL schedule; wall time scales with
    # TPUBENCH_BENCH_SLEEP_SCALE via the shared parse_sleep_scale).
    duration_s: float = 4.0
    # Aggregate offered load, requests/second across all tenants.
    rate_rps: float = 200.0
    # Arrival process: poisson | bursty (two-state MMPP) | diurnal
    # (sinusoidal-rate thinned Poisson) | trace (replayed timestamps).
    arrival: str = "poisson"
    burst_factor: float = 4.0  # bursty: burst-to-quiet rate ratio
    burst_fraction: float = 0.25  # bursty: fraction of each cycle bursting
    burst_cycle_s: float = 1.0  # bursty: quiet+burst cycle length
    diurnal_period_s: float = 4.0  # diurnal: one "day" in seconds
    trace_path: str = ""  # trace: JSON list of arrival seconds
    # Tenant population: expanded over `classes` by share; each tenant
    # draws its objects from a Zipf(alpha) popularity law over the
    # shared object set (workloads/arrivals.zipf_plan).
    tenants: int = 100
    alpha: float = 1.2
    # Priority classes: list of {"name", "share" (of tenants/traffic),
    # "weight" (cache/prefetch budget split), "deadline_ms" (per-request
    # SLO), "priority" (lower = served first)} dicts. Validated by
    # validate_serve_config; malformed specs are a one-line SystemExit.
    classes: list = field(default_factory=lambda: [
        {"name": "gold", "share": 0.1, "weight": 4.0,
         "deadline_ms": 80.0, "priority": 0},
        {"name": "silver", "share": 0.3, "weight": 2.0,
         "deadline_ms": 250.0, "priority": 1},
        {"name": "best_effort", "share": 0.6, "weight": 1.0,
         "deadline_ms": 1500.0, "priority": 2},
    ])
    # Request size: one chunk per request (0 = workload.granule_bytes).
    chunk_bytes: int = 0
    # Service worker threads (the concurrency ceiling admission caps).
    workers: int = 8
    # QoS master switch: False = FIFO queue, no shedding, no weighted
    # budgets — the baseline arm of the QoS A/B.
    qos: bool = True
    # Admission cap: requests in service at once (0 = workers). Live:
    # the tune controller actuates it through the "workers" knob.
    admission_cap: int = 0
    # Queued-request bound before overload shedding (QoS mode; 0 = a
    # default of 8x workers). The baseline arm queues unboundedly.
    queue_limit: int = 0
    # Readahead over the arrival schedule (serve knows its replayed
    # trace ahead of time the way train-ingest knows its plan): depth in
    # chunks; 0 = demand-only.
    readahead: int = 0
    # serve-sweep: offered-load multipliers of rate_rps, stepped in
    # order; per-point run length (0 = duration_s).
    sweep_points: list = field(default_factory=lambda: [
        0.25, 0.5, 1.0, 2.0, 4.0,
    ])
    sweep_duration_s: float = 0.0
    seed: int = 0
    # Elastic pod membership (tpubench/dist/membership.py): hosts > 1
    # fans the serve plane across an N-host hermetic threaded pod whose
    # misses route through coop-cache consistent-hash ownership, and
    # membership_timeline changes the pod's shape UNDER load —
    # ``[t0, t1, {action: host}]`` entries in virtual schedule seconds
    # (the arrival clock), actions kill_host (die, no handoff),
    # leave_host (cooperative warm handoff), pause_host (unresponsive
    # during [t0, t1], resumes at t1) and rejoin_host (clean re-entry).
    # The resize scorecard brackets each event with resize_window_s of
    # virtual time (SLO-during-resize vs steady state).
    hosts: int = 1
    membership_timeline: list = field(default_factory=list)
    resize_window_s: float = 1.0


def validate_serve_config(sc: "ServeConfig", where: str = "serve") -> None:
    """Parse-time sanity for the serve plane (one-line SystemExit at
    config load — the validate_fault_config style): malformed tenant
    class specs and arrival parameters fail before a single arrival."""
    if not (sc.duration_s > 0):  # also rejects NaN
        raise SystemExit(f"{where}.duration_s={sc.duration_s!r}: must be > 0")
    if not (sc.rate_rps > 0):
        raise SystemExit(f"{where}.rate_rps={sc.rate_rps!r}: must be > 0")
    if sc.arrival not in ("poisson", "bursty", "diurnal", "trace"):
        raise SystemExit(
            f"{where}.arrival={sc.arrival!r}: must be "
            "poisson|bursty|diurnal|trace"
        )
    if sc.arrival == "trace" and not sc.trace_path:
        raise SystemExit(
            f"{where}.arrival=trace requires {where}.trace_path "
            "(a JSON list of arrival seconds)"
        )
    if not (sc.burst_factor >= 1.0):
        raise SystemExit(
            f"{where}.burst_factor={sc.burst_factor!r}: must be >= 1"
        )
    if not (0.0 < sc.burst_fraction < 1.0):
        raise SystemExit(
            f"{where}.burst_fraction={sc.burst_fraction!r}: must be in (0, 1)"
        )
    for name in ("burst_cycle_s", "diurnal_period_s", "alpha"):
        v = getattr(sc, name)
        if not (v > 0):
            raise SystemExit(f"{where}.{name}={v!r}: must be > 0")
    for name, lo in (("tenants", 1), ("workers", 1), ("chunk_bytes", 0),
                     ("admission_cap", 0), ("queue_limit", 0),
                     ("readahead", 0)):
        v = getattr(sc, name)
        if v < lo:
            raise SystemExit(f"{where}.{name}={v!r}: must be >= {lo}")
    if not (sc.sweep_duration_s >= 0):
        raise SystemExit(
            f"{where}.sweep_duration_s={sc.sweep_duration_s!r}: must be >= 0"
        )
    if not sc.sweep_points or not all(
        isinstance(p, (int, float)) and p > 0 for p in sc.sweep_points
    ):
        raise SystemExit(
            f"{where}.sweep_points={sc.sweep_points!r}: must be a non-empty "
            "list of positive load multipliers"
        )
    if not sc.classes or not isinstance(sc.classes, list):
        raise SystemExit(
            f"{where}.classes: must be a non-empty list of class dicts"
        )
    allowed = {"name", "share", "weight", "deadline_ms", "priority"}
    seen = set()
    for i, c in enumerate(sc.classes):
        label = f"{where}.classes[{i}]"
        if not isinstance(c, dict):
            raise SystemExit(f"{label}: expected a dict, got {c!r}")
        unknown = sorted(set(c) - allowed)
        if unknown:
            raise SystemExit(
                f"{label}: unknown field(s) {unknown}; valid: "
                f"{sorted(allowed)}"
            )
        name = c.get("name")
        if not name or not isinstance(name, str):
            raise SystemExit(f"{label}: 'name' must be a non-empty string")
        if name in seen:
            raise SystemExit(f"{label}: duplicate class name {name!r}")
        seen.add(name)
        for fname, pred, what in (
            ("share", lambda v: v > 0, "> 0"),
            ("deadline_ms", lambda v: v > 0, "> 0"),
            ("weight", lambda v: v > 0, "> 0"),
        ):
            v = c.get(fname, 1.0 if fname == "weight" else None)
            try:
                ok = v is not None and pred(float(v))
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise SystemExit(f"{label}.{fname}={v!r}: must be {what}")
        pr = c.get("priority", i)
        if not isinstance(pr, int) or pr < 0:
            raise SystemExit(
                f"{label}.priority={pr!r}: must be an int >= 0"
            )
    if sc.hosts < 1:
        raise SystemExit(f"{where}.hosts={sc.hosts!r}: must be >= 1")
    if not (sc.resize_window_s > 0):  # also rejects NaN
        raise SystemExit(
            f"{where}.resize_window_s={sc.resize_window_s!r}: must be > 0"
        )
    if sc.hosts > 1 and sc.readahead > 0:
        # The elastic pod is demand-path only: schedule readahead is a
        # single-host plane feature, and silently no-opping the knob
        # would hand an A/B user bit-identical arms.
        raise SystemExit(
            f"{where}.readahead={sc.readahead} is a single-host plane "
            f"feature — the elastic pod ({where}.hosts={sc.hosts}) "
            "resolves misses through coop ownership only; set "
            f"{where}.readahead=0"
        )
    validate_membership_timeline(sc, where)


def validate_membership_timeline(sc: "ServeConfig",
                                 where: str = "serve") -> None:
    """Parse-time sanity for the elastic-membership timeline (the
    validate_fault_config phase style): entry shape, numeric windows,
    exactly one known host action per entry, host ids inside the pod.
    A timeline over a single-host pod is refused loudly — there is no
    membership to change."""
    tl = sc.membership_timeline
    if not tl:
        return
    if sc.hosts < 2:
        raise SystemExit(
            f"{where}.membership_timeline needs {where}.hosts >= 2 "
            f"(got {sc.hosts}): a pod of one has no membership to change"
        )
    for i, ph in enumerate(tl):
        label = f"{where}.membership_timeline[{i}]"
        if not isinstance(ph, (list, tuple)) or len(ph) != 3:
            raise SystemExit(
                f"{label}: expected [t0, t1, {{action: host}}], got {ph!r}"
            )
        t0, t1, spec = ph
        try:
            t0, t1 = float(t0), float(t1)
        except (TypeError, ValueError):
            raise SystemExit(
                f"{label}: window [{ph[0]!r}, {ph[1]!r}] must be numeric"
            ) from None
        if t0 < 0 or t1 < t0:
            raise SystemExit(
                f"{label}: window [{t0}, {t1}] must satisfy 0 <= t0 <= t1"
            )
        if not isinstance(spec, dict) or len(spec) != 1:
            raise SystemExit(
                f"{label}: third element must be one {{action: host}} "
                f"dict, got {spec!r}"
            )
        (action, host), = spec.items()
        if action not in MEMBER_TIMELINE_ACTIONS:
            raise SystemExit(
                f"{label}: unknown membership action {action!r}; valid: "
                f"{sorted(MEMBER_TIMELINE_ACTIONS)}"
            )
        if not isinstance(host, int) or not (0 <= host < sc.hosts):
            raise SystemExit(
                f"{label}.{action}={host!r}: host must be an int in "
                f"[0, {sc.hosts})"
            )


# Host-level membership actions a chaos/serve timeline may carry (the
# single source dist/membership.py, the chaos splitter and the timeline
# validator all read). pause_host resumes at its window's t1; the
# others fire at t0.
MEMBER_TIMELINE_ACTIONS = (
    "kill_host", "leave_host", "pause_host", "rejoin_host",
)


@dataclass
class LifecycleConfig:
    """Storage-lifecycle plane (tpubench/lifecycle/ + the ``ckpt-save``/
    ``ckpt-restore``/``meta-storm`` workloads).

    Every prior workload READS; this is the other half of the reference
    (``benchmark-script/``'s write/list/open binaries): a checkpoint-
    shaped write path over resumable multi-part uploads, a sharded
    restore with time-to-restore as the headline, and open-loop
    list/stat/open metadata storms driven by the arrivals plane so
    metadata ops get a knee curve too."""

    # --- checkpoint shape (save + restore) ---
    # The manifest: `objects` shard-objects of `object_bytes` each (a
    # sharded model layout — one object per parameter shard).
    objects: int = 4
    object_bytes: int = 8 * MB
    # Resumable-upload part size (each part is one content-range PUT).
    part_bytes: int = 1 * MB
    # Concurrent object uploads (save) / shard fetches (restore).
    writers: int = 4
    readers: int = 4
    # Object-name prefix; the manifest lands at <prefix>MANIFEST.json.
    prefix: str = "ckpt/"
    # Readback-verify every finalized object's crc32 against the
    # manifest (save) / verify fetched shard bytes (restore): the
    # zero-corrupt-finalizes check. Costs one extra read pass on save.
    verify: bool = True
    # Restore stages each object's per-host shard ranges into a SHARDED
    # device array across the mesh (dist.shard/reassemble path); False
    # = host-RAM restore only (jax-free).
    restore_device: bool = True
    # --- metadata storm ---
    meta_objects: int = 64  # many small objects (the pathology)
    meta_object_bytes: int = 4 * KB
    meta_rate_rps: float = 200.0  # offered metadata ops/second
    meta_duration_s: float = 2.0  # virtual schedule seconds
    meta_arrival: str = "poisson"  # poisson | bursty | diurnal
    # Op mix "kind:weight,..." over list/stat/open (open = open_read of
    # the object head, the reference's open_file analogue).
    meta_mix: str = "list:1,stat:2,open:2"
    # Wire page bound for list ops (maxResults; multi-page listings).
    meta_page_size: int = 16
    # Bytes an `open` op reads from the object head before closing.
    meta_read_bytes: int = 4 * KB
    # Storm service worker threads (the concurrency the knee saturates).
    meta_workers: int = 8
    # --serve-sweep-style offered-load multipliers for the knee curve.
    sweep_points: list = field(default_factory=lambda: [0.5, 1.0, 2.0, 4.0])
    seed: int = 0


def validate_lifecycle_config(lc: "LifecycleConfig",
                              where: str = "lifecycle") -> None:
    """Parse-time sanity for the lifecycle knobs (one-line SystemExit at
    config load — the validate_fault_config style)."""
    for name, lo in (
        ("objects", 1), ("object_bytes", 1), ("part_bytes", 1),
        ("writers", 1), ("readers", 1), ("meta_objects", 1),
        ("meta_object_bytes", 0), ("meta_page_size", 0),
        ("meta_read_bytes", 0), ("meta_workers", 1),
    ):
        v = getattr(lc, name)
        if v < lo:
            raise SystemExit(f"{where}.{name}={v!r}: must be >= {lo}")
    for name in ("meta_rate_rps", "meta_duration_s"):
        v = getattr(lc, name)
        if not (v > 0):  # also rejects NaN
            raise SystemExit(f"{where}.{name}={v!r}: must be > 0")
    if not lc.prefix:
        raise SystemExit(f"{where}.prefix: must be non-empty")
    if lc.meta_arrival not in ("poisson", "bursty", "diurnal"):
        raise SystemExit(
            f"{where}.meta_arrival={lc.meta_arrival!r}: must be "
            "poisson|bursty|diurnal"
        )
    parse_meta_mix(lc.meta_mix, where=where)
    if not lc.sweep_points or not all(
        isinstance(p, (int, float)) and p > 0 for p in lc.sweep_points
    ):
        raise SystemExit(
            f"{where}.sweep_points={lc.sweep_points!r}: must be a "
            "non-empty list of positive load multipliers"
        )


META_OP_KINDS = ("list", "stat", "open")


def parse_meta_mix(spec: str, where: str = "lifecycle") -> dict[str, float]:
    """``"list:1,stat:2,open:2"`` → normalized weight dict. Unknown op
    kinds, malformed entries and non-positive weights are one-line
    SystemExits at config load."""
    out: dict[str, float] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, _, w_s = entry.partition(":")
        kind = kind.strip()
        if kind not in META_OP_KINDS:
            raise SystemExit(
                f"{where}.meta_mix: unknown op kind {kind!r}; valid: "
                f"{'/'.join(META_OP_KINDS)}"
            )
        try:
            w = float(w_s) if w_s else 1.0
        except ValueError:
            raise SystemExit(
                f"{where}.meta_mix: bad weight {w_s!r} for {kind!r}"
            ) from None
        if not (w > 0):
            raise SystemExit(
                f"{where}.meta_mix: weight for {kind!r} must be > 0"
            )
        out[kind] = out.get(kind, 0.0) + w
    if not out:
        raise SystemExit(f"{where}.meta_mix={spec!r}: no ops configured")
    total = sum(out.values())
    return {k: v / total for k, v in out.items()}


@dataclass
class DrillConfig:
    """Composed incident drill (``tpubench drill``, workloads/drill.py):
    restore-while-serving on the elastic pod.

    The serve plane runs its open-loop QoS traffic over ``serve.hosts``
    pod hosts; at ``kill_at_s`` the membership plane kills ``victim``;
    at ``join_at_s`` a cold replacement joins and runs a checkpoint
    restore THROUGH the shared admission queue (and, on the coop arm,
    the coop cache), so restore reads, peer traffic, and gold-class
    fetches contend for the same slots, byte budgets, and — with
    ``meta_rate_rps`` > 0 — metadata quota. Periodic checkpoint DELTA
    saves (lifecycle/delta.py) ride under the same traffic on
    ``save_interval_s``."""

    # QoS identity of restore reads: their own first-class tag in the
    # admission queue and the cache/prefetch owner budgets (never a
    # masquerading tenant). Colliding with a serving class name is a
    # config error (validate_drill_config).
    restore_class: str = "restore"
    restore_priority: int = 1  # between gold (0) and best_effort
    restore_weight: float = 2.0  # byte-budget split weight
    restore_deadline_ms: float = 500.0  # per-chunk deadline (sheds count)
    # Restore driver window: chunk reads the joiner keeps in flight
    # through the shared admission queue.
    restore_inflight: int = 8
    # Bounded re-reads when a delta save lands a new shard generation
    # under an in-flight restore read (the torn-read path).
    restore_retries: int = 3
    # Scripted incident, in virtual schedule seconds on the arrival
    # clock: victim dies at kill_at_s, replacement joins cold at
    # join_at_s. victim = -1 resolves to the last host.
    kill_at_s: float = 1.0
    join_at_s: float = 1.5
    victim: int = -1
    # A/B arm: True routes restore reads through the joiner's coop
    # cache (peer hits possible); False fetches direct-to-origin (still
    # through the admission queue — slot contention stays).
    restore_via_coop: bool = True
    # Periodic checkpoint saves under traffic: interval in virtual
    # seconds (0 = no periodic saves); delta_saves=False forces every
    # save full (the delta-vs-full A/B arm); dirty_fraction of shards
    # mutate between saves.
    save_interval_s: float = 1.0
    delta_saves: bool = True
    dirty_fraction: float = 0.25
    # Concurrent open-loop metadata storm sharing the lifecycle quota
    # ledger with standalone meta-storm runs (0 = no storm mix).
    meta_rate_rps: float = 0.0
    # drill-sweep: save-interval multipliers stepped in order.
    sweep_points: list = field(default_factory=lambda: [0.5, 1.0, 2.0])


def validate_drill_config(dc: "DrillConfig", sc: "ServeConfig",
                          where: str = "drill") -> None:
    """Parse-time sanity for the drill plane (one-line SystemExit at
    config load — the validate_fault_config style). The drill composes
    the serve plane, so it also inherits validate_serve_config."""
    if not dc.restore_class or not isinstance(dc.restore_class, str):
        raise SystemExit(
            f"{where}.restore_class={dc.restore_class!r}: must be a "
            "non-empty string"
        )
    if dc.restore_class in {c.get("name") for c in sc.classes}:
        raise SystemExit(
            f"{where}.restore_class={dc.restore_class!r} collides with a "
            "serving class name — restore traffic must carry its own QoS "
            "tag"
        )
    if not isinstance(dc.restore_priority, int) or dc.restore_priority < 0:
        raise SystemExit(
            f"{where}.restore_priority={dc.restore_priority!r}: must be "
            "an int >= 0"
        )
    for name in ("restore_weight", "restore_deadline_ms", "join_at_s"):
        v = getattr(dc, name)
        if not (v > 0):  # also rejects NaN
            raise SystemExit(f"{where}.{name}={v!r}: must be > 0")
    for name, lo in (("restore_inflight", 1), ("restore_retries", 0)):
        v = getattr(dc, name)
        if v < lo:
            raise SystemExit(f"{where}.{name}={v!r}: must be >= {lo}")
    if not (dc.kill_at_s >= 0):
        raise SystemExit(f"{where}.kill_at_s={dc.kill_at_s!r}: must be >= 0")
    if not (dc.join_at_s >= dc.kill_at_s):
        raise SystemExit(
            f"{where}.join_at_s={dc.join_at_s!r}: must be >= kill_at_s "
            f"({dc.kill_at_s}) — the replacement joins after the incident"
        )
    if sc.hosts < 2:
        raise SystemExit(
            f"{where} needs serve.hosts >= 2 (got {sc.hosts}): a pod of "
            "one has no survivor to keep serving"
        )
    if not isinstance(dc.victim, int) or not (-1 <= dc.victim < sc.hosts):
        raise SystemExit(
            f"{where}.victim={dc.victim!r}: must be -1 (last host) or an "
            f"int in [0, {sc.hosts})"
        )
    if not (dc.save_interval_s >= 0):
        raise SystemExit(
            f"{where}.save_interval_s={dc.save_interval_s!r}: must be >= 0"
        )
    if not (0.0 < dc.dirty_fraction <= 1.0):  # also rejects NaN
        raise SystemExit(
            f"{where}.dirty_fraction={dc.dirty_fraction!r}: must be in "
            "(0, 1]"
        )
    if not (dc.meta_rate_rps >= 0):
        raise SystemExit(
            f"{where}.meta_rate_rps={dc.meta_rate_rps!r}: must be >= 0"
        )
    if not dc.sweep_points or not all(
        isinstance(p, (int, float)) and p > 0 for p in dc.sweep_points
    ):
        raise SystemExit(
            f"{where}.sweep_points={dc.sweep_points!r}: must be a "
            "non-empty list of positive save-interval multipliers"
        )


# Generated fleet membership-timeline kinds (fleet/driver.py builds the
# serve-format entries; "none" defers to serve.membership_timeline).
FLEET_TIMELINE_KINDS = ("none", "correlated_failure", "rolling_upgrade")


@dataclass
class FleetConfig:
    """Virtual-time fleet simulation (``tpubench fleet``,
    tpubench/fleet/): the elastic serve plane run by a discrete-event
    driver instead of worker threads, so pods scale to 64-4096 hosts.

    Service times come from a :class:`tpubench.fleet.calibrate.
    FleetProfile` — either the per-phase constants below, or a
    distribution fitted from flight journals (``--calibrate-from``)
    and round-tripped through ``--fleet-profile`` JSON."""

    # Simulated pod size. 0 = inherit serve.hosts (the agreement-gate
    # arm, where both drivers must see the identical config).
    hosts: int = 64
    # Pod partitioning: hosts split into contiguous pods, each with its
    # own coop ring; >1 pod adds the cross-pod routing tier. 0 = auto
    # (one pod per 128 hosts, minimum one).
    pods: int = 0
    # Simulated service slots: workers_per_host * hosts virtual workers
    # share one admission queue. 0 = use serve.workers as the GLOBAL
    # pool size (the agreement-gate arm again: the threaded plane's
    # worker count is global, not per-host).
    workers_per_host: int = 2
    # Synthetic object population (the fleet never opens a backend);
    # sizes come from workload.object_size.
    objects: int = 64
    # Per-phase service-time constants (ms) used when no fitted profile
    # is configured. Defaults approximate the hermetic fake backend's
    # regime: origin ~ a faulted granule read, peer ~ loopback RTT.
    origin_service_ms: float = 4.0
    peer_service_ms: float = 0.5
    hit_service_ms: float = 0.05
    cross_pod_ms: float = 1.5
    # Flat stand-in for the bounded transient-retry ladder a paused
    # owner costs its peers (PEER_MAX_ATTEMPTS x backoff, ~150 ms).
    pause_penalty_ms: float = 150.0
    # Generated membership timeline (FLEET_TIMELINE_KINDS); composes
    # with serve.membership_timeline entries.
    timeline: str = "none"
    fail_at_s: float = 0.5  # correlated failure / first upgrade start
    fail_fraction: float = 0.1  # fraction of the fleet that dies
    recover_s: float = 0.0  # > 0: victims rejoin (cold) this much later
    upgrade_pause_s: float = 0.2  # rolling upgrade: per-host pause
    upgrade_stagger_s: float = 0.0  # 0 = sequential (next as prev resumes)
    # Victim-selection seed: WHICH hosts die changes remap geometry, so
    # it must replay deterministically.
    seed: int = 20
    # Fitted service profile (the FleetProfile.to_dict round-trip);
    # populated by --fleet-profile / --calibrate-from. Empty = use the
    # per-phase constants above.
    profile: dict = field(default_factory=dict)
    # --fleet-profile path (read, or written by --calibrate-from).
    profile_path: str = ""
    # --calibrate-from journal base paths (``.p<idx>``/gz siblings are
    # discovered automatically).
    calibrate_from: list = field(default_factory=list)
    # --fleet-sweep: step offered load like --serve-sweep.
    sweep: bool = False


def validate_fleet_config(fc: "FleetConfig", sc: "ServeConfig",
                          where: str = "fleet") -> None:
    """Parse-time sanity for the fleet plane (the one-line SystemExit
    style). The fleet composes the serve plane, so it also inherits
    validate_serve_config (the driver syncs serve.hosts first)."""
    if not isinstance(fc.hosts, int) or not (0 <= fc.hosts <= 8192):
        raise SystemExit(
            f"{where}.hosts={fc.hosts!r}: must be an int in [0, 8192] "
            "(0 = inherit serve.hosts)"
        )
    if not isinstance(fc.pods, int) or fc.pods < 0:
        raise SystemExit(f"{where}.pods={fc.pods!r}: must be an int >= 0")
    if fc.pods > max(fc.hosts, sc.hosts):
        raise SystemExit(
            f"{where}.pods={fc.pods}: more pods than hosts "
            f"({max(fc.hosts, sc.hosts)})"
        )
    if not isinstance(fc.workers_per_host, int) or fc.workers_per_host < 0:
        raise SystemExit(
            f"{where}.workers_per_host={fc.workers_per_host!r}: must be "
            "an int >= 0 (0 = serve.workers as the global pool)"
        )
    if not isinstance(fc.objects, int) or fc.objects < 1:
        raise SystemExit(
            f"{where}.objects={fc.objects!r}: must be an int >= 1"
        )
    for name in ("origin_service_ms", "peer_service_ms", "cross_pod_ms"):
        v = getattr(fc, name)
        if not (v > 0):  # also rejects NaN
            raise SystemExit(f"{where}.{name}={v!r}: must be > 0")
    for name in ("hit_service_ms", "pause_penalty_ms", "fail_at_s",
                 "recover_s", "upgrade_stagger_s"):
        v = getattr(fc, name)
        if not (v >= 0):  # also rejects NaN
            raise SystemExit(f"{where}.{name}={v!r}: must be >= 0")
    if fc.timeline not in FLEET_TIMELINE_KINDS:
        raise SystemExit(
            f"{where}.timeline={fc.timeline!r}: must be one of "
            f"{FLEET_TIMELINE_KINDS}"
        )
    if not (0.0 < fc.fail_fraction < 1.0):  # also rejects NaN
        raise SystemExit(
            f"{where}.fail_fraction={fc.fail_fraction!r}: must be in "
            "(0, 1) — someone has to survive"
        )
    if not (fc.upgrade_pause_s > 0):
        raise SystemExit(
            f"{where}.upgrade_pause_s={fc.upgrade_pause_s!r}: must be > 0"
        )
    if not isinstance(fc.seed, int) or fc.seed < 0:
        raise SystemExit(f"{where}.seed={fc.seed!r}: must be an int >= 0")
    if not isinstance(fc.profile, dict):
        raise SystemExit(
            f"{where}.profile: must be a fleet-profile dict "
            f"(got {type(fc.profile).__name__})"
        )


# Knobs the tune controller may actuate (the canonical name set; the
# controller's ACTUATED registry maps each to its config field and CLI
# flag, and tests/test_tune.py pins that the three surfaces never drift).
TUNE_KNOBS = (
    "workers",
    "readahead",
    "readahead_bytes",
    "prefetch_workers",
    "hedge_delay_s",
    "staging_depth",
    "peer_budget_bytes",
    "coop",
)


@dataclass
class TuneConfig:
    """Adaptive ingest autotuner (tpubench/tune/): a congestion-control-
    style online controller that adjusts worker fan-out, readahead
    depth/bytes, prefetch workers and the hedge delay DURING a run, from
    windowed goodput and p99 latency sampled off the run's own
    recorders.

    Objective: maximize goodput subject to a p99 inflation guardrail
    (``p99 <= p99_guard x baseline p99``, baseline measured over the
    warmup windows at the starting operating point). Policy is
    AIMD-flavored hill climbing: one knob probed per window (multiplying
    knobs double/halve, additive knobs step by one quantum); a probe
    whose window improves goodput by ``epsilon`` within the guardrail is
    accepted, anything else reverts. A knob that reverts
    ``freeze_after_reverts`` times without an intervening accept freezes
    for ``cooldown_windows`` (oscillation damping); when every knob is
    frozen at once the session is CONVERGED and actuation stops — the
    operating point holds for the rest of the run.

    Off by default: ``enabled`` turns the online controller on inside
    ``read`` and ``train-ingest``; ``tpubench tune`` drives offline
    coordinate sweeps and online sessions as a workload of its own."""

    enabled: bool = False
    # Decision window (seconds): the controller samples goodput/p99 and
    # makes one accept/revert decision per window.
    window_s: float = 0.5
    # Windows measured at the starting operating point before any probe
    # (the guardrail's p99 baseline and the first goodput reference).
    warmup_windows: int = 2
    # Guardrail: a probe window whose p99 exceeds baseline_p99 x this is
    # reverted regardless of goodput (the tail must not be traded away).
    p99_guard: float = 2.0
    # Minimum relative goodput gain for a probe to be accepted.
    epsilon: float = 0.05
    # Oscillation damping: reverts-without-accept before a knob freezes,
    # and how many windows the freeze lasts. Once EVERY knob is frozen
    # simultaneously the controller is converged and stops probing.
    freeze_after_reverts: int = 2
    cooldown_windows: int = 1_000_000  # effectively "until run end"
    # Online read sessions are duration-bounded (a parked elastic worker
    # could otherwise hold the run open forever); train-ingest stays
    # step-bounded and ignores this.
    duration_s: float = 8.0
    # Which knobs to actuate (subset of TUNE_KNOBS); each workload uses
    # the intersection with what it can actually actuate live.
    knobs: list = field(default_factory=lambda: list(TUNE_KNOBS))
    # Deterministic-rng seed (probe direction tie-breaks).
    seed: int = 0


def validate_tune_config(tc: "TuneConfig", where: str = "tune") -> None:
    """Parse-time sanity for the tune knobs (validate_fault_config
    style: one-line SystemExit at config load, not mid-run)."""
    if tc.window_s <= 0 or tc.window_s != tc.window_s:
        raise SystemExit(f"{where}.window_s={tc.window_s!r}: must be > 0")
    if tc.warmup_windows < 1:
        raise SystemExit(
            f"{where}.warmup_windows={tc.warmup_windows!r}: must be >= 1"
        )
    if not (tc.p99_guard >= 1.0):  # also rejects NaN
        raise SystemExit(
            f"{where}.p99_guard={tc.p99_guard!r}: must be >= 1.0 "
            "(1.0 = no tail inflation tolerated)"
        )
    if not (tc.epsilon >= 0.0):
        raise SystemExit(f"{where}.epsilon={tc.epsilon!r}: must be >= 0")
    if tc.freeze_after_reverts < 1:
        raise SystemExit(
            f"{where}.freeze_after_reverts={tc.freeze_after_reverts!r}: "
            "must be >= 1"
        )
    if tc.cooldown_windows < 1:
        raise SystemExit(
            f"{where}.cooldown_windows={tc.cooldown_windows!r}: must be >= 1"
        )
    if not (tc.duration_s > 0.0):
        # Online READ sessions are duration-bounded because a parked
        # elastic worker can no longer gate completion: a zero/negative
        # cap would let an accepted fan-out shrink hang the run forever.
        raise SystemExit(
            f"{where}.duration_s={tc.duration_s!r}: must be > 0 "
            "(the online read session's wall-clock bound)"
        )
    unknown = sorted(set(tc.knobs) - set(TUNE_KNOBS))
    if unknown:
        raise SystemExit(
            f"{where}.knobs: unknown knob(s) {unknown}; "
            f"valid: {sorted(TUNE_KNOBS)}"
        )


@dataclass
class TelemetryConfig:
    """Live telemetry plane (tpubench/obs/telemetry.py): an in-process
    pull-based metrics registry — counters, gauges, and fixed-bucket
    latency histograms on the reference view's bucket bounds — fed
    incrementally from the flight channel, the run's latency recorders
    and the native ``tb_stats_*`` counters while the run is in flight.

    Exposed three ways: a tiny stdlib-only HTTP endpoint (Prometheus
    text exposition at ``/metrics`` + JSON ``/snapshot``), periodic
    OTLP-shaped JSON export through the exporters machinery, and the
    journal stream the live aggregator behind ``tpubench top`` tails.
    All off by default — the reference pushes to Cloud Monitoring every
    30 s or is blind; this is the same signal, scrapeable locally."""

    # Master switch for the in-run registry; implied by port >= 0 or
    # otlp, so `--telemetry-port 0` alone turns the plane on.
    enabled: bool = False
    # HTTP endpoint port: -1 = no endpoint, 0 = ephemeral (the OS picks;
    # the run prints the bound port), >0 = fixed. Loopback only.
    port: int = -1
    # Registry tick (seconds): gauge refresh, recorder/native-counter
    # sampling, and the in-run journal stream cadence.
    interval_s: float = 1.0
    # Periodic OTLP-shaped JSON metric export (resourceMetrics/
    # scopeMetrics shape). Without an endpoint the payloads are captured
    # dry-run (stamped into the result for tests/offline upload);
    # with otlp_endpoint set they POST via stdlib urllib — no new deps.
    otlp: bool = False
    otlp_interval_s: float = 30.0
    otlp_endpoint: str = ""

    @property
    def active(self) -> bool:
        return self.enabled or self.port >= 0 or self.otlp


def validate_telemetry_config(tc: "TelemetryConfig",
                              where: str = "telemetry") -> None:
    """Parse-time sanity for the telemetry knobs (one-line SystemExit at
    config load — the validate_fault_config style)."""
    if not (-1 <= tc.port <= 65535):
        raise SystemExit(
            f"{where}.port={tc.port!r}: must be -1 (off), 0 (ephemeral) "
            "or a valid TCP port"
        )
    if not (tc.interval_s > 0):  # also rejects NaN
        raise SystemExit(
            f"{where}.interval_s={tc.interval_s!r}: must be > 0"
        )
    if not (tc.otlp_interval_s > 0):
        raise SystemExit(
            f"{where}.otlp_interval_s={tc.otlp_interval_s!r}: must be > 0"
        )
    if tc.otlp_endpoint and not (
        tc.otlp_endpoint.startswith("http://")
        or tc.otlp_endpoint.startswith("https://")
    ):
        raise SystemExit(
            f"{where}.otlp_endpoint={tc.otlp_endpoint!r}: must be an "
            "http(s) URL (the OTLP/HTTP JSON receiver)"
        )


@dataclass
class TransportConfig:
    """L1 client construction knobs (reference ``main.go:30-42,62-117``)."""

    protocol: str = "http"  # "http" | "grpc" | "local" | "fake"; main.go:44-46
    # HTTP path (CreateHttpClient, main.go:62-104):
    max_conns_per_host: int = 100  # main.go:31
    max_idle_conns_per_host: int = 100  # main.go:32
    http2: bool = False  # reference disables HTTP/2 for perf (main.go:64-72)
    # Opt-in C++ receive path (SURVEY §2.5.1): body streams from the socket
    # into a pre-registered aligned buffer with a native first-byte stamp,
    # over pooled keep-alive connections; plaintext and TLS endpoints.
    native_receive: bool = False
    # TLS trust for the native receive path: a CA bundle overriding the
    # system store (test endpoints with a private CA), and an escape hatch
    # that skips verification entirely.
    tls_ca_file: str = ""
    tls_insecure_skip_verify: bool = False
    user_agent: str = "tpubench"  # reference: "prince" (main.go:100)
    # gRPC path (CreateGrpcClient, main.go:106-117):
    grpc_conn_pool_size: int = 1  # main.go:30
    directpath: bool = True  # GOOGLE_CLOUD_ENABLE_DIRECT_PATH_XDS (main.go:107)
    # Auth (auth.go): path to a service-account key file; empty = ADC.
    key_file: str = ""  # auth.go:55-68
    # Endpoint override so the same client drives the hermetic fake GCS server.
    endpoint: str = ""  # empty = https://storage.googleapis.com
    retry: RetryConfig = field(default_factory=RetryConfig)
    fault: FaultConfig = field(default_factory=FaultConfig)
    tail: TailConfig = field(default_factory=TailConfig)


@dataclass
class WorkloadConfig:
    """L4 driver knobs: the union of every benchmark binary's flag surface.

    Root bench (``main.go:36-57``), read_operation (``:18-29``),
    write_operations (``:18-32``), list/open, ssd_test (``:19-37``).
    """

    # --- root read bench (main.go) ---
    workers: int = 48  # --worker, main.go:36
    read_calls_per_worker: int = 1000  # --read-call-per-worker (ref: 1e6), main.go:37
    bucket: str = ""  # --bucket, main.go:44
    project: str = ""  # --project, main.go:45
    object_name_prefix: str = "tpubench/file_"  # main.go:50-53 (was hardcoded)
    # Transfer granule: the reference streams via a 2 MB copy buffer tuned to
    # the gRPC server's 2 MB message chunking (comment main.go:123-125).
    granule_bytes: int = 2 * MB
    # --- filesystem-path drivers (benchmark-script/*) ---
    dir: str = ""  # --dir: gcsfuse mount / local dir
    threads: int = 4  # --threads
    read_count: int = 1  # --read-count: passes per file
    block_size_kb: int = 1024  # --block-size (KB), read_operation/main.go:20
    file_size_mb: int = 64  # --file-size-mb
    write_count: int = 1  # write_operations --write-count
    fsync_every_block: bool = True  # write_operations fsyncs per block (:63-71)
    open_files: int = 64  # open_file --open-files
    hold_seconds: float = 0.0  # open_file FD-hold (ref: 180 s, :52-55)
    read_type: str = "seq"  # ssd_test --read-type: "seq" | "random" (:118-128)
    seed: int = 0  # offset-shuffle seed (ssd_test uses global rand)
    # Mount orchestration (launcher convention, read_operations.sh:18-21):
    # shell command templates run before/after FS workloads; "{dir}" expands
    # to the workload dir. Empty = assume pre-mounted (the default). With
    # both set, listing/open also get TRUE cold rounds via remount.
    mount_cmd: str = ""  # e.g. "gcsfuse --stat-cache-ttl 10000m B {dir}"
    unmount_cmd: str = ""  # e.g. "fusermount -u {dir}"
    # Listing rounds: round 0 is the cold round (after remount when
    # available), the rest are hot — the list_operations.sh:11-21 hot/cold
    # sweep in one run.
    list_rounds: int = 5
    # Object/file sizes for data generation in hermetic/fake runs.
    object_size: int = 100 * MB  # reference objects are ~100 MB-class (main.go:52)
    # errgroup semantics: first worker error aborts the run (main.go:200-219).
    # False = per-worker failure domains; failures become holes in the result
    # (SURVEY §5.3 prescription) instead of a pod-wide abort.
    abort_on_error: bool = True
    # Fan-out runtime for the read workload: "python" = worker threads
    # (each GIL-releasing I/O call native); "native" = the C++ fetch
    # executor (tb_pool_*) in its REACTOR shape — an epoll event loop
    # owning all connections with completions delivered over lock-free
    # SPSC rings (one wake drains the backlog; the per-completion
    # lock/condvar handoff BENCH_r05 blamed is gone). "native-threads"
    # pins the legacy thread-per-connection pool (the TLS path and the
    # A/B comparator); "native-reactor" pins the reactor explicitly.
    # Native scope: plain-http endpoints (reactor: plaintext; TLS falls
    # back to the thread pool), staging "none" or "device_put".
    fetch_executor: str = "python"


@dataclass
class StagingConfig:
    """GCS→HBM staging (no reference analog; the north-star delta)."""

    mode: str = "device_put"  # "none" (host RAM, reference parity) |
    # "device_put" | "pallas"
    double_buffer: bool = True  # overlap fetch with host→HBM DMA
    # In-flight window depth when overlapping (double_buffer=True): how
    # many host→HBM transfers the staging executor keeps pending at
    # once, completing them OUT OF ORDER (staging/executor.py).
    # double_buffer=False forces a fully synchronous single slot. Live:
    # the tune controller actuates this via the `staging_depth` knob.
    depth: int = 3
    # Granule-aggregation target: fetched granules are packed into slots of
    # this size and shipped with ONE device_put per slot. Host→HBM transfer
    # engines have per-transfer fixed cost; 2 MB granules transfer ~20%
    # slower than 8-16 MB slots (measured on TPU v5e: 1.47 vs 1.79 GB/s).
    # Clamped up to granule_bytes when granules are larger, and down so
    # workers × depth × slot stays within host_budget_mb.
    slot_bytes: int = 16 * MB
    # Total host staging-slot memory budget across all workers: slot_bytes
    # is scaled down (never below one granule) when workers × depth × slot
    # would exceed it — 48 default workers must not pin 2+ GB up front.
    host_budget_mb: int = 1024
    # Staging slots in native posix_memalign'd buffers (DLPack producers,
    # SURVEY §2.5.4) so fetch→slot→HBM has no Python-held copy; auto-falls
    # back to numpy slots when the C++ engine is unavailable.
    native_slots: bool = True
    # Fetch directly into the staging slot (sink acquire/commit) instead of
    # through a per-worker granule buffer that is then copied to the slot.
    zero_copy: bool = True
    # DEPRECATED (kept so old config JSONs still load): depth > 1 now
    # always rides the overlapped staging executor — a depth-K in-flight
    # window whose reaper thread submits AND completes transfers out of
    # order (staging/executor.py) — which supersedes both the old
    # "inline" (fetch-thread drains) and "thread" (serial drainer)
    # modes. depth == 1 and validate_checksum keep the serial inline
    # ring (validation needs orderly drains).
    drain: str = "inline"
    # Shape landed arrays as (granule//lane, lane) uint8 so XLA tiles them;
    # lane=128 matches the TPU lane width.
    lane: int = 128
    validate_checksum: bool = False  # on-device checksum of landed bytes


@dataclass
class DistConfig:
    """Multi-host / multi-chip fan-out (replaces "run on more VMs by hand")."""

    # jax.distributed bring-up (CLI: --num-processes/--process-id/
    # --coordinator, or TPUBENCH_NUM_PROCESSES/_PROCESS_ID/_COORDINATOR env);
    # 1 = single-process. The pod workloads then fetch only their local
    # chips' shards and reassemble over ICI — the launchable-everywhere
    # property of the reference (main.go:158) without "run on more VMs by
    # hand".
    num_processes: int = 1
    process_id: int = 0
    coordinator_address: str = ""
    mesh_axis: str = "pod"  # 1-D mesh over all chips


@dataclass
class ObservabilityConfig:
    """L2 metrics/tracing (metrics_exporter.go, trace_exporter.go)."""

    enable_tracing: bool = False  # --enable-tracing, main.go:56
    trace_sample_rate: float = 1.0  # --trace-sample-rate, main.go:57
    # Span export path: "" (spans created, not exported), "console", or
    # "cloud_trace" (reference: trace_exporter.go:19, gated on the GCP pkg).
    trace_exporter: str = ""
    metrics_interval_s: float = 30.0  # Stackdriver reporting interval (:44)
    metric_prefix: str = "custom.googleapis.com/tpubench/"  # (:41)
    # "none"/"json" = result file only; "cloud" = in-run periodic push of
    # the full latency histograms + ingest gauges every metrics_interval_s
    # (metrics_exporter.go:36-58) with a guaranteed final flush.
    export: str = "json"
    # "cloud" pushes are captured locally (and stamped into the result)
    # unless this is False, which requires google-cloud-monitoring + GCP
    # creds — absence fails loudly, never a silent no-op.
    export_dry_run: bool = True
    # Upload result JSONs to this bucket via the framework's own storage
    # backends — the execute_pb.sh:5 `gsutil cp` loop, first-class. Empty =
    # local disk only. Object names: results/<filename>.
    results_bucket: str = ""
    results_dir: str = "results"
    # Non-empty = capture a jax.profiler (xplane) trace of the run there
    # (SURVEY §5.1: the DMA/collective path profiled first-class, replacing
    # the reference's attach-an-external-profiler sleeps).
    profile_dir: str = ""
    # train-ingest only: bound the capture to a step window "N:M"
    # (inclusive; e.g. "2:5" traces steps 2..5). Empty = the whole step
    # loop. Parsed/validated by obs.profiling.parse_profile_steps; a
    # no-op when jax profiling is unavailable.
    profile_steps: str = ""
    # Flight recorder (obs/flight.py): per-worker ring capacity of
    # structured per-read phase records (enqueue/connect/first_byte/
    # body_complete/hbm_staged/gather_complete + retry annotations) — the
    # always-on, zero-GCP-dependency layer beneath spans/exporters.
    # 0 disables it entirely.
    flight_records: int = 1024
    # Non-empty = write the per-host flight journal JSON here at end of
    # run (stream: periodically, riding the SnapshotWriter flush path).
    # Multi-host processes suffix ".p<idx>" (snapshot-file convention);
    # `tpubench report timeline <paths...>` merges them pod-wide. A
    # ".gz" suffix writes the journal gzip-compressed (readers — report
    # timeline and the live aggregator — decompress transparently).
    flight_journal: str = ""
    # Size bound (bytes, on the serialized JSON doc) for each journal
    # write: when a flush would exceed it, the OLDEST records are
    # dropped and counted in the doc's `rotation_dropped` field — a
    # long serve-shaped run streaming journals every telemetry tick
    # must not fill the disk. 0 = unbounded.
    journal_max_bytes: int = 0


@dataclass
class BenchConfig:
    """Top-level config: one object covers every knob of every workload."""

    workload: WorkloadConfig = field(default_factory=WorkloadConfig)
    transport: TransportConfig = field(default_factory=TransportConfig)
    staging: StagingConfig = field(default_factory=StagingConfig)
    dist: DistConfig = field(default_factory=DistConfig)
    obs: ObservabilityConfig = field(default_factory=ObservabilityConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    tune: TuneConfig = field(default_factory=TuneConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    coop: CoopConfig = field(default_factory=CoopConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    lifecycle: LifecycleConfig = field(default_factory=LifecycleConfig)
    drill: DrillConfig = field(default_factory=DrillConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)

    # ------------------------------------------------------------------ io --
    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "BenchConfig":
        def build(tp, val):
            if not dataclasses.is_dataclass(tp) or not isinstance(val, dict):
                return val
            kwargs = {}
            for f in dataclasses.fields(tp):
                if f.name in val:
                    ftype = f.type
                    sub = _SUBTYPES.get(f.name)
                    kwargs[f.name] = build(sub, val[f.name]) if sub else val[f.name]
            return tp(**kwargs)

        return build(cls, d)

    @classmethod
    def from_json(cls, s: str) -> "BenchConfig":
        return cls.from_dict(json.loads(s))


_SUBTYPES = {
    "workload": WorkloadConfig,
    "transport": TransportConfig,
    "staging": StagingConfig,
    "dist": DistConfig,
    "obs": ObservabilityConfig,
    "pipeline": PipelineConfig,
    "tune": TuneConfig,
    "telemetry": TelemetryConfig,
    "coop": CoopConfig,
    "serve": ServeConfig,
    "lifecycle": LifecycleConfig,
    "drill": DrillConfig,
    "fleet": FleetConfig,
    "retry": RetryConfig,
    "fault": FaultConfig,
    "tail": TailConfig,
}


# --------------------------------------------------------------- presets ----
def preset(name: str) -> BenchConfig:
    """Named workload presets replacing the reference's shell sweeps.

    ``read_operations.sh:8-14`` sweeps file sizes 256KB/1MB/100MB/1GB with
    per-size read counts 1000/100/10/1.
    """
    cfg = BenchConfig()
    sweeps = {
        "256kb": (256 * KB, 1000),
        "1mb": (1 * MB, 100),
        "100mb": (100 * MB, 10),
        "1gb": (1 * GB, 1),
    }
    key = name.lower()
    if key in sweeps:
        size, count = sweeps[key]
        cfg.workload.object_size = size
        cfg.workload.file_size_mb = max(1, size // MB)
        cfg.workload.read_count = count
        cfg.workload.read_calls_per_worker = count
        return cfg
    if key == "smoke":  # tiny hermetic run for CI / laptops
        cfg.workload.workers = 2
        cfg.workload.threads = 2
        cfg.workload.read_calls_per_worker = 2
        cfg.workload.object_size = 4 * MB
        cfg.workload.file_size_mb = 4
        cfg.transport.protocol = "fake"
        return cfg
    raise KeyError(f"unknown preset {name!r}; have 256kb/1mb/100mb/1gb/smoke")


PRESET_NAMES = ("256kb", "1mb", "100mb", "1gb", "smoke")
