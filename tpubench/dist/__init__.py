"""Distributed fan-out and pod-level reassembly (SURVEY §2.6, §5.7, §5.8).

The reference's only parallelism is single-host goroutine fan-out
(``main.go:200-212``); multi-node = "run the binary on more VMs by hand".
Here the fan-out axes are first-class:

* ``bringup``   — ``jax.distributed`` process bring-up over DCN;
* ``shard``     — host×worker→object and object→byte-range shard tables
                  (the CP-analog: one logical object split across the pod);
* ``reassemble``— ICI all-gather of byte-range shards under ``shard_map``
                  (XLA-native and explicit ppermute-ring variants), the
                  TPU-native replacement for a NCCL/MPI backend.
"""

from tpubench.dist.shard import ShardTable, worker_object_index  # noqa: F401
from tpubench.dist.reassemble import (  # noqa: F401
    make_mesh,
    make_reassemble,
    make_ring_reassemble,
)
