"""Distributed fan-out and pod-level reassembly (SURVEY §2.6, §5.7, §5.8).

The reference's only parallelism is single-host goroutine fan-out
(``main.go:200-212``); multi-node = "run the binary on more VMs by hand".
Here the fan-out axes are first-class:

* ``bringup``   — ``jax.distributed`` process bring-up over DCN;
* ``shard``     — host×worker→object and object→byte-range shard tables
                  (the CP-analog: one logical object split across the pod);
* ``reassemble``— ICI all-gather of byte-range shards under ``shard_map``
                  (XLA-native and explicit ppermute-ring variants), the
                  TPU-native replacement for a NCCL/MPI backend;
* ``peer``      — lockstep ICI peer-transfer channel for the coop cache;
* ``membership``— elastic pod membership (epoch-numbered views, warm
                  handoff, the hermetic elastic fabric) — jax-free.

Package attributes resolve lazily (PEP 562): ``shard``/``reassemble``
import jax, and the jax-free planes (membership, serve, report, check)
must be able to import their dist submodules without paying — or
requiring — a jax import.
"""

_LAZY = {
    "ShardTable": "tpubench.dist.shard",
    "worker_object_index": "tpubench.dist.shard",
    "make_mesh": "tpubench.dist.reassemble",
    "make_reassemble": "tpubench.dist.reassemble",
    "make_ring_reassemble": "tpubench.dist.reassemble",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
