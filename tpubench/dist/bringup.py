"""Multi-host bring-up over DCN (SURVEY §5.8).

``jax.distributed.initialize`` is the control plane (coordinator over DCN);
data-plane collectives ride ICI via the jitted reassembly. Single-process
runs (tests, single-VM benches) skip initialization entirely.
"""

from __future__ import annotations

from tpubench.config import DistConfig


def initialize(cfg: DistConfig) -> dict:
    """Idempotent bring-up; returns topology facts for the run report.

    Single-process configs return immediately WITHOUT importing jax, so
    jax-free paths (FS workloads, config handling) stay jax-free."""
    if cfg.num_processes <= 1:
        return {"process_index": 0, "process_count": 1}
    import jax

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address or None,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
