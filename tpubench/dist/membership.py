"""Elastic pod membership: epoch-numbered views driving the coop ring.

The serve plane (PR 10) is single-host and the coop cache ring (PR 8)
assumes fixed membership — neither can measure the production scenario
where a pod *changes shape under load*: hosts join as diurnal traffic
ramps, leave cooperatively when it ebbs, and sometimes just die. This
module makes membership a first-class, observable axis:

* :class:`Membership` — a small deterministic state machine. Every host
  is ``up``, ``paused`` or ``down``; every transition (join / leave /
  fail / pause / resume) bumps a monotonically increasing **epoch** and
  is journaled as a ``kind="member"`` flight record, so the journal can
  say exactly when the pod's shape changed (and ``report timeline`` /
  ``tpubench top`` count it). The clock is injectable (the PR-12
  determinism rule): the elastic serve harness drives it with *virtual
  schedule time*, so event stamps line up with arrival stamps and tests
  replay the same timeline bit-for-bit.
* :class:`ElasticFabric` — the membership-aware loopback fabric for
  hermetic threaded pods (the ``run_coop_sim`` broker grown up): it
  owns the shared :class:`~tpubench.pipeline.coop.LoopbackBroker`, the
  shared :class:`~tpubench.pipeline.coop.HashRing` and the pod's
  :class:`~tpubench.pipeline.coop.CoopCache` handles, subscribes to the
  membership, and translates each transition into transport + ring
  effects:

  - **fail (kill)** — the host's serve side unregisters immediately (no
    handoff): peers asking it get a definitive ``PeerMissError`` and
    fall back to origin under the existing breaker/retry composition;
    its ring points leave, so ~1/N of chunk ownership remaps.
  - **leave (cooperative)** — the ring updates first, then the departing
    host **drains its hot set** over the ordinary peer channel to each
    chunk's NEW owner (:meth:`ElasticFabric.leave_host`), so the pod
    re-warms from host RAM instead of re-fetching from origin. Handoff
    bytes are journaled as a ``member`` note (no epoch bump — the view
    already changed).
  - **pause / resume** — the host stays on the ring but its peer serve
    raises *transient* errors: the requester's bounded peer-tier retry
    re-asks, then falls through to origin — the degradation path a
    stalled-but-not-dead host produces.
  - **join (rejoin)** — the host re-enters the ring CLEAN: its demotion
    state was purged when it left (``HashRing.remove_host`` forgets
    demotions) and :meth:`~tpubench.pipeline.coop.CoopCache.reset_member_state`
    drops its stale peer-transfer samples, so a host that left demoted
    never re-enters pre-demoted and old straggler evidence cannot
    outlive the epoch bump.

Ownership remap accounting (:func:`remap_stats`) is computed over the
workload's own key universe — the "~1/N of keys move per event"
consistent-hash promise becomes a measured, per-event scorecard row
rather than a docstring claim.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from tpubench.pipeline.coop import CoopCache, HashRing, LoopbackBroker

# Membership actions that change the pod view (epoch bumps), plus the
# non-view note actions the journal also carries.
VIEW_ACTIONS = ("join", "leave", "fail", "pause", "resume")
NOTE_ACTIONS = ("handoff",)

# Host-level timeline entry keys (`[t0, t1, {action: host}]`) are
# single-sourced as config.MEMBER_TIMELINE_ACTIONS — the timeline
# validator, the chaos splitter and the serve dispatcher all read that
# tuple directly.

# Event-log bound (the EXACT_SAMPLE_CAP discipline): membership events
# are rare, but a looping chaos timeline must not grow host RSS.
EVENT_LOG_CAP = 4096


class MembershipError(ValueError):
    """An invalid transition (e.g. failing a host that is already down).
    The state machine refuses and does NOT bump the epoch — a chaos
    timeline that kills a host twice gets one kill and one error."""


@dataclass(frozen=True)
class MemberEvent:
    """One journaled membership-plane event."""

    epoch: int
    action: str  # VIEW_ACTIONS | NOTE_ACTIONS
    host: int
    t_s: float  # injected-clock stamp (virtual time under the harness)
    info: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {
            "epoch": self.epoch, "action": self.action,
            "host": self.host, "t_s": self.t_s,
        }
        if self.info:
            d.update(self.info)
        return d


class Membership:
    """Epoch-numbered pod membership state machine (module docstring).

    States: ``up`` (serving, dispatchable), ``paused`` (on the ring but
    unresponsive), ``down`` (off the ring). Transitions:

    ========  ===================  =======
    action    valid from           to
    ========  ===================  =======
    join      down / absent        up
    leave     up / paused          down
    fail      up / paused          down
    pause     up                   paused
    resume    paused               up
    ========  ===================  =======

    Every valid transition bumps :attr:`epoch` by exactly one; invalid
    transitions raise :class:`MembershipError` and change nothing.
    Listeners run OUTSIDE the membership lock (they take ring/broker
    locks of their own — lock-order discipline)."""

    def __init__(self, hosts: Iterable[int] = (), *,
                 clock: Callable[[], float] = time.monotonic,
                 flight_ring=None):
        self._clock = clock
        self._flight_ring = flight_ring
        self._lock = threading.Lock()
        self._states: dict[int, str] = {int(h): "up" for h in hosts}
        self._epoch = 0
        self._events: deque = deque(maxlen=EVENT_LOG_CAP)
        self._listeners: list[Callable[[MemberEvent], None]] = []

    # ----------------------------------------------------------- queries --
    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def state(self, host: int) -> Optional[str]:
        with self._lock:
            return self._states.get(int(host))

    def live_hosts(self) -> set[int]:
        """Hosts the front end may dispatch NEW work to (state ``up``;
        a paused host is unresponsive everywhere, not just peer-side)."""
        with self._lock:
            return {h for h, s in self._states.items() if s == "up"}

    def ring_hosts(self) -> set[int]:
        """Hosts that hold ring points (``up`` + ``paused``): a paused
        owner keeps its keys — routed misses pay the transient-retry →
        origin-fallback path, which is the point."""
        with self._lock:
            return {h for h, s in self._states.items() if s != "down"}

    def is_live(self, host: int) -> bool:
        return self.state(host) == "up"

    def view(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "states": dict(self._states),
            }

    def events(self) -> list[MemberEvent]:
        with self._lock:
            return list(self._events)

    # ------------------------------------------------------- transitions --
    def subscribe(self, fn: Callable[[MemberEvent], None]) -> None:
        self._listeners.append(fn)

    def _transition(self, action: str, host: int, valid_from: tuple,
                    to: str, info: Optional[dict] = None) -> MemberEvent:
        host = int(host)
        with self._lock:
            cur = self._states.get(host)
            if cur not in valid_from:
                raise MembershipError(
                    f"cannot {action} host {host}: state is {cur!r} "
                    f"(valid from {'/'.join(str(v) for v in valid_from)})"
                )
            self._epoch += 1
            self._states[host] = to
            ev = MemberEvent(
                self._epoch, action, host, self._clock(), dict(info or {})
            )
            self._events.append(ev)
        self._journal(ev)
        for fn in self._listeners:
            fn(ev)
        return ev

    def join(self, host: int, info: Optional[dict] = None) -> MemberEvent:
        """A new or previously-departed host enters the pod (``up``)."""
        return self._transition("join", host, ("down", None), "up", info)

    def leave(self, host: int, info: Optional[dict] = None) -> MemberEvent:
        """Cooperative departure (the warm-handoff arm — the fabric
        drains the hot set right after the view changes)."""
        return self._transition("leave", host, ("up", "paused"), "down",
                                info)

    def fail(self, host: int, info: Optional[dict] = None) -> MemberEvent:
        """Host death: no handoff, no goodbye — the degradation arm."""
        return self._transition("fail", host, ("up", "paused"), "down",
                                info)

    def pause(self, host: int, info: Optional[dict] = None) -> MemberEvent:
        return self._transition("pause", host, ("up",), "paused", info)

    def resume(self, host: int, info: Optional[dict] = None) -> MemberEvent:
        return self._transition("resume", host, ("paused",), "up", info)

    def note_event(self, action: str, host: int,
                   info: Optional[dict] = None) -> MemberEvent:
        """Journal a membership-plane event that does NOT change the
        view (no epoch bump): the cooperative handoff's byte accounting
        rides here, stamped under the epoch the leave just created."""
        if action not in NOTE_ACTIONS:
            raise MembershipError(f"unknown note action {action!r}")
        with self._lock:
            ev = MemberEvent(
                self._epoch, action, int(host), self._clock(),
                dict(info or {}),
            )
            self._events.append(ev)
        self._journal(ev)
        return ev

    # ---------------------------------------------------------- journal --
    def _journal(self, ev: MemberEvent) -> None:
        if self._flight_ring is None:
            return
        op = self._flight_ring.begin(
            f"member/{ev.action}/host{ev.host}", "", install=False,
            kind="member",
        )
        op.note("member", action=ev.action, host=ev.host, epoch=ev.epoch,
                **ev.info)
        op.finish(0)


# ----------------------------------------------------------------- remap ----


def remap_stats(keys: Iterable, before: dict, after: dict) -> dict:
    """Ownership-remap accounting over one membership event: ``before``
    / ``after`` map each chunk key to its ring owner (None = no owner).
    Returns the moved-key count/fraction and the moved BYTES (the
    consistent-hash "~1/N per event" promise, measured)."""
    total = moved = 0
    moved_bytes = 0
    for k in keys:
        total += 1
        if before.get(k) != after.get(k):
            moved += 1
            moved_bytes += getattr(k, "length", 0)
    return {
        "keys": total,
        "remapped_keys": moved,
        "remap_fraction": (moved / total) if total else 0.0,
        "remap_bytes": moved_bytes,
    }


# ---------------------------------------------------------------- fabric ----


class ElasticFabric:
    """Membership-aware hermetic pod fabric (module docstring): the
    shared broker + shared ring + per-host CoopCache handles, with the
    per-host kill / pause / resume / leave / rejoin controls the chaos
    timeline drives. Mutating controls are called from ONE driver thread
    (the serve dispatcher / the test body); queries are thread-safe
    through the membership's and ring's own locks."""

    def __init__(self, n_hosts: int, *, vnodes: int = 64,
                 clock: Callable[[], float] = time.monotonic,
                 flight_ring=None):
        self.broker = LoopbackBroker()
        self.ring = HashRing(range(n_hosts), vnodes=vnodes)
        self.membership = Membership(
            range(n_hosts), clock=clock, flight_ring=flight_ring
        )
        self.membership.subscribe(self._apply)
        self._hosts: dict[int, CoopCache] = {}
        self._delays: dict[int, float] = {}

    # ---------------------------------------------------------- plumbing --
    def add_host(self, coop: CoopCache, *, delay_s: float = 0.0) -> None:
        """Register one host's CoopCache with the fabric: its serve side
        answers peer requests, its accept side lands warm handoffs."""
        h = int(coop.host_id)
        self._hosts[h] = coop
        self._delays[h] = delay_s
        self.broker.register(
            h, coop.serve, delay_s=delay_s, accept=coop.accept_handoff
        )

    def coop(self, host: int) -> CoopCache:
        return self._hosts[int(host)]

    def hosts(self) -> dict[int, CoopCache]:
        return dict(self._hosts)

    def live_hosts(self) -> set[int]:
        return self.membership.live_hosts()

    def is_dispatchable(self, host: int) -> bool:
        return self.membership.is_live(host)

    def owners_of(self, keys: Iterable) -> dict:
        """Current ring owner per key (the remap-accounting probe)."""
        return {k: self.ring.owner(k) for k in keys}

    # ---------------------------------------------------------- controls --
    def kill_host(self, host: int) -> bool:
        """Host death: fail the membership (no handoff). Returns False
        when the host was already down (double-kill in a timeline)."""
        try:
            self.membership.fail(host)
            return True
        except MembershipError:
            return False

    def pause_host(self, host: int) -> bool:
        try:
            self.membership.pause(host)
            return True
        except MembershipError:
            return False

    def resume_host(self, host: int) -> bool:
        try:
            self.membership.resume(host)
            return True
        except MembershipError:
            return False

    def rejoin_host(self, host: int) -> bool:
        """A departed host re-enters — CLEAN (see module docstring)."""
        try:
            self.membership.join(host)
            return True
        except MembershipError:
            return False

    def leave_host(self, host: int, *, max_bytes: int = 0) -> Optional[dict]:
        """Cooperative departure with warm handoff: the view changes
        first (ring excludes the host, its serve side unregisters), then
        the departing host drains its hot set over the peer channel to
        each chunk's NEW owner — re-warming the pod from host RAM
        instead of origin. Returns the handoff stats (None when the host
        was not up/paused)."""
        coop = self._hosts.get(int(host))
        try:
            self.membership.leave(host)
        except MembershipError:
            return None
        if coop is None:
            return {"chunks": 0, "bytes": 0, "rejected": 0, "skipped": 0}
        stats = coop.drain_hot_set(
            push=lambda owner, key, data, tag: self.broker.push(
                int(host), owner, key, data, owner=tag
            ),
            owner_for=self.ring.owner,
            max_bytes=max_bytes,
        )
        self.membership.note_event("handoff", host, {
            "handoff_chunks": stats["chunks"],
            "handoff_bytes": stats["bytes"],
            "handoff_rejected": stats["rejected"],
        })
        # The departed host's RAM is gone once the drain is done — a
        # rejoin starts cold, exactly like the killed arm.
        coop.cache.close()
        return stats

    # -------------------------------------------------------- membership --
    def _apply(self, ev: MemberEvent) -> None:
        """Translate one membership transition into transport + ring
        effects (runs on the transitioning thread, outside the
        membership lock)."""
        if ev.action in ("leave", "fail"):
            # Off the ring (demotion state purged by remove_host) and
            # off the broker: peers asking a dead/departed host get a
            # definitive PeerMissError and fall back to origin. Stale
            # straggler evidence about the host dies with the epoch.
            self.ring.remove_host(ev.host)
            self.broker.unregister(ev.host)
            self.broker.resume(ev.host)
            for c in self._hosts.values():
                c.purge_host_samples(ev.host)
            if ev.action == "fail":
                # A killed host's RAM is GONE: drop its cache now so a
                # later rejoin starts cold — otherwise the kill arm's
                # scorecard would describe a pod where a dead host's
                # cache survived death. (The cooperative leave clears
                # AFTER its hot-set drain — see leave_host.)
                c = self._hosts.get(ev.host)
                if c is not None:
                    c.cache.close()
        elif ev.action == "join":
            c = self._hosts.get(ev.host)
            if c is not None:
                # Clean rejoin: no pre-demotion, no stale samples.
                c.reset_member_state()
                self.broker.register(
                    ev.host, c.serve,
                    delay_s=self._delays.get(ev.host, 0.0),
                    accept=c.accept_handoff,
                )
            self.ring.add_host(ev.host)
        elif ev.action == "pause":
            self.broker.pause(ev.host)
        elif ev.action == "resume":
            self.broker.resume(ev.host)

    # ------------------------------------------------------------- stats --
    def aggregate(self) -> dict:
        """Pod-wide counter roll-up (the scorecard's snapshot source):
        sums across every registered host's CoopCache."""
        agg = {
            "peer_requests": 0, "peer_hits": 0, "peer_misses": 0,
            "peer_bytes": 0, "origin_fetches": 0, "origin_bytes": 0,
            "pod_coalesced": 0, "handoff_out_chunks": 0,
            "handoff_out_bytes": 0, "handoff_in_chunks": 0,
            "handoff_in_bytes": 0, "handoff_rejects": 0,
        }
        for c in self._hosts.values():
            s = c.stats()
            for k in agg:
                agg[k] += s.get(k, 0)
        agg["epoch"] = self.membership.epoch
        return agg

    def close(self) -> None:
        for c in self._hosts.values():
            c.close()
