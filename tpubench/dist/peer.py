"""ICI peer-transfer channel for the cooperative chunk cache.

The loopback channel (:mod:`tpubench.pipeline.coop`) is request/reply —
right for threads in one process, impossible over ICI, where data moves
by COLLECTIVES that every participant must enter together. This channel
therefore speaks the ``lockstep`` variant of the peer interface: for
each cooperatively-fetched chunk, EVERY host calls
:meth:`IciPeerChannel.broadcast` with the same ``(owner, key)`` — the
owner contributes the chunk bytes, the others contribute nothing — and
the payload rides the existing ``dist.shard``/``make_reassemble``
NamedSharding path (the owner's slot of a mesh-sharded uint8 array,
all-gathered over ICI), after which every host slices the owner's slot
back out. No new transport: the same jitted all-gather the pod-ingest
workloads already ride, reused as a byte mover.

Scope (documented, enforced by the workload guard): lockstep requires
*plan-synchronized* misses — every host walks the same access plan in
the same order with identical cache configuration, the shape of the
``pipeline.pod`` train-ingest path. Asynchronous consumers (readahead
prefetch workers, independent read pools) must use the loopback/DCN
request-reply channel instead; a desynchronized collective would hang
the pod. Hermetic single-process tests drive the identical code path
on the simulated CPU mesh (all shards local, the degenerate case of
``jax.make_array_from_single_device_arrays``); the real multi-process
rendezvous is exercised by the env-gated multihost suite.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from tpubench.obs.tracing import TraceContext
from tpubench.pipeline.cache import ChunkKey

# Trace-context lane (PR 9): a follower's slot in the broadcast is
# otherwise all-zero padding, so its first 25 bytes carry the
# requester's trace context — flag byte (also encoding the per-trace
# sampled bit: 0xA5 sampled, 0xA4 unsampled) + 16-byte trace id +
# 8-byte span id. After the all-gather EVERY host holds every slot, so
# the owner recovers which remote spans caused this collective transfer
# and records them as trace LINKS (a collective has no single remote
# parent — all followers entered together).
_CTX_FLAG_SAMPLED = 0xA5
_CTX_FLAG_UNSAMPLED = 0xA4
_CTX_BYTES = 1 + 16 + 8


def _encode_ctx(buf: np.ndarray, ctx: TraceContext) -> None:
    if buf.shape[0] < _CTX_BYTES:
        return  # sub-25-byte slot (degenerate tiny chunk): skip the lane
    flag = _CTX_FLAG_SAMPLED if ctx.sampled else _CTX_FLAG_UNSAMPLED
    raw = bytes([flag]) + bytes.fromhex(
        ctx.trace_id.zfill(32)[:32]
    ) + bytes.fromhex(ctx.span_id.zfill(16)[:16])
    buf[:_CTX_BYTES] = np.frombuffer(raw, dtype=np.uint8)


def _decode_ctx(slot: np.ndarray) -> Optional[TraceContext]:
    if slot.shape[0] < _CTX_BYTES or int(slot[0]) not in (
        _CTX_FLAG_SAMPLED, _CTX_FLAG_UNSAMPLED,
    ):
        return None
    raw = slot[1:_CTX_BYTES].tobytes()
    return TraceContext(
        raw[:16].hex(), raw[16:24].hex(),
        int(slot[0]) == _CTX_FLAG_SAMPLED,
    )


class IciPeerChannel:
    """Lockstep peer channel over the pod mesh (module docstring).

    One jitted reassemble, built lazily (jit specializes per padded
    input shape internally — a steady chunk size compiles exactly
    once). ``host_id`` defaults to
    ``jax.process_index()``; on a single-process (simulated) mesh,
    "host" h maps to mesh slot h directly, so hermetic tests exercise
    the same slotting the multi-process path uses.
    """

    lockstep = True

    def __init__(self, mesh=None, axis: str = "pod",
                 host_id: Optional[int] = None, lane: int = 128):
        import jax

        from tpubench.dist.reassemble import make_mesh

        self._mesh = mesh if mesh is not None else make_mesh(axis=axis)
        self._axis = axis
        self._lane = lane
        self.host_id = (
            int(host_id) if host_id is not None else jax.process_index()
        )
        self._multiprocess = jax.process_count() > 1
        self._reassemble = None  # built once; jit respecializes per shape
        self.broadcasts = 0
        self.broadcast_bytes = 0
        self._last_links: list[TraceContext] = []

    # ------------------------------------------------------------ helpers --
    def _slot_for_host(self, host: int) -> int:
        """The mesh slot carrying ``host``'s payload: its first local
        chip in mesh order (multi-process), or slot ``host`` itself on
        a single-process simulated mesh."""
        devices = list(self._mesh.devices.reshape(-1))
        if self._multiprocess:
            for i, d in enumerate(devices):
                if d.process_index == host:
                    return i
            raise ValueError(f"host {host} owns no device in the mesh")
        return host % len(devices)

    def _reassemble_fn(self):
        if self._reassemble is None:
            from tpubench.dist.reassemble import make_reassemble

            self._reassemble = make_reassemble(self._mesh, self._axis)
        return self._reassemble

    # ------------------------------------------------------------- surface --
    def broadcast(self, owner: int, data: Optional[bytes],
                  key: ChunkKey, ctx: Optional[TraceContext] = None
                  ) -> bytes:
        """Collective chunk transfer: every host enters with the same
        ``(owner, key)``; only the owner passes ``data``. Returns the
        owner's bytes on every host (including the owner — callers there
        usually already hold the payload and ignore the echo). A
        follower's ``ctx`` (its peer-hop trace context) rides its own
        otherwise-zero slot; :meth:`last_request_links` returns the
        contexts recovered from the most recent gather — the owner
        records them as trace links."""
        import jax

        from tpubench.dist.reassemble import (
            local_mesh_devices,
            shard_to_device_array,
        )

        lane = self._lane
        nbytes = key.length
        rows = max(1, math.ceil(nbytes / lane))
        slot = self._slot_for_host(owner)
        self_slot = self._slot_for_host(self.host_id)
        devices = list(self._mesh.devices.reshape(-1))
        n = len(devices)
        local = (
            local_mesh_devices(self._mesh) if self._multiprocess else devices
        )
        shards = []
        for d in local:
            buf = np.zeros(rows * lane, dtype=np.uint8)
            idx = devices.index(d)
            if idx == slot:
                if data is None:
                    raise ValueError(
                        f"host {self.host_id} owns broadcast slot {slot} "
                        "but contributed no data"
                    )
                buf[:nbytes] = np.frombuffer(data, dtype=np.uint8)
            elif idx == self_slot and ctx is not None:
                _encode_ctx(buf, ctx)
            shards.append(buf)
        arr = shard_to_device_array(shards, self._mesh, self._axis, lane)
        gathered, _ = self._reassemble_fn()(arr)
        out = np.asarray(jax.device_get(gathered))
        self.broadcasts += 1
        self.broadcast_bytes += nbytes
        assert out.shape[0] == n
        links = []
        for i in range(n):
            if i == slot:
                continue
            c = _decode_ctx(out[i].reshape(-1))
            if c is not None:
                links.append(c)
        self._last_links = links
        return out[slot].reshape(-1)[:nbytes].tobytes()

    def last_request_links(self) -> list[TraceContext]:
        """Follower trace contexts recovered from the most recent
        broadcast's gather (empty when no follower was traced)."""
        return list(self._last_links)

    def request(self, owner: int, key: ChunkKey) -> bytes:
        """Request/reply is not expressible over bare collectives —
        the coop layer detects ``lockstep`` and uses broadcast."""
        raise NotImplementedError(
            "IciPeerChannel is lockstep-only: use broadcast() "
            "(the CoopCache routes through it automatically)"
        )

    def close(self) -> None:
        self._reassemble = None

    def stats(self) -> dict:
        return {
            "broadcasts": self.broadcasts,
            "broadcast_bytes": self.broadcast_bytes,
            "mesh_devices": int(self._mesh.devices.size),
            "multiprocess": self._multiprocess,
        }
