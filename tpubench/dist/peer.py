"""ICI peer-transfer channel for the cooperative chunk cache.

The loopback channel (:mod:`tpubench.pipeline.coop`) is request/reply —
right for threads in one process, impossible over ICI, where data moves
by COLLECTIVES that every participant must enter together. This channel
therefore speaks the ``lockstep`` variant of the peer interface: for
each cooperatively-fetched chunk, EVERY host calls
:meth:`IciPeerChannel.broadcast` with the same ``(owner, key)`` — the
owner contributes the chunk bytes, the others contribute nothing — and
the payload rides the existing ``dist.shard``/``make_reassemble``
NamedSharding path (the owner's slot of a mesh-sharded uint8 array,
all-gathered over ICI), after which every host slices the owner's slot
back out. No new transport: the same jitted all-gather the pod-ingest
workloads already ride, reused as a byte mover.

Scope (documented, enforced by the workload guard): lockstep requires
*plan-synchronized* misses — every host walks the same access plan in
the same order with identical cache configuration, the shape of the
``pipeline.pod`` train-ingest path. Asynchronous consumers (readahead
prefetch workers, independent read pools) must use the loopback/DCN
request-reply channel instead; a desynchronized collective would hang
the pod. Hermetic single-process tests drive the identical code path
on the simulated CPU mesh (all shards local, the degenerate case of
``jax.make_array_from_single_device_arrays``); the real multi-process
rendezvous is exercised by the env-gated multihost suite.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from tpubench.pipeline.cache import ChunkKey


class IciPeerChannel:
    """Lockstep peer channel over the pod mesh (module docstring).

    One jitted reassemble, built lazily (jit specializes per padded
    input shape internally — a steady chunk size compiles exactly
    once). ``host_id`` defaults to
    ``jax.process_index()``; on a single-process (simulated) mesh,
    "host" h maps to mesh slot h directly, so hermetic tests exercise
    the same slotting the multi-process path uses.
    """

    lockstep = True

    def __init__(self, mesh=None, axis: str = "pod",
                 host_id: Optional[int] = None, lane: int = 128):
        import jax

        from tpubench.dist.reassemble import make_mesh

        self._mesh = mesh if mesh is not None else make_mesh(axis=axis)
        self._axis = axis
        self._lane = lane
        self.host_id = (
            int(host_id) if host_id is not None else jax.process_index()
        )
        self._multiprocess = jax.process_count() > 1
        self._reassemble = None  # built once; jit respecializes per shape
        self.broadcasts = 0
        self.broadcast_bytes = 0

    # ------------------------------------------------------------ helpers --
    def _slot_for_host(self, host: int) -> int:
        """The mesh slot carrying ``host``'s payload: its first local
        chip in mesh order (multi-process), or slot ``host`` itself on
        a single-process simulated mesh."""
        devices = list(self._mesh.devices.reshape(-1))
        if self._multiprocess:
            for i, d in enumerate(devices):
                if d.process_index == host:
                    return i
            raise ValueError(f"host {host} owns no device in the mesh")
        return host % len(devices)

    def _reassemble_fn(self):
        if self._reassemble is None:
            from tpubench.dist.reassemble import make_reassemble

            self._reassemble = make_reassemble(self._mesh, self._axis)
        return self._reassemble

    # ------------------------------------------------------------- surface --
    def broadcast(self, owner: int, data: Optional[bytes],
                  key: ChunkKey) -> bytes:
        """Collective chunk transfer: every host enters with the same
        ``(owner, key)``; only the owner passes ``data``. Returns the
        owner's bytes on every host (including the owner — callers there
        usually already hold the payload and ignore the echo)."""
        import jax

        from tpubench.dist.reassemble import (
            local_mesh_devices,
            shard_to_device_array,
        )

        lane = self._lane
        nbytes = key.length
        rows = max(1, math.ceil(nbytes / lane))
        slot = self._slot_for_host(owner)
        devices = list(self._mesh.devices.reshape(-1))
        n = len(devices)
        local = (
            local_mesh_devices(self._mesh) if self._multiprocess else devices
        )
        shards = []
        for d in local:
            buf = np.zeros(rows * lane, dtype=np.uint8)
            idx = devices.index(d)
            if idx == slot:
                if data is None:
                    raise ValueError(
                        f"host {self.host_id} owns broadcast slot {slot} "
                        "but contributed no data"
                    )
                buf[:nbytes] = np.frombuffer(data, dtype=np.uint8)
            shards.append(buf)
        arr = shard_to_device_array(shards, self._mesh, self._axis, lane)
        gathered, _ = self._reassemble_fn()(arr)
        out = np.asarray(jax.device_get(gathered))
        self.broadcasts += 1
        self.broadcast_bytes += nbytes
        assert out.shape[0] == n
        return out[slot].reshape(-1)[:nbytes].tobytes()

    def request(self, owner: int, key: ChunkKey) -> bytes:
        """Request/reply is not expressible over bare collectives —
        the coop layer detects ``lockstep`` and uses broadcast."""
        raise NotImplementedError(
            "IciPeerChannel is lockstep-only: use broadcast() "
            "(the CoopCache routes through it automatically)"
        )

    def close(self) -> None:
        self._reassemble = None

    def stats(self) -> dict:
        return {
            "broadcasts": self.broadcasts,
            "broadcast_bytes": self.broadcast_bytes,
            "mesh_devices": int(self._mesh.devices.size),
            "multiprocess": self._multiprocess,
        }
