"""Pod-level reassembly of byte-range shards over ICI.

The transport-level cousin of ring attention (SURVEY §5.7): each chip holds
one lane-aligned byte-range shard of a logical object in HBM; an all-gather
under ``shard_map`` over a 1-D mesh reassembles the full object on every
chip, riding ICI with XLA-scheduled collectives — the TPU-native replacement
for the NCCL/MPI backend the reference never had (§5.8; its closest ancestor
is gRPC DirectPath, ``main.go:106-117``).

Two implementations, both jitted:

* :func:`make_reassemble` — ``jax.lax.all_gather``: XLA picks the collective
  schedule (in practice a ring over ICI). The production path.
* :func:`make_ring_reassemble` — explicit ``ppermute`` ring: n-1 neighbor
  hops, each step overlapping a send with a buffer write. The
  ring-attention-style transport demonstrated at the byte level, and a
  cross-check that the XLA collective is beaten/matched by hand-rolling.

Both also emit a per-chip mod-2³² checksum (``psum``-reduced) so integrity
of the gathered bytes is validated on-device without a host round-trip.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

try:  # newer jax exports shard_map at top level …
    from jax import shard_map as _shard_map
except ImportError:  # … older releases (this image: 0.4.37) ship it under
    # experimental, same semantics but the replication-check kwarg is
    # named check_rep there instead of check_vma.
    from jax.experimental.shard_map import (  # type: ignore[no-redef]
        shard_map as _shard_map,
    )
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import inspect as _inspect

_CHECK_KW = next(
    (k for k in ("check_vma", "check_rep")
     if k in _inspect.signature(_shard_map).parameters),
    None,
)


def shard_map(f, *, check_vma: bool = True, **kw):
    """jax.shard_map with the replication-check kwarg spelled per the
    installed jax (check_vma on current releases, check_rep on the
    experimental module this image ships)."""
    if _CHECK_KW is not None:
        kw[_CHECK_KW] = check_vma
    return _shard_map(f, **kw)


def make_mesh(devices: Optional[Sequence] = None, axis: str = "pod") -> Mesh:
    """1-D mesh over all (or given) devices — the fan-out axis."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def local_mesh_devices(mesh: Mesh) -> list:
    """This process's devices, in mesh order — the chips whose shards this
    host must fetch and stage."""
    pid = jax.process_index()
    return [d for d in mesh.devices.reshape(-1) if d.process_index == pid]


def shard_to_device_array(
    host_shards: Sequence[np.ndarray], mesh: Mesh, axis: str = "pod", lane: int = 128
):
    """Stage per-chip shard buffers into one global array sharded over the
    mesh: shape (n, rows, lane) uint8, dimension 0 split across chips.

    Multi-host (SPMD, one process per host): each process passes shards for
    its LOCAL chips only — ``jax.make_array_from_single_device_arrays``
    assembles the global view from per-process locals with zero cross-host
    data movement, so the fetch stays on the host that owns the chip.
    Single-process callers may instead pass all ``n`` shards.
    """
    all_devices = list(mesh.devices.reshape(-1))
    n = len(all_devices)
    local = local_mesh_devices(mesh)
    if len(host_shards) == len(local):
        devices = local
    elif len(host_shards) == n and jax.process_count() == 1:
        devices = all_devices
    else:
        raise ValueError(
            f"pass {len(local)} local shards (or {n} on single process); "
            f"got {len(host_shards)}"
        )
    rows = host_shards[0].size // lane
    sharding = NamedSharding(mesh, P(axis, None, None))
    singles = [
        jax.device_put(s.reshape(1, rows, lane), d)
        for s, d in zip(host_shards, devices)
    ]
    return jax.make_array_from_single_device_arrays(
        (n, rows, lane), sharding, singles
    )


def make_reassemble(mesh: Mesh, axis: str = "pod"):
    """jitted: sharded (n, rows, lane) → (replicated gathered array,
    replicated checksum). XLA inserts the ICI all-gather."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None, None),
        out_specs=(P(), P()),
        # all_gather output IS replicated but the static VMA checker can't
        # prove it; the equality tests below prove it dynamically.
        check_vma=False,
    )
    def fn(local):  # local: (1, rows, lane) on each chip
        gathered = jax.lax.all_gather(local[0], axis)  # (n, rows, lane)
        csum = jax.lax.psum(jnp.sum(local.astype(jnp.uint32)), axis)
        return gathered, csum

    return fn


def make_ring_reassemble(mesh: Mesh, axis: str = "pod"):
    """jitted explicit ring all-gather via ``ppermute`` (n-1 neighbor hops).

    Static Python loop (n is a compile-time mesh constant) so XLA can
    pipeline the hops; no data-dependent control flow under jit.
    """
    n = mesh.devices.size
    perm = [(j, (j + 1) % n) for j in range(n)]

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None, None),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def fn(local):
        block = local[0]  # (rows, lane)
        idx = jax.lax.axis_index(axis)
        out = jnp.zeros((n,) + block.shape, block.dtype)
        out = jax.lax.dynamic_update_index_in_dim(out, block, idx, 0)
        buf = block
        for step in range(n - 1):
            buf = jax.lax.ppermute(buf, axis, perm)
            src = (idx - step - 1) % n
            out = jax.lax.dynamic_update_index_in_dim(out, buf, src, 0)
        csum = jax.lax.psum(jnp.sum(block.astype(jnp.uint32)), axis)
        return out, csum

    return fn


def make_reduce_scatter(mesh: Mesh, axis: str = "pod"):
    """jitted reduce-scatter (``psum_scatter``): every chip contributes its
    (rows, lane) block; the summed array is left sharded 1/n per chip —
    the other half of the collective surface (§5.8 names psum/all_gather/
    ppermute/reduce_scatter). uint8 wrap-add keeps the wire payload at one
    byte per element so bandwidth accounting stays honest; ``rows`` must
    divide by the mesh size (the bench rounds shards accordingly)."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None, None),
        out_specs=P(axis, None, None),
        check_vma=False,
    )
    def fn(local):  # (1, rows, lane) per chip
        out = jax.lax.psum_scatter(
            local[0], axis, scatter_dimension=0, tiled=True
        )  # (rows/n, lane)
        return out[None]

    return fn


def make_allreduce(mesh: Mesh, axis: str = "pod"):
    """jitted all-reduce (``psum``) of each chip's (rows, lane) block —
    replicated sum everywhere (uint8 wrap-add, see make_reduce_scatter)."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None, None),
        out_specs=P(axis, None, None),
        check_vma=False,
    )
    def fn(local):
        return jax.lax.psum(local[0], axis)[None]

    return fn


def gathered_to_bytes(gathered: jax.Array, object_size: int) -> bytes:
    """Trim the padded gather back to the true object bytes (host-side)."""
    flat = np.asarray(jax.device_get(gathered)).reshape(-1)
    return flat[:object_size].tobytes()
