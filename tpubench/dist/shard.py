"""Shard assignment math (unit-testable, pure Python — SURVEY §4).

Two tables:

* **worker→object** (the reference's DP axis): worker ``i`` on host ``h``
  owns object ``prefix + (h * workers_per_host + i)`` — the multi-host
  generalization of ``ObjectNamePrefix + workerId`` (``main.go:121``).
* **object→byte-range** (the CP-analog, SURVEY §5.7): one logical object
  split into ``n_shards`` equal lane-aligned ranges, one per chip, so the
  reassembled pod array has a static, XLA-friendly shape. Only the last
  shard can be short; padding is explicit and trimmed after gather.
"""

from __future__ import annotations

from dataclasses import dataclass


def worker_object_index(host: int, worker: int, workers_per_host: int) -> int:
    return host * workers_per_host + worker


@dataclass(frozen=True)
class Shard:
    index: int
    start: int  # byte offset into the object
    length: int  # true bytes to fetch (0 for all-padding shards)
    padded_length: int  # equal for all shards; >= length


@dataclass(frozen=True)
class ShardTable:
    """Equal-size lane-aligned decomposition of one object."""

    object_size: int
    n_shards: int
    align: int  # lane width; every shard length is a multiple of this
    shard_bytes: int  # padded per-shard size

    @classmethod
    def build(cls, object_size: int, n_shards: int, align: int = 128) -> "ShardTable":
        if object_size <= 0 or n_shards <= 0:
            raise ValueError("object_size and n_shards must be positive")
        per = -(-object_size // n_shards)  # ceil
        per = -(-per // align) * align  # round up to lane multiple
        return cls(object_size, n_shards, align, per)

    @property
    def padded_size(self) -> int:
        return self.shard_bytes * self.n_shards

    def shard(self, i: int) -> Shard:
        if not 0 <= i < self.n_shards:
            raise IndexError(i)
        start = i * self.shard_bytes
        length = max(0, min(self.object_size - start, self.shard_bytes))
        return Shard(i, start, length, self.shard_bytes)

    def shards(self) -> list[Shard]:
        return [self.shard(i) for i in range(self.n_shards)]

    def chip_shards(self, host: int, chips_per_host: int) -> list[Shard]:
        """The shards host ``host`` must fetch for its local chips."""
        lo = host * chips_per_host
        return [self.shard(i) for i in range(lo, min(lo + chips_per_host, self.n_shards))]
