"""Multi-chip scaling sweep: pod-ingest + collective bandwidth vs mesh size.

The pod is the unit under test (SURVEY §5.8), but real multi-chip hardware
isn't available in this environment — so each mesh size runs in its OWN
subprocess on a simulated CPU mesh (``JAX_PLATFORMS=cpu`` +
``--xla_force_host_platform_device_count=<n>``; the device count is fixed
at backend init, hence one process per size). Shards are REALISTIC
(default 8 MB/chip — the round-4 verdict's complaint was a 2 KB dryrun
object standing in for the pod story), and every stage is timed
separately: fetch (host, concurrent per shard), stage (host→"HBM"
device_put), gather (ICI all-gather / explicit ppermute ring, compile
excluded via warmup).

The collective sweep rides the largest child (gather_bench already sweeps
every power-of-two mesh up to the device count) and its byte accounting is
re-checked against the ring-schedule algebra (gather_bench module
docstring) before the artifact is written — `ring_algebra_ok` in the
output is a recomputation, not an echo.

Artifact: ``MULTICHIP_SWEEP.json`` (committed; regenerate with
``python -m tpubench.cli multichip-sweep`` or ``python -m
tpubench.dist.sweep``). Timings are CPU-mesh numbers — useful for
scaling SHAPE (how stage/gather fractions move with n) and correctness
at realistic sizes, not absolute ICI bandwidth.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Optional

_COLLECTIVES = ("all_gather", "ring", "reduce_scatter", "psum")


def _child_env(n: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    # Replace any prior forced count rather than appending a duplicate.
    flags = " ".join(
        f for f in flags.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n}".strip()
    )
    return env


def _run_child(n: int, shard_mb: float, reps: int, collectives: bool,
               timeout_s: float = 600.0) -> dict:
    cmd = [
        sys.executable, "-m", "tpubench.dist.sweep",
        "--child", str(n), "--shard-mb", str(shard_mb), "--reps", str(reps),
    ]
    if collectives:
        cmd.append("--collectives")
    cp = subprocess.run(
        cmd, env=_child_env(n), capture_output=True, text=True,
        timeout=timeout_s,
    )
    if cp.returncode != 0:
        raise RuntimeError(
            f"sweep child n={n} failed: {cp.stderr[-2000:]}"
        )
    return json.loads(cp.stdout.splitlines()[-1])


def child_main(n: int, shard_mb: float, reps: int, collectives: bool) -> dict:
    """Runs INSIDE the n-device subprocess."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tpubench.config import MB, BenchConfig
    from tpubench.workloads.pod_ingest import run_pod_ingest

    assert len(jax.devices()) == n, (
        f"child expected {n} devices, got {len(jax.devices())}"
    )
    cfg = BenchConfig()
    cfg.transport.protocol = "fake"
    cfg.workload.object_size = int(n * shard_mb * MB)

    # Warmup: the process's FIRST pod-ingest pays jax/thread-pool/import
    # init inside its fetch timing; a tiny untimed run absorbs that so
    # the recorded stages measure the pipeline, not process bringup.
    warm = BenchConfig()
    warm.transport.protocol = "fake"
    warm.workload.object_size = n * 256 * 1024
    run_pod_ingest(warm, verify=False)

    out: dict = {"devices": n, "shard_mb": shard_mb}
    for ring in (False, True):
        res = run_pod_ingest(cfg, ring=ring, verify=True)
        e = res.extra
        out["pod_ingest_ring" if ring else "pod_ingest_all_gather"] = {
            "verified": e["verified"],
            "errors": res.errors,
            "object_size": e["object_size"],
            "shard_bytes": e["shard_bytes"],
            "fetch_seconds": round(e["fetch_seconds"], 6),
            "stage_seconds": round(e["stage_seconds"], 6),
            "gather_seconds": round(e["gather_seconds"], 6),
            "compile_seconds": round(e["compile_seconds"], 6),
            "fetch_gbps": round(e["fetch_gbps"], 4),
            "stage_gbps": round(e["stage_gbps"], 4),
            "gather_gbps": round(e["gather_gbps"], 4),
            "ingest_gbps": round(res.gbps, 4),
            "ici_bytes_moved": e["ici_bytes_moved"],
        }
    if collectives:
        from tpubench.workloads.gather_bench import run_gather_bench

        coll: dict = {}
        for mode in _COLLECTIVES:
            res = run_gather_bench(
                cfg, shard_mb=shard_mb, reps=reps, collective=mode
            )
            coll[mode] = [
                {
                    "devices": r["devices"],
                    "shard_bytes": r["shard_bytes"],
                    "seconds": round(r["seconds"], 6),
                    "ici_bytes_moved": r["ici_bytes_moved"],
                    "per_chip_rx_gbps": round(r["per_chip_rx_gbps"], 4),
                    "total_gbps": round(r["total_gbps"], 4),
                }
                for r in res.extra["scaling"]
            ]
        out["collectives"] = coll
    return out


def check_ring_algebra(collectives: dict) -> list[str]:
    """Recompute every collective row's bytes-on-wire from the ring
    schedule (gather_bench docstring) and return the violations — the
    artifact's `ring_algebra_ok` is this check passing, not an echo of
    what gather_bench already wrote."""
    bad: list[str] = []
    for mode, rows in collectives.items():
        for r in rows:
            n, s = r["devices"], r["shard_bytes"]
            if mode in ("all_gather", "ring"):
                want = s * n * (n - 1)
            elif mode == "reduce_scatter":
                want = s * (n - 1)
            elif mode == "psum":
                want = 2 * s * (n - 1)
            else:
                bad.append(f"{mode}: unknown collective")
                continue
            if r["ici_bytes_moved"] != want:
                bad.append(
                    f"{mode} n={n}: ici_bytes_moved={r['ici_bytes_moved']} "
                    f"!= ring algebra {want}"
                )
    return bad


def run_sweep(
    sizes: tuple[int, ...] = (2, 4, 8, 16),
    shard_mb: float = 8.0,
    reps: int = 3,
    out_path: Optional[str] = None,
) -> dict:
    per_size = []
    for n in sizes:
        # The collective sweep rides the LARGEST child only: gather_bench
        # itself sweeps every power-of-two mesh up to the device count.
        per_size.append(
            _run_child(n, shard_mb, reps, collectives=(n == max(sizes)))
        )
    collectives = {}
    for c in per_size:
        if "collectives" in c:
            collectives = c.pop("collectives")  # hoist: one copy, top level
    violations = check_ring_algebra(collectives)
    result = {
        "platform": "cpu-simulated mesh (one subprocess per size; "
                    "JAX_PLATFORMS=cpu + xla_force_host_platform_device_count)",
        "sizes": list(sizes),
        "shard_mb": shard_mb,
        "pod_ingest": per_size,
        "collectives": collectives,
        "ring_algebra_ok": not violations,
        "ring_algebra_violations": violations,
        "note": (
            "CPU-mesh numbers: read for scaling SHAPE (stage/gather "
            "fractions vs n) and correctness at realistic shard sizes "
            "(>=8 MB/chip), not absolute ICI bandwidth."
        ),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--collectives", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--sizes", default="2,4,8,16")
    ap.add_argument("--shard-mb", type=float, default=8.0)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default="MULTICHIP_SWEEP.json")
    args = ap.parse_args(argv)
    if args.child:
        print(json.dumps(
            child_main(args.child, args.shard_mb, args.reps, args.collectives)
        ))
        return 0
    sizes = tuple(int(x) for x in args.sizes.split(","))
    result = run_sweep(sizes, args.shard_mb, args.reps, out_path=args.out)
    print(json.dumps(result))
    print(f"artifact: {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
