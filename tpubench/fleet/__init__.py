"""Virtual-time fleet engine (``tpubench fleet``).

The hermetic harnesses elsewhere in the tree pay wall-clock per host
thread — a 4-host elastic pod is the practical ceiling. This package
replaces the threads with a discrete-event scheduler running on the
injectable-clock seam the serve, qos, arrivals and membership planes
already expose (the PR-12 determinism gate enforces that seam), so the
SAME admission queue, membership state machine, consistent-hash ring
and scorecard math run at 64–4096 simulated hosts in seconds of wall
time.

* :mod:`tpubench.fleet.vtime` — the event-heap scheduler and the
  ``Clock`` surface that drop-in replaces ``time.monotonic`` /
  ``perf_counter_ns`` style injectables.
* :mod:`tpubench.fleet.calibrate` — per-phase service-time
  distributions fitted from flight journals (``--calibrate-from``),
  round-tripped through ``--fleet-profile`` JSON.
* :mod:`tpubench.fleet.driver` — the fleet workload: multi-pod
  topologies, correlated-failure / rolling-upgrade timelines, scored
  by the real ``serve_scorecard`` / ``membership_scorecard``.
"""

from tpubench.fleet.vtime import EventLoop, VirtualClock  # noqa: F401
