"""Fleet service-time calibration: flight journals → sampling profiles.

The fidelity of a virtual-time fleet run rests entirely on its service
times. This module fits per-phase empirical distributions from the same
flight journals every real run already writes (``--flight-journal``),
so a fleet simulation's origin fetch takes as long as the measured
``cache_miss → body_complete`` segment did, and a peer hop as long as
the measured ``peer_request → peer_hit`` round trip.

Representation is an inverse-CDF **quantile grid** (33 points, linear
interpolation between them): enough to carry a long tail faithfully,
small enough that a profile JSON stays human-readable, and sampling is
one uniform draw + one ``np.interp`` — no distributional family is
assumed, because measured storage latency fits none.

Discipline notes:

* Journal discovery reuses ``obs.live.discover_journal_paths`` — the
  ``.p<idx>`` per-host siblings and ``.gz`` variants come along exactly
  as they do for ``tpubench top``/``report timeline``.
* Empty/torn journals degrade with the one-line warning contract of
  ``load_journals`` (a dead host must not poison calibration).
* A phase with too few samples falls back to its configured constant
  with a one-line warning — silently fitting a distribution to three
  points would be worse than admitting the default.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional, Sequence

import numpy as np

PROFILE_FORMAT = "tpubench-fleet-profile/1"

# The simulated service phases and the journal segments they fit from.
# "hit" is deliberately NOT journal-fitted: the only observable segment
# (enqueue → cache_hit) includes admission-queue wait, which the
# simulator models separately — fitting it would double-count queueing.
SERVICE_PHASES = ("hit", "peer", "origin", "cross_pod")

# Below this many samples a fitted grid is noise, not a distribution.
MIN_SAMPLES = 8

_GRID_POINTS = 33
_QGRID = np.linspace(0.0, 1.0, _GRID_POINTS)


class ServiceDist:
    """One phase's service-time distribution as an inverse-CDF grid
    (milliseconds at ``_QGRID`` quantiles). ``constant(ms)`` collapses
    the grid to a single value — the uncalibrated default."""

    __slots__ = ("grid_ms", "count", "source")

    def __init__(self, grid_ms: Sequence[float], count: int = 0,
                 source: str = "fitted"):
        self.grid_ms = [float(v) for v in grid_ms]
        if len(self.grid_ms) != _GRID_POINTS:
            raise ValueError(
                f"service grid: {len(self.grid_ms)} points "
                f"(expected {_GRID_POINTS})"
            )
        self.count = int(count)
        self.source = source

    @classmethod
    def constant(cls, ms: float) -> "ServiceDist":
        return cls([float(ms)] * _GRID_POINTS, count=0, source="constant")

    @classmethod
    def fit(cls, samples_ms: Sequence[float]) -> "ServiceDist":
        arr = np.asarray(sorted(samples_ms), dtype=np.float64)
        grid = np.quantile(arr, _QGRID)
        return cls(np.round(grid, 6), count=arr.size, source="fitted")

    def sample_s(self, rng: np.random.Generator) -> float:
        """One inverse-transform draw, in SECONDS (the sim's domain)."""
        u = rng.random()
        return float(np.interp(u, _QGRID, self.grid_ms)) / 1e3

    def mean_ms(self) -> float:
        # Trapezoid over the inverse CDF = the distribution's mean.
        return float(np.trapezoid(self.grid_ms, _QGRID)) \
            if hasattr(np, "trapezoid") else float(np.trapz(self.grid_ms, _QGRID))

    def p_ms(self, q: float) -> float:
        return float(np.interp(q, _QGRID, self.grid_ms))

    def to_dict(self) -> dict:
        return {
            "grid_ms": self.grid_ms,
            "count": self.count,
            "source": self.source,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ServiceDist":
        return cls(d["grid_ms"], count=d.get("count", 0),
                   source=d.get("source", "fitted"))


class FleetProfile:
    """The complete service-time profile a fleet run samples from:
    one :class:`ServiceDist` per phase in :data:`SERVICE_PHASES`."""

    def __init__(self, phases: dict, source_paths: Optional[list] = None):
        missing = [p for p in SERVICE_PHASES if p not in phases]
        if missing:
            raise ValueError(f"fleet profile missing phases: {missing}")
        self.phases = {p: phases[p] for p in SERVICE_PHASES}
        self.source_paths = list(source_paths or [])

    @classmethod
    def from_constants(cls, *, hit_ms: float, peer_ms: float,
                       origin_ms: float, cross_pod_ms: float
                       ) -> "FleetProfile":
        return cls({
            "hit": ServiceDist.constant(hit_ms),
            "peer": ServiceDist.constant(peer_ms),
            "origin": ServiceDist.constant(origin_ms),
            "cross_pod": ServiceDist.constant(cross_pod_ms),
        })

    def summary(self) -> dict:
        return {
            name: {
                "source": d.source,
                "count": d.count,
                "mean_ms": round(d.mean_ms(), 4),
                "p50_ms": round(d.p_ms(0.5), 4),
                "p99_ms": round(d.p_ms(0.99), 4),
            }
            for name, d in self.phases.items()
        }

    def to_dict(self) -> dict:
        return {
            "format": PROFILE_FORMAT,
            "phases": {p: d.to_dict() for p, d in self.phases.items()},
            "source_paths": self.source_paths,
        }

    @classmethod
    def from_dict(cls, doc: dict, where: str = "fleet profile"
                  ) -> "FleetProfile":
        if doc.get("format") != PROFILE_FORMAT:
            raise SystemExit(
                f"{where}: not a fleet profile (format="
                f"{doc.get('format')!r}; expected {PROFILE_FORMAT!r})"
            )
        try:
            phases = {
                p: ServiceDist.from_dict(d)
                for p, d in doc.get("phases", {}).items()
            }
            return cls(phases, source_paths=doc.get("source_paths"))
        except (KeyError, TypeError, ValueError) as e:
            raise SystemExit(f"{where}: malformed ({e})") from e


def save_profile(profile: FleetProfile, path: str) -> str:
    """Atomic profile write (tmp + replace — the journal discipline)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(profile.to_dict(), f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_profile(path: str) -> FleetProfile:
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise SystemExit(f"fleet profile {path!r}: {e}") from e
    except json.JSONDecodeError as e:
        raise SystemExit(
            f"fleet profile {path!r}: invalid JSON ({e.msg} at char "
            f"{e.pos})"
        ) from e
    return FleetProfile.from_dict(doc, where=f"fleet profile {path!r}")


def _phase_samples_ms(records: list) -> dict:
    """Extract per-phase service samples (ms) from journal records.

    * ``origin``: ``cache_miss → body_complete`` (the full backend
      resolution of a demand miss), falling back to
      ``owner_fetch → body_complete`` for coop-owner records.
    * ``peer``: ``peer_request → peer_hit`` (the successful peer RTT).
    * ``hit`` / ``cross_pod``: never journal-fitted (see module doc /
      SERVICE_PHASES comment) — absent from the result by design.
    """
    out: dict = {"origin": [], "peer": []}
    for rec in records:
        ph = rec.get("phases") or {}
        if "body_complete" in ph:
            start = ph.get("cache_miss", ph.get("owner_fetch"))
            if start is not None and ph["body_complete"] >= start:
                out["origin"].append((ph["body_complete"] - start) / 1e6)
        if "peer_request" in ph and "peer_hit" in ph \
                and ph["peer_hit"] >= ph["peer_request"]:
            out["peer"].append((ph["peer_hit"] - ph["peer_request"]) / 1e6)
    return out


def fit_profile(bases: Sequence[str], *, defaults: dict) -> FleetProfile:
    """``--calibrate-from``: fit a :class:`FleetProfile` from journal
    base paths (``.p<idx>`` siblings and ``.gz`` discovered the same way
    ``tpubench top`` finds them). ``defaults`` maps phase → constant ms
    for phases that cannot be fitted (too few samples, or — hit /
    cross_pod — structurally unfittable from journals)."""
    from tpubench.obs.flight import load_journals
    from tpubench.obs.live import discover_journal_paths

    paths = discover_journal_paths(list(bases))
    docs = load_journals(paths)
    records = [r for doc in docs for r in doc.get("records", [])]
    samples = _phase_samples_ms(records)
    phases: dict = {}
    for name in SERVICE_PHASES:
        got = samples.get(name)
        if got is not None and len(got) >= MIN_SAMPLES:
            phases[name] = ServiceDist.fit(got)
            continue
        if got is not None:
            print(
                f"warning: fleet calibrate: phase {name!r}: "
                f"{len(got)} sample(s) across {len(docs)} journal(s) "
                f"(< {MIN_SAMPLES}), using the configured constant "
                f"{defaults[name]} ms",
                file=sys.stderr,
            )
        phases[name] = ServiceDist.constant(defaults[name])
    return FleetProfile(phases, source_paths=paths)
