"""``tpubench fleet`` — the elastic serve plane under virtual time.

This is the discrete-event twin of ``workloads/serve._ElasticServe``:
the same open-loop schedule (``build_schedule``), the same
:class:`~tpubench.serve.qos.AdmissionQueue` (injected virtual
``clock_ns``, so priority order, queue-limit sheds and deadline sheds
are byte-identical logic), the same :class:`~tpubench.dist.membership.
Membership` state machine and consistent-hash rings, and the same
``serve_scorecard`` / ``membership_scorecard`` math — but the worker
threads sleeping real seconds are replaced by one
:class:`~tpubench.fleet.vtime.EventLoop`, and each request's service
time is a draw from a calibrated :class:`~tpubench.fleet.calibrate.
FleetProfile` instead of a real backend fetch. That swap is what lifts
the host ceiling from ~4 (one OS thread per worker, wall-clock per
sleep) to 4096 (one heap event per state change).

What is simulated rather than executed, and the fidelity caveats that
follow, are documented in README "Fleet simulation":

* Payload bytes never materialize — caches account sizes (a real
  ``ChunkCache`` would coerce payloads to real ``bytes``, which at
  1024 hosts x 64 MB is RAM the simulation must not touch).
* The coop tier is modeled (ring owner probe -> peer RTT draw ->
  origin draw with pod-wide single-flight coalescing), not the real
  ``CoopCache``/``LoopbackBroker`` (both are thread-coupled).
* A paused owner charges a flat retry penalty
  (``fleet.pause_penalty_ms`` approximating the real
  PEER_MAX_ATTEMPTS x backoff ladder) instead of live transient
  errors.

Topology: hosts partition into contiguous pods, each pod with its own
coop ring; with >1 pod a routing ring over pod ids assigns every chunk
a HOME pod, and a pod-local miss hops cross-pod to the home owner
(``fleet.cross_pod_ms`` per hop) before paying origin — the cross-pod
routing tier ROADMAP item 3 names above the coop ring.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from tpubench.config import (
    BenchConfig,
    parse_sleep_scale,
    validate_fleet_config,
    validate_serve_config,
)
from tpubench.dist.membership import Membership, MembershipError, remap_stats
from tpubench.fleet.calibrate import FleetProfile
from tpubench.fleet.vtime import EventLoop
from tpubench.metrics.percentiles import summarize_ns
from tpubench.metrics.recorder import LatencyRecorder
from tpubench.metrics.report import RunResult
from tpubench.obs.flight import (
    flight_from_config,
    host_journal_path,
    transport_label,
)
from tpubench.pipeline.coop import HashRing
from tpubench.serve.qos import (
    AdmissionQueue,
    ClassLedger,
    Request,
    find_knee,
)
from tpubench.storage.base import ObjectMeta
from tpubench.workloads.arrivals import scaled_gaps
from tpubench.workloads.serve import (
    _merge_windows,
    build_schedule,
    membership_scorecard,
    serve_scorecard,
)

# Above this pod size the per-host stats list would dominate the result
# JSON (1024 dicts per run); the scorecard carries a roll-up instead.
PER_HOST_DETAIL_MAX = 16


class SimCache:
    """Byte-accounting LRU standing in for a host's ``ChunkCache``:
    keys map to sizes, never payloads. Hit/miss/eviction accounting
    mirrors the stats the membership scorecard's per-host block reads;
    single-flight lives in the driver's pod-wide in-flight map (where
    the real plane's per-host single-flight + coop owner routing net
    out to one origin fetch per key anyway)."""

    __slots__ = ("capacity", "bytes", "hits", "misses", "inserted_bytes",
                 "evictions", "rejects", "_lru")

    def __init__(self, capacity_bytes: int):
        self.capacity = max(0, int(capacity_bytes))
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.inserted_bytes = 0
        self.evictions = 0
        self.rejects = 0
        self._lru: OrderedDict = OrderedDict()

    def get(self, key) -> Optional[int]:
        n = self._lru.get(key)
        if n is None:
            self.misses += 1
            return None
        self._lru.move_to_end(key)
        self.hits += 1
        return n

    def contains(self, key) -> bool:
        return key in self._lru

    def insert(self, key, nbytes: int) -> bool:
        """Returns False when the chunk cannot fit even an empty cache
        (the real plane's oversize-skip / handoff-reject path)."""
        if key in self._lru:
            self._lru.move_to_end(key)
            return True
        n = int(nbytes)
        if n > self.capacity:
            self.rejects += 1
            return False
        while self.bytes + n > self.capacity and self._lru:
            _, old = self._lru.popitem(last=False)
            self.bytes -= old
            self.evictions += 1
        self._lru[key] = n
        self.bytes += n
        self.inserted_bytes += n
        return True

    def mru_items(self):
        """Hot-set drain order for the warm-handoff protocol (the real
        plane drains MRU-first so the most valuable bytes land first)."""
        return reversed(list(self._lru.items()))

    def clear(self) -> None:
        self._lru.clear()
        self.bytes = 0

    def stats(self) -> dict:
        return {
            "hits": self.hits, "misses": self.misses,
            "bytes": self.bytes, "capacity_bytes": self.capacity,
            "inserted_bytes": self.inserted_bytes,
            "evictions": self.evictions, "rejects": self.rejects,
            "entries": len(self._lru),
        }


class FleetFabric:
    """The simulated pod fabric: the REAL membership state machine over
    all hosts, per-pod consistent-hash rings, and (multi-pod) the home
    ring over pod ids — exposing the same query surface
    (``is_dispatchable`` / ``live_hosts`` / ``owners_of`` /
    ``aggregate``) the scorecards read off ``ElasticFabric``."""

    def __init__(self, n_hosts: int, n_pods: int, *, vnodes: int,
                 cache_bytes: int, clock, flight_ring=None):
        self.n_hosts = int(n_hosts)
        self.n_pods = max(1, min(int(n_pods), self.n_hosts))
        self.membership = Membership(
            range(self.n_hosts), clock=clock, flight_ring=flight_ring
        )
        self.pod_of = [
            h * self.n_pods // self.n_hosts for h in range(self.n_hosts)
        ]
        self.rings = [
            HashRing(
                (h for h in range(self.n_hosts) if self.pod_of[h] == p),
                vnodes=vnodes,
            )
            for p in range(self.n_pods)
        ]
        self.pod_ring = (
            HashRing(range(self.n_pods), vnodes=vnodes)
            if self.n_pods > 1 else None
        )
        self.caches = [SimCache(cache_bytes) for _ in range(self.n_hosts)]
        self.counters = {
            "peer_requests": 0, "peer_hits": 0, "peer_misses": 0,
            "peer_bytes": 0, "origin_fetches": 0, "origin_bytes": 0,
            "pod_coalesced": 0, "handoff_out_chunks": 0,
            "handoff_out_bytes": 0, "handoff_in_chunks": 0,
            "handoff_in_bytes": 0, "handoff_rejects": 0,
            "cross_pod_hits": 0, "cross_pod_bytes": 0,
        }

    # ------------------------------------------------------- queries --
    def is_dispatchable(self, host: int) -> bool:
        return self.membership.is_live(host)

    def live_hosts(self) -> set:
        return self.membership.live_hosts()

    def state(self, host: int) -> Optional[str]:
        return self.membership.state(host)

    def home_pod(self, key) -> int:
        if self.pod_ring is None:
            return 0
        p = self.pod_ring.owner(key)
        return 0 if p is None else p

    def owner_of(self, key) -> Optional[int]:
        """The authoritative owner: the home pod's ring owner (for one
        pod, simply the ring owner) — the remap-accounting probe."""
        return self.rings[self.home_pod(key)].owner(key)

    def owners_of(self, keys) -> dict:
        return {k: self.owner_of(k) for k in keys}

    def aggregate(self) -> dict:
        agg = dict(self.counters)
        agg["epoch"] = self.membership.epoch
        return agg

    # ------------------------------------------------------ controls --
    def _try(self, fn, host: int) -> bool:
        try:
            fn(host)
            return True
        except MembershipError:
            return False

    def kill_host(self, host: int) -> bool:
        """Host death: off the membership, off its pod ring, RAM gone
        (a rejoin starts cold — the real fabric's fail semantics)."""
        if not self._try(self.membership.fail, host):
            return False
        self.rings[self.pod_of[host]].remove_host(host)
        self.caches[host].clear()
        return True

    def leave_host(self, host: int) -> Optional[dict]:
        """Cooperative departure: view change first, then the warm
        handoff drains the hot set MRU-first to each chunk's NEW owner
        in the departing host's pod — re-warming from simulated host
        RAM instead of origin, with the same out/in/reject ledger."""
        if not self._try(self.membership.leave, host):
            return None
        ring = self.rings[self.pod_of[host]]
        ring.remove_host(host)
        c = self.counters
        stats = {"chunks": 0, "bytes": 0, "rejected": 0, "skipped": 0}
        for key, n in self.caches[host].mru_items():
            dest = ring.owner(key)
            if dest is None or not self.membership.is_live(dest):
                stats["skipped"] += 1
                continue
            stats["chunks"] += 1
            stats["bytes"] += n
            c["handoff_out_chunks"] += 1
            c["handoff_out_bytes"] += n
            if self.caches[dest].insert(key, n):
                c["handoff_in_chunks"] += 1
                c["handoff_in_bytes"] += n
            else:
                stats["rejected"] += 1
                c["handoff_rejects"] += 1
        self.membership.note_event("handoff", host, {
            "handoff_chunks": stats["chunks"],
            "handoff_bytes": stats["bytes"],
            "handoff_rejected": stats["rejected"],
        })
        self.caches[host].clear()
        return stats

    def pause_host(self, host: int) -> bool:
        # The ring keeps a paused host (the real fabric's choice):
        # requests routed to it pay the retry penalty, they don't remap.
        return self._try(self.membership.pause, host)

    def resume_host(self, host: int) -> bool:
        return self._try(self.membership.resume, host)

    def rejoin_host(self, host: int) -> bool:
        if not self._try(self.membership.join, host):
            return False
        self.rings[self.pod_of[host]].add_host(host)
        return True

    def per_host_stats(self) -> list:
        return [
            {"host": h, "cache": self.caches[h].stats(),
             "state": self.membership.state(h)}
            for h in range(self.n_hosts)
        ]


def resolve_profile(cfg: BenchConfig) -> FleetProfile:
    """The run's service-time profile: a fitted/loaded profile dict
    (``fleet.profile``, set by ``--fleet-profile`` / calibration) wins;
    otherwise the configured per-phase constants."""
    fc = cfg.fleet
    if fc.profile:
        return FleetProfile.from_dict(dict(fc.profile),
                                      where="fleet.profile")
    return FleetProfile.from_constants(
        hit_ms=fc.hit_service_ms, peer_ms=fc.peer_service_ms,
        origin_ms=fc.origin_service_ms, cross_pod_ms=fc.cross_pod_ms,
    )


def build_fleet_timeline(fc, n_hosts: int) -> list:
    """Generated membership timelines, in the serve plane's entry
    format (``[t0, t1, {action: host}]``) so windows/validation reuse
    the existing machinery.

    * ``correlated_failure``: ``fail_fraction`` of the fleet dies at
      ``fail_at_s`` (seeded draw — WHICH hosts die changes remap
      geometry, so it must replay for a seed); ``recover_s`` > 0
      rejoins every victim that much later, cold.
    * ``rolling_upgrade``: every host pauses for ``upgrade_pause_s``,
      starts staggered ``upgrade_stagger_s`` apart (0 = sequential,
      the next host starts as the previous resumes).
    """
    if fc.timeline == "none":
        return []
    if fc.timeline == "correlated_failure":
        rng = np.random.Generator(np.random.Philox(fc.seed))
        k = min(max(1, int(round(fc.fail_fraction * n_hosts))),
                n_hosts - 1)
        victims = sorted(
            int(v) for v in rng.choice(n_hosts, size=k, replace=False)
        )
        out = [[fc.fail_at_s, fc.fail_at_s, {"kill_host": v}]
               for v in victims]
        if fc.recover_s > 0:
            t = fc.fail_at_s + fc.recover_s
            out += [[t, t, {"rejoin_host": v}] for v in victims]
        return out
    if fc.timeline == "rolling_upgrade":
        stagger = fc.upgrade_stagger_s or fc.upgrade_pause_s
        return [
            [fc.fail_at_s + h * stagger,
             fc.fail_at_s + h * stagger + fc.upgrade_pause_s,
             {"pause_host": h}]
            for h in range(n_hosts)
        ]
    raise SystemExit(f"fleet.timeline={fc.timeline!r}: unknown kind")


def run_fleet(cfg: BenchConfig, rate_rps: Optional[float] = None
              ) -> RunResult:
    """One virtual-time fleet run at the configured offered load.

    Control flow tracks ``_ElasticServe.run`` step for step (membership
    events gated on arrival time before each dispatch, round-robin
    front-end assignment over live hosts, failover at pop, the
    grace-then-drain close, shed-reasons merged into the ledgers) so
    the threaded-vs-virtual agreement gate compares like with like."""
    cfg = BenchConfig.from_dict(cfg.to_dict())  # private copy: we sync knobs
    fc, sc, w = cfg.fleet, cfg.serve, cfg.workload
    if fc.hosts > 0:
        sc.hosts = fc.hosts
    sc.readahead = 0  # the sim has no prefetcher (README caveat)
    validate_serve_config(sc)
    validate_fleet_config(fc, sc)
    profile = resolve_profile(cfg)
    chunk = sc.chunk_bytes or w.granule_bytes

    # Synthetic object population: the fleet never opens a backend —
    # the schedule builder only needs names/sizes/generations.
    objects = [
        ObjectMeta(name=f"{w.object_name_prefix}fleet-{i:05d}",
                   size=w.object_size, generation=1)
        for i in range(fc.objects)
    ]
    schedule = build_schedule(cfg, None, rate_rps, objects=objects)
    scale = parse_sleep_scale("fleet arrival gaps")
    gaps = scaled_gaps([r.arrival_s for r in schedule], scale)

    n_workers = (fc.workers_per_host * sc.hosts
                 if fc.workers_per_host > 0 else sc.workers)
    qos = sc.qos
    flight = flight_from_config(cfg)
    tlabel = transport_label(cfg)

    loop = EventLoop()
    wclock = loop.clock  # simulated wall domain (service/deadline math)
    vnow = [0.0]  # arrival domain (membership/windows/snapshots)

    outcome: list = [None] * len(schedule)

    def on_shed(req: Request, reason: str) -> None:
        outcome[req.index] = False

    queue = AdmissionQueue(
        cap=sc.admission_cap or n_workers, qos=qos,
        queue_limit=(sc.queue_limit or 8 * n_workers) if qos else 0,
        clock_ns=wclock.now_ns, on_shed=on_shed,
    )

    n_pods = fc.pods or max(1, sc.hosts // 128)
    fabric = FleetFabric(
        sc.hosts, n_pods, vnodes=cfg.coop.vnodes,
        cache_bytes=cfg.pipeline.cache_bytes, clock=lambda: vnow[0],
        flight_ring=(
            flight.worker("member") if flight is not None else None
        ),
    )

    # ---- membership plan + resize windows (the threaded recipe) -----
    entries = list(sc.membership_timeline) + \
        build_fleet_timeline(fc, sc.hosts)
    member_plan: list = []
    windows: list = []
    for t0, t1, spec in entries:
        (action, host), = spec.items()
        t0, t1 = float(t0), float(t1)
        if action == "pause_host":
            member_plan.append((t0, "pause_host", int(host)))
            member_plan.append((t1, "resume_host", int(host)))
            windows.append([t0, t1 + sc.resize_window_s])
        else:
            member_plan.append((t0, action, int(host)))
            windows.append([t0, t0 + sc.resize_window_s])
    member_plan.sort(key=lambda e: e[0])
    windows = _merge_windows(windows)

    uniq_keys = list({r.key for r in schedule})
    events_out: list = []
    snapshots: list = []

    classes = sorted(sc.classes, key=lambda c: int(c.get("priority", 0)))
    ledgers = {str(c["name"]): ClassLedger() for c in classes}
    recorders = {
        str(c["name"]): LatencyRecorder(f"request_{c['name']}")
        for c in classes
    }
    agg_rec = LatencyRecorder("request")
    tenant_bytes: dict[str, int] = {}
    completed_bytes = [0]
    failovers = [0]
    no_live_host_errors = [0]
    drained = [0]

    for req in schedule:
        ledgers[req.tenant.cls].arrivals += 1

    def take_snapshot(t: float) -> None:
        agg = fabric.aggregate()
        agg["completed"] = sum(led.completed for led in ledgers.values())
        snapshots.append((t, agg))

    live_cache: list = [None]  # sorted live hosts, invalidated on events

    def live_sorted() -> list:
        if live_cache[0] is None:
            live_cache[0] = sorted(fabric.live_hosts())
        return live_cache[0]

    def apply_event(t: float, action: str, host: int) -> None:
        vnow[0] = max(vnow[0], t)
        live_cache[0] = None
        before = fabric.owners_of(uniq_keys)
        handoff = None
        if action == "kill_host":
            ok = fabric.kill_host(host)
        elif action == "leave_host":
            handoff = fabric.leave_host(host)
            ok = handoff is not None
        elif action == "pause_host":
            ok = fabric.pause_host(host)
        elif action == "resume_host":
            ok = fabric.resume_host(host)
        elif action == "rejoin_host":
            ok = fabric.rejoin_host(host)
        else:  # unreachable under validate_membership_timeline
            ok = False
        ev = {
            "t_s": t, "action": action, "host": host, "applied": ok,
            "epoch": fabric.membership.epoch,
        }
        ev.update(remap_stats(
            uniq_keys, before, fabric.owners_of(uniq_keys)
        ))
        if handoff is not None:
            ev["handoff"] = handoff
        events_out.append(ev)
        take_snapshot(t)

    # ---- service-time sampling + the modeled coop tier --------------
    srng = np.random.Generator(np.random.Philox(sc.seed + 17))
    d_hit = profile.phases["hit"]
    d_peer = profile.phases["peer"]
    d_origin = profile.phases["origin"]
    d_xpod = profile.phases["cross_pod"]
    pause_penalty_s = fc.pause_penalty_ms / 1e3
    # Pod-wide single-flight over origin fetches: key -> completion
    # time of the owning fetch; joiners coalesce at that instant.
    inflight: dict = {}
    ctr = fabric.counters

    def service_for(host: int, key, nbytes: int) -> tuple:
        """One request's resolution through the modeled tier chain:
        local hit -> pod peer -> cross-pod home owner -> origin (with
        pod-wide single-flight coalescing). Returns ``(service_s,
        paid_origin)`` — the caller registers origin-paying fetches in
        the in-flight map so later misses on the key coalesce onto
        them. Counter/cache effects apply at issue time (the payloads
        are size-only, so the completion-time distinction the real
        plane needs does not exist here — documented README caveat)."""
        cache = fabric.caches[host]
        if cache.get(key) is not None:
            return d_hit.sample_s(srng), False
        pod = fabric.pod_of[host]
        o = fabric.rings[pod].owner(key)
        svc = 0.0
        if o is not None and o != host:
            if fabric.state(o) == "paused":
                # Bounded transient retries against a stalled owner,
                # then origin — the flat-penalty approximation.
                ctr["peer_requests"] += 1
                ctr["peer_misses"] += 1
                svc += pause_penalty_s
            else:
                ctr["peer_requests"] += 1
                if fabric.caches[o].get(key) is not None:
                    ctr["peer_hits"] += 1
                    ctr["peer_bytes"] += nbytes
                    cache.insert(key, nbytes)
                    return svc + d_peer.sample_s(srng), False
                ctr["peer_misses"] += 1
                svc += d_peer.sample_s(srng)
        # Cross-pod routing tier: a pod-local miss asks the chunk's
        # HOME pod owner before paying origin.
        home = fabric.home_pod(key)
        if fabric.pod_ring is not None and home != pod:
            o2 = fabric.rings[home].owner(key)
            if o2 is not None and fabric.state(o2) == "up":
                ctr["peer_requests"] += 1
                svc += d_xpod.sample_s(srng)
                if fabric.caches[o2].get(key) is not None:
                    ctr["peer_hits"] += 1
                    ctr["peer_bytes"] += nbytes
                    ctr["cross_pod_hits"] += 1
                    ctr["cross_pod_bytes"] += nbytes
                    cache.insert(key, nbytes)
                    return svc + d_peer.sample_s(srng), False
                ctr["peer_misses"] += 1
                fl = inflight.get(key)
                if fl is not None:
                    ctr["pod_coalesced"] += 1
                    cache.insert(key, nbytes)
                    return max(svc, fl - wclock.now()), False
                # The home owner fetches origin and keeps a copy — the
                # cross-pod analogue of owner_fetch.
                svc += d_origin.sample_s(srng)
                ctr["origin_fetches"] += 1
                ctr["origin_bytes"] += nbytes
                fabric.caches[o2].insert(key, nbytes)
                cache.insert(key, nbytes)
                return svc, True
        # Origin, via the pod-local owner when one is live (the real
        # plane's owner_fetch), else direct.
        fl = inflight.get(key)
        if fl is not None:
            ctr["pod_coalesced"] += 1
            cache.insert(key, nbytes)
            return max(svc, fl - wclock.now()), False
        svc += d_origin.sample_s(srng)
        ctr["origin_fetches"] += 1
        ctr["origin_bytes"] += nbytes
        if o is not None and o != host and fabric.state(o) == "up":
            fabric.caches[o].insert(key, nbytes)
        cache.insert(key, nbytes)
        return svc, True

    # ---- the virtual worker pool ------------------------------------
    idle = [n_workers]

    def kick() -> None:
        while idle[0] > 0:
            req = queue.pop(timeout=0.0)
            if req is None:
                return
            idle[0] -= 1
            serve_one(req)

    def serve_one(req: Request) -> None:
        cls = req.tenant.cls
        host = req.host
        if not fabric.is_dispatchable(host):
            live = live_sorted()
            if not live:
                no_live_host_errors[0] += 1
                ledgers[cls].errors += 1
                outcome[req.index] = False
                queue.done()
                idle[0] += 1
                return
            host = live[req.index % len(live)]
            failovers[0] += 1
        nbytes = req.key.length
        svc, paid_origin = service_for(host, req.key, nbytes)
        if paid_origin:
            # Register the origin-owning fetch for coalescing — only
            # until it lands (later misses then hit the owner's cache).
            t_done = wclock.now() + svc
            inflight[req.key] = t_done

            def land(key=req.key, t=t_done):
                if inflight.get(key) == t:
                    del inflight[key]

            loop.call_at(t_done, land)

        def complete(req=req, cls=cls, nbytes=nbytes):
            done_ns = wclock.now_ns()
            met = done_ns <= req.deadline_ns
            led = ledgers[cls]
            led.completed += 1
            led.bytes += nbytes
            if met:
                led.deadline_met += 1
            tenant_bytes[req.tenant.name] = (
                tenant_bytes.get(req.tenant.name, 0) + nbytes
            )
            completed_bytes[0] += nbytes
            outcome[req.index] = bool(met)
            lat_ns = done_ns - req.enqueue_ns
            recorders[cls].record_ns(lat_ns)
            agg_rec.record_ns(lat_ns)
            queue.done()
            idle[0] += 1
            kick()

        loop.call_after(svc, complete)

    # ---- the open loop, one dispatch event per arrival --------------
    snap_every = max(1, len(schedule) // 64)
    cursor = [0]
    rr = [0]
    mp_i = [0]

    def close_queue() -> None:
        drained[0] = queue.close()

    def end_of_schedule() -> None:
        while mp_i[0] < len(member_plan):
            apply_event(*member_plan[mp_i[0]])
            mp_i[0] += 1
        grace_s = max(1.0, 2.0 * scale)
        loop.wait_until(
            lambda: queue.queued == 0 and queue.in_service == 0,
            close_queue, poll_s=0.005,
            deadline_s=wclock.now() + grace_s, on_timeout=close_queue,
        )

    def dispatch() -> None:
        i = cursor[0]
        cursor[0] += 1
        req = schedule[i]
        while (mp_i[0] < len(member_plan)
               and member_plan[mp_i[0]][0] <= req.arrival_s):
            apply_event(*member_plan[mp_i[0]])
            mp_i[0] += 1
        vnow[0] = max(vnow[0], req.arrival_s)
        live = live_sorted()
        req.host = live[rr[0] % len(live)] if live else -1
        rr[0] += 1
        req.enqueue_ns = wclock.now_ns()
        queue.push(req)
        if rr[0] % snap_every == 0:
            take_snapshot(req.arrival_s)
        kick()
        if cursor[0] < len(schedule):
            loop.call_after(gaps[cursor[0]], dispatch)
        else:
            end_of_schedule()

    wall_t0 = time.perf_counter_ns()
    take_snapshot(0.0)
    if schedule:
        loop.call_after(gaps[0], dispatch)
    else:
        loop.call_at(0.0, end_of_schedule)
    virtual_wall = loop.run()
    take_snapshot(max(vnow[0], sc.duration_s))
    real_wall = (time.perf_counter_ns() - wall_t0) / 1e9
    wall = max(virtual_wall, 1e-9)

    qstats = queue.stats()
    qstats["drained_at_close"] = drained[0]
    for reason, by_cls in qstats["shed"].items():
        for cls, n in by_cls.items():
            if cls in ledgers:
                ledgers[cls].shed += n

    serve_extra = serve_scorecard(
        sc, schedule, ledgers, recorders, tenant_bytes, qstats,
        wall, completed_bytes[0], classes,
    )
    per_host = (
        fabric.per_host_stats() if sc.hosts <= PER_HOST_DETAIL_MAX
        else []
    )
    membership = membership_scorecard(
        sc, schedule, outcome, events_out, windows, snapshots, per_host,
        failovers[0], no_live_host_errors[0], 0, classes, fabric,
    )

    summaries = {}
    if len(agg_rec):
        summaries["request"] = summarize_ns(agg_rec.as_ns_array())
    for cls, rec in recorders.items():
        if len(rec):
            summaries[f"request_{cls}"] = summarize_ns(rec.as_ns_array())
    gbps = (completed_bytes[0] / 1e9) / wall if wall > 0 else 0.0
    errors = sum(led.errors for led in ledgers.values())
    res = RunResult(
        workload="fleet",
        config=cfg.to_dict(),
        bytes_total=completed_bytes[0],
        wall_seconds=wall,
        gbps=gbps,
        gbps_per_chip=gbps,
        n_chips=1,
        summaries=summaries,
        errors=errors,
    )
    res.extra["serve"] = serve_extra
    res.extra["membership"] = membership
    res.extra["fleet"] = {
        "hosts": sc.hosts,
        "pods": fabric.n_pods,
        "workers": n_workers,
        "tenants": sc.tenants,
        "timeline": fc.timeline,
        "arrivals": len(schedule),
        "cross_pod": {
            "hits": ctr["cross_pod_hits"],
            "bytes": ctr["cross_pod_bytes"],
        },
        "profile": profile.summary(),
        "sim": {
            "virtual_s": round(virtual_wall, 6),
            "real_wall_s": round(real_wall, 6),
            "speedup": round(virtual_wall / real_wall, 2)
            if real_wall > 0 else None,
            "events_fired": loop.events_fired,
            "hosts_per_wall_s": round(sc.hosts / real_wall, 1)
            if real_wall > 0 else None,
        },
    }
    if flight is not None:
        ring = flight.worker("fleet")
        op = ring.begin("fleet", tlabel, kind="fleet", install=False)
        op.note(
            "fleet", hosts=sc.hosts, pods=fabric.n_pods,
            virtual_s=round(virtual_wall, 6),
            real_wall_s=round(real_wall, 6),
            events=loop.events_fired,
        )
        op.finish(0)
        res.extra["flight"] = flight.summary()
        if cfg.obs.flight_journal:
            jpath = host_journal_path(
                cfg.obs.flight_journal, cfg.dist.process_id,
                cfg.dist.num_processes,
            )
            res.extra["flight_journal"] = flight.write_journal(
                jpath, extra={"workload": "fleet", "n_chips": 1},
                max_bytes=cfg.obs.journal_max_bytes,
            )
    return res


def run_fleet_sweep(cfg: BenchConfig) -> RunResult:
    """``tpubench fleet --fleet-sweep``: the serve-plane load sweep
    under virtual time — same point schema, same knee detector, so
    converted bench cells and the agreement gate compare rung for
    rung."""
    points = []
    results = []
    for mult in cfg.serve.sweep_points:
        c = BenchConfig.from_dict(cfg.to_dict())
        if cfg.serve.sweep_duration_s > 0:
            c.serve.duration_s = cfg.serve.sweep_duration_s
        c.telemetry.port = -1
        c.telemetry.enabled = False
        c.telemetry.otlp = False
        if c.obs.flight_journal:
            c.obs.flight_journal = f"{c.obs.flight_journal}.pt{len(points)}"
        res = run_fleet(c, rate_rps=cfg.serve.rate_rps * mult)
        sv = res.extra["serve"]
        gold = min(
            sv["classes"].values(), key=lambda x: x["priority"]
        ) if sv["classes"] else {}
        s = res.summaries.get("request")
        points.append({
            "multiplier": mult,
            "offered_rps": sv["offered_rps"],
            "achieved_rps": sv["achieved_rps"],
            "goodput_gbps": sv["goodput_gbps"],
            "p99_ms": s.p99_ms if s is not None else None,
            "gold_p99_ms": gold.get("p99_ms"),
            "gold_slo_attainment": gold.get("slo_attainment"),
            "shed": sv["shed"],
            "jain_fairness": sv["jain_fairness"],
        })
        results.append(res)
    knee = find_knee(points)
    last = results[-1]
    res = RunResult(
        workload="fleet",
        config=cfg.to_dict(),
        bytes_total=sum(r.bytes_total for r in results),
        wall_seconds=sum(r.wall_seconds for r in results),
        gbps=last.gbps,
        gbps_per_chip=last.gbps,
        n_chips=1,
        summaries=last.summaries,
        errors=sum(r.errors for r in results),
    )
    res.extra["serve"] = {
        "qos": cfg.serve.qos,
        "sweep": {
            "base_rate_rps": cfg.serve.rate_rps,
            "points": points,
            "knee": knee,
        },
    }
    res.extra["fleet"] = {
        "hosts": results[-1].extra["fleet"]["hosts"],
        "pods": results[-1].extra["fleet"]["pods"],
        "workers": results[-1].extra["fleet"]["workers"],
        "tenants": cfg.serve.tenants,
        "timeline": cfg.fleet.timeline,
        "arrivals": sum(r.extra["fleet"]["arrivals"] for r in results),
        "profile": results[-1].extra["fleet"]["profile"],
        "sim": {
            "virtual_s": round(sum(
                r.extra["fleet"]["sim"]["virtual_s"] for r in results
            ), 6),
            "real_wall_s": round(sum(
                r.extra["fleet"]["sim"]["real_wall_s"] for r in results
            ), 6),
            "events_fired": sum(
                r.extra["fleet"]["sim"]["events_fired"] for r in results
            ),
        },
    }
    sim = res.extra["fleet"]["sim"]
    if sim["real_wall_s"] > 0:
        sim["speedup"] = round(sim["virtual_s"] / sim["real_wall_s"], 2)
    return res


def format_fleet_block(fl: dict) -> str:
    """Human rendering of ``extra["fleet"]`` (CLI + ``tpubench
    report``)."""
    lines = ["== fleet simulation =="]
    sim = fl.get("sim", {})
    lines.append(
        f"  hosts={fl.get('hosts')}  pods={fl.get('pods')}  "
        f"workers={fl.get('workers')}  tenants={fl.get('tenants')}  "
        f"timeline={fl.get('timeline')}"
    )
    spd = sim.get("speedup")
    lines.append(
        f"  virtual_s={sim.get('virtual_s')}  "
        f"real_wall_s={sim.get('real_wall_s')}  "
        f"speedup={f'{spd}x' if spd is not None else 'n/a'}  "
        f"events={sim.get('events_fired')}"
    )
    if sim.get("hosts_per_wall_s") is not None:
        lines.append(
            f"  simulated hosts/wall-second: {sim['hosts_per_wall_s']}"
        )
    xp = fl.get("cross_pod")
    if xp and (xp.get("hits") or xp.get("bytes")):
        lines.append(
            f"  cross-pod: hits={xp['hits']}  bytes={xp['bytes']}"
        )
    prof = fl.get("profile")
    if prof:
        lines.append("  service profile (ms):")
        for name, d in prof.items():
            lines.append(
                f"    {name:<10} {d.get('source'):<9} "
                f"p50={d.get('p50_ms')}  p99={d.get('p99_ms')}  "
                f"n={d.get('count')}"
            )
    return "\n".join(lines)
