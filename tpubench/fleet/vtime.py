"""Discrete-event virtual time: the clock/scheduler seam made load-bearing.

PR 12's determinism gate forced every plane onto injectable clocks
(``AdmissionQueue(clock_ns=...)``, ``Membership(clock=...)``, the storm
ledger, the arrival schedule). This module supplies the other half of
that contract: a single-threaded event loop whose :class:`VirtualClock`
IS those injectables — time advances only when the heap pops the next
event, so a "sleep" costs one heap operation instead of real seconds,
and a 4096-host scenario replays bit-identically for a seed because
there is no thread interleaving left to vary.

Two deliberate restrictions keep the kernel honest:

* Events at equal timestamps fire in schedule order (a monotonic
  sequence breaks ties) — FIFO at a tick, never hash order.
* The loop never runs callbacks re-entrantly: a callback that schedules
  more work enqueues it; the drain loop in :meth:`EventLoop.run` is the
  only place events fire. Exceptions propagate — a sim bug must fail
  the run, not vanish into a thread.
"""

from __future__ import annotations

import heapq
from typing import Callable, Optional


class VirtualClock:
    """The injectable-clock surface over a simulated timestamp.

    ``now()`` (seconds, the ``Membership(clock=...)`` shape) and
    ``now_ns()`` (integer nanoseconds, the ``AdmissionQueue(clock_ns=)``
    / ``Request.enqueue_ns`` shape) read the same underlying instant,
    so deadline math in the queue and window math in the membership
    plane can never skew against each other the way two real clock
    reads can."""

    __slots__ = ("_now_s",)

    def __init__(self, start_s: float = 0.0):
        self._now_s = float(start_s)

    def now(self) -> float:
        return self._now_s

    def now_ns(self) -> int:
        # Round, don't truncate: a service completion scheduled at
        # exactly its deadline must compare equal through the ns domain
        # (done_ns <= deadline_ns), not lose 1ns to float flooring.
        return round(self._now_s * 1e9)

    def _advance_to(self, t_s: float) -> None:
        # Monotonic by construction — the heap only pops forward, and a
        # stale event (scheduled in the past by float noise) clamps.
        if t_s > self._now_s:
            self._now_s = t_s


class EventLoop:
    """Event-heap scheduler: ``(t_s, seq)``-ordered callbacks over a
    :class:`VirtualClock`.

    The API is the cooperative subset a simulated worker needs —
    ``call_at`` / ``call_after`` (the virtual ``sleep``), and
    ``wait_until`` (the condition-wait: poll a predicate at a bounded
    interval until it holds or a deadline passes, the virtual analogue
    of ``threading.Condition.wait_for``). ``run()`` drains to heap
    exhaustion or an optional horizon."""

    def __init__(self, clock: Optional[VirtualClock] = None):
        self.clock = clock if clock is not None else VirtualClock()
        self._heap: list = []
        self._seq = 0
        self.events_fired = 0

    # ------------------------------------------------------ schedule --
    def call_at(self, t_s: float, fn: Callable[[], None]) -> None:
        """Run ``fn`` at virtual time ``t_s`` (clamped to now: the past
        is not schedulable, it fires at the current instant)."""
        self._seq += 1
        heapq.heappush(
            self._heap, (max(t_s, self.clock.now()), self._seq, fn)
        )

    def call_after(self, delay_s: float, fn: Callable[[], None]) -> None:
        """The virtual ``sleep(delay_s); fn()`` — negative delays clamp
        to zero (fire this tick, after already-queued work)."""
        self.call_at(self.clock.now() + max(0.0, delay_s), fn)

    def wait_until(self, predicate: Callable[[], bool],
                   fn: Callable[[], None], *, poll_s: float,
                   deadline_s: Optional[float] = None,
                   on_timeout: Optional[Callable[[], None]] = None) -> None:
        """Condition-wait: run ``fn`` as soon as ``predicate()`` holds,
        polling every ``poll_s`` virtual seconds. Past ``deadline_s``
        the wait abandons (``on_timeout`` fires if given) — an unbounded
        virtual wait on a condition nothing will satisfy would spin the
        heap forever, the sim analogue of a wedged thread."""
        if poll_s <= 0:
            raise ValueError(f"wait_until poll_s={poll_s!r}: must be > 0")

        def attempt() -> None:
            if predicate():
                fn()
                return
            if deadline_s is not None and self.clock.now() >= deadline_s:
                if on_timeout is not None:
                    on_timeout()
                return
            self.call_after(poll_s, attempt)

        self.call_at(self.clock.now(), attempt)

    # ----------------------------------------------------------- run --
    def run(self, until_s: Optional[float] = None) -> float:
        """Drain the heap in timestamp order, advancing the clock to
        each event as it fires. With ``until_s``, events strictly later
        stay queued and the clock parks at the horizon (the caller can
        ``run`` again). Returns the clock's final reading."""
        while self._heap:
            t_s, _seq, fn = self._heap[0]
            if until_s is not None and t_s > until_s:
                break
            heapq.heappop(self._heap)
            self.clock._advance_to(t_s)
            self.events_fired += 1
            fn()
        if until_s is not None:
            self.clock._advance_to(until_s)
        return self.clock.now()

    @property
    def pending(self) -> int:
        return len(self._heap)
