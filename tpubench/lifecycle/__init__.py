"""Storage-lifecycle plane (SURVEY: the reference's ``benchmark-script/``
half, reproduced TPU-first).

Three pieces, jax-free by construction (the workloads in
``tpubench/workloads/ckpt.py`` / ``meta_storm.py`` add device staging on
top):

* :mod:`manifest` — the sharded-checkpoint layout (objects + crc32s)
  save and restore agree on;
* :mod:`upload` — the resumable multi-part upload driver (parts, flight
  phases, part latency, resumed-part accounting);
* :mod:`storm` — the open-loop metadata storm engine (arrivals-plane
  schedules over list/stat/open mixes, knee-curve inputs);
* :mod:`delta` — per-shard dirty tracking + ``ifGenerationMatch``-CAS
  delta saves (the incident drill's save-under-traffic arm).
"""

from tpubench.lifecycle.delta import DeltaTracker, delta_save  # noqa: F401
from tpubench.lifecycle.manifest import (  # noqa: F401
    CkptManifest,
    build_manifest,
    manifest_name,
    read_manifest,
    shard_content,
    shard_object_name,
)
from tpubench.lifecycle.storm import (  # noqa: F401
    MetaOp,
    build_storm_schedule,
    run_storm,
)
from tpubench.lifecycle.upload import readback_crc32, upload_object  # noqa: F401


def format_lifecycle_scorecard(lc: dict) -> str:
    """Human rendering of ``extra["lifecycle"]`` — shared by the CLI
    (printed live) and ``tpubench report`` (re-rendered from the result
    file), jax-free like every report surface."""
    op = lc.get("op", "?")
    lines = [f"  lifecycle [{op}]:"]
    if op == "save":
        lines.append(
            f"    save goodput={lc.get('goodput_gbps', 0.0):.4f} GB/s  "
            f"objects={lc.get('objects', 0)}  "
            f"bytes={lc.get('bytes', 0)}  parts={lc.get('parts', 0)}"
        )
        part = lc.get("part_latency") or {}
        if part:
            lines.append(
                f"    part p50={part.get('p50_ms', 0.0):.2f} ms  "
                f"p99={part.get('p99_ms', 0.0):.2f} ms  "
                f"(n={part.get('count', 0)})"
            )
        lines.append(
            f"    resumed_parts={lc.get('resumed_parts', 0)}  "
            f"corrupt_finalizes={lc.get('corrupt_finalizes', 0)}  "
            f"verified={lc.get('verified')}"
        )
    elif op == "restore":
        lines.append(
            f"    time-to-restore={lc.get('time_to_restore_s', 0.0):.3f} s  "
            f"goodput={lc.get('goodput_gbps', 0.0):.4f} GB/s  "
            f"objects={lc.get('objects', 0)}  bytes={lc.get('bytes', 0)}"
        )
        lines.append(
            f"    fetch={lc.get('fetch_seconds', 0.0):.3f} s  "
            f"stage={lc.get('stage_seconds', 0.0):.3f} s  "
            f"staged={lc.get('staged')}  "
            f"shards/object={lc.get('shards_per_object', 1)}  "
            f"verified={lc.get('verified')}"
        )
    elif op == "meta_storm":
        pts = (lc.get("sweep") or {}).get("points")
        if pts:
            lines.append("    offered_rps  achieved_rps   p50_ms   p99_ms")
            for p in pts:
                lines.append(
                    f"    {p.get('offered_rps', 0.0):>11.1f}"
                    f"  {p.get('achieved_rps', 0.0):>12.1f}"
                    f"  {p.get('p50_ms') if p.get('p50_ms') is not None else float('nan'):>7.2f}"
                    f"  {p.get('p99_ms') if p.get('p99_ms') is not None else float('nan'):>7.2f}"
                )
            knee = (lc.get("sweep") or {}).get("knee")
            lines.append(
                f"    knee: {knee}" if knee is not None
                else "    knee: not reached in this sweep"
            )
        else:
            lines.append(
                f"    ops={lc.get('ops', 0)}  "
                f"offered={lc.get('offered_rps', 0.0):.1f} rps  "
                f"achieved={lc.get('achieved_rps', 0.0):.1f} rps  "
                f"errors={lc.get('errors', 0)}"
            )
            lat = lc.get("latency") or {}
            if lat:
                lines.append(
                    f"    op p50={lat.get('p50_ms', 0.0):.2f} ms  "
                    f"p99={lat.get('p99_ms', 0.0):.2f} ms"
                )
            for k, s in (lc.get("by_kind") or {}).items():
                lines.append(
                    f"      {k}: n={s.get('count', 0)} "
                    f"p50={s.get('p50_ms', 0.0):.2f} ms "
                    f"p99={s.get('p99_ms', 0.0):.2f} ms"
                )
    return "\n".join(lines)
