"""Checkpoint DELTA saves: per-shard dirty tracking with CAS uploads.

A full ``ckpt-save`` re-uploads every shard every time; a training step
dirties only a fraction of them. :class:`DeltaTracker` keeps the
per-shard state a delta saver needs — content version (what the trainer
last wrote), committed storage generation (what the last save landed),
and a published crc32 per ``(shard, generation)`` so a restore can
verify byte-identity against the generation it actually fetched even
while saves keep landing new ones underneath it.

Each delta save uploads ONLY the dirty shards, each guarded by
``ifGenerationMatch`` on the generation this tracker committed last: a
412 precondition failure is NON-transient (another writer moved the
shard — split-brain, not weather), so it is never silently retried.
It is counted as a ``cas_conflict`` and classified into a full-save
fallback for that shard (one unconditional re-upload that re-adopts
whatever generation results), keeping the save correct while making the
conflict loud in the scorecard. The manifest is republished LAST and
only on an error-free pass — the ckpt.py publish discipline.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Optional

from tpubench.storage.base import StorageError

from .manifest import CkptManifest, manifest_name, shard_content


def _versioned_content(name: str, size: int, version: int):
    """Deterministic shard bytes for one content version. Version 0 is
    the base ``shard_content`` (byte-identical to what build_manifest
    hashed); later versions derive from a salted name so every dirty
    step changes the bytes."""
    return shard_content(name if version == 0 else f"{name}#v{version}", size)


class DeltaTracker:
    """Per-shard dirty/generation/crc state shared by the delta saver
    and the restore verifier (leaf lock: nothing else is acquired while
    it is held)."""

    def __init__(self, manifest: CkptManifest):
        self._lock = threading.Lock()
        self.manifest = manifest
        self.version = {s.name: 0 for s in manifest.objects}
        self.dirty: set[str] = set()
        self.generation: dict[str, Optional[int]] = {
            s.name: None for s in manifest.objects
        }
        # (shard name, storage generation) -> crc32 of the committed
        # bytes. The restore plane verifies against the generation it
        # stat-pinned, so a save landing mid-restore can't make a good
        # read look torn (or a torn read look good).
        self.published_crc: dict[tuple[str, int], int] = {}

    # ------------------------------------------------------------ state --
    def adopt(self, name: str, generation: int, crc: int) -> None:
        """Record a committed shard generation (the baseline full save
        or a delta commit)."""
        with self._lock:
            self.generation[name] = generation
            self.published_crc[(name, generation)] = crc
            self.dirty.discard(name)

    def crc_for(self, name: str, generation: int) -> Optional[int]:
        with self._lock:
            return self.published_crc.get((name, generation))

    def mutate(self, rng, fraction: float) -> list[str]:
        """One training step: dirty ``fraction`` of the shards (at least
        one), bumping their content version. ``rng`` is a seeded
        ``random.Random`` — the dirty set is deterministic per run."""
        names = [s.name for s in self.manifest.objects]
        k = max(1, int(round(fraction * len(names))))
        picked = sorted(rng.sample(names, min(k, len(names))))
        with self._lock:
            for name in picked:
                self.version[name] += 1
                self.dirty.add(name)
        return picked

    def snapshot_dirty(self) -> dict[str, int]:
        """The shard set one save pass will upload: {name: version}."""
        with self._lock:
            return {n: self.version[n] for n in sorted(self.dirty)}

    def snapshot_all(self) -> dict[str, int]:
        """Every shard at its current version (the full-save arm)."""
        with self._lock:
            return {s.name: self.version[s.name]
                    for s in self.manifest.objects}


def delta_save(
    backend,
    tracker: DeltaTracker,
    part_bytes: int,
    *,
    delta: bool = True,
    ring=None,
    transport_label: str = "",
    part_recorder=None,
    clock_ns=time.perf_counter_ns,
) -> dict:
    """One save pass under live traffic.

    ``delta=True`` uploads only the tracker's dirty shards, each CAS-
    guarded on its last committed generation; ``delta=False`` is the
    full-save arm (every shard, unguarded — the A/B baseline). Returns
    the pass's ledger: shard counts by disposition, bytes uploaded, CAS
    conflicts and their classified full fallbacks, errors.
    """
    from .upload import upload_object

    manifest = tracker.manifest
    todo = tracker.snapshot_dirty() if delta else tracker.snapshot_all()
    sizes = {s.name: s.size for s in manifest.objects}
    stats = {
        "shards_total": len(manifest.objects),
        "dirty_shards": len(tracker.snapshot_dirty()),
        "uploaded_shards": 0,
        "skipped_clean": len(manifest.objects) - len(todo),
        "cas_conflicts": 0,
        "full_fallbacks": 0,
        "bytes_uploaded": 0,
        "errors": 0,
    }
    for name, version in todo.items():
        data = _versioned_content(name, sizes[name], version)
        payload = data.tobytes()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        guard = tracker.generation.get(name) if delta else None
        op = (
            ring.begin(name, transport_label, kind="upload")
            if ring is not None else None
        )
        try:
            try:
                meta, _ = upload_object(
                    backend, name, payload, part_bytes,
                    if_generation_match=guard,
                    part_recorder=part_recorder,
                )
            except StorageError as e:
                if guard is None or e.transient or e.code != 412:
                    raise
                # CAS lost: another writer committed a generation we
                # never adopted. Non-transient by design — classify it
                # and fall back to ONE unconditional full re-upload of
                # this shard rather than retrying the stale guard.
                stats["cas_conflicts"] += 1
                stats["full_fallbacks"] += 1
                if op is not None:
                    op.note("delta", shard=name, outcome="cas_conflict")
                meta, _ = upload_object(
                    backend, name, payload, part_bytes,
                    if_generation_match=None,
                    part_recorder=part_recorder,
                )
        except Exception as e:  # noqa: BLE001 — per-shard failure is data
            stats["errors"] += 1
            if op is not None:
                op.finish(error=e)
            continue
        tracker.adopt(name, meta.generation, crc)
        stats["uploaded_shards"] += 1
        stats["bytes_uploaded"] += len(payload)
        if op is not None:
            op.mark("delta_commit", clock_ns())
            op.finish(len(payload))
    if stats["errors"] == 0:
        # Publish-last discipline: the manifest only moves after an
        # error-free pass, so a crashed save never dangles pointers.
        backend.write(manifest_name(manifest.prefix),
                      manifest.to_json().encode())
    return stats
