"""Checkpoint manifest: the sharded-model layout save and restore agree on.

A checkpoint is ``objects`` shard-objects of ``object_bytes`` each — one
object per parameter shard, the Gemma-31B-scale layout shape (PAPERS.md:
arXiv 2605.25645) scaled down to whatever the run configures — plus one
``MANIFEST.json`` object naming them all with their sizes and crc32s.

Object content is :func:`~tpubench.storage.base.deterministic_bytes` of
the object's NAME, so any host (or the restore verifier) can regenerate
and check any shard without shipping bytes around — the same discipline
the multi-host reassembly tests use (SURVEY §4). The crc32 travels in
the manifest, which is what makes "zero corrupt finalizes" and
"byte-identical restore" checkable with one cheap pass instead of a
second full copy of the checkpoint.
"""

from __future__ import annotations

import json
import zlib
from dataclasses import dataclass

from tpubench.storage.base import deterministic_bytes

MANIFEST_FORMAT = "tpubench-ckpt/1"


@dataclass(frozen=True)
class ShardSpec:
    """One checkpoint shard-object."""

    name: str
    size: int
    crc32: int


@dataclass(frozen=True)
class CkptManifest:
    prefix: str
    objects: tuple

    @property
    def total_bytes(self) -> int:
        return sum(o.size for o in self.objects)

    def to_json(self) -> str:
        return json.dumps({
            "format": MANIFEST_FORMAT,
            "prefix": self.prefix,
            "objects": [
                {"name": o.name, "size": o.size, "crc32": o.crc32}
                for o in self.objects
            ],
        }, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "CkptManifest":
        doc = json.loads(text)
        if doc.get("format") != MANIFEST_FORMAT:
            raise ValueError(
                f"not a checkpoint manifest (format="
                f"{doc.get('format')!r}, want {MANIFEST_FORMAT})"
            )
        return cls(
            prefix=doc["prefix"],
            objects=tuple(
                ShardSpec(o["name"], int(o["size"]), int(o["crc32"]))
                for o in doc["objects"]
            ),
        )


def manifest_name(prefix: str) -> str:
    return f"{prefix}MANIFEST.json"


def shard_object_name(prefix: str, index: int) -> str:
    return f"{prefix}shard_{index:05d}"


def shard_content(name: str, size: int):
    """The shard's deterministic byte content (uint8 ndarray)."""
    return deterministic_bytes(name, size)


def build_manifest(prefix: str, n_objects: int,
                   object_bytes: int) -> CkptManifest:
    """The layout ``ckpt-save`` writes: crc32s computed from the same
    deterministic content the upload will stream."""
    objects = []
    for i in range(n_objects):
        name = shard_object_name(prefix, i)
        crc = zlib.crc32(shard_content(name, object_bytes).tobytes())
        objects.append(ShardSpec(name, object_bytes, crc & 0xFFFFFFFF))
    return CkptManifest(prefix=prefix, objects=tuple(objects))


def read_manifest(backend, prefix: str) -> CkptManifest:
    """Fetch and parse ``<prefix>MANIFEST.json`` through any backend."""
    name = manifest_name(prefix)
    meta = backend.stat(name)
    reader = backend.open_read(name)
    buf = bytearray(meta.size)
    mv = memoryview(buf)
    got = 0
    try:
        while got < meta.size:
            n = reader.readinto(mv[got:])
            if n <= 0:
                break
            got += n
    finally:
        reader.close()
    return CkptManifest.from_json(bytes(buf[:got]).decode())
