"""Open-loop metadata storm engine (``tpubench meta-storm``).

The reference's ``benchmark-script/`` list/open binaries hammer metadata
closed-loop (a fixed thread pool as fast as it can); real dataloaders
hit the many-small-files pathology OPEN-LOOP — list/stat/open requests
arrive on their own schedule whether or not the store keeps up, which is
the only regime where a saturation knee exists to measure. This engine
drives the PR-10 arrivals plane (Poisson/MMPP/diurnal, seeded and
replayable) over a population of small objects with a weighted
list/stat/open mix, and reports offered vs achieved rate plus per-kind
latency — the inputs :func:`tpubench.serve.qos.find_knee` needs.

Clock/sleep are injectable (CLOCK_MODULES discipline: seeded storms must
replay deterministically in tests); the ledger's lock is a leaf —
backend calls and flight appends run OUTSIDE it (LOCK_ORDER_FILES).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import numpy as np

from tpubench.config import parse_meta_mix, parse_sleep_scale
from tpubench.metrics import LatencyRecorder, merge_recorders
from tpubench.metrics.percentiles import summarize_ns
from tpubench.workloads.arrivals import make_arrivals, scaled_gaps


@dataclass(frozen=True)
class MetaOp:
    """One scheduled metadata operation."""

    t: float  # virtual arrival second
    kind: str  # list | stat | open
    obj: str  # target object name (stat/open) or listing prefix (list)


def _rng(seed: int) -> np.random.Generator:
    return np.random.Generator(np.random.Philox(seed))


def build_storm_schedule(
    object_names: Sequence[str],
    *,
    kind: str,
    rate_rps: float,
    duration_s: float,
    mix: str,
    prefix: str,
    seed: int = 0,
    burst_factor: float = 4.0,
    burst_fraction: float = 0.25,
    burst_cycle_s: float = 1.0,
    diurnal_period_s: float = 4.0,
) -> list[MetaOp]:
    """Seeded, replayable storm timeline: arrival instants from the
    shared arrivals plane, op kinds drawn by the normalized mix weights,
    targets drawn uniformly over the object population (metadata storms
    are breadth pathologies — every small object gets touched)."""
    arrivals = make_arrivals(
        kind, rate_rps, duration_s, seed=seed,
        burst_factor=burst_factor, burst_fraction=burst_fraction,
        burst_cycle_s=burst_cycle_s, diurnal_period_s=diurnal_period_s,
    )
    if not arrivals:
        return []
    weights = parse_meta_mix(mix)
    kinds = sorted(weights)
    p = np.array([weights[k] for k in kinds], dtype=np.float64)
    rng = _rng(seed + 0x5EED)
    kind_idx = rng.choice(len(kinds), size=len(arrivals), p=p)
    obj_idx = rng.integers(0, max(1, len(object_names)), size=len(arrivals))
    out = []
    for t, ki, oi in zip(arrivals, kind_idx, obj_idx):
        k = kinds[int(ki)]
        out.append(MetaOp(
            t=t, kind=k,
            obj=prefix if k == "list" else object_names[int(oi)],
        ))
    return out


class StormLedger:
    """Shared completion accounting. The lock is a LEAF: only counter
    arithmetic runs under it — never a backend call, a flight append or
    another lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.completed: dict[str, int] = {}
        self.errors: dict[str, int] = {}
        self.bytes = 0
        self.list_items = 0
        self.first_arrival_ns: Optional[int] = None
        self.last_done_ns: Optional[int] = None

    def arrival(self, ns: int) -> None:
        with self._lock:
            if self.first_arrival_ns is None or ns < self.first_arrival_ns:
                self.first_arrival_ns = ns

    def done(self, kind: str, ns: int, *, nbytes: int = 0,
             items: int = 0, error: bool = False) -> None:
        with self._lock:
            if error:
                self.errors[kind] = self.errors.get(kind, 0) + 1
            else:
                self.completed[kind] = self.completed.get(kind, 0) + 1
                self.bytes += nbytes
                self.list_items += items
            if self.last_done_ns is None or ns > self.last_done_ns:
                self.last_done_ns = ns

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "completed": dict(self.completed),
                "errors": dict(self.errors),
                "bytes": self.bytes,
                "list_items": self.list_items,
                "first_arrival_ns": self.first_arrival_ns,
                "last_done_ns": self.last_done_ns,
            }


def _execute_op(backend, op: MetaOp, *, page_size: int, read_bytes: int,
                scratch: memoryview) -> tuple[int, int]:
    """Run one metadata op; returns (bytes_read, items_listed)."""
    if op.kind == "list":
        items = backend.list(op.obj, page_size=page_size)
        return 0, len(items)
    if op.kind == "stat":
        backend.stat(op.obj)
        return 0, 0
    # open: open_read the object head, stream it, close — the
    # open_file-binary analogue (FD churn + first-byte cost).
    reader = backend.open_read(op.obj, 0, read_bytes or None)
    got = 0
    try:
        while True:
            n = reader.readinto(scratch)
            if n <= 0:
                break
            got += n
    finally:
        reader.close()
    return got, 0


def run_storm(
    backend,
    schedule: Sequence[MetaOp],
    *,
    workers: int,
    page_size: int = 0,
    read_bytes: int = 4096,
    flight=None,
    transport_label: str = "",
    sleep: Callable[[float], None] = time.sleep,
    clock_ns: Callable[[], int] = time.perf_counter_ns,
    ledger: Optional[StormLedger] = None,
) -> dict:
    """Replay one storm schedule open-loop and measure it.

    The dispatcher walks the virtual timeline under the shared
    ``TPUBENCH_BENCH_SLEEP_SCALE`` contract (per-gap floor: a scaled-down
    run still PACES its bursts); workers drain a shared queue, so once
    service falls behind the arrival process the queue grows and
    latencies carry the backlog — exactly the open-loop saturation shape
    the knee detector looks for. Per-op latency is completion minus
    ARRIVAL (queue wait included).

    ``ledger`` injects a shared StormLedger so concurrent storm mixes
    (the drill's metadata arm and a standalone meta-storm) count
    against ONE quota ledger implementation instead of drifting
    copies; None keeps a private per-run ledger."""
    ledger = ledger if ledger is not None else StormLedger()
    recs = {
        (i, k): LatencyRecorder(f"storm{i}.{k}")
        for i in range(workers) for k in ("list", "stat", "open")
    }
    q: queue.Queue = queue.Queue()

    def worker(i: int) -> None:
        ring = flight.worker(f"storm{i}") if flight is not None else None
        scratch = memoryview(bytearray(max(4096, read_bytes or 4096)))
        while True:
            item = q.get()
            if item is None:
                return
            arrival_ns, op = item
            rec_op = (
                ring.begin(op.obj, transport_label,
                           enqueue_ns=arrival_ns, kind="meta")
                if ring is not None else None
            )
            try:
                nbytes, items = _execute_op(
                    backend, op, page_size=page_size,
                    read_bytes=read_bytes, scratch=scratch,
                )
            except Exception as e:  # noqa: BLE001 — op failure is data
                now = clock_ns()
                ledger.done(op.kind, now, error=True)
                if rec_op is not None:
                    rec_op.finish(error=e)
                continue
            now = clock_ns()
            recs[(i, op.kind)].record_ns(now - arrival_ns)
            ledger.done(op.kind, now, nbytes=nbytes, items=items)
            if rec_op is not None:
                rec_op.mark("meta_op", now)
                rec_op.finish(nbytes)

    threads = [
        threading.Thread(target=worker, args=(i,), name=f"storm-{i}",
                         daemon=True)
        for i in range(workers)
    ]
    for t in threads:
        t.start()
    scale = parse_sleep_scale("arrival gaps")
    gaps = scaled_gaps([op.t for op in schedule], scale)
    t_dispatch0 = clock_ns()
    t_dispatch1 = t_dispatch0
    try:
        for gap, op in zip(gaps, schedule):
            if gap > 0:
                sleep(gap)
            now = clock_ns()
            ledger.arrival(now)
            q.put((now, op))
        # Dispatch ends when the LAST arrival is enqueued — stamped
        # BEFORE the worker join, or offered_rps would silently include
        # the queue-drain time and collapse to achieved_rps exactly when
        # the system falls behind (the backlog the knee detector needs).
        t_dispatch1 = clock_ns()
    finally:
        for _ in threads:
            q.put(None)
        for t in threads:
            t.join()
    snap = ledger.snapshot()
    n_ops = len(schedule)
    dispatch_wall_s = max(1e-9, (t_dispatch1 - t_dispatch0) / 1e9)
    span_s = (
        (snap["last_done_ns"] - snap["first_arrival_ns"]) / 1e9
        if snap["first_arrival_ns"] is not None
        and snap["last_done_ns"] is not None else 0.0
    )
    span_s = max(span_s, 1e-9)
    completed = sum(snap["completed"].values())
    errors = sum(snap["errors"].values())
    by_kind = {}
    for k in ("list", "stat", "open"):
        merged = merge_recorders([recs[(i, k)] for i in range(workers)])
        if merged.size:
            by_kind[k] = summarize_ns(merged).to_dict()
    all_ns = merge_recorders([r for r in recs.values()])
    overall = summarize_ns(all_ns).to_dict() if all_ns.size else None
    return {
        "ops": n_ops,
        "completed": completed,
        "errors": errors,
        "by_kind_completed": snap["completed"],
        "by_kind_errors": snap["errors"],
        "bytes": snap["bytes"],
        "list_items": snap["list_items"],
        # Wall-clock offered vs achieved: the arrival replay's own pace
        # (sleep-scaled) against the completion rate over the full
        # arrival→last-completion span — achieved < offered IS backlog.
        "offered_rps": round(n_ops / dispatch_wall_s, 3),
        "achieved_rps": round(completed / span_s, 3),
        "wall_s": round(span_s, 6),
        "p50_ms": overall["p50_ms"] if overall else None,
        "p99_ms": overall["p99_ms"] if overall else None,
        "latency": overall,
        "by_kind": by_kind,
        "sleep_scale": scale,
    }
