"""The ckpt-save upload driver: one object through a resumable session.

Streams an object's bytes through ``backend.open_write`` in
``part_bytes``-sized content-range parts, stamping the lifecycle flight
phases (``upload_open`` at session open — before any connection work, so
the phase order survives pooled-connection reuse — ``part_sent`` at the
first committed part, ``upload_complete`` at finalize) and a ``part``
note per committed part. Part-level retry/backoff is NOT here: it rides
the backend stack's :class:`~tpubench.storage.retrying._ResumingWriter`
(the read path's resume discipline, mirrored), so hedge/watchdog/breaker
and the gax policy compose underneath exactly like they do for reads.
"""

from __future__ import annotations

import time
import zlib
from typing import Optional

from tpubench.obs.flight import annotate as flight_annotate
from tpubench.obs.flight import note_phase as flight_note
from tpubench.storage.base import ObjectMeta


def upload_object(
    backend,
    name: str,
    data,
    part_bytes: int,
    *,
    if_generation_match: Optional[int] = None,
    part_recorder=None,
) -> tuple[ObjectMeta, dict]:
    """Upload ``data`` (any buffer) as ``name`` in resumable parts.

    Returns ``(meta, stats)`` where stats carries ``parts``,
    ``resumed_parts`` (from the resuming writer, 0 on raw backends) and
    ``bytes``. ``part_recorder`` (a LatencyRecorder) gets one sample per
    part — the save scorecard's part p50/p99. On failure the session is
    aborted (best-effort) and the error re-raised.
    """
    mv = memoryview(data)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    total = len(mv)
    flight_note("upload_open")
    writer = backend.open_write(name, if_generation_match=if_generation_match)
    parts = 0
    try:
        off = 0
        while off < total:
            n = min(part_bytes, total - off)
            t0 = time.perf_counter_ns()
            writer.write(mv[off:off + n])
            dt = time.perf_counter_ns() - t0
            if part_recorder is not None:
                part_recorder.record_ns(dt)
            parts += 1
            flight_note("part_sent")
            flight_annotate("part", bytes=n, ms=round(dt / 1e6, 3))
            off += n
        meta = writer.finalize()
        flight_note("upload_complete")
    except BaseException:
        writer.abort()
        raise
    if meta.size != total:
        # A finalize that committed the wrong byte count is corruption,
        # not a transport hiccup — surface it loudly.
        raise IOError(
            f"upload {name}: finalized {meta.size} bytes, sent {total}"
        )
    return meta, {
        "parts": parts,
        "resumed_parts": int(getattr(writer, "resumed_parts", 0)),
        "bytes": total,
    }


def readback_crc32(backend, name: str, size: int,
                   granule: int = 1 << 20) -> int:
    """crc32 of the object's stored bytes (the zero-corrupt-finalizes
    verifier): streamed through a reused granule, never materializing
    the object."""
    reader = backend.open_read(name)
    buf = memoryview(bytearray(granule))
    crc = 0
    got = 0
    try:
        while got < size:
            n = reader.readinto(buf)
            if n <= 0:
                break
            crc = zlib.crc32(buf[:n], crc)
            got += n
    finally:
        reader.close()
    if got != size:
        raise IOError(f"readback {name}: short read {got}/{size}")
    return crc & 0xFFFFFFFF
