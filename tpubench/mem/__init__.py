"""Pinned host-memory management for the zero-copy ingest datapath.

The ingest pipeline's hot path (PR 3) paid 2-3 full host-RAM copies per
chunk: the prefetcher filled a ``bytearray`` and materialized ``bytes``,
the cache re-copied on insert, and the consumer copied again into the
staging slot.  This package is the fix: a refcounted pool of fixed-size
lane-aligned slabs (:class:`~tpubench.mem.slab.SlabPool`) that the whole
pipeline leases end-to-end — the transport ``readinto``\\ s the wire bytes
straight into a leased slab, the cache stores the lease, and the consumer
stages the slab view in place, so a chunk is written once off the wire
and never copied again.

:class:`~tpubench.mem.slab.CopyMeter` is the proof: it counts every
host-RAM write of chunk payload bytes (the wire landing plus any
subsequent copy), and ``copies_per_byte`` is stamped into
``extra["pipeline"]["copies"]`` so a regression test can pin the slab
path at exactly 1.0 writes per delivered byte.
"""

from tpubench.mem.slab import (
    CopyMeter,
    SlabLease,
    SlabPool,
    payload_view,
    release_payload,
)

__all__ = [
    "CopyMeter",
    "SlabLease",
    "SlabPool",
    "payload_view",
    "release_payload",
]
