"""Refcounted slab buffer pool: the allocation substrate of the zero-copy
ingest datapath.

A :class:`SlabPool` owns ``n_slabs`` fixed-size slabs. When the native
engine is available each slab is its own ``posix_memalign``'d
:class:`~tpubench.native.engine.AlignedBuffer` (4096-aligned, so every
slab is lane-aligned for the TPU staging layout and O_DIRECT-safe);
otherwise slabs degrade to plain ``bytearray``\\ s with identical
semantics — the pool is a performance substrate, never a capability gate.

Lifecycle is **lease → share → release**:

* :meth:`SlabPool.lease` hands out a :class:`SlabLease` with refcount 1
  (the leaser's reference). The transport ``readinto``\\ s wire bytes
  straight into ``lease.view()``.
* Every party that needs the bytes to outlive the current lock scope
  takes its own reference (:meth:`SlabLease.incref`): the chunk cache
  takes one at insert, and hands one to each consumer it serves.
* :meth:`SlabLease.release` drops a reference; the LAST release retires
  the slab to the pool's free list. A cache eviction racing a consumer
  mid-read therefore can never free memory under the reader — the
  consumer's reference keeps the slab alive until it releases.

Exhaustion never deadlocks: a lease requested from an empty pool is
served from a transient **overflow** allocation (counted in
``stats()['overflow_leases']`` — sustained overflow means the pool is
undersized) that is freed, not pooled, on retirement.

Leak detection: the pool tracks outstanding leases; :meth:`SlabPool.close`
reports (and keeps alive, so no dangling views) anything still leased —
``stats()['leaked_slabs']`` must be 0 after a clean run, which the slab
test suite pins under chaos-injected mid-chunk failures.
"""

from __future__ import annotations

import threading
from typing import Optional


class SlabLease:
    """One leased slab: a bounded writable view plus a refcount.

    ``len(lease)`` is the payload size it was leased for (not the slab
    capacity), so cache byte accounting treats leases and ``bytes``
    uniformly. The underlying memory is valid until the LAST reference
    releases."""

    __slots__ = ("_pool", "_slab", "nbytes", "_refs", "overflow")

    def __init__(self, pool: "SlabPool", slab, nbytes: int, overflow: bool):
        self._pool = pool
        self._slab = slab  # AlignedBuffer | bytearray
        self.nbytes = nbytes
        self._refs = 1
        self.overflow = overflow

    def __len__(self) -> int:
        return self.nbytes

    def view(self, n: Optional[int] = None) -> memoryview:
        """Writable memoryview of the first ``n`` (default: leased) bytes."""
        slab = self._slab
        if slab is None:
            raise ValueError("slab lease already fully released")
        want = self.nbytes if n is None else n
        if isinstance(slab, bytearray):
            return memoryview(slab)[:want]
        return slab.view(want)  # AlignedBuffer

    def tobytes(self) -> bytes:
        """Copying escape hatch (NOT the hot path — callers that need an
        immutable snapshot, e.g. integrity checks)."""
        return bytes(self.view())

    def as_numpy(self):
        """Zero-copy 1-D uint8 numpy view of the leased payload — what
        the overlapped staging executor ``device_put``s directly, so a
        chunk goes wire → slab → HBM with no intermediate host copy.
        The view aliases the slab: it is valid only while the caller's
        reference is held (the executor's reaper releases at transfer
        completion, which is exactly that lifetime)."""
        import numpy as np

        return np.frombuffer(self.view(), dtype=np.uint8)

    def incref(self) -> "SlabLease":
        with self._pool._lock:
            if self._refs <= 0:
                raise ValueError("incref on a fully released slab lease")
            self._refs += 1
        return self

    def release(self) -> None:
        """Drop one reference; the last one retires the slab to the pool."""
        self._pool._release(self)


class SlabPool:
    """Fixed-size slab pool (module docstring). Thread-safe."""

    def __init__(
        self,
        slab_bytes: int,
        n_slabs: int,
        *,
        use_native: bool = True,
        engine=None,
    ):
        if slab_bytes <= 0:
            raise ValueError(f"slab_bytes={slab_bytes}: must be > 0")
        if n_slabs <= 0:
            raise ValueError(f"n_slabs={n_slabs}: must be > 0")
        self.slab_bytes = int(slab_bytes)
        self.n_slabs = int(n_slabs)
        self._lock = threading.Lock()
        self._closed = False
        if engine is None and use_native:
            # get_engine (not peek): with a cached .so this is a dlopen,
            # not a compile, and a missing toolchain degrades to bytearray
            # slabs instead of failing the run.
            from tpubench.native.engine import get_engine

            engine = get_engine()
        self._engine = engine if use_native else None
        self._free: list = []
        alloc_failed = False
        for _ in range(self.n_slabs):
            slab = None
            if self._engine is not None and not alloc_failed:
                try:
                    slab = self._engine.alloc(self.slab_bytes)
                except MemoryError:
                    alloc_failed = True  # fall through to bytearray
            if slab is None:
                slab = bytearray(self.slab_bytes)
            self._free.append(slab)
        self.native = self._engine is not None and not alloc_failed
        # Counters (the extra["pipeline"]["copies"]["pool"] stamp).
        self.leases = 0
        self.retires = 0
        self.overflow_leases = 0
        self.peak_leased = 0
        self._leased = 0
        self.leaked_slabs = 0

    # ------------------------------------------------------------ surface --
    def lease(self, nbytes: int) -> SlabLease:
        """A slab sized to hold ``nbytes`` (refcount 1, caller-owned).
        Raises ValueError when ``nbytes`` exceeds the slab size — the
        caller's chunking is wrong, not the pool's."""
        if nbytes > self.slab_bytes:
            raise ValueError(
                f"lease of {nbytes} B exceeds slab_bytes={self.slab_bytes}"
            )
        with self._lock:
            if self._closed:
                raise ValueError("pool closed")
            slab = self._free.pop() if self._free else None
            overflow = slab is None
            self.leases += 1
            if overflow:
                self.overflow_leases += 1
            self._leased += 1
            self.peak_leased = max(self.peak_leased, self._leased)
        if overflow:
            # Transient allocation outside the pool memory: never pooled
            # on retirement, so pool footprint stays bounded at
            # n_slabs × slab_bytes + whatever is CURRENTLY overflowed.
            if self._engine is not None and self.native:
                try:
                    slab = self._engine.alloc(self.slab_bytes)
                except MemoryError:
                    slab = bytearray(self.slab_bytes)
            else:
                slab = bytearray(self.slab_bytes)
        return SlabLease(self, slab, int(nbytes), overflow)

    def _release(self, lease: SlabLease) -> None:
        free_native = None
        with self._lock:
            if lease._refs <= 0:
                raise ValueError("release of a fully released slab lease")
            lease._refs -= 1
            if lease._refs > 0:
                return
            slab, lease._slab = lease._slab, None
            self._leased -= 1
            self.retires += 1
            if lease.overflow or self._closed:
                if not isinstance(slab, bytearray):
                    free_native = slab
            else:
                self._free.append(slab)
        if free_native is not None:
            free_native.free()

    def close(self) -> dict:
        """Free pooled slabs; anything still leased is counted as leaked
        and (deliberately) kept alive — a dangling view would be worse
        than the leak it reports. Returns final :meth:`stats`."""
        with self._lock:
            if self._closed:
                return self.stats_locked()
            self._closed = True
            free, self._free = self._free, []
            self.leaked_slabs = self._leased
        for slab in free:
            if not isinstance(slab, bytearray):
                slab.free()
        return self.stats()

    # -------------------------------------------------------------- stats --
    def stats_locked(self) -> dict:
        return {
            "slab_bytes": self.slab_bytes,
            "slabs": self.n_slabs,
            "native": self.native,
            "leased": self._leased,
            "peak_leased": self.peak_leased,
            "leases": self.leases,
            "retires": self.retires,
            "overflow_leases": self.overflow_leases,
            "leaked_slabs": self.leaked_slabs,
        }

    def stats(self) -> dict:
        with self._lock:
            return self.stats_locked()

    @property
    def leased(self) -> int:
        with self._lock:
            return self._leased


# ------------------------------------------------------- payload helpers --
# The pipeline's chunk payload is EITHER immutable ``bytes`` (the legacy /
# A-B baseline arm) or a SlabLease (the zero-copy arm). These two helpers
# are the only polymorphism consumers need.


def payload_view(data) -> memoryview:
    """Read view of a chunk payload (bytes or SlabLease), no copy."""
    if isinstance(data, SlabLease):
        return data.view()
    return memoryview(data)


def release_payload(data) -> None:
    """Drop the caller's reference on a payload (no-op for bytes)."""
    if isinstance(data, SlabLease):
        data.release()


class CopyMeter:
    """Counts host-RAM writes of chunk payload bytes on the ingest path.

    ``landed_bytes`` is the unavoidable write: wire → first host buffer
    (slab or bytearray). ``copied_bytes`` is every write AFTER that —
    ``bytes()`` materialization, cache insert copies, coalesce copies.
    ``copies_per_byte`` = (landed + copied) / landed: exactly 1.0 means
    a chunk is written once off the wire and never copied again (the
    slab path's contract); the legacy bytes path pays >= 2.0.

    Staging writes (host cache → slot ring / HBM) are deliberately OUT of
    scope: both A/B arms pay them identically, and the DMA feed is the
    staging subsystem's own accounting (``staged_bytes``). So is
    transport-INTERNAL buffering: a hedged read's racing producer
    streams cannot share one destination, so hedging inherently buffers
    once more inside ``storage/tail.py`` — on both arms equally; the
    meter measures the pipeline datapath, wire-landing onward.
    """

    __slots__ = ("_lock", "landed_bytes", "copied_bytes")

    def __init__(self):
        self._lock = threading.Lock()
        self.landed_bytes = 0
        self.copied_bytes = 0

    def landed(self, n: int) -> None:
        with self._lock:
            self.landed_bytes += int(n)

    def copied(self, n: int) -> None:
        with self._lock:
            self.copied_bytes += int(n)

    def copies_per_byte(self) -> Optional[float]:
        with self._lock:
            if not self.landed_bytes:
                return None
            return (self.landed_bytes + self.copied_bytes) / self.landed_bytes

    def stats(self) -> dict:
        with self._lock:
            landed, copied = self.landed_bytes, self.copied_bytes
        return {
            "landed_bytes": landed,
            "copied_bytes": copied,
            "copies_per_byte": (
                (landed + copied) / landed if landed else None
            ),
        }
