"""Metrics core: race-free per-worker recorders, ssd_test-format percentiles,
throughput accounting, and result reporting (SURVEY.md §5.5)."""

from tpubench.metrics.percentiles import LatencySummary, format_summary, summarize  # noqa: F401
from tpubench.metrics.recorder import (  # noqa: F401
    ByteCounter,
    LatencyRecorder,
    MetricSet,
    merge_recorders,
)
from tpubench.metrics.report import RunResult, write_result  # noqa: F401
