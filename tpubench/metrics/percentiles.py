"""Latency percentile math and report formatting.

Reproduces the reference ssd_test driver's in-process percentile block —
the only place the reference computes statistics itself
(``benchmark-script/ssd_test/main.go:144-163``): sort ascending, then
index-based percentiles ``sorted[p*n/100]`` (p50 = ``sorted[n/2]``,
p99 = ``sorted[99n/100]``), reported as
``Average/P20/P50/P90/p99/Min/Max`` in milliseconds. BASELINE.md adopts this
exact shape for the new framework's latency reporting, so we keep the index
convention bit-for-bit (NOT numpy's interpolated percentile).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Sequence

import numpy as np


@dataclass(frozen=True)
class LatencySummary:
    """All values in milliseconds; count is the sample count."""

    count: int
    avg_ms: float
    p20_ms: float
    p50_ms: float
    p90_ms: float
    p99_ms: float
    min_ms: float
    max_ms: float

    def to_dict(self) -> dict:
        return asdict(self)


def _index_percentile(sorted_ms: np.ndarray, p: int) -> float:
    # ssd_test/main.go:157-163 convention: sorted[p*n/100], clamped to n-1 so
    # p=100-ish indices on tiny samples stay in range.
    n = len(sorted_ms)
    idx = min((p * n) // 100, n - 1)
    return float(sorted_ms[idx])


def summarize(latencies_ms: Sequence[float] | np.ndarray) -> LatencySummary:
    arr = np.asarray(latencies_ms, dtype=np.float64)
    if arr.size == 0:
        raise ValueError("summarize() needs at least one sample")
    s = np.sort(arr)
    return LatencySummary(
        count=int(s.size),
        avg_ms=float(s.mean()),
        p20_ms=_index_percentile(s, 20),
        p50_ms=_index_percentile(s, 50),
        p90_ms=_index_percentile(s, 90),
        p99_ms=_index_percentile(s, 99),
        min_ms=float(s[0]),
        max_ms=float(s[-1]),
    )


def summarize_ns(latencies_ns: Sequence[int] | np.ndarray) -> LatencySummary:
    return summarize(np.asarray(latencies_ns, dtype=np.float64) / 1e6)


# The ssd_test percentile block's field order (``ssd_test/main.go:157-163``)
# — ONE definition shared by format_summary and the offline ``tpubench
# report`` renderer so the two can't drift.
PCT_FIELDS = (
    ("Avg", "avg_ms"),
    ("P20", "p20_ms"),
    ("P50", "p50_ms"),
    ("P90", "p90_ms"),
    ("p99", "p99_ms"),
    ("Min", "min_ms"),
    ("Max", "max_ms"),
)


def format_summary(label: str, s: LatencySummary) -> str:
    """Human block in the ssd_test stdout shape (``ssd_test/main.go:157-163``)."""
    lines = [f"[{label}] n={s.count}"]
    for head, key in PCT_FIELDS:
        name = "Average" if head == "Avg" else head  # reference stdout label
        lines.append(f"{name}: {getattr(s, key):.3f} ms")
    return "\n".join(lines)
