"""Latency percentile math and report formatting.

Reproduces the reference ssd_test driver's in-process percentile block —
the only place the reference computes statistics itself
(``benchmark-script/ssd_test/main.go:144-163``): sort ascending, then
index-based percentiles ``sorted[p*n/100]`` (p50 = ``sorted[n/2]``,
p99 = ``sorted[99n/100]``), reported as
``Average/P20/P50/P90/p99/Min/Max`` in milliseconds. BASELINE.md adopts this
exact shape for the new framework's latency reporting, so we keep the index
convention bit-for-bit (NOT numpy's interpolated percentile).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Sequence

import numpy as np


@dataclass(frozen=True)
class LatencySummary:
    """All values in milliseconds; count is the sample count."""

    count: int
    avg_ms: float
    p20_ms: float
    p50_ms: float
    p90_ms: float
    p99_ms: float
    min_ms: float
    max_ms: float

    def to_dict(self) -> dict:
        return asdict(self)


def _index_percentile(sorted_ms: np.ndarray, p: int) -> float:
    # ssd_test/main.go:157-163 convention: sorted[p*n/100], clamped to n-1 so
    # p=100-ish indices on tiny samples stay in range. The array must be
    # sorted — or np.partition'ed at this index, which places the same
    # order statistic there.
    n = len(sorted_ms)
    idx = min((p * n) // 100, n - 1)
    return float(sorted_ms[idx])


# The ssd_test percentile points summarize() extracts.
_PCT_POINTS = (20, 50, 90, 99)


def summarize(latencies_ms: Sequence[float] | np.ndarray) -> LatencySummary:
    arr = np.asarray(latencies_ms, dtype=np.float64)
    if arr.size == 0:
        raise ValueError("summarize() needs at least one sample")
    n = arr.size
    # Index-based selection via ONE np.partition over all four order
    # statistics — O(n) where the previous full np.sort paid O(n log n)
    # on every multi-million-sample journal summary. A partitioned array
    # holds the exact order statistic at every partition index, so
    # _index_percentile (the ONE home of the ssd_test index convention)
    # reads the same sorted[p*n//100] value bit-for-bit — regression-
    # pinned against a sorted reference in test_metrics.py.
    idxs = sorted({min((p * n) // 100, n - 1) for p in _PCT_POINTS})
    part = np.partition(arr, idxs)
    pcts = {p: _index_percentile(part, p) for p in _PCT_POINTS}
    return LatencySummary(
        count=int(n),
        avg_ms=float(arr.mean()),
        p20_ms=pcts[20],
        p50_ms=pcts[50],
        p90_ms=pcts[90],
        p99_ms=pcts[99],
        min_ms=float(arr.min()),
        max_ms=float(arr.max()),
    )


def summarize_ns(latencies_ns: Sequence[int] | np.ndarray) -> LatencySummary:
    return summarize(np.asarray(latencies_ns, dtype=np.float64) / 1e6)


# The ssd_test percentile block's field order (``ssd_test/main.go:157-163``)
# — ONE definition shared by format_summary and the offline ``tpubench
# report`` renderer so the two can't drift.
PCT_FIELDS = (
    ("Avg", "avg_ms"),
    ("P20", "p20_ms"),
    ("P50", "p50_ms"),
    ("P90", "p90_ms"),
    ("p99", "p99_ms"),
    ("Min", "min_ms"),
    ("Max", "max_ms"),
)


def format_summary(label: str, s: LatencySummary) -> str:
    """Human block in the ssd_test stdout shape (``ssd_test/main.go:157-163``)."""
    lines = [f"[{label}] n={s.count}"]
    for head, key in PCT_FIELDS:
        name = "Average" if head == "Avg" else head  # reference stdout label
        lines.append(f"{name}: {getattr(s, key):.3f} ms")
    return "\n".join(lines)
