"""Race-free measurement primitives.

The reference appends per-read latencies from all goroutines into one shared
slice with no synchronization — an actual data race
(``ssd_test/main.go:80``, SURVEY §2.2 #15). Here each worker owns a private
:class:`LatencyRecorder`; arrays are merged only after the workers join, so
there is no shared mutable state in the hot loop by construction.
"""

from __future__ import annotations

import time
from array import array
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np

from tpubench.metrics.percentiles import LatencySummary, summarize_ns


class LatencyRecorder:
    """One per worker. Appends int nanoseconds; no locking needed."""

    __slots__ = ("name", "_ns")

    def __init__(self, name: str = ""):
        self.name = name
        self._ns = array("q")

    def record_ns(self, ns: int) -> None:
        self._ns.append(ns)

    def record_s(self, seconds: float) -> None:
        self._ns.append(int(seconds * 1e9))

    def time(self) -> "_Timer":
        return _Timer(self)

    def __len__(self) -> int:
        return len(self._ns)

    def as_ns_array(self) -> np.ndarray:
        return np.frombuffer(self._ns, dtype=np.int64).copy() if self._ns else np.empty(0, np.int64)

    def snapshot_ns(self) -> np.ndarray:
        """Mid-run-safe copy for the periodic exporter: ``tolist()`` never
        exports the array's buffer, so the owning worker's concurrent
        ``append`` cannot hit BufferError-on-resize (which a ``frombuffer``
        view would cause). Items appended during the copy may or may not be
        included — fine for an in-flight flush."""
        return np.array(self._ns.tolist(), dtype=np.int64)

    def snapshot_tail_ns(self, start: int) -> tuple[np.ndarray, int]:
        """Mid-run-safe copy of samples [start:len) plus the new consumed
        offset — the periodic exporter's incremental read, O(new samples)
        instead of O(all samples) per flush. Array slicing copies in C
        without exporting the buffer, so concurrent appends stay safe."""
        end = len(self._ns)
        if end <= start:
            return np.empty(0, np.int64), start
        return np.array(self._ns[start:end].tolist(), dtype=np.int64), end

    def extend_ns(self, values: Iterable[int]) -> None:
        self._ns.extend(int(v) for v in values)

    def summarize(self) -> LatencySummary:
        return summarize_ns(self.as_ns_array())


class _Timer:
    __slots__ = ("_rec", "_t0")

    def __init__(self, rec: LatencyRecorder):
        self._rec = rec
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._rec.record_ns(time.perf_counter_ns() - self._t0)
        return False


def merge_recorders(recorders: Iterable[LatencyRecorder]) -> np.ndarray:
    """Post-join merge of per-worker arrays (the fix for ssd_test's race)."""
    arrays = [r.as_ns_array() for r in recorders]
    arrays = [a for a in arrays if a.size]
    if not arrays:
        return np.empty(0, np.int64)
    return np.concatenate(arrays)


class ByteCounter:
    """Bytes-ingested counter + wall-clock window → GB/s accounting."""

    __slots__ = ("bytes", "_t0", "_t1")

    def __init__(self):
        self.bytes = 0
        self._t0 = None
        self._t1 = None

    def start(self) -> None:
        self._t0 = time.perf_counter_ns()

    def add(self, n: int) -> None:
        self.bytes += n

    def stop(self) -> None:
        self._t1 = time.perf_counter_ns()

    @property
    def seconds(self) -> float:
        if self._t0 is None:
            return 0.0
        t1 = self._t1 if self._t1 is not None else time.perf_counter_ns()
        return (t1 - self._t0) / 1e9

    def gbps(self) -> float:
        """Gigabytes (1e9) per second over the started window."""
        sec = self.seconds
        return (self.bytes / 1e9) / sec if sec > 0 else 0.0


@dataclass
class MetricSet:
    """The framework's first-class measures (SURVEY §5.5 north star).

    Reference has a single measure ``readLatency`` ms
    (``metrics_exporter.go:17``); we add bytes-ingested, GB/s/chip, first-byte
    and stage (HBM-landing) latency histograms.
    """

    read_latency: list[LatencyRecorder] = field(default_factory=list)
    first_byte_latency: list[LatencyRecorder] = field(default_factory=list)
    stage_latency: list[LatencyRecorder] = field(default_factory=list)
    gather_latency: list[LatencyRecorder] = field(default_factory=list)
    ingest: ByteCounter = field(default_factory=ByteCounter)

    def new_worker(self, name: str) -> tuple[LatencyRecorder, LatencyRecorder]:
        """Returns (read, first_byte) recorders owned by one worker."""
        r = LatencyRecorder(f"{name}/read")
        fb = LatencyRecorder(f"{name}/first_byte")
        self.read_latency.append(r)
        self.first_byte_latency.append(fb)
        return r, fb

    def new_stage_recorder(self, name: str) -> LatencyRecorder:
        rec = LatencyRecorder(f"{name}/stage")
        self.stage_latency.append(rec)
        return rec

    def summaries(self) -> dict[str, LatencySummary]:
        out = {}
        for key, recs in (
            ("read", self.read_latency),
            ("first_byte", self.first_byte_latency),
            ("stage", self.stage_latency),
            ("gather", self.gather_latency),
        ):
            merged = merge_recorders(recs)
            if merged.size:
                out[key] = summarize_ns(merged)
        return out
