"""First-class result files.

The reference's primary output channel is stdout redirected by shell
(``execute_pb.sh:4``) plus Cloud Monitoring dashboards. Here every run writes
a structured JSON result (SURVEY §3.5 prescription) and prints the ssd_test
percentile block for humans.
"""

from __future__ import annotations

import json
import os
import platform
import time
from dataclasses import dataclass, field
from typing import Any

from tpubench.metrics.percentiles import LatencySummary, format_summary


@dataclass
class RunResult:
    workload: str
    config: dict[str, Any]
    bytes_total: int = 0
    wall_seconds: float = 0.0
    gbps: float = 0.0
    gbps_per_chip: float = 0.0
    n_chips: int = 1
    summaries: dict[str, LatencySummary] = field(default_factory=dict)
    extra: dict[str, Any] = field(default_factory=dict)
    errors: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "workload": self.workload,
            "timestamp": time.time(),
            "host": platform.node(),
            "config": self.config,
            "bytes_total": self.bytes_total,
            "wall_seconds": self.wall_seconds,
            "gbps": self.gbps,
            "gbps_per_chip": self.gbps_per_chip,
            "n_chips": self.n_chips,
            "errors": self.errors,
            "summaries": {k: s.to_dict() for k, s in self.summaries.items()},
            "extra": self.extra,
        }

    def format(self) -> str:
        lines = [
            f"== tpubench {self.workload} ==",
            f"bytes={self.bytes_total} wall={self.wall_seconds:.3f}s "
            f"GB/s={self.gbps:.3f} GB/s/chip={self.gbps_per_chip:.3f} "
            f"chips={self.n_chips} errors={self.errors}",
        ]
        for key, s in self.summaries.items():
            lines.append(format_summary(key, s))
        return "\n".join(lines)


def upload_result(cfg, path: str, backend=None) -> str:
    """Push one result JSON to ``cfg.obs.results_bucket`` over the run's own
    storage protocol — the ``gsutil cp`` step of the reference's experiment
    loop (execute_pb.sh:5) as a first-class framework capability. Returns
    the uploaded object name."""
    from tpubench.storage import open_backend

    owns = backend is None
    if backend is None:
        proto = cfg.transport.protocol
        if proto not in ("http", "grpc"):
            # 'local' would ignore the bucket (it roots at workload.dir) and
            # 'fake' would drop the bytes in a throwaway in-process store —
            # either way "uploaded" would be a lie. Fail loudly instead.
            raise ValueError(
                f"results_bucket requires an object-store protocol "
                f"(http|grpc), not {proto!r}"
            )
        up_cfg = type(cfg).from_dict(cfg.to_dict())
        up_cfg.workload.bucket = cfg.obs.results_bucket
        backend = open_backend(up_cfg)
    try:
        name = f"results/{os.path.basename(path)}"
        with open(path, "rb") as f:
            backend.write(name, f.read())
        return name
    finally:
        if owns:
            backend.close()


def write_result(result: RunResult, results_dir: str, tag: str = "") -> str:
    os.makedirs(results_dir, exist_ok=True)
    fname = f"{result.workload}_{tag + '_' if tag else ''}{int(time.time() * 1000)}.json"
    path = os.path.join(results_dir, fname)
    with open(path, "w") as f:
        json.dump(result.to_dict(), f, indent=2)
    return path
