"""ctypes bindings for the native data-path engine (SURVEY §2.5).

Build-on-first-import with mtime caching: ``engine.cc`` → ``libtpubench.so``
via g++ (no pybind11 in this image; the C ABI + ctypes keeps the boundary
thin and releases the GIL for every blocking call). If the toolchain is
unavailable the import still succeeds and ``available()`` returns False —
pure-Python fallbacks keep the framework functional, just slower.
"""

from tpubench.native.build import build_library, library_path  # noqa: F401
from tpubench.native.engine import (  # noqa: F401
    AlignedBuffer,
    NativeEngine,
    NativeError,
    get_engine,
)
