"""Compile the native engine, cached by source mtime — plus the
sanitizer matrix for the stress harness.

The engine's only correctness net used to be TSAN; the matrix adds
ASAN (heap errors + leak checking on the destroy-hammer path) and
UBSAN (UB trapped, not recovered) builds of engine.cc+stress.cc, all
driven by the same stress phases (per-thread arrays, fetch pool,
srv/discard, reactor exactly-once, stale churn, destroy hammer).
``build_stress`` raises :class:`SanitizerUnavailable` when the
compiler lacks a sanitizer runtime so CI skips gracefully instead of
failing the build."""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "engine.cc")
_STRESS = os.path.join(_DIR, "stress.cc")
_LIB = os.path.join(_DIR, "libtpubench.so")
_lock = threading.Lock()

# sanitizer name -> (compile flags, runtime env). halt_on_error +
# exitcode=66 everywhere: a finding is a hard failure, never a warning
# scrolled past. ASAN runs with leak detection ON — the destroy-hammer
# phase is exactly where an engine teardown leak would hide; UBSAN
# compiles with -fno-sanitize-recover so UB traps instead of logging.
SANITIZERS: dict[str, tuple[list[str], dict[str, str]]] = {
    "thread": (
        ["-fsanitize=thread"],
        {"TSAN_OPTIONS": "halt_on_error=1 exitcode=66"},
    ),
    "address": (
        ["-fsanitize=address", "-fno-omit-frame-pointer"],
        {"ASAN_OPTIONS": "detect_leaks=1:halt_on_error=1:exitcode=66"},
    ),
    "undefined": (
        ["-fsanitize=undefined", "-fno-sanitize-recover=all"],
        {"UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1:"
                          "exitcode=66"},
    ),
}

# stderr markers that mean "a sanitizer spoke" — asserted absent even
# when the exit code lies (forked children, _exit paths).
SANITIZER_FINDING_MARKERS = (
    "WARNING: ThreadSanitizer",
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "runtime error:",
)


class SanitizerUnavailable(RuntimeError):
    """The toolchain cannot build/link this sanitizer — a skip, not a
    failure (containers often ship g++ without every libsan)."""


def sanitizer_env(sanitizer: str) -> dict[str, str]:
    return dict(SANITIZERS[sanitizer][1])


def build_stress(sanitizer: str, out_path: str) -> str:
    """Build engine.cc+stress.cc under ``sanitizer`` at ``out_path``.

    Raises :class:`SanitizerUnavailable` when the compile/link failure
    names the sanitizer runtime, ``CalledProcessError`` on a genuine
    source build break (that one must fail the test)."""
    flags, _env = SANITIZERS[sanitizer]
    cmd = [
        "g++", "-O1", "-g", "-std=c++17", *flags,
        _SRC, _STRESS,
        # -ldl matches build_library: engine.cc dlopens OpenSSL at
        # first use.
        "-o", out_path, "-lpthread", "-ldl",
    ]
    cp = subprocess.run(cmd, capture_output=True, text=True)
    if cp.returncode != 0:
        err = (cp.stderr or "").lower()
        if any(tok in err for tok in ("sanitize", "asan", "tsan", "ubsan",
                                      "libtsan", "libasan", "libubsan")):
            raise SanitizerUnavailable(
                f"{sanitizer}: {cp.stderr.strip()[-200:]}"
            )
        raise subprocess.CalledProcessError(
            cp.returncode, cmd, cp.stdout, cp.stderr
        )
    return out_path


def library_path() -> str:
    return _LIB


def build_library(force: bool = False) -> str:
    """Returns the .so path; raises on compile failure."""
    with _lock:
        if (
            not force
            and os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        ):
            return _LIB
        cmd = [
            "g++",
            "-O3",
            "-std=c++17",
            "-shared",
            "-fPIC",
            "-pthread",
            "-Wall",
            "-o",
            _LIB + ".tmp",
            _SRC,
            "-ldl",  # TLS loader: dlopen(libssl) at first use
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(_LIB + ".tmp", _LIB)
        return _LIB
