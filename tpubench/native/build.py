"""Compile the native engine, cached by source mtime."""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "engine.cc")
_LIB = os.path.join(_DIR, "libtpubench.so")
_lock = threading.Lock()


def library_path() -> str:
    return _LIB


def build_library(force: bool = False) -> str:
    """Returns the .so path; raises on compile failure."""
    with _lock:
        if (
            not force
            and os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        ):
            return _LIB
        cmd = [
            "g++",
            "-O3",
            "-std=c++17",
            "-shared",
            "-fPIC",
            "-pthread",
            "-Wall",
            "-o",
            _LIB + ".tmp",
            _SRC,
            "-ldl",  # TLS loader: dlopen(libssl) at first use
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(_LIB + ".tmp", _LIB)
        return _LIB
