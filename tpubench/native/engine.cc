// tpubench native data-path engine.
//
// The reference's entire data path is native (Go compiles to machine code);
// SURVEY §2.5 ledgers the components that must therefore be native here:
//
//   1. O_DIRECT aligned block I/O (reference: read_operation/main.go:34,
//      write_operations/main.go:36, ssd_test/main.go:42 — Go got alignment
//      only incidentally; we handle it explicitly).
//   2. Per-op high-resolution timing in the hot loop, written into
//      caller-owned (per-thread) latency arrays — fixing the reference's
//      shared-slice data race (ssd_test/main.go:80).
//   3. fsync-per-block durable write path (write_operations/main.go:63-71).
//   4. A streaming HTTP/1.1 receive path that lands response bodies directly
//      in pre-registered buffers (reference granule loop main.go:125,140),
//      with a first-byte timestamp the Go code never measured.
//
// Plain C ABI; Python binds via ctypes (no pybind11 in this image). All
// blocking calls run without the GIL (ctypes releases it), so Python worker
// threads get real I/O concurrency.

#include <cctype>
#include <cerrno>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

#include <arpa/inet.h>
#include <dlfcn.h>
#include <fcntl.h>
#include <poll.h>
#include <pthread.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include "hpack_huffman.h"

extern "C" {

// ----------------------------------------------------------------- clock --
int64_t tb_now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

// --------------------------------------------------- transport counters --
// tb_stats_*: engine-wide transport state that was previously invisible
// from Python — bytes on the wire, h2 frame/flow-control activity, recv
// wait time, connects/handshakes. Process-cumulative, atomically updated
// (relaxed: they are monotone counters, not synchronization); callers
// (the flight recorder) diff two snapshots to scope a run. The counter
// NAMES are API (Python builds its dict from tb_stats_name); indices are
// not — always resolve by name.
enum {
  TB_STAT_BYTES_TX = 0,       // payload bytes handed to send/SSL_write
  TB_STAT_BYTES_RX,           // payload bytes returned by recv/SSL_read
  TB_STAT_RECV_WAIT_NS,       // wall time blocked inside recv/SSL_read —
                              // the receive-side stall (peer/flow-control
                              // starvation shows up here)
  TB_STAT_CONNECTS,           // tb_http_connect successes (TCP connects)
  TB_STAT_TLS_HANDSHAKES,     // completed TLS handshakes
  TB_STAT_CONN_CLOSES,        // tb_conn handles closed
  TB_STAT_H2_FRAMES_RX,       // h2 frames consumed by the poll loop
  TB_STAT_H2_DATA_BYTES_RX,   // DATA frame payload bytes (incl. padding)
  TB_STAT_H2_WINDOW_UPDATES_TX,  // flow-control credit frames sent
  TB_STAT_H2_STREAMS_OPENED,  // streams submitted (gRPC + raw GET)
  TB_STAT_H2_RST_RX,          // RST_STREAM frames received
  TB_STAT_H2_GOAWAY_RX,       // GOAWAY frames received
  // Fetch-executor completion-queue handoff (BENCH_r05 attributed the
  // native executor's deficit to per-completion queue crossings):
  TB_STAT_POOL_WAKES,          // consumer wakes that returned >=1 completion
  TB_STAT_POOL_COMPLETIONS,    // completions delivered across all wakes —
                               // completions/wakes is the batching ratio
  TB_STAT_POOL_BATCHED_WAKES,  // wakes that drained >1 completion in one
                               // lock crossing (tb_pool_next_batch)
  // Reactor-mode executor (tb_pool_create2 mode=reactor): the epoll loop
  // and the lock-free completion-ring handoff, counted so the three-arm
  // A/B's verdict is attributable to the dispatch path, not asserted.
  TB_STAT_REACTOR_LOOPS,       // epoll_wait iterations across all loops
  TB_STAT_REACTOR_EPOLL_EVENTS,  // epoll events delivered — events/loops
                                 // is the per-iteration batching of I/O
  TB_STAT_REACTOR_COMPLETIONS,   // completions enqueued to SPSC rings
  TB_STAT_REACTOR_DOORBELL_WAKES,  // eventfd doorbells rung (only on a
                                   // ring's empty→nonempty transition —
                                   // steady-state backlog rings none)
  TB_STAT_REACTOR_RING_DEPTH_SUM,  // ring depth observed at each enqueue,
                                   // summed — mean depth = sum/completions
  TB_STAT_REACTOR_RING_DEPTH_MAX,  // max ring depth observed (per reset)
  // Reactor TLS/h2 (the nonblocking transport state machines):
  TB_STAT_REACTOR_TLS_HANDSHAKES,  // handshakes completed by the epoll-
                                   // driven WANT_READ/WANT_WRITE machine
  TB_STAT_REACTOR_TLS_RESUMES,     // handshakes that resumed a cached
                                   // session (keep-alive reconnect hits)
  TB_STAT_REACTOR_H2_STREAMS,      // h2 streams opened by the reactor
                                   // (many per connection — the FIFO's
                                   // in-flight dimension)
  TB_STAT_REACTOR_FLOW_STALL_NS,   // ns flow-control credit (WINDOW_
                                   // UPDATE) sat queued before reaching
                                   // the wire — credit-return latency
  TB_STAT_COUNT
};
static int64_t tb_stats_v[TB_STAT_COUNT];
static const char* const tb_stats_names[TB_STAT_COUNT] = {
    "bytes_tx",
    "bytes_rx",
    "recv_wait_ns",
    "connects",
    "tls_handshakes",
    "conn_closes",
    "h2_frames_rx",
    "h2_data_bytes_rx",
    "h2_window_updates_tx",
    "h2_streams_opened",
    "h2_rst_rx",
    "h2_goaway_rx",
    "pool_wakes",
    "pool_completions",
    "pool_batched_wakes",
    "reactor_loops",
    "reactor_epoll_events",
    "reactor_completions",
    "reactor_doorbell_wakes",
    "reactor_ring_depth_sum",
    "reactor_ring_depth_max",
    "reactor_tls_handshakes",
    "reactor_tls_resumes",
    "reactor_h2_streams",
    "reactor_flow_stall_ns",
};

static inline void tb_stat_add(int idx, int64_t v) {
  __atomic_fetch_add(&tb_stats_v[idx], v, __ATOMIC_RELAXED);
}

static inline void tb_stat_max(int idx, int64_t v) {
  int64_t cur = __atomic_load_n(&tb_stats_v[idx], __ATOMIC_RELAXED);
  while (cur < v &&
         !__atomic_compare_exchange_n(&tb_stats_v[idx], &cur, v, true,
                                      __ATOMIC_RELAXED, __ATOMIC_RELAXED)) {
  }
}

int tb_stats_count() { return TB_STAT_COUNT; }

const char* tb_stats_name(int i) {
  return (i >= 0 && i < TB_STAT_COUNT) ? tb_stats_names[i] : "";
}

int tb_stats_read(int64_t* out, int cap) {
  int n = cap < TB_STAT_COUNT ? cap : TB_STAT_COUNT;
  for (int i = 0; i < n; i++)
    out[i] = __atomic_load_n(&tb_stats_v[i], __ATOMIC_RELAXED);
  return n;
}

void tb_stats_reset() {
  for (int i = 0; i < TB_STAT_COUNT; i++)
    __atomic_store_n(&tb_stats_v[i], 0, __ATOMIC_RELAXED);
}

// --------------------------------------------------------------- buffers --
// Aligned allocation: O_DIRECT requires buffer, offset and length aligned to
// the logical block size (typically 512; 4096 is safe for both).
void* tb_alloc_aligned(size_t size, size_t align) {
  void* p = nullptr;
  if (posix_memalign(&p, align, size) != 0) return nullptr;
  return p;
}

void tb_free_aligned(void* p) { free(p); }

// ---------------------------------------------------------------- dlpack --
// DLPack producer over engine-owned aligned buffers (SURVEY §2.5.4: expose
// pinned host buffers to JAX/numpy with no Python-held copy). Minimal stable
// ABI structs (dlpack.h v0.8 layout); the tensor does NOT own the bytes —
// buffer lifetime stays with the AlignedBuffer, the deleter frees only the
// descriptor. kDLCPU = 1, uint dtype code = 1.
struct TbDLDevice { int32_t device_type; int32_t device_id; };
struct TbDLDataType { uint8_t code; uint8_t bits; uint16_t lanes; };
struct TbDLTensor {
  void* data;
  TbDLDevice device;
  int32_t ndim;
  TbDLDataType dtype;
  int64_t* shape;
  int64_t* strides;
  uint64_t byte_offset;
};
struct TbDLManagedTensor {
  TbDLTensor dl_tensor;
  void* manager_ctx;
  void (*deleter)(TbDLManagedTensor*);
};

static void tb_dlpack_deleter(TbDLManagedTensor* t) {
  if (!t) return;
  free(t->dl_tensor.shape);  // strides allocated in the same block
  free(t);
}

// 2-D row-major uint8 tensor (rows, cols) viewing `data`. Returns an opaque
// DLManagedTensor* for Python to wrap in a "dltensor" PyCapsule. `deleter`
// (optional) overrides the default descriptor-free — the Python side passes
// a ctypes callback here so the consumer's deleter call also un-pins the
// producer buffer (DLPack contract: the managed tensor keeps data alive).
void* tb_dlpack_create(void* data, int64_t rows, int64_t cols,
                       void (*deleter)(TbDLManagedTensor*)) {
  if (!data || rows <= 0 || cols <= 0) return nullptr;
  TbDLManagedTensor* t =
      static_cast<TbDLManagedTensor*>(calloc(1, sizeof(TbDLManagedTensor)));
  if (!t) return nullptr;
  int64_t* dims = static_cast<int64_t*>(malloc(4 * sizeof(int64_t)));
  if (!dims) {
    free(t);
    return nullptr;
  }
  dims[0] = rows;
  dims[1] = cols;
  dims[2] = cols;  // strides (elements): row-major contiguous
  dims[3] = 1;
  t->dl_tensor.data = data;
  t->dl_tensor.device.device_type = 1;  // kDLCPU
  t->dl_tensor.device.device_id = 0;
  t->dl_tensor.ndim = 2;
  t->dl_tensor.dtype.code = 1;  // kDLUInt
  t->dl_tensor.dtype.bits = 8;
  t->dl_tensor.dtype.lanes = 1;
  t->dl_tensor.shape = dims;
  t->dl_tensor.strides = dims + 2;
  t->dl_tensor.byte_offset = 0;
  t->manager_ctx = nullptr;
  t->deleter = deleter ? deleter : tb_dlpack_deleter;
  return t;
}

// Invokes the tensor's registered deleter (unconsumed-capsule destructor
// path; consumers call t->deleter themselves).
void tb_dlpack_free(void* managed) {
  TbDLManagedTensor* t = static_cast<TbDLManagedTensor*>(managed);
  if (t && t->deleter) t->deleter(t);
}

// Descriptor-only free, for custom deleters to delegate to.
void tb_dlpack_free_descriptor(void* managed) {
  tb_dlpack_deleter(static_cast<TbDLManagedTensor*>(managed));
}

// ------------------------------------------------------------------ open --
// flags: bit0 write (else read), bit1 create+trunc, bit2 O_DIRECT wanted.
// Returns fd >= 0; *direct_applied set to 1 if O_DIRECT actually engaged
// (tmpfs and some FUSE configs reject it — we fall back and report, rather
// than failing the benchmark).
int tb_open(const char* path, int flags, int* direct_applied) {
  int oflags = (flags & 1) ? O_WRONLY : O_RDONLY;
  if (flags & 2) oflags |= O_CREAT | O_TRUNC;
  int want_direct = (flags & 4) ? 1 : 0;
  if (direct_applied) *direct_applied = 0;
#ifdef O_DIRECT
  if (want_direct) {
    int fd = open(path, oflags | O_DIRECT, 0644);
    if (fd >= 0) {
      if (direct_applied) *direct_applied = 1;
      return fd;
    }
    if (errno != EINVAL && errno != ENOTSUP && errno != EOPNOTSUPP)
      return -errno;
  }
#endif
  int fd = open(path, oflags, 0644);
  return fd >= 0 ? fd : -errno;
}

int tb_close(int fd) { return close(fd) == 0 ? 0 : -errno; }

int64_t tb_file_size(const char* path) {
  struct stat st;
  if (stat(path, &st) != 0) return -errno;
  return static_cast<int64_t>(st.st_size);
}

// ----------------------------------------------------------- block reads --
// The ssd_test hot loop (ssd_test/main.go:65-89): for each offset, one timed
// pread of block_size bytes into `buf`. Latencies (ns) land in lat_ns[i] —
// the caller passes a private per-thread array, so there is no shared
// mutable state (the reference raced here). Returns total bytes read, or
// -errno on the first failure.
int64_t tb_pread_blocks(int fd, void* buf, int64_t block_size,
                        const int64_t* offsets, int64_t n, int64_t* lat_ns) {
  int64_t total = 0;
  char* p = static_cast<char*>(buf);
  for (int64_t i = 0; i < n; i++) {
    int64_t t0 = tb_now_ns();
    int64_t got = 0;
    while (got < block_size) {
      ssize_t k = pread(fd, p + got, block_size - got, offsets[i] + got);
      if (k < 0) {
        if (errno == EINTR) continue;
        return -errno;
      }
      if (k == 0) break;  // EOF: short final block is legal
      got += k;
    }
    if (lat_ns) lat_ns[i] = tb_now_ns() - t0;
    total += got;
  }
  return total;
}

// Sequential whole-file streaming (read_operation/main.go:45-53 semantics,
// minus its re-read-at-EOF bug: we always pread from explicit offsets).
// Repeat passes re-read from offset 0 deliberately (SURVEY §3.3 note).
int64_t tb_read_file_seq(int fd, void* buf, int64_t buf_size, int64_t passes,
                         int64_t* pass_lat_ns) {
  int64_t total = 0;
  char* p = static_cast<char*>(buf);
  for (int64_t pass = 0; pass < passes; pass++) {
    int64_t t0 = tb_now_ns();
    int64_t off = 0;
    for (;;) {
      ssize_t k = pread(fd, p, buf_size, off);
      if (k < 0) {
        if (errno == EINTR) continue;
        return -errno;
      }
      if (k == 0) break;
      off += k;
      total += k;
    }
    if (pass_lat_ns) pass_lat_ns[pass] = tb_now_ns() - t0;
  }
  return total;
}

// ----------------------------------------------------------- block writes --
// write_operations/main.go:46-76 semantics: per block seek+write and
// (optionally) fsync-per-block. Data comes from the caller-filled buffer.
// Latency per block includes the fsync when enabled (that IS the measured
// durable-write cost). Returns total bytes written or -errno.
int64_t tb_pwrite_blocks(int fd, const void* buf, int64_t block_size,
                         const int64_t* offsets, int64_t n, int fsync_each,
                         int64_t* lat_ns) {
  int64_t total = 0;
  const char* p = static_cast<const char*>(buf);
  for (int64_t i = 0; i < n; i++) {
    int64_t t0 = tb_now_ns();
    int64_t put = 0;
    while (put < block_size) {
      ssize_t k = pwrite(fd, p + put, block_size - put, offsets[i] + put);
      if (k < 0) {
        if (errno == EINTR) continue;
        return -errno;
      }
      put += k;
    }
    if (fsync_each && fsync(fd) != 0) return -errno;
    if (lat_ns) lat_ns[i] = tb_now_ns() - t0;
    total += put;
  }
  return total;
}

// xorshift64* fill — fast deterministic "random" payload for write benches
// (reference uses crypto/rand per block, write_operations/main.go:46; the
// bench measures the I/O path, not the RNG, so a cheap PRNG is the right
// trade and is reproducible).
void tb_fill_random(void* buf, int64_t n, uint64_t seed) {
  uint64_t x = seed ? seed : 0x9E3779B97F4A7C15ULL;
  uint64_t* p64 = static_cast<uint64_t*>(buf);
  int64_t words = n / 8;
  for (int64_t i = 0; i < words; i++) {
    x ^= x >> 12;
    x ^= x << 25;
    x ^= x >> 27;
    p64[i] = x * 0x2545F4914F6CDD1DULL;
  }
  char* tail = static_cast<char*>(buf) + words * 8;
  for (int64_t i = 0; i < n % 8; i++) tail[i] = static_cast<char>(x >> (8 * i));
}

// ------------------------------------------------------- HTTP/1.1 client --
// Minimal plain-TCP GET: connect, send request, parse headers, stream the
// body into the caller's pre-registered buffer. Out-params: HTTP status,
// first-byte timestamp (ns, CLOCK_MONOTONIC — comparable with tb_now_ns),
// and total body bytes. Supports Content-Length bodies (what the fake GCS
// server and GCS JSON media GETs produce). Returns body length, or -errno /
// -1000-series protocol errors.
//
// TLS is supported through the tb_conn layer below (dlopen'd OpenSSL), so
// the same receive loop can face both localhost plaintext servers and real
// https endpoints (SURVEY hard-part (b)).
// Error-code contract with the Python layer (gcs_http classifies
// transient-vs-permanent on these codes, NOT on message text): -1001/-1002
// are protocol-shape failures (permanent — retrying the same request against
// the same server yields the same malformed/oversized response); -1003/-1004
// are network-condition failures (transient, like plain -errno socket
// errors).
enum {
  TB_EPROTO = -1001,    // malformed response [permanent]
  TB_ETOOBIG = -1002,   // body exceeds buffer [permanent]
  TB_ERESOLVE = -1003,  // getaddrinfo failure [transient]
  TB_ESHORT = -1004,    // peer closed before the response was complete
                        // (mid-headers or body short of Content-Length)
                        // [transient]
  TB_ECHUNKED = -1005,  // Transfer-Encoding: chunked — unsupported here;
                        // rejected loudly instead of returning chunk
                        // framing as body bytes [permanent]
  TB_ETLS = -1006,      // TLS unavailable / handshake or verification
                        // failure — reproduces against the same endpoint
                        // and trust config [permanent]
  TB_EGRPC = -1007,     // RPC finished with a nonzero grpc-status; the
                        // status lands in grpc_status_out and the caller
                        // classifies on it (NOT_FOUND permanent,
                        // UNAVAILABLE transient, …)
};

// Connect a TCP socket for HTTP use (TCP_NODELAY). Returns fd >= 0, or
// TB_ERESOLVE / -errno.
int tb_http_connect(const char* host, int port) {
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  struct addrinfo hints, *res = nullptr;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host, portstr, &hints, &res) != 0) return TB_ERESOLVE;
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return -ECONNREFUSED;
  tb_stat_add(TB_STAT_CONNECTS, 1);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  // Bounded blocking I/O (the Python pool uses timeout=60 — same here):
  // a hung peer surfaces as -EAGAIN (classified transient, retried under
  // policy) instead of stalling a worker thread forever.
  struct timeval tv;
  tv.tv_sec = 60;
  tv.tv_usec = 0;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
  return fd;
}

int tb_http_close(int fd) { return close(fd) == 0 ? 0 : -errno; }

// ------------------------------------------------------------------- TLS --
// TLS via dlopen(libssl.so.3 / .so.1.1): the image ships OpenSSL runtime
// libraries but not headers, so the handful of client-side entry points are
// declared here and resolved at first use. The receive loop itself is shared with
// the plaintext path through the tb_conn vtable below — TLS is a transport
// detail, not a second implementation.
namespace tls {
typedef void* (*fn_pv)();
static void* libssl = nullptr;
static void* libcrypto = nullptr;
static void* (*SSL_CTX_new_)(void*) = nullptr;
static void (*SSL_CTX_free_)(void*) = nullptr;
static void* (*TLS_client_method_)() = nullptr;
static int (*SSL_CTX_set_default_verify_paths_)(void*) = nullptr;
static int (*SSL_CTX_load_verify_locations_)(void*, const char*, const char*) =
    nullptr;
static void (*SSL_CTX_set_verify_)(void*, int, void*) = nullptr;
static void* (*SSL_new_)(void*) = nullptr;
static void (*SSL_free_)(void*) = nullptr;
static int (*SSL_set_fd_)(void*, int) = nullptr;
static int (*SSL_connect_)(void*) = nullptr;
static int (*SSL_read_)(void*, void*, int) = nullptr;
static int (*SSL_write_)(void*, const void*, int) = nullptr;
static int (*SSL_shutdown_)(void*) = nullptr;
static int (*SSL_pending_)(void*) = nullptr;
static long (*SSL_ctrl_)(void*, int, long, void*) = nullptr;
static void* (*SSL_get0_param_)(void*) = nullptr;
static int (*SSL_CTX_up_ref_)(void*) = nullptr;
static int (*SSL_set_alpn_protos_)(void*, const unsigned char*, unsigned) =
    nullptr;
static void (*SSL_get0_alpn_selected_)(const void*, const unsigned char**,
                                       unsigned*) = nullptr;
static int (*X509_VERIFY_PARAM_set1_host_)(void*, const char*, size_t) = nullptr;
static int (*X509_VERIFY_PARAM_set1_ip_asc_)(void*, const char*) = nullptr;
// Nonblocking-reactor additions: WANT_READ/WANT_WRITE classification and
// session resumption on keep-alive reconnect.
static int (*SSL_get_error_)(const void*, int) = nullptr;
static int (*SSL_session_reused_)(void*) = nullptr;
static void* (*SSL_get1_session_)(void*) = nullptr;
static int (*SSL_set_session_)(void*, void*) = nullptr;
static void (*SSL_SESSION_free_)(void*) = nullptr;

static bool do_load() {
  // RTLD_GLOBAL so libssl can resolve its libcrypto dependency if the
  // loader brings them in separately.
  // Try 3.x, then 1.1 (every symbol used here exists since 1.0.2),
  // then the unversioned dev symlink.
  libcrypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
  if (!libcrypto) libcrypto = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_GLOBAL);
  if (!libcrypto) libcrypto = dlopen("libcrypto.so", RTLD_NOW | RTLD_GLOBAL);
  libssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
  if (!libssl) libssl = dlopen("libssl.so.1.1", RTLD_NOW | RTLD_GLOBAL);
  if (!libssl) libssl = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
  if (!libssl || !libcrypto) return false;
#define TB_SYM(lib, name)                                       \
  do {                                                          \
    *reinterpret_cast<void**>(&name##_) = dlsym(lib, #name);    \
    if (!name##_) return false;                                 \
  } while (0)
  TB_SYM(libssl, SSL_CTX_new);
  TB_SYM(libssl, SSL_CTX_free);
  TB_SYM(libssl, TLS_client_method);
  TB_SYM(libssl, SSL_CTX_set_default_verify_paths);
  TB_SYM(libssl, SSL_CTX_load_verify_locations);
  TB_SYM(libssl, SSL_CTX_set_verify);
  TB_SYM(libssl, SSL_new);
  TB_SYM(libssl, SSL_free);
  TB_SYM(libssl, SSL_set_fd);
  TB_SYM(libssl, SSL_connect);
  TB_SYM(libssl, SSL_read);
  TB_SYM(libssl, SSL_write);
  TB_SYM(libssl, SSL_shutdown);
  TB_SYM(libssl, SSL_pending);
  TB_SYM(libssl, SSL_ctrl);
  TB_SYM(libssl, SSL_get0_param);
  TB_SYM(libssl, SSL_CTX_up_ref);
  TB_SYM(libssl, SSL_set_alpn_protos);
  TB_SYM(libssl, SSL_get0_alpn_selected);
  TB_SYM(libcrypto, X509_VERIFY_PARAM_set1_host);
  TB_SYM(libcrypto, X509_VERIFY_PARAM_set1_ip_asc);
  TB_SYM(libssl, SSL_get_error);
  TB_SYM(libssl, SSL_session_reused);
  TB_SYM(libssl, SSL_get1_session);
  TB_SYM(libssl, SSL_set_session);
  TB_SYM(libssl, SSL_SESSION_free);
#undef TB_SYM
  return true;
}

static bool load() {
  // C++11 magic-static: exactly one thread runs do_load(), concurrent
  // callers block until the init completes — the global function-pointer
  // stores are fully visible before any caller proceeds (workers hit
  // first-https-use concurrently; an unsynchronized flag would race).
  static bool ok = do_load();
  return ok;
}

// One SSL_CTX per trust configuration, created once and shared by every
// connection: re-parsing the CA bundle / system trust store per connect
// costs tens of ms and would skew exactly the connect/TTFB timings this
// benchmark measures (the Python pool it is A/B'd against also shares one
// ssl.SSLContext). SSL_new up-refs the CTX, so cached entries can live for
// the process lifetime.
struct CtxCacheEntry {
  char cafile[512];
  int insecure;
  void* ctx;
};
static CtxCacheEntry ctx_cache[8];
static int ctx_cache_n = 0;
static pthread_mutex_t ctx_cache_mu = PTHREAD_MUTEX_INITIALIZER;

static void* make_ctx(const char* cafile, int insecure) {
  void* ctx = SSL_CTX_new_(TLS_client_method_());
  if (!ctx) return nullptr;
  if (insecure) {
    SSL_CTX_set_verify_(ctx, 0 /*SSL_VERIFY_NONE*/, nullptr);
  } else {
    SSL_CTX_set_verify_(ctx, 1 /*SSL_VERIFY_PEER*/, nullptr);
    int ok = (cafile && cafile[0])
                 ? SSL_CTX_load_verify_locations_(ctx, cafile, nullptr)
                 : SSL_CTX_set_default_verify_paths_(ctx);
    if (ok != 1) {
      SSL_CTX_free_(ctx);
      return nullptr;
    }
  }
  return ctx;
}

static void* get_ctx(const char* cafile, int insecure) {
  const char* cf = cafile ? cafile : "";
  if (strlen(cf) >= sizeof ctx_cache[0].cafile)
    return make_ctx(cafile, insecure);  // pathological path: uncached
  // The caller always receives an OWNED reference (freed after SSL_new):
  // cache hits up-ref the cached CTX, so the cache's own reference keeps
  // it alive for the process lifetime.
  pthread_mutex_lock(&ctx_cache_mu);
  for (int i = 0; i < ctx_cache_n; i++) {
    if (ctx_cache[i].insecure == insecure &&
        strcmp(ctx_cache[i].cafile, cf) == 0) {
      void* c = ctx_cache[i].ctx;
      SSL_CTX_up_ref_(c);
      pthread_mutex_unlock(&ctx_cache_mu);
      return c;
    }
  }
  void* ctx = make_ctx(cafile, insecure);
  if (ctx && ctx_cache_n < static_cast<int>(sizeof ctx_cache / sizeof ctx_cache[0])) {
    snprintf(ctx_cache[ctx_cache_n].cafile, sizeof ctx_cache[0].cafile, "%s", cf);
    ctx_cache[ctx_cache_n].insecure = insecure;
    ctx_cache[ctx_cache_n].ctx = ctx;
    ctx_cache_n++;
    SSL_CTX_up_ref_(ctx);  // the cache's reference
  }
  pthread_mutex_unlock(&ctx_cache_mu);
  return ctx;
}
}  // namespace tls

int tb_tls_available() { return tls::load() ? 1 : 0; }

// State of one in-progress HTTP/1.1 response (the streaming receive):
// headers parsed by http_begin, body served incrementally by resp_read so
// callers stream socket→destination with no full-body intermediate buffer
// (the reference's hot loop streams through a 2 MB granule, main.go:140 —
// an up-front full-body landing would be a different, worse program).
struct tb_resp {
  int active;       // body not yet fully consumed
  int status;       // HTTP status code
  int http_minor;   // 0 or 1
  int server_close; // server announced Connection: close
  int client_close; // we requested Connection: close
  int junk;         // bytes beyond Content-Length arrived with the headers
  int64_t content_len;  // -1 = close-delimited
  int64_t body_got;
  int64_t first_byte_ns;
  // Body bytes that arrived in the same recv as the headers (bounded by
  // the header scratch size).
  int leftover_off, leftover_len;
  uint8_t leftover[16384];
};

// One h2 stream in flight on a connection (gRPC ReadObject or plain h2
// GET). Slots live in tb_conn's fixed table; id == 0 marks a free slot.
struct h2_stream {
  uint32_t id;       // h2 stream id (odd); 0 = slot free
  uint64_t tag;      // caller correlation id
  int raw_body;      // 1 = plain GET (DATA bytes land in `out` verbatim);
                     // 0 = gRPC (DATA carries length-prefixed messages)
  uint8_t* out;      // caller's destination buffer
  int64_t out_cap;
  int64_t out_len;
  uint8_t* scratch;  // gRPC message reassembly (from the conn's pool)
  size_t msg_len, msg_got, prefix_got;
  uint8_t prefix[5];
  int grpc_status;   // -1 until a trailer carries one
  int http_status;   // -1 until response HEADERS carry :status
  int64_t content_len;  // -1 until response HEADERS carry content-length
  int got_headers;
  int done;          // END_STREAM (or RST_STREAM) seen
  int64_t err;       // terminal per-stream error (0 = none)
  int64_t t_start, first_byte_ns;
  uint64_t unacked;  // consumed DATA not yet returned as stream window
};

static const int kH2MaxStreams = 32;  // concurrent streams per connection
static const size_t kGrpcScratchCap = (2u << 20) + 65536;

// Connection handle: plaintext (ssl == null) or TLS. Returned to Python as
// an opaque int64 (heap pointer); every path through the receive loop goes
// through the conn_* helpers so both transports share one implementation.
struct tb_conn {
  int fd;
  void* ssl;
  // h2 session state: lazily initialized on first gRPC/h2 use; streams on
  // one connection use odd ids 1, 3, 5, … and may be CONCURRENT (the
  // stream table below) — grpc-go multiplexes by default, and that is
  // where a native gRPC receive wins.
  int h2_started;
  uint32_t next_stream;
  h2_stream* streams;  // kH2MaxStreams slots, lazily allocated
  // Free-list of gRPC reassembly scratches: a per-RPC 2 MiB malloc/free
  // would sit inside the timed window of the very path being benchmarked.
  uint8_t* scratch_pool[8];
  int scratch_pool_n;
  // Streaming-GET state (lazily allocated by tb_conn_get_begin, reused
  // across sequential GETs on this connection, freed in tb_conn_close).
  tb_resp* resp;
};

// SSL_read/SSL_write take int lengths: cap chunks well under INT_MAX so
// multi-GiB receive buffers never produce a negative length (the loop in
// request_on just calls again for the rest).
static const size_t kTlsIoCap = size_t{1} << 30;

static ssize_t conn_send(tb_conn* c, const void* p, size_t n) {
  if (!c->ssl) {
    ssize_t k = send(c->fd, p, n, 0);
    if (k > 0) tb_stat_add(TB_STAT_BYTES_TX, k);
    return k;
  }
  if (n > kTlsIoCap) n = kTlsIoCap;
  for (;;) {
    errno = 0;  // stale EINTR from an earlier call must not loop us
    int k = tls::SSL_write_(c->ssl, p, static_cast<int>(n));
    if (k <= 0) {
      if (errno == EINTR) continue;  // interrupted syscall under SSL_write
      errno = ECONNRESET;  // classified transient, like any mid-stream break
      return -1;
    }
    tb_stat_add(TB_STAT_BYTES_TX, k);
    return k;
  }
}

static ssize_t conn_recv(tb_conn* c, void* p, size_t n) {
  // Receive-side stall accounting: wall time blocked waiting for bytes
  // (two vDSO clock reads per recv — noise next to a syscall).
  int64_t t0 = tb_now_ns();
  if (!c->ssl) {
    ssize_t k = recv(c->fd, p, n, 0);
    tb_stat_add(TB_STAT_RECV_WAIT_NS, tb_now_ns() - t0);
    if (k > 0) tb_stat_add(TB_STAT_BYTES_RX, k);
    return k;
  }
  if (n > kTlsIoCap) n = kTlsIoCap;
  for (;;) {
    errno = 0;  // stale EINTR from an earlier call must not loop us
    int k = tls::SSL_read_(c->ssl, p, static_cast<int>(n));
    if (k < 0) {
      if (errno == EINTR) continue;  // interrupted syscall under SSL_read
      tb_stat_add(TB_STAT_RECV_WAIT_NS, tb_now_ns() - t0);
      errno = ECONNRESET;
      return -1;
    }
    tb_stat_add(TB_STAT_RECV_WAIT_NS, tb_now_ns() - t0);
    if (k > 0) tb_stat_add(TB_STAT_BYTES_RX, k);
    return k;  // 0 = close_notify / EOF, same contract as recv
  }
}

// True only for a provably idle connection (nothing buffered, nothing
// pending on the wire) — the reuse-time drain check.
static int conn_idle(tb_conn* c) {
  if (c->ssl && tls::SSL_pending_(c->ssl) > 0) return 0;
  char junk;
  ssize_t pk = recv(c->fd, &junk, 1, MSG_PEEK | MSG_DONTWAIT);
  // Raw bytes pending on a TLS socket may be an in-flight close_notify —
  // conservatively not reusable either way.
  if (pk >= 0 || (errno != EAGAIN && errno != EWOULDBLOCK)) return 0;
  return 1;
}

int64_t tb_conn_plain(int fd) {
  tb_conn* c = static_cast<tb_conn*>(calloc(1, sizeof(tb_conn)));
  if (!c) return -ENOMEM;
  c->fd = fd;
  return reinterpret_cast<int64_t>(c);
}

// TLS handshake on a connected fd. On failure the fd is NOT closed (the
// caller owns it). ``sni`` is the server name for SNI + certificate
// verification; ``cafile`` overrides the system trust store; ``insecure``
// skips verification entirely (tests against self-signed endpoints);
// ``alpn_h2`` offers ALPN "h2" and REQUIRES the server to select it (the
// gRPC path misparses an HTTP/1.1 fallback as frame garbage — fail the
// handshake instead).
int64_t tb_conn_tls(int fd, const char* sni, const char* cafile, int insecure,
                    int alpn_h2) {
  if (!tls::load()) return TB_ETLS;
  void* ctx = tls::get_ctx(cafile, insecure);
  if (!ctx) return TB_ETLS;
  void* ssl = tls::SSL_new_(ctx);
  tls::SSL_CTX_free_(ctx);  // drop our reference; SSL holds its own
  if (!ssl) return TB_ETLS;
  if (sni && sni[0]) {
    // SNI (SSL_set_tlsext_host_name macro = SSL_ctrl 55/0).
    tls::SSL_ctrl_(ssl, 55, 0, const_cast<char*>(sni));
    if (!insecure) {
      void* param = tls::SSL_get0_param_(ssl);
      struct in_addr a4;
      struct in6_addr a6;
      int is_ip = inet_pton(AF_INET, sni, &a4) == 1 ||
                  inet_pton(AF_INET6, sni, &a6) == 1;
      int ok = is_ip ? tls::X509_VERIFY_PARAM_set1_ip_asc_(param, sni)
                     : tls::X509_VERIFY_PARAM_set1_host_(param, sni, 0);
      if (ok != 1) {
        tls::SSL_free_(ssl);
        return TB_ETLS;
      }
    }
  }
  if (alpn_h2) {
    static const unsigned char kH2[] = {2, 'h', '2'};
    if (tls::SSL_set_alpn_protos_(ssl, kH2, sizeof kH2) != 0) {
      tls::SSL_free_(ssl);
      return TB_ETLS;
    }
  }
  if (tls::SSL_set_fd_(ssl, fd) != 1) {
    tls::SSL_free_(ssl);
    return TB_ETLS;
  }
  errno = 0;
  if (tls::SSL_connect_(ssl) != 1) {
    // Distinguish network conditions (socket timeout from SO_RCVTIMEO,
    // reset, interrupt — transient, retried under policy) from
    // protocol/trust failures (TB_ETLS, permanent: they reproduce).
    int e = errno;
    tls::SSL_free_(ssl);
    if (e == EAGAIN || e == EWOULDBLOCK || e == ETIMEDOUT ||
        e == ECONNRESET || e == EPIPE || e == EINTR)
      return -e;
    return TB_ETLS;
  }
  if (alpn_h2) {
    const unsigned char* sel = nullptr;
    unsigned sel_len = 0;
    tls::SSL_get0_alpn_selected_(ssl, &sel, &sel_len);
    if (sel_len != 2 || memcmp(sel, "h2", 2) != 0) {
      tls::SSL_shutdown_(ssl);
      tls::SSL_free_(ssl);
      return TB_ETLS;
    }
  }
  tb_conn* c = static_cast<tb_conn*>(calloc(1, sizeof(tb_conn)));
  if (!c) {
    tls::SSL_free_(ssl);
    return -ENOMEM;
  }
  tb_stat_add(TB_STAT_TLS_HANDSHAKES, 1);
  c->fd = fd;
  c->ssl = ssl;
  return reinterpret_cast<int64_t>(c);
}

int tb_conn_close(int64_t h) {
  if (h <= 0) return -EINVAL;
  tb_stat_add(TB_STAT_CONN_CLOSES, 1);
  tb_conn* c = reinterpret_cast<tb_conn*>(h);
  if (c->ssl) {
    tls::SSL_shutdown_(c->ssl);  // best-effort close_notify
    tls::SSL_free_(c->ssl);
  }
  int rc = close(c->fd) == 0 ? 0 : -errno;
  if (c->streams) {
    for (int i = 0; i < kH2MaxStreams; i++) free(c->streams[i].scratch);
    free(c->streams);
  }
  for (int i = 0; i < c->scratch_pool_n; i++) free(c->scratch_pool[i]);
  free(c->resp);
  free(c);
  return rc;
}

// One GET on an ALREADY-CONNECTED socket (keep-alive: the caller pools
// connections, so the receive loop can be measured with the same
// connection discipline as the pooled Python client instead of paying a
// fresh TCP handshake per GET). The socket is NOT closed here on success;
// *reusable_out reports whether it may carry another request (complete
// Content-Length body, no "Connection: close" from the server). On ANY
// error return the caller must tb_http_close the fd — the stream state is
// unknown.
// Send one GET and parse the response headers into ``r``; body bytes that
// arrived with the headers are stashed in ``r->leftover``. Body streams via
// resp_read. Returns 0, or -errno / TB_* (the connection is then unusable).
static int64_t http_begin(tb_conn* cn, const char* host, int port,
                          const char* path,
                          const char* extra_headers,  // "K: V\r\n..." or ""
                          tb_resp* r) {
  r->active = 0;
  r->status = 0;
  r->http_minor = 0;
  r->server_close = r->client_close = r->junk = 0;
  r->content_len = -1;
  r->body_got = 0;
  r->first_byte_ns = 0;
  r->leftover_off = r->leftover_len = 0;
  char req[4096];
  int m = snprintf(req, sizeof req,
                   "GET %s HTTP/1.1\r\nHost: %s:%d\r\nUser-Agent: tpubench-native\r\n"
                   "%s\r\n",
                   path, host, port, extra_headers ? extra_headers : "");
  if (m <= 0 || m >= static_cast<int>(sizeof req)) return TB_EPROTO;
  for (int sent = 0; sent < m;) {
    ssize_t k = conn_send(cn, req + sent, m - sent);
    if (k < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    sent += k;
  }

  // Read headers (into a bounded scratch), find \r\n\r\n.
  char hdr[16384];
  const int hdr_cap = static_cast<int>(sizeof hdr) - 1;  // reserve NUL slot
  int hlen = 0;
  char* body_start = nullptr;
  int body_in_hdr = 0;
  while (hlen < hdr_cap) {
    ssize_t k = conn_recv(cn, hdr + hlen, hdr_cap - hlen);
    if (k < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (k == 0) break;
    if (r->first_byte_ns == 0) r->first_byte_ns = tb_now_ns();
    hlen += k;
    hdr[hlen] = 0;
    char* p = static_cast<char*>(memmem(hdr, hlen, "\r\n\r\n", 4));
    if (p) {
      body_start = p + 4;
      body_in_hdr = hlen - static_cast<int>(body_start - hdr);
      break;
    }
  }
  if (!body_start) {
    // Header buffer exhausted without a terminator: the server is speaking
    // broken HTTP (permanent). EOF mid-headers: early close (transient) —
    // same condition class as a body cut short.
    return hlen >= hdr_cap ? TB_EPROTO : TB_ESHORT;
  }

  if (sscanf(hdr, "HTTP/1.%d %d", &r->http_minor, &r->status) != 2)
    return TB_EPROTO;

  // Case-insensitive Content-Length / Transfer-Encoding / Connection scan
  // over the header block. Chunked bodies are rejected (TB_ECHUNKED): this
  // receive path has no de-chunker, and copying chunk framing into the
  // buffer as body bytes would be silent corruption.
  for (char* line = hdr; line < body_start;) {
    char* eol = static_cast<char*>(memmem(line, body_start - line, "\r\n", 2));
    if (!eol) break;
    if (strncasecmp(line, "Content-Length:", 15) == 0)
      r->content_len = strtoll(line + 15, nullptr, 10);
    if (strncasecmp(line, "Transfer-Encoding:", 18) == 0) {
      // Transfer-coding names are case-insensitive (RFC 9112 §7).
      for (char* p = line + 18; p + 7 <= eol; p++) {
        if (strncasecmp(p, "chunked", 7) == 0) return TB_ECHUNKED;
      }
    }
    if (strncasecmp(line, "Connection:", 11) == 0) {
      for (char* p = line + 11; p + 5 <= eol; p++) {
        if (strncasecmp(p, "close", 5) == 0) r->server_close = 1;
      }
    }
    line = eol + 2;
  }

  // Unknown body length is only readable when the connection is committed
  // to closing — server announced close, HTTP/1.0 default-close, or WE
  // requested "Connection: close" (a conformant server must then close
  // after responding, RFC 9112 §9.6, whether or not it echoes the
  // header): read-to-FIN then terminates. A keep-alive response with
  // neither Content-Length nor Transfer-Encoding leaves no way to find
  // the body end — recv would block forever — so that shape is a
  // protocol error, not a hang.
  r->client_close =
      extra_headers && strcasestr(extra_headers, "connection: close") != nullptr;
  if (r->content_len < 0 && !r->server_close && !r->client_close &&
      r->http_minor >= 1)
    return TB_EPROTO;

  if (body_in_hdr > 0) {
    memcpy(r->leftover, body_start, body_in_hdr);
    r->leftover_len = body_in_hdr;
    // Bytes beyond Content-Length arrived with the headers: pipelined
    // junk — the stream is served correctly (consumption stops at
    // Content-Length) but the connection must not be pooled.
    if (r->content_len >= 0 && body_in_hdr > r->content_len) r->junk = 1;
  }
  r->active = !(r->content_len == 0);
  return 0;
}

// Serve body bytes into ``dst``: fills ``want`` bytes completely unless
// the body ends first (buffered-reader semantics — a 2 MB granule costs
// ONE call, not one per TCP segment). Returns bytes served (0 = body
// complete), or -errno / TB_ESHORT (peer FIN before Content-Length).
static int64_t resp_read(tb_conn* cn, tb_resp* r, uint8_t* dst, int64_t want) {
  if (!r->active || want <= 0) return 0;
  if (r->content_len >= 0) {
    int64_t left = r->content_len - r->body_got;
    if (want > left) want = left;
    if (want <= 0) {
      r->active = 0;
      return 0;
    }
  }
  int64_t got = 0;
  // Leftover body bytes from the header read serve first.
  if (r->leftover_off < r->leftover_len) {
    int64_t take = r->leftover_len - r->leftover_off;
    if (take > want) take = want;
    memcpy(dst, r->leftover + r->leftover_off, take);
    r->leftover_off += static_cast<int>(take);
    got = take;
  }
  while (got < want) {
    ssize_t k = conn_recv(cn, dst + got, static_cast<size_t>(want - got));
    if (k < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (k == 0) {
      if (r->content_len < 0) {  // close-delimited: FIN ends the body
        r->active = 0;
        break;
      }
      return TB_ESHORT;  // peer FIN before Content-Length bytes arrived
    }
    if (r->first_byte_ns == 0) r->first_byte_ns = tb_now_ns();
    got += k;
  }
  r->body_got += got;
  if (r->content_len >= 0 && r->body_got >= r->content_len) r->active = 0;
  return got;
}

// Keep-alive verdict after a response: body boundary known and fully
// consumed, HTTP/1.1, no close announced either way, no pipelined junk,
// and the socket provably idle.
static int resp_reusable(tb_conn* cn, tb_resp* r) {
  if (r->active || r->content_len < 0 || r->server_close || r->client_close ||
      r->http_minor < 1 || r->junk)
    return 0;
  return conn_idle(cn);
}

static int64_t request_on(tb_conn* cn, const char* host, int port,
                          const char* path,
                          const char* extra_headers,  // "K: V\r\n..." or ""
                          void* buf, int64_t buf_len, int* status_out,
                          int64_t* first_byte_ns_out, int64_t* total_ns_out,
                          int* reusable_out) {
  int64_t t_start = tb_now_ns();
  if (reusable_out) *reusable_out = 0;
  tb_resp r;
  int64_t rc = http_begin(cn, host, port, path, extra_headers, &r);
  if (rc != 0) return rc;
  if (status_out) *status_out = r.status;
  uint8_t* out = static_cast<uint8_t*>(buf);
  int64_t got = 0;
  for (;;) {
    int64_t want = buf_len - got;
    if (want <= 0) {
      if (r.content_len >= 0) {
        if (r.active) return TB_ETOOBIG;  // known length doesn't fit
        break;
      }
      // Close-delimited body that exactly fills the buffer: probe one
      // byte — EOF proves an exact fit; more data is a real overflow.
      uint8_t probe;
      int64_t k = resp_read(cn, &r, &probe, 1);
      if (k < 0) return k;
      if (k > 0) return TB_ETOOBIG;
      break;
    }
    int64_t k = resp_read(cn, &r, out + got, want);
    if (k < 0) return k;
    if (k == 0) break;
    got += k;
  }
  if (reusable_out) *reusable_out = resp_reusable(cn, &r);
  if (first_byte_ns_out) *first_byte_ns_out = r.first_byte_ns;
  if (total_ns_out) *total_ns_out = tb_now_ns() - t_start;
  return got;
}

// ---- streaming GET on a connection handle ----
// The zero-intermediate-copy receive path: begin parses headers, then the
// caller pulls body bytes directly into its own memory (granule buffer or
// staging slot) — the same socket→destination streaming discipline as the
// Python client's readinto loop, with native header parse and timestamps.
// Contract: begin → N× body_read → end. On any negative return the
// connection is unusable and the caller must tb_conn_close it.

int64_t tb_conn_get_begin(int64_t h, const char* host, int port,
                          const char* path, const char* extra_headers,
                          int* status_out, int64_t* content_len_out,
                          int64_t* first_byte_ns_out) {
  if (h <= 0) return -EINVAL;
  tb_conn* cn = reinterpret_cast<tb_conn*>(h);
  if (!cn->resp) {
    cn->resp = static_cast<tb_resp*>(malloc(sizeof(tb_resp)));
    if (!cn->resp) return -ENOMEM;
  }
  int64_t rc = http_begin(cn, host, port, path, extra_headers, cn->resp);
  if (rc != 0) return rc;
  if (status_out) *status_out = cn->resp->status;
  if (content_len_out) *content_len_out = cn->resp->content_len;
  if (first_byte_ns_out) *first_byte_ns_out = cn->resp->first_byte_ns;
  return 0;
}

int64_t tb_conn_body_read(int64_t h, void* dst, int64_t want) {
  if (h <= 0) return -EINVAL;
  tb_conn* cn = reinterpret_cast<tb_conn*>(h);
  if (!cn->resp) return -EINVAL;
  return resp_read(cn, cn->resp, static_cast<uint8_t*>(dst), want);
}

// Finish the streaming GET: *reusable_out reports whether the connection
// may carry another request (not reusable when the body was abandoned
// mid-stream). Always safe to call once after begin succeeded.
int tb_conn_get_end(int64_t h, int* reusable_out) {
  if (h <= 0) return -EINVAL;
  tb_conn* cn = reinterpret_cast<tb_conn*>(h);
  if (!cn->resp) return -EINVAL;
  if (reusable_out) *reusable_out = resp_reusable(cn, cn->resp);
  cn->resp->active = 0;
  return 0;
}

// Plain-fd wrapper (back-compat entry point; plaintext only).
int64_t tb_http_request(int fd, const char* host, int port, const char* path,
                        const char* extra_headers, void* buf, int64_t buf_len,
                        int* status_out, int64_t* first_byte_ns_out,
                        int64_t* total_ns_out, int* reusable_out) {
  tb_conn c{};
  c.fd = fd;
  return request_on(&c, host, port, path, extra_headers, buf, buf_len,
                    status_out, first_byte_ns_out, total_ns_out, reusable_out);
}

// Handle-based entry point: one GET on a tb_conn (plaintext or TLS).
int64_t tb_conn_request(int64_t h, const char* host, int port,
                        const char* path, const char* extra_headers, void* buf,
                        int64_t buf_len, int* status_out,
                        int64_t* first_byte_ns_out, int64_t* total_ns_out,
                        int* reusable_out) {
  if (h <= 0) return -EINVAL;
  return request_on(reinterpret_cast<tb_conn*>(h), host, port, path,
                    extra_headers, buf, buf_len, status_out, first_byte_ns_out,
                    total_ns_out, reusable_out);
}

// One-shot GET: fresh connection, with an explicit "Connection: close"
// request header so a close-delimited (no Content-Length) HTTP/1.1
// response is legal: the server commits to closing and read-to-FIN
// terminates. The pooled path is tb_http_connect + tb_http_request
// (keep-alive).
int64_t tb_http_get(const char* host, int port, const char* path,
                    const char* extra_headers, void* buf, int64_t buf_len,
                    int* status_out, int64_t* first_byte_ns_out,
                    int64_t* total_ns_out) {
  int64_t t_start = tb_now_ns();
  int fd = tb_http_connect(host, port);
  if (fd < 0) return fd;
  char hdrs[4096];
  int hm = snprintf(hdrs, sizeof hdrs, "%sConnection: close\r\n",
                    extra_headers ? extra_headers : "");
  if (hm <= 0 || hm >= static_cast<int>(sizeof hdrs)) {
    close(fd);
    return TB_EPROTO;
  }
  int64_t n = tb_http_request(fd, host, port, path, hdrs, buf,
                              buf_len, status_out, first_byte_ns_out,
                              nullptr, nullptr);
  close(fd);
  if (n >= 0 && total_ns_out) *total_ns_out = tb_now_ns() - t_start;
  return n;
}

// ------------------------------------------------------------- gRPC / h2 --
// Native receive for the gRPC path (SURVEY §2.5.1 names "HTTP/gRPC
// response bodies"): a hand-rolled minimal HTTP/2 client speaking exactly
// the google.storage.v2.Storage/ReadObject RPC shape over h2c prior
// knowledge (what an insecure gRPC port speaks) or TLS via the tb_conn
// layer. Scope decisions, made for a benchmark receive path rather than a
// general h2 stack:
//
// * HPACK: requests encode every header as "literal, never indexed, new
//   name", no huffman — minimal and legal. Responses are parsed
//   STRUCTURALLY: every entry form has explicit lengths, so entries can
//   be skipped exactly without maintaining the dynamic table or decoding
//   huffman; grpc-status is extracted opportunistically when it appears
//   in plain literal form, and success is otherwise judged by stream
//   completion + delivered byte count (the caller sized the buffer from
//   object metadata).
// * Flow control: we advertise a 2^31-1 stream window and widen the
//   connection window up front, then top both up as DATA is consumed.
// * One connection = sequential RPCs on odd stream ids (1, 3, 5, …) —
//   keep-alive parity with the pooled paths; no concurrent streams.
// * gRPC messages (5-byte length-prefixed ReadObjectResponse protos) are
//   reassembled in a scratch buffer, then ChecksummedData.content bytes
//   are copied into the caller's aligned buffer. That is one scratch→dest
//   copy — same count as the Python client's deserialize path, and the
//   protobuf wire format (length-delimited submessages) does not permit
//   landing content in place without first seeing the enclosing lengths.

namespace h2 {

// ---- frame io ----
static const uint8_t kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

static int send_all(tb_conn* c, const uint8_t* p, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = conn_send(c, p + off, n - off);
    if (k < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    off += static_cast<size_t>(k);
  }
  return 0;
}

static int recv_all(tb_conn* c, uint8_t* p, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = conn_recv(c, p + off, n - off);
    if (k < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (k == 0) return TB_ESHORT;
    off += static_cast<size_t>(k);
  }
  return 0;
}

static void put32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24;
  p[1] = v >> 16;
  p[2] = v >> 8;
  p[3] = v;
}

static int send_frame(tb_conn* c, uint8_t type, uint8_t flags, uint32_t stream,
                      const uint8_t* payload, uint32_t len) {
  uint8_t hdr[9];
  hdr[0] = len >> 16;
  hdr[1] = len >> 8;
  hdr[2] = len;
  hdr[3] = type;
  hdr[4] = flags;
  put32(hdr + 5, stream & 0x7fffffffu);
  int rc = send_all(c, hdr, 9);
  if (rc != 0) return rc;
  if (len) rc = send_all(c, payload, len);
  return rc;
}

// ---- HPACK request encoding: literal never-indexed, new name, no huffman.
static size_t hp_int(uint8_t* out, uint64_t v) {
  // 7-bit prefix integer with a zeroed first byte (string length form).
  if (v < 127) {
    out[0] = static_cast<uint8_t>(v);
    return 1;
  }
  out[0] = 127;
  v -= 127;
  size_t n = 1;
  while (v >= 128) {
    out[n++] = static_cast<uint8_t>(v | 0x80);
    v >>= 7;
  }
  out[n++] = static_cast<uint8_t>(v);
  return n;
}

static size_t hp_header(uint8_t* out, const char* name, const char* value) {
  size_t n = 0;
  out[n++] = 0x10;  // literal never-indexed, name literal
  n += hp_int(out + n, strlen(name));
  memcpy(out + n, name, strlen(name));
  n += strlen(name);
  n += hp_int(out + n, strlen(value));
  memcpy(out + n, value, strlen(value));
  n += strlen(value);
  return n;
}

// ---- structural HPACK response parsing ----
// Decode a prefix integer; returns bytes consumed or 0 on truncation.
static size_t hpd_int(const uint8_t* p, size_t n, int prefix_bits,
                      uint64_t* out) {
  if (n == 0) return 0;
  uint64_t max_prefix = (1u << prefix_bits) - 1;
  uint64_t v = p[0] & max_prefix;
  size_t i = 1;
  if (v == max_prefix) {
    uint64_t m = 0;
    for (;;) {
      if (i >= n || m > 56) return 0;
      uint8_t b = p[i++];
      v += static_cast<uint64_t>(b & 0x7f) << m;
      if (!(b & 0x80)) break;
      m += 7;
    }
  }
  *out = v;
  return i;
}

// HPACK Huffman decoding (RFC 7541 §5.2 + Appendix B): canonical decode
// tree built once from the spec table. Real gRPC servers huffman-encode
// trailer names/values (grpc-status), so the parser must decode, not
// just skip.
struct HuffNode {
  int16_t next[2];
  int16_t sym;  // >= 0: leaf (256 = EOS)
};

static const HuffNode* huff_tree() {
  static HuffNode* tree = [] {
    // 257 codes x <= 30 bits bounds the node count.
    static HuffNode nodes[257 * 30 + 1];
    int count = 1;
    nodes[0] = {{-1, -1}, -1};
    for (int sym = 0; sym < 257; sym++) {
      uint32_t code = kHpackHuffman[sym].code;
      int bits = kHpackHuffman[sym].bits;
      int cur = 0;
      for (int b = bits - 1; b >= 0; b--) {
        int bit = (code >> b) & 1;
        if (nodes[cur].next[bit] < 0) {
          nodes[cur].next[bit] = static_cast<int16_t>(count);
          nodes[count] = {{-1, -1}, -1};
          count++;
        }
        cur = nodes[cur].next[bit];
      }
      nodes[cur].sym = static_cast<int16_t>(sym);
    }
    return nodes;
  }();
  return tree;
}

// Decode a huffman-coded string into out[cap]. Returns decoded length or
// -1 (EOS in stream, truncated code mid-symbol is tolerated as RFC
// padding, output overflow).
static int64_t huff_decode(const uint8_t* p, size_t n, uint8_t* out,
                           size_t cap) {
  const HuffNode* t = huff_tree();
  int cur = 0;
  size_t o = 0;
  for (size_t i = 0; i < n; i++) {
    for (int b = 7; b >= 0; b--) {
      int nxt = t[cur].next[(p[i] >> b) & 1];
      if (nxt < 0) return -1;
      cur = nxt;
      if (t[cur].sym >= 0) {
        if (t[cur].sym == 256) return -1;  // EOS must not appear
        if (o >= cap) return -1;
        out[o++] = static_cast<uint8_t>(t[cur].sym);
        cur = 0;
      }
    }
  }
  // Leftover bits are EOS-prefix padding (<= 7 bits), consumed above.
  return static_cast<int64_t>(o);
}

// One string (possibly huffman-coded): returns bytes consumed; *s/*slen
// point at the raw (still-encoded when *huff) payload.
static size_t hpd_str(const uint8_t* p, size_t n, const uint8_t** s,
                      size_t* slen, int* huff) {
  if (n == 0) return 0;
  *huff = p[0] & 0x80;
  uint64_t len;
  size_t i = hpd_int(p, n, 7, &len);
  if (i == 0 || len > n - i) return 0;
  *s = p + i;
  *slen = static_cast<size_t>(len);
  return i + static_cast<size_t>(len);
}

// Resolve a parsed string into a bounded plain-text buffer. Returns the
// plain length, or -1 when it cannot fit / cannot decode (caller treats
// the entry as not-the-one-it-wants — never fatal).
static int64_t hp_resolve(const uint8_t* s, size_t slen, int huff,
                          uint8_t* out, size_t cap) {
  if (!huff) {
    if (slen > cap) return -1;
    memcpy(out, s, slen);
    return static_cast<int64_t>(slen);
  }
  return huff_decode(s, slen, out, cap);
}

// Parse an ASCII-decimal value into *out (leaves it untouched on junk —
// including values that would overflow: a hostile 23-digit content-length
// must not reach signed-overflow UB in the accumulate).
static void parse_int_value(const uint8_t* v, int64_t n, int* out) {
  if (n <= 0) return;
  int st = 0;
  for (int64_t j = 0; j < n; j++) {
    if (v[j] < '0' || v[j] > '9') return;
    int d = v[j] - '0';
    if (st > (INT_MAX - d) / 10) return;
    st = st * 10 + d;
  }
  *out = st;
}

static void parse_int64_value(const uint8_t* v, int64_t n, int64_t* out) {
  if (n <= 0) return;
  int64_t st = 0;
  for (int64_t j = 0; j < n; j++) {
    if (v[j] < '0' || v[j] > '9') return;
    int64_t d = v[j] - '0';
    if (st > (INT64_MAX - d) / 10) return;
    st = st * 10 + d;
  }
  *out = st;
}

// h2 static-table :status entries (RFC 7541 Appendix A, indices 8-14):
// responses commonly encode the status as a single indexed byte (0x88 =
// ":status 200").
static int static_status(uint64_t idx) {
  static const int kStatus[] = {200, 204, 206, 304, 400, 404, 500};
  return (idx >= 8 && idx <= 14) ? kStatus[idx - 8] : -1;
}

// Walk one header block, extracting grpc-status (plain or huffman-coded
// literals; indexed entries cannot carry it — grpc-status is not in the
// h2 static table and we advertise a zero-size dynamic table) and, when
// ``http_status`` is given, :status (indexed static-table entries 8-14,
// literal-with-name-index 8, or a literal ":status" name). When
// ``content_len`` is given, content-length is extracted the same two
// ways (literal name, or literal with static name-index 28) so the raw
// h2 GET path can detect under-delivery — the h1 path's TB_ESHORT rule
// (tb_resp.content_len, above). Returns 0 on success, TB_EPROTO on a
// malformed block.
static int parse_header_block(const uint8_t* p, size_t n, int* grpc_status,
                              int* http_status = nullptr,
                              int64_t* content_len = nullptr) {
  size_t i = 0;
  while (i < n) {
    uint8_t b = p[i];
    uint64_t idx;
    size_t k;
    if (b & 0x80) {  // indexed field: nothing to skip beyond the index
      k = hpd_int(p + i, n - i, 7, &idx);
      if (k == 0) return TB_EPROTO;
      i += k;
      if (http_status) {
        int st = static_status(idx);
        if (st > 0) *http_status = st;
      }
      continue;
    } else if ((b & 0xe0) == 0x20) {  // dynamic table size update
      k = hpd_int(p + i, n - i, 5, &idx);
      if (k == 0) return TB_EPROTO;
      i += k;
      continue;
    } else if (b & 0x40) {  // literal with incremental indexing
      k = hpd_int(p + i, n - i, 6, &idx);
    } else {  // literal without indexing / never indexed (4-bit prefix)
      k = hpd_int(p + i, n - i, 4, &idx);
    }
    if (k == 0) return TB_EPROTO;
    int has_name_literal = (idx == 0);
    i += k;
    const uint8_t* name = nullptr;
    size_t name_len = 0;
    int name_huff = 0;
    if (has_name_literal) {
      k = hpd_str(p + i, n - i, &name, &name_len, &name_huff);
      if (k == 0) return TB_EPROTO;
      i += k;
    }
    const uint8_t* val = nullptr;
    size_t val_len = 0;
    int val_huff = 0;
    k = hpd_str(p + i, n - i, &val, &val_len, &val_huff);
    if (k == 0) return TB_EPROTO;
    i += k;
    if (name && (grpc_status || http_status || content_len)) {
      uint8_t nbuf[32];
      int64_t nl = hp_resolve(name, name_len, name_huff, nbuf, sizeof nbuf);
      int is_grpc = grpc_status && nl == 11 &&
                    memcmp(nbuf, "grpc-status", 11) == 0;
      int is_http = http_status && nl == 7 && memcmp(nbuf, ":status", 7) == 0;
      int is_clen = content_len && nl == 14 &&
                    memcmp(nbuf, "content-length", 14) == 0;
      if (is_grpc || is_http) {
        uint8_t vbuf[16];
        int64_t vl = hp_resolve(val, val_len, val_huff, vbuf, sizeof vbuf);
        parse_int_value(vbuf, vl, is_grpc ? grpc_status : http_status);
      } else if (is_clen) {
        uint8_t vbuf[24];
        int64_t vl = hp_resolve(val, val_len, val_huff, vbuf, sizeof vbuf);
        parse_int64_value(vbuf, vl, content_len);
      }
    } else if (!name && http_status && idx >= 8 && idx <= 14) {
      // Literal with an indexed NAME (static entries 8-14 all carry the
      // name ":status") and a literal value — how servers encode statuses
      // outside the static table's seven.
      uint8_t vbuf[16];
      int64_t vl = hp_resolve(val, val_len, val_huff, vbuf, sizeof vbuf);
      parse_int_value(vbuf, vl, http_status);
    } else if (!name && content_len && idx == 28) {
      // Static entry 28 is "content-length" (empty value) — servers
      // emit the header as literal-with-name-index 28 + literal value.
      uint8_t vbuf[24];
      int64_t vl = hp_resolve(val, val_len, val_huff, vbuf, sizeof vbuf);
      parse_int64_value(vbuf, vl, content_len);
    }
  }
  return 0;
}

// ---- minimal protobuf ----
static size_t pb_varint(uint8_t* out, uint64_t v) {
  size_t n = 0;
  while (v >= 128) {
    out[n++] = static_cast<uint8_t>(v | 0x80);
    v >>= 7;
  }
  out[n++] = static_cast<uint8_t>(v);
  return n;
}

static size_t pb_str(uint8_t* out, uint32_t field, const char* s) {
  size_t n = 0;
  out[n++] = static_cast<uint8_t>(field << 3 | 2);
  n += pb_varint(out + n, strlen(s));
  memcpy(out + n, s, strlen(s));
  return n + strlen(s);
}

static size_t pbd_varint(const uint8_t* p, size_t n, uint64_t* out) {
  uint64_t v = 0;
  size_t i = 0;
  int shift = 0;
  for (;;) {
    if (i >= n || shift > 63) return 0;
    uint8_t b = p[i++];
    v |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *out = v;
  return i;
}

// Extract ChecksummedData.content (field 1 of field 1) from one serialized
// ReadObjectResponse; appends into dst. Returns bytes appended or TB_EPROTO.
static int64_t pb_extract_content(const uint8_t* msg, size_t n, uint8_t* dst,
                                  int64_t dst_cap) {
  size_t i = 0;
  int64_t out = 0;
  while (i < n) {
    uint64_t tag;
    size_t k = pbd_varint(msg + i, n - i, &tag);
    if (k == 0) return TB_EPROTO;
    i += k;
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wire = tag & 7;
    uint64_t len = 0;
    switch (wire) {
      case 0:  // varint
        k = pbd_varint(msg + i, n - i, &len);
        if (k == 0) return TB_EPROTO;
        i += k;
        break;
      case 1:  // fixed64
        if (i + 8 > n) return TB_EPROTO;
        i += 8;
        break;
      case 5:  // fixed32
        if (i + 4 > n) return TB_EPROTO;
        i += 4;
        break;
      case 2: {  // length-delimited
        k = pbd_varint(msg + i, n - i, &len);
        // Subtraction-form bound: i + k + len can wrap uint64.
        if (k == 0 || len > n - i - k) return TB_EPROTO;
        i += k;
        if (field == 1) {
          // checksummed_data submessage: find content (field 1, bytes).
          const uint8_t* sub = msg + i;
          size_t sn = static_cast<size_t>(len);
          size_t j = 0;
          while (j < sn) {
            uint64_t stag;
            size_t sk = pbd_varint(sub + j, sn - j, &stag);
            if (sk == 0) return TB_EPROTO;
            j += sk;
            uint32_t sfield = static_cast<uint32_t>(stag >> 3);
            uint32_t swire = stag & 7;
            uint64_t slen = 0;
            if (swire == 2) {
              sk = pbd_varint(sub + j, sn - j, &slen);
              // Subtraction form again: j + sk + slen can wrap uint64.
              if (sk == 0 || slen > sn - j - sk) return TB_EPROTO;
              j += sk;
              if (sfield == 1) {
                if (slen > static_cast<uint64_t>(dst_cap - out))
                  return TB_ETOOBIG;
                memcpy(dst + out, sub + j, slen);
                out += static_cast<int64_t>(slen);
              }
              j += static_cast<size_t>(slen);
            } else if (swire == 0) {
              sk = pbd_varint(sub + j, sn - j, &slen);
              if (sk == 0) return TB_EPROTO;
              j += sk;
            } else if (swire == 5) {
              if (j + 4 > sn) return TB_EPROTO;
              j += 4;
            } else if (swire == 1) {
              if (j + 8 > sn) return TB_EPROTO;
              j += 8;
            } else {
              return TB_EPROTO;
            }
          }
        }
        i += static_cast<size_t>(len);
        break;
      }
      default:
        return TB_EPROTO;
    }
  }
  return out;
}

}  // namespace h2

// -------------------------------------------------------- fetch executor --
// Native fan-out runtime (the errgroup analog in C++): N worker threads
// pull GET tasks from a queue, run the streaming receive into the task's
// caller-owned aligned buffer over a per-thread keep-alive connection, and
// push completions to a ring the caller drains — the per-request hot path
// never touches the Python interpreter. HTTP/1.1 over plaintext or TLS
// (pool-level transport config); gRPC fan-out rides the Python-orchestrated
// pools or the multiplexed h2 stream machinery above.
namespace fp {

struct Task {
  char host[256];
  int port;
  char path[1024];
  char headers[2048];
  uint8_t* buf;
  int64_t buf_len;
  uint64_t tag;  // caller correlation id
  // results
  int64_t start_ns;  // request start (CLOCK_MONOTONIC): first-byte
                     // latency = first_byte_ns - start_ns
  int64_t result;  // body length or negative TB_*/-errno
  int status;
  int64_t first_byte_ns;
  int64_t total_ns;
  // Reactor-mode fields (legacy thread pool ignores them):
  Task* next;    // intrusive FIFO link (target queue / submit inbox)
  int attempt;   // stale-keep-alive retransmit budget consumed
};

// Both executor flavors return an opaque int64 handle whose pointee
// BEGINS with a kind tag, so every tb_pool_* entry point dispatches on
// the same handle type (Python never needs to know which it holds).
enum { kPoolKindThreads = 0x7b01, kPoolKindReactor = 0x7b02 };

struct Pool {
  int kind;  // kPoolKindThreads — MUST stay the first member
  pthread_mutex_t mu;
  pthread_cond_t sub_cv;   // signals workers: task available / shutdown
  pthread_cond_t done_cv;  // signals consumer: completion available
  Task** subq;             // submission ring
  Task** doneq;            // completion ring
  int cap;
  int sub_head, sub_len;
  int done_head, done_len;
  int inflight;  // submitted but not yet in doneq
  int shutdown;
  pthread_t* threads;
  int n_threads;
  // Endpoint transport (pool-level: a pool serves one endpoint class):
  // tls wraps each worker connection via the tb_conn TLS layer, verified
  // against `cafile`/system store with the task host as SNI.
  int tls;
  int insecure;
  char cafile[512];
};

struct WorkerConn {
  char host[256];
  int port;
  int64_t h;  // tb_conn handle; 0 = none
};

static void wc_close(WorkerConn* wc) {
  if (wc->h > 0) tb_conn_close(wc->h);
  wc->h = 0;
}

// Discard-mode GET (task buf == NULL): stream the body through one hot
// granule-sized scratch window and drop it — the reference's io.Discard
// hot loop (main.go:140) and the Python staging-"none" path both discard
// this way, so the fetch-only A/B compares like with like (landing a
// whole 48 MB body through DRAM costs real memory bandwidth the discard
// path never pays). Returns total body bytes or a negative code.
static const int64_t kDiscardScratch = 2 << 20;  // reference granule

static int64_t discard_get(WorkerConn* wc, Task* t, uint8_t* scratch,
                           int* reusable_out) {
  int status = 0;
  int64_t clen = -1, fb = 0;
  int64_t rc = tb_conn_get_begin(wc->h, t->host, t->port, t->path,
                                 t->headers, &status, &clen, &fb);
  if (rc != 0) return rc;
  t->status = status;
  t->first_byte_ns = fb;
  int64_t total = 0;
  for (;;) {
    int64_t k = tb_conn_body_read(wc->h, scratch, kDiscardScratch);
    if (k < 0) return k;
    if (k == 0) break;
    total += k;
  }
  tb_conn_get_end(wc->h, reusable_out);
  return total;
}

static void* worker_main(void* arg) {
  Pool* p = static_cast<Pool*>(arg);
  WorkerConn wc;
  wc.host[0] = 0;
  wc.port = -1;
  wc.h = 0;
  uint8_t* scratch = nullptr;  // lazily allocated, discard tasks only
  for (;;) {
    pthread_mutex_lock(&p->mu);
    while (p->sub_len == 0 && !p->shutdown)
      pthread_cond_wait(&p->sub_cv, &p->mu);
    if (p->sub_len == 0 && p->shutdown) {
      pthread_mutex_unlock(&p->mu);
      break;
    }
    Task* t = p->subq[p->sub_head];
    p->sub_head = (p->sub_head + 1) % p->cap;
    p->sub_len--;
    pthread_mutex_unlock(&p->mu);

    // Per-thread keep-alive: reuse the connection while the target
    // matches (the benchmark pattern: one endpoint, many GETs).
    if (wc.h > 0 && (strcmp(wc.host, t->host) != 0 || wc.port != t->port))
      wc_close(&wc);
    int attempt = 0;
    for (;;) {
      int fresh = 0;
      if (wc.h <= 0) {
        int fd = tb_http_connect(t->host, t->port);
        if (fd < 0) {
          t->result = fd;
          break;
        }
        int64_t h = p->tls
                        ? tb_conn_tls(fd, t->host, p->cafile, p->insecure, 0)
                        : tb_conn_plain(fd);
        if (h <= 0) {
          close(fd);  // handshake failed: fd still ours
          t->result = h;
          break;
        }
        wc.h = h;
        snprintf(wc.host, sizeof wc.host, "%s", t->host);
        wc.port = t->port;
        fresh = 1;
      }
      int reusable = 0;
      t->start_ns = tb_now_ns();
      if (t->buf == nullptr) {
        if (!scratch)
          scratch = static_cast<uint8_t*>(malloc(kDiscardScratch));
        t->result = scratch ? discard_get(&wc, t, scratch, &reusable)
                            : -ENOMEM;
        t->total_ns = tb_now_ns() - t->start_ns;
      } else {
        t->result = tb_conn_request(wc.h, t->host, t->port, t->path,
                                    t->headers, t->buf, t->buf_len,
                                    &t->status, &t->first_byte_ns,
                                    &t->total_ns, &reusable);
      }
      if (t->result >= 0) {
        if (!reusable) wc_close(&wc);
        break;
      }
      wc_close(&wc);
      // One retransmit when the FIRST use of a kept-alive connection
      // failed (stale pool socket) — same discipline as NativeConnPool,
      // including its permanent-code carve-out: protocol-shape failures
      // (TB_EPROTO/TB_ETOOBIG/TB_ECHUNKED/TB_ETLS) reproduce on a fresh
      // socket, so a retransmit only re-measures the failure.
      int permanent = t->result == TB_EPROTO || t->result == TB_ETOOBIG ||
                      t->result == TB_ECHUNKED || t->result == TB_ETLS;
      if (!fresh && attempt == 0 && !permanent) {
        attempt = 1;
        continue;
      }
      break;
    }

    pthread_mutex_lock(&p->mu);
    p->doneq[(p->done_head + p->done_len) % p->cap] = t;
    p->done_len++;
    pthread_cond_signal(&p->done_cv);
    pthread_mutex_unlock(&p->mu);
  }
  wc_close(&wc);
  free(scratch);
  return nullptr;
}

}  // namespace fp

// ------------------------------------------------ reactor-mode executor --
// The epoll rebuild of the fetch pool (ROADMAP item 3): BENCH_r05 measured
// the thread-per-connection pool LOSING to the pure-Python hot loop on a
// share-capped host because every completion pays a mutex/condvar crossing
// and every connection pays a context switch. Reactor mode replaces both:
//
//   * One (or a few) event-loop threads own ALL connections through epoll;
//     a connection is a nonblocking HTTP/1.1 state machine
//     (CONNECT→SEND→HEADERS→BODY→IDLE) with keep-alive reuse, so many
//     in-flight GETs share few sockets and zero per-request threads.
//   * Completions travel to the consumer over a lock-free SPSC ring per
//     loop (producer = the loop thread, consumer = the draining caller)
//     with an eventfd doorbell rung only on the ring's empty→nonempty
//     transition — the steady-state hot path has NO lock crossing and no
//     syscall per completion; one consumer wake drains the whole backlog.
//   * Submission stays mutex-guarded (it is the cold path: the Python
//     caller already serializes submits) with its own eventfd doorbell
//     into the loop.
//
// Scope: HTTP/1.1 and HTTP/2 over plaintext or TLS. TLS is a nonblocking
// OpenSSL state machine driven by WANT_READ/WANT_WRITE off epoll
// readiness (C_TLS_HANDSHAKE below), with session resumption cached per
// target for keep-alive reconnects. h2 grows the same state machine to
// frame multiplexing: many concurrent streams ride one connection (the
// per-target FIFO's in-flight dimension), with connection+stream
// flow-control credit surfaced through tb_stats_*. ALPN picks h2 vs
// http/1.1 per target; plaintext h2 uses prior knowledge (test servers).
// Error-code and retransmit contracts match the legacy pool exactly: the
// first use of a kept-alive connection gets one retransmit on a fresh
// socket (transient codes only); per-task errors land in the completion's
// result; the pool itself survives.
namespace rx {

enum {
  C_CONNECTING = 0,
  C_TLS_HANDSHAKE,  // SSL_connect in flight, driven by epoll readiness
  C_SEND,
  C_HDR,
  C_BODY,
  C_IDLE,
  C_H2,             // established h2 session (streams carry the tasks)
};

// SSL_get_error results the nonblocking machine dispatches on.
enum {
  kSslErrWantRead = 2,
  kSslErrWantWrite = 3,
  kSslErrSyscall = 5,
  kSslErrZeroReturn = 6,
};

struct Loop;
struct Target;

// One h2 stream in flight on a reactor connection (id == 0 = slot free).
struct H2Stream {
  uint32_t id;
  fp::Task* task;
  int64_t body_got;
  int status;            // :status from response HEADERS (0 until seen)
  int64_t content_len;   // -1 until response HEADERS carry content-length
  int got_headers;
  int64_t unacked;       // consumed DATA not yet returned as stream window
};

static const int kRxH2Streams = 32;              // streams per connection
static const int64_t kRxStreamWindow = 1 << 20;  // SETTINGS initial window
static const int64_t kRxConnWindow = 1 << 23;    // connection window target
static const int kRxH2OutCap = 32 * 1024;        // pending-frame send buffer

struct Conn {
  int fd;
  int state;
  int fresh;        // no request completed on this connection yet
  int registered;   // fd added to the loop's epoll set
  uint32_t events;  // current epoll interest
  Target* target;
  Loop* loop;
  fp::Task* task;   // in-flight task (null when IDLE); during
                    // CONNECTING/TLS_HANDSHAKE: the task waiting for the
                    // transport to come up (not yet begun)
  int64_t last_activity_ns;
  int resp_bytes;   // any response bytes seen for the CURRENT task
  int dead;         // closed this iteration; freed at the batch edge
  // TLS (nonblocking): ssl != null once the handshake starts. tls_want
  // records the last WANT_READ/WANT_WRITE so the epoll interest can
  // follow OpenSSL's state machine, not just the socket direction.
  void* ssl;
  int tls_want;     // 0, EPOLLIN or EPOLLOUT
  // request send state
  char req[4608];
  int req_len, req_off;
  // response header state
  uint8_t hdr[16384];
  int hlen;
  // parsed response state
  int status, http_minor, server_close, junk;
  int64_t content_len, body_got;
  // body bytes that arrived in the same recv as the headers
  int lo_off, lo_len;  // window into hdr[]
  // ---- h2 flavor (ALPN selected h2, or prior-knowledge mode) ----
  int h2;                   // transport is h2
  int h2_started;           // preface+SETTINGS queued
  uint32_t h2_next_stream;  // next odd stream id
  int h2_nstreams;          // active streams
  int h2_peer_max_streams;  // peer SETTINGS_MAX_CONCURRENT_STREAMS
  uint8_t* h2_out;          // pending frame bytes (lazily allocated)
  int h2_out_len, h2_out_off;
  int64_t h2_wu_queued_ns;  // oldest unflushed WINDOW_UPDATE enqueue time
  uint8_t h2_fh[9];         // frame-header accumulate
  int h2_fh_got;
  uint32_t h2_flen, h2_fstream;
  uint8_t h2_ftype, h2_fflags;
  int h2_fbuf_got;          // non-DATA payload accumulated into hdr[]
  int h2_data_rem;          // DATA payload bytes still to stream
  int h2_pad_rem;           // trailing padding still to discard
  int h2_pad_pending;       // PADDED flag seen, pad-length byte unread
  int64_t h2_conn_unacked;  // consumed bytes not yet conn-window-updated
  uint8_t* h2_hb;           // HEADERS+CONTINUATION accumulate (lazy)
  int h2_hb_len;
  uint32_t h2_hdr_stream;   // stream whose header block is accumulating
  uint8_t h2_hdr_flags;     // flags of the initiating HEADERS frame
  int h2_hdr_cont;          // awaiting CONTINUATION
  H2Stream h2_streams[kRxH2Streams];
  Conn* next;  // intrusive list per target
};

static const int kRxH2HbCap = 32 * 1024;  // header-block accumulate cap

struct Target {
  char host[256];
  int port;
  int resolved;  // sockaddr cached (getaddrinfo once per target)
  struct sockaddr_storage addr;
  socklen_t addr_len;
  void* tls_session;  // cached SSL_SESSION: resumption on reconnect
  fp::Task *q_head, *q_tail;  // pending tasks FIFO
  Conn* conns;
  int n_conns;
  Target* next;
};

struct Reactor;

struct Loop {
  Reactor* r;
  pthread_t thread;
  int started;
  int epfd;
  int submit_efd;  // doorbell: submissions / shutdown
  // SPSC completion ring: loop thread produces, the draining caller
  // consumes. Capacity >= pool cap, so it can never overflow (inflight
  // is capped at submit time).
  fp::Task** ring;
  uint32_t ring_mask;
  uint32_t ring_head;  // producer-owned (atomic)
  uint32_t ring_tail;  // consumer-owned (atomic)
  // submit inbox (mutex: cold path)
  pthread_mutex_t in_mu;
  fp::Task *in_head, *in_tail;
  Target* targets;
  int max_conns;  // this loop's share of the connection budget
  uint8_t* scratch;  // discard-mode landing window (loop-thread-owned)
  int ding_pending;  // completions enqueued since the last doorbell
                     // flush (loop-thread-local)
  Conn* dead;        // conns closed mid-iteration, freed at the batch
                     // edge — an epoll_wait batch can still hold a
                     // pending event whose data.ptr is such a conn
                     // (EPOLL_CTL_DEL does not retract already-returned
                     // events), so the memory must outlive the batch
};

struct Reactor {
  int kind;  // fp::kPoolKindReactor — MUST stay the first member
  int cap;
  int n_loops;
  int done_efd;  // consumer doorbell, shared by all loops
  int shutdown;  // atomic
  int inflight;  // atomic
  uint64_t rr;   // round-robin submit cursor (atomic)
  Loop* loops;
  // Endpoint transport (reactor-wide, mirroring fp::Pool's):
  int tls;
  int insecure;
  int h2_mode;   // 0 = h1 only; 1 = ALPN h2-or-http/1.1 (TLS);
                 // 2 = h2 prior knowledge (plaintext test servers)
  char cafile[512];
  void* ssl_ctx; // one owned SSL_CTX reference for the reactor lifetime
};

static const int64_t kIoTimeoutNs = 60LL * 1000000000LL;  // legacy parity
static const int64_t kDiscardWin = 256 * 1024;

// ---- SPSC ring ----
static void ring_push(Loop* L, fp::Task* t) {
  uint32_t h = __atomic_load_n(&L->ring_head, __ATOMIC_RELAXED);
  uint32_t tl = __atomic_load_n(&L->ring_tail, __ATOMIC_ACQUIRE);
  uint32_t depth = h - tl;
  L->ring[h & L->ring_mask] = t;
  __atomic_store_n(&L->ring_head, h + 1, __ATOMIC_RELEASE);
  tb_stat_add(TB_STAT_REACTOR_COMPLETIONS, 1);
  tb_stat_add(TB_STAT_REACTOR_RING_DEPTH_SUM, depth + 1);
  tb_stat_max(TB_STAT_REACTOR_RING_DEPTH_MAX, depth + 1);
  // Doorbell COALESCING: the ring is not rung per completion but when
  // kDingBatch completions have piled up (and always at the end of the
  // loop iteration) — one consumer wake hands over a batch. Measured on
  // the loopback A/B: per-completion dings wake the consumer so eagerly
  // that batches collapse to 1 (the handoff tax in eventfd form), while
  // flushing ONLY at iteration end serializes consumer against loop
  // (goodput halves). The threshold keeps both: batches ≥ kDingBatch at
  // high completion rate, iteration-end latency bound at low rate.
  L->ding_pending++;
}

static const int kDingBatch = 16;

static void ding_flush(Loop* L) {
  if (!L->ding_pending) return;
  L->ding_pending = 0;
  uint64_t one = 1;
  ssize_t k = write(L->r->done_efd, &one, sizeof one);
  (void)k;
  tb_stat_add(TB_STAT_REACTOR_DOORBELL_WAKES, 1);
}

// Drain up to max_n completed tasks across all loop rings (consumer side
// of the SPSC contract: ONE draining thread at a time, which is what the
// Python executor does — the legacy pool's multi-consumer mutex is the
// cost this path exists to remove).
static int ring_drain(Reactor* r, int max_n, fp::Task** out) {
  int n = 0;
  for (int li = 0; li < r->n_loops && n < max_n; li++) {
    Loop* L = &r->loops[li];
    uint32_t tl = __atomic_load_n(&L->ring_tail, __ATOMIC_RELAXED);
    uint32_t h = __atomic_load_n(&L->ring_head, __ATOMIC_ACQUIRE);
    while (tl != h && n < max_n) {
      out[n++] = L->ring[tl & L->ring_mask];
      tl++;
    }
    __atomic_store_n(&L->ring_tail, tl, __ATOMIC_RELEASE);
  }
  return n;
}

// ---- completion ----
static void complete_task(Loop* L, fp::Task* t, int64_t result) {
  t->result = result;
  t->total_ns = tb_now_ns() - t->start_ns;
  ring_push(L, t);
}

// ---- transport I/O (plaintext or nonblocking TLS) ----
// Same contract as send/recv on a nonblocking socket: >0 bytes moved,
// 0 = orderly EOF (recv only), -1 with errno. OpenSSL's WANT_READ /
// WANT_WRITE both surface as errno=EAGAIN with c->tls_want recording
// WHICH readiness unblocks the machine (an SSL_read can want EPOLLOUT
// mid-renegotiation) so callers can set epoll interest accordingly.
static ssize_t rx_send(Conn* c, const void* p, size_t n) {
  if (!c->ssl) {
    ssize_t k = send(c->fd, p, n, MSG_NOSIGNAL);
    if (k > 0) tb_stat_add(TB_STAT_BYTES_TX, k);
    return k;
  }
  if (n > kTlsIoCap) n = kTlsIoCap;
  errno = 0;
  int k = tls::SSL_write_(c->ssl, p, static_cast<int>(n));
  if (k > 0) {
    c->tls_want = 0;
    tb_stat_add(TB_STAT_BYTES_TX, k);
    return k;
  }
  int err = tls::SSL_get_error_(c->ssl, k);
  if (err == kSslErrWantRead) {
    c->tls_want = EPOLLIN;
    errno = EAGAIN;
    return -1;
  }
  if (err == kSslErrWantWrite) {
    c->tls_want = EPOLLOUT;
    errno = EAGAIN;
    return -1;
  }
  if (err == kSslErrSyscall && errno == EINTR) return -1;  // caller loops
  if (errno == 0 || errno == EAGAIN) errno = ECONNRESET;
  return -1;  // classified transient, like any mid-stream break (legacy)
}

static ssize_t rx_recv(Conn* c, void* p, size_t n) {
  if (!c->ssl) {
    ssize_t k = recv(c->fd, p, n, 0);
    if (k > 0) tb_stat_add(TB_STAT_BYTES_RX, k);
    return k;
  }
  if (n > kTlsIoCap) n = kTlsIoCap;
  errno = 0;
  int k = tls::SSL_read_(c->ssl, p, static_cast<int>(n));
  if (k > 0) {
    c->tls_want = 0;
    tb_stat_add(TB_STAT_BYTES_RX, k);
    return k;
  }
  int err = tls::SSL_get_error_(c->ssl, k);
  if (err == kSslErrZeroReturn) return 0;  // close_notify = orderly EOF
  if (err == kSslErrWantRead) {
    c->tls_want = EPOLLIN;
    errno = EAGAIN;
    return -1;
  }
  if (err == kSslErrWantWrite) {
    c->tls_want = EPOLLOUT;
    errno = EAGAIN;
    return -1;
  }
  if (err == kSslErrSyscall && k == 0) return 0;  // EOF sans close_notify
  if (err == kSslErrSyscall && errno == EINTR) return -1;  // caller loops
  if (errno == 0 || errno == EAGAIN) errno = ECONNRESET;
  return -1;
}

// ---- connection helpers ----
static void conn_want(Conn* c, uint32_t ev) {
  if (c->registered && c->events == ev) return;
  struct epoll_event e;
  e.events = ev;
  e.data.ptr = c;
  epoll_ctl(c->loop->epfd, c->registered ? EPOLL_CTL_MOD : EPOLL_CTL_ADD,
            c->fd, &e);
  c->registered = 1;
  c->events = ev;
}

// Close + unlink a connection, DEFERRING the free to the batch edge
// (dead list): the current epoll_wait batch may still hold an event for
// this conn, and loop_main must be able to recognize and skip it.
static void conn_free(Loop* L, Conn* c) {
  Target* t = c->target;
  Conn** pp = &t->conns;
  while (*pp && *pp != c) pp = &(*pp)->next;
  if (*pp) *pp = c->next;
  t->n_conns--;
  epoll_ctl(L->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  if (c->ssl) {
    tls::SSL_shutdown_(c->ssl);  // best-effort close_notify (nonblocking)
    tls::SSL_free_(c->ssl);
    c->ssl = nullptr;
  }
  free(c->h2_out);
  c->h2_out = nullptr;
  free(c->h2_hb);
  c->h2_hb = nullptr;
  close(c->fd);
  tb_stat_add(TB_STAT_CONN_CLOSES, 1);
  c->dead = 1;
  c->next = L->dead;
  L->dead = c;
}

// Batch edge: no event returned by the PREVIOUS epoll_wait can still
// reference these (DEL + close happened before the next wait).
static void reap_dead(Loop* L) {
  Conn* c = L->dead;
  L->dead = nullptr;
  while (c) {
    Conn* nxt = c->next;
    free(c);
    c = nxt;
  }
}

static void target_queue_push(Target* t, fp::Task* task, int front) {
  task->next = nullptr;
  if (front) {
    task->next = t->q_head;
    t->q_head = task;
    if (!t->q_tail) t->q_tail = task;
  } else if (t->q_tail) {
    t->q_tail->next = task;
    t->q_tail = task;
  } else {
    t->q_head = t->q_tail = task;
  }
}

static fp::Task* target_queue_pop(Target* t) {
  fp::Task* task = t->q_head;
  if (!task) return nullptr;
  t->q_head = task->next;
  if (!t->q_head) t->q_tail = nullptr;
  task->next = nullptr;
  return task;
}

static void pump_target(Loop* L, Target* t);

// Fail the conn's current task. When the failure happened on the FIRST
// use of a kept-alive connection with nothing of the response seen yet,
// the task gets one retransmit on a fresh socket — the legacy pool's
// stale-connection discipline, permanent-code carve-out included.
static void conn_fail(Loop* L, Conn* c, int64_t code) {
  fp::Task* task = c->task;
  c->task = nullptr;
  Target* t = c->target;
  int was_fresh = c->fresh;
  int saw_bytes = c->resp_bytes;
  conn_free(L, c);
  if (task) {
    int permanent = code == TB_EPROTO || code == TB_ETOOBIG ||
                    code == TB_ECHUNKED || code == TB_ETLS;
    if (!was_fresh && !saw_bytes && task->attempt == 0 && !permanent) {
      task->attempt = 1;
      target_queue_push(t, task, /*front=*/1);
    } else {
      complete_task(L, task, code);
    }
  }
  pump_target(L, t);
}

// Cache this connection's TLS session on the target so the NEXT fresh
// connection resumes it (abbreviated handshake on keep-alive reconnect).
// Captured on the first completed request, not at handshake time: TLS 1.3
// session tickets arrive after the handshake, and by the first response
// they have been consumed into the session.
static void tls_cache_session(Conn* c) {
  if (!c->ssl || !c->fresh) return;
  void* sess = tls::SSL_get1_session_(c->ssl);
  if (!sess) return;
  if (c->target->tls_session)
    tls::SSL_SESSION_free_(c->target->tls_session);
  c->target->tls_session = sess;
}

// Finish the current task successfully and decide connection reuse.
static void conn_finish(Loop* L, Conn* c) {
  fp::Task* task = c->task;
  c->task = nullptr;
  tls_cache_session(c);
  c->fresh = 0;
  task->status = c->status;
  int reusable = c->content_len >= 0 && !c->server_close &&
                 c->http_minor >= 1 && !c->junk &&
                 !(c->ssl && tls::SSL_pending_(c->ssl) > 0);
  complete_task(L, task, c->body_got);
  if (!reusable) {
    Target* t = c->target;
    conn_free(L, c);
    pump_target(L, t);
    return;
  }
  c->state = C_IDLE;
  c->resp_bytes = 0;
  conn_want(c, EPOLLIN);  // idle: readable means FIN/junk → close
  pump_target(L, c->target);
}

// =================== h2 flavor: nonblocking frame multiplexing ==========
// The h1 state machine above owns one task per connection; the h2 flavor
// owns a STREAM TABLE — queued tasks become concurrent streams on the
// same socket, which is where the per-target FIFO's in-flight dimension
// moves from sockets to stream ids. Frame building reuses the blocking
// path's HPACK helpers (h2::hp_header / h2::parse_header_block); frame
// I/O is rebuilt nonblocking: sends accumulate in h2_out and drain on
// writability, DATA payloads stream straight into task buffers.

static void conn_h2_io(Loop* L, Conn* c);

// Ensure `need` bytes of send-buffer room (compacts; lazily allocates).
static int h2_out_room(Conn* c, int need) {
  if (!c->h2_out) {
    c->h2_out = static_cast<uint8_t*>(malloc(kRxH2OutCap));
    if (!c->h2_out) return 0;
  }
  if (c->h2_out_off > 0) {
    memmove(c->h2_out, c->h2_out + c->h2_out_off,
            c->h2_out_len - c->h2_out_off);
    c->h2_out_len -= c->h2_out_off;
    c->h2_out_off = 0;
  }
  return kRxH2OutCap - c->h2_out_len >= need;
}

// Append one frame (caller guaranteed room via h2_out_room).
static void h2_out_frame(Conn* c, uint8_t type, uint8_t flags,
                         uint32_t stream, const uint8_t* payload,
                         uint32_t len) {
  uint8_t* p = c->h2_out + c->h2_out_len;
  p[0] = len >> 16;
  p[1] = len >> 8;
  p[2] = len;
  p[3] = type;
  p[4] = flags;
  h2::put32(p + 5, stream & 0x7fffffffu);
  if (len) memcpy(p + 9, payload, len);
  c->h2_out_len += 9 + static_cast<int>(len);
}

// Queue the session prologue: preface, SETTINGS (zero HPACK dynamic
// table — parse_header_block never indexes; finite per-stream window so
// flow control is real, not 2^31-sized), push disabled, and the
// connection window top-up.
static int h2_session_begin(Conn* c) {
  if (!h2_out_room(c, 128)) return -ENOMEM;
  memcpy(c->h2_out + c->h2_out_len, h2::kPreface, 24);
  c->h2_out_len += 24;
  uint8_t s[18];
  s[0] = 0; s[1] = 0x1;  // HEADER_TABLE_SIZE = 0
  h2::put32(s + 2, 0);
  s[6] = 0; s[7] = 0x2;  // ENABLE_PUSH = 0
  h2::put32(s + 8, 0);
  s[12] = 0; s[13] = 0x4;  // INITIAL_WINDOW_SIZE
  h2::put32(s + 14, static_cast<uint32_t>(kRxStreamWindow));
  h2_out_frame(c, 4, 0, 0, s, 18);
  uint8_t wu[4];
  h2::put32(wu, static_cast<uint32_t>(kRxConnWindow - 65535));
  h2_out_frame(c, 8, 0, 0, wu, 4);
  tb_stat_add(TB_STAT_H2_WINDOW_UPDATES_TX, 1);
  c->h2_started = 1;
  c->h2_next_stream = 1;
  c->h2_peer_max_streams = kRxH2Streams;
  c->state = C_H2;
  return 0;
}

// Can this connection take another queued task as a new stream?
static int h2_can_admit(Conn* c) {
  if (!c->h2 || !c->h2_started || c->dead) return 0;
  int max = c->h2_peer_max_streams < kRxH2Streams ? c->h2_peer_max_streams
                                                  : kRxH2Streams;
  if (c->h2_nstreams >= max) return 0;
  if (c->h2_next_stream >= 0x40000000u) return 0;  // id space spent
  // Room for a worst-case HEADERS frame, keeping slack for control
  // frames (ACKs / WINDOW_UPDATEs are tens of bytes).
  return h2_out_room(c, 4608 + 9 + 256);
}

static H2Stream* rx_h2_stream_of(Conn* c, uint32_t id) {
  if (id == 0) return nullptr;
  for (int i = 0; i < kRxH2Streams; i++)
    if (c->h2_streams[i].id == id) return &c->h2_streams[i];
  return nullptr;
}

static void h2_stream_done(Loop* L, Conn* c, H2Stream* s, int64_t result) {
  fp::Task* task = s->task;
  task->status = s->status;
  s->id = 0;
  s->task = nullptr;
  c->h2_nstreams--;
  tls_cache_session(c);
  c->fresh = 0;  // a completed stream proves the connection live
  complete_task(L, task, result);
}

// Fail one stream, honoring the stale-keep-alive retransmit discipline
// per stream: first failure on a NON-fresh connection with none of this
// stream's response seen retransmits once on a fresh socket.
static void h2_stream_fail(Loop* L, Conn* c, H2Stream* s, int64_t code) {
  fp::Task* task = s->task;
  int saw = s->got_headers || s->body_got > 0;
  s->id = 0;
  s->task = nullptr;
  c->h2_nstreams--;
  int permanent = code == TB_EPROTO || code == TB_ETOOBIG ||
                  code == TB_ECHUNKED || code == TB_ETLS;
  if (!c->fresh && !saw && task->attempt == 0 && !permanent) {
    task->attempt = 1;
    target_queue_push(c->target, task, /*front=*/1);
  } else {
    complete_task(L, task, code);
  }
}

// Connection-level failure: every active stream settles (retransmit rule
// per stream), then the socket dies.
static void h2_conn_fail(Loop* L, Conn* c, int64_t code) {
  Target* t = c->target;
  for (int i = 0; i < kRxH2Streams; i++)
    if (c->h2_streams[i].id) h2_stream_fail(L, c, &c->h2_streams[i], code);
  if (c->task) {  // transport-pending task (pre-session failure)
    fp::Task* task = c->task;
    c->task = nullptr;
    complete_task(L, task, code);
  }
  conn_free(L, c);
  pump_target(L, t);
}

// Open a queued task as a new stream: HPACK-encode the request (h2 wants
// lowercase names; Host becomes :authority) and queue HEADERS with
// END_STREAM|END_HEADERS.
static void h2_admit(Loop* L, Conn* c, fp::Task* task) {
  uint8_t hb[4608];
  size_t n = 0;
  char auth[300];
  snprintf(auth, sizeof auth, "%s:%d", task->host, task->port);
  n += h2::hp_header(hb + n, ":method", "GET");
  n += h2::hp_header(hb + n, ":scheme", c->loop->r->tls ? "https" : "http");
  n += h2::hp_header(hb + n, ":authority", auth);
  n += h2::hp_header(hb + n, ":path", task->path);
  const char* h = task->headers;
  char name[256], val[2048];
  int bad = 0;
  while (*h && !bad) {
    const char* eol = strstr(h, "\r\n");
    if (!eol) eol = h + strlen(h);
    const char* colon = static_cast<const char*>(
        memchr(h, ':', static_cast<size_t>(eol - h)));
    if (colon && colon > h) {
      size_t nl = static_cast<size_t>(colon - h);
      const char* v = colon + 1;
      while (v < eol && *v == ' ') v++;
      size_t vl = static_cast<size_t>(eol - v);
      if (nl >= sizeof name || vl >= sizeof val) {
        bad = 1;
        break;
      }
      for (size_t i = 0; i < nl; i++)
        name[i] = static_cast<char>(tolower(static_cast<unsigned char>(h[i])));
      name[nl] = 0;
      memcpy(val, v, vl);
      val[vl] = 0;
      // Connection-specific headers don't exist in h2; Host rode in as
      // :authority above.
      if (strcmp(name, "host") != 0 && strcmp(name, "connection") != 0) {
        if (n + nl + vl + 12 > sizeof hb) {
          bad = 1;
          break;
        }
        n += h2::hp_header(hb + n, name, val);
      }
    }
    h = *eol ? eol + 2 : eol;
  }
  if (bad) {
    complete_task(L, task, TB_EPROTO);
    return;
  }
  H2Stream* s = nullptr;
  for (int i = 0; i < kRxH2Streams; i++)
    if (c->h2_streams[i].id == 0) {
      s = &c->h2_streams[i];
      break;
    }
  if (!s || !h2_out_room(c, static_cast<int>(n) + 9)) {
    // h2_can_admit guards both; belt+braces.
    target_queue_push(c->target, task, /*front=*/1);
    return;
  }
  memset(s, 0, sizeof *s);
  s->id = c->h2_next_stream;
  c->h2_next_stream += 2;
  s->task = task;
  s->content_len = -1;
  c->h2_nstreams++;
  task->start_ns = tb_now_ns();
  h2_out_frame(c, 1, 0x4 | 0x1 /*END_HEADERS|END_STREAM*/, s->id, hb,
               static_cast<uint32_t>(n));
  tb_stat_add(TB_STAT_H2_STREAMS_OPENED, 1);
  tb_stat_add(TB_STAT_REACTOR_H2_STREAMS, 1);
}

// Return consumed flow-control credit. The WHOLE DATA frame length
// (padding included) counts against both windows, so the caller credits
// once per frame. Updates are queued at half-window consumption;
// REACTOR_FLOW_STALL_NS measures how long queued credit waits for the
// wire (stamped here, settled when h2_out drains).
static void h2_credit(Conn* c, H2Stream* s, int64_t nbytes) {
  c->h2_conn_unacked += nbytes;
  if (s) s->unacked += nbytes;
  int queued = 0;
  uint8_t wu[4];
  if (c->h2_conn_unacked > kRxConnWindow / 2 && h2_out_room(c, 13)) {
    h2::put32(wu, static_cast<uint32_t>(c->h2_conn_unacked));
    h2_out_frame(c, 8, 0, 0, wu, 4);
    c->h2_conn_unacked = 0;
    tb_stat_add(TB_STAT_H2_WINDOW_UPDATES_TX, 1);
    queued = 1;
  }
  if (s && s->unacked > kRxStreamWindow / 2 && h2_out_room(c, 13)) {
    h2::put32(wu, static_cast<uint32_t>(s->unacked));
    h2_out_frame(c, 8, 0, s->id, wu, 4);
    s->unacked = 0;
    tb_stat_add(TB_STAT_H2_WINDOW_UPDATES_TX, 1);
    queued = 1;
  }
  if (queued && !c->h2_wu_queued_ns) c->h2_wu_queued_ns = tb_now_ns();
}

// A stream's response ended (END_STREAM): settle against content-length
// the way the h1 machine settles against TB_ESHORT.
static void h2_stream_end(Loop* L, Conn* c, H2Stream* s) {
  if (s->content_len >= 0 && s->body_got != s->content_len) {
    h2_stream_fail(L, c, s,
                   s->body_got < s->content_len ? TB_ESHORT : TB_EPROTO);
    return;
  }
  h2_stream_done(L, c, s, s->body_got);
}

// Parse one complete header block for a stream (response HEADERS or
// trailers). Returns 0, or a connection-fatal code.
static int64_t h2_on_header_block(Loop* L, Conn* c, const uint8_t* p,
                                  size_t n, uint32_t stream_id,
                                  int end_stream) {
  int status = 0;
  int64_t clen = -1;
  if (h2::parse_header_block(p, n, nullptr, &status, &clen) != 0)
    return TB_EPROTO;
  H2Stream* s = rx_h2_stream_of(c, stream_id);
  if (!s) return 0;  // already settled (e.g. RST after overflow)
  if (!s->got_headers) {
    s->got_headers = 1;
    s->status = status ? status : s->status;
    if (clen >= 0) s->content_len = clen;
    if (s->task->first_byte_ns == 0) s->task->first_byte_ns = tb_now_ns();
    // The h1 machine rejects a known-length body that can't fit before
    // landing a byte; same here.
    if (s->task->buf && s->content_len > s->task->buf_len) {
      h2_stream_fail(L, c, s, TB_ETOOBIG);
      return 0;
    }
  }
  if (end_stream && s->id) h2_stream_end(L, c, s);
  return 0;
}

// Dispatch one fully-buffered non-DATA frame (payload in c->hdr).
// Returns 0, or a code that fails the whole connection.
static int64_t h2_on_frame(Loop* L, Conn* c) {
  const uint8_t* p = c->hdr;
  uint32_t len = c->h2_flen;
  switch (c->h2_ftype) {
    case 1: {  // HEADERS
      uint32_t off = 0, end = len;
      if (c->h2_fflags & 0x8) {  // PADDED
        if (len < 1) return TB_EPROTO;
        uint8_t pl = p[0];
        off = 1;
        if (1u + pl > len) return TB_EPROTO;
        end = len - pl;
      }
      if (c->h2_fflags & 0x20) {  // PRIORITY fields
        if (off + 5 > end) return TB_EPROTO;
        off += 5;
      }
      if (off > end) return TB_EPROTO;
      if (c->h2_fflags & 0x4) {  // END_HEADERS: parse in place
        return h2_on_header_block(L, c, p + off, end - off, c->h2_fstream,
                                  c->h2_fflags & 0x1);
      }
      // CONTINUATION follows: start accumulating.
      if (!c->h2_hb) {
        c->h2_hb = static_cast<uint8_t*>(malloc(kRxH2HbCap));
        if (!c->h2_hb) return -ENOMEM;
      }
      if (end - off > static_cast<uint32_t>(kRxH2HbCap)) return TB_EPROTO;
      memcpy(c->h2_hb, p + off, end - off);
      c->h2_hb_len = static_cast<int>(end - off);
      c->h2_hdr_stream = c->h2_fstream;
      c->h2_hdr_flags = c->h2_fflags;
      c->h2_hdr_cont = 1;
      return 0;
    }
    case 9: {  // CONTINUATION
      if (!c->h2_hdr_cont || c->h2_fstream != c->h2_hdr_stream)
        return TB_EPROTO;
      if (c->h2_hb_len + len > static_cast<uint32_t>(kRxH2HbCap))
        return TB_EPROTO;
      memcpy(c->h2_hb + c->h2_hb_len, p, len);
      c->h2_hb_len += static_cast<int>(len);
      if (c->h2_fflags & 0x4) {
        c->h2_hdr_cont = 0;
        return h2_on_header_block(L, c, c->h2_hb,
                                  static_cast<size_t>(c->h2_hb_len),
                                  c->h2_hdr_stream, c->h2_hdr_flags & 0x1);
      }
      return 0;
    }
    case 3: {  // RST_STREAM
      if (len != 4) return TB_EPROTO;
      tb_stat_add(TB_STAT_H2_RST_RX, 1);
      H2Stream* s = rx_h2_stream_of(c, c->h2_fstream);
      if (s) h2_stream_fail(L, c, s, -ECONNRESET);
      return 0;
    }
    case 4: {  // SETTINGS
      if (c->h2_fflags & 0x1) return 0;  // ACK of ours
      if (len % 6 != 0) return TB_EPROTO;
      for (uint32_t i = 0; i + 6 <= len; i += 6) {
        uint16_t id = static_cast<uint16_t>(p[i] << 8 | p[i + 1]);
        uint32_t v = static_cast<uint32_t>(p[i + 2]) << 24 |
                     static_cast<uint32_t>(p[i + 3]) << 16 |
                     static_cast<uint32_t>(p[i + 4]) << 8 | p[i + 5];
        if (id == 0x3)  // MAX_CONCURRENT_STREAMS (0 would deadlock: clamp)
          c->h2_peer_max_streams =
              v == 0 ? 1
                     : (v > static_cast<uint32_t>(kRxH2Streams)
                            ? kRxH2Streams
                            : static_cast<int>(v));
      }
      if (!h2_out_room(c, 9)) return -ENOMEM;
      h2_out_frame(c, 4, 0x1 /*ACK*/, 0, nullptr, 0);
      return 0;
    }
    case 6: {  // PING
      if (len != 8) return TB_EPROTO;
      if (c->h2_fflags & 0x1) return 0;
      if (!h2_out_room(c, 17)) return -ENOMEM;
      h2_out_frame(c, 6, 0x1 /*ACK*/, 0, p, 8);
      return 0;
    }
    case 7:  // GOAWAY: the peer is done with this connection
      tb_stat_add(TB_STAT_H2_GOAWAY_RX, 1);
      return -ECONNRESET;
    case 5:  // PUSH_PROMISE with ENABLE_PUSH=0 advertised is a violation
      return TB_EPROTO;
    default:  // PRIORITY / WINDOW_UPDATE (we send no DATA) / unknown
      return 0;
  }
}
static void conn_begin(Loop* L, Conn* c, fp::Task* task) {
  c->task = task;
  c->resp_bytes = 0;
  c->hlen = 0;
  c->status = 0;
  c->http_minor = 0;
  c->server_close = 0;
  c->junk = 0;
  c->content_len = -1;
  c->body_got = 0;
  c->lo_off = c->lo_len = 0;
  task->start_ns = tb_now_ns();
  c->req_len = snprintf(
      c->req, sizeof c->req,
      "GET %s HTTP/1.1\r\nHost: %s:%d\r\nUser-Agent: tpubench-native\r\n"
      "%s\r\n",
      task->path, task->host, task->port, task->headers);
  c->req_off = 0;
  if (c->req_len <= 0 || c->req_len >= static_cast<int>(sizeof c->req)) {
    complete_task(L, c->task, TB_EPROTO);
    c->task = nullptr;
    c->state = C_IDLE;
    return;
  }
  c->state = C_SEND;
  c->last_activity_ns = tb_now_ns();
  conn_want(c, EPOLLIN | EPOLLOUT);
}

static void conn_io(Loop* L, Conn* c);

// ---- TLS handshake (nonblocking SSL_connect off epoll readiness) ----

// Attach an SSL object to a connected fd: SNI + hostname verification +
// ALPN offer + cached-session resumption, mirroring tb_conn_tls's setup.
// Returns 0 (state = C_TLS_HANDSHAKE) or TB_ETLS.
static int64_t rx_tls_begin(Loop* L, Conn* c) {
  Reactor* r = L->r;
  Target* t = c->target;
  void* ssl = tls::SSL_new_(r->ssl_ctx);
  if (!ssl) return TB_ETLS;
  // SNI (SSL_set_tlsext_host_name macro = SSL_ctrl 55/0).
  tls::SSL_ctrl_(ssl, 55, 0, t->host);
  if (!r->insecure) {
    void* param = tls::SSL_get0_param_(ssl);
    struct in_addr a4;
    struct in6_addr a6;
    int is_ip = inet_pton(AF_INET, t->host, &a4) == 1 ||
                inet_pton(AF_INET6, t->host, &a6) == 1;
    int ok = is_ip ? tls::X509_VERIFY_PARAM_set1_ip_asc_(param, t->host)
                   : tls::X509_VERIFY_PARAM_set1_host_(param, t->host, 0);
    if (ok != 1) {
      tls::SSL_free_(ssl);
      return TB_ETLS;
    }
  }
  if (r->h2_mode == 1) {
    // Offer h2 AND http/1.1: unlike the gRPC conn path, the reactor has
    // an h1 state machine to fall back to when the server declines h2.
    static const unsigned char kAlpn[] = {2,  'h', '2', 8,   'h', 't',
                                          't', 'p', '/', '1', '.', '1'};
    if (tls::SSL_set_alpn_protos_(ssl, kAlpn, sizeof kAlpn) != 0) {
      tls::SSL_free_(ssl);
      return TB_ETLS;
    }
  }
  if (t->tls_session) tls::SSL_set_session_(ssl, t->tls_session);
  if (tls::SSL_set_fd_(ssl, c->fd) != 1) {
    tls::SSL_free_(ssl);
    return TB_ETLS;
  }
  c->ssl = ssl;
  c->state = C_TLS_HANDSHAKE;
  return 0;
}

static void conn_transport_ready(Loop* L, Conn* c);

// Drive SSL_connect one readiness notification's worth: WANT_READ /
// WANT_WRITE retune the epoll interest; completion classifies ALPN and
// hands off; failure is terminal for the pending task (handshakes only
// ever run on fresh sockets — legacy parity with the worker's
// tb_conn_tls failure path, transient-errno carve-out included).
static void rx_tls_handshake(Loop* L, Conn* c) {
  errno = 0;
  int k = tls::SSL_connect_(c->ssl);
  if (k == 1) {
    tb_stat_add(TB_STAT_TLS_HANDSHAKES, 1);
    tb_stat_add(TB_STAT_REACTOR_TLS_HANDSHAKES, 1);
    if (tls::SSL_session_reused_(c->ssl))
      tb_stat_add(TB_STAT_REACTOR_TLS_RESUMES, 1);
    if (L->r->h2_mode == 1) {
      const unsigned char* sel = nullptr;
      unsigned sel_len = 0;
      tls::SSL_get0_alpn_selected_(c->ssl, &sel, &sel_len);
      if (sel_len == 2 && memcmp(sel, "h2", 2) == 0) c->h2 = 1;
    }
    conn_transport_ready(L, c);
    return;
  }
  int err = tls::SSL_get_error_(c->ssl, k);
  if (err == kSslErrWantRead) {
    conn_want(c, EPOLLIN);
    return;
  }
  if (err == kSslErrWantWrite) {
    conn_want(c, EPOLLOUT);
    return;
  }
  int e = errno;
  int64_t code = (e == EAGAIN || e == EWOULDBLOCK || e == ETIMEDOUT ||
                  e == ECONNRESET || e == EPIPE || e == EINTR)
                     ? -e
                     : TB_ETLS;
  fp::Task* task = c->task;
  c->task = nullptr;
  Target* t = c->target;
  conn_free(L, c);
  if (task) complete_task(L, task, code);
  pump_target(L, t);
}

// The transport (TCP, and TLS when configured) is up: start the h2
// session or begin the pending h1 request.
static void conn_transport_ready(Loop* L, Conn* c) {
  fp::Task* task = c->task;
  c->task = nullptr;
  if (c->h2) {
    if (h2_session_begin(c) != 0) {
      Target* t = c->target;
      conn_free(L, c);
      if (task) complete_task(L, task, -ENOMEM);
      pump_target(L, t);
      return;
    }
    if (task) h2_admit(L, c, task);
    conn_h2_io(L, c);  // flush the prologue + HEADERS now
    if (!c->dead) pump_target(L, c->target);
    return;
  }
  if (!task) {  // nothing pending anymore (cannot happen today)
    c->state = C_IDLE;
    conn_want(c, EPOLLIN);
    pump_target(L, c->target);
    return;
  }
  conn_begin(L, c, task);
  if (c->task && c->state == C_SEND) conn_io(L, c);
}

// TCP connect completed: count it and enter the transport bring-up.
static void conn_connected(Loop* L, Conn* c) {
  tb_stat_add(TB_STAT_CONNECTS, 1);
  if (L->r->tls) {
    int64_t rc = rx_tls_begin(L, c);
    if (rc != 0) {
      fp::Task* task = c->task;
      c->task = nullptr;
      Target* t = c->target;
      conn_free(L, c);
      if (task) complete_task(L, task, rc);
      pump_target(L, t);
      return;
    }
    rx_tls_handshake(L, c);
    return;
  }
  conn_transport_ready(L, c);
}

// Open a new nonblocking connection for `t` carrying `task`.
static void conn_open(Loop* L, Target* t, fp::Task* task) {
  if (!t->resolved) {
    char portstr[16];
    snprintf(portstr, sizeof portstr, "%d", t->port);
    struct addrinfo hints, *res = nullptr;
    memset(&hints, 0, sizeof hints);
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(t->host, portstr, &hints, &res) != 0 || !res) {
      complete_task(L, task, TB_ERESOLVE);
      return;
    }
    memcpy(&t->addr, res->ai_addr, res->ai_addrlen);
    t->addr_len = res->ai_addrlen;
    freeaddrinfo(res);
    t->resolved = 1;
  }
  int fd = socket(t->addr.ss_family, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) {
    complete_task(L, task, -errno);
    return;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  Conn* c = static_cast<Conn*>(calloc(1, sizeof(Conn)));
  if (!c) {
    close(fd);
    complete_task(L, task, -ENOMEM);
    return;
  }
  c->fd = fd;
  c->loop = L;
  c->target = t;
  c->fresh = 1;
  if (L->r->h2_mode == 2) c->h2 = 1;  // prior-knowledge h2c
  c->task = task;  // pending: begun once the transport is up
  task->start_ns = tb_now_ns();
  c->next = t->conns;
  t->conns = c;
  t->n_conns++;
  int rc = connect(fd, reinterpret_cast<struct sockaddr*>(&t->addr),
                   t->addr_len);
  if (rc == 0) {
    conn_connected(L, c);
    return;
  }
  if (errno != EINPROGRESS) {
    // conn_fail would retransmit; a connect failure on a FRESH socket is
    // terminal for the task (legacy parity: tb_http_connect error).
    int cerr = errno;
    c->task = nullptr;
    conn_free(L, c);
    complete_task(L, task, -cerr);
    pump_target(L, t);
    return;
  }
  c->state = C_CONNECTING;
  conn_want(c, EPOLLOUT);
}

// Admit queued tasks: reuse idle connections first, then open new ones
// up to this loop's connection budget — the multiplexing that lets many
// in-flight GETs share few sockets. Exception: a task on its
// stale-keep-alive RETRANSMIT (attempt > 0) must land on a FRESH socket
// (the legacy-pool contract) — another idle keep-alive conn may be
// exactly as stale (a server idle-timeout typically FINs the whole pool
// at once), and a second stale failure would surface a spurious error.
static void pump_target(Loop* L, Target* t) {
  for (;;) {
    if (!t->q_head) return;
    // h2: established connections with free stream slots take queued
    // tasks first — the FIFO's in-flight dimension is stream ids, not
    // sockets. Retransmits still demand a FRESH socket (below).
    if (t->q_head->attempt == 0) {
      Conn* hc = nullptr;
      for (Conn* c = t->conns; c; c = c->next)
        if (h2_can_admit(c)) {
          hc = c;
          break;
        }
      if (hc) {
        h2_admit(L, hc, target_queue_pop(t));
        conn_h2_io(L, hc);  // flush the HEADERS now
        continue;
      }
    }
    Conn* idle = nullptr;
    for (Conn* c = t->conns; c; c = c->next)
      if (c->state == C_IDLE && !c->task) {
        idle = c;
        break;
      }
    if (t->q_head->attempt > 0) {
      if (t->n_conns >= L->max_conns) {
        if (!idle) return;   // all busy: wait for capacity
        conn_free(L, idle);  // suspect idle socket makes the room
      }
      conn_open(L, t, target_queue_pop(t));
      continue;
    }
    if (idle) {
      fp::Task* task = target_queue_pop(t);
      conn_begin(L, idle, task);
      if (idle->state == C_SEND) conn_io(L, idle);
      continue;
    }
    if (t->n_conns >= L->max_conns) return;
    fp::Task* task = target_queue_pop(t);
    conn_open(L, t, task);
  }
}

// ---- response parsing (nonblocking flavor of http_begin) ----
static int64_t parse_headers(Conn* c) {
  c->hdr[c->hlen] = 0;
  char* h = reinterpret_cast<char*>(c->hdr);
  char* p = static_cast<char*>(memmem(h, c->hlen, "\r\n\r\n", 4));
  if (!p) return 1;  // need more bytes
  char* body_start = p + 4;
  int body_in_hdr = c->hlen - static_cast<int>(body_start - h);
  if (sscanf(h, "HTTP/1.%d %d", &c->http_minor, &c->status) != 2)
    return TB_EPROTO;
  for (char* line = h; line < body_start;) {
    char* eol = static_cast<char*>(memmem(line, body_start - line, "\r\n", 2));
    if (!eol) break;
    if (strncasecmp(line, "Content-Length:", 15) == 0)
      c->content_len = strtoll(line + 15, nullptr, 10);
    if (strncasecmp(line, "Transfer-Encoding:", 18) == 0) {
      for (char* q = line + 18; q + 7 <= eol; q++)
        if (strncasecmp(q, "chunked", 7) == 0) return TB_ECHUNKED;
    }
    if (strncasecmp(line, "Connection:", 11) == 0) {
      for (char* q = line + 11; q + 5 <= eol; q++)
        if (strncasecmp(q, "close", 5) == 0) c->server_close = 1;
    }
    line = eol + 2;
  }
  // Keep-alive response with no body delimiter: unreadable (the reactor
  // never sends "Connection: close" — it exists to pool connections).
  if (c->content_len < 0 && !c->server_close && c->http_minor >= 1)
    return TB_EPROTO;
  c->lo_off = static_cast<int>(body_start - h);
  c->lo_len = c->lo_off + body_in_hdr;
  if (c->content_len >= 0 && body_in_hdr > c->content_len) c->junk = 1;
  return 0;
}

// Land body bytes into the task's destination (or the loop's discard
// scratch). Returns dest pointer + capacity for the next recv.
static uint8_t* body_dest(Loop* L, Conn* c, int64_t* cap) {
  fp::Task* t = c->task;
  if (t->buf == nullptr) {
    *cap = kDiscardWin;
    return L->scratch;
  }
  *cap = t->buf_len - c->body_got;
  return t->buf + c->body_got;
}

static void conn_body_done(Loop* L, Conn* c) { conn_finish(L, c); }

// One readiness notification worth of I/O on a connection: advance the
// state machine until EAGAIN or the task settles.
static void conn_io(Loop* L, Conn* c) {
  c->last_activity_ns = tb_now_ns();
  if (c->state == C_H2) {
    conn_h2_io(L, c);
    return;
  }
  if (c->state == C_TLS_HANDSHAKE) {
    rx_tls_handshake(L, c);
    return;
  }
  if (c->state == C_CONNECTING) {
    int err = 0;
    socklen_t len = sizeof err;
    getsockopt(c->fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if (err != 0) {
      // Fresh-socket connect failure: terminal (legacy parity).
      fp::Task* task = c->task;
      c->task = nullptr;
      Target* t = c->target;
      conn_free(L, c);
      if (task) complete_task(L, task, -err);
      pump_target(L, t);
      return;
    }
    conn_connected(L, c);
    return;
  }
  if (c->state == C_SEND) {
    while (c->req_off < c->req_len) {
      ssize_t k = rx_send(c, c->req + c->req_off, c->req_len - c->req_off);
      if (k < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          // A TLS write can want READ readiness (and vice versa): follow
          // the state machine, not the socket direction.
          conn_want(c, c->ssl && c->tls_want == EPOLLIN
                           ? EPOLLIN
                           : EPOLLIN | EPOLLOUT);
          return;
        }
        conn_fail(L, c, errno ? -errno : -ECONNRESET);
        return;
      }
      c->req_off += static_cast<int>(k);
    }
    c->state = C_HDR;
    conn_want(c, EPOLLIN);
  }
  if (c->state == C_HDR) {
    for (;;) {
      int cap = static_cast<int>(sizeof c->hdr) - 1 - c->hlen;
      if (cap <= 0) {
        conn_fail(L, c, TB_EPROTO);
        return;
      }
      ssize_t k = rx_recv(c, c->hdr + c->hlen, cap);
      if (k < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          conn_want(c, c->ssl && c->tls_want == EPOLLOUT
                           ? EPOLLIN | EPOLLOUT
                           : EPOLLIN);
          return;
        }
        conn_fail(L, c, errno ? -errno : -ECONNRESET);
        return;
      }
      if (k == 0) {
        conn_fail(L, c, TB_ESHORT);
        return;
      }
      c->resp_bytes = 1;
      if (c->task->first_byte_ns == 0) c->task->first_byte_ns = tb_now_ns();
      c->hlen += static_cast<int>(k);
      int64_t rc = parse_headers(c);
      if (rc == 1) continue;  // headers incomplete
      if (rc != 0) {
        conn_fail(L, c, rc);
        return;
      }
      c->state = C_BODY;
      // Serve leftover body bytes that rode in with the headers.
      int64_t lo = c->lo_len - c->lo_off;
      if (lo > 0) {
        if (c->content_len >= 0 && lo > c->content_len) lo = c->content_len;
        if (c->task->buf != nullptr) {
          if (lo > c->task->buf_len) {
            conn_fail(L, c, TB_ETOOBIG);
            return;
          }
          memcpy(c->task->buf, c->hdr + c->lo_off, lo);
        }
        c->body_got = lo;
      }
      if (c->content_len >= 0 && c->body_got >= c->content_len) {
        conn_body_done(L, c);
        return;
      }
      break;
    }
  }
  if (c->state == C_BODY) {
    for (;;) {
      int64_t cap = 0;
      uint8_t* dst = body_dest(L, c, &cap);
      int64_t left = c->content_len >= 0 ? c->content_len - c->body_got
                                         : INT64_MAX;
      if (c->task->buf != nullptr && cap <= 0 && left > 0) {
        if (c->content_len >= 0) {  // known length doesn't fit
          conn_fail(L, c, TB_ETOOBIG);
          return;
        }
        // Close-delimited body that exactly fills the buffer: probe one
        // byte — EOF proves an exact fit; more data is a real overflow
        // (legacy request_on parity).
        uint8_t probe;
        ssize_t pk = rx_recv(c, &probe, 1);
        if (pk < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            conn_want(c, c->ssl && c->tls_want == EPOLLOUT
                             ? EPOLLIN | EPOLLOUT
                             : EPOLLIN);
            return;
          }
          conn_fail(L, c, errno ? -errno : -ECONNRESET);
          return;
        }
        if (pk == 0) {
          conn_body_done(L, c);
          return;
        }
        conn_fail(L, c, TB_ETOOBIG);
        return;
      }
      int64_t want = cap < left ? cap : left;
      if (want <= 0) {
        conn_body_done(L, c);
        return;
      }
      ssize_t k = rx_recv(c, dst, static_cast<size_t>(want));
      if (k < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          conn_want(c, c->ssl && c->tls_want == EPOLLOUT
                           ? EPOLLIN | EPOLLOUT
                           : EPOLLIN);
          return;
        }
        conn_fail(L, c, errno ? -errno : -ECONNRESET);
        return;
      }
      if (k == 0) {
        if (c->content_len < 0) {
          conn_body_done(L, c);  // close-delimited: FIN ends the body
          return;
        }
        conn_fail(L, c, TB_ESHORT);
        return;
      }
      c->body_got += k;
      if (c->content_len >= 0 && c->body_got >= c->content_len) {
        conn_body_done(L, c);
        return;
      }
    }
  }
  if (c->state == C_IDLE) {
    // Readable while idle = server FIN or junk: either way, not a
    // connection we may reuse.
    Target* t = c->target;
    conn_free(L, c);
    pump_target(L, t);
  }
}

// One readiness notification worth of h2 I/O: drain the send buffer,
// then consume frames until EAGAIN. DATA payloads stream directly into
// task buffers (discard tasks land in the loop scratch); non-DATA frames
// buffer whole in c->hdr (bounded by the default 16384 MAX_FRAME_SIZE we
// never raise) and dispatch through h2_on_frame.
static void conn_h2_io(Loop* L, Conn* c) {
  if (c->dead) return;
  c->last_activity_ns = tb_now_ns();
  // ---- send side ----
  for (;;) {
    if (c->h2_out_off >= c->h2_out_len) {
      c->h2_out_off = c->h2_out_len = 0;
      if (c->h2_wu_queued_ns) {
        tb_stat_add(TB_STAT_REACTOR_FLOW_STALL_NS,
                    tb_now_ns() - c->h2_wu_queued_ns);
        c->h2_wu_queued_ns = 0;
      }
      break;
    }
    ssize_t k = rx_send(c, c->h2_out + c->h2_out_off,
                        c->h2_out_len - c->h2_out_off);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      h2_conn_fail(L, c, errno ? -errno : -ECONNRESET);
      return;
    }
    c->h2_out_off += static_cast<int>(k);
  }
  // ---- receive side ----
  int blocked = 0;
  while (!blocked) {
    if (c->h2_fh_got < 9) {
      ssize_t k = rx_recv(c, c->h2_fh + c->h2_fh_got, 9 - c->h2_fh_got);
      if (k < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          blocked = 1;
          break;
        }
        h2_conn_fail(L, c, errno ? -errno : -ECONNRESET);
        return;
      }
      if (k == 0) {
        // Orderly close: with streams in flight it's an early end
        // (TB_ESHORT, per-stream retransmit rule applies); an idle
        // keep-alive close settles nothing.
        h2_conn_fail(L, c, TB_ESHORT);
        return;
      }
      c->h2_fh_got += static_cast<int>(k);
      if (c->h2_fh_got < 9) continue;
      c->h2_flen = static_cast<uint32_t>(c->h2_fh[0]) << 16 |
                   static_cast<uint32_t>(c->h2_fh[1]) << 8 | c->h2_fh[2];
      c->h2_ftype = c->h2_fh[3];
      c->h2_fflags = c->h2_fh[4];
      c->h2_fstream = (static_cast<uint32_t>(c->h2_fh[5]) << 24 |
                       static_cast<uint32_t>(c->h2_fh[6]) << 16 |
                       static_cast<uint32_t>(c->h2_fh[7]) << 8 | c->h2_fh[8]) &
                      0x7fffffffu;
      tb_stat_add(TB_STAT_H2_FRAMES_RX, 1);
      if (c->h2_ftype == 0) {  // DATA: stream it
        tb_stat_add(TB_STAT_H2_DATA_BYTES_RX, c->h2_flen);
        // The WHOLE payload (padding included) counts against both
        // flow-control windows; credit once, up front.
        h2_credit(c, rx_h2_stream_of(c, c->h2_fstream), c->h2_flen);
        c->h2_data_rem = static_cast<int>(c->h2_flen);
        c->h2_pad_rem = 0;
        c->h2_pad_pending = (c->h2_fflags & 0x8) ? 1 : 0;
      } else {
        if (c->h2_flen > sizeof c->hdr) {
          h2_conn_fail(L, c, TB_EPROTO);
          return;
        }
        c->h2_fbuf_got = 0;
      }
    }
    if (c->h2_ftype == 0) {
      if (c->h2_pad_pending) {
        uint8_t pl;
        ssize_t k = rx_recv(c, &pl, 1);
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            blocked = 1;
            break;
          }
          h2_conn_fail(L, c, errno ? -errno : -ECONNRESET);
          return;
        }
        if (k == 0) {
          h2_conn_fail(L, c, TB_ESHORT);
          return;
        }
        if (1u + pl > c->h2_flen) {
          h2_conn_fail(L, c, TB_EPROTO);
          return;
        }
        c->h2_pad_pending = 0;
        c->h2_data_rem = static_cast<int>(c->h2_flen) - 1 - pl;
        c->h2_pad_rem = pl;
      }
      while (c->h2_data_rem > 0) {
        H2Stream* s = rx_h2_stream_of(c, c->h2_fstream);
        uint8_t* dst;
        int64_t cap;
        if (s && s->task->buf) {
          cap = s->task->buf_len - s->body_got;
          dst = s->task->buf + s->body_got;
          if (cap <= 0) {
            // Over-delivery into a sized buffer: stream-level TB_ETOOBIG
            // (permanent), cancel the stream, swallow the rest.
            uint32_t sid = s->id;
            h2_stream_fail(L, c, s, TB_ETOOBIG);
            if (h2_out_room(c, 13)) {
              uint8_t rst[4];
              h2::put32(rst, 0x8 /*CANCEL*/);
              h2_out_frame(c, 3, 0, sid, rst, 4);
            }
            continue;
          }
        } else {
          dst = L->scratch;
          cap = kDiscardWin;
        }
        int64_t want = cap < c->h2_data_rem ? cap : c->h2_data_rem;
        ssize_t k = rx_recv(c, dst, static_cast<size_t>(want));
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            blocked = 1;
            break;
          }
          h2_conn_fail(L, c, errno ? -errno : -ECONNRESET);
          return;
        }
        if (k == 0) {
          h2_conn_fail(L, c, TB_ESHORT);
          return;
        }
        c->h2_data_rem -= static_cast<int>(k);
        if (s) {
          if (s->body_got == 0 && s->task->first_byte_ns == 0)
            s->task->first_byte_ns = tb_now_ns();
          s->body_got += k;
        }
      }
      if (blocked) break;
      while (c->h2_pad_rem > 0) {
        int64_t want =
            c->h2_pad_rem < kDiscardWin ? c->h2_pad_rem : kDiscardWin;
        ssize_t k = rx_recv(c, L->scratch, static_cast<size_t>(want));
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            blocked = 1;
            break;
          }
          h2_conn_fail(L, c, errno ? -errno : -ECONNRESET);
          return;
        }
        if (k == 0) {
          h2_conn_fail(L, c, TB_ESHORT);
          return;
        }
        c->h2_pad_rem -= static_cast<int>(k);
      }
      if (blocked) break;
      if (c->h2_fflags & 0x1) {  // END_STREAM
        H2Stream* s = rx_h2_stream_of(c, c->h2_fstream);
        if (s) h2_stream_end(L, c, s);
      }
      c->h2_fh_got = 0;
      continue;
    }
    // Non-DATA: buffer the whole payload, then dispatch.
    while (c->h2_fbuf_got < static_cast<int>(c->h2_flen)) {
      ssize_t k =
          rx_recv(c, c->hdr + c->h2_fbuf_got, c->h2_flen - c->h2_fbuf_got);
      if (k < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          blocked = 1;
          break;
        }
        h2_conn_fail(L, c, errno ? -errno : -ECONNRESET);
        return;
      }
      if (k == 0) {
        h2_conn_fail(L, c, TB_ESHORT);
        return;
      }
      c->h2_fbuf_got += static_cast<int>(k);
    }
    if (blocked) break;
    int64_t rc = h2_on_frame(L, c);
    if (rc != 0) {
      h2_conn_fail(L, c, rc);
      return;
    }
    c->h2_fh_got = 0;
  }
  if (c->dead) return;
  uint32_t ev = EPOLLIN;
  if (c->h2_out_off < c->h2_out_len) ev |= EPOLLOUT;
  if (c->ssl && c->tls_want == EPOLLOUT) ev |= EPOLLOUT;
  conn_want(c, ev);
}

static Target* find_target(Loop* L, const char* host, int port) {
  for (Target* t = L->targets; t; t = t->next)
    if (t->port == port && strcmp(t->host, host) == 0) return t;
  Target* t = static_cast<Target*>(calloc(1, sizeof(Target)));
  if (!t) return nullptr;
  snprintf(t->host, sizeof t->host, "%s", host);
  t->port = port;
  t->next = L->targets;
  L->targets = t;
  return t;
}

static void dispatch_task(Loop* L, fp::Task* task) {
  Target* t = find_target(L, task->host, task->port);
  if (!t) {
    complete_task(L, task, -ENOMEM);
    return;
  }
  target_queue_push(t, task, /*front=*/0);
  pump_target(L, t);
}

static void sweep_timeouts(Loop* L) {
  int64_t now = tb_now_ns();
  for (Target* t = L->targets; t; t = t->next) {
    Conn* c = t->conns;
    while (c) {
      Conn* nxt = c->next;
      int busy = c->task != nullptr || (c->h2 && c->h2_nstreams > 0);
      if (busy && now - c->last_activity_ns > kIoTimeoutNs) {
        // Same surface as the legacy pool's SO_RCVTIMEO expiry: every
        // in-flight task fails -EAGAIN (transient, bypasses the stale
        // retransmit rule), the connection dies.
        fp::Task* task = c->task;
        c->task = nullptr;
        for (int si = 0; si < kRxH2Streams; si++) {
          if (!c->h2_streams[si].id) continue;
          fp::Task* st = c->h2_streams[si].task;
          c->h2_streams[si].id = 0;
          c->h2_streams[si].task = nullptr;
          c->h2_nstreams--;
          complete_task(L, st, -EAGAIN);
        }
        conn_free(L, c);
        if (task) complete_task(L, task, -EAGAIN);
        pump_target(L, t);
        // conn list mutated: restart the walk for this target.
        nxt = t->conns;
      }
      c = nxt;
    }
  }
}

static void* loop_main(void* arg) {
  Loop* L = static_cast<Loop*>(arg);
  Reactor* r = L->r;
  struct epoll_event evs[64];
  int64_t last_sweep = tb_now_ns();
  while (!__atomic_load_n(&r->shutdown, __ATOMIC_ACQUIRE)) {
    int n = epoll_wait(L->epfd, evs, 64, 500);
    tb_stat_add(TB_STAT_REACTOR_LOOPS, 1);
    if (n > 0) tb_stat_add(TB_STAT_REACTOR_EPOLL_EVENTS, n);
    if (__atomic_load_n(&r->shutdown, __ATOMIC_ACQUIRE)) break;
    for (int i = 0; i < n; i++) {
      if (evs[i].data.ptr == L) {
        // Submission doorbell: drain the eventfd, then the inbox.
        uint64_t v;
        ssize_t k = read(L->submit_efd, &v, sizeof v);
        (void)k;
        pthread_mutex_lock(&L->in_mu);
        fp::Task* head = L->in_head;
        L->in_head = L->in_tail = nullptr;
        pthread_mutex_unlock(&L->in_mu);
        while (head) {
          fp::Task* nxt = head->next;
          head->next = nullptr;
          dispatch_task(L, head);
          head = nxt;
        }
        continue;
      }
      Conn* c = static_cast<Conn*>(evs[i].data.ptr);
      if (c->dead) continue;  // closed earlier in this same batch
      if (evs[i].events & (EPOLLERR | EPOLLHUP)) {
        if (c->state == C_H2 && c->h2_nstreams > 0) {
          conn_io(L, c);  // h2 streams in flight: surface the error per stream
        } else if (c->state == C_IDLE || !c->task) {
          Target* t = c->target;
          conn_free(L, c);
          pump_target(L, t);
        } else if (c->state == C_BODY && c->content_len < 0) {
          conn_io(L, c);  // close-delimited body: HUP may carry the end
        } else {
          conn_io(L, c);  // let recv/getsockopt surface the real errno
        }
      } else {
        conn_io(L, c);
      }
      if (L->ding_pending >= kDingBatch) ding_flush(L);
    }
    int64_t now = tb_now_ns();
    if (now - last_sweep > 1000000000LL) {
      sweep_timeouts(L);
      last_sweep = now;
    }
    // Flush the coalesced doorbell BEFORE blocking again: a deferred
    // ring that survived into epoll_wait would leave the consumer
    // sleeping on ready completions. Then reap this batch's closed
    // conns — the next epoll_wait can't reference them.
    ding_flush(L);
    reap_dead(L);
  }
  ding_flush(L);  // shutdown path: wake a blocked consumer
  reap_dead(L);
  return nullptr;
}

static uint32_t pow2_at_least(uint32_t v) {
  uint32_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

static int64_t reactor_create(int conns, int cap, int n_loops, int tls,
                              const char* cafile, int insecure, int h2_mode) {
  if (conns <= 0 || cap <= 0) return 0;
  if (n_loops <= 0) n_loops = 1;
  if (n_loops > conns) n_loops = conns;
  if (n_loops > 16) n_loops = 16;
  Reactor* r = static_cast<Reactor*>(calloc(1, sizeof(Reactor)));
  if (!r) return 0;
  r->kind = fp::kPoolKindReactor;
  r->cap = cap;
  r->n_loops = n_loops;
  r->tls = tls;
  r->insecure = insecure;
  r->h2_mode = h2_mode;
  snprintf(r->cafile, sizeof r->cafile, "%s", cafile ? cafile : "");
  if (tls) {
    r->ssl_ctx = tls::get_ctx(r->cafile[0] ? r->cafile : nullptr, insecure);
    if (!r->ssl_ctx) {
      free(r);
      return 0;
    }
  }
  r->done_efd = eventfd(0, EFD_NONBLOCK);
  r->loops = static_cast<Loop*>(calloc(n_loops, sizeof(Loop)));
  if (r->done_efd < 0 || !r->loops) {
    if (r->done_efd >= 0) close(r->done_efd);
    free(r->loops);
    if (r->ssl_ctx) tls::SSL_CTX_free_(r->ssl_ctx);
    free(r);
    return 0;
  }
  uint32_t ring_cap = pow2_at_least(static_cast<uint32_t>(cap) + 1);
  int ok = 1;
  for (int i = 0; i < n_loops; i++) {
    Loop* L = &r->loops[i];
    L->r = r;
    L->epfd = epoll_create1(0);
    L->submit_efd = eventfd(0, EFD_NONBLOCK);
    L->ring = static_cast<fp::Task**>(calloc(ring_cap, sizeof(fp::Task*)));
    L->ring_mask = ring_cap - 1;
    L->scratch = static_cast<uint8_t*>(malloc(kDiscardWin));
    L->max_conns = conns / n_loops + (i < conns % n_loops ? 1 : 0);
    if (L->max_conns < 1) L->max_conns = 1;
    pthread_mutex_init(&L->in_mu, nullptr);
    if (L->epfd < 0 || L->submit_efd < 0 || !L->ring || !L->scratch) {
      ok = 0;
      continue;
    }
    struct epoll_event e;
    e.events = EPOLLIN;
    e.data.ptr = L;  // loop pointer marks the submit doorbell
    if (epoll_ctl(L->epfd, EPOLL_CTL_ADD, L->submit_efd, &e) != 0) ok = 0;
  }
  if (ok) {
    for (int i = 0; i < n_loops; i++) {
      Loop* L = &r->loops[i];
      if (pthread_create(&L->thread, nullptr, loop_main, L) == 0)
        L->started = 1;
      else
        ok = 0;
    }
  }
  if (!ok) {
    __atomic_store_n(&r->shutdown, 1, __ATOMIC_RELEASE);
    for (int i = 0; i < n_loops; i++) {
      Loop* L = &r->loops[i];
      if (L->started) {
        uint64_t one = 1;
        ssize_t k = write(L->submit_efd, &one, sizeof one);
        (void)k;
        pthread_join(L->thread, nullptr);
      }
      if (L->epfd >= 0) close(L->epfd);
      if (L->submit_efd >= 0) close(L->submit_efd);
      free(L->ring);
      free(L->scratch);
      pthread_mutex_destroy(&L->in_mu);
    }
    close(r->done_efd);
    free(r->loops);
    if (r->ssl_ctx) tls::SSL_CTX_free_(r->ssl_ctx);
    free(r);
    return 0;
  }
  return reinterpret_cast<int64_t>(r);
}

static int reactor_submit(Reactor* r, fp::Task* t) {
  if (__atomic_load_n(&r->shutdown, __ATOMIC_ACQUIRE)) {
    free(t);
    return -EINVAL;
  }
  // Admission cap: inflight is bounded by `cap`, which also bounds ring
  // depth (the ring can therefore never overflow).
  int cur = __atomic_load_n(&r->inflight, __ATOMIC_RELAXED);
  for (;;) {
    if (cur >= r->cap) {
      free(t);
      return -EAGAIN;
    }
    if (__atomic_compare_exchange_n(&r->inflight, &cur, cur + 1, true,
                                    __ATOMIC_ACQ_REL, __ATOMIC_RELAXED))
      break;
  }
  uint64_t i = __atomic_fetch_add(&r->rr, 1, __ATOMIC_RELAXED);
  Loop* L = &r->loops[i % r->n_loops];
  pthread_mutex_lock(&L->in_mu);
  t->next = nullptr;
  int was_empty = L->in_head == nullptr;
  if (L->in_tail)
    L->in_tail->next = t;
  else
    L->in_head = t;
  L->in_tail = t;
  pthread_mutex_unlock(&L->in_mu);
  // Doorbell only on the inbox's empty→nonempty transition: the loop
  // drains the WHOLE inbox per ding, so a burst of resubmissions costs
  // one syscall, not one per task.
  if (was_empty) {
    uint64_t one = 1;
    ssize_t k = write(L->submit_efd, &one, sizeof one);
    (void)k;
  }
  return 0;
}

// Consumer wait-and-drain. Returns count (0 on timeout). SPSC contract:
// one draining thread at a time.
static int reactor_drain(Reactor* r, int timeout_ms, int max_n,
                         fp::Task** out) {
  int n = ring_drain(r, max_n, out);
  if (n > 0 || timeout_ms == 0) return n;
  int64_t deadline =
      timeout_ms < 0 ? INT64_MAX : tb_now_ns() + timeout_ms * 1000000LL;
  for (;;) {
    if (__atomic_load_n(&r->shutdown, __ATOMIC_ACQUIRE) &&
        __atomic_load_n(&r->inflight, __ATOMIC_RELAXED) == 0)
      return 0;
    int64_t left_ms = timeout_ms < 0
                          ? 1000
                          : (deadline - tb_now_ns()) / 1000000LL;
    if (left_ms <= 0) return 0;
    if (left_ms > 1000) left_ms = 1000;  // bounded: shutdown stays visible
    struct pollfd pfd;
    pfd.fd = r->done_efd;
    pfd.events = POLLIN;
    int prc = poll(&pfd, 1, static_cast<int>(left_ms));
    if (prc > 0) {
      uint64_t v;
      ssize_t k = read(r->done_efd, &v, sizeof v);
      (void)k;
    }
    n = ring_drain(r, max_n, out);
    if (n > 0) return n;
  }
}

static int reactor_destroy(Reactor* r) {
  __atomic_store_n(&r->shutdown, 1, __ATOMIC_RELEASE);
  for (int i = 0; i < r->n_loops; i++) {
    uint64_t one = 1;
    ssize_t k = write(r->loops[i].submit_efd, &one, sizeof one);
    (void)k;
  }
  // Join EVERY loop thread before freeing anything it might touch —
  // the destroy-vs-in-flight-wake ordering the thread-per-connection
  // teardown never pinned.
  for (int i = 0; i < r->n_loops; i++)
    if (r->loops[i].started) pthread_join(r->loops[i].thread, nullptr);
  for (int i = 0; i < r->n_loops; i++) {
    Loop* L = &r->loops[i];
    // Undrained submissions.
    fp::Task* t = L->in_head;
    while (t) {
      fp::Task* nxt = t->next;
      free(t);
      t = nxt;
    }
    // Targets: queued tasks + live connections (their in-flight tasks
    // are cancelled; buffers stay caller-owned, and after the joins
    // above nothing writes into them anymore).
    Target* tg = L->targets;
    while (tg) {
      Target* tn = tg->next;
      fp::Task* q = tg->q_head;
      while (q) {
        fp::Task* qn = q->next;
        free(q);
        q = qn;
      }
      Conn* c = tg->conns;
      while (c) {
        Conn* cn = c->next;
        if (c->ssl) tls::SSL_free_(c->ssl);
        close(c->fd);
        tb_stat_add(TB_STAT_CONN_CLOSES, 1);
        for (int si = 0; si < kRxH2Streams; si++)
          if (c->h2_streams[si].id) free(c->h2_streams[si].task);
        free(c->h2_out);
        free(c->h2_hb);
        free(c->task);
        free(c);
        c = cn;
      }
      if (tg->tls_session) tls::SSL_SESSION_free_(tg->tls_session);
      free(tg);
      tg = tn;
    }
    // Undrained completions in the ring.
    uint32_t tl = __atomic_load_n(&L->ring_tail, __ATOMIC_RELAXED);
    uint32_t h = __atomic_load_n(&L->ring_head, __ATOMIC_ACQUIRE);
    while (tl != h) {
      free(L->ring[tl & L->ring_mask]);
      tl++;
    }
    close(L->epfd);
    close(L->submit_efd);
    free(L->ring);
    free(L->scratch);
    pthread_mutex_destroy(&L->in_mu);
  }
  close(r->done_efd);
  free(r->loops);
  if (r->ssl_ctx) tls::SSL_CTX_free_(r->ssl_ctx);
  free(r);
  return 0;
}

}  // namespace rx

// Create a fetch pool: `threads` workers, submission/completion capacity
// `cap` tasks; `tls` makes every worker connection TLS (verified against
// `cafile` or the system store, task host as SNI; `insecure` skips
// verification for self-signed test endpoints). Returns an opaque handle
// (or 0 on failure — including TLS requested but OpenSSL unavailable).
int64_t tb_pool_create(int threads, int cap, int tls, const char* cafile,
                       int insecure) {
  if (threads <= 0 || cap <= 0) return 0;
  if (tls && !tb_tls_available()) return 0;
  if (cafile && strlen(cafile) >= sizeof(fp::Pool{}.cafile)) return 0;
  fp::Pool* p = static_cast<fp::Pool*>(calloc(1, sizeof(fp::Pool)));
  if (!p) return 0;
  p->kind = fp::kPoolKindThreads;
  p->cap = cap;
  p->tls = tls;
  p->insecure = insecure;
  snprintf(p->cafile, sizeof p->cafile, "%s", cafile ? cafile : "");
  p->subq = static_cast<fp::Task**>(calloc(cap, sizeof(fp::Task*)));
  p->doneq = static_cast<fp::Task**>(calloc(cap, sizeof(fp::Task*)));
  p->threads = static_cast<pthread_t*>(calloc(threads, sizeof(pthread_t)));
  if (!p->subq || !p->doneq || !p->threads) {
    free(p->subq);
    free(p->doneq);
    free(p->threads);
    free(p);
    return 0;
  }
  pthread_mutex_init(&p->mu, nullptr);
  pthread_cond_init(&p->sub_cv, nullptr);
  pthread_cond_init(&p->done_cv, nullptr);
  // Only successfully spawned threads count (and get joined): under
  // RLIMIT_NPROC pressure a partial pool still serves; zero workers is a
  // creation failure.
  int created = 0;
  for (int i = 0; i < threads; i++) {
    if (pthread_create(&p->threads[created], nullptr, fp::worker_main, p) == 0)
      created++;
  }
  p->n_threads = created;
  if (created == 0) {
    pthread_mutex_destroy(&p->mu);
    pthread_cond_destroy(&p->sub_cv);
    pthread_cond_destroy(&p->done_cv);
    free(p->subq);
    free(p->doneq);
    free(p->threads);
    free(p);
    return 0;
  }
  return reinterpret_cast<int64_t>(p);
}

// Mode-aware pool creation. ``mode`` low byte: 0 = legacy
// thread-per-connection pool (exactly tb_pool_create), 1 = reactor
// (epoll event loop + SPSC completion rings); bits 8-15 carry the
// reactor loop-thread count (0 → 1); bit 16 (0x10000) offers h2 via
// ALPN and falls back to http/1.1 per the server's selection (TLS
// only); bit 17 (0x20000) speaks h2 with prior knowledge on plaintext
// sockets (h2c test servers). TLS in reactor mode is the same
// nonblocking state machine (handshake off epoll readiness, session
// resumption on keep-alive reconnect) — it no longer falls back to the
// legacy pool. In reactor mode ``threads`` is the CONNECTION budget,
// not a thread count: the loop multiplexes all of them; in-flight GETs
// beyond it queue per target (and, on h2, fan out as concurrent
// streams) and reuse keep-alive sockets as they free — many GETs, few
// sockets, zero per-request threads.
int64_t tb_pool_create2(int threads, int cap, int tls, const char* cafile,
                        int insecure, int mode) {
  int flavor = mode & 0xff;
  if (flavor == 0) return tb_pool_create(threads, cap, tls, cafile, insecure);
  if (flavor != 1) return 0;
  int loops = (mode >> 8) & 0xff;
  int h2_mode = (mode & 0x20000) ? 2 : ((mode & 0x10000) ? 1 : 0);
  if (h2_mode == 1 && !tls) return 0;  // ALPN needs a TLS handshake
  if (h2_mode == 2 && tls) return 0;   // prior knowledge is plaintext h2c
  if (tls && !tb_tls_available()) return 0;
  if (cafile && strlen(cafile) >= sizeof(rx::Reactor{}.cafile)) return 0;
  return rx::reactor_create(threads, cap, loops, tls, cafile, insecure,
                            h2_mode);
}

// 1 when the handle is a reactor-mode pool (introspection for tests and
// the Python mode label).
int tb_pool_is_reactor(int64_t h) {
  if (h == 0) return 0;
  return *reinterpret_cast<int*>(h) == fp::kPoolKindReactor ? 1 : 0;
}


// Submit one GET. The caller owns `buf` until the task completes (comes
// back from tb_pool_next). Returns 0, or -EAGAIN when the ring is full
// (the caller drains completions and resubmits), or -EINVAL.
int tb_pool_submit(int64_t h, const char* host, int port, const char* path,
                   const char* headers, void* buf, int64_t buf_len,
                   uint64_t tag) {
  if (h == 0) return -EINVAL;
  fp::Pool* p = reinterpret_cast<fp::Pool*>(h);
  if (!host || strlen(host) >= sizeof(fp::Task{}.host)) return -EINVAL;
  if (!path || strlen(path) >= sizeof(fp::Task{}.path)) return -EINVAL;
  if (headers && strlen(headers) >= sizeof(fp::Task{}.headers)) return -EINVAL;
  fp::Task* t = static_cast<fp::Task*>(calloc(1, sizeof(fp::Task)));
  if (!t) return -ENOMEM;
  snprintf(t->host, sizeof t->host, "%s", host);
  t->port = port;
  snprintf(t->path, sizeof t->path, "%s", path);
  snprintf(t->headers, sizeof t->headers, "%s", headers ? headers : "");
  t->buf = static_cast<uint8_t*>(buf);
  t->buf_len = buf_len;
  t->tag = tag;
  if (p->kind == fp::kPoolKindReactor)
    return rx::reactor_submit(reinterpret_cast<rx::Reactor*>(h), t);
  pthread_mutex_lock(&p->mu);
  if (p->inflight >= p->cap || p->shutdown) {
    int sd = p->shutdown;  // read under the lock
    pthread_mutex_unlock(&p->mu);
    free(t);
    return sd ? -EINVAL : -EAGAIN;
  }
  p->subq[(p->sub_head + p->sub_len) % p->cap] = t;
  p->sub_len++;
  p->inflight++;
  pthread_cond_signal(&p->sub_cv);
  pthread_mutex_unlock(&p->mu);
  return 0;
}

// Reactor drain → caller arrays: copy results, free tasks, settle the
// admission count, and keep the pool_* wake counters comparable across
// both executor flavors (completions/wakes stays THE batching ratio).
static int rx_drain_out(rx::Reactor* r, int timeout_ms, int max_n,
                        uint64_t* tags, int64_t* results, int* statuses,
                        int64_t* fbs, int64_t* totals, int64_t* starts) {
  fp::Task* batch[256];
  if (max_n > 256) max_n = 256;
  int n = rx::reactor_drain(r, timeout_ms, max_n, batch);
  for (int i = 0; i < n; i++) {
    fp::Task* t = batch[i];
    if (tags) tags[i] = t->tag;
    if (results) results[i] = t->result;
    if (statuses) statuses[i] = t->status;
    if (fbs) fbs[i] = t->first_byte_ns;
    if (totals) totals[i] = t->total_ns;
    if (starts) starts[i] = t->start_ns;
    free(t);
    __atomic_fetch_sub(&r->inflight, 1, __ATOMIC_ACQ_REL);
  }
  if (n > 0) {
    tb_stat_add(TB_STAT_POOL_WAKES, 1);
    tb_stat_add(TB_STAT_POOL_COMPLETIONS, n);
    if (n > 1) tb_stat_add(TB_STAT_POOL_BATCHED_WAKES, 1);
  }
  return n;
}

// Wait for one completion (timeout_ms < 0 = forever, 0 = poll). Fills the
// out params; returns 1 on a completion, 0 on timeout, -EINVAL on a bad
// handle. The completed task's buffer is back in the caller's hands.
int tb_pool_next(int64_t h, int timeout_ms, uint64_t* tag_out,
                 int64_t* result_out, int* status_out,
                 int64_t* first_byte_ns_out, int64_t* total_ns_out,
                 int64_t* start_ns_out) {
  if (h == 0) return -EINVAL;
  fp::Pool* p = reinterpret_cast<fp::Pool*>(h);
  if (p->kind == fp::kPoolKindReactor)
    return rx_drain_out(reinterpret_cast<rx::Reactor*>(h), timeout_ms, 1,
                        tag_out, result_out, status_out, first_byte_ns_out,
                        total_ns_out, start_ns_out);
  pthread_mutex_lock(&p->mu);
  if (p->done_len == 0) {
    if (timeout_ms == 0) {
      pthread_mutex_unlock(&p->mu);
      return 0;
    }
    if (timeout_ms < 0) {
      while (p->done_len == 0 && !(p->shutdown && p->inflight == 0))
        pthread_cond_wait(&p->done_cv, &p->mu);
    } else {
      struct timespec ts;
      clock_gettime(CLOCK_REALTIME, &ts);
      ts.tv_sec += timeout_ms / 1000;
      ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
      if (ts.tv_nsec >= 1000000000L) {
        ts.tv_sec++;
        ts.tv_nsec -= 1000000000L;
      }
      while (p->done_len == 0 && !(p->shutdown && p->inflight == 0)) {
        if (pthread_cond_timedwait(&p->done_cv, &p->mu, &ts) != 0) break;
      }
    }
    if (p->done_len == 0) {
      pthread_mutex_unlock(&p->mu);
      return 0;
    }
  }
  fp::Task* t = p->doneq[p->done_head];
  p->done_head = (p->done_head + 1) % p->cap;
  p->done_len--;
  p->inflight--;
  pthread_mutex_unlock(&p->mu);
  if (tag_out) *tag_out = t->tag;
  if (result_out) *result_out = t->result;
  if (status_out) *status_out = t->status;
  if (first_byte_ns_out) *first_byte_ns_out = t->first_byte_ns;
  if (total_ns_out) *total_ns_out = t->total_ns;
  if (start_ns_out) *start_ns_out = t->start_ns;
  free(t);
  tb_stat_add(TB_STAT_POOL_WAKES, 1);
  tb_stat_add(TB_STAT_POOL_COMPLETIONS, 1);
  return 1;
}

// Batched completion handoff: wait like tb_pool_next, then drain up to
// `max_n` ready completions in the SAME lock crossing — under fan-out,
// completions pile up while the consumer processes the previous one, so
// one wake amortizes the mutex/condvar cost across the whole backlog
// (the per-completion handoff tax BENCH_r05 measured). Fills the
// parallel out arrays; returns the count drained (0 on timeout),
// -EINVAL on a bad handle or max_n. max_n is clamped to 256.
int tb_pool_next_batch(int64_t h, int timeout_ms, int max_n,
                       uint64_t* tags_out, int64_t* results_out,
                       int* statuses_out, int64_t* first_byte_ns_out,
                       int64_t* total_ns_out, int64_t* start_ns_out) {
  if (h == 0 || max_n <= 0) return -EINVAL;
  fp::Pool* p = reinterpret_cast<fp::Pool*>(h);
  if (p->kind == fp::kPoolKindReactor)
    return rx_drain_out(reinterpret_cast<rx::Reactor*>(h), timeout_ms, max_n,
                        tags_out, results_out, statuses_out,
                        first_byte_ns_out, total_ns_out, start_ns_out);
  fp::Task* batch[256];
  if (max_n > 256) max_n = 256;
  pthread_mutex_lock(&p->mu);
  if (p->done_len == 0) {
    if (timeout_ms == 0) {
      pthread_mutex_unlock(&p->mu);
      return 0;
    }
    if (timeout_ms < 0) {
      while (p->done_len == 0 && !(p->shutdown && p->inflight == 0))
        pthread_cond_wait(&p->done_cv, &p->mu);
    } else {
      struct timespec ts;
      clock_gettime(CLOCK_REALTIME, &ts);
      ts.tv_sec += timeout_ms / 1000;
      ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
      if (ts.tv_nsec >= 1000000000L) {
        ts.tv_sec++;
        ts.tv_nsec -= 1000000000L;
      }
      while (p->done_len == 0 && !(p->shutdown && p->inflight == 0)) {
        if (pthread_cond_timedwait(&p->done_cv, &p->mu, &ts) != 0) break;
      }
    }
    if (p->done_len == 0) {
      pthread_mutex_unlock(&p->mu);
      return 0;
    }
  }
  int n = 0;
  while (p->done_len > 0 && n < max_n) {
    fp::Task* t = p->doneq[p->done_head];
    p->done_head = (p->done_head + 1) % p->cap;
    p->done_len--;
    p->inflight--;
    batch[n++] = t;
  }
  pthread_mutex_unlock(&p->mu);
  for (int i = 0; i < n; i++) {
    fp::Task* t = batch[i];
    if (tags_out) tags_out[i] = t->tag;
    if (results_out) results_out[i] = t->result;
    if (statuses_out) statuses_out[i] = t->status;
    if (first_byte_ns_out) first_byte_ns_out[i] = t->first_byte_ns;
    if (total_ns_out) total_ns_out[i] = t->total_ns;
    if (start_ns_out) start_ns_out[i] = t->start_ns;
    free(t);
  }
  tb_stat_add(TB_STAT_POOL_WAKES, 1);
  tb_stat_add(TB_STAT_POOL_COMPLETIONS, n);
  if (n > 1) tb_stat_add(TB_STAT_POOL_BATCHED_WAKES, 1);
  return n;
}

// The ring-drain entry point (the symbol whose ABSENCE marks a stale
// .so: Python degrades to tb_pool_next_batch, then to tb_pool_next).
// On a reactor pool this IS the lock-free SPSC drain; on a legacy pool
// it delegates to the mutex-guarded batch drain, so either symbol works
// on either handle.
int tb_pool_ring_next_batch(int64_t h, int timeout_ms, int max_n,
                            uint64_t* tags_out, int64_t* results_out,
                            int* statuses_out, int64_t* first_byte_ns_out,
                            int64_t* total_ns_out, int64_t* start_ns_out) {
  return tb_pool_next_batch(h, timeout_ms, max_n, tags_out, results_out,
                            statuses_out, first_byte_ns_out, total_ns_out,
                            start_ns_out);
}

// Shut down: workers finish queued tasks, then exit; joins all threads.
// Undrained completions are freed (their buffers stay caller-owned).
// Reactor pools CANCEL queued/in-flight tasks instead: the doorbell and
// rings are drained and every loop thread joined BEFORE anything is
// freed, so after destroy returns nothing writes into caller buffers.
int tb_pool_destroy(int64_t h) {
  if (h == 0) return -EINVAL;
  fp::Pool* p = reinterpret_cast<fp::Pool*>(h);
  if (p->kind == fp::kPoolKindReactor)
    return rx::reactor_destroy(reinterpret_cast<rx::Reactor*>(h));
  pthread_mutex_lock(&p->mu);
  p->shutdown = 1;
  pthread_cond_broadcast(&p->sub_cv);
  pthread_cond_broadcast(&p->done_cv);
  pthread_mutex_unlock(&p->mu);
  for (int i = 0; i < p->n_threads; i++) pthread_join(p->threads[i], nullptr);
  for (int i = 0; i < p->done_len; i++)
    free(p->doneq[(p->done_head + i) % p->cap]);
  pthread_mutex_destroy(&p->mu);
  pthread_cond_destroy(&p->sub_cv);
  pthread_cond_destroy(&p->done_cv);
  free(p->subq);
  free(p->doneq);
  free(p->threads);
  free(p);
  return 0;
}

// Test hook: run the structural HPACK parse over one header block and
// return the extracted grpc-status (-1 unknown) or TB_EPROTO — lets the
// huffman-coded trailer path be exercised directly (the hermetic grpc
// server happens to send grpc-status unencoded).
int tb_hpack_scan_status(const void* block, int64_t n) {
  int st = -1;
  int rc = h2::parse_header_block(static_cast<const uint8_t*>(block),
                                  static_cast<size_t>(n), &st);
  return rc != 0 ? rc : st;
}

// ------------------------------------------------- h2 stream machinery --
// One receive loop serves BOTH native h2 flavors — gRPC ReadObject
// streams (length-prefixed messages reassembled per stream, content
// extracted) and plain h2 GETs (DATA bytes land in the caller's buffer
// verbatim) — with CONCURRENT streams per connection: submit N, then
// poll completions. grpc-go multiplexes streams per connection by
// default (the reference's transport, go.mod:20); sequential-only was
// the round-2 limitation.

// Bring up the h2 session once per connection: client preface +
// SETTINGS(HEADER_TABLE_SIZE=0, INITIAL_WINDOW_SIZE=2^31-1,
// MAX_FRAME_SIZE=2^24-1) + connection WINDOW_UPDATE, and the stream
// table.
static int h2_ensure_session(tb_conn* c) {
  if (c->h2_started) return 0;
  int rc;
  if ((rc = h2::send_all(c, h2::kPreface, sizeof(h2::kPreface) - 1)) != 0)
    return rc;
  uint8_t st[18];
  uint8_t* p = st;
  p[0] = 0; p[1] = 1; h2::put32(p + 2, 0); p += 6;              // table 0
  p[0] = 0; p[1] = 4; h2::put32(p + 2, 0x7fffffffu); p += 6;    // window
  p[0] = 0; p[1] = 5; h2::put32(p + 2, 0x00ffffffu); p += 6;    // frame
  if ((rc = h2::send_frame(c, 4 /*SETTINGS*/, 0, 0, st, 18)) != 0) return rc;
  uint8_t wu[4];
  h2::put32(wu, 0x40000000u - 65535);
  if ((rc = h2::send_frame(c, 8 /*WINDOW_UPDATE*/, 0, 0, wu, 4)) != 0)
    return rc;
  if (!c->streams) {
    c->streams = static_cast<h2_stream*>(
        calloc(kH2MaxStreams, sizeof(h2_stream)));
    if (!c->streams) return -ENOMEM;
  }
  c->h2_started = 1;
  c->next_stream = 1;
  return 0;
}

static h2_stream* h2_find_stream(tb_conn* c, uint32_t id) {
  for (int i = 0; i < kH2MaxStreams; i++)
    if (c->streams[i].id == id) return &c->streams[i];
  return nullptr;
}

// Append caller metadata ("k: v\r\n" lines, e.g. authorization) to an
// HPACK block; h2 requires lowercase field names, enforced here rather
// than trusted. Returns new block length or 0 on malformed input.
static size_t h2_append_metadata(uint8_t* hb, size_t hn,
                                 const char* extra_headers) {
  for (const char* ph = extra_headers ? extra_headers : ""; *ph;) {
    const char* eol = strstr(ph, "\r\n");
    size_t line_len = eol ? static_cast<size_t>(eol - ph) : strlen(ph);
    const char* colon = static_cast<const char*>(memchr(ph, ':', line_len));
    if (!colon || colon == ph) return 0;
    char nbuf[128];
    size_t nl = static_cast<size_t>(colon - ph);
    if (nl >= sizeof nbuf) return 0;
    for (size_t i = 0; i < nl; i++)
      nbuf[i] = static_cast<char>(tolower(static_cast<unsigned char>(ph[i])));
    nbuf[nl] = 0;
    const char* v = colon + 1;
    while (*v == ' ' && v < ph + line_len) v++;
    char vbuf[4096];
    size_t vl = static_cast<size_t>(ph + line_len - v);
    if (vl >= sizeof vbuf) return 0;
    memcpy(vbuf, v, vl);
    vbuf[vl] = 0;
    hn += h2::hp_header(hb + hn, nbuf, vbuf);
    ph = eol ? eol + 2 : ph + line_len;
  }
  return hn;
}

// Open a stream slot with common init. ``*err_out`` distinguishes the two
// failure modes: -EAGAIN (table full — the caller polls a completion and
// retries) vs -ENOMEM (scratch allocation failed — retrying cannot help;
// reporting it as EAGAIN would spin the caller forever).
static h2_stream* h2_open_stream(tb_conn* c, uint64_t tag, void* buf,
                                 int64_t buf_len, int raw_body,
                                 int* err_out) {
  h2_stream* s = h2_find_stream(c, 0);
  if (!s) {
    *err_out = -EAGAIN;
    return nullptr;
  }
  memset(s, 0, sizeof *s);
  s->tag = tag;
  s->raw_body = raw_body;
  s->out = static_cast<uint8_t*>(buf);
  s->out_cap = buf_len;
  s->grpc_status = -1;
  s->http_status = -1;
  s->content_len = -1;
  s->t_start = tb_now_ns();
  if (!raw_body) {
    s->scratch = c->scratch_pool_n
                     ? c->scratch_pool[--c->scratch_pool_n]
                     : static_cast<uint8_t*>(malloc(kGrpcScratchCap));
    if (!s->scratch) {
      *err_out = -ENOMEM;
      return nullptr;
    }
  }
  s->id = c->next_stream;
  c->next_stream += 2;
  tb_stat_add(TB_STAT_H2_STREAMS_OPENED, 1);
  return s;
}

static void h2_close_stream(tb_conn* c, h2_stream* s) {
  if (s->scratch) {
    if (c->scratch_pool_n <
        static_cast<int>(sizeof c->scratch_pool / sizeof c->scratch_pool[0]))
      c->scratch_pool[c->scratch_pool_n++] = s->scratch;
    else
      free(s->scratch);
    s->scratch = nullptr;
  }
  s->id = 0;
}

// Submit one gRPC ReadObject as a new concurrent stream. Returns 0, or
// -EAGAIN (stream table full — poll a completion first), or a fatal
// -errno/TB_* (the connection is then unusable).
int64_t tb_grpc_submit(int64_t h, const char* authority,
                       const char* bucket_path, const char* object_name,
                       const char* extra_headers, int64_t read_offset,
                       int64_t read_limit, void* buf, int64_t buf_len,
                       uint64_t tag) {
  if (h <= 0) return -EINVAL;
  // Headers land in hb[8192] (fixed fields ~120 B + authority + extra
  // metadata such as an OAuth bearer token) and the request proto in
  // req[2048] (framing ~30 B + bucket + object): bound the
  // caller-supplied strings so neither buffer can overflow. GCS caps
  // object names at 1024 bytes — these limits sit above real use.
  if (!authority || strlen(authority) > 512) return -EINVAL;
  if (!bucket_path || !object_name ||
      strlen(bucket_path) + strlen(object_name) > 1700)
    return -EINVAL;
  if (extra_headers && strlen(extra_headers) > 4096) return -EINVAL;
  tb_conn* c = reinterpret_cast<tb_conn*>(h);
  int rc;
  if ((rc = h2_ensure_session(c)) != 0) return rc;
  int oerr = 0;
  h2_stream* s = h2_open_stream(c, tag, buf, buf_len, 0, &oerr);
  if (!s) return oerr;

  // HEADERS: the gRPC request headers, literal never-indexed.
  uint8_t hb[8192];
  size_t hn = 0;
  hn += h2::hp_header(hb + hn, ":method", "POST");
  hn += h2::hp_header(hb + hn, ":scheme", c->ssl ? "https" : "http");
  hn += h2::hp_header(hb + hn, ":path",
                      "/google.storage.v2.Storage/ReadObject");
  hn += h2::hp_header(hb + hn, ":authority", authority);
  hn += h2::hp_header(hb + hn, "content-type", "application/grpc");
  hn += h2::hp_header(hb + hn, "te", "trailers");
  size_t hn2 = h2_append_metadata(hb, hn, extra_headers);
  if (extra_headers && extra_headers[0] && hn2 == 0) {
    h2_close_stream(c, s);
    return -EINVAL;
  }
  hn = hn2 ? hn2 : hn;
  if ((rc = h2::send_frame(c, 1 /*HEADERS*/, 0x4 /*END_HEADERS*/, s->id, hb,
                           static_cast<uint32_t>(hn))) != 0) {
    h2_close_stream(c, s);
    return rc;
  }

  // DATA: 5-byte gRPC prefix + ReadObjectRequest proto, END_STREAM.
  uint8_t req[2048];
  size_t rn = 5;
  rn += h2::pb_str(req + rn, 1, bucket_path);
  rn += h2::pb_str(req + rn, 2, object_name);
  if (read_offset > 0) {
    req[rn++] = 4 << 3;  // field 4 varint
    rn += h2::pb_varint(req + rn, static_cast<uint64_t>(read_offset));
  }
  if (read_limit > 0) {
    req[rn++] = 5 << 3;  // field 5 varint
    rn += h2::pb_varint(req + rn, static_cast<uint64_t>(read_limit));
  }
  req[0] = 0;  // uncompressed — and no grpc-accept-encoding offered, so a
               // conformant server may not send compressed messages back
  h2::put32(req + 1, static_cast<uint32_t>(rn - 5));
  if ((rc = h2::send_frame(c, 0 /*DATA*/, 0x1 /*END_STREAM*/, s->id, req,
                           static_cast<uint32_t>(rn))) != 0) {
    h2_close_stream(c, s);
    return rc;
  }
  return 0;
}

// Submit one plain h2 GET (the HTTP/2 branch of the reference's client,
// main.go:76-80) as a new concurrent stream: DATA payload bytes land in
// ``buf`` verbatim; :status surfaces in the completion's http_status.
int64_t tb_h2_submit_get(int64_t h, const char* authority, const char* path,
                         const char* extra_headers, void* buf,
                         int64_t buf_len, uint64_t tag) {
  if (h <= 0) return -EINVAL;
  if (!authority || strlen(authority) > 512) return -EINVAL;
  if (!path || strlen(path) > 2048) return -EINVAL;
  if (extra_headers && strlen(extra_headers) > 4096) return -EINVAL;
  tb_conn* c = reinterpret_cast<tb_conn*>(h);
  int rc;
  if ((rc = h2_ensure_session(c)) != 0) return rc;
  int oerr = 0;
  h2_stream* s = h2_open_stream(c, tag, buf, buf_len, 1, &oerr);
  if (!s) return oerr;
  uint8_t hb[8192];
  size_t hn = 0;
  hn += h2::hp_header(hb + hn, ":method", "GET");
  hn += h2::hp_header(hb + hn, ":scheme", c->ssl ? "https" : "http");
  hn += h2::hp_header(hb + hn, ":path", path);
  hn += h2::hp_header(hb + hn, ":authority", authority);
  size_t hn2 = h2_append_metadata(hb, hn, extra_headers);
  if (extra_headers && extra_headers[0] && hn2 == 0) {
    h2_close_stream(c, s);
    return -EINVAL;
  }
  hn = hn2 ? hn2 : hn;
  // GET has no request body: END_STREAM rides the HEADERS frame.
  if ((rc = h2::send_frame(c, 1 /*HEADERS*/, 0x4 | 0x1, s->id, hb,
                           static_cast<uint32_t>(hn))) != 0) {
    h2_close_stream(c, s);
    return rc;
  }
  return 0;
}

// Receive ``payload`` DATA bytes for stream ``s`` DIRECTLY into its
// destination — raw flavor: the caller's buffer; gRPC flavor: the
// reassembly scratch (then content-extracted into the caller's buffer,
// the one copy the protobuf framing forces) — no intermediate chunk
// buffer on the hot path. Unknown/errored streams drain through a scrap
// buffer. Returns 0, or a connection-fatal -errno/TB_ESHORT; per-stream
// failures land in s->err (remaining payload is drained, the connection
// survives).
static int h2_recv_data(tb_conn* c, h2_stream* s, uint32_t payload) {
  int rc;
  uint32_t done = 0;
  while (done < payload) {
    if (!s || s->err) {  // discard: junk stream or already-failed stream
      uint8_t sink[65536];
      uint32_t w = payload - done;
      if (w > sizeof sink) w = sizeof sink;
      if ((rc = h2::recv_all(c, sink, w)) != 0) return rc;
      done += w;
      continue;
    }
    if (s->first_byte_ns == 0) s->first_byte_ns = tb_now_ns();
    if (s->raw_body) {
      uint32_t w = payload - done;
      if (static_cast<int64_t>(w) > s->out_cap - s->out_len) {
        s->err = TB_ETOOBIG;
        continue;
      }
      if ((rc = h2::recv_all(c, s->out + s->out_len, w)) != 0) return rc;
      s->out_len += w;
      done += w;
      continue;
    }
    if (s->msg_len == 0) {
      // Reading the 5-byte gRPC message prefix.
      uint8_t b;
      if ((rc = h2::recv_all(c, &b, 1)) != 0) return rc;
      done += 1;
      s->prefix[s->prefix_got++] = b;
      if (s->prefix_got == 5) {
        if (s->prefix[0] != 0) {
          // Compressed message: we never offered grpc-accept-encoding,
          // so this violates the negotiation (gRPC protocol spec §
          // "Message-Encoding") — reject loudly rather than mis-deliver.
          s->err = TB_EPROTO;
          continue;
        }
        s->msg_len = (static_cast<size_t>(s->prefix[1]) << 24) |
                     (s->prefix[2] << 16) | (s->prefix[3] << 8) |
                     s->prefix[4];
        s->msg_got = 0;
        s->prefix_got = 0;
        if (s->msg_len > kGrpcScratchCap) {
          s->err = TB_ETOOBIG;
          continue;
        }
        // msg_len == 0 (empty message): next iteration reads a prefix.
      }
      continue;
    }
    uint32_t want = payload - done;
    size_t need = s->msg_len - s->msg_got;
    if (want > need) want = static_cast<uint32_t>(need);
    if ((rc = h2::recv_all(c, s->scratch + s->msg_got, want)) != 0) return rc;
    s->msg_got += want;
    done += want;
    if (s->msg_got == s->msg_len) {
      int64_t k = h2::pb_extract_content(s->scratch, s->msg_len,
                                         s->out + s->out_len,
                                         s->out_cap - s->out_len);
      if (k < 0) {
        s->err = k;
        continue;
      }
      s->out_len += k;
      s->msg_len = 0;
      s->msg_got = 0;
    }
  }
  return 0;
}

// Mark stream terminal state at END_STREAM and compute its result.
static void h2_stream_finish(h2_stream* s) {
  s->done = 1;
  if (s->err) return;
  if (!s->raw_body) {
    if (s->msg_len != 0 || s->prefix_got != 0) s->err = TB_ESHORT;
    else if (!s->got_headers) s->err = TB_EPROTO;
    else if (s->grpc_status > 0) s->err = TB_EGRPC;
  } else if (!s->got_headers) {
    s->err = TB_EPROTO;
  } else if ((s->http_status == 200 || s->http_status == 206) &&
             s->content_len >= 0 && s->out_len < s->content_len) {
    // Cleanly END_STREAMed short of the announced content-length: a
    // truncated success is still a failure (proxy died mid-stream,
    // backend exhausted). Same rule as the h1 path's TB_ESHORT and
    // gcs_grpc read_ranges' short-stream rejection; scoped to success
    // statuses so error bodies keep their existing reporting path.
    s->err = TB_ESHORT;
  }
}

// Run the receive loop until SOME stream completes (or a connection-fatal
// error). Returns 1 with the completion out-params filled; 0 when no
// streams are active; negative on a fatal error — every in-flight stream
// on this connection is then dead and the caller must tb_conn_close it.
int64_t tb_grpc_poll(int64_t h, uint64_t* tag_out, int64_t* result_out,
                     int* grpc_status_out, int* http_status_out,
                     int64_t* first_byte_ns_out, int64_t* total_ns_out) {
  if (h <= 0) return -EINVAL;
  tb_conn* c = reinterpret_cast<tb_conn*>(h);
  if (!c->h2_started || !c->streams) return 0;
  int rc;
  uint64_t conn_unacked = 0;
  h2_stream* ready = nullptr;
  for (;;) {
    // A stream completed during an earlier pass (frames interleave)?
    for (int i = 0; i < kH2MaxStreams && !ready; i++)
      if (c->streams[i].id && c->streams[i].done) ready = &c->streams[i];
    if (ready) break;
    int any_active = 0;
    for (int i = 0; i < kH2MaxStreams; i++)
      if (c->streams[i].id) any_active = 1;
    if (!any_active) return 0;

    uint8_t fh[9];
    if ((rc = h2::recv_all(c, fh, 9)) != 0) return rc;
    uint32_t flen = (fh[0] << 16) | (fh[1] << 8) | fh[2];
    uint8_t ftype = fh[3];
    uint8_t fflags = fh[4];
    uint32_t fstream = ((fh[5] & 0x7f) << 24) | (fh[6] << 16) |
                       (fh[7] << 8) | fh[8];
    if (flen > (16u << 20)) return TB_EPROTO;
    tb_stat_add(TB_STAT_H2_FRAMES_RX, 1);
    if (ftype == 0) tb_stat_add(TB_STAT_H2_DATA_BYTES_RX, flen);
    switch (ftype) {
      case 0: {  // DATA
        h2_stream* s = h2_find_stream(c, fstream);
        if (!s && fstream == 0) return TB_EPROTO;
        uint32_t left = flen;
        uint32_t pad = 0;
        if (fflags & 0x8) {  // PADDED
          // A PADDED frame carries at least the pad-length byte; flen ==
          // 0 would otherwise consume a byte of the NEXT frame.
          if (flen < 1) return TB_EPROTO;
          uint8_t pl;
          if ((rc = h2::recv_all(c, &pl, 1)) != 0) return rc;
          pad = pl;
          left -= 1;
          if (pad + 1 > flen) return TB_EPROTO;
        }
        uint32_t payload = left - pad;
        if ((rc = h2_recv_data(c, s, payload)) != 0) return rc;
        while (pad) {
          uint8_t sink[256];
          uint32_t w = pad > sizeof sink ? sizeof sink : pad;
          if ((rc = h2::recv_all(c, sink, w)) != 0) return rc;
          pad -= w;
        }
        // Flow control: return consumed DATA as connection credit plus
        // PER-STREAM credit — each stream's own consumption tops up its
        // own window (batched at 1 MB) so concurrent streams never starve
        // each other.
        conn_unacked += flen;
        if (conn_unacked >= (1u << 20)) {
          uint8_t wu[4];
          h2::put32(wu, static_cast<uint32_t>(conn_unacked));
          h2::send_frame(c, 8, 0, 0, wu, 4);
          tb_stat_add(TB_STAT_H2_WINDOW_UPDATES_TX, 1);
          conn_unacked = 0;
        }
        if (s) {
          s->unacked += flen;
          if (s->unacked >= (1u << 20) && !s->done && !(fflags & 0x1)) {
            uint8_t wu[4];
            h2::put32(wu, static_cast<uint32_t>(s->unacked));
            h2::send_frame(c, 8, 0, fstream, wu, 4);
            tb_stat_add(TB_STAT_H2_WINDOW_UPDATES_TX, 1);
            s->unacked = 0;
          }
          if (fflags & 0x1) {
            h2_stream_finish(s);  // END_STREAM
          } else if (s->err && !s->done) {
            // Per-stream failure mid-body (buffer overflow, compressed
            // message, bad proto): CANCEL the stream so the server stops
            // sending, instead of silently draining — and crediting —
            // the entire remaining body. Late frames for this id are
            // discarded by the unknown-stream path once the slot frees.
            uint8_t code[4];
            h2::put32(code, 8 /*CANCEL*/);
            h2::send_frame(c, 3 /*RST_STREAM*/, 0, fstream, code, 4);
            s->done = 1;
          }
        }
        break;
      }
      case 1: {  // HEADERS (response headers or trailers)
        h2_stream* s = h2_find_stream(c, fstream);
        uint8_t* hbuf = static_cast<uint8_t*>(malloc(flen ? flen : 1));
        if (!hbuf) return -ENOMEM;
        if ((rc = h2::recv_all(c, hbuf, flen)) != 0) {
          free(hbuf);
          return rc;
        }
        size_t off = 0;
        uint32_t blen = flen;
        if (fflags & 0x8) {  // PADDED
          // flen == 0 has no pad-length byte to read — hbuf[0] would be
          // uninitialized memory.
          if (blen < 1) {
            free(hbuf);
            return TB_EPROTO;
          }
          uint8_t pad = hbuf[0];
          off = 1;
          if (pad + 1u > blen) {
            free(hbuf);
            return TB_EPROTO;
          }
          blen -= 1 + pad;
        }
        if (fflags & 0x20) {  // PRIORITY
          if (blen < 5) {
            free(hbuf);
            return TB_EPROTO;
          }
          off += 5;
          blen -= 5;
        }
        // Header blocks larger than one frame arrive as HEADERS +
        // CONTINUATION frames (RFC 9113 §6.10): until END_HEADERS, the
        // very next frames MUST be CONTINUATIONs on the same stream —
        // append their fragments. Bounded: a block past 64 KB is not a
        // storage-endpoint response.
        static const size_t kHdrBlockCap = 64 * 1024;
        static const int kMaxContinuations = 64;  // byte cap alone doesn't
        // bound the loop: zero-length CONTINUATIONs never advance bn.
        uint8_t* block = hbuf + off;  // view into hbuf while single-frame
        uint8_t* owned = nullptr;     // reassembly buffer once continuing
        size_t bn = blen;
        uint8_t hflags = fflags;
        int fragments = 0;
        while (!(hflags & 0x4)) {  // no END_HEADERS yet
          if (++fragments > kMaxContinuations) {
            free(hbuf);
            free(owned);
            return TB_EPROTO;
          }
          uint8_t ch[9];
          if ((rc = h2::recv_all(c, ch, 9)) != 0) {
            free(hbuf);
            free(owned);
            return rc;
          }
          uint32_t clen2 = (ch[0] << 16) | (ch[1] << 8) | ch[2];
          uint32_t cstream = ((ch[5] & 0x7f) << 24) | (ch[6] << 16) |
                             (ch[7] << 8) | ch[8];
          if (ch[3] != 9 /*CONTINUATION*/ || cstream != fstream ||
              bn + clen2 > kHdrBlockCap) {
            free(hbuf);
            free(owned);
            return TB_EPROTO;
          }
          if (!owned) {
            owned = static_cast<uint8_t*>(malloc(kHdrBlockCap));
            if (!owned) {
              free(hbuf);
              return -ENOMEM;
            }
            memcpy(owned, block, bn);
            block = owned;
          }
          if (clen2 && (rc = h2::recv_all(c, owned + bn, clen2)) != 0) {
            free(hbuf);
            free(owned);
            return rc;
          }
          bn += clen2;
          hflags = ch[4];  // only END_HEADERS (0x4) is defined here
        }
        int gs = -1, hs = -1;
        int64_t cl = -1;
        rc = h2::parse_header_block(block, bn, &gs, &hs, &cl);
        free(hbuf);
        free(owned);
        if (rc != 0) return rc;
        if (s) {
          if (s->first_byte_ns == 0) s->first_byte_ns = tb_now_ns();
          if (gs >= 0) s->grpc_status = gs;
          // Only the FINAL response HEADERS' announcement counts: an
          // interim 1xx block (RFC 9113 §8.1) is informational — marking
          // it as "the response" would discard the real block's
          // content-length and silently disable the truncation check —
          // and trailers (got_headers already set) must not
          // retroactively change it. The interim guard covers :status
          // too: a late 1xx block must not overwrite the response status
          // (which would flip the 200/206 gate of the truncation check).
          bool interim = hs >= 100 && hs < 200;
          if (!interim) {
            if (hs >= 0) s->http_status = hs;
            if (cl >= 0 && !s->got_headers) s->content_len = cl;
            s->got_headers = 1;
          }
          if (fflags & 0x1) {
            if (interim) {
              // END_STREAM on an interim response is a stream protocol
              // violation (RFC 9113 §8.1: interim responses cannot end a
              // stream). Finishing normally here would run
              // h2_stream_finish with the truncation check silently
              // disabled (no final headers ⇒ no content-length) — fail
              // the STREAM loudly instead; the connection survives.
              if (!s->err) s->err = TB_EPROTO;
              s->done = 1;
            } else {
              h2_stream_finish(s);
            }
          }
        }
        break;
      }
      case 3: {  // RST_STREAM: fatal for THAT stream, not the connection
        uint8_t code[4];
        if (flen != 4) return TB_EPROTO;
        if ((rc = h2::recv_all(c, code, 4)) != 0) return rc;
        tb_stat_add(TB_STAT_H2_RST_RX, 1);
        h2_stream* s = h2_find_stream(c, fstream);
        if (s) {
          s->err = TB_ESHORT;
          s->done = 1;
        }
        break;
      }
      case 4: {  // SETTINGS
        if (!(fflags & 0x1)) {  // not an ACK: read, then ACK
          uint8_t sink[256];
          uint32_t left = flen;
          while (left) {
            uint32_t w = left > sizeof sink ? sizeof sink : left;
            if ((rc = h2::recv_all(c, sink, w)) != 0) return rc;
            left -= w;
          }
          h2::send_frame(c, 4, 0x1, 0, nullptr, 0);
        }
        break;
      }
      case 6: {  // PING
        uint8_t pp[8];
        if (flen != 8) return TB_EPROTO;
        if ((rc = h2::recv_all(c, pp, 8)) != 0) return rc;
        if (!(fflags & 0x1)) h2::send_frame(c, 6, 0x1, 0, pp, 8);
        break;
      }
      case 7: {  // GOAWAY: connection-fatal for our purposes
        tb_stat_add(TB_STAT_H2_GOAWAY_RX, 1);
        return TB_ESHORT;
      }
      default: {  // WINDOW_UPDATE, PRIORITY, PUSH_PROMISE(never), unknown
        uint8_t sink[256];
        uint32_t left = flen;
        while (left) {
          uint32_t w = left > sizeof sink ? sizeof sink : left;
          if ((rc = h2::recv_all(c, sink, w)) != 0) return rc;
          left -= w;
        }
        break;
      }
    }
  }
  // Flush remaining connection-window credit so long-lived connections
  // never slowly drain the shared window.
  if (conn_unacked > 0) {
    uint8_t wu[4];
    h2::put32(wu, static_cast<uint32_t>(conn_unacked));
    h2::send_frame(c, 8, 0, 0, wu, 4);
    tb_stat_add(TB_STAT_H2_WINDOW_UPDATES_TX, 1);
  }
  if (tag_out) *tag_out = ready->tag;
  if (grpc_status_out) *grpc_status_out = ready->grpc_status;
  if (http_status_out) *http_status_out = ready->http_status;
  if (first_byte_ns_out) *first_byte_ns_out = ready->first_byte_ns;
  if (total_ns_out) *total_ns_out = tb_now_ns() - ready->t_start;
  if (result_out) *result_out = ready->err ? ready->err : ready->out_len;
  h2_close_stream(c, ready);
  return 1;
}

// One gRPC ReadObject on a tb_conn handle — the sequential convenience
// wrapper over submit+poll (exactly one stream in flight). Returns
// content bytes landed in ``buf``, or a negative TB_*/-errno code.
// ``grpc_status_out`` is the trailer's grpc-status when it was parseable,
// else -1 (success is then judged by the caller comparing the byte count
// against object metadata).
int64_t tb_grpc_read(int64_t h, const char* authority, const char* bucket_path,
                     const char* object_name,
                     const char* extra_headers,  // "k: v\r\n..." or ""
                     int64_t read_offset, int64_t read_limit, void* buf,
                     int64_t buf_len, int64_t* first_byte_ns_out,
                     int64_t* total_ns_out, int* grpc_status_out) {
  if (grpc_status_out) *grpc_status_out = -1;
  int64_t rc = tb_grpc_submit(h, authority, bucket_path, object_name,
                              extra_headers, read_offset, read_limit, buf,
                              buf_len, 0);
  if (rc != 0) return rc;
  uint64_t tag;
  int64_t result = 0;
  int gs = -1;
  rc = tb_grpc_poll(h, &tag, &result, &gs, nullptr, first_byte_ns_out,
                    total_ns_out);
  if (grpc_status_out) *grpc_status_out = gs;
  if (rc < 0) return rc;
  if (rc == 0) return TB_EPROTO;  // submitted stream vanished: broken state
  return result;
}

// --------------------------- loopback source server (tb_srv_*) -----------
// A minimal HTTP/1.1 object server running entirely on native threads,
// serving pre-rendered bytes from caller-owned memory. Purpose: the
// native-executor bench window needs a loopback source that does NOT
// burn the host CPU in a Python interpreter loop — on a single-core
// host a Python loopback server competes with the client and the JAX
// transfer path for the one core, confounding the measurement. Routes:
// GET ...alt=media (+ optional "Range: bytes=a-b") → 200/206 slice of
// the body; any other GET → the caller-provided metadata JSON.
// Keep-alive; one detached pthread per connection.

namespace srv {

struct server {
  int listen_fd;
  const uint8_t* body;
  int64_t body_len;
  char* meta_json;
  pthread_t accept_thread;
  int stop;  // cross-thread: access ONLY via __atomic builtins
  pthread_mutex_t mu;
  int conn_fds[256];  // live connection fds, for shutdown on stop
  int n_conns;
  int active;  // live connection-thread count (atomic access only)
  int64_t rejected;  // connections refused at the tracking cap (under mu)
};

struct srv_conn_arg {
  server* s;
  int fd;
};

static int srv_send_all(int fd, const void* p, int64_t n) {
  const uint8_t* b = static_cast<const uint8_t*>(p);
  int64_t off = 0;
  while (off < n) {
    ssize_t w = send(fd, b + off, static_cast<size_t>(n - off), MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return -1;
    }
    off += w;
  }
  return 0;
}

// Track/untrack a live connection fd. Returns 1 on success; 0 when the
// 256-fd tracking table is full — the caller must then REJECT the
// connection. An untracked connection would survive tb_srv_stop's
// shutdown sweep, block the bounded thread-join wait, and force the
// server struct (and the caller's body buffer) to leak silently with
// nothing attributing it; rejecting + logging makes the leak condition
// loud and attributable instead.
static int srv_track_conn(server* s, int fd, int add) {
  int ok = 1;
  pthread_mutex_lock(&s->mu);
  if (add) {
    if (s->n_conns < 256) {
      s->conn_fds[s->n_conns++] = fd;
    } else {
      ok = 0;
      s->rejected++;
      fprintf(stderr,
              "tpubench tb_srv: connection-tracking cap (256) reached; "
              "rejecting new connection (total rejected: %lld)\n",
              static_cast<long long>(s->rejected));
    }
  } else {
    for (int i = 0; i < s->n_conns; i++) {
      if (s->conn_fds[i] == fd) {
        s->conn_fds[i] = s->conn_fds[--s->n_conns];
        break;
      }
    }
  }
  pthread_mutex_unlock(&s->mu);
  return ok;
}

static void* srv_conn_main(void* argp) {
  srv_conn_arg* a = static_cast<srv_conn_arg*>(argp);
  server* s = a->s;
  int fd = a->fd;
  free(a);
  char req[8192];
  size_t have = 0;
  while (!__atomic_load_n(&s->stop, __ATOMIC_ACQUIRE)) {
    // Accumulate one request head (these clients send no bodies).
    char* end = nullptr;
    while (!(end = static_cast<char*>(
                 memmem(req, have, "\r\n\r\n", 4)))) {
      if (have >= sizeof(req) - 1) goto done;  // oversized head: drop
      ssize_t r = recv(fd, req + have, sizeof(req) - 1 - have, 0);
      if (r <= 0) goto done;
      have += static_cast<size_t>(r);
    }
    {
      size_t head_len = static_cast<size_t>(end - req) + 4;
      req[head_len - 1] = '\0';  // NUL-terminate for strstr/sscanf
      int is_media = strstr(req, "alt=media") != nullptr;
      int64_t start = 0, last = s->body_len - 1;
      int ranged = 0;
      int unsatisfiable = 0;
      const char* rg = strstr(req, "\r\nRange: bytes=");
      if (!rg) rg = strstr(req, "\r\nrange: bytes=");
      if (rg) {
        const char* rv = rg + 15;
        if (rv[0] == '-' && isdigit(static_cast<unsigned char>(rv[1]))) {
          // Suffix range "bytes=-N" (RFC 9110 §14.1.2): the LAST N bytes
          // — sscanf's "%lld" would otherwise swallow the sign and serve
          // a 206 from offset 0 with a wrong Content-Range. N == 0 and
          // empty bodies are unsatisfiable → 416, never a bogus 206.
          long long suf = atoll(rv + 1);
          ranged = 1;
          if (suf <= 0 || s->body_len == 0) {
            unsatisfiable = 1;
          } else {
            start = suf >= s->body_len ? 0 : s->body_len - suf;
            last = s->body_len - 1;
          }
        } else {
          long long as = 0, bs = -1;
          if (sscanf(rv, "%lld-%lld", &as, &bs) >= 1) {
            ranged = 1;
            start = as;
            last = bs >= 0 ? bs : s->body_len - 1;
            if (as >= s->body_len) unsatisfiable = 1;  // past EOF → 416
          }
        }
      }
      char hdr[512];
      if (!is_media) {
        int mlen = static_cast<int>(strlen(s->meta_json));
        int hn = snprintf(hdr, sizeof(hdr),
                          "HTTP/1.1 200 OK\r\n"
                          "Content-Type: application/json\r\n"
                          "Content-Length: %d\r\n\r\n",
                          mlen);
        if (srv_send_all(fd, hdr, hn) != 0) goto done;
        if (srv_send_all(fd, s->meta_json, mlen) != 0) goto done;
      } else if (unsatisfiable) {
        int hn = snprintf(hdr, sizeof(hdr),
                          "HTTP/1.1 416 Range Not Satisfiable\r\n"
                          "Content-Range: bytes */%lld\r\n"
                          "Content-Length: 0\r\n\r\n",
                          static_cast<long long>(s->body_len));
        if (srv_send_all(fd, hdr, hn) != 0) goto done;
      } else {
        if (start < 0) start = 0;
        if (last > s->body_len - 1) last = s->body_len - 1;
        int64_t n = last >= start ? last - start + 1 : 0;
        int hn;
        if (ranged) {
          hn = snprintf(hdr, sizeof(hdr),
                        "HTTP/1.1 206 Partial Content\r\n"
                        "Content-Type: application/octet-stream\r\n"
                        "Content-Range: bytes %lld-%lld/%lld\r\n"
                        "Content-Length: %lld\r\n\r\n",
                        static_cast<long long>(start),
                        static_cast<long long>(last),
                        static_cast<long long>(s->body_len),
                        static_cast<long long>(n));
        } else {
          hn = snprintf(hdr, sizeof(hdr),
                        "HTTP/1.1 200 OK\r\n"
                        "Content-Type: application/octet-stream\r\n"
                        "Content-Length: %lld\r\n\r\n",
                        static_cast<long long>(n));
        }
        if (srv_send_all(fd, hdr, hn) != 0) goto done;
        if (n > 0 && srv_send_all(fd, s->body + start, n) != 0) goto done;
      }
      // Keep-alive: drop the consumed head, keep any pipelined tail.
      memmove(req, req + head_len, have - head_len);
      have -= head_len;
    }
  }
done:
  srv_track_conn(s, fd, 0);
  close(fd);
  __sync_fetch_and_sub(&s->active, 1);
  return nullptr;
}

static void* srv_accept_main(void* argp) {
  server* s = static_cast<server*>(argp);
  while (!__atomic_load_n(&s->stop, __ATOMIC_ACQUIRE)) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      return nullptr;  // listen fd closed: stopping
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    srv_conn_arg* a = static_cast<srv_conn_arg*>(malloc(sizeof(srv_conn_arg)));
    if (!a) {
      close(fd);
      continue;
    }
    a->s = s;
    a->fd = fd;
    if (!srv_track_conn(s, fd, 1)) {
      // Tracking table full: refuse rather than serve an fd that stop()
      // could never shut down (see srv_track_conn).
      close(fd);
      free(a);
      continue;
    }
    __sync_fetch_and_add(&s->active, 1);
    pthread_t t;
    pthread_attr_t attr;
    pthread_attr_init(&attr);
    pthread_attr_setdetachstate(&attr, PTHREAD_CREATE_DETACHED);
    if (pthread_create(&t, &attr, srv_conn_main, a) != 0) {
      srv_track_conn(s, fd, 0);
      __sync_fetch_and_sub(&s->active, 1);
      close(fd);
      free(a);
    }
    pthread_attr_destroy(&attr);
  }
  return nullptr;
}

}  // namespace srv

// Start the loopback server on 127.0.0.1:<ephemeral>. ``body``/``meta_json``
// are BORROWED: the caller keeps them alive until tb_srv_stop returns.
// Returns an opaque handle (NULL on failure); *port_out gets the port.
void* tb_srv_start(const void* body, int64_t body_len, const char* meta_json,
                   int* port_out) {
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return nullptr;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0 ||
      listen(lfd, 64) != 0) {
    close(lfd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  if (getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen) != 0) {
    close(lfd);
    return nullptr;
  }
  srv::server* s =
      static_cast<srv::server*>(calloc(1, sizeof(srv::server)));
  if (!s) {
    close(lfd);
    return nullptr;
  }
  s->listen_fd = lfd;
  s->body = static_cast<const uint8_t*>(body);
  s->body_len = body_len;
  s->meta_json = strdup(meta_json ? meta_json : "{}");
  pthread_mutex_init(&s->mu, nullptr);
  if (pthread_create(&s->accept_thread, nullptr, srv::srv_accept_main, s) != 0) {
    close(lfd);
    free(s->meta_json);
    free(s);
    return nullptr;
  }
  if (port_out) *port_out = ntohs(addr.sin_port);
  return s;
}

// Stop. Closes the listener, shuts down live (tracked) connections, and
// waits (bounded) for connection threads to exit. Returns 0 when every
// connection thread exited — the caller may free the body buffer — or 1
// when some thread is still alive (blocked on an untracked/stalled
// peer): the server struct is then intentionally LEAKED rather than
// freed under a thread that still dereferences it, and the caller must
// keep the body buffer pinned for the life of the process.
int tb_srv_stop(void* handle) {
  if (!handle) return 0;
  srv::server* s = static_cast<srv::server*>(handle);
  __atomic_store_n(&s->stop, 1, __ATOMIC_RELEASE);
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  pthread_join(s->accept_thread, nullptr);
  pthread_mutex_lock(&s->mu);
  for (int i = 0; i < s->n_conns; i++) shutdown(s->conn_fds[i], SHUT_RDWR);
  pthread_mutex_unlock(&s->mu);
  for (int spins = 0;
       __atomic_load_n(&s->active, __ATOMIC_ACQUIRE) > 0 && spins < 2000;
       spins++)
    usleep(1000);  // connection threads close their own fds
  if (__atomic_load_n(&s->active, __ATOMIC_ACQUIRE) > 0)
    return 1;  // leak: never free under a live thread
  free(s->meta_json);
  pthread_mutex_destroy(&s->mu);
  free(s);
  return 0;
}

}  // extern "C"
