"""ctypes wrapper over libtpubench.so."""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from tpubench.native.build import build_library


class NativeError(OSError):
    """Engine failure. ``code`` is the raw negative return: -1000-series
    protocol codes (see ``PERMANENT_CODES``) or ``-errno`` for socket/fs
    failures. Callers classify on the code, never on message text (the
    wording is free to change; the codes are the engine's ABI)."""

    def __init__(self, msg: str, code: int = 0):
        super().__init__(msg)
        self.code = code


# Engine error-code ABI (mirrors the engine.cc TB_* enum) — compare against
# these names, never bare numbers.
TB_EPROTO = -1001
TB_ETOOBIG = -1002
TB_ERESOLVE = -1003
TB_ESHORT = -1004
TB_ECHUNKED = -1005
TB_ETLS = -1006
TB_EGRPC = -1007

_PROTO_ERRORS = {
    TB_EPROTO: "malformed HTTP response",
    TB_ETOOBIG: "body exceeds buffer",
    TB_ERESOLVE: "hostname resolution failed",
    TB_ESHORT: "short response: connection closed early",
    TB_ECHUNKED: "chunked transfer encoding (unsupported by the native receive path)",
    TB_ETLS: "TLS unavailable, handshake failed, or certificate rejected",
    TB_EGRPC: "RPC finished with a nonzero grpc-status",
}

# Protocol-shape failures: re-sending the same request to the same server
# reproduces them, so retry is futile (engine.cc TB_EPROTO/TB_ETOOBIG/
# TB_ECHUNKED). Resolution failures and short bodies are network
# conditions — transient. (-1002 has one caller-visible exception: when the
# buffer was sized from a cached stat, the caller may treat it as
# retryable after invalidating the cache — see gcs_http.)
PERMANENT_CODES = frozenset({TB_EPROTO, TB_ETOOBIG, TB_ECHUNKED, TB_ETLS})


def _check(rc: int, what: str) -> int:
    if rc < 0:
        if rc in _PROTO_ERRORS:
            raise NativeError(f"{what}: {_PROTO_ERRORS[rc]}", code=rc)
        import os

        raise NativeError(f"{what}: {os.strerror(-rc)} (errno {-rc})", code=rc)
    return rc


# --------------------------------------------------------------- dlpack ----
# PyCapsule plumbing for the native DLPack producer (SURVEY §2.5.4): the
# DLManagedTensor descriptor is built in C++ (tb_dlpack_create); here we only
# wrap it in the standard "dltensor" capsule. Consumers (np.from_dlpack,
# jax.dlpack) rename the capsule to "used_dltensor" and invoke the embedded
# deleter themselves; the ctypes destructor below only fires for capsules
# that were never consumed.
_PyCapsule_New = ctypes.pythonapi.PyCapsule_New
_PyCapsule_New.restype = ctypes.py_object
_PyCapsule_New.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p]
_PyCapsule_GetName = ctypes.pythonapi.PyCapsule_GetName
_PyCapsule_GetName.restype = ctypes.c_char_p
_PyCapsule_GetName.argtypes = [ctypes.c_void_p]
_PyCapsule_GetPointer = ctypes.pythonapi.PyCapsule_GetPointer
_PyCapsule_GetPointer.restype = ctypes.c_void_p
_PyCapsule_GetPointer.argtypes = [ctypes.c_void_p, ctypes.c_char_p]

_CAPSULE_DTOR_T = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_MANAGED_DELETER_T = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class AlignedBuffer:
    """posix_memalign'd buffer exposed as numpy/memoryview/DLPack, zero-copy.

    O_DIRECT needs buffer alignment the Go reference never arranged
    explicitly (SURVEY hard-part (e)); 4096 covers all common logical block
    sizes. Also serves as the pre-registered receive buffer for the native
    HTTP path, and as a DLPack producer so JAX/numpy consume the bytes with
    no Python-held copy (``np.from_dlpack(buf)`` / ``jax.device_put`` of
    :meth:`as_2d`). Lifetime: DLPack consumers pin the buffer (their
    deleter un-pins; ``free()`` defers while pinned), so ``from_dlpack``
    arrays never dangle. Plain numpy views (:attr:`array` / :meth:`as_2d`)
    do NOT pin — holders must keep the buffer alive, which the staging slot
    ring does by draining a slot's in-flight transfer before reuse/free.
    """

    def __init__(self, engine: "NativeEngine", size: int, align: int = 4096):
        self._engine = engine
        self.size = size
        ptr = engine.lib.tb_alloc_aligned(size, align)
        if not ptr:
            raise MemoryError(f"aligned alloc of {size} failed")
        self._ptr = ptr
        self._pins = 0  # live DLPack consumers; memory free defers on them
        self._free_pending = False
        self.array = np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)), shape=(size,)
        )

    @property
    def address(self) -> int:
        return self._ptr

    def view(self, n: Optional[int] = None) -> memoryview:
        return memoryview(self.array)[: self.size if n is None else n]

    def as_2d(self, lane: int = 128) -> np.ndarray:
        """Zero-copy ``(size//lane, lane) uint8`` view — the lane-aligned
        layout the staging pipeline ships to HBM (static shape, XLA tiles
        it directly)."""
        if self.size % lane:
            raise ValueError(f"buffer size {self.size} not a multiple of lane {lane}")
        return self.array.reshape(self.size // lane, lane)

    # DLPack producer protocol -------------------------------------------
    def __dlpack_device__(self) -> tuple[int, int]:
        return (1, 0)  # (kDLCPU, 0)

    def __dlpack__(self, stream=None, lane: int = 128):
        """``dltensor`` capsule viewing this buffer as ``(size//lane, lane)
        uint8`` (falls back to ``(1, size)`` when unaligned). Descriptor is
        built natively (tb_dlpack_create); bytes are NOT copied. The buffer
        is pinned until the consumer's deleter runs, so consumer arrays
        never dangle — an explicit :meth:`free` while pinned defers until
        the last consumer lets go."""
        if not self._ptr or self._free_pending:
            raise ValueError("buffer already freed")
        rows, cols = (
            (self.size // lane, lane) if self.size % lane == 0 else (1, self.size)
        )
        managed = self._engine.lib.tb_dlpack_create(
            self._ptr, rows, cols, self._engine._managed_deleter_addr
        )
        if not managed:
            raise MemoryError("tb_dlpack_create failed")
        self._engine._dlpack_pin(managed, self)
        self._pins += 1
        return _PyCapsule_New(managed, b"dltensor", self._engine.capsule_dtor_addr)

    def free(self) -> None:
        if self._pins > 0:
            # DLPack consumers still view this memory; defer the actual
            # free until the last consumer's deleter un-pins us.
            self._free_pending = True
            return
        if self._ptr:
            self._engine.lib.tb_free_aligned(self._ptr)
            self._ptr = 0

    def _unpin(self) -> None:
        self._pins -= 1
        if self._pins == 0 and self._free_pending:
            self._free_pending = False
            self.free()

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass


class NativeEngine:
    def __init__(self):
        path = build_library()
        lib = ctypes.CDLL(path)
        c = ctypes
        lib.tb_now_ns.restype = c.c_int64
        lib.tb_alloc_aligned.restype = c.c_void_p
        lib.tb_alloc_aligned.argtypes = [c.c_size_t, c.c_size_t]
        lib.tb_free_aligned.argtypes = [c.c_void_p]
        lib.tb_open.restype = c.c_int
        lib.tb_open.argtypes = [c.c_char_p, c.c_int, c.POINTER(c.c_int)]
        lib.tb_close.argtypes = [c.c_int]
        lib.tb_file_size.restype = c.c_int64
        lib.tb_file_size.argtypes = [c.c_char_p]
        lib.tb_pread_blocks.restype = c.c_int64
        lib.tb_pread_blocks.argtypes = [
            c.c_int, c.c_void_p, c.c_int64,
            c.POINTER(c.c_int64), c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.tb_read_file_seq.restype = c.c_int64
        lib.tb_read_file_seq.argtypes = [
            c.c_int, c.c_void_p, c.c_int64, c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.tb_pwrite_blocks.restype = c.c_int64
        lib.tb_pwrite_blocks.argtypes = [
            c.c_int, c.c_void_p, c.c_int64,
            c.POINTER(c.c_int64), c.c_int64, c.c_int, c.POINTER(c.c_int64),
        ]
        lib.tb_fill_random.argtypes = [c.c_void_p, c.c_int64, c.c_uint64]
        lib.tb_dlpack_create.restype = c.c_void_p
        lib.tb_dlpack_create.argtypes = [c.c_void_p, c.c_int64, c.c_int64, c.c_void_p]
        lib.tb_dlpack_free.argtypes = [c.c_void_p]
        lib.tb_dlpack_free_descriptor.argtypes = [c.c_void_p]
        lib.tb_http_get.restype = c.c_int64
        lib.tb_http_get.argtypes = [
            c.c_char_p, c.c_int, c.c_char_p, c.c_char_p,
            c.c_void_p, c.c_int64, c.POINTER(c.c_int),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64),
        ]
        lib.tb_http_connect.restype = c.c_int
        lib.tb_http_connect.argtypes = [c.c_char_p, c.c_int]
        lib.tb_http_close.argtypes = [c.c_int]
        lib.tb_http_request.restype = c.c_int64
        lib.tb_http_request.argtypes = [
            c.c_int, c.c_char_p, c.c_int, c.c_char_p, c.c_char_p,
            c.c_void_p, c.c_int64, c.POINTER(c.c_int),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.POINTER(c.c_int),
        ]
        lib.tb_tls_available.restype = c.c_int
        lib.tb_conn_plain.restype = c.c_int64
        lib.tb_conn_plain.argtypes = [c.c_int]
        lib.tb_conn_tls.restype = c.c_int64
        lib.tb_conn_tls.argtypes = [
            c.c_int, c.c_char_p, c.c_char_p, c.c_int, c.c_int,
        ]
        lib.tb_conn_close.restype = c.c_int
        lib.tb_conn_close.argtypes = [c.c_int64]
        lib.tb_conn_request.restype = c.c_int64
        lib.tb_conn_request.argtypes = [
            c.c_int64, c.c_char_p, c.c_int, c.c_char_p, c.c_char_p,
            c.c_void_p, c.c_int64, c.POINTER(c.c_int),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.POINTER(c.c_int),
        ]
        lib.tb_conn_get_begin.restype = c.c_int64
        lib.tb_conn_get_begin.argtypes = [
            c.c_int64, c.c_char_p, c.c_int, c.c_char_p, c.c_char_p,
            c.POINTER(c.c_int), c.POINTER(c.c_int64), c.POINTER(c.c_int64),
        ]
        lib.tb_conn_body_read.restype = c.c_int64
        lib.tb_conn_body_read.argtypes = [c.c_int64, c.c_void_p, c.c_int64]
        lib.tb_conn_get_end.restype = c.c_int
        lib.tb_conn_get_end.argtypes = [c.c_int64, c.POINTER(c.c_int)]
        lib.tb_hpack_scan_status.restype = c.c_int
        lib.tb_hpack_scan_status.argtypes = [c.c_char_p, c.c_int64]
        lib.tb_pool_create.restype = c.c_int64
        lib.tb_pool_create.argtypes = [
            c.c_int, c.c_int, c.c_int, c.c_char_p, c.c_int,
        ]
        lib.tb_pool_submit.restype = c.c_int
        lib.tb_pool_submit.argtypes = [
            c.c_int64, c.c_char_p, c.c_int, c.c_char_p, c.c_char_p,
            c.c_void_p, c.c_int64, c.c_uint64,
        ]
        lib.tb_pool_next.restype = c.c_int
        lib.tb_pool_next.argtypes = [
            c.c_int64, c.c_int, c.POINTER(c.c_uint64), c.POINTER(c.c_int64),
            c.POINTER(c.c_int), c.POINTER(c.c_int64), c.POINTER(c.c_int64),
            c.POINTER(c.c_int64),
        ]
        lib.tb_pool_destroy.restype = c.c_int
        lib.tb_pool_destroy.argtypes = [c.c_int64]
        # Batched completion handoff: bound defensively (same policy as
        # tb_stats) so a stale .so degrades to the one-at-a-time drain
        # instead of an import-time crash.
        try:
            lib.tb_pool_next_batch.restype = c.c_int
            lib.tb_pool_next_batch.argtypes = [
                c.c_int64, c.c_int, c.c_int, c.POINTER(c.c_uint64),
                c.POINTER(c.c_int64), c.POINTER(c.c_int),
                c.POINTER(c.c_int64), c.POINTER(c.c_int64),
                c.POINTER(c.c_int64),
            ]
            self._has_pool_batch = True
        except AttributeError:
            self._has_pool_batch = False
        # Reactor-mode executor (tb_pool_create2 + the SPSC ring drain):
        # bound defensively so a stale .so predating the reactor degrades
        # to the legacy thread pool (pool_create falls back, mode label
        # says so) and the ring drain degrades to tb_pool_next_batch —
        # old binaries stay loadable, nothing crashes.
        try:
            lib.tb_pool_create2.restype = c.c_int64
            lib.tb_pool_create2.argtypes = [
                c.c_int, c.c_int, c.c_int, c.c_char_p, c.c_int, c.c_int,
            ]
            lib.tb_pool_is_reactor.restype = c.c_int
            lib.tb_pool_is_reactor.argtypes = [c.c_int64]
            self._has_pool_create2 = True
        except AttributeError:
            self._has_pool_create2 = False
        try:
            lib.tb_pool_ring_next_batch.restype = c.c_int
            lib.tb_pool_ring_next_batch.argtypes = [
                c.c_int64, c.c_int, c.c_int, c.POINTER(c.c_uint64),
                c.POINTER(c.c_int64), c.POINTER(c.c_int),
                c.POINTER(c.c_int64), c.POINTER(c.c_int64),
                c.POINTER(c.c_int64),
            ]
            self._has_pool_ring = True
        except AttributeError:
            self._has_pool_ring = False
        lib.tb_grpc_read.restype = c.c_int64
        lib.tb_grpc_read.argtypes = [
            c.c_int64, c.c_char_p, c.c_char_p, c.c_char_p, c.c_char_p,
            c.c_int64, c.c_int64, c.c_void_p, c.c_int64,
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.POINTER(c.c_int),
        ]
        lib.tb_grpc_submit.restype = c.c_int64
        lib.tb_grpc_submit.argtypes = [
            c.c_int64, c.c_char_p, c.c_char_p, c.c_char_p, c.c_char_p,
            c.c_int64, c.c_int64, c.c_void_p, c.c_int64, c.c_uint64,
        ]
        lib.tb_h2_submit_get.restype = c.c_int64
        lib.tb_h2_submit_get.argtypes = [
            c.c_int64, c.c_char_p, c.c_char_p, c.c_char_p,
            c.c_void_p, c.c_int64, c.c_uint64,
        ]
        lib.tb_grpc_poll.restype = c.c_int64
        lib.tb_grpc_poll.argtypes = [
            c.c_int64, c.POINTER(c.c_uint64), c.POINTER(c.c_int64),
            c.POINTER(c.c_int), c.POINTER(c.c_int),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64),
        ]
        lib.tb_srv_start.restype = c.c_void_p
        lib.tb_srv_start.argtypes = [
            c.c_void_p, c.c_int64, c.c_char_p, c.POINTER(c.c_int),
        ]
        lib.tb_srv_stop.restype = c.c_int
        lib.tb_srv_stop.argtypes = [c.c_void_p]
        # Transport counters (tb_stats_*): bound defensively so a stale
        # .so predating the API degrades to stats() == {} instead of an
        # import-time crash.
        try:
            lib.tb_stats_count.restype = c.c_int
            lib.tb_stats_count.argtypes = []
            lib.tb_stats_name.restype = c.c_char_p
            lib.tb_stats_name.argtypes = [c.c_int]
            lib.tb_stats_read.restype = c.c_int
            lib.tb_stats_read.argtypes = [c.POINTER(c.c_int64), c.c_int]
            lib.tb_stats_reset.restype = None
            lib.tb_stats_reset.argtypes = []
            self._has_stats = True
        except AttributeError:
            self._has_stats = False
        self.lib = lib

        # DLPack lifetime plumbing. Every managed tensor we produce gets a
        # Python-side deleter callback as its `deleter` field, so whichever
        # party disposes of the tensor — the consumer (numpy/jax call
        # t->deleter when the consuming array dies) or the unconsumed-capsule
        # destructor — un-pins the producer AlignedBuffer and frees the
        # descriptor. The pin registry keeps the buffer (and its memory)
        # alive for as long as any consumer array views it, per the DLPack
        # contract. ctypes callbacks acquire the GIL on entry, so the
        # registry mutation is safe from whatever thread the consumer's
        # deallocator runs on.
        self._dlpack_pins: dict[int, "AlignedBuffer"] = {}

        def _managed_deleter(managed_ptr):
            buf = self._dlpack_pins.pop(managed_ptr, None)
            lib.tb_dlpack_free_descriptor(managed_ptr)
            if buf is not None:
                buf._unpin()

        self._managed_deleter = _MANAGED_DELETER_T(_managed_deleter)
        self._managed_deleter_addr = ctypes.cast(self._managed_deleter, ctypes.c_void_p)

        def _dtor(capsule_ptr):
            name = _PyCapsule_GetName(capsule_ptr)
            if name == b"dltensor":  # never consumed: dispose via deleter
                managed = _PyCapsule_GetPointer(capsule_ptr, name)
                if managed:
                    lib.tb_dlpack_free(managed)

        self._capsule_dtor = _CAPSULE_DTOR_T(_dtor)
        self.capsule_dtor_addr = ctypes.cast(self._capsule_dtor, ctypes.c_void_p)

    def _dlpack_pin(self, managed: int, buf: "AlignedBuffer") -> None:
        self._dlpack_pins[managed] = buf

    # ------------------------------------------------------------ helpers --
    def now_ns(self) -> int:
        return self.lib.tb_now_ns()

    def stats(self) -> dict[str, int]:
        """Engine-wide transport counter snapshot (tb_stats_*): bytes on
        the wire, h2 frames, flow-control credit returns, recv wait time,
        connects/handshakes — the native engine's previously-invisible
        state. Cumulative per process; callers diff two snapshots to
        scope a run."""
        if not self._has_stats:
            return {}
        n = int(self.lib.tb_stats_count())
        arr = (ctypes.c_int64 * n)()
        got = self.lib.tb_stats_read(arr, n)
        return {
            self.lib.tb_stats_name(i).decode(): int(arr[i])
            for i in range(min(n, got))
        }

    def stats_reset(self) -> None:
        if self._has_stats:
            self.lib.tb_stats_reset()

    def alloc(self, size: int, align: int = 4096) -> AlignedBuffer:
        return AlignedBuffer(self, size, align)

    def open(
        self, path: str, write: bool = False, create: bool = False, direct: bool = False
    ) -> tuple[int, bool]:
        """Returns (fd, direct_applied). Falls back transparently when the
        filesystem rejects O_DIRECT (tmpfs does), reporting the downgrade."""
        flags = (1 if write else 0) | (2 if create else 0) | (4 if direct else 0)
        applied = ctypes.c_int(0)
        fd = self.lib.tb_open(path.encode(), flags, ctypes.byref(applied))
        _check(fd, f"open {path}")
        return fd, bool(applied.value)

    def close(self, fd: int) -> None:
        _check(self.lib.tb_close(fd), "close")

    def file_size(self, path: str) -> int:
        return _check(self.lib.tb_file_size(path.encode()), f"stat {path}")

    def pread_blocks(
        self, fd: int, buf: AlignedBuffer, block_size: int, offsets: np.ndarray
    ) -> tuple[int, np.ndarray]:
        """Timed block reads; returns (total_bytes, per-block ns latencies)."""
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        lat = np.zeros(len(offs), dtype=np.int64)
        total = self.lib.tb_pread_blocks(
            fd,
            buf.address,
            block_size,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(offs),
            lat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        _check(total, "pread_blocks")
        return total, lat

    def read_file_seq(
        self, fd: int, buf: AlignedBuffer, passes: int = 1
    ) -> tuple[int, np.ndarray]:
        lat = np.zeros(passes, dtype=np.int64)
        total = self.lib.tb_read_file_seq(
            fd,
            buf.address,
            buf.size,
            passes,
            lat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        _check(total, "read_file_seq")
        return total, lat

    def pwrite_blocks(
        self,
        fd: int,
        buf: AlignedBuffer,
        block_size: int,
        offsets: np.ndarray,
        fsync_each: bool = True,
    ) -> tuple[int, np.ndarray]:
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        lat = np.zeros(len(offs), dtype=np.int64)
        total = self.lib.tb_pwrite_blocks(
            fd,
            buf.address,
            block_size,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(offs),
            1 if fsync_each else 0,
            lat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        _check(total, "pwrite_blocks")
        return total, lat

    def fill_random(self, buf: AlignedBuffer, n: Optional[int] = None, seed: int = 1):
        self.lib.tb_fill_random(buf.address, buf.size if n is None else n, seed)

    def http_get(
        self,
        host: str,
        port: int,
        path: str,
        buf: AlignedBuffer,
        headers: str = "",
    ) -> dict:
        """Native receive path: body streamed into ``buf``; returns status,
        body length, first-byte and total ns."""
        status = ctypes.c_int(0)
        fb = ctypes.c_int64(0)
        total_ns = ctypes.c_int64(0)
        n = self.lib.tb_http_get(
            host.encode(),
            port,
            path.encode(),
            headers.encode(),
            buf.address,
            buf.size,
            ctypes.byref(status),
            ctypes.byref(fb),
            ctypes.byref(total_ns),
        )
        _check(n, f"http_get {host}:{port}{path}")
        return {
            "status": status.value,
            "length": n,
            "first_byte_ns": fb.value,
            "total_ns": total_ns.value,
        }

    def http_connect(self, host: str, port: int) -> int:
        """Keep-alive path: open a TCP connection for repeated
        :meth:`http_request` calls (the pooled-connection discipline of the
        Python client, so native-vs-Python A/Bs isolate the receive loop
        rather than conflating it with per-GET connect cost)."""
        return _check(self.lib.tb_http_connect(host.encode(), port),
                      f"connect {host}:{port}")

    def http_close(self, fd: int) -> None:
        self.lib.tb_http_close(fd)

    def http_request(
        self,
        fd: int,
        host: str,
        port: int,
        path: str,
        buf: AlignedBuffer,
        headers: str = "",
    ) -> dict:
        """One GET on a kept-alive connection; ``reusable`` reports whether
        the socket may carry another request. On NativeError the caller must
        :meth:`http_close` the fd (stream state unknown)."""
        status = ctypes.c_int(0)
        fb = ctypes.c_int64(0)
        total_ns = ctypes.c_int64(0)
        reusable = ctypes.c_int(0)
        n = self.lib.tb_http_request(
            fd,
            host.encode(),
            port,
            path.encode(),
            headers.encode(),
            buf.address,
            buf.size,
            ctypes.byref(status),
            ctypes.byref(fb),
            ctypes.byref(total_ns),
            ctypes.byref(reusable),
        )
        _check(n, f"http_request {host}:{port}{path}")
        return {
            "status": status.value,
            "length": n,
            "first_byte_ns": fb.value,
            "total_ns": total_ns.value,
            "reusable": bool(reusable.value),
        }

    # ------------------------------------------------------ conn handles --
    # Transport-agnostic connection handles: the same receive loop over
    # plaintext TCP or TLS (dlopen'd OpenSSL — SURVEY hard-part (b): the
    # native path can face real https endpoints, not just localhost fakes).

    def tls_available(self) -> bool:
        return bool(self.lib.tb_tls_available())

    def connect(
        self,
        host: str,
        port: int,
        *,
        tls: bool = False,
        sni: str = "",
        cafile: str = "",
        insecure: bool = False,
        alpn_h2: bool = False,
    ) -> int:
        """Open a connection handle for :meth:`conn_request` calls. TLS
        verification: peer cert against ``cafile`` (or the system store)
        plus hostname/IP match on ``sni`` — ``insecure`` skips both (tests
        against self-signed endpoints). ``alpn_h2`` offers and REQUIRES
        ALPN h2 (the gRPC path; an HTTP/1.1 fallback would be misparsed
        as frames)."""
        fd = _check(self.lib.tb_http_connect(host.encode(), port),
                    f"connect {host}:{port}")
        if not tls:
            return _check(self.lib.tb_conn_plain(fd), "conn_plain")
        h = self.lib.tb_conn_tls(
            fd, (sni or host).encode(), cafile.encode(),
            1 if insecure else 0, 1 if alpn_h2 else 0,
        )
        if h <= 0:
            self.lib.tb_http_close(fd)  # handshake failed: fd still ours
            _check(int(h), f"tls handshake {host}:{port}")
        return h

    def conn_plain(self, fd: int) -> int:
        """Wrap an existing connected fd (ownership transfers)."""
        return _check(self.lib.tb_conn_plain(fd), "conn_plain")

    def conn_close(self, handle: int) -> None:
        self.lib.tb_conn_close(handle)

    def conn_request(
        self,
        handle: int,
        host: str,
        port: int,
        path: str,
        buf: AlignedBuffer,
        headers: str = "",
    ) -> dict:
        """One GET on a connection handle; same contract as
        :meth:`http_request` (on NativeError the caller must
        :meth:`conn_close` the handle — stream state unknown)."""
        status = ctypes.c_int(0)
        fb = ctypes.c_int64(0)
        total_ns = ctypes.c_int64(0)
        reusable = ctypes.c_int(0)
        n = self.lib.tb_conn_request(
            handle,
            host.encode(),
            port,
            path.encode(),
            headers.encode(),
            buf.address,
            buf.size,
            ctypes.byref(status),
            ctypes.byref(fb),
            ctypes.byref(total_ns),
            ctypes.byref(reusable),
        )
        _check(n, f"conn_request {host}:{port}{path}")
        return {
            "status": status.value,
            "length": n,
            "first_byte_ns": fb.value,
            "total_ns": total_ns.value,
            "reusable": bool(reusable.value),
        }

    def conn_get_begin(
        self,
        handle: int,
        host: str,
        port: int,
        path: str,
        headers: str = "",
    ) -> dict:
        """Streaming GET, phase 1: send the request and parse the response
        headers. Body bytes stream via :meth:`conn_body_read` directly into
        caller memory — no full-body intermediate buffer (the same
        socket→destination discipline as the Python client's ``readinto``).
        ``content_len`` is -1 for a close-delimited body. On NativeError the
        caller must :meth:`conn_close` the handle."""
        status = ctypes.c_int(0)
        clen = ctypes.c_int64(-1)
        fb = ctypes.c_int64(0)
        rc = self.lib.tb_conn_get_begin(
            handle, host.encode(), port, path.encode(), headers.encode(),
            ctypes.byref(status), ctypes.byref(clen), ctypes.byref(fb),
        )
        _check(rc, f"conn_get_begin {host}:{port}{path}")
        return {
            "status": status.value,
            "content_len": clen.value,
            "first_byte_ns": fb.value,
        }

    def conn_body_read(self, handle: int, dst, want: int) -> int:
        """Streaming GET, phase 2: up to ``want`` body bytes land directly
        in ``dst`` (a writable buffer — memoryview/bytearray/numpy). Returns
        0 at body end. The recv runs without the GIL (ctypes releases it).
        ``want`` is clamped to the destination's byte size — the engine
        fills ``want`` fully on close-delimited bodies, so an unclamped
        over-ask would be a heap overflow, not a short read."""
        mv = memoryview(dst)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(mv))
        return _check(
            self.lib.tb_conn_body_read(handle, addr, min(want, mv.nbytes)),
            "conn_body_read",
        )

    def conn_get_end(self, handle: int) -> bool:
        """Streaming GET, phase 3: returns whether the connection may carry
        another request (False when the body was abandoned mid-stream)."""
        reusable = ctypes.c_int(0)
        _check(
            self.lib.tb_conn_get_end(handle, ctypes.byref(reusable)),
            "conn_get_end",
        )
        return bool(reusable.value)

    def hpack_scan_status(self, block: bytes) -> int:
        """Test hook: structural HPACK parse of one header block; returns
        the extracted grpc-status (-1 unknown) or raises on a malformed
        block."""
        rc = self.lib.tb_hpack_scan_status(block, len(block))
        if rc <= -1000:  # -1 is the legitimate "status unknown" answer
            _check(rc, "hpack_scan")
        return rc

    def pool_create(
        self,
        threads: int,
        cap: int = 256,
        *,
        tls: bool = False,
        cafile: str = "",
        insecure: bool = False,
        mode: str = "threads",
        loops: int = 0,
        h2: bool = False,
    ) -> "NativeFetchPool":
        """Native fetch executor. Two dispatch shapes behind one handle:

        ``mode="threads"`` (legacy): ``threads`` worker pthreads, one
        keep-alive connection each, completions through a mutex/condvar
        queue — plaintext or TLS.

        ``mode="reactor"``: epoll event loop(s) owning ALL connections
        (``threads`` becomes the CONNECTION budget; in-flight GETs
        beyond it queue per target and share keep-alive sockets),
        completions delivered over lock-free SPSC rings with an eventfd
        doorbell — zero lock crossings on the steady-state hot path
        (the BENCH_r05 handoff tax, removed). ``loops`` sets the
        event-loop thread count (0 = one). TLS runs the same nonblocking
        state machine (handshake off epoll readiness, session resumption
        across keep-alive reconnects). ``h2=True`` multiplexes GETs as
        concurrent HTTP/2 streams: ALPN-negotiated on TLS (the server
        may still pick http/1.1 — the pool follows), prior-knowledge
        h2c on plaintext. Only a stale ``.so`` without the reactor
        symbols (or a creation failure) falls back to the legacy pool —
        check :attr:`NativeFetchPool.mode` for what actually engaged
        (A/Bs must label arms honestly).
        """
        want_reactor = mode == "reactor"
        if want_reactor and self._has_pool_create2:
            mbits = 1 | (max(0, min(loops, 16)) << 8)
            if h2:
                mbits |= 0x10000 if tls else 0x20000
            h = self.lib.tb_pool_create2(
                threads, cap, 1 if tls else 0, cafile.encode(),
                1 if insecure else 0, mbits,
            )
            if h != 0:
                return NativeFetchPool(self, h, mode="reactor")
            # Reactor creation failed (fd limits?): legacy still serves.
        if h2:
            # No legacy h2 GET pool exists: quietly serving http/1.1
            # under an ``h2=True`` request would mislabel an A/B arm.
            raise NativeError(
                "h2 fetch pool requires reactor mode "
                "(stale .so without tb_pool_create2, or creation failed)",
                code=-22,
            )
        h = self.lib.tb_pool_create(
            threads, cap, 1 if tls else 0, cafile.encode(),
            1 if insecure else 0,
        )
        if h == 0:
            raise NativeError(
                "tb_pool_create failed"
                + (" (TLS requested but OpenSSL unavailable?)" if tls else ""),
                code=-12,
            )
        return NativeFetchPool(self, h, mode="threads")

    def grpc_submit(
        self,
        handle: int,
        authority: str,
        bucket_path: str,
        object_name: str,
        buf: AlignedBuffer,
        read_offset: int = 0,
        read_limit: int = 0,
        headers: str = "",
        tag: int = 0,
    ) -> None:
        """Open one google.storage.v2 ReadObject as a CONCURRENT h2 stream
        on the connection (grpc-go multiplexes by default — this is the
        native equivalent). Up to 32 streams per connection; raises
        NativeError(-EAGAIN) when the table is full (poll a completion
        first). Completions come back from :meth:`h2_poll` by ``tag``."""
        self.grpc_submit_to(
            handle, authority, bucket_path, object_name,
            buf.address, buf.size,
            read_offset=read_offset, read_limit=read_limit,
            headers=headers, tag=tag,
        )

    def grpc_submit_to(
        self,
        handle: int,
        authority: str,
        bucket_path: str,
        object_name: str,
        address: int,
        nbytes: int,
        read_offset: int = 0,
        read_limit: int = 0,
        headers: str = "",
        tag: int = 0,
    ) -> None:
        """Raw-destination variant of :meth:`grpc_submit`: content bytes
        land at (address, nbytes) — e.g. a numpy shard buffer — which must
        stay valid until the stream's completion comes back."""
        rc = self.lib.tb_grpc_submit(
            handle, authority.encode(), bucket_path.encode(),
            object_name.encode(), headers.encode(),
            read_offset, read_limit, address, nbytes, tag,
        )
        if rc != 0:
            _check(int(rc), f"grpc_submit {object_name}")

    def h2_submit_get(
        self,
        handle: int,
        authority: str,
        path: str,
        buf: AlignedBuffer,
        headers: str = "",
        tag: int = 0,
    ) -> None:
        """Open one plain HTTP/2 GET stream (the reference's HTTP/2 client
        branch, main.go:76-80): DATA payload bytes land in ``buf``
        verbatim; the completion's ``http_status`` carries :status."""
        self.h2_submit_get_to(
            handle, authority, path, buf.address, buf.size,
            headers=headers, tag=tag,
        )

    def h2_submit_get_to(
        self,
        handle: int,
        authority: str,
        path: str,
        address: int,
        nbytes: int,
        headers: str = "",
        tag: int = 0,
    ) -> None:
        """Raw-destination variant of :meth:`h2_submit_get`: DATA bytes
        land at (address, nbytes) — e.g. a numpy shard buffer — which must
        stay valid until the stream's completion comes back."""
        rc = self.lib.tb_h2_submit_get(
            handle, authority.encode(), path.encode(), headers.encode(),
            address, nbytes, tag,
        )
        if rc != 0:
            _check(int(rc), f"h2_submit_get {path}")

    def h2_poll(self, handle: int) -> Optional[dict]:
        """Wait for the next stream completion on the connection. Returns
        None when no streams are active. ``result`` >= 0 is the byte count
        landed; negative is that STREAM's error code (the connection
        survives). Raises NativeError on connection-fatal errors — every
        in-flight stream is then dead and the caller must conn_close."""
        tag = ctypes.c_uint64(0)
        result = ctypes.c_int64(0)
        gs = ctypes.c_int(-1)
        hs = ctypes.c_int(-1)
        fb = ctypes.c_int64(0)
        total = ctypes.c_int64(0)
        rc = self.lib.tb_grpc_poll(
            handle, ctypes.byref(tag), ctypes.byref(result),
            ctypes.byref(gs), ctypes.byref(hs),
            ctypes.byref(fb), ctypes.byref(total),
        )
        if rc < 0:
            _check(int(rc), "h2_poll")
        if rc == 0:
            return None
        return {
            "tag": tag.value,
            "result": result.value,
            "grpc_status": gs.value,
            "http_status": hs.value,
            "first_byte_ns": fb.value,
            "total_ns": total.value,
        }

    def grpc_read(
        self,
        handle: int,
        authority: str,
        bucket_path: str,
        object_name: str,
        buf: AlignedBuffer,
        read_offset: int = 0,
        read_limit: int = 0,
        headers: str = "",
    ) -> dict:
        """One google.storage.v2.Storage/ReadObject on a connection handle
        (native h2 client): content bytes land in ``buf``. ``headers`` is
        extra request metadata as "k: v\\r\\n" lines (e.g. authorization).
        Sequential RPCs reuse the handle (h2 streams 1, 3, 5, …). On
        nonzero grpc-status the NativeError carries ``grpc_status``; on
        any error the caller must :meth:`conn_close` the handle."""
        fb = ctypes.c_int64(0)
        total_ns = ctypes.c_int64(0)
        grpc_status = ctypes.c_int(-1)
        n = self.lib.tb_grpc_read(
            handle,
            authority.encode(),
            bucket_path.encode(),
            object_name.encode(),
            headers.encode(),
            read_offset,
            read_limit,
            buf.address,
            buf.size,
            ctypes.byref(fb),
            ctypes.byref(total_ns),
            ctypes.byref(grpc_status),
        )
        if n < 0:
            try:
                _check(n, f"grpc_read {object_name}")
            except NativeError as e:
                e.grpc_status = grpc_status.value  # type: ignore[attr-defined]
                raise
        return {
            "length": n,
            "first_byte_ns": fb.value,
            "total_ns": total_ns.value,
            "grpc_status": grpc_status.value,
        }


class NativeFetchPool:
    """Handle over the C++ fetch executor (``tb_pool_*``).

    Contract: a buffer passed to :meth:`submit` is OWNED BY THE POOL until
    its completion comes back from :meth:`next` (identified by ``tag``).
    ``close()`` joins the workers after queued tasks finish (legacy mode)
    or cancels outstanding work after joining the event loop (reactor
    mode) — either way, after close() returns nothing writes into caller
    buffers. Reactor completions ride an SPSC ring: drain from ONE thread
    at a time (the executor runners already do).
    """

    def __init__(self, engine: NativeEngine, handle: int,
                 mode: str = "threads"):
        self._engine = engine
        self._h = handle
        self.mode = mode  # "threads" | "reactor" — what actually engaged

    def submit(
        self,
        host: str,
        port: int,
        path: str,
        buf,
        headers: str = "",
        tag: int = 0,
    ) -> None:
        self.submit_to(host, port, path, buf.address, buf.size,
                       headers=headers, tag=tag)

    def submit_to(
        self,
        host: str,
        port: int,
        path: str,
        address: int,
        nbytes: int,
        headers: str = "",
        tag: int = 0,
    ) -> None:
        """Submit a GET whose body lands at a raw (address, nbytes) region —
        e.g. a staging slot's native buffer, so completed fetches sit in
        slot memory with zero copies. The memory must stay valid until the
        completion returns from :meth:`next`."""
        rc = self._engine.lib.tb_pool_submit(
            self._h, host.encode(), port, path.encode(), headers.encode(),
            address, nbytes, tag,
        )
        if rc != 0:
            _check(rc, "pool_submit")

    def next(self, timeout_ms: int = -1) -> Optional[dict]:
        """One completion, or None on timeout. ``result`` < 0 is the
        engine error code for that task (the pool keeps running)."""
        tag = ctypes.c_uint64(0)
        result = ctypes.c_int64(0)
        status = ctypes.c_int(0)
        fb = ctypes.c_int64(0)
        total = ctypes.c_int64(0)
        start = ctypes.c_int64(0)
        rc = self._engine.lib.tb_pool_next(
            self._h, timeout_ms, ctypes.byref(tag), ctypes.byref(result),
            ctypes.byref(status), ctypes.byref(fb), ctypes.byref(total),
            ctypes.byref(start),
        )
        if rc < 0:
            _check(rc, "pool_next")
        if rc == 0:
            return None
        return {
            "tag": tag.value,
            "result": result.value,
            "status": status.value,
            "first_byte_ns": fb.value,
            "total_ns": total.value,
            "start_ns": start.value,
        }

    def next_batch(self, timeout_ms: int = -1, max_n: int = 64) -> list[dict]:
        """Drain up to ``max_n`` completions in ONE handoff: the SPSC
        ring drain (tb_pool_ring_next_batch — zero lock crossings on a
        reactor pool, delegating to the batched mutex drain on a legacy
        one) when the .so has it, else tb_pool_next_batch (one native
        lock crossing for the whole backlog), else a drain loop over
        :meth:`next` (one blocking wait, then zero-timeout polls — same
        observable behavior, minus the single-crossing economy). Returns
        ``[]`` on timeout. The two-stage degrade is the stale-.so
        contract: old binaries stay loadable, never crash."""
        max_n = max(1, int(max_n))
        if not self._engine._has_pool_ring and not self._engine._has_pool_batch:
            first = self.next(timeout_ms=timeout_ms)
            if first is None:
                return []
            out = [first]
            while len(out) < max_n:
                c = self.next(timeout_ms=0)
                if c is None:
                    break
                out.append(c)
            return out
        n = min(max_n, 256)
        tags = (ctypes.c_uint64 * n)()
        results = (ctypes.c_int64 * n)()
        statuses = (ctypes.c_int * n)()
        fbs = (ctypes.c_int64 * n)()
        totals = (ctypes.c_int64 * n)()
        starts = (ctypes.c_int64 * n)()
        drain = (
            self._engine.lib.tb_pool_ring_next_batch
            if self._engine._has_pool_ring
            else self._engine.lib.tb_pool_next_batch
        )
        rc = drain(
            self._h, timeout_ms, n, tags, results, statuses, fbs, totals,
            starts,
        )
        if rc < 0:
            _check(rc, "pool_next_batch")
        return [
            {
                "tag": int(tags[i]),
                "result": int(results[i]),
                "status": int(statuses[i]),
                "first_byte_ns": int(fbs[i]),
                "total_ns": int(totals[i]),
                "start_ns": int(starts[i]),
            }
            for i in range(rc)
        ]

    def close(self) -> None:
        if self._h:
            self._engine.lib.tb_pool_destroy(self._h)
            self._h = 0

    def __enter__(self) -> "NativeFetchPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NativeSourceServer:
    """In-process HTTP/1.1 object server on native threads (``tb_srv_*``).

    Serves ONE object's pre-rendered bytes (media GETs with Range →
    200/206 slices, anything else → the metadata JSON) with zero Python
    in the serving path — the loopback source the native-executor bench
    window needs on a single-core host, where a Python server would
    compete with the client for the core (round-4 verdict, task #3).
    The server BORROWS ``body``: this wrapper pins it until ``stop()``.
    """

    def __init__(self, engine: NativeEngine, name: str, body):
        import json

        from tpubench.storage.base import ObjectMeta, object_meta_dict

        self._engine = engine
        self._body = np.ascontiguousarray(
            np.frombuffer(body, dtype=np.uint8)
            if not isinstance(body, np.ndarray) else body
        )
        meta = json.dumps(
            object_meta_dict(ObjectMeta(name, self._body.nbytes, 1))
        )
        port = ctypes.c_int(0)
        self._h = engine.lib.tb_srv_start(
            self._body.ctypes.data, self._body.nbytes, meta.encode(),
            ctypes.byref(port),
        )
        if not self._h:
            raise NativeError("tb_srv_start failed", 0)
        self.port = port.value
        self.host = "127.0.0.1"

    @property
    def endpoint(self) -> str:
        return f"http://{self.host}:{self.port}"

    _leaked_pins: list = []  # bodies of servers whose threads never exited

    def stop(self) -> None:
        if self._h:
            rc = self._engine.lib.tb_srv_stop(self._h)
            self._h = None
            if rc != 0:
                # A connection thread is still alive (stalled peer): the C
                # side leaked its struct rather than free under the
                # thread; the body must stay pinned for the process life.
                NativeSourceServer._leaked_pins.append(self._body)
            self._body = None

    def __enter__(self) -> "NativeSourceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


_engine: Optional[NativeEngine] = None
_engine_error: Optional[BaseException] = None
_engine_lock = threading.Lock()


def get_engine() -> Optional[NativeEngine]:
    """Singleton; None if the toolchain/build is unavailable."""
    global _engine, _engine_error
    with _engine_lock:
        if _engine is None and _engine_error is None:
            try:
                _engine = NativeEngine()
            except BaseException as e:  # noqa: BLE001
                _engine_error = e
        return _engine


def peek_engine() -> Optional[NativeEngine]:
    """The engine IF this process already built it — never triggers a
    compile (read-only callers: per-run tb_stats deltas, `info`)."""
    return _engine
