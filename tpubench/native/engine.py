"""ctypes wrapper over libtpubench.so."""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from tpubench.native.build import build_library


class NativeError(OSError):
    pass


_PROTO_ERRORS = {
    -1001: "malformed HTTP response",
    -1002: "body exceeds buffer",
    -1003: "hostname resolution failed",
}


def _check(rc: int, what: str) -> int:
    if rc < 0:
        if rc in _PROTO_ERRORS:
            raise NativeError(f"{what}: {_PROTO_ERRORS[rc]}")
        import os

        raise NativeError(f"{what}: {os.strerror(-rc)} (errno {-rc})")
    return rc


class AlignedBuffer:
    """posix_memalign'd buffer exposed as numpy/memoryview, zero-copy.

    O_DIRECT needs buffer alignment the Go reference never arranged
    explicitly (SURVEY hard-part (e)); 4096 covers all common logical block
    sizes. Also serves as the pre-registered receive buffer for the native
    HTTP path.
    """

    def __init__(self, engine: "NativeEngine", size: int, align: int = 4096):
        self._engine = engine
        self.size = size
        ptr = engine.lib.tb_alloc_aligned(size, align)
        if not ptr:
            raise MemoryError(f"aligned alloc of {size} failed")
        self._ptr = ptr
        self.array = np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)), shape=(size,)
        )

    @property
    def address(self) -> int:
        return self._ptr

    def view(self, n: Optional[int] = None) -> memoryview:
        return memoryview(self.array)[: self.size if n is None else n]

    def free(self) -> None:
        if self._ptr:
            self._engine.lib.tb_free_aligned(self._ptr)
            self._ptr = 0

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass


class NativeEngine:
    def __init__(self):
        path = build_library()
        lib = ctypes.CDLL(path)
        c = ctypes
        lib.tb_now_ns.restype = c.c_int64
        lib.tb_alloc_aligned.restype = c.c_void_p
        lib.tb_alloc_aligned.argtypes = [c.c_size_t, c.c_size_t]
        lib.tb_free_aligned.argtypes = [c.c_void_p]
        lib.tb_open.restype = c.c_int
        lib.tb_open.argtypes = [c.c_char_p, c.c_int, c.POINTER(c.c_int)]
        lib.tb_close.argtypes = [c.c_int]
        lib.tb_file_size.restype = c.c_int64
        lib.tb_file_size.argtypes = [c.c_char_p]
        lib.tb_pread_blocks.restype = c.c_int64
        lib.tb_pread_blocks.argtypes = [
            c.c_int, c.c_void_p, c.c_int64,
            c.POINTER(c.c_int64), c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.tb_read_file_seq.restype = c.c_int64
        lib.tb_read_file_seq.argtypes = [
            c.c_int, c.c_void_p, c.c_int64, c.c_int64, c.POINTER(c.c_int64),
        ]
        lib.tb_pwrite_blocks.restype = c.c_int64
        lib.tb_pwrite_blocks.argtypes = [
            c.c_int, c.c_void_p, c.c_int64,
            c.POINTER(c.c_int64), c.c_int64, c.c_int, c.POINTER(c.c_int64),
        ]
        lib.tb_fill_random.argtypes = [c.c_void_p, c.c_int64, c.c_uint64]
        lib.tb_http_get.restype = c.c_int64
        lib.tb_http_get.argtypes = [
            c.c_char_p, c.c_int, c.c_char_p, c.c_char_p,
            c.c_void_p, c.c_int64, c.POINTER(c.c_int),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64),
        ]
        self.lib = lib

    # ------------------------------------------------------------ helpers --
    def now_ns(self) -> int:
        return self.lib.tb_now_ns()

    def alloc(self, size: int, align: int = 4096) -> AlignedBuffer:
        return AlignedBuffer(self, size, align)

    def open(
        self, path: str, write: bool = False, create: bool = False, direct: bool = False
    ) -> tuple[int, bool]:
        """Returns (fd, direct_applied). Falls back transparently when the
        filesystem rejects O_DIRECT (tmpfs does), reporting the downgrade."""
        flags = (1 if write else 0) | (2 if create else 0) | (4 if direct else 0)
        applied = ctypes.c_int(0)
        fd = self.lib.tb_open(path.encode(), flags, ctypes.byref(applied))
        _check(fd, f"open {path}")
        return fd, bool(applied.value)

    def close(self, fd: int) -> None:
        _check(self.lib.tb_close(fd), "close")

    def file_size(self, path: str) -> int:
        return _check(self.lib.tb_file_size(path.encode()), f"stat {path}")

    def pread_blocks(
        self, fd: int, buf: AlignedBuffer, block_size: int, offsets: np.ndarray
    ) -> tuple[int, np.ndarray]:
        """Timed block reads; returns (total_bytes, per-block ns latencies)."""
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        lat = np.zeros(len(offs), dtype=np.int64)
        total = self.lib.tb_pread_blocks(
            fd,
            buf.address,
            block_size,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(offs),
            lat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        _check(total, "pread_blocks")
        return total, lat

    def read_file_seq(
        self, fd: int, buf: AlignedBuffer, passes: int = 1
    ) -> tuple[int, np.ndarray]:
        lat = np.zeros(passes, dtype=np.int64)
        total = self.lib.tb_read_file_seq(
            fd,
            buf.address,
            buf.size,
            passes,
            lat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        _check(total, "read_file_seq")
        return total, lat

    def pwrite_blocks(
        self,
        fd: int,
        buf: AlignedBuffer,
        block_size: int,
        offsets: np.ndarray,
        fsync_each: bool = True,
    ) -> tuple[int, np.ndarray]:
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        lat = np.zeros(len(offs), dtype=np.int64)
        total = self.lib.tb_pwrite_blocks(
            fd,
            buf.address,
            block_size,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(offs),
            1 if fsync_each else 0,
            lat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        _check(total, "pwrite_blocks")
        return total, lat

    def fill_random(self, buf: AlignedBuffer, n: Optional[int] = None, seed: int = 1):
        self.lib.tb_fill_random(buf.address, buf.size if n is None else n, seed)

    def http_get(
        self,
        host: str,
        port: int,
        path: str,
        buf: AlignedBuffer,
        headers: str = "",
    ) -> dict:
        """Native receive path: body streamed into ``buf``; returns status,
        body length, first-byte and total ns."""
        status = ctypes.c_int(0)
        fb = ctypes.c_int64(0)
        total_ns = ctypes.c_int64(0)
        n = self.lib.tb_http_get(
            host.encode(),
            port,
            path.encode(),
            headers.encode(),
            buf.address,
            buf.size,
            ctypes.byref(status),
            ctypes.byref(fb),
            ctypes.byref(total_ns),
        )
        _check(n, f"http_get {host}:{port}{path}")
        return {
            "status": status.value,
            "length": n,
            "first_byte_ns": fb.value,
            "total_ns": total_ns.value,
        }


_engine: Optional[NativeEngine] = None
_engine_error: Optional[BaseException] = None
_engine_lock = threading.Lock()


def get_engine() -> Optional[NativeEngine]:
    """Singleton; None if the toolchain/build is unavailable."""
    global _engine, _engine_error
    with _engine_lock:
        if _engine is None and _engine_error is None:
            try:
                _engine = NativeEngine()
            except BaseException as e:  # noqa: BLE001
                _engine_error = e
        return _engine
