// Thread-stress harness for the native engine, built with -fsanitize=thread
// by tests/test_tsan.py. The reference has an actual data race on a shared
// latency slice (ssd_test/main.go:80, all goroutines append to one slice);
// this engine's contract is caller-owned PER-THREAD latency arrays and
// per-thread buffers — this harness drives that contract hard under TSAN:
// N threads share one read-only offsets table (the reference shared its
// offset pattern too, ssd_test/main.go:133) but write only their own
// buffers/latency arrays. Any aliasing bug in the engine shows up as a
// ThreadSanitizer report, failing the test.
//
// Exit 0 + no TSAN output = clean.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int64_t tb_now_ns();
void* tb_alloc_aligned(size_t size, size_t align);
void tb_free_aligned(void* p);
int tb_open(const char* path, int flags, int* direct_applied);
int tb_close(int fd);
int64_t tb_pread_blocks(int fd, void* buf, int64_t block_size,
                        const int64_t* offsets, int64_t n_offsets,
                        int64_t* lat_ns);
int64_t tb_pwrite_blocks(int fd, const void* buf, int64_t block_size,
                         const int64_t* offsets, int64_t n_offsets,
                         int fsync_each, int64_t* lat_ns);
void tb_fill_random(void* buf, int64_t n, uint64_t seed);
void* tb_dlpack_create(void* data, int64_t rows, int64_t cols, void* deleter);
void tb_dlpack_free(void* managed);
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <scratch-dir>\n", argv[0]);
    return 2;
  }
  const std::string dir = argv[1];
  const int kThreads = 8;
  const int64_t kBlock = 4096;
  const int64_t kBlocks = 64;

  // Shared read-only offset table (reference shared its pattern too).
  std::vector<int64_t> offsets(kBlocks);
  for (int64_t i = 0; i < kBlocks; ++i) offsets[i] = i * kBlock;

  // Each thread: write its own file, read it back, dlpack round-trips —
  // all through engine entry points, with thread-owned buffers/latencies.
  std::vector<std::thread> threads;
  std::vector<int> rc(kThreads, 0);
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t]() {
      std::string path = dir + "/stress_" + std::to_string(t);
      void* buf = tb_alloc_aligned(kBlock * kBlocks, 4096);
      if (!buf) { rc[t] = 1; return; }
      tb_fill_random(buf, kBlock * kBlocks, 1234 + t);
      std::vector<int64_t> lat(kBlocks);  // per-thread latency array

      int direct = 0;
      int fd = tb_open(path.c_str(), /*write|create|direct*/ 1 | 2 | 4, &direct);
      if (fd < 0) { rc[t] = 2; tb_free_aligned(buf); return; }
      if (tb_pwrite_blocks(fd, buf, kBlock, offsets.data(), kBlocks, 0,
                           lat.data()) < 0) rc[t] = 3;
      tb_close(fd);

      fd = tb_open(path.c_str(), /*read|direct*/ 4, &direct);
      if (fd < 0) { rc[t] = 4; tb_free_aligned(buf); return; }
      for (int pass = 0; pass < 4 && rc[t] == 0; ++pass) {
        if (tb_pread_blocks(fd, buf, kBlock, offsets.data(), kBlocks,
                            lat.data()) < 0) rc[t] = 5;
        void* m = tb_dlpack_create(buf, kBlocks, kBlock, nullptr);
        if (!m) rc[t] = 6;
        else tb_dlpack_free(m);
        (void)tb_now_ns();
      }
      tb_close(fd);
      tb_free_aligned(buf);
      std::remove(path.c_str());
    });
  }
  for (auto& th : threads) th.join();
  for (int t = 0; t < kThreads; ++t) {
    if (rc[t]) { std::fprintf(stderr, "thread %d failed rc=%d\n", t, rc[t]); return 1; }
  }
  std::puts("stress ok");
  return 0;
}
