// Thread-stress harness for the native engine, built with -fsanitize=thread
// by tests/test_tsan.py. The reference has an actual data race on a shared
// latency slice (ssd_test/main.go:80, all goroutines append to one slice);
// this engine's contract is caller-owned PER-THREAD latency arrays and
// per-thread buffers — this harness drives that contract hard under TSAN:
// N threads share one read-only offsets table (the reference shared its
// offset pattern too, ssd_test/main.go:133) but write only their own
// buffers/latency arrays. Any aliasing bug in the engine shows up as a
// ThreadSanitizer report, failing the test.
//
// The reactor phases at the bottom extend the matrix to the TLS and
// HTTP/2 state machines: h2c exactly-once multiplexing against a canned
// in-file server, mid-handshake plaintext garbage, pre-handshake RSTs,
// and pool destroy with handshakes still in flight.
//
// Exit 0 + no TSAN output = clean.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <atomic>
#include <thread>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <sched.h>
#include <unistd.h>

extern "C" {
int64_t tb_now_ns();
void* tb_alloc_aligned(size_t size, size_t align);
void tb_free_aligned(void* p);
int tb_open(const char* path, int flags, int* direct_applied);
int tb_close(int fd);
int64_t tb_pread_blocks(int fd, void* buf, int64_t block_size,
                        const int64_t* offsets, int64_t n_offsets,
                        int64_t* lat_ns);
int64_t tb_pwrite_blocks(int fd, const void* buf, int64_t block_size,
                         const int64_t* offsets, int64_t n_offsets,
                         int fsync_each, int64_t* lat_ns);
void tb_fill_random(void* buf, int64_t n, uint64_t seed);
void* tb_dlpack_create(void* data, int64_t rows, int64_t cols, void* deleter);
void tb_dlpack_free(void* managed);
int64_t tb_pool_create(int threads, int cap, int tls,
                       const char* cafile, int insecure);
int64_t tb_pool_create2(int threads, int cap, int tls,
                        const char* cafile, int insecure, int mode);
int tb_pool_is_reactor(int64_t h);
int tb_pool_ring_next_batch(int64_t h, int timeout_ms, int max_n,
                            uint64_t* tags, int64_t* results, int* statuses,
                            int64_t* fbs, int64_t* totals, int64_t* starts);
int tb_pool_submit(int64_t h, const char* host, int port, const char* path,
                   const char* headers, void* buf, int64_t buf_len,
                   uint64_t tag);
int tb_pool_next_batch(int64_t h, int timeout_ms, int max_n, uint64_t* tags,
                       int64_t* results, int* statuses, int64_t* fbs,
                       int64_t* totals, int64_t* starts);
int tb_pool_next(int64_t h, int timeout_ms, uint64_t* tag, int64_t* result,
                 int* status, int64_t* fb, int64_t* total, int64_t* start);
int tb_pool_destroy(int64_t h);
void* tb_srv_start(const void* body, int64_t body_len, const char* meta_json,
                   int* port_out);
int tb_srv_stop(void* handle);
int tb_tls_available();
}

// Minimal single-purpose HTTP server for the pool stress: keep-alive —
// each accepted connection serves up to 4 requests (so the pool workers'
// per-thread connection REUSE path runs), then closes (so the reconnect
// path runs too).
static int g_srv_fd = -1;

static void handle_conn(int c) {
  for (int served = 0; served < 4; served++) {
    char req[2048];
    ssize_t n = 0, got = 0;
    bool have = false;
    while (got < static_cast<ssize_t>(sizeof req) &&
           (n = recv(c, req + got, sizeof req - got, 0)) > 0) {
      got += n;
      if (memmem(req, got, "\r\n\r\n", 4)) {
        have = true;
        break;
      }
    }
    if (!have) break;  // peer closed between requests
    const bool last = served == 3;
    char resp[256];
    int m = snprintf(resp, sizeof resp,
                     "HTTP/1.1 200 OK\r\nContent-Length: 16\r\n%s\r\n"
                     "0123456789abcdef",
                     last ? "Connection: close\r\n" : "");
    send(c, resp, m, 0);
    if (last) break;
  }
  close(c);
}

static void serve_loop() {
  // One handler thread per connection: a serial server deadlocks with
  // keep-alive pool workers (worker A idles between requests on its held
  // connection while B/C/D block behind it in the backlog). Handlers are
  // joined before returning; they unblock when the peer closes.
  std::vector<std::thread> handlers;
  for (;;) {
    int c = accept(g_srv_fd, nullptr, nullptr);
    if (c < 0) break;  // listener shut down
    handlers.emplace_back(handle_conn, c);
  }
  for (auto& h : handlers) h.join();
}

// Fetch-pool stress: 2 submitter threads race 64 tasks into a 4-worker
// pool against the in-process keep-alive server while the main thread
// drains — the pool's mutex/condvar/ring accounting plus the workers'
// connection-reuse and reconnect paths all run under TSAN.
static int stress_fetch_pool() {
  g_srv_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (g_srv_fd < 0) return 1;
  int one = 1;
  setsockopt(g_srv_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  a.sin_port = 0;
  if (bind(g_srv_fd, reinterpret_cast<struct sockaddr*>(&a), sizeof a) != 0) {
    close(g_srv_fd);
    return 2;
  }
  socklen_t alen = sizeof a;
  getsockname(g_srv_fd, reinterpret_cast<struct sockaddr*>(&a), &alen);
  int port = ntohs(a.sin_port);
  listen(g_srv_fd, 16);
  std::thread srv(serve_loop);

  // Every exit path below must stop the listener and join srv — a
  // joinable std::thread destroyed alive calls std::terminate.
  auto stop_server = [&]() {
    shutdown(g_srv_fd, SHUT_RDWR);  // close() alone does not wake accept()
    close(g_srv_fd);
    srv.join();
  };

  const int kTasks = 64;
  int64_t pool = tb_pool_create(4, 32, 0, "", 0);
  if (pool == 0) {
    stop_server();
    return 3;
  }
  std::vector<void*> bufs(kTasks);
  for (int i = 0; i < kTasks; i++) bufs[i] = tb_alloc_aligned(4096, 4096);

  std::atomic<int> submitted{0};
  std::atomic<int> done_submitters{0};
  std::atomic<bool> submit_failed{false};
  std::vector<std::thread> submitters;
  for (int si = 0; si < 2; si++) {
    submitters.emplace_back([&, si]() {
      for (int i = si; i < kTasks; i += 2) {
        for (;;) {
          int rc = tb_pool_submit(pool, "127.0.0.1", port, "/x", "",
                                  bufs[i], 4096, i);
          if (rc == 0) break;
          if (rc == -EAGAIN) {
            // Ring full (64 tasks vs cap 32): the MAIN thread drains
            // concurrently; yield instead of hammering the pool mutex.
            sched_yield();
            continue;
          }
          submit_failed.store(true);  // hard error: stop submitting
          done_submitters.fetch_add(1);
          return;
        }
        submitted.fetch_add(1);
      }
      done_submitters.fetch_add(1);
    });
  }
  // Drain CONCURRENTLY with submission (the ring is smaller than the
  // task count, so a submit-then-drain sequence would deadlock). Done
  // when everything submitted has drained and both submitters finished —
  // a hard submit error just shrinks the total instead of turning into
  // 30s-per-missing-task timeouts.
  // Alternate the single and BATCHED drain paths so TSAN sees both
  // completion handoffs racing the submitters.
  int drained = 0;
  int bad = 0;
  bool use_batch = false;
  for (;;) {
    if (drained == kTasks) break;
    if (done_submitters.load() == 2 && drained >= submitted.load()) break;
    if (use_batch) {
      uint64_t tags[8];
      int64_t results[8], fbs[8], totals[8], starts[8];
      int statuses[8];
      int n = tb_pool_next_batch(pool, 30000, 8, tags, results, statuses,
                                 fbs, totals, starts);
      if (n <= 0) {  // stall: bail with a failure instead of hanging
        bad++;
        break;
      }
      for (int i = 0; i < n; i++)
        if (results[i] != 16 || statuses[i] != 200) bad++;
      drained += n;
    } else {
      uint64_t tag;
      int64_t result, fb, total, start;
      int status;
      int rc = tb_pool_next(pool, 30000, &tag, &result, &status, &fb,
                            &total, &start);
      if (rc != 1) {  // stall: bail with a failure instead of hanging
        bad++;
        break;
      }
      if (result != 16 || status != 200) bad++;
      drained++;
    }
    use_batch = !use_batch;
  }
  for (auto& t : submitters) t.join();
  if (submit_failed.load()) bad++;
  tb_pool_destroy(pool);
  for (auto b : bufs) tb_free_aligned(b);
  stop_server();
  return bad ? 10 : 0;
}

// C loopback server + discard-mode stress: the fetch pool's 4 workers
// hammer tb_srv_* with a mix of ranged media GETs (landed + content-
// checked), discard tasks (NULL buffer → per-thread scratch), and
// metadata GETs — both new concurrency surfaces (server conn threads,
// worker discard scratch) race under TSAN, and the stop protocol's
// tracked-connection shutdown runs at the end.
static int stress_srv_and_discard() {
  const int64_t kBody = 1 << 20;
  uint8_t* body = static_cast<uint8_t*>(tb_alloc_aligned(kBody, 4096));
  if (!body) return 1;
  tb_fill_random(body, kBody, 77);
  int port = 0;
  void* srv = tb_srv_start(body, kBody, "{\"size\": \"1048576\"}", &port);
  if (!srv) {
    tb_free_aligned(body);
    return 2;
  }
  const int kTasks = 48;
  int64_t pool = tb_pool_create(4, 64, 0, "", 0);
  if (!pool) {
    tb_srv_stop(srv);
    tb_free_aligned(body);
    return 3;
  }
  std::vector<void*> bufs(kTasks, nullptr);
  std::vector<int> starts(kTasks, 0);
  const char* media = "/storage/v1/b/b/o/x?alt=media";
  int bad = 0;
  int submitted_ok = 0;  // drain exactly what actually enqueued
  for (int i = 0; i < kTasks; i++) {
    int rc;
    if (i % 3 == 0) {  // discard full-media (NULL buffer)
      rc = tb_pool_submit(pool, "127.0.0.1", port, media, "", nullptr, 0, i);
    } else if (i % 3 == 1) {  // ranged media, landed + verified below
      bufs[i] = tb_alloc_aligned(65536, 4096);
      if (!bufs[i]) {  // NULL means DISCARD to the pool: never submit it
        bad++;
        continue;
      }
      starts[i] = (i * 4096) % (1 << 19);
      char hdrs[64];
      snprintf(hdrs, sizeof hdrs, "Range: bytes=%d-%d\r\n", starts[i],
               starts[i] + 65535);
      rc = tb_pool_submit(pool, "127.0.0.1", port, media, hdrs, bufs[i],
                          65536, i);
    } else {  // metadata JSON
      bufs[i] = tb_alloc_aligned(4096, 4096);
      if (!bufs[i]) {
        bad++;
        continue;
      }
      rc = tb_pool_submit(pool, "127.0.0.1", port, "/storage/v1/b/b/o/x", "",
                          bufs[i], 4096, i);
    }
    if (rc)
      bad++;
    else
      submitted_ok++;
  }
  for (int n = 0; n < submitted_ok; n++) {
    uint64_t tag;
    int64_t result, fb, total, start;
    int status;
    int rc = tb_pool_next(pool, 30000, &tag, &result, &status, &fb, &total,
                          &start);
    if (rc != 1) {
      bad++;
      break;
    }
    int i = static_cast<int>(tag);
    if (i % 3 == 0) {
      if (result != kBody || status != 200) bad++;
    } else if (i % 3 == 1) {
      if (result != 65536 || status != 206 ||
          memcmp(bufs[i], body + starts[i], 65536) != 0)
        bad++;
    } else {
      if (result <= 0 || status != 200) bad++;
    }
  }
  tb_pool_destroy(pool);
  int leaked = tb_srv_stop(srv);
  for (auto b : bufs)
    if (b) tb_free_aligned(b);
  if (!leaked) tb_free_aligned(body);  // leak contract: keep body pinned
  return bad ? 20 : 0;
}

// Abrupt keep-alive server: serves 2 responses per connection WITHOUT a
// "Connection: close" announcement, then closes — the peer sees a bare
// FIN on a conn it believed reusable. This is the stale-keep-alive
// shape that triggers (a) the batch-edge deferred conn free (a FIN
// event and a reuse race in one epoll batch) and (b) the fresh-socket
// retransmit contract.
static int g_srv2_fd = -1;

static void handle_conn_abrupt(int c) {
  for (int served = 0; served < 2; served++) {
    char req[2048];
    ssize_t n = 0, got = 0;
    bool have = false;
    while (got < static_cast<ssize_t>(sizeof req) &&
           (n = recv(c, req + got, sizeof req - got, 0)) > 0) {
      got += n;
      if (memmem(req, got, "\r\n\r\n", 4)) {
        have = true;
        break;
      }
    }
    if (!have) break;
    const char* resp =
        "HTTP/1.1 200 OK\r\nContent-Length: 16\r\n\r\n0123456789abcdef";
    send(c, resp, strlen(resp), 0);
  }
  close(c);  // unannounced: keep-alive peers must survive the bare FIN
}

static void serve_loop_abrupt() {
  std::vector<std::thread> handlers;
  for (;;) {
    int c = accept(g_srv2_fd, nullptr, nullptr);
    if (c < 0) break;
    handlers.emplace_back(handle_conn_abrupt, c);
  }
  for (auto& h : handlers) h.join();
}

// Reactor vs the abrupt server: single-threaded submit/drain interleave
// (the ring cap forces -EAGAIN backpressure) with every completion
// REQUIRED to succeed — a stale FIN racing connection reuse must end in
// a fresh-socket retransmit, never a surfaced error — and exactly-once
// delivery asserted. The FIN-vs-reuse races also hammer the batch-edge
// deferred conn free under TSAN.
static int stress_reactor_stale_churn() {
  g_srv2_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (g_srv2_fd < 0) return 1;
  int one = 1;
  setsockopt(g_srv2_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  a.sin_port = 0;
  if (bind(g_srv2_fd, reinterpret_cast<struct sockaddr*>(&a), sizeof a) != 0) {
    close(g_srv2_fd);
    return 2;
  }
  socklen_t alen = sizeof a;
  getsockname(g_srv2_fd, reinterpret_cast<struct sockaddr*>(&a), &alen);
  int port = ntohs(a.sin_port);
  listen(g_srv2_fd, 16);
  std::thread srv(serve_loop_abrupt);

  const int kTasks = 96;
  int64_t pool = tb_pool_create2(3, 24, 0, "", 0, 1);
  int bad = 0;
  std::vector<int> seen(kTasks, 0);
  std::vector<void*> bufs(kTasks, nullptr);
  if (!pool) {
    bad = 100;
  } else {
    int next = 0, drained = 0;
    auto drain_some = [&](int timeout_ms) {
      uint64_t tags[8];
      int64_t results[8], fbs[8], totals[8], starts[8];
      int statuses[8];
      int n = tb_pool_ring_next_batch(pool, timeout_ms, 8, tags, results,
                                      statuses, fbs, totals, starts);
      for (int i = 0; i < n; i++) {
        int t = static_cast<int>(tags[i]);
        if (t < 0 || t >= kTasks || seen[t]++) {
          bad++;
          continue;
        }
        // Success REQUIRED: stale FINs must be absorbed by the
        // fresh-socket retransmit, not surfaced.
        if (results[i] != 16 || statuses[i] != 200) bad++;
      }
      return n;
    };
    while (drained < kTasks) {
      while (next < kTasks) {
        void* b = tb_alloc_aligned(4096, 4096);
        if (!b) {
          bad++;
          break;
        }
        int rc = tb_pool_submit(pool, "127.0.0.1", port, "/x", "", b, 4096,
                                next);
        if (rc == -EAGAIN) {
          tb_free_aligned(b);
          break;  // backpressure: drain below
        }
        if (rc != 0) {
          tb_free_aligned(b);
          bad++;
          break;
        }
        bufs[next++] = b;
      }
      int n = drain_some(30000);
      if (n <= 0) {
        bad++;  // stall: bail instead of hanging
        break;
      }
      drained += n;
    }
    tb_pool_destroy(pool);
  }
  shutdown(g_srv2_fd, SHUT_RDWR);
  close(g_srv2_fd);
  srv.join();
  for (auto b : bufs)
    if (b) tb_free_aligned(b);
  return bad ? 50 : 0;
}

// Reactor stress: 2 submitter threads race a mixed task set (landed +
// content-checked ranges, discard, metadata) into the epoll reactor
// against the all-native loopback server while the main thread drains
// through MIXED single/batched paths (tb_pool_next, tb_pool_next_batch,
// tb_pool_ring_next_batch) — the SPSC ring handoff, the doorbell
// eventfd, the submit inbox and the loop's connection state machines
// all race under TSAN, and EXACTLY-ONCE delivery is asserted on a tag
// bitmap (a duplicated or lost completion is a correctness bug, not
// just a race).
static int stress_reactor() {
  const int64_t kBody = 1 << 20;
  uint8_t* body = static_cast<uint8_t*>(tb_alloc_aligned(kBody, 4096));
  if (!body) return 1;
  tb_fill_random(body, kBody, 99);
  int port = 0;
  void* srv = tb_srv_start(body, kBody, "{\"size\": \"1048576\"}", &port);
  if (!srv) {
    tb_free_aligned(body);
    return 2;
  }
  const int kTasks = 64;
  // 2 event loops, 6-connection budget, cap 32 < kTasks so the -EAGAIN
  // admission path races the drain too.
  int64_t pool = tb_pool_create2(6, 32, 0, "", 0, 1 | (2 << 8));
  int bad = 0;
  if (!pool || !tb_pool_is_reactor(pool)) {
    tb_srv_stop(srv);
    tb_free_aligned(body);
    return 3;
  }
  std::vector<void*> bufs(kTasks, nullptr);
  std::vector<int> starts(kTasks, 0);
  const char* media = "/storage/v1/b/b/o/x?alt=media";
  std::atomic<int> submitted{0};
  std::atomic<int> done_submitters{0};
  std::atomic<bool> submit_failed{false};
  std::vector<std::thread> submitters;
  for (int si = 0; si < 2; si++) {
    submitters.emplace_back([&, si]() {
      for (int i = si; i < kTasks; i += 2) {
        int rc;
        if (i % 3 == 1) {
          bufs[i] = tb_alloc_aligned(65536, 4096);
          if (!bufs[i]) {
            submit_failed.store(true);
            continue;
          }
          starts[i] = (i * 4096) % (1 << 19);
        } else if (i % 3 == 2) {
          bufs[i] = tb_alloc_aligned(4096, 4096);
          if (!bufs[i]) {
            submit_failed.store(true);
            continue;
          }
        }
        for (;;) {
          if (i % 3 == 0) {
            rc = tb_pool_submit(pool, "127.0.0.1", port, media, "", nullptr,
                                0, i);
          } else if (i % 3 == 1) {
            char hdrs[64];
            snprintf(hdrs, sizeof hdrs, "Range: bytes=%d-%d\r\n", starts[i],
                     starts[i] + 65535);
            rc = tb_pool_submit(pool, "127.0.0.1", port, media, hdrs,
                                bufs[i], 65536, i);
          } else {
            rc = tb_pool_submit(pool, "127.0.0.1", port,
                                "/storage/v1/b/b/o/x", "", bufs[i], 4096, i);
          }
          if (rc == 0) break;
          if (rc == -EAGAIN) {
            sched_yield();  // main thread drains concurrently
            continue;
          }
          submit_failed.store(true);
          break;
        }
        if (rc == 0) submitted.fetch_add(1);
      }
      done_submitters.fetch_add(1);
    });
  }
  // Exactly-once ledger: each tag must come back exactly once.
  std::vector<int> seen(kTasks, 0);
  int drained = 0;
  int which = 0;
  for (;;) {
    if (done_submitters.load() == 2 && drained >= submitted.load()) break;
    uint64_t tags[8];
    int64_t results[8], fbs[8], totals[8], st_ns[8];
    int statuses[8];
    int n;
    if (which == 0) {
      int rc = tb_pool_next(pool, 30000, &tags[0], &results[0], &statuses[0],
                            &fbs[0], &totals[0], &st_ns[0]);
      n = rc == 1 ? 1 : rc;
    } else if (which == 1) {
      n = tb_pool_next_batch(pool, 30000, 8, tags, results, statuses, fbs,
                             totals, st_ns);
    } else {
      n = tb_pool_ring_next_batch(pool, 30000, 8, tags, results, statuses,
                                  fbs, totals, st_ns);
    }
    which = (which + 1) % 3;
    if (n <= 0) {
      if (done_submitters.load() == 2 && drained >= submitted.load()) break;
      bad++;  // stall: bail instead of hanging
      break;
    }
    for (int i = 0; i < n; i++) {
      int t = static_cast<int>(tags[i]);
      if (t < 0 || t >= kTasks || seen[t]++) {
        bad++;  // duplicate or junk tag: delivery not exactly-once
        continue;
      }
      if (t % 3 == 0) {
        if (results[i] != kBody || statuses[i] != 200) bad++;
      } else if (t % 3 == 1) {
        if (results[i] != 65536 || statuses[i] != 206 ||
            memcmp(bufs[t], body + starts[t], 65536) != 0)
          bad++;
      } else {
        if (results[i] <= 0 || statuses[i] != 200) bad++;
      }
    }
    drained += n;
  }
  for (auto& t : submitters) t.join();
  if (submit_failed.load()) bad++;
  for (int t = 0; t < kTasks; t++)
    if (seen[t] > 1) bad++;  // belt+braces: ledger re-check after joins
  tb_pool_destroy(pool);
  int leaked = tb_srv_stop(srv);
  for (auto b : bufs)
    if (b) tb_free_aligned(b);
  if (!leaked) tb_free_aligned(body);
  return bad ? 30 : 0;
}

// Destroy-ordering hammer: create → submit (leaving work IN FLIGHT) →
// destroy, in a tight loop. tb_pool_destroy must drain the doorbell and
// rings and join every loop thread BEFORE freeing — the shutdown
// ordering the thread-per-connection teardown never had a test for. A
// use-after-free here is a TSAN/ASAN report or a crash; a missed join
// is a leaked-thread wreck on iteration 2.
static int stress_reactor_destroy_hammer() {
  const int64_t kBody = 512 * 1024;
  uint8_t* body = static_cast<uint8_t*>(tb_alloc_aligned(kBody, 4096));
  if (!body) return 1;
  tb_fill_random(body, kBody, 123);
  int port = 0;
  void* srv = tb_srv_start(body, kBody, "{\"size\": \"524288\"}", &port);
  if (!srv) {
    tb_free_aligned(body);
    return 2;
  }
  const char* media = "/storage/v1/b/b/o/x?alt=media";
  int bad = 0;
  for (int it = 0; it < 12; it++) {
    int64_t pool = tb_pool_create2(4, 16, 0, "", 0, 1 | ((it % 2 + 1) << 8));
    if (!pool) {
      bad++;
      continue;
    }
    for (int i = 0; i < 8; i++)
      tb_pool_submit(pool, "127.0.0.1", port, media, "", nullptr, 0, i);
    // Vary how much settles before the teardown races the in-flight
    // wakes: drain nothing / one / a small batch.
    if (it % 3 == 1) {
      uint64_t tag;
      int64_t result, fb, total, start;
      int status;
      tb_pool_next(pool, 50, &tag, &result, &status, &fb, &total, &start);
    } else if (it % 3 == 2) {
      uint64_t tags[4];
      int64_t results[4], fbs[4], totals[4], starts2[4];
      int statuses[4];
      tb_pool_ring_next_batch(pool, 50, 4, tags, results, statuses, fbs,
                              totals, starts2);
    }
    if (tb_pool_destroy(pool) != 0) bad++;
  }
  int leaked = tb_srv_stop(srv);
  if (!leaked) tb_free_aligned(body);  // leak contract: keep body pinned
  return bad ? 40 : 0;
}

// Loopback listener helper for the TLS/h2 reactor phases below (the
// earlier phases predate it and keep their inline setup).
static int mk_listener(int* fd_out, int* port_out) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a;
  memset(&a, 0, sizeof a);
  a.sin_family = AF_INET;
  a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  a.sin_port = 0;
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&a), sizeof a) != 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof a;
  getsockname(fd, reinterpret_cast<struct sockaddr*>(&a), &alen);
  listen(fd, 16);
  *fd_out = fd;
  *port_out = ntohs(a.sin_port);
  return 0;
}

// TLS reactor vs a server that answers the ClientHello with PLAINTEXT
// GARBAGE mid-handshake: every task must settle with a surfaced error
// (TB_ETLS is permanent — no retransmit storm, no hang), exactly once,
// and the nonblocking handshake state machine's error path plus the
// SSL teardown run under the sanitizer.
static int stress_reactor_tls_midreset() {
  if (!tb_tls_available()) return 0;  // no OpenSSL in this image: skip
  int lfd = -1, port = 0;
  if (mk_listener(&lfd, &port)) return 1;
  std::thread srv([lfd]() {
    std::vector<std::thread> handlers;
    for (;;) {
      int c = accept(lfd, nullptr, nullptr);
      if (c < 0) break;
      handlers.emplace_back([c]() {
        char b[512];
        recv(c, b, sizeof b, 0);  // swallow (part of) the ClientHello
        const char* junk = "HTTP/1.1 400 this is not TLS\r\n\r\n";
        send(c, junk, strlen(junk), 0);
        close(c);
      });
    }
    for (auto& h : handlers) h.join();
  });
  const int kTasks = 12;
  int64_t pool = tb_pool_create2(2, 16, 1, "", 1, 1);
  int bad = 0;
  std::vector<void*> bufs(kTasks, nullptr);
  std::vector<int> seen(kTasks, 0);
  if (!pool) {
    bad = 100;
  } else {
    int ok_sub = 0;
    for (int i = 0; i < kTasks; i++) {
      bufs[i] = tb_alloc_aligned(4096, 4096);
      if (!bufs[i]) {
        bad++;
        continue;
      }
      if (tb_pool_submit(pool, "127.0.0.1", port, "/x", "", bufs[i], 4096, i))
        bad++;
      else
        ok_sub++;
    }
    for (int n = 0; n < ok_sub; n++) {
      uint64_t tag;
      int64_t result, fb, total, start;
      int status;
      int rc = tb_pool_next(pool, 30000, &tag, &result, &status, &fb, &total,
                            &start);
      if (rc != 1) {  // stall: bail instead of hanging
        bad++;
        break;
      }
      int t = static_cast<int>(tag);
      if (t < 0 || t >= kTasks || seen[t]++) {
        bad++;
        continue;
      }
      if (result >= 0) bad++;  // garbage-for-TLS MUST surface as an error
    }
    tb_pool_destroy(pool);
  }
  shutdown(lfd, SHUT_RDWR);
  close(lfd);
  srv.join();
  for (auto b : bufs)
    if (b) tb_free_aligned(b);
  return bad ? 60 : 0;
}

// TLS reactor vs a server that RSTs every accepted connection before a
// single handshake byte flows (SO_LINGER{1,0} close): the reset lands
// in C_CONNECTING or C_TLS_HANDSHAKE depending on timing, and either
// way each task must settle exactly once — fresh-connection failures
// surface, they never loop the retransmit rule.
static int stress_reactor_tls_reset() {
  if (!tb_tls_available()) return 0;
  int lfd = -1, port = 0;
  if (mk_listener(&lfd, &port)) return 1;
  std::thread srv([lfd]() {
    for (;;) {
      int c = accept(lfd, nullptr, nullptr);
      if (c < 0) break;
      struct linger lg;
      lg.l_onoff = 1;
      lg.l_linger = 0;
      setsockopt(c, SOL_SOCKET, SO_LINGER, &lg, sizeof lg);
      close(c);  // RST, not FIN
    }
  });
  const int kTasks = 12;
  int64_t pool = tb_pool_create2(2, 16, 1, "", 1, 1);
  int bad = 0;
  std::vector<int> seen(kTasks, 0);
  if (!pool) {
    bad = 100;
  } else {
    int ok_sub = 0;
    for (int i = 0; i < kTasks; i++) {
      if (tb_pool_submit(pool, "127.0.0.1", port, "/x", "", nullptr, 0, i))
        bad++;
      else
        ok_sub++;
    }
    for (int n = 0; n < ok_sub; n++) {
      uint64_t tag;
      int64_t result, fb, total, start;
      int status;
      int rc = tb_pool_next(pool, 30000, &tag, &result, &status, &fb, &total,
                            &start);
      if (rc != 1) {
        bad++;
        break;
      }
      int t = static_cast<int>(tag);
      if (t < 0 || t >= kTasks || seen[t]++) {
        bad++;
        continue;
      }
      if (result >= 0) bad++;  // the RST must surface, not succeed
    }
    tb_pool_destroy(pool);
  }
  shutdown(lfd, SHUT_RDWR);
  close(lfd);
  srv.join();
  return bad ? 70 : 0;
}

// Destroy-with-handshake-in-flight: the server accepts and then says
// NOTHING, so every connection parks in C_TLS_HANDSHAKE waiting for a
// ServerHello that never comes — and tb_pool_destroy tears the reactor
// down mid-handshake, repeatedly. SSL objects owned by half-open
// connections must be freed exactly once (ASAN), and the loop join
// must not race the in-flight wakes (TSAN).
static int stress_reactor_tls_destroy_inflight() {
  if (!tb_tls_available()) return 0;
  int lfd = -1, port = 0;
  if (mk_listener(&lfd, &port)) return 1;
  std::thread srv([lfd]() {
    std::vector<int> conns;
    for (;;) {
      int c = accept(lfd, nullptr, nullptr);
      if (c < 0) break;
      conns.push_back(c);  // hold silently: the handshake never advances
    }
    for (int c : conns) close(c);
  });
  int bad = 0;
  for (int it = 0; it < 6; it++) {
    int64_t pool = tb_pool_create2(2, 16, 1, "", 1, 1 | ((it % 2 + 1) << 8));
    if (!pool) {
      bad++;
      continue;
    }
    for (int i = 0; i < 6; i++)
      tb_pool_submit(pool, "127.0.0.1", port, "/x", "", nullptr, 0, i);
    if (it % 2) {  // sometimes give the handshakes a beat to get airborne
      uint64_t tag;
      int64_t result, fb, total, start;
      int status;
      tb_pool_next(pool, 20, &tag, &result, &status, &fb, &total, &start);
    }
    if (tb_pool_destroy(pool) != 0) bad++;
  }
  shutdown(lfd, SHUT_RDWR);
  close(lfd);
  srv.join();
  return bad ? 80 : 0;
}

// Minimal canned h2c server for the multiplexing stress: consume the
// client preface, speak just enough HTTP/2 (SETTINGS + ACK, canned
// ":status 200" HEADERS and a 16-byte END_STREAM DATA per request
// stream) to complete real streams. Everything else (WINDOW_UPDATE,
// PRIORITY) is read and ignored.
static void h2c_handle(int c) {
  uint8_t buf[65536];
  size_t got = 0;
  while (got < 24) {  // client connection preface
    ssize_t n = recv(c, buf + got, sizeof buf - got, 0);
    if (n <= 0) {
      close(c);
      return;
    }
    got += static_cast<size_t>(n);
  }
  uint8_t sf[9] = {0, 0, 0, 4, 0, 0, 0, 0, 0};  // empty server SETTINGS
  send(c, sf, sizeof sf, 0);
  memmove(buf, buf + 24, got - 24);
  got -= 24;
  for (;;) {
    while (got < 9) {
      ssize_t n = recv(c, buf + got, sizeof buf - got, 0);
      if (n <= 0) {
        close(c);
        return;
      }
      got += static_cast<size_t>(n);
    }
    size_t flen = static_cast<size_t>(buf[0]) << 16 |
                  static_cast<size_t>(buf[1]) << 8 | buf[2];
    uint8_t ftype = buf[3], fflags = buf[4];
    uint32_t sid = (static_cast<uint32_t>(buf[5]) << 24 |
                    static_cast<uint32_t>(buf[6]) << 16 |
                    static_cast<uint32_t>(buf[7]) << 8 | buf[8]) &
                   0x7fffffffu;
    if (9 + flen > sizeof buf) {
      close(c);
      return;
    }
    while (got < 9 + flen) {
      ssize_t n = recv(c, buf + got, sizeof buf - got, 0);
      if (n <= 0) {
        close(c);
        return;
      }
      got += static_cast<size_t>(n);
    }
    if (ftype == 4 && !(fflags & 0x1)) {  // SETTINGS: ACK it
      uint8_t ack[9] = {0, 0, 0, 4, 1, 0, 0, 0, 0};
      send(c, ack, sizeof ack, 0);
    } else if (ftype == 1) {  // HEADERS: canned 200 + END_STREAM DATA
      uint8_t resp[9 + 1 + 9 + 16];
      resp[0] = 0; resp[1] = 0; resp[2] = 1;    // HEADERS, len 1
      resp[3] = 1; resp[4] = 0x4;               // END_HEADERS
      resp[5] = static_cast<uint8_t>(sid >> 24);
      resp[6] = static_cast<uint8_t>(sid >> 16);
      resp[7] = static_cast<uint8_t>(sid >> 8);
      resp[8] = static_cast<uint8_t>(sid);
      resp[9] = 0x88;                           // indexed ":status 200"
      uint8_t* d = resp + 10;
      d[0] = 0; d[1] = 0; d[2] = 16;            // DATA, len 16
      d[3] = 0; d[4] = 0x1;                     // END_STREAM
      d[5] = static_cast<uint8_t>(sid >> 24);
      d[6] = static_cast<uint8_t>(sid >> 16);
      d[7] = static_cast<uint8_t>(sid >> 8);
      d[8] = static_cast<uint8_t>(sid);
      memcpy(d + 9, "0123456789abcdef", 16);
      send(c, resp, sizeof resp, 0);
    }
    memmove(buf, buf + 9 + flen, got - 9 - flen);
    got -= 9 + flen;
  }
}

// h2c prior-knowledge reactor stress: 48 tasks multiplex as streams
// over at most 2 connections against the canned server while the main
// thread drains — frame reassembly, the per-stream ledger in the conn
// state machine, and stream-vs-connection completion all race under
// the sanitizer, with exactly-once delivery asserted per tag.
static int stress_reactor_h2() {
  int lfd = -1, port = 0;
  if (mk_listener(&lfd, &port)) return 1;
  std::thread srv([lfd]() {
    std::vector<std::thread> handlers;
    for (;;) {
      int c = accept(lfd, nullptr, nullptr);
      if (c < 0) break;
      handlers.emplace_back(h2c_handle, c);
    }
    for (auto& h : handlers) h.join();
  });
  const int kTasks = 48;
  int64_t pool = tb_pool_create2(2, 32, 0, "", 0, 1 | 0x20000);
  int bad = 0;
  std::vector<void*> bufs(kTasks, nullptr);
  std::vector<int> seen(kTasks, 0);
  if (!pool) {
    bad = 100;
  } else {
    int next = 0, drained = 0, ok_sub = 0;
    bool sub_done = false;
    while (!sub_done || drained < ok_sub) {
      while (next < kTasks) {
        void* b = tb_alloc_aligned(4096, 4096);
        if (!b) {
          bad++;
          next++;
          continue;
        }
        int rc = tb_pool_submit(pool, "127.0.0.1", port, "/x", "", b, 4096,
                                next);
        if (rc == -EAGAIN) {
          tb_free_aligned(b);
          break;  // backpressure: drain below
        }
        if (rc != 0) {
          tb_free_aligned(b);
          bad++;
          next++;
          continue;
        }
        bufs[next++] = b;
        ok_sub++;
      }
      sub_done = next >= kTasks;
      if (sub_done && drained >= ok_sub) break;
      uint64_t tags[8];
      int64_t results[8], fbs[8], totals[8], starts[8];
      int statuses[8];
      int n = tb_pool_ring_next_batch(pool, 30000, 8, tags, results, statuses,
                                      fbs, totals, starts);
      if (n <= 0) {  // stall: bail instead of hanging
        bad++;
        break;
      }
      for (int i = 0; i < n; i++) {
        int t = static_cast<int>(tags[i]);
        if (t < 0 || t >= kTasks || seen[t]++) {
          bad++;
          continue;
        }
        if (results[i] != 16 || statuses[i] != 200 ||
            memcmp(bufs[t], "0123456789abcdef", 16) != 0)
          bad++;
      }
      drained += n;
    }
    tb_pool_destroy(pool);
  }
  shutdown(lfd, SHUT_RDWR);
  close(lfd);
  srv.join();
  for (auto b : bufs)
    if (b) tb_free_aligned(b);
  return bad ? 90 : 0;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <scratch-dir>\n", argv[0]);
    return 2;
  }
  const std::string dir = argv[1];
  const int kThreads = 8;
  const int64_t kBlock = 4096;
  const int64_t kBlocks = 64;

  // Shared read-only offset table (reference shared its pattern too).
  std::vector<int64_t> offsets(kBlocks);
  for (int64_t i = 0; i < kBlocks; ++i) offsets[i] = i * kBlock;

  // Each thread: write its own file, read it back, dlpack round-trips —
  // all through engine entry points, with thread-owned buffers/latencies.
  std::vector<std::thread> threads;
  std::vector<int> rc(kThreads, 0);
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t]() {
      std::string path = dir + "/stress_" + std::to_string(t);
      void* buf = tb_alloc_aligned(kBlock * kBlocks, 4096);
      if (!buf) { rc[t] = 1; return; }
      tb_fill_random(buf, kBlock * kBlocks, 1234 + t);
      std::vector<int64_t> lat(kBlocks);  // per-thread latency array

      int direct = 0;
      int fd = tb_open(path.c_str(), /*write|create|direct*/ 1 | 2 | 4, &direct);
      if (fd < 0) { rc[t] = 2; tb_free_aligned(buf); return; }
      if (tb_pwrite_blocks(fd, buf, kBlock, offsets.data(), kBlocks, 0,
                           lat.data()) < 0) rc[t] = 3;
      tb_close(fd);

      fd = tb_open(path.c_str(), /*read|direct*/ 4, &direct);
      if (fd < 0) { rc[t] = 4; tb_free_aligned(buf); return; }
      for (int pass = 0; pass < 4 && rc[t] == 0; ++pass) {
        if (tb_pread_blocks(fd, buf, kBlock, offsets.data(), kBlocks,
                            lat.data()) < 0) rc[t] = 5;
        void* m = tb_dlpack_create(buf, kBlocks, kBlock, nullptr);
        if (!m) rc[t] = 6;
        else tb_dlpack_free(m);
        (void)tb_now_ns();
      }
      tb_close(fd);
      tb_free_aligned(buf);
      std::remove(path.c_str());
    });
  }
  for (auto& th : threads) th.join();
  for (int t = 0; t < kThreads; ++t) {
    if (rc[t]) { std::fprintf(stderr, "thread %d failed rc=%d\n", t, rc[t]); return 1; }
  }
  int prc = stress_fetch_pool();
  if (prc) { std::fprintf(stderr, "fetch-pool stress failed rc=%d\n", prc); return 1; }
  int src = stress_srv_and_discard();
  if (src) { std::fprintf(stderr, "srv/discard stress failed rc=%d\n", src); return 1; }
  int rrc = stress_reactor();
  if (rrc) { std::fprintf(stderr, "reactor stress failed rc=%d\n", rrc); return 1; }
  int crc = stress_reactor_stale_churn();
  if (crc) { std::fprintf(stderr, "reactor stale-churn stress failed rc=%d\n", crc); return 1; }
  int hrc = stress_reactor_destroy_hammer();
  if (hrc) { std::fprintf(stderr, "reactor destroy hammer failed rc=%d\n", hrc); return 1; }
  int h2rc = stress_reactor_h2();
  if (h2rc) { std::fprintf(stderr, "reactor h2 stress failed rc=%d\n", h2rc); return 1; }
  int t1 = stress_reactor_tls_midreset();
  if (t1) { std::fprintf(stderr, "reactor tls midreset stress failed rc=%d\n", t1); return 1; }
  int t2 = stress_reactor_tls_reset();
  if (t2) { std::fprintf(stderr, "reactor tls reset stress failed rc=%d\n", t2); return 1; }
  int t3 = stress_reactor_tls_destroy_inflight();
  if (t3) { std::fprintf(stderr, "reactor tls destroy-inflight stress failed rc=%d\n", t3); return 1; }
  std::puts("stress ok");
  return 0;
}
