"""Observability: tracing spans, metric export, and the per-read flight
recorder (SURVEY §5.1, §5.5)."""

from tpubench.obs.flight import (  # noqa: F401
    FlightRecorder,
    flight_from_config,
    render_timeline,
)
from tpubench.obs.telemetry import (  # noqa: F401
    TelemetryRegistry,
    TelemetrySession,
    telemetry_from_config,
)
from tpubench.obs.tracing import (  # noqa: F401
    NoopTracer,
    RecordingTracer,
    Tracer,
    make_tracer,
)
