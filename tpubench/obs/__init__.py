"""Observability: tracing spans and metric export (SURVEY §5.1, §5.5)."""

from tpubench.obs.tracing import (  # noqa: F401
    NoopTracer,
    RecordingTracer,
    Tracer,
    make_tracer,
)
