"""Metric export (reference ``metrics_exporter.go``).

The reference registers one OpenCensus view (``princer_go_client_read_latency``
with the default latency histogram buckets) and ships it to Cloud Monitoring
under ``custom.googleapis.com/custom-go-client/`` every 30 s
(metrics_exporter.go:22-44). Known bug NOT reproduced: the shadowed exporter
var that silently skipped the final flush (``:37``, SURVEY §2.1 #7) — here
``close()`` always flushes.

Implementations:

* :class:`LatencyDistribution` — the OpenCensus default latency buckets, so
  dashboards keyed to the reference's view line up bucket-for-bucket.
* :class:`CloudMonitoringExporter` — periodic Cloud Monitoring time-series
  push (gated on ``google-cloud-monitoring``); ``dry_run`` collects the
  payloads locally so tests can assert on them without GCP.
* :class:`SnapshotWriter` — periodic local JSON snapshots per host, the
  checkpoint/resume story (SURVEY §5.4): runs are restartable and partial
  results survive a crash.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

# OpenCensus ochttp.DefaultLatencyDistribution bucket bounds (ms) — the
# aggregation the reference's view uses (metrics_exporter.go:28).
DEFAULT_LATENCY_BUCKETS_MS = [
    1, 2, 3, 4, 5, 6, 8, 10, 13, 16, 20, 25, 30, 40, 50, 65, 80, 100, 130,
    160, 200, 250, 300, 400, 500, 650, 800, 1000, 2000, 5000, 10000, 20000,
    50000, 100000,
]


class LatencyDistribution:
    """Histogram with the reference view's bucket bounds."""

    def __init__(self, bounds_ms=None):
        self.bounds = list(bounds_ms or DEFAULT_LATENCY_BUCKETS_MS)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum_ms = 0.0

    def record_many_ms(self, values_ms) -> None:
        arr = np.asarray(values_ms, dtype=np.float64)
        if arr.size == 0:
            return
        idx = np.searchsorted(np.asarray(self.bounds), arr, side="right")
        binned = np.bincount(idx, minlength=len(self.counts))
        for i in binned.nonzero()[0]:
            self.counts[i] += int(binned[i])
        self.count += int(arr.size)
        self.sum_ms += float(arr.sum())

    @property
    def mean_ms(self) -> float:
        return self.sum_ms / self.count if self.count else 0.0

    def to_dict(self) -> dict:
        return {
            "bounds_ms": self.bounds,
            "counts": self.counts,
            "count": self.count,
            "mean_ms": self.mean_ms,
        }


class CloudMonitoringExporter:
    """Pushes the read-latency distribution + GB/s gauge as custom metrics.

    Reporting interval mirrors the reference's 30 s (metrics_exporter.go:44);
    the metric prefix is config (default ``custom.googleapis.com/tpubench/``).
    """

    def __init__(
        self,
        project: str,
        metric_prefix: str,
        interval_s: float = 30.0,
        dry_run: bool = False,
        base_labels: Optional[dict] = None,
    ):
        self.project = project
        self.prefix = metric_prefix.rstrip("/")
        self.interval_s = interval_s
        self.dry_run = dry_run
        # Stamped on every series; multi-host runs MUST carry a per-process
        # label or N hosts write the same time series and Cloud Monitoring
        # rejects all but one per sampling period.
        self.base_labels = dict(base_labels or {})
        self.exported: list[dict] = []  # dry-run capture
        self._client = None
        if not dry_run:
            from google.cloud import monitoring_v3  # gated import

            self._client = monitoring_v3.MetricServiceClient()
            self._monitoring_v3 = monitoring_v3

    def _labels(self, labels: Optional[dict]) -> dict:
        return {**self.base_labels, **(labels or {})}

    def export_point(self, name: str, value: float, labels: Optional[dict] = None):
        payload = {
            "type": f"{self.prefix}/{name}",
            "value": value,
            "labels": self._labels(labels),
            "time": time.time(),
        }
        if self.dry_run or self._client is None:
            self.exported.append(payload)
            return
        mv3 = self._monitoring_v3
        series = mv3.TimeSeries()
        series.metric.type = payload["type"]
        for k, v in payload["labels"].items():
            series.metric.labels[k] = str(v)
        series.resource.type = "global"
        point = mv3.Point()
        point.value.double_value = float(value)
        now = time.time()
        point.interval = mv3.TimeInterval(
            {"end_time": {"seconds": int(now), "nanos": int((now % 1) * 1e9)}}
        )
        series.points = [point]
        self._client.create_time_series(
            name=f"projects/{self.project}", time_series=[series]
        )

    def export_distribution(self, name: str, dist: LatencyDistribution, labels=None):
        """Typed Distribution time-series: full histogram (explicit bucket
        bounds + per-bucket counts), never a lossy mean-only stand-in. The
        dry-run payload keeps the same histogram for assertion/offline
        upload."""
        payload = {
            "type": f"{self.prefix}/{name}",
            "distribution": dist.to_dict(),
            "labels": self._labels(labels),
            "time": time.time(),
        }
        if self.dry_run or self._client is None:
            self.exported.append(payload)
            return
        mv3 = self._monitoring_v3
        series = mv3.TimeSeries()
        series.metric.type = payload["type"]
        for k, v in payload["labels"].items():
            series.metric.labels[k] = str(v)
        series.resource.type = "global"
        dval = mv3.types.Distribution(
            count=dist.count,
            mean=dist.mean_ms,
            bucket_options=mv3.types.Distribution.BucketOptions(
                explicit_buckets=mv3.types.Distribution.BucketOptions.Explicit(
                    bounds=[float(b) for b in dist.bounds]
                )
            ),
            bucket_counts=[int(c) for c in dist.counts],
        )
        point = mv3.Point()
        point.value.distribution_value = dval
        now = time.time()
        point.interval = mv3.TimeInterval(
            {"end_time": {"seconds": int(now), "nanos": int((now % 1) * 1e9)}}
        )
        series.points = [point]
        self._client.create_time_series(
            name=f"projects/{self.project}", time_series=[series]
        )

    def summary(self, periodic: Optional["PeriodicExporter"] = None) -> dict:
        """The run-report stamp shared by every workload's extras."""
        out = {
            "flushes": periodic.flush_count if periodic else 1,
            "points": len(self.exported),
            "dry_run": self.dry_run,
            "prefix": self.prefix,
        }
        if periodic and periodic.error_count:
            out["flush_errors"] = periodic.error_count
            out["last_error"] = periodic.last_error
        return out

    def close(self) -> None:  # always flush (unlike the reference's bug)
        pass


class PeriodicExporter:
    """Background thread: calls ``fn()`` every ``interval_s`` and once at
    close — the 30 s reporting loop + guaranteed final flush.

    A flush error (live Cloud Monitoring push hitting a network blip) must
    never kill the flush thread silently NOR crash the workload's finally
    block at the very end of a long run: errors are counted and the last
    one is kept for the run report. A lock serializes flushes so close()'s
    final flush cannot run concurrently with a slow in-flight one."""

    def __init__(self, fn: Callable[[], None], interval_s: float = 30.0):
        self._fn = fn
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="periodic-exporter", daemon=True
        )
        self._flush_lock = threading.Lock()
        self.flush_count = 0
        self.error_count = 0
        self.last_error: Optional[str] = None

    def start(self) -> "PeriodicExporter":
        self._thread.start()
        return self

    def _flush_once(self) -> None:
        with self._flush_lock:
            try:
                self._fn()
                self.flush_count += 1
            except Exception as e:  # noqa: BLE001 — see class docstring
                self.error_count += 1
                self.last_error = f"{type(e).__name__}: {e}"

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self._flush_once()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
        self._flush_once()  # final flush ALWAYS runs (metrics_exporter.go:37 bug fix)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


class SnapshotWriter:
    """Periodic per-host JSON snapshots of in-flight metrics (SURVEY §5.4)."""

    def __init__(
        self,
        snapshot_fn: Callable[[], dict],
        path: str,
        interval_s: float = 30.0,
        process_index: int = 0,
    ):
        self.path = path
        self._fn = snapshot_fn
        self._process_index = process_index
        self._periodic = PeriodicExporter(self._write, interval_s)

    def _write(self) -> None:
        snap = {
            "time": time.time(),
            "process_index": self._process_index,
            **self._fn(),
        }
        tmp = f"{self.path}.tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, self.path)  # atomic: a crash never leaves torn JSON

    def __enter__(self):
        self._periodic.start()
        return self

    def __exit__(self, *exc):
        self._periodic.close()


def load_snapshot(path: str) -> Optional[dict]:
    """Crash-tolerant read of a SnapshotWriter file (the resume path's
    loader): a missing, empty or truncated/partial JSON snapshot — a
    torn write from a crash mid-flush, or a ``.tmp`` that never got its
    atomic rename — returns ``None`` with a one-line stderr warning
    instead of poisoning the reader with a traceback. The run then
    starts from scratch, which is exactly what a corrupt checkpoint
    must mean."""
    import sys

    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        print(f"warning: {path}: unreadable snapshot ({e}), ignored",
              file=sys.stderr)
        return None
    if not raw.strip():
        print(f"warning: {path}: empty snapshot, ignored", file=sys.stderr)
        return None
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError as e:
        print(
            f"warning: {path}: truncated/partial snapshot "
            f"({e.msg} at char {e.pos}), ignored",
            file=sys.stderr,
        )
        return None
    if not isinstance(doc, dict):
        print(
            f"warning: {path}: snapshot is not a JSON object "
            f"({type(doc).__name__}), ignored",
            file=sys.stderr,
        )
        return None
    return doc


def _otlp_export(exp, payload: dict) -> None:
    """Shared dry-run-capture + POST tail of the OTLP exporters (metrics
    and traces ride the SAME machinery — one copy, so a future retry/
    auth/compression change cannot silently miss one): append to the
    bounded newest-kept capture window, then POST when an endpoint is
    configured (stdlib urllib, 10 s timeout)."""
    exp.exported.append(payload)
    if len(exp.exported) > exp._keep:
        # Keep the newest payloads: a day-long run's dry-run capture
        # must not grow without bound.
        del exp.exported[: len(exp.exported) - exp._keep]
    if exp.endpoint:
        import urllib.request

        req = urllib.request.Request(
            exp.endpoint,
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10):
            pass
        exp.posts += 1


class OTLPMetricsExporter:
    """OTLP-shaped JSON metric export (resourceMetrics/scopeMetrics/
    metrics — the OTLP/HTTP JSON wire shape) off a snapshot function,
    periodic via :class:`PeriodicExporter` like every other exporter
    here. Without an endpoint every payload is captured dry-run (the
    CloudMonitoringExporter discipline: tests and offline uploaders
    assert on ``exported``); with an endpoint set, payloads POST via
    stdlib urllib — no OTel SDK, no new hard deps."""

    def __init__(self, snapshot_fn: Callable[[], dict],
                 endpoint: str = "", resource: Optional[dict] = None,
                 keep_payloads: int = 64):
        self._fn = snapshot_fn
        self.endpoint = endpoint
        self.resource = dict(resource or {})
        self.exported: list[dict] = []  # dry-run / latest-payload capture
        self._keep = max(1, keep_payloads)
        self.posts = 0

    def build_payload(self) -> dict:
        """One OTLP ExportMetricsServiceRequest-shaped dict from the
        registry snapshot: counters → monotonic cumulative sums, gauges
        → gauge points, histograms → explicit-bounds histogram points."""
        snap = self._fn()
        now_ns = time.time_ns()
        metrics = []
        for name, c in snap.get("counters", {}).items():
            points = []
            if isinstance(c, dict):
                # Labeled family (registry snapshot shape:
                # {"label": <key>, "children": {<value>: n}}).
                key = c.get("label", "label")
                for lv, v in sorted(c.get("children", {}).items()):
                    points.append({
                        "asDouble": float(v),
                        "timeUnixNano": str(now_ns),
                        "attributes": [{
                            "key": key,
                            "value": {"stringValue": str(lv)},
                        }],
                    })
            else:
                points.append({
                    "asDouble": float(c),
                    "timeUnixNano": str(now_ns),
                })
            metrics.append({
                "name": name,
                "sum": {
                    "dataPoints": points,
                    "aggregationTemporality": 2,  # CUMULATIVE
                    "isMonotonic": True,
                },
            })
        for name, v in snap.get("gauges", {}).items():
            metrics.append({
                "name": name,
                "gauge": {"dataPoints": [{
                    "asDouble": float(v), "timeUnixNano": str(now_ns),
                }]},
            })
        for name, h in snap.get("histograms", {}).items():
            metrics.append({
                "name": name,
                "histogram": {
                    "dataPoints": [{
                        "count": str(h.get("count", 0)),
                        "sum": float(h.get("sum_ms", 0.0)),
                        "explicitBounds": [
                            float(b) for b in h.get("bounds_ms", [])
                        ],
                        "bucketCounts": [
                            str(c) for c in h.get("counts", [])
                        ],
                        "timeUnixNano": str(now_ns),
                    }],
                    "aggregationTemporality": 2,
                },
            })
        return {
            "resourceMetrics": [{
                "resource": {"attributes": [
                    {"key": k, "value": {"stringValue": str(v)}}
                    for k, v in self.resource.items()
                ]},
                "scopeMetrics": [{
                    "scope": {"name": "tpubench"},
                    "metrics": metrics,
                }],
            }],
        }

    def export_once(self) -> None:
        _otlp_export(self, self.build_payload())

    def summary(self, periodic: Optional["PeriodicExporter"] = None) -> dict:
        out = {
            "payloads": len(self.exported),
            "posts": self.posts,
            "endpoint": self.endpoint or "dry_run",
        }
        if periodic is not None:
            out["flushes"] = periodic.flush_count
            if periodic.error_count:
                out["flush_errors"] = periodic.error_count
                out["last_error"] = periodic.last_error
        return out


class OTLPTraceExporter:
    """OTLP-shaped JSON TRACE export over the run's flight records —
    the span twin of :class:`OTLPMetricsExporter`, riding the same
    dry-run-capture / stdlib-urllib-POST machinery. ``records_fn``
    yields the journal records (the trace store); payload shape comes
    from :func:`tpubench.obs.trace.otlp_trace_payload`. A metrics
    endpoint ending in ``/v1/metrics`` is rewritten to ``/v1/traces``
    (the OTLP/HTTP path convention); any other endpoint is used as-is.
    """

    def __init__(self, records_fn: Callable[[], list],
                 endpoint: str = "", resource: Optional[dict] = None,
                 keep_payloads: int = 4):
        self._fn = records_fn
        self.endpoint = (
            endpoint.replace("/v1/metrics", "/v1/traces")
            if endpoint else ""
        )
        self.resource = dict(resource or {})
        self.exported: list[dict] = []
        self._keep = max(1, keep_payloads)
        self.posts = 0
        self.spans_exported = 0

    def export_once(self) -> None:
        from tpubench.obs.trace import otlp_trace_payload

        payload = otlp_trace_payload(self._fn(), resource=self.resource)
        spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
        self.spans_exported += len(spans)
        _otlp_export(self, payload)

    def summary(self) -> dict:
        return {
            "payloads": len(self.exported),
            "spans": self.spans_exported,
            "posts": self.posts,
            "endpoint": self.endpoint or "dry_run",
        }


class MetricsExportSession:
    """In-run periodic metric export — the reference's L2 core behavior
    (view + histogram pushed to Cloud Monitoring every 30 s DURING the run,
    ``metrics_exporter.go:36-58``), generalized to the framework's measure
    set: read/first-byte/stage latency distributions (full histograms) plus
    bytes-ingested and GB/s gauges, flushed every ``interval_s`` and once at
    close (final flush ALWAYS runs — the reference's shadowed-exporter bug
    is not reproduced).

    A long pod run emits its first series after one interval, not only when
    it finishes.
    """

    def __init__(
        self,
        exporter: CloudMonitoringExporter,
        metrics,
        interval_s: float = 30.0,
        labels: Optional[dict] = None,
        bytes_fn: Optional[Callable[[], int]] = None,
    ):
        self.exporter = exporter
        self._metrics = metrics
        self._labels = labels or {}
        # Live progress source for mid-run flushes (the MetricSet's ingest
        # counter is only finalized after the workers join).
        self._bytes_fn = bytes_fn
        self._periodic = PeriodicExporter(self._flush, interval_s)
        # Incremental histogram state: cumulative distribution per series +
        # consumed-sample offset per recorder, so each flush reads only the
        # NEW samples (O(new) per flush, not O(all-so-far) — a long run's
        # flush cost must not grow over time).
        self._dists: dict[str, LatencyDistribution] = {}
        self._offsets: dict[tuple[str, int], int] = {}

    def _dist_of(self, name: str, recorders) -> LatencyDistribution:
        dist = self._dists.setdefault(name, LatencyDistribution())
        for rec in recorders:
            key = (name, id(rec))
            ns, self._offsets[key] = rec.snapshot_tail_ns(
                self._offsets.get(key, 0)
            )
            if ns.size:
                dist.record_many_ms(ns / 1e6)
        return dist

    def _flush(self) -> None:
        m = self._metrics
        for name, recs in (
            ("read_latency", m.read_latency),
            ("first_byte_latency", m.first_byte_latency),
            ("stage_latency", m.stage_latency),
            ("gather_latency", m.gather_latency),
        ):
            dist = self._dist_of(name, recs)
            if dist.count:
                self.exporter.export_distribution(name, dist, self._labels)
        nbytes = self._bytes_fn() if self._bytes_fn else m.ingest.bytes
        self.exporter.export_point("bytes_ingested", float(nbytes), self._labels)
        sec = m.ingest.seconds
        self.exporter.export_point(
            "ingest_gbps", (nbytes / 1e9) / sec if sec > 0 else 0.0, self._labels
        )

    @property
    def flush_count(self) -> int:
        return self._periodic.flush_count

    def summary(self) -> dict:
        """Small run-report stamp: how much was exported, where."""
        return self.exporter.summary(self._periodic)

    def __enter__(self):
        self._periodic.start()
        return self

    def __exit__(self, *exc):
        self._periodic.close()
        self.exporter.close()


def cloud_exporter_from_config(cfg) -> Optional[CloudMonitoringExporter]:
    """``export="cloud"`` activates the push path (dry-run unless
    ``export_dry_run=False``, which needs google-cloud-monitoring and GCP
    creds — absence fails loudly, never a silent no-op). ``"json"``/
    ``"none"`` mean no in-run export."""
    o = cfg.obs
    if o.export in ("", "none", "json"):
        return None
    if o.export != "cloud":
        raise ValueError(f"obs.export={o.export!r}: expected none|json|cloud")
    return CloudMonitoringExporter(
        project=cfg.workload.project or "local",
        metric_prefix=o.metric_prefix,
        interval_s=o.metrics_interval_s,
        dry_run=o.export_dry_run,
        # Per-process label: without it a multi-host pod's N processes write
        # one identical time series and N-1 pushes are rejected.
        base_labels={
            "transport": cfg.transport.protocol,
            "process": str(cfg.dist.process_id),
        },
    )


def metrics_session_from_config(
    cfg, metrics, bytes_fn: Optional[Callable[[], int]] = None
) -> Optional[MetricsExportSession]:
    """MetricSet-driven session (read workload family) per
    ObservabilityConfig; see :func:`cloud_exporter_from_config`."""
    exporter = cloud_exporter_from_config(cfg)
    if exporter is None:
        return None
    return MetricsExportSession(
        exporter, metrics, interval_s=cfg.obs.metrics_interval_s,
        bytes_fn=bytes_fn,
    )
