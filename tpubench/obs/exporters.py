"""Metric export (reference ``metrics_exporter.go``).

The reference registers one OpenCensus view (``princer_go_client_read_latency``
with the default latency histogram buckets) and ships it to Cloud Monitoring
under ``custom.googleapis.com/custom-go-client/`` every 30 s
(metrics_exporter.go:22-44). Known bug NOT reproduced: the shadowed exporter
var that silently skipped the final flush (``:37``, SURVEY §2.1 #7) — here
``close()`` always flushes.

Implementations:

* :class:`LatencyDistribution` — the OpenCensus default latency buckets, so
  dashboards keyed to the reference's view line up bucket-for-bucket.
* :class:`CloudMonitoringExporter` — periodic Cloud Monitoring time-series
  push (gated on ``google-cloud-monitoring``); ``dry_run`` collects the
  payloads locally so tests can assert on them without GCP.
* :class:`SnapshotWriter` — periodic local JSON snapshots per host, the
  checkpoint/resume story (SURVEY §5.4): runs are restartable and partial
  results survive a crash.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

# OpenCensus ochttp.DefaultLatencyDistribution bucket bounds (ms) — the
# aggregation the reference's view uses (metrics_exporter.go:28).
DEFAULT_LATENCY_BUCKETS_MS = [
    1, 2, 3, 4, 5, 6, 8, 10, 13, 16, 20, 25, 30, 40, 50, 65, 80, 100, 130,
    160, 200, 250, 300, 400, 500, 650, 800, 1000, 2000, 5000, 10000, 20000,
    50000, 100000,
]


class LatencyDistribution:
    """Histogram with the reference view's bucket bounds."""

    def __init__(self, bounds_ms=None):
        self.bounds = list(bounds_ms or DEFAULT_LATENCY_BUCKETS_MS)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum_ms = 0.0

    def record_many_ms(self, values_ms) -> None:
        arr = np.asarray(values_ms, dtype=np.float64)
        if arr.size == 0:
            return
        idx = np.searchsorted(np.asarray(self.bounds), arr, side="right")
        binned = np.bincount(idx, minlength=len(self.counts))
        for i in binned.nonzero()[0]:
            self.counts[i] += int(binned[i])
        self.count += int(arr.size)
        self.sum_ms += float(arr.sum())

    @property
    def mean_ms(self) -> float:
        return self.sum_ms / self.count if self.count else 0.0

    def to_dict(self) -> dict:
        return {
            "bounds_ms": self.bounds,
            "counts": self.counts,
            "count": self.count,
            "mean_ms": self.mean_ms,
        }


class CloudMonitoringExporter:
    """Pushes the read-latency distribution + GB/s gauge as custom metrics.

    Reporting interval mirrors the reference's 30 s (metrics_exporter.go:44);
    the metric prefix is config (default ``custom.googleapis.com/tpubench/``).
    """

    def __init__(
        self,
        project: str,
        metric_prefix: str,
        interval_s: float = 30.0,
        dry_run: bool = False,
    ):
        self.project = project
        self.prefix = metric_prefix.rstrip("/")
        self.interval_s = interval_s
        self.dry_run = dry_run
        self.exported: list[dict] = []  # dry-run capture
        self._client = None
        if not dry_run:
            from google.cloud import monitoring_v3  # gated import

            self._client = monitoring_v3.MetricServiceClient()
            self._monitoring_v3 = monitoring_v3

    def export_point(self, name: str, value: float, labels: Optional[dict] = None):
        payload = {
            "type": f"{self.prefix}/{name}",
            "value": value,
            "labels": labels or {},
            "time": time.time(),
        }
        if self.dry_run or self._client is None:
            self.exported.append(payload)
            return
        mv3 = self._monitoring_v3
        series = mv3.TimeSeries()
        series.metric.type = payload["type"]
        for k, v in payload["labels"].items():
            series.metric.labels[k] = str(v)
        series.resource.type = "global"
        point = mv3.Point()
        point.value.double_value = float(value)
        now = time.time()
        point.interval = mv3.TimeInterval(
            {"end_time": {"seconds": int(now), "nanos": int((now % 1) * 1e9)}}
        )
        series.points = [point]
        self._client.create_time_series(
            name=f"projects/{self.project}", time_series=[series]
        )

    def export_distribution(self, name: str, dist: LatencyDistribution, labels=None):
        # Cloud Monitoring distributions need a typed series; the dry-run
        # payload keeps the full histogram for assertion/offline upload.
        payload = {
            "type": f"{self.prefix}/{name}",
            "distribution": dist.to_dict(),
            "labels": labels or {},
            "time": time.time(),
        }
        if self.dry_run or self._client is None:
            self.exported.append(payload)
            return
        self.export_point(f"{name}_mean_ms", dist.mean_ms, labels)

    def close(self) -> None:  # always flush (unlike the reference's bug)
        pass


class PeriodicExporter:
    """Background thread: calls ``fn()`` every ``interval_s`` and once at
    close — the 30 s reporting loop + guaranteed final flush."""

    def __init__(self, fn: Callable[[], None], interval_s: float = 30.0):
        self._fn = fn
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self.flush_count = 0

    def start(self) -> "PeriodicExporter":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self._fn()
            self.flush_count += 1

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._fn()  # final flush ALWAYS runs (metrics_exporter.go:37 bug fix)
        self.flush_count += 1

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


class SnapshotWriter:
    """Periodic per-host JSON snapshots of in-flight metrics (SURVEY §5.4)."""

    def __init__(
        self,
        snapshot_fn: Callable[[], dict],
        path: str,
        interval_s: float = 30.0,
        process_index: int = 0,
    ):
        self.path = path
        self._fn = snapshot_fn
        self._process_index = process_index
        self._periodic = PeriodicExporter(self._write, interval_s)

    def _write(self) -> None:
        snap = {
            "time": time.time(),
            "process_index": self._process_index,
            **self._fn(),
        }
        tmp = f"{self.path}.tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, self.path)  # atomic: a crash never leaves torn JSON

    def __enter__(self):
        self._periodic.start()
        return self

    def __exit__(self, *exc):
        self._periodic.close()
