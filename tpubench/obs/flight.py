"""Per-read flight recorder: bounded phase timelines with straggler
attribution (the always-on, zero-GCP-dependency observability layer).

Each read (and each staging slot / pod-ingest object) becomes one
structured record carrying nanosecond timestamps for the paper's phase
split — ``enqueue``, ``connect``, ``stream_open``, ``first_byte``,
``body_complete``, ``hbm_staged``, ``gather_complete`` — plus retry/fault
annotations. A p99 regression is then attributable: a connection stall
shows up as a fat ``connect``/``first_byte`` segment, a slow
``device_put`` as a fat ``hbm_staged`` segment, and a straggling host as
one row of the straggler table (arXiv:1804.01138 and the Pulsar latency
study both show percentile tails are only actionable decomposed per
phase and per endpoint).

Race-freedom is by the same worker-owned-array construction as
:mod:`tpubench.metrics.recorder`: every worker thread owns a private
bounded ring of records (:class:`WorkerFlight`); rings are merged only
after the workers join. The ring keeps the NEWEST records when it
overflows, so a long run's journal is its recent history, not its
ancient one.

Backends emit connection-level events (connect, stream-open, stale
retries) without any signature change through a thread-local channel:
the workload opens an op (:meth:`WorkerFlight.begin`), which installs
itself as the thread's current op; :func:`note_phase` / :func:`annotate`
called anywhere down-stack (connection pools, retry wrappers) attach to
it, and are free no-ops when no op is active. One worker thread performs
one read at a time, so the channel is race-free by construction.

Journals are plain JSON docs (``format: tpubench-flight-v1``), one per
host (multi-host runs suffix ``.p<process_index>``, the same convention
as the stream snapshot files); :func:`merge_journal_docs` +
:func:`render_timeline` are the pod-level aggregation pass behind
``tpubench report timeline``.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from typing import Callable, Iterable, Optional

import numpy as np

from tpubench.metrics.percentiles import summarize_ns
from tpubench.obs.tracing import (
    TraceContext,
    adopt_trace,
    current_trace,
    new_span_id,
    new_trace_id,
)

JOURNAL_FORMAT = "tpubench-flight-v1"

# Journal CONTENT schema, stamped into every journal doc as
# ``journal_schema`` (the format string above is the envelope and never
# changes for compatible additions). Bump when a field's meaning changes
# or a consumer-visible field is added: readers warn-once-and-continue
# on a NEWER schema (additions are forward-readable), while
# record/replay — which must rebuild a run faithfully, not just render
# it — refuse journals newer than they understand. History:
#   1 — implicit (journals predating the stamp carry no field)
#   2 — the stamp itself + the serve plane's ``replay`` scenario block
JOURNAL_SCHEMA = 2

# Canonical phase order; segment durations are computed between
# consecutive phases PRESENT in a record and attributed to the later one
# ("time spent reaching first_byte from the previous milestone").
# Pipeline phases (PR 3): cache_hit/cache_miss stamp a chunk access's
# resolution, prefetch_issue marks a readahead fetch leaving the queue,
# and stall_begin/stall_end bracket a train-ingest step's data wait — so
# `report timeline` attributes stalls (the stall_end segment IS the
# stall duration) the same way it attributes connect/first_byte time.
# Staging phases (PR 6): the overlapped executor splits a transfer into
# stage_submit (the device_put left the reaper) and stage_complete (the
# bytes LANDED in HBM; hbm_staged is stamped at the same instant) — the
# stage_complete segment IS the transfer's flight time, and with
# out-of-order completion it is the honest per-transfer quantity a
# submit-time stamp would have corrupted.
# Lifecycle phases (PR 15): a resumable upload stamps upload_open when
# its session opens (before any connection work), part_sent at its first
# committed part (per-part detail rides "part" notes and the part
# latency recorder) and upload_complete at finalize; meta_op stamps an
# open-loop metadata operation's completion (the meta_op segment IS its
# service time, queue wait included).
# Coop phases (PR 8): a miss routed to a peer owner stamps peer_request
# when the ask leaves, then peer_hit (the owner served — the peer_hit
# segment IS the peer transfer round-trip) or peer_miss (the owner shed;
# the read falls through to origin, so connect/first_byte follow on the
# SAME record). owner_fetch marks an origin read made AS the chunk's
# ring owner (the one fetch pod-wide single-flight permits).
# Drill phases (PR 17): delta_commit stamps a delta save committing one
# CAS-guarded shard generation (the delta_commit segment IS that
# shard's upload+finalize time under live traffic), and shard_restored
# stamps a restoring joiner completing one shard — all its chunk reads
# landed and the crc verified against the stat-pinned generation (the
# shard_restored segment IS the shard's restore time, contention
# included).
PHASES = (
    "enqueue",
    "cache_hit",
    "cache_miss",
    "prefetch_issue",
    "peer_request",
    "peer_hit",
    "peer_miss",
    "owner_fetch",
    "upload_open",
    "connect",
    "stream_open",
    "first_byte",
    "body_complete",
    "meta_op",
    "part_sent",
    "upload_complete",
    "delta_commit",
    "shard_restored",
    "stall_begin",
    "stall_end",
    "stage_submit",
    "stage_complete",
    "hbm_staged",
    "gather_complete",
)

_tls = threading.local()


def current_op() -> Optional["FlightOp"]:
    return getattr(_tls, "op", None)


def adopt_op(op: Optional["FlightOp"]) -> None:
    """Install ``op`` as THIS thread's current op (None clears it).

    For helper threads doing work on behalf of a read that began on a
    workload thread — the hedged reader's producer threads adopt the
    consumer's op so backend-level phases/annotations (connect,
    first_byte, breaker events) still land on the read's record.
    Appends from two threads interleave but never tear (GIL-atomic
    list/dict ops; first-stamp-wins already governs phase marks).

    Adopting an op also adopts its TRACE position (and None clears
    both): any record the helper thread begins while working for the
    read — a staging-slot transfer completed by the reaper, a nested
    fetch — parents under the read's span in the trace tree."""
    _tls.op = op
    adopt_trace(op.trace_context() if op is not None else None)


def note_phase(phase: str, ns: Optional[int] = None) -> None:
    """Stamp ``phase`` on the calling thread's current op (no-op when no
    op is active — the backends call this unconditionally)."""
    op = getattr(_tls, "op", None)
    if op is not None:
        op.mark(phase, ns)


def annotate(kind: str, **info) -> None:
    """Attach a retry/fault annotation to the current op (no-op when no
    op is active)."""
    op = getattr(_tls, "op", None)
    if op is not None:
        op.note(kind, **info)


class FlightOp:
    """One in-flight read: phase stamps + annotations, appended to the
    owning ring at :meth:`finish`. Context-manager use finishes with the
    exception (if any) recorded as the op's error.

    Every op is also a SPAN in the causal trace plane: it allocates a
    ``span_id``, joins the thread's ambient :class:`TraceContext` (the
    enclosing tracer span, workload step, or in-flight read) as a child
    — or roots a fresh trace when none is active — and, when installed,
    becomes the thread's trace position so nested records parent under
    it. The ids ride the journal record (``trace_id``/``span_id``/
    ``parent_id``), which is what lets ``tpubench report trace`` stitch
    per-host journals into cross-host span trees."""

    __slots__ = ("_ring", "worker", "object", "transport", "kind",
                 "phases", "notes", "bytes", "error", "_done", "_installed",
                 "trace_id", "span_id", "parent_id", "_prev_ctx",
                 "_sampled")

    def __init__(self, ring: "WorkerFlight", object_name: str,
                 transport: str, enqueue_ns: Optional[int] = None,
                 install: bool = True, kind: str = "read"):
        self._ring = ring
        self.worker = ring.name
        self.object = object_name
        self.transport = transport
        # "read": one network read (the straggler tables compare these);
        # "object": a pod-level fetch→stage→gather span; "stage": one
        # staging-slot transfer; "serve": an origin fetch made to answer
        # a peer's request (owner side of a coop hop — excluded from
        # goodput byte credit: the requester's record carries the bytes).
        self.kind = kind
        self.phases: dict[str, int] = {
            "enqueue": enqueue_ns if enqueue_ns is not None
            else time.perf_counter_ns()
        }
        self.notes: list[dict] = []
        self.bytes = 0
        self.error: Optional[str] = None
        self._done = False
        self.span_id = new_span_id()
        ctx = current_trace()
        if ctx is not None:
            self.trace_id = ctx.trace_id
            self.parent_id = ctx.span_id
            # The per-trace sampling decision rides through the op: a
            # tracer span nested under this op (backend client spans)
            # must inherit the ROOT's draw, not re-default to sampled —
            # or an unsampled trace's descendants would record as
            # orphans of spans that were never kept.
            self._sampled = ctx.sampled
        else:
            self.trace_id = new_trace_id()
            self.parent_id = None
            self._sampled = True
        self._prev_ctx = None
        # install=False: side-channel records (e.g. staging-slot records
        # created while a read op is in flight on the same thread) must
        # not displace the thread's current op.
        self._installed = install
        if install:
            _tls.op = self
            self._prev_ctx = ctx
            adopt_trace(self.trace_context())

    def trace_context(self) -> TraceContext:
        """This op's position in the trace tree — what children (nested
        records, helper threads, remote peers) parent under. Carries
        the trace's sampling decision forward."""
        return TraceContext(self.trace_id, self.span_id, self._sampled)

    def mark(self, phase: str, ns: Optional[int] = None) -> None:
        # First stamp wins (e.g. "connect" fires once even when a stale
        # retry reconnects — the retry itself is an annotation). A
        # finished op is immutable: its record is already in the ring,
        # and a straggling helper thread (cancelled hedge loser) must
        # not add out-of-order stamps that would break the journal's
        # monotonicity invariant.
        if self._done or phase in self.phases:
            return
        self.phases[phase] = int(ns if ns else time.perf_counter_ns())

    def note(self, kind: str, **info) -> None:
        if self._done:
            return
        self.notes.append({"kind": kind, "t": time.perf_counter_ns(), **info})

    def abandon(self) -> None:
        """Discard the op WITHOUT appending a record: the work it was
        opened for turned out to be a no-op (e.g. a prefetch pop whose
        chunk a demand read claimed first). A zero-byte ~0 ms record
        would dilute every downstream percentile, so none is written;
        the thread's channel is still released."""
        if self._done:
            return
        self._done = True
        if self._installed and getattr(_tls, "op", None) is self:
            _tls.op = None
            adopt_trace(self._prev_ctx)

    def finish(self, nbytes: int = 0, error: Optional[BaseException] = None
               ) -> None:
        if self._done:
            return
        self._done = True
        if self._installed and getattr(_tls, "op", None) is self:
            _tls.op = None
            adopt_trace(self._prev_ctx)
        self.bytes = int(nbytes)
        if error is not None:
            self.error = f"{type(error).__name__}: {error}"
        rec = {
            "worker": self.worker,
            "object": self.object,
            "transport": self.transport,
            "kind": self.kind,
            "phases": self.phases,
            "bytes": self.bytes,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
        }
        if self.parent_id is not None:
            rec["parent_id"] = self.parent_id
        if self.notes:
            rec["notes"] = self.notes
        if self.error:
            rec["error"] = self.error
        self._ring.append(rec)

    def __enter__(self) -> "FlightOp":
        return self

    def __exit__(self, etype, exc, tb) -> bool:
        self.finish(self.bytes, error=exc)
        return False


class WorkerFlight:
    """One worker thread's private bounded record ring (newest kept)."""

    __slots__ = ("name", "capacity", "_buf", "_pos", "total", "tap")

    def __init__(self, name: str, capacity: int,
                 tap: Optional[Callable[[dict], None]] = None):
        self.name = name
        self.capacity = max(1, capacity)
        self._buf: list[dict] = []
        self._pos = 0
        self.total = 0  # appends ever; total - len(buf) = dropped
        # Live-telemetry tap (obs/telemetry.py): called once per appended
        # record, on the appending worker's thread, BEFORE ring overwrite
        # can drop it — so the registry sees every record even when the
        # journal keeps only the newest. Contract: the tap must not
        # raise (the telemetry feeder catches and counts its own
        # errors); None = no live consumer.
        self.tap = tap

    def begin(self, object_name: str, transport: str = "",
              enqueue_ns: Optional[int] = None,
              install: bool = True, kind: str = "read") -> FlightOp:
        return FlightOp(self, object_name, transport, enqueue_ns,
                        install=install, kind=kind)

    def append(self, rec: dict) -> None:
        self.total += 1
        tap = self.tap
        if tap is not None:
            tap(rec)
        if len(self._buf) < self.capacity:
            self._buf.append(rec)
            return
        # Overwrite the OLDEST slot (ring semantics: newest records win).
        self._buf[self._pos] = rec
        self._pos = (self._pos + 1) % self.capacity

    def records(self) -> list[dict]:
        """Oldest→newest copy (safe post-join; mid-run snapshots may miss
        or double-see the record being appended — fine for a flush)."""
        buf = list(self._buf)
        if self.total <= self.capacity:
            return buf
        pos = self._pos % len(buf) if buf else 0
        return buf[pos:] + buf[:pos]


def _is_gz_path(path: str) -> bool:
    """True when the journal path should be written gzip-compressed: a
    bare ``.gz`` suffix OR a per-host ``.gz.p<idx>`` sibling
    (:func:`host_journal_path` appends the process suffix after the
    extension, and the non-zero hosts must honor the compression the
    base path asked for)."""
    base = os.path.basename(path)
    if base.endswith(".gz"):
        return True
    stem, _, tail = base.rpartition(".")
    return tail.startswith("p") and tail[1:].isdigit() and stem.endswith(".gz")


class FlightRecorder:
    """Per-run registry of worker rings + journal/summary rendering."""

    def __init__(self, capacity_per_worker: int = 1024, host: int = 0):
        self.capacity = capacity_per_worker
        self.host = host
        self._workers: dict[str, WorkerFlight] = {}
        self._lock = threading.Lock()
        self._tap: Optional[Callable[[dict], None]] = None
        # Rotation accounting: successive flushes re-serialize the same
        # ring and re-drop the same oldest records, so the cumulative
        # counter only counts records NEWER than the last rotation
        # watermark (each record counted at most once).
        self.rotation_dropped_total = 0
        self._rotation_watermark_ns = -1

    def set_tap(self, tap: Optional[Callable[[dict], None]]) -> None:
        """Install a per-record live consumer on every ring (existing and
        future) — the telemetry registry's feed. The tap runs on the
        appending worker's thread and must not raise."""
        with self._lock:
            self._tap = tap
            for wf in self._workers.values():
                wf.tap = tap

    def activate(self) -> "_Activation":
        """Install as the run's ambient recorder for the scope: layers
        that the workload cannot hand a ring to directly (the staging
        slot pipeline) reach it via :func:`active_worker`."""
        return _Activation(self)

    def worker(self, name: str) -> WorkerFlight:
        """Get-or-create the ring for ``name`` (creation is locked so
        worker threads may call this concurrently; each ring still has
        exactly one appending owner)."""
        with self._lock:
            wf = self._workers.get(name)
            if wf is None:
                wf = self._workers[name] = WorkerFlight(
                    name, self.capacity, tap=self._tap
                )
            return wf

    def records(self) -> list[dict]:
        out: list[dict] = []
        with self._lock:
            rings = list(self._workers.values())
        for wf in rings:
            for r in wf.records():
                if "host" not in r:
                    r["host"] = self.host
                out.append(r)
        out.sort(key=lambda r: r["phases"].get("enqueue", 0))
        return out

    @property
    def dropped(self) -> int:
        with self._lock:
            rings = list(self._workers.values())
        return sum(max(0, wf.total - wf.capacity) for wf in rings)

    def journal(self, extra: Optional[dict] = None) -> dict:
        doc = {
            "format": JOURNAL_FORMAT,
            "journal_schema": JOURNAL_SCHEMA,
            "host": self.host,
            "time": time.time(),
            "dropped": self.dropped,
            "records": self.records(),
        }
        if extra:
            doc.update(extra)
        return doc

    def write_journal(self, path: str, extra: Optional[dict] = None,
                      max_bytes: int = 0) -> str:
        """Atomic per-host journal write (same torn-JSON-proof discipline
        as SnapshotWriter). A ``.gz`` path writes gzip-compressed (so do
        its ``.gz.p<idx>`` per-host siblings);
        ``max_bytes`` > 0 bounds the SERIALIZED doc size by dropping the
        oldest records (counted in the doc's ``rotation_dropped``) — the
        disk-safety valve for long runs streaming journals every tick."""
        doc = self.journal(extra)
        payload = json.dumps(doc)
        self.last_rotation_dropped = 0
        if max_bytes > 0 and len(payload) > max_bytes:
            records = doc["records"]
            # Records are sorted oldest-first; drop from the front until
            # the doc fits (per-record sizes include the separator).
            over = len(payload) - max_bytes
            dropped = 0
            fresh = 0
            while records and over > 0:
                rec = records[0]
                over -= len(json.dumps(rec)) + 2
                del records[0]
                dropped += 1
                enq = rec["phases"].get("enqueue", 0)
                if enq > self._rotation_watermark_ns:
                    fresh += 1
                    self._rotation_watermark_ns = enq
            doc["rotation_dropped"] = dropped
            self.last_rotation_dropped = dropped
            self.rotation_dropped_total += fresh
            payload = json.dumps(doc)
        tmp = f"{path}.tmp"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if _is_gz_path(path):
            with gzip.open(tmp, "wt", encoding="utf-8") as f:
                f.write(payload)
        else:
            with open(tmp, "w") as f:
                f.write(payload)
        os.replace(tmp, path)
        return path

    def summary(self) -> dict:
        """The RunResult stamp: per-phase p50/p99 + straggler attribution
        over this host's records."""
        return timeline_summary(self.records())


_active: Optional[FlightRecorder] = None


class _Activation:
    __slots__ = ("_rec", "_prev")

    def __init__(self, rec: FlightRecorder):
        self._rec = rec
        self._prev: Optional[FlightRecorder] = None

    def __enter__(self) -> FlightRecorder:
        global _active
        self._prev = _active
        _active = self._rec
        return self._rec

    def __exit__(self, *exc) -> None:
        global _active
        _active = self._prev


def active_worker(name: str) -> Optional[WorkerFlight]:
    """Ring on the run's ambient recorder, or None outside any
    activation — the staging pipeline's zero-config hookup."""
    rec = _active
    return rec.worker(name) if rec is not None else None


def host_journal_path(path: str, process_index: int,
                      process_count: int) -> str:
    """Per-host journal file: process 0 keeps the bare path (single-host
    unchanged), others suffix ``.p<idx>`` — the stream-snapshot
    convention, so one glob collects the pod."""
    if process_count <= 1 or process_index == 0:
        return path
    return f"{path}.p{process_index}"


def flight_from_config(cfg) -> Optional[FlightRecorder]:
    """Recorder per ObservabilityConfig: ``flight_records`` is the
    per-worker ring capacity (0 disables the layer entirely)."""
    cap = getattr(cfg.obs, "flight_records", 0)
    if cap <= 0:
        return None
    return FlightRecorder(
        capacity_per_worker=cap, host=cfg.dist.process_id
    )


def transport_label(cfg) -> str:
    """One stable per-record transport tag (protocol + receive path)."""
    t = cfg.transport
    label = t.protocol
    if t.http2:
        label += "+h2"
    elif t.native_receive:
        label += "+native"
    return label


# ------------------------------------------------------------ analysis ----

def phase_segments(rec: dict) -> dict[str, int]:
    """Segment durations (ns) between consecutive present phases,
    attributed to the later phase, plus ``total`` (last - enqueue)."""
    ph = rec.get("phases", {})
    present = [(p, ph[p]) for p in PHASES if p in ph]
    out: dict[str, int] = {}
    for (_, t0), (p1, t1) in zip(present, present[1:]):
        out[p1] = t1 - t0
    if len(present) >= 2:
        out["total"] = present[-1][1] - present[0][1]
    return out


def monotone(rec: dict) -> bool:
    """True when the record's present phases are in non-decreasing
    timestamp order (the journal invariant the acceptance pins)."""
    ph = rec.get("phases", {})
    ts = [ph[p] for p in PHASES if p in ph]
    return all(a <= b for a, b in zip(ts, ts[1:]))


def _phase_stats(records: Iterable[dict]) -> dict[str, dict]:
    segs: dict[str, list[int]] = {}
    for rec in records:
        for name, dur in phase_segments(rec).items():
            segs.setdefault(name, []).append(dur)
    order = list(PHASES[1:]) + ["total"]
    out: dict[str, dict] = {}
    for name in order:
        vals = segs.get(name)
        if not vals:
            continue
        s = summarize_ns(np.asarray(vals, dtype=np.int64))
        out[name] = {
            "count": s.count,
            "p50_ms": s.p50_ms,
            "p99_ms": s.p99_ms,
        }
    return out


def straggler_attribution(records: list[dict], by: str = "host"
                          ) -> list[dict]:
    """Per-``by`` (host/worker/transport) tail ownership over completed
    records: who owns the slow tail of the total-read latency.

    ``tail_share`` is the fraction of the run's slowest-decile reads
    owned by the group — an injected per-host delay puts that host's
    share near 1.0. Rows sort slowest-p99 first, so row 0 IS the
    straggler. Only "read"-kind records compete (pod-level object spans
    and staging-slot records measure different quantities and would
    dominate the tail by construction); when a journal has no read
    records at all, everything competes."""
    pool = [r for r in records if r.get("kind", "read") == "read"]
    if not pool:
        pool = records
    totals: list[tuple[object, int]] = []
    for rec in pool:
        seg = phase_segments(rec)
        if "total" in seg and not rec.get("error"):
            totals.append((rec.get(by, "?"), seg["total"]))
    if not totals:
        return []
    durs = np.asarray([t for _, t in totals], dtype=np.int64)
    # Slowest decile (at least one read) defines "the tail".
    k = max(1, len(durs) // 10)
    tail_cut = np.sort(durs)[-k]
    tail_total = int((durs >= tail_cut).sum())
    rows = []
    for key in sorted({g for g, _ in totals}, key=str):
        mine = np.asarray([t for g, t in totals if g == key], dtype=np.int64)
        s = summarize_ns(mine)
        rows.append({
            by: key,
            "count": int(mine.size),
            "p50_ms": s.p50_ms,
            "p99_ms": s.p99_ms,
            "tail_share": float((mine >= tail_cut).sum() / tail_total),
        })
    rows.sort(key=lambda r: (-r["p99_ms"], str(r[by])))
    return rows


def record_span_ns(rec: dict) -> tuple[Optional[int], Optional[int]]:
    """(first, last) phase timestamps of a record, or (None, None) when
    it carries no phases."""
    ph = rec.get("phases", {})
    ts = [ph[p] for p in PHASES if p in ph]
    if not ts:
        return None, None
    return min(ts), max(ts)


def goodput_summary(records: list[dict]) -> dict:
    """Journal goodput: delivered bytes over the records' observed wall
    span, per host (perf_counter timestamps are host-relative, so spans
    never mix hosts) and summed pod-wide.

    Byte credit follows the scorecard discipline: ``step`` records carry
    a train-ingest run's consumed bytes (each chunk counted once per
    step); without steps, ``read``-kind records' owner-credited bytes
    are the goodput. THIS is the formula the live telemetry registry
    computes incrementally — ``tpubench top``, the ``/snapshot``
    endpoint and ``report timeline`` must agree because they share it.
    """
    per_host: dict = {}
    for rec in records:
        t0, t1 = record_span_ns(rec)
        if t0 is None:
            continue
        h = per_host.setdefault(rec.get("host", 0), {
            "t0": t0, "t1": t1, "read_bytes": 0, "step_bytes": 0,
            "steps": 0,
        })
        h["t0"] = min(h["t0"], t0)
        h["t1"] = max(h["t1"], t1)
        kind = rec.get("kind", "read")
        if kind == "step":
            h["steps"] += 1
            h["step_bytes"] += rec.get("bytes", 0)
        elif kind == "read" and not rec.get("error"):
            h["read_bytes"] += rec.get("bytes", 0)
    hosts = {}
    total_bytes = 0
    total_gbps = 0.0
    for host, h in sorted(per_host.items(), key=lambda kv: str(kv[0])):
        nbytes = h["step_bytes"] if h["steps"] else h["read_bytes"]
        wall_s = (h["t1"] - h["t0"]) / 1e9
        gbps = (nbytes / 1e9) / wall_s if wall_s > 0 else 0.0
        hosts[host] = {"bytes": nbytes, "wall_s": wall_s, "gbps": gbps}
        total_bytes += nbytes
        total_gbps += gbps
    return {"bytes": total_bytes, "gbps": total_gbps, "hosts": hosts}


def timeline_summary(records: list[dict]) -> dict:
    """Journal → {phases: per-segment p50/p99, stragglers, counts}."""
    errors = sum(1 for r in records if r.get("error"))
    notes = [n for r in records for n in r.get("notes", ())]
    retries = sum(1 for n in notes if n.get("kind") == "retry")
    # Tail-tolerance attribution (storage/tail.py): every hedge launch/
    # win/loss, watchdog stall and breaker transition is a note on the
    # read it happened to, so the timeline can say WHICH reads the
    # resilience machinery touched.
    tail = {
        "hedges": sum(
            1 for n in notes
            if n.get("kind") == "hedge" and n.get("event") == "launch"
        ),
        "hedge_wins": sum(
            1 for n in notes
            if n.get("kind") == "hedge" and n.get("event") == "win"
        ),
        "stalls": sum(1 for n in notes if n.get("kind") == "stall"),
        "breaker_events": sum(1 for n in notes if n.get("kind") == "breaker"),
    }
    # Autotuner attribution (tpubench/tune/): each controller decision is
    # a kind="tune" record carrying a tune note, so the timeline can say
    # when the operating point moved (and which windows accepted vs
    # reverted) next to the reads those windows measured.
    tune_notes = [n for n in notes if n.get("kind") == "tune"]
    tune = {
        "decisions": len(tune_notes),
        "accepts": sum(1 for n in tune_notes if n.get("verdict") == "accept"),
        "reverts": sum(
            1 for n in tune_notes
            if str(n.get("verdict", "")).startswith("revert")
        ),
    }
    # Ingest-pipeline attribution (PR 3): step records carry
    # stall_begin/stall_end only when the step actually waited for data,
    # so the stalled-step count and the stall_end segment stats below ARE
    # the timeline's data-stall story; chunk records carry their cache
    # resolution (hit/miss/prefetch) as phases.
    steps = [r for r in records if r.get("kind") == "step"]
    pipeline = {
        "steps": len(steps),
        # Any step that waited on data at all (has the stall phases).
        # Deliberately NOT named "stalled_steps": the scorecard's
        # stalled-step count applies stall_threshold_ms, which the
        # journal doesn't carry — two different quantities must not
        # share a name.
        "steps_with_data_wait": sum(
            1 for r in steps if "stall_end" in r.get("phases", {})
        ),
        "cache_hits": sum(
            1 for r in records if "cache_hit" in r.get("phases", {})
        ),
        "cache_misses": sum(
            1 for r in records if "cache_miss" in r.get("phases", {})
        ),
        "prefetch_issues": sum(
            1 for r in records if "prefetch_issue" in r.get("phases", {})
        ),
        # Slab-pool pressure (tpubench/mem/): a read that had to lease an
        # overflow slab notes it — sustained overflow here means the pool
        # is undersized for the working set (raise --pool-slabs).
        "slab_overflows": sum(
            1 for n in notes
            if n.get("kind") == "slab" and n.get("event") == "overflow"
        ),
    }
    # Cooperative-cache attribution (PR 8): a peer-routed miss carries
    # peer_request plus its resolution (peer_hit = the transfer landed;
    # peer_miss = the owner shed and origin served the same record);
    # owner_fetch marks the one origin read pod-wide single-flight
    # permits. Demotion/restore decisions are kind="coop" records with a
    # coop note, so the timeline can say when the ring rebalanced.
    peer_hit_recs = [
        r for r in records if "peer_hit" in r.get("phases", {})
    ]
    coop_notes = [n for n in notes if n.get("kind") == "coop"]
    coop = {
        "peer_requests": sum(
            1 for r in records if "peer_request" in r.get("phases", {})
        ),
        "peer_transfers": len(peer_hit_recs),
        "peer_bytes": sum(
            r.get("bytes", 0) for r in peer_hit_recs if not r.get("error")
        ),
        "peer_misses": sum(
            1 for r in records if "peer_miss" in r.get("phases", {})
        ),
        "owner_fetches": sum(
            1 for r in records if "owner_fetch" in r.get("phases", {})
        ),
        "demotions": sum(
            1 for n in coop_notes if n.get("event") == "demote"
        ),
        "restores": sum(
            1 for n in coop_notes if n.get("event") == "restore"
        ),
    }
    # Overlapped-staging attribution (PR 6): every host→HBM transfer is a
    # kind="stage" record whose stage_submit→stage_complete segment is
    # its flight time, stamped at true completion by the window's reaper
    # — so the timeline can say how many transfers ran and how many
    # overlapped-submit records the journal carries.
    stage_recs = [r for r in records if r.get("kind") == "stage"]
    staging = {
        "transfers": len(stage_recs),
        "transfer_bytes": sum(r.get("bytes", 0) for r in stage_recs),
        # Window transfers carry an explicit overlap note; the serial
        # inline ring stamps stage_submit too, so phase presence alone
        # cannot discriminate overlapped from synchronous transfers.
        "overlapped": sum(
            1 for r in stage_recs
            if any(
                n.get("kind") == "stage" and n.get("event") == "overlap"
                for n in r.get("notes", ())
            )
        ),
    }
    # Serve-plane attribution (the open-loop traffic workload): every
    # resolved request notes its outcome (`serve_req`) and every
    # admission-control drop notes `shed` — the timeline can say how
    # much offered load the run absorbed vs refused, per journal.
    # Elastic-membership attribution: every pod-view transition is a
    # kind="member" record carrying a member note (epoch, action, host;
    # the cooperative handoff's byte accounting rides an action=handoff
    # note under the same kind) — the timeline can say when, and how
    # violently, the pod changed shape.
    member_notes = [n for n in notes if n.get("kind") == "member"]
    membership = {
        "events": sum(
            1 for n in member_notes if n.get("action") != "handoff"
        ),
        "by_action": {},
        "handoff_chunks": sum(
            n.get("handoff_chunks", 0) for n in member_notes
        ),
        "handoff_bytes": sum(
            n.get("handoff_bytes", 0) for n in member_notes
        ),
        "last_epoch": max(
            (n.get("epoch", 0) for n in member_notes), default=0
        ),
    }
    for n in member_notes:
        a = n.get("action")
        if a and a != "handoff":
            membership["by_action"][a] = (
                membership["by_action"].get(a, 0) + 1
            )
    serve_notes = [n for n in notes if n.get("kind") == "serve_req"]
    serve = {
        "requests": len(serve_notes),
        "shed": sum(1 for n in notes if n.get("kind") == "shed"),
        "deadline_misses": sum(
            1 for n in serve_notes
            if n.get("outcome") == "completed"
            and n.get("deadline_met") is False
        ),
    }
    return {
        "records": len(records),
        "errors": errors,
        "retries": retries,
        "tail": tail,
        "tune": tune,
        "pipeline": pipeline,
        "coop": coop,
        "membership": membership,
        "staging": staging,
        "serve": serve,
        "goodput": goodput_summary(records),
        "hosts": sorted({r.get("host", 0) for r in records}),
        "phases": _phase_stats(records),
        "stragglers": {
            "by_host": straggler_attribution(records, by="host"),
            "by_worker": straggler_attribution(records, by="worker"),
        },
    }


def merge_journal_docs(docs: Iterable[dict]) -> list[dict]:
    """Pod-level merge: per-host journal docs → one record list, each
    record carrying its host (doc-level host stamped onto records that
    predate the per-record stamp)."""
    out: list[dict] = []
    for doc in docs:
        host = doc.get("host", 0)
        for rec in doc.get("records", ()):
            if "host" not in rec:
                rec = {**rec, "host": host}
            out.append(rec)
    out.sort(key=lambda r: r["phases"].get("enqueue", 0))
    return out


def render_timeline(docs: list[dict]) -> str:
    """The ``tpubench report timeline`` body: per-phase p50/p99 block +
    straggler tables over the merged journals."""
    records = merge_journal_docs(docs)
    summ = timeline_summary(records)
    dropped = sum(int(d.get("dropped", 0)) for d in docs)
    lines = [
        f"== flight timeline: {summ['records']} records, "
        f"{len(docs)} journal(s), hosts={summ['hosts']} "
        f"errors={summ['errors']} retries={summ['retries']}"
        + (f" dropped={dropped}" if dropped else "")
        + " ==",
    ]
    if not records:
        lines.append("  (no records)")
        return "\n".join(lines)
    gp = summ.get("goodput", {})
    if gp.get("bytes"):
        lines.append(
            f"goodput: {gp['gbps']:.4f} GB/s over {gp['bytes']} bytes "
            f"({len(gp.get('hosts', {}))} host(s))"
        )
    tail = summ.get("tail", {})
    if any(tail.values()):
        lines.append(
            f"tail events: hedges={tail['hedges']} "
            f"(wins={tail['hedge_wins']}) stalls={tail['stalls']} "
            f"breaker={tail['breaker_events']}"
        )
    tn = summ.get("tune", {})
    if tn.get("decisions"):
        lines.append(
            f"tune decisions: {tn['decisions']} "
            f"(accepts={tn['accepts']} reverts={tn['reverts']})"
        )
    pipe = summ.get("pipeline", {})
    if any(pipe.values()):
        lines.append(
            f"pipeline: steps={pipe['steps']} "
            f"(with_data_wait={pipe['steps_with_data_wait']}) "
            f"cache_hits={pipe['cache_hits']} "
            f"cache_misses={pipe['cache_misses']} "
            f"prefetch_issues={pipe['prefetch_issues']}"
            + (
                f" slab_overflows={pipe['slab_overflows']}"
                if pipe.get("slab_overflows") else ""
            )
        )
    coop = summ.get("coop", {})
    if any(coop.values()):
        lines.append(
            f"coop: peer_transfers={coop['peer_transfers']} "
            f"bytes={coop['peer_bytes']} "
            f"(requests={coop['peer_requests']} "
            f"misses={coop['peer_misses']}) "
            f"owner_fetches={coop['owner_fetches']} "
            f"demotions={coop['demotions']} restores={coop['restores']}"
        )
    mem = summ.get("membership", {})
    if mem.get("events"):
        by = " ".join(
            f"{a}={n}" for a, n in sorted(mem["by_action"].items())
        )
        lines.append(
            f"membership: events={mem['events']} ({by}) "
            f"epoch={mem['last_epoch']} "
            f"handoff={mem['handoff_chunks']} chunks/"
            f"{mem['handoff_bytes']}B"
        )
    srv = summ.get("serve", {})
    if srv.get("requests") or srv.get("shed"):
        lines.append(
            f"serve: requests={srv['requests']} shed={srv['shed']} "
            f"deadline_misses={srv['deadline_misses']}"
        )
    stg = summ.get("staging", {})
    if stg.get("transfers"):
        lines.append(
            f"staging: transfers={stg['transfers']} "
            f"bytes={stg['transfer_bytes']} "
            f"overlapped={stg['overlapped']}"
        )
    lines.append("phase segments (ms):")
    for name, s in summ["phases"].items():
        lines.append(
            f"  {name:<16} n={s['count']:<6} p50={s['p50_ms']:9.3f}  "
            f"p99={s['p99_ms']:9.3f}"
        )
    for by in ("host", "worker"):
        rows = summ["stragglers"][f"by_{by}"]
        if len(rows) < 2:
            continue
        lines.append(f"stragglers by {by} (slowest p99 first):")
        for r in rows:
            lines.append(
                f"  {by}={r[by]!s:<12} n={r['count']:<6} "
                f"p50={r['p50_ms']:9.3f}  p99={r['p99_ms']:9.3f}  "
                f"tail_share={r['tail_share']:.2f}"
            )
        top = rows[0]
        lines.append(
            f"  -> straggler: {by}={top[by]} "
            f"(p99 {top['p99_ms']:.3f} ms, owns "
            f"{top['tail_share']:.0%} of the slowest decile)"
        )
    return "\n".join(lines)


def read_journal_text(path: str) -> str:
    """Raw journal text, decompressing gzip transparently (detected by
    magic bytes, not the filename — a rotated/renamed .gz still reads).
    A truncated gzip stream raises like truncated JSON parses: callers
    treat both as a partial file."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    return raw.decode("utf-8", errors="replace")


# journal_schema values already warned about (once per process, not per
# file: a 40-host pod's journals are one upgrade notice, not 40).
_SCHEMA_WARNED: set = set()


def load_journals(paths: Iterable[str]) -> list[dict]:
    """Load journal docs, degrading gracefully on partial files: an empty
    or truncated journal (a run died mid-flush, or the stream writer was
    killed between SnapshotWriter flushes) is SKIPPED with a one-line
    warning instead of a traceback — one dead host must not make the
    pod's other journals unreadable. Gzip journals (``.gz``) decompress
    transparently. A well-formed JSON doc that is not a flight journal
    is still a hard error (wrong file, not a partial one)."""
    import sys

    docs = []
    for p in paths:
        try:
            raw = read_journal_text(p)
        except (OSError, EOFError, gzip.BadGzipFile) as e:
            print(
                f"warning: {p}: unreadable flight journal ({e}), skipped",
                file=sys.stderr,
            )
            continue
        if not raw.strip():
            print(f"warning: {p}: empty flight journal, skipped",
                  file=sys.stderr)
            continue
        try:
            doc = json.loads(raw)
        except json.JSONDecodeError as e:
            print(
                f"warning: {p}: truncated/partial flight journal "
                f"({e.msg} at char {e.pos}), skipped",
                file=sys.stderr,
            )
            continue
        if doc.get("format") != JOURNAL_FORMAT:
            raise ValueError(
                f"{p}: not a flight journal (format="
                f"{doc.get('format')!r}; expected {JOURNAL_FORMAT!r})"
            )
        schema = doc.get("journal_schema", 1)
        if isinstance(schema, int) and schema > JOURNAL_SCHEMA \
                and schema not in _SCHEMA_WARNED:
            # Warn ONCE per unknown schema, then render what we can:
            # schema bumps are additive for rendering consumers (report
            # timeline/trace, top), so continuing beats refusing — only
            # record/replay, which must rebuild a run faithfully, hard-
            # refuse newer journals (replay/bundle.py).
            _SCHEMA_WARNED.add(schema)
            print(
                f"warning: {p}: journal_schema {schema} is newer than "
                f"this build understands ({JOURNAL_SCHEMA}); rendering "
                "the fields it knows",
                file=sys.stderr,
            )
        docs.append(doc)
    return docs
