"""Live cross-host journal aggregation + the ``tpubench top`` dashboard.

The flight journals are per-host, atomically-rewritten JSON docs
(``.p<idx>`` suffixes, optionally ``.gz``) that stream during a run —
either on the telemetry session's tick or on the stream workload's
SnapshotWriter cadence. This module tails them the way the MLPerf
TPU-pod methodology demands (cross-host aggregation WHILE the run is in
flight, not post-mortem): re-read whichever files changed, merge the
docs, and fold them into one rolling view — goodput GB/s(/chip),
per-phase p50/p99, cache hit ratio, staging efficiency, hedge/breaker/
tune event counts, and per-host straggler attribution.

``tpubench top`` renders that view as a curses-free ANSI frame
(``--once`` prints a single plain frame for tests/CI); everything here
is jax-free so the dashboard can run on a coordinator VM that never
touches a device.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from typing import Optional

from tpubench.obs.flight import (
    JOURNAL_FORMAT,
    PHASES,
    goodput_summary,
    read_journal_text,
    record_span_ns,
    timeline_summary,
)


def discover_journal_paths(bases: list[str]) -> list[str]:
    """Expand base journal paths into the per-host file set: each base
    plus its ``.p<idx>`` siblings (the multi-host suffix convention),
    existing files only, deduplicated, stable order."""
    seen: dict[str, None] = {}
    for base in bases:
        candidates = [base]
        # foo.json -> foo.json.p1 …; foo.json.gz -> foo.json.gz.p1 is
        # not written (the suffix rides BEFORE nothing — hosts suffix
        # the configured path itself), so glob on the base.
        candidates.extend(sorted(glob.glob(glob.escape(base) + ".p*")))
        for c in candidates:
            if os.path.exists(c) and not c.endswith(".tmp"):
                seen[c] = None
    return list(seen)


def read_journal_doc(path: str) -> Optional[dict]:
    """Tolerant single-doc read for the tailer: a missing, empty,
    truncated or non-journal file returns None (the poll just shows the
    host as not-reporting-yet) — a live dashboard must survive every
    partial state a crashing writer can leave behind."""
    try:
        raw = read_journal_text(path)
        doc = json.loads(raw)
    except Exception:  # noqa: BLE001 — any partial state = not yet
        return None
    if not isinstance(doc, dict) or doc.get("format") != JOURNAL_FORMAT:
        return None
    return doc


class LiveAggregator:
    """Poll-based merge of streaming per-host journals.

    Each ``poll()`` re-reads only files whose (mtime, size) changed,
    keeps the latest good doc per path (a torn mid-rewrite read keeps
    the previous view alive), and returns the merged rolling view."""

    def __init__(self, bases: list[str], window_s: float = 10.0):
        self.bases = list(bases)
        self.window_s = window_s
        self._stamp: dict[str, tuple] = {}
        self._docs: dict[str, dict] = {}

    def poll(self) -> dict:
        for path in discover_journal_paths(self.bases):
            try:
                st = os.stat(path)
                stamp = (st.st_mtime_ns, st.st_size)
            except OSError:
                continue
            if self._stamp.get(path) == stamp:
                continue
            doc = read_journal_doc(path)
            if doc is not None:
                self._docs[path] = doc
                self._docs[path]["_age_base"] = st.st_mtime
                self._stamp[path] = stamp
        return self._view()

    def _view(self) -> dict:
        docs = list(self._docs.values())
        records: list[dict] = []
        files = []
        # Per-host stamps (read, local train-ingest) sum across hosts;
        # pod workloads stamp the mesh-GLOBAL count into every host's
        # journal (chips_global), so those merge by max, never sum —
        # a 4-host 16-chip pod is 16 chips, not 64.
        host_chips = 0
        global_chips = 0
        now = time.time()
        for path, doc in self._docs.items():
            host = doc.get("host", 0)
            c = max(1, int(doc.get("n_chips", 1) or 1))
            if doc.get("chips_global"):
                global_chips = max(global_chips, c)
            else:
                host_chips += c
            files.append({
                "path": path,
                "host": host,
                "records": len(doc.get("records", ())),
                "dropped": int(doc.get("dropped", 0)),
                "rotation_dropped": int(doc.get("rotation_dropped", 0)),
                "age_s": max(0.0, now - doc.get("_age_base", now)),
                "workload": doc.get("workload", ""),
            })
            for rec in doc.get("records", ()):
                if "host" not in rec:
                    rec = {**rec, "host": host}
                records.append(rec)
        records.sort(key=lambda r: r["phases"].get("enqueue", 0))
        summ = timeline_summary(records) if records else None
        rolling = self._rolling_goodput(records)
        return {
            "files": files,
            "hosts": sorted({f["host"] for f in files}),
            "n_chips": max(1, host_chips + global_chips),
            "summary": summ,
            "rolling": rolling,
            "window_s": self.window_s,
        }

    def _rolling_goodput(self, records: list[dict]) -> dict:
        """Goodput over each host's trailing window (perf_counter
        timestamps are host-relative, so the window anchors per host at
        that host's newest record)."""
        if not records:
            return {"gbps": 0.0, "hosts": {}}
        horizon = int(self.window_s * 1e9)
        max_ts: dict = {}
        for rec in records:
            _, t1 = record_span_ns(rec)
            if t1 is not None:
                h = rec.get("host", 0)
                max_ts[h] = max(max_ts.get(h, t1), t1)
        recent = []
        for rec in records:
            _, t1 = record_span_ns(rec)
            h = rec.get("host", 0)
            if t1 is not None and t1 >= max_ts.get(h, 0) - horizon:
                recent.append(rec)
        return goodput_summary(recent)


# --------------------------------------------------------------- render -----

_RED = "\x1b[31;1m"
_BOLD = "\x1b[1m"
_DIM = "\x1b[2m"
_RESET = "\x1b[0m"
_CLEAR = "\x1b[2J\x1b[H"


def render_top(view: dict, color: bool = False) -> str:
    """One ``tpubench top`` frame from a LiveAggregator view: the merged
    rolling numbers with the straggler host highlighted. Plain ASCII
    when ``color`` is False (``--once`` / piped output)."""

    def c(code: str, s: str) -> str:
        return f"{code}{s}{_RESET}" if color else s

    files = view.get("files", [])
    summ = view.get("summary")
    lines = []
    if not files or summ is None:
        lines.append("tpubench top: waiting for journals "
                     f"({len(files)} file(s) found, no records yet)")
        return "\n".join(lines)
    dropped = sum(f["dropped"] for f in files)
    rotated = sum(f["rotation_dropped"] for f in files)
    head = (
        f"tpubench top — {len(files)} journal(s) hosts={view['hosts']} "
        f"records={summ['records']} errors={summ['errors']} "
        f"retries={summ['retries']}"
    )
    if dropped:
        head += f" dropped={dropped}"
    if rotated:
        head += f" rotated={rotated}"
    lines.append(c(_BOLD, head))
    gp = summ.get("goodput", {})
    roll = view.get("rolling", {})
    chips = view.get("n_chips", 1)
    lines.append(
        f"goodput: {gp.get('gbps', 0.0):.4f} GB/s "
        f"({gp.get('gbps', 0.0) / chips:.4f} GB/s/chip, {chips} chip(s))"
        f"   rolling({view.get('window_s', 0):.0f}s): "
        f"{roll.get('gbps', 0.0):.4f} GB/s"
    )
    pipe = summ.get("pipeline", {})
    hits, misses = pipe.get("cache_hits", 0), pipe.get("cache_misses", 0)
    bits = []
    if hits + misses:
        bits.append(f"cache hit {hits / (hits + misses):.1%}")
    coop = summ.get("coop", {})
    if coop.get("peer_requests"):
        bits.append(
            f"peer hit {coop['peer_transfers'] / coop['peer_requests']:.1%} "
            f"({coop['peer_transfers']} transfers, "
            f"{coop['peer_bytes']}B)"
        )
    if coop.get("demotions") or coop.get("restores"):
        bits.append(
            f"coop demotions={coop.get('demotions', 0)}"
            f"/restores={coop.get('restores', 0)}"
        )
    stg = summ.get("staging", {})
    if stg.get("transfers"):
        bits.append(
            f"staging transfers={stg['transfers']} "
            f"overlapped={stg['overlapped']}"
        )
    tail = summ.get("tail", {})
    if any(tail.values()):
        bits.append(
            f"hedges={tail['hedges']}(w{tail['hedge_wins']}) "
            f"stalls={tail['stalls']} breaker={tail['breaker_events']}"
        )
    tn = summ.get("tune", {})
    if tn.get("decisions"):
        bits.append(
            f"tune={tn['decisions']}d/{tn['accepts']}a/{tn['reverts']}r"
        )
    mem = summ.get("membership", {})
    if mem.get("events"):
        bits.append(
            f"membership ev={mem['events']} epoch={mem['last_epoch']} "
            f"handoff={mem['handoff_bytes']}B"
        )
    srv = summ.get("serve", {})
    if srv.get("requests") or srv.get("shed"):
        bits.append(
            f"serve req={srv['requests']} shed={srv['shed']} "
            f"dl_miss={srv['deadline_misses']}"
        )
    if pipe.get("steps"):
        bits.append(
            f"steps={pipe['steps']} "
            f"waited={pipe['steps_with_data_wait']}"
        )
    if bits:
        lines.append("  ".join(bits))
    lines.append("phase segments (ms):        p50        p99")
    for name, s in summ.get("phases", {}).items():
        lines.append(
            f"  {name:<16} {s['p50_ms']:>10.3f} {s['p99_ms']:>10.3f}"
            f"   n={s['count']}"
        )
    rows = summ.get("stragglers", {}).get("by_host", [])
    gp_hosts = gp.get("hosts", {})
    roll_hosts = roll.get("hosts", {})
    ages = {f["host"]: f["age_s"] for f in files}
    if rows:
        lines.append("hosts (slowest p99 first; * = straggler):")
        for i, r in enumerate(rows):
            h = r["host"]

            def _g(d, key=h):
                e = d.get(key) or d.get(str(key)) or {}
                return e.get("gbps", 0.0)

            straggler = i == 0 and len(rows) > 1
            mark = "*" if straggler else " "
            row = (
                f"{mark} host={h!s:<4} n={r['count']:<6} "
                f"p50={r['p50_ms']:9.3f}  p99={r['p99_ms']:9.3f}  "
                f"tail_share={r['tail_share']:.2f}  "
                f"rolling={_g(roll_hosts):.4f} GB/s  "
                f"age={ages.get(h, 0.0):.1f}s"
            )
            lines.append(c(_RED, row) if straggler else row)
    return "\n".join(lines)


def run_top(bases: list[str], interval_s: float = 2.0, once: bool = False,
            window_s: float = 10.0, color: Optional[bool] = None,
            iterations: Optional[int] = None) -> int:
    """The ``tpubench top`` loop: poll, render, repeat. ``--once``
    prints one plain frame and exits (the CI/tests mode); interactive
    mode clears the screen per frame and exits on Ctrl-C."""
    agg = LiveAggregator(bases, window_s=window_s)
    if color is None:
        color = (not once) and sys.stdout.isatty()
    n = 0
    try:
        while True:
            frame = render_top(agg.poll(), color=color)
            if once:
                print(frame)
                return 0
            sys.stdout.write(_CLEAR + frame + "\n")
            sys.stdout.flush()
            n += 1
            if iterations is not None and n >= iterations:
                return 0
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0
