"""JAX profiler capture around workload runs (SURVEY §5.1 north star).

The reference's profiling story is indirect — ``GODEBUG=asyncpreemptoff=1``
in every launcher (read_operations.sh:8) plus 3-minute post-run sleeps so an
external profiler can attach (write_operations/main.go:115-117). The
TPU-native equivalent is first-class: wrap the run in ``jax.profiler.trace``
so the device_put/Pallas DMA path, XLA compilation, and ICI collectives land
in an xplane trace viewable in TensorBoard/XProf (plus optional annotations
via :func:`annotate` for host-side pipeline stages).
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def maybe_profile(profile_dir: str) -> Iterator[None]:
    """Capture a jax.profiler (xplane) trace of the enclosed run into
    ``profile_dir``; no-op when the dir is empty/None."""
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named host-side region inside a capture (shows as a TraceAnnotation
    row in xprof); no-op outside a trace and on failure."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:
        yield
        return
    with ctx:
        yield
