"""JAX profiler capture around workload runs (SURVEY §5.1 north star).

The reference's profiling story is indirect — ``GODEBUG=asyncpreemptoff=1``
in every launcher (read_operations.sh:8) plus 3-minute post-run sleeps so an
external profiler can attach (write_operations/main.go:115-117). The
TPU-native equivalent is first-class: wrap the run in ``jax.profiler.trace``
so the device_put/Pallas DMA path, XLA compilation, and ICI collectives land
in an xplane trace viewable in TensorBoard/XProf (plus optional annotations
via :func:`annotate` for host-side pipeline stages).
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def maybe_profile(profile_dir: str) -> Iterator[None]:
    """Capture a jax.profiler (xplane) trace of the enclosed run into
    ``profile_dir``; no-op when the dir is empty/None."""
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield


def parse_profile_steps(spec: str) -> tuple[int, int] | None:
    """``obs.profile_steps`` "N:M" → (N, M) inclusive step window, None
    when empty. Malformed specs fail at parse time with a one-line
    SystemExit (the validate_fault_config style)."""
    if not spec:
        return None
    parts = spec.split(":")
    try:
        if len(parts) != 2:
            raise ValueError
        lo, hi = int(parts[0]), int(parts[1])
    except ValueError:
        raise SystemExit(
            f"obs.profile_steps={spec!r}: expected \"N:M\" "
            "(inclusive step window, e.g. 2:5)"
        ) from None
    if lo < 0 or hi < lo:
        raise SystemExit(
            f"obs.profile_steps={spec!r}: must satisfy 0 <= N <= M"
        )
    return lo, hi


class StepProfiler:
    """Step-windowed ``jax.profiler`` capture for the train-ingest loop:
    the trace starts when the step counter enters [start, stop] and
    stops when it leaves — profiling a steady-state slice (steps N..M)
    instead of burying the signal under warmup/compile steps.

    A no-op that records WHY when jax profiling is unavailable (no jax,
    profiler API missing, or a second trace already active): the run
    must never fail because its observer couldn't attach."""

    def __init__(self, profile_dir: str, start_step: int, stop_step: int):
        self.dir = profile_dir
        self.start_step = start_step
        self.stop_step = stop_step
        self.active = False
        self.captured = False
        self.error: str | None = None

    def on_step_begin(self, step: int) -> None:
        if (not self.dir or self.active or self.captured
                or step != self.start_step):
            return
        try:
            import jax

            jax.profiler.start_trace(self.dir)
            self.active = True
        except Exception as e:  # noqa: BLE001 — observer must not kill the run
            self.error = f"{type(e).__name__}: {e}"

    def on_step_end(self, step: int) -> None:
        if self.active and step >= self.stop_step:
            self._stop()

    def close(self) -> None:
        """Stop a still-open capture (short runs whose stop step never
        arrived) so the trace file is complete."""
        if self.active:
            self._stop()

    def _stop(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
            self.captured = True
        except Exception as e:  # noqa: BLE001
            self.error = f"{type(e).__name__}: {e}"
        self.active = False

    def info(self) -> dict | None:
        """The ``extra["profile"]`` stamp; None when profiling is off."""
        if not self.dir:
            return None
        out = {
            "dir": self.dir,
            "steps": [self.start_step, self.stop_step],
            "captured": self.captured,
        }
        if self.error:
            out["error"] = self.error
        return out


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named host-side region inside a capture (shows as a TraceAnnotation
    row in xprof); no-op outside a trace and on failure."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:
        yield
        return
    with ctx:
        yield
