"""Live telemetry plane: the in-run, pull-based metrics registry.

Half the reference's value is *cloud observability export* — an
OpenCensus view pushed to Cloud Monitoring every 30 s during the run
(``metrics_exporter.go:36-58``). tpubench's richer signal (flight
records, ``tb_stats_*`` native counters, pipeline/staging/tune stats)
was until now only inspectable *after* a run, via journal merge in
``tpubench report timeline``. This module makes the same signal
scrapeable while the run is in flight:

* :class:`TelemetryRegistry` — counters, gauges and fixed-bucket
  histograms (the reference view's ``LatencyDistribution`` bucket
  bounds, so dashboards line up bucket-for-bucket with the Cloud
  Monitoring series), every metric registered WITH help text (the
  metric-drift guard in tests pins registry ↔ README table ↔ PHASES);
* a **flight-channel feeder**: the registry taps every appended flight
  record (``FlightRecorder.set_tap``) on the worker's own thread —
  per-phase segment histograms, byte/error/hedge/breaker/tune/cache
  counters, and the goodput tally all update record-by-record, before
  ring overwrite can drop anything;
* :class:`TelemetrySession` — the per-run wiring: a tiny stdlib-only
  HTTP endpoint (Prometheus text exposition at ``/metrics``, JSON at
  ``/snapshot``; ``--telemetry-port``, 0 = ephemeral), periodic
  OTLP-shaped JSON export through the exporters machinery, incremental
  sampling of the run's own ``LatencyRecorder`` tails and the native
  ``tb_stats_*`` counters, and the in-run journal stream the live
  aggregator behind ``tpubench top`` (:mod:`tpubench.obs.live`) tails.

Agreement discipline: the registry computes goodput with the SAME
formula as :func:`tpubench.obs.flight.goodput_summary` and keeps exact
nanosecond samples per phase next to the bucketed histograms, so the
``/snapshot`` percentiles and the post-hoc ``report timeline`` numbers
agree on the same records (the acceptance test pins <1 %).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from bisect import bisect_right
from typing import Callable, Optional, Sequence

import numpy as np

from tpubench.config import TelemetryConfig
from tpubench.metrics.percentiles import summarize_ns
from tpubench.obs.exporters import (
    DEFAULT_LATENCY_BUCKETS_MS,
    OTLPMetricsExporter,
    PeriodicExporter,
)
from tpubench.obs.flight import PHASES, phase_segments, record_span_ns

# --------------------------------------------------------------- metrics ----

# Per-histogram bound on retained exact nanosecond samples; reaching it
# halves the list and doubles the keep stride (deterministic systematic
# subsample — no RNG, so resumable/replayable runs stay bit-identical).
EXACT_SAMPLE_CAP = 65536


class Counter:
    """Monotone counter. Mutations happen under the registry lock (the
    feeder/ticker serialize); reads are snapshot/render-side."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def set_cumulative(self, v: float) -> None:
        """Adopt an externally-cumulative value (native ``tb_stats_*``
        deltas); clamped monotone so a stale sample can never make a
        Prometheus counter go backwards."""
        if v > self.value:
            self.value = v


class LabeledCounter:
    """One-label counter family (the native-transport counters: one
    child per ``tb_stats_*`` key, a bounded, known-at-runtime set)."""

    __slots__ = ("name", "help", "label", "children")

    def __init__(self, name: str, help_: str, label: str):
        self.name = name
        self.help = help_
        self.label = label
        self.children: dict[str, float] = {}

    def inc(self, label_value: str, n: float = 1.0) -> None:
        self.children[label_value] = self.children.get(label_value, 0.0) + n

    def set_cumulative(self, label_value: str, v: float) -> None:
        if v > self.children.get(label_value, 0.0):
            self.children[label_value] = v


class Gauge:
    __slots__ = ("name", "help", "value", "known")

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.value = 0.0
        self.known = False  # unset gauges are omitted, not rendered as 0

    def set(self, v: float) -> None:
        self.value = float(v)
        self.known = True


class Histogram:
    """Fixed-bucket latency histogram on the reference view's bounds
    (``DEFAULT_LATENCY_BUCKETS_MS``) PLUS the exact nanosecond samples:
    buckets feed Prometheus/OTLP, the exact samples feed ``/snapshot``
    percentiles that match ``report timeline`` bit-for-bit.

    The exact list is bounded (``EXACT_SAMPLE_CAP``): past the cap it
    decimates deterministically — keep every other retained sample,
    double the keep stride — so a serve-shaped run can tick for days
    without the registry's RSS growing, while runs under the cap (every
    hermetic test) keep the full-fidelity bit-for-bit identity.

    Each bucket additionally keeps its LAST observed exemplar — the
    (value, trace_id) pair of the newest observation that landed there,
    when the observer supplied a trace id. Rendered only in the
    OpenMetrics exposition (``Accept: application/openmetrics-text``):
    the slow buckets' exemplars are exactly the trace ids ``tpubench
    report trace`` resolves — the scrape-side handle from "the p99
    bucket grew" to "THIS read's span tree"."""

    __slots__ = ("name", "help", "bounds", "counts", "count", "sum_ms",
                 "_ns", "_stride", "_phase", "exemplars")

    def __init__(self, name: str, help_: str,
                 bounds_ms: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help_
        self.bounds = list(bounds_ms or DEFAULT_LATENCY_BUCKETS_MS)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum_ms = 0.0
        self._ns: list[int] = []
        self._stride = 1
        self._phase = 0
        # bucket index -> (value_ms, trace_id); last-write-wins.
        self.exemplars: dict[int, tuple[float, str]] = {}

    def observe_ns(self, ns: int, trace_id: Optional[str] = None) -> None:
        ms = ns / 1e6
        idx = bisect_right(self.bounds, ms)
        self.counts[idx] += 1
        if trace_id:
            self.exemplars[idx] = (ms, trace_id)
        self.count += 1
        self.sum_ms += ms
        self._phase += 1
        if self._phase >= self._stride:
            self._phase = 0
            self._ns.append(int(ns))
            if len(self._ns) >= EXACT_SAMPLE_CAP:
                del self._ns[::2]
                self._stride *= 2

    def exact_summary(self) -> Optional[dict]:
        if not self._ns:
            return None
        s = summarize_ns(np.asarray(self._ns, dtype=np.int64))
        out = {"count": self.count, "p50_ms": s.p50_ms, "p99_ms": s.p99_ms}
        if self._stride > 1:
            # Percentiles come from a 1-in-stride systematic subsample.
            out["sample_stride"] = self._stride
        return out

    def to_dict(self) -> dict:
        return {
            "bounds_ms": self.bounds,
            "counts": list(self.counts),
            "count": self.count,
            "sum_ms": self.sum_ms,
        }


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class TelemetryRegistry:
    """Name → metric map with mandatory help text, Prometheus text
    exposition and a JSON snapshot. One lock guards every mutation and
    render — the feeder runs on worker threads, the ticker and HTTP
    handlers on their own."""

    def __init__(self):
        self.lock = threading.RLock()
        self._metrics: dict[str, object] = {}

    def _register(self, metric):
        if not metric.help:
            raise ValueError(
                f"metric {metric.name!r}: help text is mandatory "
                "(the drift guard pins registry <-> README)"
            )
        if metric.name in self._metrics:
            raise ValueError(f"metric {metric.name!r} already registered")
        self._metrics[metric.name] = metric
        return metric

    def counter(self, name: str, help_: str) -> Counter:
        return self._register(Counter(name, help_))

    def labeled_counter(self, name: str, help_: str,
                        label: str) -> LabeledCounter:
        return self._register(LabeledCounter(name, help_, label))

    def gauge(self, name: str, help_: str) -> Gauge:
        return self._register(Gauge(name, help_))

    def histogram(self, name: str, help_: str,
                  bounds_ms: Optional[Sequence[float]] = None) -> Histogram:
        return self._register(Histogram(name, help_, bounds_ms))

    def get(self, name: str):
        return self._metrics[name]

    def names(self) -> list[str]:
        return sorted(self._metrics)

    def helps(self) -> dict[str, str]:
        return {n: m.help for n, m in self._metrics.items()}

    # ---------------------------------------------------------- render ----
    def render_prometheus(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition (format 0.0.4 by default): HELP/
        TYPE pairs, cumulative histogram buckets with the ``+Inf``
        terminator. ``openmetrics=True`` renders the OpenMetrics shape
        instead — bucket lines carry their trace-id exemplars
        (``# {trace_id="..."} <value>``) and the body ends with
        ``# EOF`` — the exposition that links a slow histogram bucket
        to the exact trace ``report trace`` can resolve."""
        with self.lock:
            lines: list[str] = []
            for name in sorted(self._metrics):
                m = self._metrics[name]
                help_ = m.help.replace("\\", "\\\\").replace("\n", "\\n")
                # OpenMetrics 1.0 names a counter FAMILY without the
                # `_total` suffix (samples keep it); declaring the
                # family as `*_total counter` fails a stock Prometheus
                # OpenMetrics parse and takes the whole scrape down.
                # 0.0.4 keeps the historical suffixed declaration.
                family = name
                if (openmetrics
                        and isinstance(m, (Counter, LabeledCounter))
                        and name.endswith("_total")):
                    family = name[: -len("_total")]
                lines.append(f"# HELP {family} {help_}")
                if isinstance(m, Counter):
                    lines.append(f"# TYPE {family} counter")
                    lines.append(f"{name} {_fmt(m.value)}")
                elif isinstance(m, LabeledCounter):
                    lines.append(f"# TYPE {family} counter")
                    for lv in sorted(m.children):
                        lines.append(
                            f'{name}{{{m.label}="{lv}"}} '
                            f"{_fmt(m.children[lv])}"
                        )
                elif isinstance(m, Gauge):
                    lines.append(f"# TYPE {name} gauge")
                    if m.known:
                        lines.append(f"{name} {_fmt(m.value)}")
                elif isinstance(m, Histogram):
                    lines.append(f"# TYPE {name} histogram")

                    def _exemplar(idx: int, hist=m) -> str:
                        if not openmetrics or idx not in hist.exemplars:
                            return ""
                        ms, tid = hist.exemplars[idx]
                        return f' # {{trace_id="{tid}"}} {repr(float(ms))}'

                    cum = 0
                    for i, (bound, c) in enumerate(zip(m.bounds, m.counts)):
                        cum += c
                        lines.append(
                            f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}'
                            + _exemplar(i)
                        )
                    lines.append(
                        f'{name}_bucket{{le="+Inf"}} {m.count}'
                        + _exemplar(len(m.bounds))
                    )
                    lines.append(f"{name}_sum {repr(float(m.sum_ms))}")
                    lines.append(f"{name}_count {m.count}")
            if openmetrics:
                lines.append("# EOF")
            return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able registry state: flat counters/gauges, bucketed
        histograms, plus exact per-histogram p50/p99 (``phases``)."""
        with self.lock:
            counters: dict = {}
            gauges: dict = {}
            hists: dict = {}
            phases: dict = {}
            for name, m in self._metrics.items():
                if isinstance(m, Counter):
                    counters[name] = m.value
                elif isinstance(m, LabeledCounter):
                    counters[name] = {
                        "label": m.label, "children": dict(m.children),
                    }
                elif isinstance(m, Gauge):
                    if m.known:
                        gauges[name] = m.value
                elif isinstance(m, Histogram):
                    hists[name] = m.to_dict()
                    ex = m.exact_summary()
                    if ex is not None:
                        phases[name] = ex
            return {
                "time": time.time(),
                "counters": counters,
                "gauges": gauges,
                "histograms": hists,
                "exact": phases,
            }


# The registry's metric surface. Every name here must appear in the
# README "Live telemetry" metric table; tests/test_telemetry.py's drift
# guard asserts registry == table and PHASES ⊆ histograms.
PHASE_HIST_PREFIX = "tpubench_phase_"

COUNTER_METRICS = {
    "tpubench_records_total": "flight records appended (all kinds)",
    "tpubench_reads_total": "completed read-kind flight records",
    "tpubench_read_errors_total": "read-kind records that ended in error",
    "tpubench_bytes_total":
        "payload bytes delivered by read-kind records (fetch-owner credit)",
    "tpubench_steps_total": "train-ingest step records",
    "tpubench_step_bytes_total": "bytes consumed by train-ingest steps",
    "tpubench_steps_with_data_wait_total":
        "steps that waited on data at all (stall phases present)",
    "tpubench_retries_total": "retry annotations on reads",
    "tpubench_hedges_total": "hedged-read launches",
    "tpubench_hedge_wins_total": "hedge races the hedge won",
    "tpubench_stalls_total": "stall-watchdog events",
    "tpubench_breaker_events_total": "circuit-breaker transitions",
    "tpubench_tune_decisions_total": "autotuner decision windows",
    "tpubench_tune_accepts_total": "autotuner probes accepted",
    "tpubench_tune_reverts_total": "autotuner probes reverted",
    "tpubench_cache_hits_total": "chunk-cache hit records",
    "tpubench_cache_misses_total": "chunk-cache miss records",
    "tpubench_prefetch_issues_total": "readahead prefetch issues",
    "tpubench_peer_requests_total":
        "cooperative-cache misses routed to a peer owner",
    "tpubench_peer_hits_total":
        "peer requests served by the owner (origin fetches avoided)",
    "tpubench_peer_misses_total":
        "peer requests the owner shed (fell back to origin)",
    "tpubench_peer_bytes_total": "chunk bytes received over the peer channel",
    "tpubench_owner_fetches_total":
        "origin fetches made as the chunk's ring owner",
    "tpubench_coop_demotions_total":
        "straggler owners demoted off the ownership ring",
    "tpubench_coop_restores_total":
        "demoted owners restored to the ownership ring",
    "tpubench_membership_events_total":
        "elastic-membership transitions (join/leave/fail/pause/resume)",
    "tpubench_membership_handoff_chunks_total":
        "chunks drained to new owners by cooperative warm handoff",
    "tpubench_membership_handoff_bytes_total":
        "bytes drained to new owners by cooperative warm handoff",
    "tpubench_slab_overflows_total": "slab-pool overflow leases",
    "tpubench_stage_transfers_total": "host-to-HBM staging transfers",
    "tpubench_stage_bytes_total": "bytes staged to HBM",
    "tpubench_stage_overlapped_total":
        "staging transfers completed by the overlapped window",
    "tpubench_serve_requests_total":
        "open-loop serve requests resolved (completed or shed)",
    "tpubench_serve_shed_total":
        "serve requests shed by admission control "
        "(queue overload / deadline / drain)",
    "tpubench_serve_deadline_miss_total":
        "completed serve requests that missed their tenant deadline",
    "tpubench_upload_sessions_total":
        "resumable upload sessions completed (one per ckpt-save object)",
    "tpubench_upload_parts_total":
        "upload parts committed (content-range PUTs)",
    "tpubench_upload_resumed_parts_total":
        "upload parts resumed after a mid-part fault "
        "(committed offset re-probed, tail resent)",
    "tpubench_upload_bytes_total":
        "bytes finalized through resumable uploads",
    "tpubench_grpc_frames_total":
        "gRPC wire events on client calls "
        "(stream open / message sent / message received)",
    "tpubench_bidi_acks_total":
        "BidiWriteObject persisted-size acks received "
        "(one per lockstep flush)",
    "tpubench_meta_ops_total":
        "open-loop metadata ops completed (meta-storm list/stat/open)",
    "tpubench_meta_errors_total": "metadata ops that failed",
    "tpubench_journal_flushes_total": "in-run flight-journal stream flushes",
    "tpubench_journal_rotated_records_total":
        "oldest journal records dropped by size-bounded rotation",
    "tpubench_tap_errors_total":
        "flight-tap feed errors (swallowed, never on the hot path)",
    "tpubench_scrapes_total": "/metrics scrapes served",
}

LABELED_COUNTER_METRICS = {
    "tpubench_native_transport_total": (
        "native tb_stats_* transport counters, delta since session start",
        "counter",
    ),
}

# The native tb_stats_* counter catalog — the `counter=` label values of
# tpubench_native_transport_total, pinned here so the three surfaces that
# carry them (engine.py stats() keys, this catalog, the README native
# counter table) cannot drift apart silently (the drift guard in
# tests/test_telemetry.py walks all three). Adding a counter to
# engine.cc's tb_stats enum REQUIRES a row here and in the README.
NATIVE_TRANSPORT_COUNTERS = {
    "bytes_tx": "payload bytes handed to send/SSL_write",
    "bytes_rx": "payload bytes returned by recv/SSL_read",
    "recv_wait_ns": "wall time blocked inside recv/SSL_read",
    "connects": "TCP connects (tb_http_connect + reactor sockets)",
    "tls_handshakes": "completed TLS handshakes",
    "conn_closes": "connection handles closed",
    "h2_frames_rx": "h2 frames consumed by the poll loop",
    "h2_data_bytes_rx": "h2 DATA frame payload bytes (incl. padding)",
    "h2_window_updates_tx": "h2 flow-control credit frames sent",
    "h2_streams_opened": "h2 streams submitted (gRPC + raw GET)",
    "h2_rst_rx": "RST_STREAM frames received",
    "h2_goaway_rx": "GOAWAY frames received",
    "pool_wakes": "executor consumer wakes returning >=1 completion",
    "pool_completions": "executor completions across all wakes",
    "pool_batched_wakes": "wakes that drained >1 completion in one handoff",
    "reactor_loops": "reactor epoll_wait iterations",
    "reactor_epoll_events": "epoll events delivered to the reactor",
    "reactor_completions": "completions enqueued to reactor SPSC rings",
    "reactor_doorbell_wakes":
        "eventfd doorbells rung (coalesced: batch threshold or loop edge)",
    "reactor_ring_depth_sum":
        "ring depth observed at each enqueue, summed (mean = sum/completions)",
    "reactor_ring_depth_max": "max reactor ring depth observed",
    "reactor_tls_handshakes":
        "TLS handshakes completed by the reactor's nonblocking state machine",
    "reactor_tls_resumes":
        "reactor handshakes that resumed a cached per-target TLS session",
    "reactor_h2_streams": "h2 streams opened by the reactor's multiplexer",
    "reactor_flow_stall_ns":
        "time queued h2 flow-control credit waited for the socket to drain",
}

GAUGE_METRICS = {
    "tpubench_up": "1 while the telemetry session is live",
    "tpubench_run_seconds": "wall seconds since the session started",
    "tpubench_goodput_gbps":
        "delivered GB/s over the flight records' observed span "
        "(goodput_summary formula)",
    "tpubench_goodput_gbps_per_chip": "goodput divided by staged chip count",
    "tpubench_cache_hit_ratio": "cache hits / (hits + misses), record-derived",
    "tpubench_peer_hit_ratio":
        "peer hits / peer requests, record-derived (coop cache)",
    "tpubench_staging_efficiency":
        "fraction of transfer flight time hidden from the fetch threads",
    "tpubench_membership_epoch":
        "current elastic-membership view epoch (bumps on every "
        "join/leave/fail/pause/resume)",
    "tpubench_fleet_hosts":
        "simulated host count of the last virtual-time fleet run",
    "tpubench_fleet_virtual_seconds":
        "virtual seconds the last fleet simulation covered (its "
        "real wall cost is the run's wall_seconds)",
}

HISTOGRAM_METRICS = {
    "tpubench_read_latency_ms":
        "full-read latency sampled off the run's LatencyRecorders",
}


def phase_metric_name(phase: str) -> str:
    return f"{PHASE_HIST_PREFIX}{phase}_ms"


def metric_catalog() -> dict[str, str]:
    """Every registry metric name -> help, including the per-phase
    histograms — the single source the README table and the drift guard
    both walk."""
    cat = dict(COUNTER_METRICS)
    for name, (help_, _) in LABELED_COUNTER_METRICS.items():
        cat[name] = help_
    cat.update(GAUGE_METRICS)
    cat.update(HISTOGRAM_METRICS)
    for p in PHASES + ("total",):
        cat[phase_metric_name(p)] = (
            f"'{p}' phase segment latency (ms), attributed per flight "
            "record" if p != "total"
            else "whole-record latency (first to last phase stamp, ms)"
        )
    return cat


def build_registry() -> TelemetryRegistry:
    """The default tpubench registry: every catalog metric registered
    with its help text (drift guard: registry names == catalog names ==
    README table rows)."""
    reg = TelemetryRegistry()
    for name, help_ in COUNTER_METRICS.items():
        reg.counter(name, help_)
    for name, (help_, label) in LABELED_COUNTER_METRICS.items():
        reg.labeled_counter(name, help_, label)
    for name, help_ in GAUGE_METRICS.items():
        reg.gauge(name, help_)
    for name, help_ in HISTOGRAM_METRICS.items():
        reg.histogram(name, help_)
    for p in PHASES + ("total",):
        reg.histogram(phase_metric_name(p), metric_catalog()[
            phase_metric_name(p)
        ])
    return reg


# ---------------------------------------------------------------- feeder ----


class FlightFeeder:
    """Per-record registry feed, installed as the FlightRecorder's tap.

    Runs on the appending worker's thread under the registry lock;
    errors are counted and swallowed (the hot path must never pay for a
    telemetry bug). Keeps the goodput tally with the exact
    :func:`goodput_summary` byte-credit rules so live and post-hoc
    numbers agree."""

    def __init__(self, registry: TelemetryRegistry):
        self.reg = registry
        # Single-host span/byte tally (the registry lives in-process).
        self.t0_ns: Optional[int] = None
        self.t1_ns: Optional[int] = None
        self.read_bytes = 0
        self.step_bytes = 0
        self.steps = 0

    # One bound-method handle per hot counter (dict lookups once).
    def __call__(self, rec: dict) -> None:
        try:
            with self.reg.lock:
                self._feed(rec)
        except Exception:  # noqa: BLE001 — tap contract: never raise
            try:
                with self.reg.lock:
                    self.reg.get("tpubench_tap_errors_total").inc()
            except Exception:  # noqa: BLE001
                pass

    def _feed(self, rec: dict) -> None:
        reg = self.reg
        reg.get("tpubench_records_total").inc()
        phases = rec.get("phases", {})
        # Trace-id exemplar per observation: the record's trace id rides
        # into the bucket it lands in, so an OpenMetrics scrape can walk
        # from a fat p99 bucket straight to the trace tree.
        tid = rec.get("trace_id")
        for name, dur in phase_segments(rec).items():
            reg.get(phase_metric_name(name)).observe_ns(dur, trace_id=tid)
        t0, t1 = record_span_ns(rec)
        if t0 is not None:
            self.t0_ns = t0 if self.t0_ns is None else min(self.t0_ns, t0)
            self.t1_ns = t1 if self.t1_ns is None else max(self.t1_ns, t1)
        kind = rec.get("kind", "read")
        nbytes = rec.get("bytes", 0)
        if kind == "read":
            reg.get("tpubench_reads_total").inc()
            if rec.get("error"):
                reg.get("tpubench_read_errors_total").inc()
            else:
                reg.get("tpubench_bytes_total").inc(nbytes)
                self.read_bytes += nbytes
        elif kind == "step":
            reg.get("tpubench_steps_total").inc()
            reg.get("tpubench_step_bytes_total").inc(nbytes)
            self.steps += 1
            self.step_bytes += nbytes
            if "stall_end" in phases:
                reg.get("tpubench_steps_with_data_wait_total").inc()
        elif kind == "stage":
            reg.get("tpubench_stage_transfers_total").inc()
            reg.get("tpubench_stage_bytes_total").inc(nbytes)
        elif kind == "upload":
            if not rec.get("error"):
                # "Sessions COMPLETED" by its help text: an errored
                # upload record (e.g. a 412 after session open) must
                # not count.
                reg.get("tpubench_upload_sessions_total").inc()
                reg.get("tpubench_upload_bytes_total").inc(nbytes)
        elif kind == "meta":
            reg.get("tpubench_meta_ops_total").inc()
            if rec.get("error"):
                reg.get("tpubench_meta_errors_total").inc()
        if "cache_hit" in phases:
            reg.get("tpubench_cache_hits_total").inc()
        if "cache_miss" in phases:
            reg.get("tpubench_cache_misses_total").inc()
        if "prefetch_issue" in phases:
            reg.get("tpubench_prefetch_issues_total").inc()
        if "peer_request" in phases:
            reg.get("tpubench_peer_requests_total").inc()
        if "peer_hit" in phases:
            reg.get("tpubench_peer_hits_total").inc()
            if not rec.get("error"):
                reg.get("tpubench_peer_bytes_total").inc(nbytes)
        if "peer_miss" in phases:
            reg.get("tpubench_peer_misses_total").inc()
        if "owner_fetch" in phases:
            reg.get("tpubench_owner_fetches_total").inc()
        for n in rec.get("notes", ()):
            nk = n.get("kind")
            if nk == "retry":
                reg.get("tpubench_retries_total").inc()
                if n.get("reason") == "upload_resume":
                    reg.get("tpubench_upload_resumed_parts_total").inc()
            elif nk == "part":
                reg.get("tpubench_upload_parts_total").inc()
            elif nk == "grpc_frame":
                reg.get("tpubench_grpc_frames_total").inc()
            elif nk == "bidi_ack":
                reg.get("tpubench_bidi_acks_total").inc()
            elif nk == "hedge":
                if n.get("event") == "launch":
                    reg.get("tpubench_hedges_total").inc()
                elif n.get("event") == "win":
                    reg.get("tpubench_hedge_wins_total").inc()
            elif nk == "serve_req":
                reg.get("tpubench_serve_requests_total").inc()
                if (n.get("outcome") == "completed"
                        and n.get("deadline_met") is False):
                    reg.get("tpubench_serve_deadline_miss_total").inc()
            elif nk == "shed":
                reg.get("tpubench_serve_shed_total").inc()
            elif nk == "stall":
                reg.get("tpubench_stalls_total").inc()
            elif nk == "breaker":
                reg.get("tpubench_breaker_events_total").inc()
            elif nk == "tune":
                reg.get("tpubench_tune_decisions_total").inc()
                verdict = str(n.get("verdict", ""))
                if verdict == "accept":
                    reg.get("tpubench_tune_accepts_total").inc()
                elif verdict.startswith("revert"):
                    reg.get("tpubench_tune_reverts_total").inc()
            elif nk == "slab" and n.get("event") == "overflow":
                reg.get("tpubench_slab_overflows_total").inc()
            elif nk == "coop":
                if n.get("event") == "demote":
                    reg.get("tpubench_coop_demotions_total").inc()
                elif n.get("event") == "restore":
                    reg.get("tpubench_coop_restores_total").inc()
            elif nk == "member":
                action = n.get("action")
                if action == "handoff":
                    reg.get(
                        "tpubench_membership_handoff_chunks_total"
                    ).inc(n.get("handoff_chunks", 0))
                    reg.get(
                        "tpubench_membership_handoff_bytes_total"
                    ).inc(n.get("handoff_bytes", 0))
                else:
                    reg.get("tpubench_membership_events_total").inc()
                epoch = n.get("epoch")
                if epoch is not None:
                    reg.get("tpubench_membership_epoch").set(epoch)
            elif nk == "fleet":
                hosts = n.get("hosts")
                if hosts is not None:
                    reg.get("tpubench_fleet_hosts").set(hosts)
                virtual_s = n.get("virtual_s")
                if virtual_s is not None:
                    reg.get("tpubench_fleet_virtual_seconds").set(virtual_s)
            elif nk == "stage" and n.get("event") == "overlap":
                reg.get("tpubench_stage_overlapped_total").inc()

    def goodput(self) -> dict:
        """The live twin of ``goodput_summary`` over this host's tapped
        records: same byte credit (steps win over reads), same span."""
        nbytes = self.step_bytes if self.steps else self.read_bytes
        wall_s = (
            (self.t1_ns - self.t0_ns) / 1e9
            if self.t0_ns is not None and self.t1_ns > self.t0_ns else 0.0
        )
        gbps = (nbytes / 1e9) / wall_s if wall_s > 0 else 0.0
        return {"bytes": nbytes, "wall_s": wall_s, "gbps": gbps}


# ----------------------------------------------------------------- http -----


def _make_server(session: "TelemetrySession", port: int):
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — stdlib API
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/metrics":
                # Content negotiation: an OpenMetrics scraper (Accept:
                # application/openmetrics-text) gets bucket exemplars
                # linking slow buckets to trace ids; plain scrapers get
                # unchanged 0.0.4 text.
                om = "application/openmetrics-text" in (
                    self.headers.get("Accept") or ""
                )
                body = session.render_prometheus(
                    openmetrics=om
                ).encode("utf-8")
                ctype = (
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8" if om
                    else "text/plain; version=0.0.4; charset=utf-8"
                )
            elif path == "/snapshot":
                body = json.dumps(session.snapshot()).encode("utf-8")
                ctype = "application/json"
            elif path == "/":
                body = (
                    b"tpubench telemetry: /metrics (Prometheus), "
                    b"/snapshot (JSON)\n"
                )
                ctype = "text/plain; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # noqa: A003 — silence stdlib
            pass

    # Loopback only: the endpoint is a local scrape/debug surface, not a
    # service — never bound on external interfaces.
    return ThreadingHTTPServer(("127.0.0.1", port), Handler)


# --------------------------------------------------------------- session ----


class TelemetrySession:
    """One run's telemetry wiring: registry + feeder + tick thread +
    optional HTTP endpoint + optional OTLP export + optional in-run
    journal stream. Workloads attach their sources, ``start()``, and
    stamp ``close()``'s summary into ``extra["telemetry"]``."""

    def __init__(self, tcfg: TelemetryConfig, resource: Optional[dict] = None):
        self.cfg = tcfg
        self.resource = dict(resource or {})
        self.registry = build_registry()
        self.feeder = FlightFeeder(self.registry)
        self.scrapes = 0
        self.port: Optional[int] = None
        self._flight = None
        self._recorders: list = []
        self._rec_offsets: list[int] = []
        self._chips = 1
        self._journal: Optional[tuple] = None  # (flight, path, extra_fn, max)
        self._rotation_seen = 0
        self._server = None
        self._server_thread = None
        self._ticker: Optional[PeriodicExporter] = None
        self._otlp: Optional[OTLPMetricsExporter] = None
        self._otlp_periodic: Optional[PeriodicExporter] = None
        self._t0 = time.monotonic()
        self._native_base: Optional[dict] = None
        self._closed = False
        self._last_summary: dict = {}

    # ---------------------------------------------------------- attach ----
    def attach_flight(self, flight) -> None:
        """Tap the run's FlightRecorder: every appended record feeds the
        registry before ring overwrite can drop it."""
        self._flight = flight
        flight.set_tap(self.feeder)

    def attach_recorders(self, recorders: Sequence) -> None:
        """Latency recorders sampled incrementally each tick into
        ``tpubench_read_latency_ms`` (the RecorderSampler discipline:
        ``snapshot_tail_ns``, O(new) per tick)."""
        for rec in recorders:
            self._recorders.append(rec)
            self._rec_offsets.append(0)

    def set_chips(self, n: int) -> None:
        self._chips = max(1, int(n))

    def stream_journal(self, flight, path: str,
                       extra_fn: Optional[Callable[[], dict]] = None,
                       max_bytes: int = 0) -> None:
        """Flush the flight journal every tick so ``tpubench top`` (and
        any cross-host aggregator) can tail it mid-run; writes stay
        atomic, ``.gz`` and rotation ride the write_journal path."""
        self._journal = (flight, path, extra_fn, max_bytes)

    # ------------------------------------------------------------ tick ----
    def _sample_recorders(self) -> None:
        hist = self.registry.get("tpubench_read_latency_ms")
        for i, rec in enumerate(self._recorders):
            arr, self._rec_offsets[i] = rec.snapshot_tail_ns(
                self._rec_offsets[i]
            )
            for ns in arr.tolist():
                hist.observe_ns(ns)

    def _sample_native(self) -> None:
        try:
            from tpubench.native.engine import peek_engine

            eng = peek_engine()
        except Exception:  # noqa: BLE001 — engine truly optional
            return
        if eng is None:
            return
        stats = eng.stats()
        if self._native_base is None:
            self._native_base = dict(stats)
            return
        fam = self.registry.get("tpubench_native_transport_total")
        for k, v in stats.items():
            fam.set_cumulative(k, v - self._native_base.get(k, 0))

    def _update_gauges(self) -> None:
        reg = self.registry
        reg.get("tpubench_up").set(1.0)
        reg.get("tpubench_run_seconds").set(time.monotonic() - self._t0)
        gp = self.feeder.goodput()
        reg.get("tpubench_goodput_gbps").set(gp["gbps"])
        reg.get("tpubench_goodput_gbps_per_chip").set(
            gp["gbps"] / self._chips
        )
        hits = reg.get("tpubench_cache_hits_total").value
        misses = reg.get("tpubench_cache_misses_total").value
        if hits + misses > 0:
            reg.get("tpubench_cache_hit_ratio").set(hits / (hits + misses))
        preq = reg.get("tpubench_peer_requests_total").value
        if preq > 0:
            reg.get("tpubench_peer_hit_ratio").set(
                reg.get("tpubench_peer_hits_total").value / preq
            )

    def tick(self) -> None:
        with self.registry.lock:
            self._sample_recorders()
            self._sample_native()
            self._update_gauges()
        if self._journal is not None:
            flight, path, extra_fn, max_bytes = self._journal
            flight.write_journal(
                path, extra=extra_fn() if extra_fn else None,
                max_bytes=max_bytes,
            )
            with self.registry.lock:
                self.registry.get("tpubench_journal_flushes_total").inc()
                # Cumulative-delta, not last_rotation_dropped: each flush
                # re-drops the same oldest records (the ring still holds
                # them), so summing per-write drops would inflate the
                # counter every tick. The recorder's watermarked total
                # counts each record once.
                total = getattr(flight, "rotation_dropped_total", 0)
                if total > self._rotation_seen:
                    self.registry.get(
                        "tpubench_journal_rotated_records_total"
                    ).inc(total - self._rotation_seen)
                    self._rotation_seen = total

    # ------------------------------------------------------- endpoints ----
    def render_prometheus(self, openmetrics: bool = False) -> str:
        with self.registry.lock:
            self.scrapes += 1
            self.registry.get("tpubench_scrapes_total").inc()
        return self.registry.render_prometheus(openmetrics=openmetrics)

    def snapshot(self) -> dict:
        snap = self.registry.snapshot()
        snap["goodput"] = self.feeder.goodput()
        snap["goodput"]["gbps_per_chip"] = (
            snap["goodput"]["gbps"] / self._chips
        )
        snap["resource"] = self.resource
        return snap

    # ------------------------------------------------------- lifecycle ----
    def start(self) -> "TelemetrySession":
        with self.registry.lock:
            self._update_gauges()
        if self.cfg.port >= 0:
            self._server = _make_server(self, self.cfg.port)
            self.port = self._server.server_address[1]
            self._server_thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="telemetry-http",
            )
            self._server_thread.start()
            print(
                f"telemetry: http://127.0.0.1:{self.port}/metrics "
                f"(+ /snapshot)",
                file=sys.stderr,
            )
        self._ticker = PeriodicExporter(self.tick, self.cfg.interval_s)
        self._ticker.start()
        if self.cfg.otlp or self.cfg.otlp_endpoint:
            self._otlp = OTLPMetricsExporter(
                self.snapshot, endpoint=self.cfg.otlp_endpoint,
                resource=self.resource,
            )
            self._otlp_periodic = PeriodicExporter(
                self._otlp.export_once, self.cfg.otlp_interval_s
            )
            self._otlp_periodic.start()
        return self

    def finalize_extra(self, extra: dict) -> None:
        """Fold a finished run's ``extra`` blocks into the gauges the
        records alone can't derive (staging efficiency, chip count)."""
        staging = (extra or {}).get("staging") or {}
        eff = staging.get("staging_efficiency")
        with self.registry.lock:
            if eff is not None:
                self.registry.get("tpubench_staging_efficiency").set(eff)

    def close(self, final_extra: Optional[dict] = None) -> dict:
        """Final tick + final OTLP flush, server shutdown, and the
        ``extra["telemetry"]`` stamp (port, scrape/flush counts, final
        goodput + exact per-phase percentiles)."""
        if self._closed:
            return self._last_summary
        self._closed = True
        if final_extra:
            self.finalize_extra(final_extra)
        if self._flight is not None:
            self._flight.set_tap(None)
        if self._ticker is not None:
            self._ticker.close()  # guaranteed final tick
        if self._otlp_periodic is not None:
            self._otlp_periodic.close()  # guaranteed final flush
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        snap = self.snapshot()
        summary = {
            "port": self.port,
            "scrapes": self.scrapes,
            "ticks": self._ticker.flush_count if self._ticker else 0,
            "goodput": snap["goodput"],
            "phases": snap["exact"],
            "counters": {
                k: v for k, v in snap["counters"].items()
                if not isinstance(v, dict) and v
            },
            "gauges": snap["gauges"],
        }
        if self._otlp is not None:
            summary["otlp"] = self._otlp.summary(self._otlp_periodic)
            # Dry-run payload capture rides the stamp only when small —
            # tests read it; result files must not balloon.
            if not self._otlp.endpoint and len(self._otlp.exported) <= 4:
                summary["otlp"]["payloads_captured"] = self._otlp.exported
            # Trace twin: one final OTLP-shaped span export over the
            # run's flight records (the trace store), riding the same
            # dry-run/POST machinery — a run that exported metrics also
            # ships its span trees, never silently only half the signal.
            if self._flight is not None:
                from tpubench.obs.exporters import OTLPTraceExporter

                texp = OTLPTraceExporter(
                    self._flight.records, endpoint=self.cfg.otlp_endpoint,
                    resource=self.resource,
                )
                try:
                    texp.export_once()
                    summary["otlp"]["traces"] = texp.summary()
                except Exception as e:  # noqa: BLE001 — close() never raises
                    summary["otlp"]["traces"] = {
                        "error": f"{type(e).__name__}: {e}",
                    }
        self._last_summary = summary
        return summary


def telemetry_from_config(cfg) -> Optional[TelemetrySession]:
    """Session per ``cfg.telemetry`` (None when the plane is off). The
    resource labels carry the transport/process identity every export
    path stamps (the multi-host series-collision discipline from
    CloudMonitoringExporter)."""
    tc = getattr(cfg, "telemetry", None)
    if tc is None or not tc.active:
        return None
    from tpubench.obs.flight import transport_label

    return TelemetrySession(tc, resource={
        "transport": transport_label(cfg),
        "process": str(cfg.dist.process_id),
        "workload": "",
    })
