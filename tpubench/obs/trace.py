"""Causal trace plane: journals → cross-host span trees (PR 9).

The flight recorder stamps every record with ``trace_id``/``span_id``/
``parent_id`` (:mod:`tpubench.obs.flight`), and the propagation layer
(:mod:`tpubench.obs.tracing`) threads one :class:`TraceContext` through
tracer spans, workload steps, the tail stack's helper threads, the coop
peer channel and the staging reaper. This module is the MERGE side:

* :func:`assemble_traces` — merged journal records → span trees. Each
  record is a span node; its phase timeline is decomposed into
  SYNTHESIZED child spans (one per phase segment, ids derived with
  :func:`~tpubench.obs.tracing.derive_span_id` so both sides of a
  cross-host hop compute the same id — the owner host's ``serve``
  record parents under the requester's ``peer_request`` segment with no
  id exchange beyond the propagated context); retry/hedge annotations
  become annotation child spans (a retry's span covers its backoff
  pause, a hedge leg runs launch→verdict).
* :func:`tail_sample` — per-TRACE tail-based sampling: keep full trees
  only for the slowest ``slow_fraction`` plus an unbiased head sample
  (a deterministic hash of the trace id — the same trace keeps or drops
  on every host and every re-run), memory-bounded by ``max_keep`` (the
  telemetry ``EXACT_SAMPLE_CAP`` discipline: a serve-shaped run's
  report cannot grow without bound).
* :func:`critical_path` / :func:`blame_table` — per-trace dominant-child
  walk and the pod-wide "p99 blame" rollup: which span (phase segment or
  cross-host child) owned the wall time of the slowest-decile reads.
* :func:`render_trace_report` — the ``tpubench report trace`` body.
* :func:`otlp_trace_payload` — OTLP/HTTP-JSON ``resourceSpans`` shape
  over the records (dry-run capture / POST via the exporters machinery).

Clock honesty: phase timestamps are ``perf_counter`` nanoseconds —
host-relative. Tree STRUCTURE stitches across hosts by ids; DURATIONS
are compared (both are ns), but a child's position is never placed on
the parent host's absolute timeline.
"""

from __future__ import annotations

from typing import Iterable, Optional

from tpubench.obs.flight import PHASES, merge_journal_docs
from tpubench.obs.tracing import derive_span_id

# ------------------------------------------------------------- catalog ------

# Every span KIND the trace plane emits (flight-record kinds) → meaning.
# The span-drift guard (tests/test_trace_plane.py) pins three surfaces:
# this catalog, the PHASES tuple (every phase is a synthesized child-span
# name and must be documented here), and the README "Distributed
# tracing" section — a new kind or phase that skips any surface fails
# tier-1.
SPAN_KINDS = {
    "read": "one network read (demand or prefetch)",
    "step": "one train-ingest step (stall window bracketed)",
    "stage": "one host-to-HBM staging transfer (reaper-completed)",
    "object": "one pod-level fetch-stage-gather object span",
    "cache": "one chunk-cache access resolution (hit records)",
    "serve": "an origin fetch made to answer a peer's request "
             "(owner side of a cross-host coop hop)",
    "coop": "a cooperative-cache ring decision (demote/restore)",
    "member": "an elastic-membership transition (join/leave/fail/"
              "pause/resume — epoch-numbered pod view changes) or its "
              "warm-handoff byte accounting",
    "tune": "one autotuner decision window",
    "upload": "one resumable object upload (ckpt-save: session open "
              "to finalize; per-part detail rides its notes)",
    "meta": "one open-loop metadata operation (meta-storm "
            "list/stat/open)",
    "fleet": "one virtual-time fleet simulation (tpubench fleet: "
             "simulated topology + virtual-vs-real wall accounting "
             "rides its note)",
}

# Annotation kinds synthesized into child spans (notes with a duration
# story: a retry covers its backoff pause, a hedge leg runs from launch
# to its win/lose verdict).
NOTE_SPANS = {
    "retry": "one retry/resume attempt (span covers the backoff pause)",
    "hedge": "one hedged-read leg (launch to win/lose verdict)",
    "grpc_frame": "one gRPC wire event on a client call — stream open, "
                  "message sent, or message received (point span)",
    "bidi_ack": "one BidiWriteObject persisted-size ack — the server's "
                "committed state after a lockstep flush (point span)",
}

_PHASE_HELP = {
    "enqueue": "the read left the workload queue",
    "cache_hit": "chunk resolved from the local cache",
    "cache_miss": "chunk missed the local cache",
    "prefetch_issue": "readahead fetch left the prefetch queue",
    "peer_request": "miss routed to the chunk's peer owner",
    "peer_hit": "owner served the chunk (peer round-trip)",
    "peer_miss": "owner shed; the read fell through to origin",
    "owner_fetch": "origin read made as the chunk's ring owner",
    "upload_open": "resumable upload session opened",
    "connect": "connection establishment",
    "stream_open": "request stream opened",
    "first_byte": "time to first payload byte",
    "body_complete": "payload fully delivered",
    "meta_op": "metadata operation completed (service time incl. queue)",
    "part_sent": "first upload part committed",
    "upload_complete": "resumable upload finalized",
    "delta_commit": "delta save committed one CAS-guarded shard",
    "shard_restored": "joiner finished restoring one verified shard",
    "stall_begin": "train-ingest step began waiting for data",
    "stall_end": "train-ingest step's data wait ended",
    "stage_submit": "host-to-HBM transfer left the reaper",
    "stage_complete": "transfer bytes landed in HBM (flight time)",
    "hbm_staged": "bytes resident in HBM",
    "gather_complete": "pod gather collective finished",
}


def span_catalog() -> dict[str, str]:
    """name → help for every span the plane can emit: record kinds,
    synthesized phase-segment spans, and annotation spans. The single
    source the README section and the drift guard both walk."""
    cat = dict(SPAN_KINDS)
    for p in PHASES:
        cat[p] = _PHASE_HELP[p]
    cat.update(NOTE_SPANS)
    return cat


# ------------------------------------------------------------ assembly ------


class SpanNode:
    """One assembled span: a flight record, or a synthesized child
    (phase segment / annotation) of one."""

    __slots__ = ("span_id", "trace_id", "parent_id", "name", "kind",
                 "host", "worker", "start_ns", "end_ns", "bytes", "error",
                 "synth", "children", "record")

    def __init__(self, *, span_id, trace_id, parent_id, name, kind, host,
                 worker="", start_ns=0, end_ns=0, nbytes=0, error=None,
                 synth=False, record=None):
        self.span_id = span_id
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.host = host
        self.worker = worker
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.bytes = nbytes
        self.error = error
        self.synth = synth
        self.children: list[SpanNode] = []
        self.record = record

    @property
    def duration_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)

    def label(self) -> str:
        tag = self.name if self.synth else f"{self.kind} {self.name}"
        return f"{tag}"


class Trace:
    """One stitched trace: its root spans (usually one) and rollups."""

    __slots__ = ("trace_id", "roots", "orphans")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.roots: list[SpanNode] = []
        self.orphans: list[SpanNode] = []

    @property
    def duration_ns(self) -> int:
        return max((r.duration_ns for r in self.roots), default=0)

    def span_count(self) -> int:
        # orphans ⊆ roots (an orphan still tops its trace): walking
        # roots alone covers every span exactly once.
        n = 0
        stack = list(self.roots)
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children)
        return n


def _synth_children(node: SpanNode, rec: dict) -> list[SpanNode]:
    """Phase segments + annotation spans of one record, as child nodes
    with deterministic derived ids (the cross-host stitch points)."""
    out: list[SpanNode] = []
    ph = rec.get("phases", {})
    present = [(p, ph[p]) for p in PHASES if p in ph]
    for (p0, t0), (p1, t1) in zip(present, present[1:]):
        # Segment NAMED by its end phase (the "connect" segment is the
        # time it took to connect) but KEYED by its start phase: each
        # phase starts at most one segment, and the propagation side
        # only knows where a hop BEGINS — `_peer_hop_ctx` derives the
        # parent from "peer_request" without knowing whether the
        # round-trip will end at peer_hit or peer_miss, and the id
        # derived here from the same start phase is what the owner
        # host's serve span stitches under.
        out.append(SpanNode(
            span_id=derive_span_id(node.span_id, p0),
            trace_id=node.trace_id, parent_id=node.span_id,
            name=p1, kind=node.kind, host=node.host, worker=node.worker,
            start_ns=t0, end_ns=t1, synth=True,
        ))
    notes = rec.get("notes", ())
    hedge_open: Optional[SpanNode] = None
    idx = 0
    for n in notes:
        nk = n.get("kind")
        t = int(n.get("t", 0))
        if nk == "retry":
            end = t + int(float(n.get("backoff_s", 0.0)) * 1e9)
            out.append(SpanNode(
                span_id=derive_span_id(node.span_id, f"retry#{idx}"),
                trace_id=node.trace_id, parent_id=node.span_id,
                name="retry", kind=node.kind, host=node.host,
                start_ns=t, end_ns=end, synth=True,
            ))
            idx += 1
        elif nk in ("grpc_frame", "bidi_ack"):
            # Point spans: the wire event has no duration story of its
            # own — its value is WHERE it lands on the parent's timeline
            # (ack cadence exposes lockstep stalls in the trace view).
            out.append(SpanNode(
                span_id=derive_span_id(node.span_id, f"{nk}#{idx}"),
                trace_id=node.trace_id, parent_id=node.span_id,
                name=nk, kind=node.kind, host=node.host,
                start_ns=t, end_ns=t, synth=True,
            ))
            idx += 1
        elif nk == "hedge":
            ev = n.get("event")
            if ev == "launch":
                hedge_open = SpanNode(
                    span_id=derive_span_id(node.span_id, f"hedge#{idx}"),
                    trace_id=node.trace_id, parent_id=node.span_id,
                    name="hedge", kind=node.kind, host=node.host,
                    start_ns=t, end_ns=t, synth=True,
                )
                out.append(hedge_open)
                idx += 1
            elif ev in ("win", "lose") and hedge_open is not None:
                hedge_open.end_ns = t
                hedge_open = None
    return out


def _node_from_record(rec: dict, sid: str) -> SpanNode:
    """Record → SpanNode with its span window (min/max phase stamp) —
    the ONE construction both the report-trace assembly and the OTLP
    export use, so their notion of a record's span can never diverge."""
    node = SpanNode(
        span_id=sid, trace_id=rec.get("trace_id", ""),
        parent_id=rec.get("parent_id"), name=rec.get("object", "?"),
        kind=rec.get("kind", "read"), host=rec.get("host", 0),
        worker=rec.get("worker", ""), nbytes=rec.get("bytes", 0),
        error=rec.get("error"), record=rec,
    )
    ph = rec.get("phases", {})
    ts = [ph[p] for p in PHASES if p in ph]
    if ts:
        node.start_ns, node.end_ns = min(ts), max(ts)
    return node


def assemble_traces(records: Iterable[dict]) -> tuple[list[Trace], dict]:
    """Merged records → stitched traces + assembly stats
    (``cross_host_edges``: child spans attached under a parent recorded
    on a DIFFERENT host — the stitch the coop hop exists for;
    ``orphans``: spans whose parent id never appeared, kept as extra
    roots of their trace so nothing is silently dropped)."""
    nodes: list[SpanNode] = []
    index: dict[str, SpanNode] = {}
    for rec in records:
        sid = rec.get("span_id")
        if not sid:
            continue  # pre-trace-plane journal record: nothing to stitch
        node = _node_from_record(rec, sid)
        nodes.append(node)
        index[node.span_id] = node
        for child in _synth_children(node, rec):
            node.children.append(child)
            index[child.span_id] = child
    stats = {"spans": 0, "cross_host_edges": 0, "orphans": 0}
    traces: dict[str, Trace] = {}
    for node in nodes:
        tr = traces.setdefault(node.trace_id, Trace(node.trace_id))
        parent = index.get(node.parent_id) if node.parent_id else None
        if parent is not None:
            parent.children.append(node)
            if parent.host != node.host:
                stats["cross_host_edges"] += 1
        elif node.parent_id:
            # Parent outside the journal — most commonly a TRACER span
            # (read.py opens the op inside the workload span, and tracer
            # spans export through the SDK, not the journal). The record
            # is still its trace's tree top: counted as an orphan for
            # the header, but a ROOT for duration/blame rollups — or a
            # traced run's reads would vanish from the p99 story while
            # an untraced run's identical reads (parentless roots)
            # dominate it.
            stats["orphans"] += 1
            tr.orphans.append(node)
            tr.roots.append(node)
        else:
            tr.roots.append(node)
    out = sorted(traces.values(), key=lambda t: -t.duration_ns)
    stats["spans"] = sum(t.span_count() for t in out)
    stats["traces"] = len(out)
    return out, stats


# ------------------------------------------------------------- sampling -----


def head_sampled(trace_id: str, rate: float) -> bool:
    """Unbiased per-trace head-sample decision: a deterministic function
    of the trace id (no RNG — every host and every re-run agree)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0 or not trace_id:
        return False
    return (int(trace_id[:8] or "0", 16) / 0xFFFFFFFF) < rate


def tail_sample(traces: list[Trace], *, slow_fraction: float = 0.1,
                head_rate: float = 0.05, max_keep: int = 512,
                ) -> tuple[list[Trace], dict]:
    """Tail-based sampling over ASSEMBLED traces: full trees survive for
    the slowest ``slow_fraction`` (at least one) plus the unbiased head
    sample; everything is bounded by ``max_keep`` (slowest win). The
    decision is per-TRACE — a tree is kept or dropped whole, never a
    sampled child under a dropped parent."""
    if not traces:
        return [], {"kept": 0, "slow": 0, "head": 0, "total": 0}
    by_slow = sorted(traces, key=lambda t: -t.duration_ns)
    k = max(1, int(len(by_slow) * slow_fraction))
    slow = by_slow[:k]
    slow_ids = {t.trace_id for t in slow}
    head = [
        t for t in traces
        if t.trace_id not in slow_ids and head_sampled(t.trace_id, head_rate)
    ]
    kept = slow + head
    dropped = 0
    if len(kept) > max_keep:
        kept = sorted(kept, key=lambda t: -t.duration_ns)[:max_keep]
        dropped = len(slow) + len(head) - max_keep
    stats = {
        "total": len(traces), "kept": len(kept), "slow": len(slow),
        "head": len(head), "bound_dropped": dropped,
    }
    return kept, stats


# -------------------------------------------------------- critical path -----


def critical_path(root: SpanNode) -> list[SpanNode]:
    """Dominant-child walk: at every level, descend into the child span
    (synthesized segment or real child record — including one recorded
    on another host) covering the most wall time, while that child
    actually DOMINATES (covers at least half of the current span).
    Unexplained time belongs to the span itself: a 50 ms peer hop whose
    owner-side serve took 0.5 ms terminates at the hop segment, not at
    the serve — the wait was the hop, and blaming its fastest descendant
    would invert the story. The returned path (root excluded) is "what
    actually made this read slow"."""
    path: list[SpanNode] = []
    node = root
    seen = {id(root)}
    while True:
        kids = [c for c in node.children if c.duration_ns > 0
                and id(c) not in seen]
        if not kids:
            return path
        best = max(kids, key=lambda c: c.duration_ns)
        if node.duration_ns > 0 and best.duration_ns * 2 < node.duration_ns:
            return path
        path.append(best)
        seen.add(id(best))
        node = best


def blame_table(traces: list[Trace], *, slow_fraction: float = 0.1
                ) -> list[dict]:
    """The pod-wide "p99 blame" rollup: over the slowest-decile traces,
    group by the critical path's TERMINAL span (the leaf dominator) and
    report how often and how hard each one owned the tail. Rows sort by
    dominated wall time, so row 0 is the pod's p99 story."""
    pool = [t for t in traces if t.roots and t.duration_ns > 0]
    if not pool:
        return []
    pool.sort(key=lambda t: -t.duration_ns)
    k = max(1, int(len(pool) * slow_fraction))
    slow = pool[:k]
    groups: dict[str, dict] = {}
    for t in slow:
        root = max(t.roots, key=lambda r: r.duration_ns)
        path = critical_path(root)
        leaf = path[-1] if path else root
        key = leaf.name if leaf.synth else f"{leaf.kind}:{leaf.name}"
        g = groups.setdefault(key, {
            "span": key, "traces": 0, "dominated_ms": 0.0, "share_sum": 0.0,
        })
        g["traces"] += 1
        g["dominated_ms"] += leaf.duration_ns / 1e6
        g["share_sum"] += (
            leaf.duration_ns / root.duration_ns if root.duration_ns else 0.0
        )
    rows = []
    for g in groups.values():
        rows.append({
            "span": g["span"],
            "traces": g["traces"],
            "trace_share": g["traces"] / len(slow),
            "mean_ms": g["dominated_ms"] / g["traces"],
            "mean_share_of_root": g["share_sum"] / g["traces"],
        })
    rows.sort(key=lambda r: (-r["traces"] * r["mean_ms"], r["span"]))
    return rows


# ------------------------------------------------------------- rendering ----


def _render_node(node: SpanNode, lines: list[str], depth: int,
                 root_host: int) -> None:
    pad = "  " * depth
    dur = node.duration_ns / 1e6
    host = f"[host {node.host}] " if node.host != root_host else ""
    err = f"  ERROR {node.error}" if node.error else ""
    lines.append(f"{pad}{host}{node.label()}  {dur:.3f} ms{err}")
    for c in sorted(node.children, key=lambda c: c.start_ns):
        _render_node(c, lines, depth + 1, node.host)


def render_trace_report(docs: list[dict], *, slow_fraction: float = 0.1,
                        head_rate: float = 0.05, max_keep: int = 512,
                        show: int = 3) -> str:
    """The ``tpubench report trace`` body: merge per-host journals,
    assemble span trees, tail-sample, and print the p99 blame table plus
    the slowest ``show`` trees."""
    records = merge_journal_docs(docs)
    traces, astats = assemble_traces(records)
    kept, sstats = tail_sample(
        traces, slow_fraction=slow_fraction, head_rate=head_rate,
        max_keep=max_keep,
    )
    hosts = sorted({r.get("host", 0) for r in records})
    lines = [
        f"== trace report: {astats.get('traces', 0)} traces, "
        f"{astats['spans']} spans over {len(records)} records, "
        f"hosts={hosts} cross_host_edges={astats['cross_host_edges']} "
        f"orphans={astats['orphans']} ==",
    ]
    if not traces:
        lines.append("  (no traceable records — journal predates the "
                     "trace plane, or the flight recorder was off)")
        return "\n".join(lines)
    lines.append(
        f"sampling: kept {sstats['kept']}/{sstats['total']} trees "
        f"(slowest {slow_fraction:.0%} = {sstats['slow']}, head sample "
        f"@ {head_rate:.0%} = {sstats['head']}"
        + (f", bound dropped {sstats['bound_dropped']}"
           if sstats.get("bound_dropped") else "")
        + ")"
    )
    # Blame over the TRUE slowest decile of the whole run, not a decile
    # of the already tail-sampled set (slow_fraction twice over would
    # shrink the "p99 story" to ~1% of traces — or one trace on small
    # runs). tail_sample kept exactly this slow set whole, so selecting
    # it again from `traces` and pooling it all is the honest header.
    slow_k = max(1, int(len(traces) * slow_fraction))
    slow = sorted(traces, key=lambda t: -t.duration_ns)[:slow_k]
    rows = blame_table(slow, slow_fraction=1.0)
    if rows:
        lines.append("p99 blame (slowest decile, by critical-path leaf):")
        for r in rows:
            lines.append(
                f"  {r['span']:<24} traces={r['traces']:<4} "
                f"({r['trace_share']:.0%} of slow)  "
                f"mean {r['mean_ms']:9.3f} ms  "
                f"({r['mean_share_of_root']:.0%} of root)"
            )
    for t in kept[:show]:
        if not t.roots:
            continue
        lines.append(
            f"trace {t.trace_id[:16]}  total={t.duration_ns / 1e6:.3f} ms  "
            f"spans={t.span_count()}"
        )
        orphan_ids = {o.span_id for o in t.orphans}
        for root in t.roots:
            if root.span_id in orphan_ids:
                lines.append(
                    f"  (parent {root.parent_id} is outside the journal "
                    "— e.g. an exported tracer span)"
                )
            _render_node(root, lines, 1, root.host)
    return "\n".join(lines)


# ----------------------------------------------------------------- OTLP -----


def otlp_trace_payload(records: Iterable[dict],
                       resource: Optional[dict] = None) -> dict:
    """OTLP/HTTP-JSON ``ExportTraceServiceRequest`` shape over flight
    records (traceId/spanId/parentSpanId + name + start/end). The
    SYNTHESIZED segment/annotation spans ship too — a coop serve
    record's parent is a derived segment id, so without them the
    cross-host stitch would reference a span no backend ever receives.
    A record whose parent is a TRACER span resolves only when that
    tracer exports through the same backend (the OtelTracer path, which
    journals the SDK's exact ids); the in-process RecordingTracer's
    spans surface as missing-parent roots, which backends tolerate.
    Timestamps are the records' monotonic ``perf_counter`` nanoseconds,
    NOT unix epoch — honest for relative analysis, stamped as-is
    (documented; consumers aligning across hosts must use the id graph,
    not clocks)."""
    spans = []

    def emit(node: SpanNode, error=None) -> None:
        span = {
            "traceId": node.trace_id,
            "spanId": node.span_id,
            "name": node.name if node.synth
            else f"{node.kind}:{node.name}",
            "startTimeUnixNano": str(node.start_ns),
            "endTimeUnixNano": str(node.end_ns),
            "attributes": [
                {"key": "host",
                 "value": {"intValue": str(node.host)}},
                {"key": "worker",
                 "value": {"stringValue": str(node.worker)}},
            ],
        }
        if node.parent_id:
            span["parentSpanId"] = node.parent_id
        if error:
            span["status"] = {"code": 2, "message": str(error)}
        spans.append(span)

    for rec in records:
        sid = rec.get("span_id")
        if not sid:
            continue
        node = _node_from_record(rec, sid)
        emit(node, error=node.error)
        for child in _synth_children(node, rec):
            emit(child)
    return {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in (resource or {}).items()
            ]},
            "scopeSpans": [{
                "scope": {"name": "tpubench"},
                "spans": spans,
            }],
        }],
    }
