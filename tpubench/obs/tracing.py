"""Span-per-read tracing (reference ``trace_exporter.go`` + main.go:129-132).

The reference opens an OTel span per read with a bucket attribute and bridges
OpenCensus spans from inside the storage library. Here the workload code
talks to a tiny ``Tracer`` protocol; implementations:

* ``NoopTracer`` — default, zero overhead;
* ``RecordingTracer`` — in-process, for tests and local span dumps;
* OTel-backed tracer via :func:`make_tracer` when ``enable_tracing`` is set
  and ``opentelemetry`` is importable (sampling via ``trace_sample_rate``,
  trace_exporter.go:44).

Beyond the reference: spans get ``first_byte`` and ``stage`` (HBM-landing)
events — the north-star observability split (SURVEY §5.1).

This module is also the home of the CAUSAL trace plane's context layer
(PR 9): a thread-local :class:`TraceContext` (``trace_id``/``span_id``/
per-trace ``sampled`` bit) that the flight recorder, the tail stack's
helper threads, the coop peer channel and the staging reaper all thread
through, so every flight record lands with ``trace_id``/``span_id``/
``parent_id`` and journals become the trace store (assembled by
:mod:`tpubench.obs.trace` / ``tpubench report trace``). Sampling is
decided per-TRACE at the root — a child span always inherits its
parent's decision, so a sampled child can never orphan under an
unsampled parent.
"""

from __future__ import annotations

import contextlib
import hashlib
import random
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Iterator, Optional, Protocol

# ---------------------------------------------------------- trace context ---

_ctx_tls = threading.local()
_id_tls = threading.local()


def _id_rng() -> random.Random:
    """Per-thread id generator (no lock, no per-op urandom syscall on the
    hot read path); seeded from the process RNG pool once per thread."""
    rng = getattr(_id_tls, "rng", None)
    if rng is None:
        import os

        rng = _id_tls.rng = random.Random(
            int.from_bytes(os.urandom(16), "big") ^ threading.get_ident()
        )
    return rng


def seed_trace_ids(seed: int) -> None:
    """Deterministic ids for THIS thread (tests/replays only)."""
    _id_tls.rng = random.Random(seed)


def new_trace_id() -> str:
    return f"{_id_rng().getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_id_rng().getrandbits(64):016x}"


def derive_span_id(parent_span_id: str, name: str) -> str:
    """Deterministic child-span id for a SYNTHESIZED span (a phase
    segment of a flight record). Both sides of a cross-host hop can
    compute it independently — the requester propagates
    ``derive_span_id(read_span, "peer_request")`` and the merge pass
    re-derives the same id from the requester's record, which is what
    stitches the owner's spans under the right parent with no id
    exchange beyond the context itself."""
    return hashlib.blake2b(
        f"{parent_span_id}/{name}".encode(), digest_size=8
    ).hexdigest()


@dataclass(frozen=True)
class TraceContext:
    """One position in a trace tree: new spans created under this
    context join ``trace_id`` with ``span_id`` as their parent, and
    inherit the per-trace ``sampled`` decision."""

    trace_id: str
    span_id: str
    sampled: bool = True


def current_trace() -> Optional[TraceContext]:
    return getattr(_ctx_tls, "ctx", None)


def adopt_trace(ctx: Optional[TraceContext]) -> None:
    """Install ``ctx`` as THIS thread's trace position (None clears it)
    — the helper-thread half of the propagation discipline (hedge
    producers, the staging reaper, peer serves), mirroring
    ``flight.adopt_op``."""
    _ctx_tls.ctx = ctx


@contextlib.contextmanager
def trace_scope(ctx: Optional[TraceContext]) -> Iterator[None]:
    """Scoped adopt/restore: spans/records created inside parent under
    ``ctx`` (a None ctx scopes a no-op — callers need no branching)."""
    if ctx is None:
        yield
        return
    prev = current_trace()
    _ctx_tls.ctx = ctx
    try:
        yield
    finally:
        _ctx_tls.ctx = prev


class Span(Protocol):
    def event(self, name: str, **attrs) -> None: ...


class Tracer(Protocol):
    def span(self, name: str, **attrs) -> contextlib.AbstractContextManager[Span]: ...

    def shutdown(self) -> None:
        """Flush-on-exit (reference trace_exporter.go:55-60): callers wrap
        runs in try/finally shutdown() so batched spans are never lost."""


class _NoopSpan:
    __slots__ = ()

    def event(self, name: str, **attrs) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class NoopTracer:
    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        yield _NOOP_SPAN

    def shutdown(self) -> None:
        pass


@dataclass
class RecordedSpan:
    name: str
    attrs: dict
    start_ns: int
    end_ns: int = 0
    events: list = field(default_factory=list)
    trace_id: str = ""
    span_id: str = ""
    parent_id: str = ""

    def event(self, name: str, **attrs) -> None:
        self.events.append((name, time.perf_counter_ns(), attrs))


class RecordingTracer:
    """Thread-safe in-process tracer; sampling mirrors TraceIDRatioBased.

    Sampling is decided per-TRACE, at the root span: a span opened under
    an active :class:`TraceContext` inherits the root's decision instead
    of re-drawing. (The old per-span draw could sample a child whose
    parent was dropped — an orphan span no tool can ever stitch.) Every
    span installs its context for its scope, so child spans — and flight
    records begun inside it — parent under it."""

    def __init__(self, sample_rate: float = 1.0, seed: int = 0,
                 max_spans: int = 65536):
        self.sample_rate = sample_rate
        # Bounded (the EXACT_SAMPLE_CAP discipline, enforced by
        # `tpubench check`): an open-loop serve run is unbounded in
        # time, and journals — not this in-process buffer — are the
        # durable trace store. Keep-first + a drop counter: the run
        # report can say how much was cut.
        self.spans: list[RecordedSpan] = []
        self.max_spans = max(1, int(max_spans))
        self.dropped_spans = 0
        self._lock = threading.Lock()
        self._rng = random.Random(seed)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        parent = current_trace()
        if parent is not None:
            # Per-trace decision: inherit the root's draw verbatim.
            sampled = parent.sampled
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            with self._lock:
                sampled = self._rng.random() < self.sample_rate
            trace_id, parent_id = new_trace_id(), ""
        span_id = new_span_id()
        ctx = TraceContext(trace_id, span_id, sampled)
        if not sampled:
            # Unsampled root still scopes its (unsampled) context so the
            # whole tree shares one decision — children skip too.
            with trace_scope(ctx):
                yield _NOOP_SPAN
            return
        sp = RecordedSpan(
            name=name, attrs=attrs, start_ns=time.perf_counter_ns(),
            trace_id=trace_id, span_id=span_id, parent_id=parent_id,
        )
        try:
            with trace_scope(ctx):
                yield sp
        finally:
            sp.end_ns = time.perf_counter_ns()
            with self._lock:
                if len(self.spans) < self.max_spans:
                    self.spans.append(sp)
                else:
                    self.dropped_spans += 1

    def shutdown(self) -> None:
        # Same one-line-warning discipline as OtelTracer.shutdown: a
        # truncated span set must not LOOK complete.
        if self.dropped_spans:
            warnings.warn(
                f"RecordingTracer dropped {self.dropped_spans} spans "
                f"past the max_spans={self.max_spans} cap — the kept "
                "set is the run's FIRST spans, not all of them",
                stacklevel=2,
            )


class SpanCarrier:
    """A manually-entered span whose lifetime crosses a call boundary —
    the client-internal request spans end when their READER closes, not
    when ``open_read`` returns. Enter at construction; end exactly once via
    :meth:`close` (optionally with the exception that ended the request, so
    failed reads export as failed spans, not OK ones). Idempotent."""

    __slots__ = ("_cm", "span")

    def __init__(self, tracer: Tracer, name: str, **attrs):
        self._cm = tracer.span(name, **attrs)
        self.span = self._cm.__enter__()

    def event(self, name: str, **attrs) -> None:
        self.span.event(name, **attrs)

    def close(self, exc: Optional[BaseException] = None) -> None:
        if self._cm is None:
            return
        cm, self._cm = self._cm, None
        if exc is not None:
            cm.__exit__(type(exc), exc, exc.__traceback__)
        else:
            cm.__exit__(None, None, None)


class OtelTracer:
    """OTel SDK-backed tracer (gated; reference trace_exporter.go:18-61).

    ``span_processor`` (or the ``exporter`` name) attaches the export path —
    the reference ships spans to Cloud Trace; here "console" (stdout, for
    local inspection), "cloud_trace" (gated on the GCP exporter package), or
    a caller-supplied processor (tests use an in-memory one). Without one,
    spans are sampled/created but not exported.
    """

    def __init__(
        self,
        sample_rate: float,
        service_name: str,
        transport: str,
        span_processor=None,
        exporter: str = "",
    ):
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.sampling import TraceIdRatioBased

        resource = Resource.create(
            {"service.name": service_name, "transport": transport}
        )
        self._provider = TracerProvider(
            sampler=TraceIdRatioBased(sample_rate), resource=resource
        )
        if span_processor is None and exporter:
            span_processor = self._make_processor(exporter)
        if span_processor is not None:
            self._provider.add_span_processor(span_processor)
        self._tracer = self._provider.get_tracer("tpubench")

    @staticmethod
    def _make_processor(exporter: str):
        from opentelemetry.sdk.trace.export import (
            BatchSpanProcessor,
            ConsoleSpanExporter,
        )

        if exporter == "console":
            return BatchSpanProcessor(ConsoleSpanExporter())
        if exporter == "cloud_trace":
            # Reference: texporter.New → Cloud Trace (trace_exporter.go:19).
            from opentelemetry.exporter.cloud_trace import (  # gated
                CloudTraceSpanExporter,
            )

            return BatchSpanProcessor(CloudTraceSpanExporter())
        raise ValueError(f"unknown trace exporter {exporter!r}")

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        with self._tracer.start_as_current_span(name) as otel_span:
            for k, v in attrs.items():
                otel_span.set_attribute(k, v)

            class _Wrap:
                def event(self, ename: str, **eattrs) -> None:
                    otel_span.add_event(ename, eattrs)

            # Install this span's position as the thread's TraceContext
            # (the same contract RecordingTracer honors), so flight ops
            # begun inside join the SAME trace the SDK exports instead
            # of rooting their own: with the real SDK the journal
            # records carry the exported span's exact ids. A double/
            # older SDK without get_span_context falls back to local
            # ids — parenting among records stays consistent, and the
            # read workload's `trace_context` span event remains the
            # bidirectional handle.
            parent = current_trace()
            sc = getattr(otel_span, "get_span_context", lambda: None)()
            if sc is not None and getattr(sc, "trace_id", 0):
                trace_id = f"{sc.trace_id:032x}"
                span_id = f"{sc.span_id:016x}"
            else:
                trace_id = parent.trace_id if parent else new_trace_id()
                span_id = new_span_id()
            recording = getattr(otel_span, "is_recording", lambda: True)()
            sampled = bool(recording) and (
                parent.sampled if parent is not None else True
            )
            with trace_scope(TraceContext(trace_id, span_id, sampled)):
                yield _Wrap()

    def shutdown(self) -> None:
        # Flush-on-exit must never turn a finished run into a traceback:
        # an exporter raising inside the SDK's shutdown (endpoint gone,
        # batch processor already torn down — broken-SDK shapes) degrades
        # to a one-line warning. The run's RESULTS are already written by
        # the time any tracer flushes.
        try:
            self._provider.shutdown()
        except Exception as e:  # noqa: BLE001 — see above
            warnings.warn(
                f"trace exporter flush failed at shutdown "
                f"({type(e).__name__}: {e}); spans may be incomplete",
                stacklevel=2,
            )


@contextlib.contextmanager
def tracer_session(cfg) -> Iterator[Tracer]:
    """The ONE flush-on-exit discipline for every subcommand that runs a
    workload (reference trace_exporter.go:55-60): build the configured
    tracer, yield it, and shutdown() in the finally — so batched spans
    (console/cloud_trace exporters) survive chaos/tune/read alike, and a
    flush error degrades per OtelTracer.shutdown's one-line-warning
    contract instead of masking the run's real outcome."""
    tracer = make_tracer(cfg)
    try:
        yield tracer
    finally:
        tracer.shutdown()


def make_tracer(cfg) -> Tracer:
    """From an ObservabilityConfig (+TransportConfig context)."""
    if not cfg.obs.enable_tracing:
        return NoopTracer()
    requested_exporter = getattr(cfg.obs, "trace_exporter", "")
    try:
        import opentelemetry.sdk.trace  # noqa: F401 — availability probe
    except ImportError:
        if requested_exporter:
            # The user explicitly asked for an export path; dropping it
            # silently would hide that no spans ever leave the process.
            raise RuntimeError(
                f"trace_exporter={requested_exporter!r} requires the "
                "opentelemetry-sdk package, which is not installed"
            ) from None
        # OTel SDK missing, no exporter requested: degrade to in-process
        # recording rather than failing the benchmark run.
        return RecordingTracer(sample_rate=cfg.obs.trace_sample_rate)
    # SDK present: an explicitly requested exporter that cannot be built
    # (unknown name, cloud-trace package absent) is a CONFIG error and must
    # surface, not silently degrade.
    try:
        return OtelTracer(
            sample_rate=cfg.obs.trace_sample_rate,
            service_name="tpubench",
            transport=cfg.transport.protocol,
            exporter=requested_exporter,
        )
    except (ImportError, AttributeError, TypeError) as e:
        # Import/ABI shape failures = SDK version skew (TypeError covers
        # constructor-signature drift across SDK versions). Config-shaped
        # errors (e.g. an out-of-range sample rate raising ValueError) are
        # NOT caught — a bad config must surface, not silently downgrade.
        if requested_exporter:
            raise
        # Skew with no exporter asked for: degrade to in-process recording
        # rather than failing the run — but VISIBLY.
        import warnings

        warnings.warn(
            f"OTel tracer construction failed ({type(e).__name__}: {e}); "
            "degrading to in-process RecordingTracer",
            stacklevel=2,
        )
        return RecordingTracer(sample_rate=cfg.obs.trace_sample_rate)
