"""Span-per-read tracing (reference ``trace_exporter.go`` + main.go:129-132).

The reference opens an OTel span per read with a bucket attribute and bridges
OpenCensus spans from inside the storage library. Here the workload code
talks to a tiny ``Tracer`` protocol; implementations:

* ``NoopTracer`` — default, zero overhead;
* ``RecordingTracer`` — in-process, for tests and local span dumps;
* OTel-backed tracer via :func:`make_tracer` when ``enable_tracing`` is set
  and ``opentelemetry`` is importable (sampling via ``trace_sample_rate``,
  trace_exporter.go:44).

Beyond the reference: spans get ``first_byte`` and ``stage`` (HBM-landing)
events — the north-star observability split (SURVEY §5.1).
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional, Protocol


class Span(Protocol):
    def event(self, name: str, **attrs) -> None: ...


class Tracer(Protocol):
    def span(self, name: str, **attrs) -> contextlib.AbstractContextManager[Span]: ...

    def shutdown(self) -> None:
        """Flush-on-exit (reference trace_exporter.go:55-60): callers wrap
        runs in try/finally shutdown() so batched spans are never lost."""


class _NoopSpan:
    __slots__ = ()

    def event(self, name: str, **attrs) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class NoopTracer:
    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        yield _NOOP_SPAN

    def shutdown(self) -> None:
        pass


@dataclass
class RecordedSpan:
    name: str
    attrs: dict
    start_ns: int
    end_ns: int = 0
    events: list = field(default_factory=list)

    def event(self, name: str, **attrs) -> None:
        self.events.append((name, time.perf_counter_ns(), attrs))


class RecordingTracer:
    """Thread-safe in-process tracer; sampling mirrors TraceIDRatioBased."""

    def __init__(self, sample_rate: float = 1.0, seed: int = 0):
        self.sample_rate = sample_rate
        self.spans: list[RecordedSpan] = []
        self._lock = threading.Lock()
        self._rng = random.Random(seed)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        with self._lock:
            sampled = self._rng.random() < self.sample_rate
        if not sampled:
            yield _NOOP_SPAN
            return
        sp = RecordedSpan(name=name, attrs=attrs, start_ns=time.perf_counter_ns())
        try:
            yield sp
        finally:
            sp.end_ns = time.perf_counter_ns()
            with self._lock:
                self.spans.append(sp)

    def shutdown(self) -> None:
        pass


class SpanCarrier:
    """A manually-entered span whose lifetime crosses a call boundary —
    the client-internal request spans end when their READER closes, not
    when ``open_read`` returns. Enter at construction; end exactly once via
    :meth:`close` (optionally with the exception that ended the request, so
    failed reads export as failed spans, not OK ones). Idempotent."""

    __slots__ = ("_cm", "span")

    def __init__(self, tracer: Tracer, name: str, **attrs):
        self._cm = tracer.span(name, **attrs)
        self.span = self._cm.__enter__()

    def event(self, name: str, **attrs) -> None:
        self.span.event(name, **attrs)

    def close(self, exc: Optional[BaseException] = None) -> None:
        if self._cm is None:
            return
        cm, self._cm = self._cm, None
        if exc is not None:
            cm.__exit__(type(exc), exc, exc.__traceback__)
        else:
            cm.__exit__(None, None, None)


class OtelTracer:
    """OTel SDK-backed tracer (gated; reference trace_exporter.go:18-61).

    ``span_processor`` (or the ``exporter`` name) attaches the export path —
    the reference ships spans to Cloud Trace; here "console" (stdout, for
    local inspection), "cloud_trace" (gated on the GCP exporter package), or
    a caller-supplied processor (tests use an in-memory one). Without one,
    spans are sampled/created but not exported.
    """

    def __init__(
        self,
        sample_rate: float,
        service_name: str,
        transport: str,
        span_processor=None,
        exporter: str = "",
    ):
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.sampling import TraceIdRatioBased

        resource = Resource.create(
            {"service.name": service_name, "transport": transport}
        )
        self._provider = TracerProvider(
            sampler=TraceIdRatioBased(sample_rate), resource=resource
        )
        if span_processor is None and exporter:
            span_processor = self._make_processor(exporter)
        if span_processor is not None:
            self._provider.add_span_processor(span_processor)
        self._tracer = self._provider.get_tracer("tpubench")

    @staticmethod
    def _make_processor(exporter: str):
        from opentelemetry.sdk.trace.export import (
            BatchSpanProcessor,
            ConsoleSpanExporter,
        )

        if exporter == "console":
            return BatchSpanProcessor(ConsoleSpanExporter())
        if exporter == "cloud_trace":
            # Reference: texporter.New → Cloud Trace (trace_exporter.go:19).
            from opentelemetry.exporter.cloud_trace import (  # gated
                CloudTraceSpanExporter,
            )

            return BatchSpanProcessor(CloudTraceSpanExporter())
        raise ValueError(f"unknown trace exporter {exporter!r}")

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        with self._tracer.start_as_current_span(name) as otel_span:
            for k, v in attrs.items():
                otel_span.set_attribute(k, v)

            class _Wrap:
                def event(self, ename: str, **eattrs) -> None:
                    otel_span.add_event(ename, eattrs)

            yield _Wrap()

    def shutdown(self) -> None:
        self._provider.shutdown()


def make_tracer(cfg) -> Tracer:
    """From an ObservabilityConfig (+TransportConfig context)."""
    if not cfg.obs.enable_tracing:
        return NoopTracer()
    requested_exporter = getattr(cfg.obs, "trace_exporter", "")
    try:
        import opentelemetry.sdk.trace  # noqa: F401 — availability probe
    except ImportError:
        if requested_exporter:
            # The user explicitly asked for an export path; dropping it
            # silently would hide that no spans ever leave the process.
            raise RuntimeError(
                f"trace_exporter={requested_exporter!r} requires the "
                "opentelemetry-sdk package, which is not installed"
            ) from None
        # OTel SDK missing, no exporter requested: degrade to in-process
        # recording rather than failing the benchmark run.
        return RecordingTracer(sample_rate=cfg.obs.trace_sample_rate)
    # SDK present: an explicitly requested exporter that cannot be built
    # (unknown name, cloud-trace package absent) is a CONFIG error and must
    # surface, not silently degrade.
    try:
        return OtelTracer(
            sample_rate=cfg.obs.trace_sample_rate,
            service_name="tpubench",
            transport=cfg.transport.protocol,
            exporter=requested_exporter,
        )
    except (ImportError, AttributeError, TypeError) as e:
        # Import/ABI shape failures = SDK version skew (TypeError covers
        # constructor-signature drift across SDK versions). Config-shaped
        # errors (e.g. an out-of-range sample rate raising ValueError) are
        # NOT caught — a bad config must surface, not silently downgrade.
        if requested_exporter:
            raise
        # Skew with no exporter asked for: degrade to in-process recording
        # rather than failing the run — but VISIBLY.
        import warnings

        warnings.warn(
            f"OTel tracer construction failed ({type(e).__name__}: {e}); "
            "degrading to in-process RecordingTracer",
            stacklevel=2,
        )
        return RecordingTracer(sample_rate=cfg.obs.trace_sample_rate)
