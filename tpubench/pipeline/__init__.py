"""Ingest pipeline subsystem (the fetch∥consume overlap the reference
lacks): a bounded host-RAM chunk cache (:mod:`cache`), a plan-walking
readahead prefetcher (:mod:`prefetch`), and the step-paced
``train-ingest`` workload (:mod:`tpubench.workloads.train_ingest`) that
measures how well they hide storage latency behind compute —
per-step data-stall time, cache hit ratio, prefetch efficiency.
"""

from tpubench.pipeline.cache import ChunkCache, ChunkKey  # noqa: F401
from tpubench.pipeline.prefetch import Prefetcher  # noqa: F401
