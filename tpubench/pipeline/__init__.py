"""Ingest pipeline subsystem (the fetch∥consume overlap the reference
lacks): a bounded host-RAM chunk cache (:mod:`cache`), a plan-walking
readahead prefetcher (:mod:`prefetch`), and the step-paced
``train-ingest`` workload (:mod:`tpubench.workloads.train_ingest`) that
measures how well they hide storage latency behind compute —
per-step data-stall time, cache hit ratio, prefetch efficiency.

Chunk payloads ride the zero-copy slab datapath (:mod:`tpubench.mem`):
leased pinned slabs filled once off the wire, cached and staged as
views — ``copies_per_byte == 1.0``, regression-pinned.
"""

from tpubench.pipeline.cache import ChunkCache, ChunkKey  # noqa: F401
from tpubench.pipeline.prefetch import (  # noqa: F401
    Prefetcher,
    fetch_chunk,
    read_chunk,
)
