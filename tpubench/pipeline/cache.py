"""Bounded host-RAM chunk cache with single-flight miss dedup.

The pipeline's working set is chunks — ``(bucket, object, generation,
range)``-keyed byte slices of storage objects. The cache is byte-budgeted
(not entry-counted: a 100 MB chunk and a 256 KB chunk are not the same
cost) with LRU eviction, and **single-flight**: N concurrent misses for
one chunk issue ONE backend read, the rest wait on it (the coalesce
counter records how many reads the dedup saved — the thundering-herd
shape a prefetcher racing demand reads produces constantly).

Generation is part of the key, so an overwritten object can never serve
stale bytes; entries of superseded generations are dropped eagerly the
moment a newer generation is seen (counted, so invalidation is
observable in the ``extra["pipeline"]["cache"]`` stamp).

Prefetch-efficiency accounting lives here because only the cache sees
both sides: entries carry their origin (``prefetch`` vs ``demand``) and
a used bit; a prefetched entry's bytes count as *used* on its first hit
and as *wasted* when it is evicted — or still sitting unused at the end
of the run — without ever being consumed.

**Payloads** are either immutable ``bytes`` (the legacy / A-B baseline
arm) or refcounted :class:`~tpubench.mem.slab.SlabLease`\\ s (the
zero-copy arm). The cache stores the payload object as-is — never a
copy — and manages lease references: it takes one reference when an
entry lands, drops it on eviction (retiring the slab once no consumer
still reads it), and hands every *consumer* access its OWN reference,
which the consumer releases when done. Non-consumer accesses (the
prefetcher probing its own work) get the payload without a reference
and must not release.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, NamedTuple, Optional

from tpubench.mem.slab import SlabLease


def _freeze(data):
    """Storable payload: ``bytes`` and slab leases pass through untouched;
    only mutable buffers (bytearray/memoryview) are copied — and at most
    ONCE (the PR-3 path copied every miss twice: ``bytes(fetch())`` then
    ``bytes(data)`` again inside insert)."""
    if isinstance(data, (bytes, SlabLease)):
        return data
    return bytes(data)


class ChunkKey(NamedTuple):
    bucket: str
    object: str
    generation: int
    start: int
    length: int


class _Entry:
    __slots__ = ("data", "origin", "used", "owner", "pins")

    def __init__(self, data: bytes, origin: str, owner: Optional[str] = None):
        self.data = data
        self.origin = origin
        self.used = False
        # QoS tagging (the serve plane): which tenant class these bytes
        # belong to — the weighted-eviction victim-selection key.
        self.owner = owner
        # Single-flight waiter pins: consumers registered on the fetch
        # that produced this entry but not yet woken. A pinned entry is
        # never an eviction victim — evicting bytes a waiter is about
        # to consume would turn the single-flight save into an instant
        # re-fetch (and, on the weighted path, let one class's budget
        # pressure break another class's in-flight coalesce).
        self.pins = 0


class _Flight:
    """One in-flight fetch; losers of the single-flight race wait on it."""

    __slots__ = ("event", "data", "error", "consumer_waiters")

    def __init__(self):
        self.event = threading.Event()
        self.data = None  # bytes | SlabLease once the fetch lands
        self.error: Optional[BaseException] = None
        # Consumers blocked on this fetch (lock-guarded): the owner
        # marks the landed entry used at INSERT time when any exist, so
        # an eviction racing the waiter's wakeup can never count bytes
        # that were consumed as prefetch waste.
        self.consumer_waiters = 0


class ChunkCache:
    """Thread-safe byte-budgeted LRU chunk cache (see module docstring).

    ``capacity_bytes <= 0`` disables storage entirely — every access is a
    recorded miss that fetches through (the cold baseline arm of the
    pipeline A/B), and single-flight dedup still applies.
    """

    def __init__(self, capacity_bytes: int, debug: bool = False,
                 owner_budgets: Optional[dict] = None):
        self.capacity = max(0, int(capacity_bytes))
        # Weighted per-owner (tenant-class) byte budgets — the serve
        # plane's QoS hook. None/empty = classic single-tenant LRU.
        # With budgets set, an insert first evicts the INSERTING
        # owner's own least-recent unpinned entries while it is over
        # its budget (a class pays for its own overrun), and capacity
        # eviction prefers victims from the most-over-budget owner
        # before falling back to global LRU. Budgets are soft caps:
        # when an over-budget owner has only pinned entries the insert
        # still lands (correctness over strictness) and the overrun is
        # counted.
        self.owner_budgets = dict(owner_budgets or {})
        self.owner_bytes: dict[str, int] = {}
        # debug=True re-derives the byte-accounting invariants after
        # every mutation (O(entries) each — test harnesses only). The
        # live-reclamp path (Prefetcher.reclamp) leans on exactly these:
        # a depth/budget shrink mid-flight must never strand in-flight
        # chunk bytes in the resident-unused counter.
        self._debug = debug
        self.bytes = 0
        self._lock = threading.Lock()
        self._entries: "OrderedDict[ChunkKey, _Entry]" = OrderedDict()
        self._inflight: dict[ChunkKey, _Flight] = {}
        self._obj_gen: dict[tuple[str, str], int] = {}
        # Counters (the extra["pipeline"]["cache"] stamp).
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.coalesced = 0  # misses served by an already-in-flight fetch
        self.inserted_bytes = 0
        self.evicted_bytes = 0
        self.oversize_skips = 0  # chunks larger than the whole budget
        self.generation_invalidations = 0
        self.stale_rejects = 0  # superseded-generation inserts refused
        self.prefetch_inserted_bytes = 0
        self.prefetch_used_bytes = 0
        # Two flavors of prefetch waste, kept separate on purpose: the
        # prefetcher's byte-budget estimate relies on the identity
        # resident_unused = inserted - used - wasted, so `wasted` may
        # only count bytes that WERE resident (evictions). Bytes that
        # never entered the cache (oversize skip, stale-generation
        # reject) go in `dropped` — folding them into `wasted` would
        # deflate the identity and let prefetch exceed its budget.
        self.prefetch_wasted_bytes = 0  # evicted before any use
        self.prefetch_dropped_bytes = 0  # never cached at all
        self.prefetch_invalidated_bytes = 0  # dropped by a newer generation
        self.owner_evictions = 0  # evictions charged to an owner budget
        self.owner_budget_overruns = 0  # soft-cap overruns (pins held)
        self.pinned_capacity_overruns = 0  # capacity exceeded, all pinned
        # Directly-maintained count of resident prefetched-but-unused
        # bytes: the prefetcher's byte-budget source of truth (O(1),
        # no derived identity to keep consistent across drop reasons).
        self.prefetch_resident_unused = 0

    # ------------------------------------------------------------ internal --
    def _assert_invariants_locked(self) -> None:
        """Debug-mode accounting invariants (the resize-safety guard):
        the directly-maintained resident-unused counter must equal the
        sum over resident prefetched-but-unused entries, and the byte
        total must match what is actually resident — whatever sequence
        of inserts/evictions/invalidations/live-reclamps ran."""
        resident = sum(len(e.data) for e in self._entries.values())
        assert self.bytes == resident, (
            f"cache bytes drift: counter={self.bytes} resident={resident}"
        )
        unused = sum(
            len(e.data) for e in self._entries.values()
            if e.origin == "prefetch" and not e.used
        )
        assert self.prefetch_resident_unused == unused, (
            f"prefetch_resident_unused drift: "
            f"counter={self.prefetch_resident_unused} actual={unused}"
        )
        assert 0 <= self.prefetch_resident_unused <= (
            self.prefetch_inserted_bytes
        )
        by_owner: dict = {}
        for e in self._entries.values():
            if e.owner is not None:
                by_owner[e.owner] = by_owner.get(e.owner, 0) + len(e.data)
        assert {k: v for k, v in self.owner_bytes.items() if v} == by_owner, (
            f"owner_bytes drift: counter={self.owner_bytes} actual={by_owner}"
        )

    def _note_generation_locked(self, key: ChunkKey) -> None:
        """Eager invalidation: the first sighting of a newer generation
        drops every entry of the object's older generations."""
        ok = (key.bucket, key.object)
        g = self._obj_gen.get(ok)
        if g is None or key.generation > g:
            if g is not None:
                stale = [
                    k for k in self._entries
                    if (k.bucket, k.object) == ok and k.generation < key.generation
                ]
                for k in stale:
                    self._drop_locked(k, reason="invalidate")
                    self.generation_invalidations += 1
            self._obj_gen[ok] = key.generation

    def _mark_used_locked(self, e: _Entry) -> None:
        if e.origin == "prefetch" and not e.used:
            self.prefetch_used_bytes += len(e.data)
            self.prefetch_resident_unused -= len(e.data)
        e.used = True
        if self._debug:
            self._assert_invariants_locked()

    def _drop_locked(self, key: ChunkKey, reason: str = "evict") -> None:
        e = self._entries.pop(key)
        self.bytes -= len(e.data)
        self.evicted_bytes += len(e.data)
        if e.owner is not None:
            left = self.owner_bytes.get(e.owner, 0) - len(e.data)
            if left > 0:
                self.owner_bytes[e.owner] = left
            else:
                self.owner_bytes.pop(e.owner, None)
        if e.origin == "prefetch" and not e.used:
            self.prefetch_resident_unused -= len(e.data)
            if reason == "invalidate":
                # Kept out of `wasted`: the prefetcher's cancel-on-
                # eviction thrash guard watches wasted bytes, and a
                # generation invalidation is data churn, not a sign the
                # readahead window outran the cache budget.
                self.prefetch_invalidated_bytes += len(e.data)
            else:
                self.prefetch_wasted_bytes += len(e.data)
        if isinstance(e.data, SlabLease):
            # Drop the CACHE's reference only: a consumer still reading
            # the slab holds its own, so the memory outlives the entry.
            e.data.release()
        if self._debug:
            self._assert_invariants_locked()

    def _victim_locked(self, prefer_owner: Optional[str]) -> Optional[ChunkKey]:
        """Next eviction victim: least-recent UNPINNED entry, preferring
        ``prefer_owner``'s entries when given, else (with budgets set)
        the most-over-budget owner's, else global LRU. None when every
        entry is pinned (single-flight waiters hold them all — the
        caller overruns rather than break an in-flight coalesce)."""
        if prefer_owner is None and self.owner_budgets:
            worst, worst_ratio = None, 1.0
            for owner, b in self.owner_bytes.items():
                budget = self.owner_budgets.get(owner)
                if budget and b > budget and b / budget > worst_ratio:
                    worst, worst_ratio = owner, b / budget
            prefer_owner = worst
        fallback = None
        for k, e in self._entries.items():  # OrderedDict: LRU first
            if e.pins:
                continue
            if prefer_owner is not None and e.owner == prefer_owner:
                return k
            if fallback is None:
                fallback = k
        return fallback

    def _evict_to_fit_locked(self, n: int, owner: Optional[str]) -> None:
        """Make room for an ``n``-byte insert by ``owner``: first charge
        the inserting owner's own budget (its unpinned LRU entries go
        while it is over), then global capacity with over-budget-owner
        preference. Stops (soft overrun, counted) when only pinned
        entries remain."""
        budget = self.owner_budgets.get(owner) if owner is not None else None
        while budget and self.owner_bytes.get(owner, 0) + n > budget:
            victim = None
            for k, e in self._entries.items():
                if e.owner == owner and not e.pins:
                    victim = k
                    break
            if victim is None:
                if self.owner_bytes.get(owner, 0) + n > budget:
                    self.owner_budget_overruns += 1
                break
            self._drop_locked(victim)
            self.evictions += 1
            self.owner_evictions += 1
        while self.bytes + n > self.capacity:
            victim = self._victim_locked(None)
            if victim is None:
                # Every resident entry is pinned by single-flight
                # waiters: capacity soft-overruns. Counted separately
                # from owner_budget_overruns — this fires on a classic
                # (budget-less) cache too and must not read as phantom
                # QoS budget pressure.
                self.pinned_capacity_overruns += 1
                break
            self._drop_locked(victim)
            self.evictions += 1

    def _insert_locked(self, key: ChunkKey, data, origin: str,
                       owner: Optional[str] = None, pins: int = 0) -> None:
        n = len(data)
        g = self._obj_gen.get((key.bucket, key.object))
        if g is not None and key.generation < g:
            # An in-flight fetch of a superseded generation completed
            # AFTER the invalidation pass — never resurrect stale bytes
            # (a later gen-g sighting would not drop them: invalidation
            # fires only on strictly newer generations).
            self.stale_rejects += 1
            if origin == "prefetch":
                self.prefetch_dropped_bytes += n
            return
        if n > self.capacity:
            # A chunk that cannot fit even an empty cache would evict the
            # whole working set for nothing — serve it uncached.
            self.oversize_skips += 1
            if origin == "prefetch":
                self.prefetch_dropped_bytes += n
            return
        if key in self._entries:
            return  # racer already inserted the same (immutable) bytes
        self._evict_to_fit_locked(n, owner)
        if isinstance(data, SlabLease):
            # The cache's own reference (dropped by _drop_locked). Lock
            # order is cache lock -> pool lock, everywhere.
            data.incref()
        entry = _Entry(data, origin, owner)
        entry.pins = pins
        self._entries[key] = entry
        if owner is not None:
            self.owner_bytes[owner] = self.owner_bytes.get(owner, 0) + n
        self.bytes += n
        self.inserted_bytes += n
        if origin == "prefetch":
            self.prefetch_inserted_bytes += n
            self.prefetch_resident_unused += n
        if self._debug:
            self._assert_invariants_locked()

    def _hit_locked(self, key: ChunkKey, e: _Entry):
        self._entries.move_to_end(key)
        self.hits += 1
        self._mark_used_locked(e)
        if isinstance(e.data, SlabLease):
            # Every consumer access owns a reference: an eviction between
            # this return and the consumer's read must not retire the slab.
            e.data.incref()
        return e.data

    # ------------------------------------------------------------- surface --
    def get(self, key: ChunkKey):
        """Consumer hit-or-None lookup (no fetch, no miss accounting).
        The prefetcher's membership probe is :meth:`contains` — this one
        counts a hit, marks the entry used, and (lease payloads) hands the
        caller its own reference to release."""
        with self._lock:
            e = self._entries.get(key)
            return self._hit_locked(key, e) if e is not None else None

    def contains(self, key: ChunkKey) -> bool:
        with self._lock:
            return key in self._entries or key in self._inflight

    def set_owner_budgets(self, budgets: dict) -> None:
        """Live re-split of the per-owner byte budgets (the serve
        plane's weighted-cache knob); enforcement is lazy — the next
        insert by an over-budget owner pays."""
        with self._lock:
            self.owner_budgets = dict(budgets or {})

    def get_or_fetch(
        self, key: ChunkKey, fetch: Callable[[], object],
        origin: str = "demand", consumer: bool = True,
        owner: Optional[str] = None,
    ):
        """The consumer path: hit → cached bytes; miss → ``fetch()`` once
        per key no matter how many threads ask concurrently (losers wait
        and share the winner's bytes — or its exception).

        ``consumer=False`` is the prefetcher's variant: a hit neither
        counts nor marks the entry used (the prefetcher finding its work
        already done is not a consumption), and joining an in-flight
        fetch is not a coalesce save."""
        return self.get_or_fetch_info(key, fetch, origin, consumer, owner)[0]

    def get_or_fetch_info(
        self, key: ChunkKey, fetch: Callable[[], object],
        origin: str = "demand", consumer: bool = True,
        owner: Optional[str] = None,
    ) -> tuple:
        """:meth:`get_or_fetch` plus HOW the bytes arrived — ``"hit"``
        (already cached), ``"fetched"`` (this caller issued the backend
        read) or ``"coalesced"`` (joined another caller's in-flight
        read). Callers that account delivered-from-storage bytes (the
        flight records the chaos scorecard sums) credit them only to the
        ``"fetched"`` owner, so one backend read is never counted
        twice."""
        # One consumer access contributes exactly ONE count — hit, miss
        # or coalesce — decided by its FINAL outcome: a consumer that
        # joins a failed fetch and loops back to fetch itself is one
        # miss, not a coalesce plus a miss (hit_ratio's denominator
        # would otherwise inflate precisely in fault runs).
        while True:
            with self._lock:
                self._note_generation_locked(key)
                e = self._entries.get(key)
                if e is not None:
                    if not consumer:
                        return e.data, "hit"
                    return self._hit_locked(key, e), "hit"
                fl = self._inflight.get(key)
                if fl is None:
                    fl = self._inflight[key] = _Flight()
                    if consumer:
                        self.misses += 1
                    break  # owner: fetch below
                if consumer:
                    # Register on EVERY flight joined (a consumer whose
                    # first joined fetch failed loops back and may join
                    # a re-scheduled attempt — that flight too must
                    # mark-at-insert). The coalesce COUNT, by contrast,
                    # is only taken on a successful join below.
                    fl.consumer_waiters += 1
            fl.event.wait()
            if fl.error is None:
                assert fl.data is not None
                if not consumer:
                    # A prefetch worker that raced another fetch for the
                    # same chunk: the chunk landed, its job is done. No
                    # payload reference is taken (only consumers own
                    # references), so the caller must not release.
                    return fl.data, "coalesced"
                # A demand read joining an in-flight PREFETCH consumed
                # those bytes: mark the landed entry used, or the very
                # overlap the pipeline exists to produce would be counted
                # as prefetch waste (and a readahead byte budget would
                # slowly choke on phantom outstanding bytes). The
                # consumer's payload reference was taken by the owner at
                # insert time (one per registered waiter).
                with self._lock:
                    self.coalesced += 1
                    e = self._entries.get(key)
                    if e is not None:
                        if e.origin == "prefetch" and not e.used:
                            self._mark_used_locked(e)
                        if e.pins > 0:
                            # This waiter's pin is spent: once every
                            # registered waiter has woken the entry
                            # competes for eviction like any other.
                            e.pins -= 1
                return fl.data, "coalesced"
            if not consumer:
                # A prefetch worker joining a failed fetch stays
                # advisory: surface the error, the worker records it.
                raise fl.error
            # The joined fetch failed — but prefetch (the usual owner)
            # is advisory, and its retry window may have opened long
            # before this consumer arrived. A demand read is entitled
            # to its OWN attempt with a fresh retry stack: loop back
            # and (most likely) become the owner. Readahead must never
            # make a run strictly LESS fault-tolerant than cold reads.
        try:
            # At most ONE copy, and only for mutable fetch results: bytes
            # and slab leases store as-is (the PR-3 path paid bytes(fetch())
            # here AND bytes(data) again inside insert — two full copies
            # per miss even when the result was already immutable).
            data = _freeze(fetch())
        except BaseException as exc:
            with self._lock:
                fl.error = exc
                del self._inflight[key]
            fl.event.set()
            raise
        with self._lock:
            fl.data = data
            del self._inflight[key]
            # Registered waiters pin the entry until each wakes (the
            # weighted evictor skips pinned entries — see _Entry.pins).
            self._insert_locked(
                key, data, origin, owner=owner, pins=fl.consumer_waiters
            )
            if fl.consumer_waiters:
                # A consumer is already waiting on these bytes: they ARE
                # consumed. Mark at insert, not at the waiter's wakeup —
                # an eviction in between must not count them as waste
                # (and spuriously clamp the readahead depth).
                e = self._entries.get(key)
                if e is not None:
                    self._mark_used_locked(e)
                if isinstance(data, SlabLease):
                    # One payload reference per registered consumer waiter
                    # (they wake after the event and each release when
                    # done); taken under the cache lock, BEFORE the event,
                    # so no waiter can observe an unreferenced payload.
                    for _ in range(fl.consumer_waiters):
                        data.incref()
        fl.event.set()
        return data, "fetched"

    def insert(self, key: ChunkKey, data, origin: str = "demand",
               owner: Optional[str] = None) -> None:
        with self._lock:
            self._note_generation_locked(key)
            self._insert_locked(key, _freeze(data), origin, owner=owner)

    def close(self) -> None:
        """Run teardown: drop every resident entry, releasing the cache's
        lease references so the slab pool's leak detector sees only REAL
        leaks. Deliberately touches no counters — end-of-run stats were
        already snapshotted, and resident-but-unused prefetched bytes are
        ALREADY counted as waste by ``unused_prefetched_bytes``."""
        with self._lock:
            entries, self._entries = self._entries, OrderedDict()
            self.bytes = 0
        for e in entries.values():
            if isinstance(e.data, SlabLease):
                e.data.release()

    def export_manifest(self, max_bytes: int = 0) -> list:
        """MRU-first snapshot of resident entry identities as ``(key,
        owner)`` pairs — the cooperative-departure **hot set** a leaving
        owner drains to its chunks' new owners (the owner tag travels
        too, so QoS byte-budget accounting survives the hop). Read-only:
        no counters move, no LRU order changes, no payload bytes are
        copied here — the drain copies one entry at a time through
        :meth:`peek_bytes`, so a whole-cache drain never transiently
        doubles the host's cache footprint. A byte budget
        (``max_bytes``; 0 = everything) bounds the manifest to the
        hottest entries."""
        out: list = []
        total = 0
        with self._lock:
            for k in reversed(self._entries):  # OrderedDict: MRU first
                n = len(self._entries[k].data)
                if max_bytes and total + n > max_bytes:
                    break
                out.append((k, self._entries[k].owner))
                total += n
        return out

    def peek_bytes(self, key: ChunkKey):
        """One entry's payload as immutable bytes, or None when it is
        no longer resident. No counters move, no LRU reorder, no
        payload reference taken — the copy happens under the cache
        lock, so a concurrent eviction can never retire a slab
        mid-read."""
        from tpubench.mem.slab import payload_view

        with self._lock:
            e = self._entries.get(key)
            return bytes(payload_view(e.data)) if e is not None else None

    def unused_prefetched_bytes(self) -> int:
        """Prefetched entries still waiting for their first use — at end
        of run these are waste (the prefetcher folds them in)."""
        with self._lock:
            return self.prefetch_resident_unused

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses + self.coalesced
            return {
                "capacity_bytes": self.capacity,
                "resident_bytes": self.bytes,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "coalesced": self.coalesced,
                "hit_ratio": (self.hits / lookups) if lookups else None,
                "evictions": self.evictions,
                "inserted_bytes": self.inserted_bytes,
                "evicted_bytes": self.evicted_bytes,
                "oversize_skips": self.oversize_skips,
                "generation_invalidations": self.generation_invalidations,
                "stale_rejects": self.stale_rejects,
                "prefetch_inserted_bytes": self.prefetch_inserted_bytes,
                "prefetch_used_bytes": self.prefetch_used_bytes,
                "prefetch_wasted_bytes": self.prefetch_wasted_bytes,
                "prefetch_dropped_bytes": self.prefetch_dropped_bytes,
                "prefetch_invalidated_bytes": self.prefetch_invalidated_bytes,
                "owner_evictions": self.owner_evictions,
                "owner_budget_overruns": self.owner_budget_overruns,
                "pinned_capacity_overruns": self.pinned_capacity_overruns,
                "owner_bytes": dict(self.owner_bytes),
                "owner_budgets": dict(self.owner_budgets),
            }
