"""Pod-scale cooperative chunk cache: peer sharing with pod-wide
single-flight.

The PR-3 chunk cache is strictly per-host: an N-host pod downloads every
hot object from GCS N times, paying N× egress and N× first-byte latency
for bytes a peer already holds in its slab pool. This module makes "the
pod is the unit under test" true for the cache layer:

* **Consistent-hash ownership** (:class:`HashRing`): every chunk key
  ``(bucket, object, generation, range)`` has exactly ONE owner host,
  computed from a stable hash ring (virtual nodes, so a host join/leave
  remaps only ~1/N of the keys). Ownership is a pure function of the
  membership set — every host computes the same owner without
  coordination.
* **Peer-first miss path** (:class:`CoopCache`): a local
  :class:`~tpubench.pipeline.cache.ChunkCache` miss whose owner is a
  peer requests the chunk over the peer channel instead of fetching
  from origin; only a peer miss (or an unreachable/demoted owner) falls
  back to an origin fetch. Received bytes land in a leased slab — one
  host-RAM write, so the local path's ``copies_per_byte <= 1.0``
  guarantee survives.
* **Pod-wide single-flight**: the owner serves peer requests through
  its OWN cache's single-flight path, so N hosts missing the same chunk
  concurrently produce exactly one origin fetch — the followers (local
  threads and remote peers alike) register as waiters on the owner's
  in-flight fetch and share its bytes. The ``pod_coalesced`` counter
  records how many origin reads the pod-wide dedup saved.
* **Straggler demotion**: fed the flight recorder's per-host straggler
  table (:func:`tpubench.obs.flight.straggler_attribution`), an owner
  in the slowest decile is demoted — its virtual nodes leave the ring
  (keys rebalance consistent-hash-minimally to the remaining hosts) and
  its serve side answers pass-through misses — so one slow host cannot
  set the pod's chunk-fetch p99. Demoted hosts are restored when a
  later table clears them.

Two interchangeable peer channels sit behind one interface:

* :class:`LoopbackChannel` over a :class:`LoopbackBroker` — in-process
  request/reply for hermetic multi-"host" tests, single-host dev, and
  the bench's simulated pod (threaded hosts, no TPU, no network).
* :class:`tpubench.dist.peer.IciPeerChannel` — the chunk bytes ride the
  existing ``dist.shard``/``make_reassemble`` NamedSharding path over
  ICI for real pods (lockstep/SPMD scope documented there).

Peer reads compose under the same machinery as any backend:
:class:`PeerBackend` is a :class:`~tpubench.storage.base.StorageBackend`
whose ``open_read`` resolves the chunk's owner and streams the peer
payload through an :class:`~tpubench.storage.base.ObjectReader`, so
``RetryingBackend`` (and the tail stack) wrap it exactly like the GCS
clients — a transient channel error retries, a definitive peer miss
(``PeerMissError``, non-transient) falls through to origin immediately.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional, Protocol, Sequence

import numpy as np

from tpubench.mem.slab import CopyMeter, SlabPool, payload_view, release_payload
from tpubench.metrics.percentiles import summarize_ns
from tpubench.obs import flight as _flight
from tpubench.obs.tracing import (
    TraceContext,
    adopt_trace,
    current_trace,
    derive_span_id,
    trace_scope,
)
from tpubench.pipeline.cache import ChunkCache, ChunkKey
from tpubench.storage.base import ObjectMeta, StorageError

MB = 1024 * 1024

# Peer-tier retry bound (wrap_peer_backend): re-asking an owner is only
# worth a few attempts — the origin fallback is always available.
PEER_MAX_ATTEMPTS = 3

# Peer-tier backoff ceilings (wrap_peer_backend): the origin gax
# schedule (1 s initial, ×2, 30 s cap) is sized for a cloud service's
# recovery, not a peer one ICI/loopback hop away — a transient peer
# error re-asked on that schedule would stall a demand miss for seconds
# when the origin fallback is immediately available behind it.
PEER_BACKOFF_INITIAL_S = 0.05
PEER_BACKOFF_MAX_S = 0.25

# Requester-side peer transfer sample window (stats percentiles + the
# local demotion signal). Bounded: a serve-shaped run with millions of
# peer hits must not grow host RSS (the telemetry registry's
# EXACT_SAMPLE_CAP discipline); a recent window is also the honest
# signal for demotion — an owner that WAS slow an hour ago isn't.
TRANSFER_SAMPLE_CAP = 8192


# --------------------------------------------------------------- hashing ----


def _h64(s: str) -> int:
    """Stable 64-bit hash (blake2b, not ``hash()``: PYTHONHASHSEED must
    never change chunk ownership between hosts or runs)."""
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "big"
    )


def chunk_point(key: ChunkKey) -> int:
    """The ring position of one chunk key — the full identity hashes
    (bucket, object, generation, range), so an overwritten object's new
    generation may land on a different owner while the stale
    generation's entries age out where they were."""
    return _h64(
        f"{key.bucket}\x00{key.object}\x00{key.generation}"
        f"\x00{key.start}\x00{key.length}"
    )


class HashRing:
    """Consistent-hash ring over host ids with virtual nodes.

    Deterministic by construction: two rings built from the same
    membership (in any order) place every key identically — ownership
    needs no coordination. Adding or removing one host remaps ~1/N of
    the key space (the stability property the tests pin). Demotion
    removes a host's points from the LOOKUP without forgetting the
    host, so a restored straggler gets its exact original points back
    (rehash-minimal in both directions)."""

    def __init__(self, hosts: Iterable[int] = (), vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._hosts: set[int] = set()
        self._demoted: set[int] = set()
        self._points: list[int] = []
        self._owners: list[int] = []
        self._lock = threading.Lock()
        for h in hosts:
            self._hosts.add(int(h))
        self._rebuild_locked()

    # ------------------------------------------------------------ internal --
    def _rebuild_locked(self) -> None:
        pts: list[tuple[int, int]] = []
        for h in sorted(self._hosts - self._demoted):
            for v in range(self.vnodes):
                pts.append((_h64(f"host:{h}\x00vnode:{v}"), h))
        pts.sort()
        self._points = [p for p, _ in pts]
        self._owners = [o for _, o in pts]

    # ------------------------------------------------------------- surface --
    def add_host(self, host: int) -> None:
        with self._lock:
            self._hosts.add(int(host))
            self._rebuild_locked()

    def remove_host(self, host: int) -> None:
        with self._lock:
            self._hosts.discard(int(host))
            self._demoted.discard(int(host))
            self._rebuild_locked()

    def demote(self, host: int) -> bool:
        """Take ``host``'s points out of the lookup (straggler
        rebalancing). Returns True when this call changed state."""
        with self._lock:
            if host not in self._hosts or host in self._demoted:
                return False
            self._demoted.add(int(host))
            self._rebuild_locked()
            return True

    def restore(self, host: int) -> bool:
        with self._lock:
            if host not in self._demoted:
                return False
            self._demoted.discard(int(host))
            self._rebuild_locked()
            return True

    @property
    def hosts(self) -> set[int]:
        with self._lock:
            return set(self._hosts)

    @property
    def demoted(self) -> set[int]:
        with self._lock:
            return set(self._demoted)

    @property
    def active_hosts(self) -> set[int]:
        with self._lock:
            return self._hosts - self._demoted

    def owner(self, key: ChunkKey) -> Optional[int]:
        """The key's owner among the ACTIVE (non-demoted) hosts, or None
        when the ring is empty — the caller fetches origin."""
        with self._lock:
            if not self._points:
                return None
            i = bisect.bisect_right(self._points, chunk_point(key))
            return self._owners[i % len(self._owners)]


# --------------------------------------------------------------- channels ---


class PeerMissError(StorageError):
    """The owner definitively does not serve this chunk (budget shed,
    demoted, serve-side failure). Non-transient on purpose: retrying the
    peer would just re-shed — the correct recovery is the ORIGIN fetch,
    which the coop miss path falls through to immediately."""

    def __init__(self, msg: str):
        super().__init__(msg, transient=False, code=404)


class PeerChannel(Protocol):
    """One host's handle on the pod's peer transport. ``request`` routes
    to the owner and returns the chunk bytes, raising ``StorageError``
    (transient ⇒ the retry stack may re-ask; ``PeerMissError`` ⇒ fall
    back to origin now). ``lockstep`` channels (ICI) instead require
    every host to enter ``broadcast`` together — see dist/peer.py."""

    host_id: int
    lockstep: bool

    def request(self, owner: int, key: ChunkKey) -> bytes: ...

    def close(self) -> None: ...


class LoopbackBroker:
    """In-process pod: host id → serve callable. The hermetic stand-in
    for the network — multi-"host" tests register N CoopCaches here and
    exercise the identical routing/dedup/demotion logic real pods run.
    ``delay_s`` injects per-host serve latency (straggler shaping for
    the demotion tests/bench). ``accept`` is the host's warm-handoff
    landing callable (:meth:`CoopCache.accept_handoff`) — a departing
    owner :meth:`push`\\ es its hot set there. A **paused** host (the
    elastic fabric's stalled-but-not-dead state) raises *transient*
    errors instead of serving: the requester's bounded peer-tier retry
    re-asks, then falls through to origin."""

    def __init__(self):
        self._serves: dict[int, Callable[[ChunkKey], Optional[bytes]]] = {}
        self._accepts: dict[int, Callable[[ChunkKey, bytes], bool]] = {}
        self._delay: dict[int, float] = {}
        self._paused: set[int] = set()
        self._lock = threading.Lock()

    def register(self, host_id: int,
                 serve: Callable[[ChunkKey], Optional[bytes]],
                 delay_s: float = 0.0,
                 accept: Optional[Callable[[ChunkKey, bytes], bool]] = None,
                 ) -> None:
        with self._lock:
            self._serves[int(host_id)] = serve
            if delay_s:
                self._delay[int(host_id)] = delay_s
            if accept is not None:
                self._accepts[int(host_id)] = accept

    def unregister(self, host_id: int) -> None:
        with self._lock:
            self._serves.pop(int(host_id), None)
            self._accepts.pop(int(host_id), None)
            self._delay.pop(int(host_id), None)

    def pause(self, host_id: int) -> None:
        """Make ``host_id`` unresponsive without removing it: requests
        raise transient 503s (the retry stack's domain), pushes bounce."""
        with self._lock:
            self._paused.add(int(host_id))

    def resume(self, host_id: int) -> None:
        with self._lock:
            self._paused.discard(int(host_id))

    def request(self, src: int, owner: int, key: ChunkKey) -> bytes:
        with self._lock:
            serve = self._serves.get(int(owner))
            delay = self._delay.get(int(owner), 0.0)
            paused = int(owner) in self._paused
        if serve is None:
            # Definitive, not transient: a host this broker has never
            # seen will not appear by retrying (loopback brokers span
            # one process). The follower's remedy is its origin fetch.
            raise PeerMissError(f"peer host {owner} not registered")
        if paused:
            # Transient on purpose: a paused host may come back, so the
            # peer-tier retry gets its (bounded, fast-backoff) say —
            # after which the requester falls through to origin.
            raise StorageError(
                f"peer host {owner} is paused", transient=True, code=503,
            )
        if delay:
            time.sleep(delay)
        data = serve(key)
        if data is None:
            raise PeerMissError(f"host {owner} shed {key.object} chunk")
        return data

    def push(self, src: int, dst: int, key: ChunkKey, data: bytes,
             owner: Optional[str] = None) -> bool:
        """Warm-handoff delivery: ``src``'s departing owner hands one
        hot chunk to ``dst`` (its new owner), QoS owner tag riding
        along. Returns False when the destination cannot take it
        (unregistered, paused, or its accept refused) — the pusher
        counts the reject and moves on; the pod re-fetches that chunk
        from origin like the killed-host arm."""
        with self._lock:
            accept = self._accepts.get(int(dst))
            paused = int(dst) in self._paused
        if accept is None or paused:
            return False
        return bool(accept(key, data, owner))


class LoopbackChannel:
    """The broker-backed :class:`PeerChannel` (request/reply, runs the
    owner's serve on the requester's thread)."""

    lockstep = False

    def __init__(self, broker: LoopbackBroker, host_id: int):
        self._broker = broker
        self.host_id = int(host_id)

    def request(self, owner: int, key: ChunkKey) -> bytes:
        return self._broker.request(self.host_id, owner, key)

    def close(self) -> None:
        self._broker.unregister(self.host_id)


# ---------------------------------------------------------- peer backend ----

_SEP = "\x00"


def encode_chunk_name(key: ChunkKey) -> str:
    """The chunk's peer-read object name: ``open_read(name, start,
    length)`` carries the range natively; bucket + generation ride the
    name (NUL-separated — never legal in a GCS object name)."""
    return f"{key.bucket}{_SEP}{key.object}{_SEP}{key.generation}"


def decode_chunk_name(name: str, start: int, length: int) -> ChunkKey:
    bucket, obj, gen = name.split(_SEP)
    return ChunkKey(bucket, obj, int(gen), int(start), int(length))


class PeerReader:
    """ObjectReader over a received peer payload (cursor + readinto), so
    the peer path measures on the same reader shape as every transport:
    ``first_byte_ns`` is the request round-trip, ``generation`` is the
    key's (the owner's cache is generation-keyed — a served chunk IS
    that generation's bytes)."""

    def __init__(self, data: bytes, first_byte_ns: int, generation: int):
        self._data = memoryview(data)
        self._pos = 0
        self.first_byte_ns = first_byte_ns
        self.generation = generation

    def readinto(self, buf: memoryview) -> int:
        n = min(len(buf), len(self._data) - self._pos)
        if n <= 0:
            return 0
        buf[:n] = self._data[self._pos : self._pos + n]
        self._pos += n
        return n

    def close(self) -> None:
        self._data = memoryview(b"")
        self._pos = 0


class PeerBackend:
    """StorageBackend adapter over a peer channel: peer reads ride the
    ordinary ``open_read`` protocol so ``RetryingBackend`` (and the tail
    stack) compose over them exactly as over the GCS clients. A ring
    lookup that lands on SELF or an empty ring raises ``PeerMissError``
    — this backend only ever serves *remote* chunks."""

    def __init__(self, channel, ring: HashRing):
        self._channel = channel
        self._ring = ring
        self._tls = threading.local()

    def last_serving_owner(self) -> Optional[int]:
        """The owner that served THIS thread's most recent successful
        ``open_read``. The ring is re-resolved per attempt (a demotion
        between retries must redirect the re-ask), so the host a
        transfer sample should be attributed to is the one the LAST
        attempt landed on, not the one the caller resolved up front."""
        return getattr(self._tls, "owner", None)

    def open_read(self, name: str, start: int = 0,
                  length: Optional[int] = None):
        if length is None:
            raise ValueError("peer reads are ranged: length is required")
        key = decode_chunk_name(name, start, length)
        owner = self._ring.owner(key)
        if owner is None or owner == self._channel.host_id:
            raise PeerMissError(f"no remote owner for {key.object} chunk")
        data = self._channel.request(owner, key)
        if len(data) != key.length:
            raise StorageError(
                f"peer {owner} served {len(data)}/{key.length} B for "
                f"{key.object}", transient=True, code=502,
            )
        self._tls.owner = owner
        return PeerReader(data, time.perf_counter_ns(), key.generation)

    # StorageBackend protocol completeness (the peer tier is read-only).
    def write(self, name: str, data: bytes,
              if_generation_match=None) -> ObjectMeta:
        raise StorageError("peer backend is read-only", transient=False)

    def open_write(self, name: str, if_generation_match=None):
        raise StorageError("peer backend is read-only", transient=False)

    def list(self, prefix: str = "", page_size: int = 0) -> list:
        return []

    def stat(self, name: str) -> ObjectMeta:
        raise StorageError("peer backend has no metadata surface",
                           transient=False, code=404)

    def delete(self, name: str) -> None:
        raise StorageError("peer backend is read-only", transient=False)

    def close(self) -> None:
        pass


def wrap_peer_backend(channel, ring: HashRing, retry_cfg=None, *, inner=None):
    """The composition ``open_backend`` applies to every transport,
    applied to the peer tier: ``Retrying(PeerBackend)`` when a retry
    policy is given (transient channel errors re-ask the owner;
    ``PeerMissError`` is non-transient and surfaces immediately).

    The peer tier always retries under "idempotent" semantics, whatever
    the origin policy: "always" (the gax default) retries ANY
    StorageError, which would re-ask a shedding owner ``max_attempts``
    times for a definitive miss whose correct remedy — the origin
    fetch — is sitting right behind the fallback path. Attempts are
    also BOUNDED (the origin policy's 0 = retry-forever would park a
    read behind an unreachable peer when the same bytes are one origin
    fetch away), and the backoff schedule is SHRUNK to peer scale
    (``PEER_BACKOFF_*`` — the gax 1 s-initial origin schedule would add
    seconds of sleep before a fallback that is one step away)."""
    if inner is None:
        inner = PeerBackend(channel, ring)
    if retry_cfg is None or retry_cfg.policy == "never":
        return inner
    import dataclasses

    from tpubench.storage.retrying import RetryingBackend

    attempts = retry_cfg.max_attempts
    if attempts <= 0 or attempts > PEER_MAX_ATTEMPTS:
        attempts = PEER_MAX_ATTEMPTS
    initial = min(retry_cfg.initial_backoff_s, PEER_BACKOFF_INITIAL_S)
    cap = min(retry_cfg.max_backoff_s, PEER_BACKOFF_MAX_S)
    if (retry_cfg.policy != "idempotent"
            or attempts != retry_cfg.max_attempts
            or initial != retry_cfg.initial_backoff_s
            or cap != retry_cfg.max_backoff_s):
        retry_cfg = dataclasses.replace(
            retry_cfg, policy="idempotent", max_attempts=attempts,
            initial_backoff_s=initial, max_backoff_s=cap,
        )
    return RetryingBackend(inner, retry_cfg)


# -------------------------------------------------------------- CoopCache ---


class CoopCache:
    """The pod-coherent tier over one host's :class:`ChunkCache` (module
    docstring). Construct one per host; register :meth:`serve` with the
    pod's peer transport; hand :meth:`fetch` to the cache's miss path
    (demand reads and the prefetcher alike) as the routed fetch.

    ``peer_budget_bytes`` bounds the bytes this host is concurrently
    serving to peers: past it, serve sheds with a miss (the follower
    falls back to origin) instead of queueing unboundedly behind a hot
    owner — the valve the ``peer_budget_bytes`` tune knob actuates
    live. ``set_enabled(False)`` (the ``coop`` knob) short-circuits
    routing to plain origin fetches without restarting anything."""

    def __init__(
        self,
        cache: ChunkCache,
        *,
        host_id: int,
        ring: HashRing,
        channel=None,
        origin_fetch: Callable[[ChunkKey], object],
        pool: Optional[SlabPool] = None,
        meter: Optional[CopyMeter] = None,
        enabled: bool = True,
        peer_budget_bytes: int = 0,
        demote_share: float = 0.5,
        demote_interval_s: float = 2.0,
        retry_cfg=None,
        flight_ring=None,
        flight_recorder=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cache = cache
        self.host_id = int(host_id)
        self.ring = ring
        self._channel = channel
        self._origin_fetch = origin_fetch
        self._pool = pool
        self._meter = meter
        self._enabled = bool(enabled)
        self._budget = max(0, int(peer_budget_bytes))
        self._demote_share = demote_share
        self._demote_interval_s = demote_interval_s
        self._clock = clock
        self._flight_ring = flight_ring
        # THIS host's recorder: serve-side origin fetches record on it
        # (kind="serve" on a pooled single-appender ring — see
        # _acquire_serve_ring), so the owner half of a cross-host hop
        # lands in the OWNER's journal carrying the REQUESTER's
        # propagated trace context.
        self._flight_recorder = flight_recorder
        self._serve_ring_free: list[str] = []
        self._serve_ring_seq = 0
        self._peer_inner = (
            PeerBackend(channel, ring)
            if channel is not None and not getattr(channel, "lockstep", False)
            else None
        )
        self._peer_backend = (
            wrap_peer_backend(channel, ring, retry_cfg, inner=self._peer_inner)
            if self._peer_inner is not None
            else None
        )
        self._lock = threading.Lock()
        self._closed = False
        self._serving_bytes = 0
        self._last_demote_check = clock()
        # Counters (the extra["pipeline"]["coop"] stamp).
        self.peer_requests = 0
        self.peer_hits = 0
        self.peer_misses = 0
        self.peer_bytes = 0
        self.peer_serves = 0
        self.peer_served_bytes = 0
        self.serve_errors = 0
        self.budget_rejects = 0
        self.pod_coalesced = 0  # peer requests that joined an in-flight fetch
        self.origin_fetches = 0
        self.origin_bytes = 0
        self.owner_fetches = 0  # origin fetches made AS the ring owner
        # Origin bytes fetched ONLY to answer a peer request (a serve
        # miss in the owner's cache). A per-host baseline would not have
        # made these fetches — the requester's own origin fetch for the
        # same bytes is already counted in its peer_bytes — so they are
        # excluded from per_host_origin_estimate_bytes.
        self.serve_origin_bytes = 0
        self.demotions = 0
        self.restores = 0
        # Warm-handoff accounting (elastic membership): chunks this host
        # DRAINED to new owners at cooperative departure (out) and
        # chunks it RECEIVED from a departing owner (in). The
        # cooperative-vs-killed resize A/B is exactly out+in vs the
        # origin re-fetch bytes the killed arm pays instead.
        self.handoff_out_chunks = 0
        self.handoff_out_bytes = 0
        self.handoff_in_chunks = 0
        self.handoff_in_bytes = 0
        self.handoff_rejects = 0  # pushes the destination refused
        # Recent (owner, round-trip ns) peer transfer samples — the
        # stats percentiles AND the local demotion signal's source.
        self._transfer_ns: deque = deque(maxlen=TRANSFER_SAMPLE_CAP)

    # ------------------------------------------------------------ routing --
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def lockstep(self) -> bool:
        """True when the peer channel is a collective (ICI): every host
        must enter each broadcast together, so only plan-synchronized
        consumers may route through :meth:`fetch` — the workload guards
        enforce this (asynchronous prefetch workers and desynchronized
        demand misses would hang the pod's mesh)."""
        return bool(getattr(self._channel, "lockstep", False))

    def set_enabled(self, on) -> None:
        """Live coop on/off (the ``coop`` tune knob): off = every miss
        is a plain origin fetch; serve sheds so peers fall back too."""
        self._enabled = bool(on)

    def set_peer_budget(self, nbytes: int) -> None:
        """Live serve-side byte budget (the ``peer_budget_bytes`` tune
        knob); 0 = unbounded."""
        self._budget = max(0, int(nbytes))

    @property
    def peer_budget_bytes(self) -> int:
        return self._budget

    def _owner(self, key: ChunkKey) -> Optional[int]:
        if not self._enabled or self._channel is None:
            return None
        if len(self.ring.active_hosts) < 2:
            return None  # a pod of one has no peers to share with
        return self.ring.owner(key)

    def _count_origin(self, payload, owner: bool, serving: bool) -> None:
        with self._lock:
            self.origin_fetches += 1
            self.origin_bytes += len(payload)
            if owner:
                self.owner_fetches += 1
            if serving:
                self.serve_origin_bytes += len(payload)

    def _origin(self, key: ChunkKey, owner: bool = False,
                serving: bool = False):
        if owner:
            _flight.note_phase("owner_fetch")
        payload = self._origin_fetch(key)
        self._count_origin(payload, owner, serving)
        return payload

    def fetch(self, key: ChunkKey):
        """The routed miss fetch — what the local cache's single-flight
        runs on a miss. Owner (or no-peer) keys fetch origin; follower
        keys ask the owner first and fall back to origin on a peer
        miss/failure. Returns a caller-owned payload (``SlabLease`` or
        ``bytes``), exactly like ``fetch_chunk``."""
        owner = self._owner(key)
        if owner is None:
            return self._origin(key)
        if getattr(self._channel, "lockstep", False):
            return self._fetch_lockstep(key, owner)
        if owner == self.host_id:
            return self._origin(key, owner=True)
        _flight.note_phase("peer_request")
        with self._lock:
            self.peer_requests += 1
        t0 = time.perf_counter_ns()
        try:
            # Trace propagation: the hop travels as a child of THIS
            # read's synthesized peer_request segment — the derived id
            # is recomputable from the requester's record at merge time,
            # so the owner host's serve span stitches under it with no
            # extra wire data (loopback/request-reply channels carry the
            # context thread-locally; a networked channel would marshal
            # the same two ids).
            with trace_scope(self._peer_hop_ctx()):
                payload = self._receive(key)
        except StorageError:
            _flight.note_phase("peer_miss")
            with self._lock:
                self.peer_misses += 1
            return self._origin(key)
        _flight.note_phase("peer_hit")
        served_by = self._peer_inner.last_serving_owner()
        with self._lock:
            self.peer_hits += 1
            self.peer_bytes += len(payload)
            self._transfer_ns.append(
                (owner if served_by is None else served_by,
                 time.perf_counter_ns() - t0)
            )
        return payload

    def _peer_hop_ctx(self) -> Optional[TraceContext]:
        """The context a peer hop travels under: the current read op's
        trace with the DERIVED peer_request segment id as parent (so the
        owner's spans nest under the hop, not the whole read), carrying
        the read's per-trace sampling decision across the hop. Falls
        back to the thread's ambient trace context; None when the read
        is untraced."""
        op = _flight.current_op()
        if op is not None:
            base = op.trace_context()
            return TraceContext(
                base.trace_id,
                derive_span_id(base.span_id, "peer_request"),
                base.sampled,
            )
        return current_trace()

    def _receive(self, key: ChunkKey):
        """Stream the peer payload through the composed peer backend
        into its landing buffer — a leased slab when the pool is on (one
        host-RAM write: the local path stays <= 1.0 copies/byte), bytes
        otherwise. Raises StorageError on miss/short reads (after the
        retry stack had its say)."""
        name = encode_chunk_name(key)
        if self._pool is not None:
            lease = self._pool.lease(key.length)
            if lease.overflow:
                _flight.annotate("slab", event="overflow")
            try:
                self._readinto(name, key, lease.view())
            except BaseException:
                lease.release()
                raise
            if self._meter is not None:
                self._meter.landed(key.length)
            return lease
        buf = bytearray(key.length)
        self._readinto(name, key, memoryview(buf))
        if self._meter is not None:
            self._meter.landed(key.length)
        return bytes(buf)

    def _readinto(self, name: str, key: ChunkKey, mv: memoryview) -> None:
        reader = self._peer_backend.open_read(
            name, start=key.start, length=key.length
        )
        got = 0
        try:
            while got < key.length:
                n = reader.readinto(mv[got:])
                if n <= 0:
                    break
                got += n
        finally:
            reader.close()
        if got != key.length:
            raise StorageError(
                f"{key.object}: short peer read {got}/{key.length}",
                transient=True, code=502,
            )

    def _fetch_lockstep(self, key: ChunkKey, owner: int):
        """ICI (SPMD) transfer: EVERY host enters the broadcast for this
        key together — the owner contributes the chunk (fetched from
        origin under its local single-flight position), followers
        contribute nothing and receive it off the mesh. Scope: plan-
        synchronized pod workloads (see dist/peer.py)."""
        if owner == self.host_id:
            payload = self._origin(key, owner=True)
            self._channel.broadcast(owner, bytes(payload_view(payload)), key)
            # A collective cannot parent one remote span under another
            # (every host enters together; the owner fetched under its
            # OWN plan-walk span) — instead the followers' contexts ride
            # the gather's spare slots and land here as TRACE LINKS, the
            # OTel link shape for causal-but-not-parental edges.
            links = getattr(self._channel, "last_request_links", lambda: [])()
            if links:
                _flight.annotate(
                    "trace_link",
                    peers=[
                        {"trace_id": c.trace_id, "span_id": c.span_id}
                        for c in links
                    ],
                )
            return payload
        _flight.note_phase("peer_request")
        with self._lock:
            self.peer_requests += 1
        t0 = time.perf_counter_ns()
        data = self._channel.broadcast(
            owner, None, key, ctx=self._peer_hop_ctx()
        )
        _flight.note_phase("peer_hit")
        with self._lock:
            self.peer_hits += 1
            self.peer_bytes += len(data)
            self._transfer_ns.append((owner, time.perf_counter_ns() - t0))
        return self._land(data, key)

    def _land(self, data: bytes, key: ChunkKey):
        if self._pool is not None:
            lease = self._pool.lease(key.length)
            if lease.overflow:
                _flight.annotate("slab", event="overflow")
            lease.view()[:] = data
            if self._meter is not None:
                self._meter.landed(key.length)
            return lease
        if self._meter is not None:
            self._meter.landed(key.length)
        return data

    # -------------------------------------------------------------- serve --
    def serve(self, key: ChunkKey) -> Optional[bytes]:
        """The owner side of a peer request (invoked by the transport,
        on whatever thread it uses). Serves through this host's OWN
        cache single-flight path — which is what extends single-flight
        pod-wide: concurrent peers (and local threads) asking for one
        chunk coalesce onto one origin fetch. Returns None to shed
        (budget exceeded, demoted, disabled, or the fetch failed) — the
        follower's remedy is its own origin fetch."""
        if self._closed or not self._enabled:
            return None
        if self.host_id in self.ring.demoted:
            return None  # demoted owners pass peers through to origin
        n = key.length
        with self._lock:
            if self._budget and self._serving_bytes + n > self._budget:
                self.budget_rejects += 1
                return None
            self._serving_bytes += n
        # The serve's backend work must not stamp phases on the
        # REQUESTER's flight op (loopback runs serve on the requester's
        # thread; connect/first_byte stamps here would break the peer
        # record's phase monotonicity) — but the requester's PROPAGATED
        # trace context is kept: the serve's own record (kind="serve",
        # on THIS host's recorder) parents under the remote peer_request
        # segment, which is the cross-host stitch `report trace` merges.
        peer_ctx = current_trace()
        caller_op = _flight.current_op()
        _flight.adopt_op(None)
        adopt_trace(peer_ctx)
        sop = None
        ring_name = None
        if self._flight_recorder is not None:
            # The transport invokes serve on arbitrary threads and a
            # ring has exactly one appending owner — but keying rings
            # by thread ident would grow one 1024-slot ring per ident
            # forever on a per-connection-thread transport. A free-list
            # bounds the pool at PEAK serve concurrency: a name is held
            # exclusively for the duration of this serve (single
            # appender by construction) and recycled after.
            ring_name = self._acquire_serve_ring()
            sop = self._flight_recorder.worker(ring_name).begin(
                key.object, "peer", kind="serve"
            )
        try:
            payload, source = self.cache.get_or_fetch_info(
                key, lambda: self._origin(key, owner=True, serving=True),
            )
            try:
                data = bytes(payload_view(payload))
            finally:
                release_payload(payload)
            if sop is not None:
                if source == "hit":
                    sop.mark("cache_hit")
                # First-stamp-wins: when the origin path already stamped
                # body_complete (the composed backend stack does), this
                # is a no-op — it guarantees the serve SPAN covers the
                # fetch even over an origin_fetch that stamps nothing,
                # so the owner side of a slow hop has a duration the
                # critical-path walk can descend into.
                sop.mark("body_complete")
                sop.finish(len(data))
            with self._lock:
                self.peer_serves += 1
                self.peer_served_bytes += len(data)
                if source == "coalesced":
                    self.pod_coalesced += 1
            return data
        except Exception as e:  # noqa: BLE001 — shed, requester recovers
            # Exception, not BaseException: loopback runs serve on the
            # REQUESTER's thread — a KeyboardInterrupt here must stop
            # the run, not be counted as a shed.
            if sop is not None:
                sop.finish(error=e)
            with self._lock:
                self.serve_errors += 1
            return None
        finally:
            if sop is not None:
                sop.abandon()  # no-op when finished; never leak the op
            if ring_name is not None:
                self._release_serve_ring(ring_name)
            _flight.adopt_op(caller_op)
            # adopt_op set the trace position to the caller op's context
            # (or cleared it when there is no op) — restore the ACTUAL
            # entry state: on a loopback serve the requester thread was
            # inside its hop scope, and anything it begins after this
            # return (payload streaming in _receive) must parent under
            # the hop segment, not the whole read or a fresh root.
            adopt_trace(peer_ctx)
            with self._lock:
                self._serving_bytes -= n

    # ------------------------------------------------------ warm handoff --
    def accept_handoff(self, key: ChunkKey, data: bytes,
                       owner: Optional[str] = None) -> bool:
        """Land one hot chunk a departing owner drained to this host
        (invoked by the fabric's push, on the departing host's thread).
        The payload takes the ordinary landing path — a leased slab when
        the pool is on — and inserts as a demand entry under the SAME
        QoS owner tag it carried on the departing host (per-class cache
        budgets must survive the hop, or every cooperative departure
        would dilute the weighted-eviction guarantee with untagged
        bytes). The next miss for the key is a local hit instead of an
        origin fetch. Returns False when this host cannot take it
        (closed/disabled, or the bytes don't match the key)."""
        if self._closed or not self._enabled:
            return False
        if len(data) != key.length:
            return False
        payload = self._land(data, key)
        try:
            self.cache.insert(key, payload, owner=owner)
        finally:
            release_payload(payload)  # the cache holds its own reference
        with self._lock:
            self.handoff_in_chunks += 1
            self.handoff_in_bytes += key.length
        return True

    def drain_hot_set(self, push: Callable[..., bool],
                      owner_for: Callable[[ChunkKey], Optional[int]],
                      max_bytes: int = 0) -> dict:
        """Cooperative departure: hand this host's resident hot set to
        each chunk's NEW owner (``owner_for`` resolves against the
        post-departure ring) over ``push(owner_host, key, data,
        owner_tag)``. MRU-first, so a byte budget (``max_bytes``; 0 =
        everything) drains the hottest chunks first. Chunks whose new
        owner is this host or nobody are skipped; refused pushes are
        counted and abandoned (the pod re-fetches those from origin —
        strictly no worse than a kill)."""
        chunks = nbytes = rejected = skipped = 0
        for key, tag in self.cache.export_manifest(max_bytes=max_bytes):
            owner = owner_for(key)
            if owner is None or owner == self.host_id:
                skipped += 1
                continue
            # One entry at a time (manifest first, bytes per push): a
            # whole-cache drain must not transiently double the host's
            # cache footprint at the exact moment the pod is resizing.
            data = self.cache.peek_bytes(key)
            if data is None:
                skipped += 1  # evicted since the manifest snapshot
                continue
            if push(owner, key, data, tag):
                chunks += 1
                nbytes += len(data)
            else:
                rejected += 1
        with self._lock:
            self.handoff_out_chunks += chunks
            self.handoff_out_bytes += nbytes
            self.handoff_rejects += rejected
        return {
            "chunks": chunks, "bytes": nbytes,
            "rejected": rejected, "skipped": skipped,
        }

    def purge_host_samples(self, host: int) -> None:
        """Forget peer-transfer samples attributed to ``host`` (called
        on every membership epoch that removes it): straggler evidence
        about a departed owner must not survive the view change — a
        rejoining host starts from a clean slate, and the demotion scan
        must never act on rounds served by a host that is gone."""
        with self._lock:
            kept = [s for s in self._transfer_ns if s[0] != int(host)]
            self._transfer_ns.clear()
            self._transfer_ns.extend(kept)

    def reset_member_state(self) -> None:
        """Clean-rejoin reset for THIS host: drop every peer-transfer
        sample (they were measured under a dead epoch's view). Ring
        demotion state needs no reset here — ``HashRing.remove_host``
        already forgot it when the host left."""
        with self._lock:
            self._transfer_ns.clear()

    def _acquire_serve_ring(self) -> str:
        """Exclusive serve-ring name: pool bounded by peak concurrency,
        each name held by exactly one in-flight serve (the ring's one
        appender), recycled on release."""
        with self._lock:
            if self._serve_ring_free:
                return self._serve_ring_free.pop()
            self._serve_ring_seq += 1
            return f"serve-{self._serve_ring_seq}"

    def _release_serve_ring(self, name: str) -> None:
        with self._lock:
            self._serve_ring_free.append(name)

    # ----------------------------------------------------------- demotion --
    def _slow_hosts_from_rows(self, rows: Sequence[dict]) -> set[int]:
        """Hosts owning at least ``demote_share`` of a table's slowest
        decile. A single-row table demotes nobody: with no second host
        to compare against, 100% tail ownership is vacuous (and on a
        real pod the LOCAL recorder only ever sees its own host id)."""
        slow: set[int] = set()
        if len(rows) >= 2:
            for row in rows:
                if row.get("tail_share", 0.0) >= self._demote_share:
                    try:
                        slow.add(int(row["host"]))
                    except (KeyError, TypeError, ValueError):
                        continue
        return slow

    def _apply_slow_set(self, slow: set[int]) -> dict:
        demoted, restored = [], []
        for h in self.ring.hosts:
            if h in slow:
                if self.ring.demote(h):
                    demoted.append(h)
            elif self.ring.restore(h):
                restored.append(h)
        with self._lock:
            self.demotions += len(demoted)
            self.restores += len(restored)
            if demoted:
                # Demotion CONSUMES its transfer-sample evidence: a
                # demoted owner receives no new peer requests, so its
                # stale slow samples would otherwise keep its
                # tail_share at the cut forever (restore could only
                # happen after TRANSFER_SAMPLE_CAP newer appends).
                # Purging gives the host a clean local slate — it is
                # restored at the next refresh unless another signal
                # still flags it, and fresh round-trips re-demote it if
                # it is still slow (probation re-probe, not exile).
                gone = set(demoted)
                kept = [s for s in self._transfer_ns if s[0] not in gone]
                self._transfer_ns.clear()
                self._transfer_ns.extend(kept)
        for h in demoted:
            self._note_demotion("demote", h)
        for h in restored:
            self._note_demotion("restore", h)
        return {"demoted": demoted, "restored": restored}

    def apply_straggler_table(self, rows: Sequence[dict]) -> dict:
        """Apply one per-host straggler table (the
        ``straggler_attribution(records, by="host")`` row shape): a host
        owning at least ``demote_share`` of the slowest-decile reads is
        demoted out of the ring; every other known host is restored.
        Returns {"demoted": [...], "restored": [...]}."""
        return self._apply_slow_set(self._slow_hosts_from_rows(rows))

    def _local_transfer_rows(self) -> list[dict]:
        """Straggler rows derived from THIS host's own peer transfer
        round-trips, grouped by owner — the demotion signal that exists
        on a real pod, where the local flight recorder's records all
        carry one host id (cross-host flight tables only appear in
        post-hoc journal merges or a shared recorder). An owner whose
        serves own the slowest decile of the requester's recent
        transfers is a straggler from where this host stands."""
        with self._lock:
            samples = list(self._transfer_ns)
        if len(samples) < 16:
            return []  # too few round-trips to call anyone slow
        durs = sorted(ns for _, ns in samples)
        k = max(1, len(durs) // 10)
        cut = durs[-k]
        tail_total = sum(1 for _, ns in samples if ns >= cut)
        rows = []
        for owner in {o for o, _ in samples}:
            mine = [ns for o, ns in samples if o == owner]
            rows.append({
                "host": owner,
                "count": len(mine),
                "p99_ms": max(mine) / 1e6,
                "tail_share": (
                    sum(1 for ns in mine if ns >= cut) / tail_total
                ),
            })
        return rows

    def _note_demotion(self, event: str, host: int) -> None:
        if self._flight_ring is None:
            return
        op = self._flight_ring.begin(
            f"coop/{event}/host{host}", "", install=False, kind="coop"
        )
        op.note("coop", event=event, host=host)
        op.finish(0)

    def maybe_refresh_demotions(self, flight) -> None:
        """Rate-limited live demotion pass (the workload calls this per
        step; the scan only runs every ``demote_interval_s``). Two
        signal sources, slow sets unioned: the recorder's per-host
        straggler table (meaningful when the recorder holds multi-host
        records — the hermetic threaded pod, a shared-journal merge) and
        this host's own per-owner peer transfer round-trips
        (:meth:`_local_transfer_rows` — the signal a real pod host has
        locally). A host slow by either measure leaves the ring; hosts
        clean in both are restored."""
        now = self._clock()
        if now - self._last_demote_check < self._demote_interval_s:
            return
        self._last_demote_check = now
        from tpubench.obs.flight import straggler_attribution

        slow = self._slow_hosts_from_rows(
            straggler_attribution(flight.records(), by="host")
        )
        slow |= self._slow_hosts_from_rows(self._local_transfer_rows())
        self._apply_slow_set(slow)

    # ---------------------------------------------------------- lifecycle --
    def close(self) -> None:
        self._closed = True
        if self._channel is not None:
            self._channel.close()

    def stats(self) -> dict:
        with self._lock:
            requests = self.peer_requests
            transfer = (
                summarize_ns(np.asarray(
                    [ns for _, ns in self._transfer_ns], dtype=np.int64
                ))
                if self._transfer_ns else None
            )
            return {
                "enabled": self._enabled,
                "host_id": self.host_id,
                "hosts": len(self.ring.hosts),
                "active_hosts": len(self.ring.active_hosts),
                "demoted_hosts": sorted(self.ring.demoted),
                "peer_requests": requests,
                "peer_hits": self.peer_hits,
                "peer_misses": self.peer_misses,
                "peer_hit_ratio": (
                    self.peer_hits / requests if requests else None
                ),
                "peer_bytes": self.peer_bytes,
                "peer_serves": self.peer_serves,
                "peer_served_bytes": self.peer_served_bytes,
                "serve_errors": self.serve_errors,
                "budget_rejects": self.budget_rejects,
                "peer_budget_bytes": self._budget,
                "pod_coalesced": self.pod_coalesced,
                "origin_fetches": self.origin_fetches,
                "origin_bytes": self.origin_bytes,
                "owner_fetches": self.owner_fetches,
                "serve_origin_bytes": self.serve_origin_bytes,
                # What a per-host cache would have pulled from origin:
                # every peer hit would have been this host's own origin
                # fetch, while serve-triggered owner fetches would not
                # exist at all (their bytes already appear in the
                # requester's peer_bytes — counting both would inflate
                # the saved-% headline). A serve-fetched chunk the
                # owner LATER consumes from cache makes this estimate
                # conservative: the baseline would have fetched it.
                "per_host_origin_estimate_bytes": (
                    self.origin_bytes - self.serve_origin_bytes
                    + self.peer_bytes
                ),
                "demotions": self.demotions,
                "restores": self.restores,
                "handoff_out_chunks": self.handoff_out_chunks,
                "handoff_out_bytes": self.handoff_out_bytes,
                "handoff_in_chunks": self.handoff_in_chunks,
                "handoff_in_bytes": self.handoff_in_bytes,
                "handoff_rejects": self.handoff_rejects,
                "transfer_p50_ms": transfer.p50_ms if transfer else None,
                "transfer_p99_ms": transfer.p99_ms if transfer else None,
            }


# Shared-fabric broker slot: a membership-aware fabric (the elastic
# serve harness, an embedding test pod) registers its broker here so
# coop_from_config can build a MULTI-host loopback membership whose
# peers are actually reachable. One process, one pod fabric — a module
# slot, not a registry keyed by name.
_SHARED_BROKER: list = []


def register_shared_broker(broker: Optional[LoopbackBroker]) -> None:
    """Install (or, with None, clear) the process's shared pod broker.
    While installed, loopback multi-host memberships in
    :func:`coop_from_config` attach to it instead of failing."""
    _SHARED_BROKER[:] = [] if broker is None else [broker]


def shared_broker() -> Optional[LoopbackBroker]:
    return _SHARED_BROKER[0] if _SHARED_BROKER else None


def coop_from_config(cfg, cache: ChunkCache, origin_fetch,
                     *, pool=None, meter=None, flight=None, channel=None):
    """Build the run's :class:`CoopCache` from ``cfg.coop`` (None when
    the plane is off). Membership defaults to the dist topology
    (``num_processes`` hosts, this process's id); the channel defaults
    to loopback (a single-process pod degenerates to owner-local fetches
    with zero routing overhead), ``coop.channel="ici"`` rides
    :class:`tpubench.dist.peer.IciPeerChannel` over the pod mesh."""
    cc = getattr(cfg, "coop", None)
    if cc is None or not cc.enabled:
        return None
    n_hosts = cc.hosts or cfg.dist.num_processes
    host_id = cc.host_id if cc.host_id >= 0 else cfg.dist.process_id
    if channel is None:
        if cc.channel == "ici":
            from tpubench.dist.peer import IciPeerChannel

            channel = IciPeerChannel(host_id=host_id)
        else:
            # Loopback + multi-host: only legal over a SHARED broker (a
            # membership-aware fabric registered one for this process).
            # A private broker spans exactly this process, so an N-host
            # membership over it would route most misses at peers that
            # can never answer — with elastic membership in the picture
            # that silent degrade is a measurement lie (the run claims
            # an N-host pod and measures a pod of one), so it is now a
            # hard error instead of a warning-and-collapse.
            broker = shared_broker()
            if n_hosts > 1 and broker is None:
                raise SystemExit(
                    f"coop: loopback channel cannot reach the other "
                    f"{n_hosts - 1} host(s) from process {host_id} — a "
                    "multi-host loopback membership needs a shared pod "
                    "fabric (register_shared_broker / the elastic serve "
                    "harness); on a real pod use --coop-channel ici"
                )
            if broker is None:
                broker = LoopbackBroker()
            channel = LoopbackChannel(broker, host_id)
    ring = HashRing(
        range(n_hosts) if n_hosts >= 1 else [host_id], vnodes=cc.vnodes
    )
    coop = CoopCache(
        cache,
        host_id=host_id,
        ring=ring,
        channel=channel,
        origin_fetch=origin_fetch,
        pool=pool,
        meter=meter,
        enabled=True,
        peer_budget_bytes=cc.peer_budget_bytes,
        demote_share=cc.demote_share,
        demote_interval_s=cc.demote_interval_s,
        retry_cfg=cfg.transport.retry,
        flight_ring=flight.worker("coop") if flight is not None else None,
        flight_recorder=flight,
    )
    broker = getattr(channel, "_broker", None)
    if broker is not None:
        broker.register(host_id, coop.serve, accept=coop.accept_handoff)
    return coop


# ------------------------------------------------------------- simulation ---


def zipf_plan(*args, **kwargs):
    """Promoted to :func:`tpubench.workloads.arrivals.zipf_plan` (the
    one popularity-law definition serve and the coop sim share); this
    re-export keeps the coop surface stable. Imported lazily so the
    pipeline package never depends on workloads at import time."""
    from tpubench.workloads.arrivals import zipf_plan as _zp

    return _zp(*args, **kwargs)


def run_coop_sim(
    *,
    n_hosts: int = 2,
    n_objects: int = 4,
    object_bytes: int = 2 * MB,
    chunk_bytes: int = 256 * 1024,
    accesses_per_host: int = 64,
    cache_bytes: int = 64 * MB,
    alpha: float = 1.2,
    seed: int = 0,
    coop: bool = True,
    slab_pool: bool = False,
    peer_budget_bytes: int = 0,
    host_delay_s: Optional[dict] = None,
    plan: Optional[list] = None,
) -> dict:
    """Hermetic multi-"host" pod simulation: N threaded hosts over one
    shared fake origin and a loopback peer transport, each walking its
    own Zipf-hot access sequence drawn from the SAME hot set. This is
    the coop-vs-per-host A/B harness behind the acceptance test and the
    bench's ``coop_cache`` cell — ``coop=False`` runs the identical
    machinery with routing disabled (the per-host-cache baseline), so
    the delta is the cooperation, not incidental code differences.

    ``plan`` overrides the per-host Zipf sequences with ONE shared
    access sequence every host walks — the N-hosts-read-overlapping-
    shards shape of a replicated checkpoint restore, where cooperation
    turns N× origin traffic into ~1×.

    Returns the pod scorecard: ``origin_bytes_per_pod``, per-chunk
    origin fetch counts (the pod-wide single-flight proof), pod/peer
    hit ratios, and per-host stats."""
    from tpubench.storage.fake import FakeBackend

    prefix = "coop/file_"
    backend = FakeBackend.prepopulated(
        prefix=prefix, count=n_objects, size=object_bytes
    )
    objects = backend.list(prefix)
    # Per-chunk origin fetch ledger: the exactly-once assertion's source.
    fetch_counts: dict[ChunkKey, int] = {}
    ledger_lock = threading.Lock()
    ring = HashRing(range(n_hosts))
    broker = LoopbackBroker()
    hosts: list[dict] = []
    for h in range(n_hosts):
        pool = (
            SlabPool(chunk_bytes, 64, use_native=False) if slab_pool else None
        )
        meter = CopyMeter()
        cache = ChunkCache(cache_bytes)

        def origin_fetch(key: ChunkKey, _pool=pool, _meter=meter):
            from tpubench.pipeline.prefetch import fetch_chunk

            with ledger_lock:
                fetch_counts[key] = fetch_counts.get(key, 0) + 1
            return fetch_chunk(backend, key, pool=_pool, meter=_meter)

        cc = CoopCache(
            cache,
            host_id=h,
            ring=ring,
            channel=LoopbackChannel(broker, h),
            origin_fetch=origin_fetch,
            pool=pool,
            meter=meter,
            enabled=coop,
            peer_budget_bytes=peer_budget_bytes,
        )
        broker.register(
            h, cc.serve,
            delay_s=(host_delay_s or {}).get(h, 0.0),
        )
        host_plan = list(plan) if plan is not None else zipf_plan(
            objects, chunk_bytes, accesses_per_host,
            alpha=alpha, seed=seed * 1000 + h,
        )
        hosts.append({
            "coop": cc, "cache": cache, "pool": pool, "meter": meter,
            "plan": host_plan, "error": None,
        })

    def run_host(entry: dict) -> None:
        cc: CoopCache = entry["coop"]
        try:
            for key in entry["plan"]:
                payload = cc.cache.get_or_fetch(
                    key, lambda k=key: cc.fetch(k)
                )
                release_payload(payload)
        except Exception as exc:  # noqa: BLE001 — surfaced below
            # Exception, not BaseException (the Ctrl-C rule): a
            # KeyboardInterrupt on a sim host thread should unwind the
            # sim, not masquerade as a per-host fetch error.
            entry["error"] = f"{type(exc).__name__}: {exc}"

    threads = [
        threading.Thread(target=run_host, args=(e,), name=f"coop-host-{i}")
        for i, e in enumerate(hosts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    per_host = []
    agg = {
        "origin_fetches": 0, "origin_bytes": 0, "peer_requests": 0,
        "peer_hits": 0, "peer_misses": 0, "peer_bytes": 0,
        "pod_coalesced": 0, "budget_rejects": 0,
        "hits": 0, "misses": 0, "coalesced": 0,
    }
    copies_ok = True
    errors = []
    for e in hosts:
        cc, cache = e["coop"], e["cache"]
        s = cc.stats()
        cs = cache.stats()
        cp = e["meter"].stats()
        if e["pool"] is not None:
            cache.close()
            e["pool"].close()
            cpb = cp.get("copies_per_byte")
            if cpb is not None and cpb > 1.0 + 1e-9:
                copies_ok = False
        per_host.append({"coop": s, "cache": cs, "copies": cp})
        if e["error"]:
            errors.append(e["error"])
        for k in ("origin_fetches", "origin_bytes", "peer_requests",
                  "peer_hits", "peer_misses", "peer_bytes",
                  "pod_coalesced", "budget_rejects"):
            agg[k] += s[k]
        for k, ck in (("hits", "hits"), ("misses", "misses"),
                      ("coalesced", "coalesced")):
            agg[k] += cs[ck]
    lookups = agg["hits"] + agg["misses"] + agg["coalesced"]
    unique = len(fetch_counts)
    return {
        "n_hosts": n_hosts,
        "coop": coop,
        "accesses_per_host": accesses_per_host,
        "origin_bytes_per_pod": agg["origin_bytes"],
        "origin_fetches_per_pod": agg["origin_fetches"],
        "unique_chunks_fetched": unique,
        "max_origin_fetches_per_chunk": (
            max(fetch_counts.values()) if fetch_counts else 0
        ),
        "pod_hit_ratio": (agg["hits"] / lookups) if lookups else None,
        "peer_hit_ratio": (
            agg["peer_hits"] / agg["peer_requests"]
            if agg["peer_requests"] else None
        ),
        "peer_bytes": agg["peer_bytes"],
        "peer_hits": agg["peer_hits"],
        "peer_misses": agg["peer_misses"],
        "pod_coalesced": agg["pod_coalesced"],
        "budget_rejects": agg["budget_rejects"],
        "backend_opens": backend.open_count,
        "copies_per_byte_ok": copies_ok,
        "errors": errors,
        "per_host": per_host,
    }
