"""Readahead prefetcher: walk the workload's future access plan and warm
the chunk cache ahead of the consumer.

The access plan is the ordered list of :class:`~tpubench.pipeline.cache.
ChunkKey`\\ s the workload will consume (train-ingest knows its epoch
schedule up front — the property real input pipelines exploit). The
prefetcher keeps a bounded readahead window ``[cursor, cursor+depth)``
scheduled on a small worker pool; reads go through the ordinary
``open_backend`` stack, so hedging, the stall watchdog, the circuit
breaker and retry all compose underneath readahead exactly as they do
under demand reads.

Priority is plan order (a min-heap on plan index): the next-needed chunk
is always fetched before deeper readahead, so a slow backend degrades to
"barely ahead of the consumer", never to "busy fetching step N+8 while
step N+1 starves". Two safety valves bound memory:

* ``readahead_bytes`` — scheduled + cached-but-unconsumed prefetched
  bytes never exceed it;
* cancel-on-eviction — when the cache reports prefetched-unused bytes
  being evicted (budget thrash: readahead outran the cache), the
  effective depth halves, creeping back up one chunk per thrash-free
  advance. Queued entries behind the consumer's cursor are cancelled on
  every advance.

Demand misses are NOT queued here — the consumer fetches them on its own
thread through the cache's single-flight path, which coalesces with any
in-flight prefetch of the same chunk (so a demand read never waits behind
pool scheduling, and a half-done prefetch is joined, not duplicated).
"""

from __future__ import annotations

import heapq
import threading
from typing import Callable, Optional, Sequence

from tpubench.mem.slab import CopyMeter, SlabPool, release_payload
from tpubench.obs import flight as _flight
from tpubench.pipeline.cache import ChunkCache, ChunkKey
from tpubench.storage.base import StorageError


def _stream_into(backend, key: ChunkKey, mv: memoryview) -> None:
    """Stream ``key``'s exact byte range through the backend stack into
    caller memory (slab or bytearray — the ONE read shape both A/B arms
    and both the demand and prefetch paths measure).

    When the transport surfaces the served object's generation
    (``reader.generation`` — the fake backend and the h1.1 JSON-API
    HTTP client do, from ``x-goog-generation``; forwarded through every
    wrapper reader), a mismatch with the plan's keyed generation is a
    hard error: the object was overwritten after the plan was built,
    and caching these bytes under the stale key would poison the cache
    with content that doesn't match its key. The caller's remedy is to
    rebuild the plan (re-stat), not to retry. Transports that don't
    surface response headers (the native h2/receive engine paths) read
    ``generation=None`` = *unknown*: enforcement degrades to plan-build
    keying there — a documented scope line, not a silent guarantee."""
    reader = backend.open_read(key.object, start=key.start, length=key.length)
    got = 0
    try:
        while got < key.length:
            n = reader.readinto(mv[got:])
            if n <= 0:
                break
            got += n
    finally:
        fb = getattr(reader, "first_byte_ns", None)
        if fb:
            _flight.note_phase("first_byte", fb)
        reader.close()
    gen = getattr(reader, "generation", None)
    if gen and key.generation and gen != key.generation:
        raise StorageError(
            f"{key.object}: generation changed under the plan "
            f"({key.generation} -> {gen}); rebuild the access plan",
            transient=False,
        )
    if got != key.length:
        raise IOError(
            f"{key.object} [{key.start}:+{key.length}]: short chunk read "
            f"{got}/{key.length}"
        )


def read_chunk(backend, key: ChunkKey,
               meter: Optional[CopyMeter] = None) -> bytes:
    """The legacy ``bytes`` chunk read (the A/B baseline arm): wire →
    scratch bytearray (one write), then a full ``bytes`` materialization
    (a second write of every byte) — exactly the copy tax the slab path
    (:func:`fetch_chunk`) exists to delete."""
    buf = bytearray(key.length)
    _stream_into(backend, key, memoryview(buf))
    if meter is not None:
        meter.landed(key.length)
        meter.copied(key.length)  # the bytes() below re-writes every byte
    return bytes(buf)


def fetch_chunk(backend, key: ChunkKey, pool: Optional[SlabPool] = None,
                meter: Optional[CopyMeter] = None):
    """One chunk fetch, zero-copy when a slab pool is given: the backend
    stack ``readinto``\\ s the wire bytes straight into a leased slab and
    the LEASE is the payload — the cache stores it, the consumer stages
    its view in place, and nothing re-copies. Returns the caller-owned
    payload (``SlabLease`` with refcount 1, or ``bytes`` without a
    pool); any failure mid-chunk releases the lease back to the pool
    before propagating — chaos faults must never leak slabs."""
    if pool is None:
        return read_chunk(backend, key, meter=meter)
    lease = pool.lease(key.length)
    if lease.overflow:
        # Pool-pressure breadcrumb on the read's flight record: sustained
        # overflow means --pool-slabs is undersized for the working set.
        _flight.annotate("slab", event="overflow")
    try:
        _stream_into(backend, key, lease.view())
    except BaseException:
        lease.release()
        raise
    if meter is not None:
        meter.landed(key.length)  # wire → slab: the one and only write
    return lease


class Prefetcher:
    """Plan-walking readahead over a :class:`ChunkCache` (module doc)."""

    def __init__(
        self,
        backend,
        cache: ChunkCache,
        plan: Sequence[ChunkKey],
        *,
        workers: int = 2,
        depth: int = 8,
        byte_budget: int = 0,
        transport: str = "",
        pool: Optional[SlabPool] = None,
        meter: Optional[CopyMeter] = None,
        max_workers: int = 0,
        fetch_fn: Optional[Callable[[ChunkKey], object]] = None,
        owners: Optional[Sequence[Optional[str]]] = None,
        owner_budgets: Optional[dict] = None,
    ):
        self._backend = backend
        self._cache = cache
        self._pool = pool
        self._meter = meter
        # Routed miss fetch (the cooperative cache's peer-first path):
        # when given, readahead misses resolve through it instead of a
        # direct origin read — the prefetcher warms the cache through
        # the SAME owner-routing/single-flight the demand path uses.
        self._fetch_fn = fetch_fn
        self._plan = list(plan)
        # QoS (serve plane): owners[i] tags plan[i] with its tenant
        # class; owner_budgets bounds each class's scheduled+in-flight
        # prefetch bytes — one greedy class can't monopolize the
        # readahead window. Over-budget items are SKIPPED (not a window
        # barrier): other classes' items behind them still schedule.
        self._owners = list(owners) if owners is not None else None
        if self._owners is not None and len(self._owners) != len(self._plan):
            raise ValueError(
                f"prefetch owners length {len(self._owners)} != plan "
                f"length {len(self._plan)}"
            )
        self._owner_budgets = dict(owner_budgets or {})
        self._owner_out: dict[str, int] = {}
        self.owner_budget_skips = 0
        # Indices already counted as budget-skipped: _fill_locked
        # re-scans the window on every advance()/completion, so without
        # this a single persistently-over-budget item would re-count on
        # every pass (the per-tick re-count bug class).
        self._owner_skip_seen: set[int] = set()
        self._depth = max(0, depth)
        self._depth_effective = self._depth
        self._budget = max(0, byte_budget)
        self._transport = transport
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._heap: list[tuple[int, ChunkKey]] = []
        self._scheduled: set[int] = set()  # queued or fetching
        self._cursor = 0
        self._inflight_bytes = 0
        self._stop = False
        self._wasted_seen = 0
        # Counters (the extra["pipeline"]["prefetch"] stamp).
        self.issued = 0
        self.completed = 0
        self.cancelled = 0
        self.skipped = 0  # already cached/in-flight at pop time
        self.errors = 0
        self.last_error: Optional[str] = None
        self.depth_clamps = 0  # cancel-on-eviction engagements
        # Flight rings are bound HERE, on the constructing thread, while
        # the run's recorder activation is known-live — a worker thread
        # resolving the ambient recorder at its own start time could race
        # the activation scope and silently record nothing.
        # max_workers pre-spawns a larger pool with only `workers` of it
        # ACTIVE (the rest park on the condvar): the tune controller's
        # prefetch_workers knob then grows/shrinks the live set without
        # ever spawning mid-run.
        n_active = max(1, workers) if self._depth else 0
        n_threads = max(n_active, max_workers) if self._depth else 0
        self._active_workers = n_active
        self._threads = [
            threading.Thread(
                target=self._worker,
                args=(i, _flight.active_worker(f"prefetch-{i}")),
                name=f"prefetch-{i}", daemon=True,
            )
            for i in range(n_threads)
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------- control --
    def advance(self, pos: int) -> None:
        """The consumer reached plan position ``pos``: drop stale queue
        entries, apply the eviction thrash-guard, and top the readahead
        window back up (within the byte budget)."""
        if not self._depth:
            return
        with self._cond:
            self._cursor = max(self._cursor, pos)
            # Cancel-on-eviction: prefetched-unused bytes being evicted
            # means readahead outran the cache budget — halve the window.
            wasted = self._cache.prefetch_wasted_bytes
            if wasted > self._wasted_seen:
                self._wasted_seen = wasted
                if self._depth_effective > 1:
                    self._depth_effective = max(1, self._depth_effective // 2)
                    self.depth_clamps += 1
            elif self._depth_effective < self._depth:
                self._depth_effective += 1
            self._fill_locked()
            self._cond.notify_all()

    def _owner_of(self, i: int) -> Optional[str]:
        return self._owners[i] if self._owners is not None else None

    def _sched_add_locked(self, i: int, key: ChunkKey) -> None:
        self._scheduled.add(i)
        o = self._owner_of(i)
        if o is not None:
            self._owner_out[o] = self._owner_out.get(o, 0) + key.length

    def _sched_drop_locked(self, i: int) -> None:
        """A scheduled item left the system (fetched, cancelled, or
        stale): release its owner's outstanding-byte charge with its
        scheduled-set slot — the two must move together or a class's
        budget slowly leaks shut."""
        if i in self._scheduled:
            self._scheduled.discard(i)
            o = self._owner_of(i)
            if o is not None:
                left = self._owner_out.get(o, 0) - self._plan[i].length
                if left > 0:
                    self._owner_out[o] = left
                else:
                    self._owner_out.pop(o, None)

    def _fill_locked(self) -> None:
        hi = min(len(self._plan), self._cursor + self._depth_effective)
        for i in range(self._cursor, hi):
            if i in self._scheduled:
                continue
            key = self._plan[i]
            if self._budget and (
                self._outstanding_locked() + key.length > self._budget
            ):
                break
            if self._cache.contains(key):
                # Residency first: an already-cached item was never a
                # budget casualty and must not count as one.
                continue
            o = self._owner_of(i)
            if o is not None:
                b = self._owner_budgets.get(o)
                if b and self._owner_out.get(o, 0) + key.length > b:
                    # Per-class budget: skip, don't break — the window
                    # keeps filling with OTHER classes' items. Each
                    # plan item counts as ONE skip no matter how many
                    # re-scans defer it.
                    if i not in self._owner_skip_seen:
                        self._owner_skip_seen.add(i)
                        self.owner_budget_skips += 1
                    continue
            self._sched_add_locked(i, key)
            heapq.heappush(self._heap, (i, key))

    def reclamp(self, depth: Optional[int] = None,
                byte_budget: Optional[int] = None) -> None:
        """Live depth/byte-budget re-clamp (the tune controller's
        readahead actuation — no restart). A shrink drops QUEUED entries
        beyond the new window (counted as cancelled; in-flight fetches
        complete and land through the normal cache-insert accounting, so
        the resident-unused counter stays exact — nothing is stranded);
        growth takes effect immediately by re-filling the window."""
        if not self._depth:
            return  # constructed cold (no worker threads): knob is inert
        with self._cond:
            if depth is not None:
                depth = max(1, int(depth))
                if depth < self._depth:
                    hi = self._cursor + depth
                    keep = [(i, k) for i, k in self._heap if i < hi]
                    for i, _ in self._heap:
                        if i >= hi:
                            self._sched_drop_locked(i)
                            self.cancelled += 1
                    self._heap = keep
                    heapq.heapify(self._heap)
                grow = depth > self._depth
                self._depth = depth
                if grow:
                    # A commanded grow resets the thrash clamp: the
                    # controller asked for the window NOW; eviction
                    # waste re-clamps it if the cache disagrees.
                    self._depth_effective = depth
                else:
                    # Shrink: the clamp (if tighter) survives.
                    self._depth_effective = max(
                        1, min(self._depth_effective, depth)
                    )
            if byte_budget is not None:
                self._budget = max(0, int(byte_budget))
            self._fill_locked()
            self._cond.notify_all()

    def set_workers(self, n: int) -> None:
        """Live worker fan-out: activate the first ``n`` of the
        pre-spawned pool (parked threads resume on the condvar; threads
        beyond the active count finish their current fetch, then park)."""
        with self._cond:
            self._active_workers = max(1, min(int(n), len(self._threads)))
            self._cond.notify_all()

    @property
    def active_workers(self) -> int:
        return self._active_workers

    def _outstanding_locked(self) -> int:
        # prefetch_resident_unused is the cache's directly-maintained
        # count (not a derived identity over insert/use/waste counters,
        # which drop paths like stale-rejects would silently skew).
        queued = sum(k.length for _, k in self._heap)
        return (
            max(0, self._cache.prefetch_resident_unused)
            + self._inflight_bytes + queued
        )

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for t in self._threads:
            t.join()

    # -------------------------------------------------------------- worker --
    def _worker(self, widx: int, wf) -> None:
        while True:
            with self._cond:
                # Parked workers (widx >= the live fan-out) wait without
                # popping; set_workers() wakes them when the controller
                # grows the pool back.
                while (not self._heap or widx >= self._active_workers) \
                        and not self._stop:
                    self._cond.wait()
                if self._stop:
                    # Shutdown cancels queued readahead — close() must
                    # not sit through deep-window fetches nobody will
                    # ever consume.
                    while self._heap:
                        i, _ = heapq.heappop(self._heap)
                        self._sched_drop_locked(i)
                        self.cancelled += 1
                    return
                idx, key = heapq.heappop(self._heap)
                if idx < self._cursor:
                    self._sched_drop_locked(idx)
                    self.cancelled += 1
                    continue
                self._inflight_bytes += key.length
                self.issued += 1
            op = None
            try:
                if self._cache.contains(key):
                    # Already cached or in flight: nothing to do, and no
                    # flight record either — a zero-byte ~0 ms "read"
                    # would dilute every percentile downstream (the
                    # chaos scorecard sums kind="read" records).
                    with self._lock:
                        self.skipped += 1
                    continue
                op = (
                    wf.begin(key.object, self._transport)
                    if wf is not None else None
                )
                if op is not None:
                    op.mark("prefetch_issue")
                data, source = self._cache.get_or_fetch_info(
                    key,
                    (lambda: self._fetch_fn(key))
                    if self._fetch_fn is not None
                    else lambda: fetch_chunk(
                        self._backend, key,
                        pool=self._pool, meter=self._meter,
                    ),
                    origin="prefetch", consumer=False,
                    owner=self._owner_of(idx),
                )
                if source == "fetched":
                    nbytes = len(data)
                    # The worker's own (leaser) reference: the cache took
                    # its reference at insert — the prefetcher does not
                    # consume, so it lets go here. A refused insert
                    # (stale generation / oversize) retires the slab
                    # right now instead of leaking it.
                    release_payload(data)
                    with self._lock:
                        self.completed += 1
                    if op is not None:
                        op.mark("body_complete")
                        op.finish(nbytes)
                else:
                    # A demand read claimed the chunk between the
                    # contains() probe and the fetch (hit or joined
                    # in-flight): that read's record carries the bytes
                    # and the wait — appending one here would both
                    # double-count and dilute percentiles. Drop the op.
                    with self._lock:
                        self.skipped += 1
                    if op is not None:
                        op.abandon()
            except Exception as exc:  # noqa: BLE001 — best-effort layer
                # Prefetch is advisory: the error is recorded, the chunk
                # stays uncached, and the demand path (with its own retry
                # stack) surfaces any real failure to the workload.
                with self._lock:
                    self.errors += 1
                    self.last_error = f"{type(exc).__name__}: {exc}"
                if op is not None:
                    op.finish(error=exc)
            finally:
                with self._cond:
                    self._inflight_bytes -= key.length
                    self._sched_drop_locked(idx)

    # --------------------------------------------------------------- stats --
    def stats(self) -> dict:
        used = self._cache.prefetch_used_bytes
        # Everything prefetched that never served a consumer is waste,
        # whatever dropped it: LRU eviction, never-cached (oversize/
        # stale-reject), generation invalidation, or still sitting
        # unused at end of run.
        wasted = (
            self._cache.prefetch_wasted_bytes
            + self._cache.prefetch_dropped_bytes
            + self._cache.prefetch_invalidated_bytes
            + self._cache.unused_prefetched_bytes()
        )
        denom = used + wasted
        return {
            "depth": self._depth,
            "depth_effective": self._depth_effective,
            "workers": self._active_workers,
            "workers_max": len(self._threads),
            "issued": self.issued,
            "completed": self.completed,
            "cancelled": self.cancelled,
            "skipped": self.skipped,
            "errors": self.errors,
            "last_error": self.last_error,
            "depth_clamps": self.depth_clamps,
            "owner_budget_skips": self.owner_budget_skips,
            "prefetched_bytes": self._cache.prefetch_inserted_bytes,
            "used_bytes": used,
            "wasted_bytes": wasted,
            "efficiency": (used / denom) if denom else None,
        }
