"""Record/replay + regression plane.

Every serve-plane run journals enough to be re-driven: the arrival
timeline, the object population, the fault timeline, the membership
plan and the tenant/class map. This package closes that loop:

* ``bundle``  — the portable, versioned replay bundle
  (``tpubench record``): distilled from a run's flight journal,
  gzip-JSON, byte-deterministic for a given run;
* ``driver``  — ``tpubench replay <bundle>``: re-drives a bundle's
  scenario through ANY transport/cache/QoS/coop/membership
  configuration (arrivals ride the ``trace`` schedule kind, faults
  re-arm via FaultPlan, membership entries feed the elastic pod) and
  stamps the replay-vs-original scorecard diff;
* ``gate``    — the ``tpubench report --fail-on <metric><op><threshold>``
  exit-code contract that turns any diff into a CI gate.

Golden bundles live under ``scenarios/`` and are gated by a bench.py
replay cell — every incident run becomes a permanent named scenario.
"""

from tpubench.replay.bundle import (  # noqa: F401
    BUNDLE_FIELDS,
    BUNDLE_FORMAT,
    BUNDLE_SCHEMA,
    bundle_from_stamp,
    config_fingerprint,
    distill_baseline,
    format_replay_block,
    journal_replay_stamp,
    load_bundle,
    record_bundle,
    scorecard_diff,
    validate_bundle,
    write_bundle,
)
