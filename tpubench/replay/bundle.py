"""Replay bundles — a run's scenario as a portable, versioned artifact.

A **bundle** (schema ``tpubench-bundle/1``, gzip JSON) is the distilled
scenario of one serve-plane run: the arrival timeline (virtual seconds),
the object population with sizes and generations, the unscaled fault
timeline, the membership plan, the tenant/class map, and the system-half
config fingerprint of the run that produced it — plus the original
run's ``baseline`` scorecard so a replay can diff against it offline.

Two disciplines make bundles regression-grade:

* **determinism** — ``write_bundle`` serializes with sorted keys, no
  timestamps, and a zeroed gzip mtime, so record → replay → record is
  byte-identical (the PR-12 discipline applied to the new plane); the
  schedule itself replays exactly because every serve RNG stream depends
  only on seeds and counts, never on the arrival kind;
* **versioned refusal** — journals stamp ``journal_schema``, bundles
  stamp ``format`` + the source journal's schema; record/replay refuse
  anything newer than they understand instead of silently rebuilding an
  unfaithful scenario.

This module is jax-free and import-light (``tpubench record`` and
``tpubench report`` run on coordinator VMs that never touch a device);
the run-driving half lives in :mod:`tpubench.replay.driver`.
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
from typing import Iterable, Optional, Sequence

BUNDLE_FORMAT = "tpubench-bundle/1"

# Version of the bundle CONTENT contract (what journal_replay_stamp
# promises); the format string above is the envelope. A reader refuses
# stamps/bundles newer than this rather than misparse them.
BUNDLE_SCHEMA = 1

# The bundle field catalog — the drift-guard surface (analysis/drift.py
# ``bundle-schema``): every field a bundle carries, with its meaning.
# README's "Record & replay" schema table must list exactly these.
BUNDLE_FIELDS = {
    "format": "bundle envelope version (tpubench-bundle/1)",
    "name": "scenario name (CLI --name, or derived from the output path)",
    "workload": "workload the bundle replays (serve | drill)",
    "journal_schema": "journal_schema of the source flight journal",
    "config_fingerprint": "system-half config fingerprint of the source run",
    "arrivals": "virtual arrival timestamps, seconds from run start",
    "rate_rps": "offered load the source run was driven at",
    "duration_s": "virtual schedule length in seconds",
    "seed": "serve seed (tenant map + class assignment + Zipf streams)",
    "tenants": "synthetic tenant population size",
    "alpha": "Zipf popularity exponent over the shared chunk set",
    "chunk_bytes": "resolved request chunk size (serve.chunk_bytes or granule)",
    "classes": "priority class map (share/weight/deadline_ms/priority)",
    "objects": "object population: sorted [name, size, generation] triples",
    "object_prefix": "object name prefix the population lives under",
    "bucket": "bucket the chunk keys are scoped to",
    "fault": "unscaled fault plan (FaultConfig fields incl. phases)",
    "membership": "elastic pod plan: hosts, timeline, resize_window_s",
    "drill": "incident-drill plan + checkpoint shape + drill baseline "
             "(null for serve bundles)",
    "baseline": "the source run's distilled scorecard (the diff target)",
}

_REQUIRED = tuple(BUNDLE_FIELDS)


# ---------------------------------------------------------- fingerprint --


def _system_view(cfg_dict: dict) -> dict:
    """The SYSTEM half of a config — the knobs that shape how a scenario
    is served, not what the scenario is. Endpoint and fault are excluded
    (the endpoint is per-process ephemera; the fault plan is scenario,
    carried verbatim in the bundle), and only the serve knobs that are
    not scenario-owned count."""
    transport = dict(cfg_dict.get("transport") or {})
    transport.pop("endpoint", None)
    transport.pop("fault", None)
    serve = cfg_dict.get("serve") or {}
    return {
        "transport": transport,
        "pipeline": cfg_dict.get("pipeline"),
        "staging": cfg_dict.get("staging"),
        "coop": cfg_dict.get("coop"),
        "tune": cfg_dict.get("tune"),
        "serve_system": {
            k: serve.get(k)
            for k in (
                "workers", "qos", "admission_cap", "queue_limit",
                "readahead",
            )
        },
    }


def config_fingerprint(cfg_dict: dict) -> str:
    """Short stable digest of the system half of a config. Two runs with
    the same fingerprint served their scenario through the same stack —
    the replay scorecard's "identical config" precondition, and the
    A/B marker when a bundle is replayed under a different one."""
    payload = json.dumps(
        _system_view(cfg_dict), sort_keys=True, separators=(",", ":"),
    )
    return hashlib.blake2b(payload.encode(), digest_size=12).hexdigest()


# ------------------------------------------------------------- distilling --


def distill_baseline(
    serve_extra: dict,
    *,
    errors: int = 0,
    p99_ms: Optional[float] = None,
    membership: Optional[dict] = None,
) -> dict:
    """The replay-comparable core of a serve scorecard: the numbers a
    replay is judged against (and re-measures for itself). ``gold`` is
    the highest-priority class — the one QoS exists to protect."""
    classes = serve_extra.get("classes") or {}
    gold = min(
        classes.values(), key=lambda c: c.get("priority", 0)
    ) if classes else {}
    rewarm = None
    failovers = None
    if membership:
        rewarms = [
            ev.get("time_to_rewarm_s")
            for ev in membership.get("events", ())
            if ev.get("time_to_rewarm_s") is not None
        ]
        rewarm = max(rewarms) if rewarms else None
        failovers = membership.get("failovers")
    return {
        "arrivals": serve_extra.get("arrivals"),
        "completed": serve_extra.get("completed"),
        "shed": serve_extra.get("shed"),
        "errors": errors,
        "goodput_gbps": serve_extra.get("goodput_gbps"),
        "achieved_rps": serve_extra.get("achieved_rps"),
        "jain_fairness": serve_extra.get("jain_fairness"),
        "gold_slo": gold.get("slo_attainment"),
        "gold_p99_ms": gold.get("p99_ms"),
        "p99_ms": p99_ms,
        "rewarm_s": rewarm,
        "failovers": failovers,
    }


def journal_replay_stamp(
    cfg,
    schedule: Sequence,
    objects: Sequence,
    serve_extra: dict,
    *,
    rate_rps: float,
    membership: Optional[dict] = None,
    drill: Optional[dict] = None,
    errors: int = 0,
    p99_ms: Optional[float] = None,
    source: Optional[dict] = None,
) -> dict:
    """The ``replay`` block a serve run stamps into its flight journal —
    everything ``tpubench record`` needs to rebuild the run as a bundle.
    ``objects`` MUST be the same list the schedule was built over (the
    population, not a re-listing that might race a mutating backend);
    ``rate_rps`` is the EFFECTIVE offered load (sweep points override
    the config's). ``drill`` (from :func:`drill_replay_plan`) marks the
    run as an incident drill: the bundle carries the incident plan and
    checkpoint shape alongside the serve scenario, and replays through
    ``run_drill``. ``source`` is set by replay runs: the bundle identity
    they were driven from, so re-recording a replay reproduces the
    original bundle byte-for-byte."""
    sc = cfg.serve
    w = cfg.workload
    import dataclasses

    stamp = {
        "bundle_schema": BUNDLE_SCHEMA,
        "workload": "drill" if drill is not None else "serve",
        "scenario": {
            "arrivals": [float(r.arrival_s) for r in schedule],
            "rate_rps": float(rate_rps),
            "duration_s": float(sc.duration_s),
            "seed": int(sc.seed),
            "tenants": int(sc.tenants),
            "alpha": float(sc.alpha),
            "chunk_bytes": int(sc.chunk_bytes or w.granule_bytes),
            "classes": [dict(c) for c in sc.classes],
            "objects": sorted(
                [m.name, int(m.size), int(m.generation)] for m in objects
            ),
            "object_prefix": w.object_name_prefix,
            "bucket": w.bucket,
            "fault": dataclasses.asdict(cfg.transport.fault),
            "membership": {
                "hosts": int(sc.hosts),
                "timeline": [
                    [float(t0), float(t1), dict(spec)]
                    for t0, t1, spec in sc.membership_timeline
                ],
                "resize_window_s": float(sc.resize_window_s),
            },
            # Emitted unconditionally (None for serve) — the bundle
            # field catalog is a drift-guard surface, never optional.
            "drill": drill,
        },
        "baseline": distill_baseline(
            serve_extra, errors=errors, p99_ms=p99_ms,
            membership=membership,
        ),
        "fingerprint": config_fingerprint(cfg.to_dict()),
    }
    if source:
        stamp["source"] = dict(source)
    return stamp


def drill_replay_plan(cfg, drill_extra: dict,
                      save_interval_s: float) -> dict:
    """The drill half of a replay stamp: the incident plan (kill/join
    epochs, restore identity, save cadence), the checkpoint shape the
    run rebuilds deterministically (shard contents are
    ``shard_content``-derived, so only the SHAPE needs recording), and
    the distilled drill baseline a replay diffs against.
    ``save_interval_s`` is the EFFECTIVE interval (sweep points override
    the config's)."""
    dc, lc, sc = cfg.drill, cfg.lifecycle, cfg.serve
    return {
        "plan": {
            "kill_at_s": float(dc.kill_at_s),
            "join_at_s": float(dc.join_at_s),
            "victim": int(
                dc.victim if dc.victim >= 0 else sc.hosts - 1
            ),
            "restore_class": dc.restore_class,
            "restore_priority": int(dc.restore_priority),
            "restore_weight": float(dc.restore_weight),
            "restore_deadline_ms": float(dc.restore_deadline_ms),
            "restore_inflight": int(dc.restore_inflight),
            "restore_retries": int(dc.restore_retries),
            "restore_via_coop": bool(dc.restore_via_coop),
            "save_interval_s": float(save_interval_s),
            "delta_saves": bool(dc.delta_saves),
            "dirty_fraction": float(dc.dirty_fraction),
            "meta_rate_rps": float(dc.meta_rate_rps),
        },
        "checkpoint": {
            "objects": int(lc.objects),
            "object_bytes": int(lc.object_bytes),
            "part_bytes": int(lc.part_bytes),
            "prefix": lc.prefix,
            "seed": int(lc.seed),
            "meta_objects": int(lc.meta_objects),
            "meta_object_bytes": int(lc.meta_object_bytes),
        },
        "baseline": distill_drill(drill_extra),
    }


def distill_drill(drill_extra: dict) -> dict:
    """The replay-comparable core of a drill scorecard — the incident
    numbers a replayed drill is judged against."""
    d = drill_extra or {}
    rst = d.get("restore") or {}
    saves = d.get("saves") or {}
    amp = d.get("amplification") or {}
    slo = d.get("gold_slo") or {}
    return {
        "time_to_restore_s": rst.get("time_to_restore_s"),
        "time_to_rewarm_s": d.get("time_to_rewarm_s"),
        "restore_verified": rst.get("verified"),
        "shards_restored": rst.get("shards_restored"),
        "torn_rereads": rst.get("torn_rereads"),
        "forced_direct": rst.get("forced_direct"),
        "restore_errors": rst.get("errors"),
        "slo_restore_window": dict(slo.get("restore_window") or {}),
        "slo_steady": dict(slo.get("steady") or {}),
        "save_passes": saves.get("passes"),
        "save_uploaded_shards": saves.get("uploaded_shards"),
        "save_cas_conflicts": saves.get("cas_conflicts"),
        "save_bytes_uploaded": saves.get("bytes_uploaded"),
        "origin_amplification": amp.get("ratio"),
    }


def drill_diff(baseline: dict, replayed: dict) -> dict:
    """Drill replay-vs-original deltas, None-safe — the drill analogue
    of :func:`scorecard_diff` (which still covers the serve half)."""
    b, r = baseline or {}, replayed or {}
    slo_deltas = {}
    b_slo = b.get("slo_restore_window") or {}
    r_slo = r.get("slo_restore_window") or {}
    for cls in sorted(set(b_slo) & set(r_slo)):
        if b_slo[cls] is not None and r_slo[cls] is not None:
            slo_deltas[cls] = (r_slo[cls] - b_slo[cls]) * 100.0
    worst = min(slo_deltas.values()) if slo_deltas else None
    return {
        "time_to_restore_ratio": _ratio(
            r.get("time_to_restore_s"), b.get("time_to_restore_s")
        ),
        "verified_match": (
            bool(b.get("restore_verified"))
            == bool(r.get("restore_verified"))
        ),
        "restore_slo_delta_pts": slo_deltas,
        "worst_restore_slo_delta_pts": worst,
        "amplification_ratio": _ratio(
            r.get("origin_amplification"), b.get("origin_amplification")
        ),
        "save_pass_delta": (
            r["save_passes"] - b["save_passes"]
            if r.get("save_passes") is not None
            and b.get("save_passes") is not None else None
        ),
    }


def bundle_from_stamp(
    stamp: dict, *, name: str = "", journal_schema: int = 1,
) -> dict:
    """A bundle from a journal's ``replay`` stamp. A replay run's stamp
    carries ``source`` (the bundle it was driven from); its identity
    fields pass through so record(replay(record(run))) converges —
    re-recording a replay names, fingerprints and baselines the ORIGINAL
    scenario, not the replay of it."""
    src = stamp.get("source") or {}
    bundle = {
        "format": BUNDLE_FORMAT,
        "name": name or src.get("name") or "unnamed",
        "workload": stamp.get("workload", "serve"),
        "journal_schema": int(journal_schema),
        "config_fingerprint": (
            src.get("fingerprint") or stamp.get("fingerprint")
        ),
        "baseline": src.get("baseline") or stamp.get("baseline"),
    }
    bundle.update(stamp["scenario"])
    # Pre-drill stamps (older journals) have no drill key: rebuild them
    # as explicit serve bundles rather than missing-field refusals.
    bundle.setdefault("drill", None)
    return bundle


# ----------------------------------------------------------------- disk --


def _derive_name(path: str) -> str:
    base = os.path.basename(path)
    for ext in (".gz", ".tpb", ".json"):
        if base.endswith(ext):
            base = base[: -len(ext)]
    return base or "unnamed"


def write_bundle(bundle: dict, path: str) -> str:
    """Atomic, byte-deterministic bundle write: canonical JSON (sorted
    keys, no whitespace), gzip with a zeroed mtime and no embedded
    filename when the path says ``.gz`` — the same input bundle always
    produces the same bytes, which is what lets a golden bundle be
    checked in and diffed."""
    payload = json.dumps(
        bundle, sort_keys=True, separators=(",", ":"),
    ).encode()
    tmp = f"{path}.tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if path.endswith(".gz"):
        with open(tmp, "wb") as f:
            with gzip.GzipFile(
                filename="", mode="wb", fileobj=f, mtime=0,
            ) as gz:
                gz.write(payload)
    else:
        with open(tmp, "wb") as f:
            f.write(payload)
    os.replace(tmp, path)
    return path


def load_bundle(path: str) -> Optional[dict]:
    """Crash-tolerant bundle read (the ``load_snapshot`` degrade model):
    a missing, unreadable, empty, truncated or non-object bundle returns
    ``None`` with a one-line stderr warning instead of a traceback.
    Gzip is detected by magic bytes, not the filename. Semantic
    validation (format, schema, fields) is :func:`validate_bundle` —
    a WELL-FORMED bundle this build can't honor is a hard error there,
    not a silent skip here."""
    import sys

    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            raw = f.read()
        if raw[:2] == b"\x1f\x8b":
            raw = gzip.decompress(raw)
    except (OSError, EOFError, gzip.BadGzipFile) as e:
        print(f"warning: {path}: unreadable replay bundle ({e}), ignored",
              file=sys.stderr)
        return None
    text = raw.decode("utf-8", errors="replace")
    if not text.strip():
        print(f"warning: {path}: empty replay bundle, ignored",
              file=sys.stderr)
        return None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        print(
            f"warning: {path}: truncated/partial replay bundle "
            f"({e.msg} at char {e.pos}), ignored",
            file=sys.stderr,
        )
        return None
    if not isinstance(doc, dict):
        print(
            f"warning: {path}: replay bundle is not a JSON object "
            f"({type(doc).__name__}), ignored",
            file=sys.stderr,
        )
        return None
    return doc


def validate_bundle(bundle: dict, path: str) -> None:
    """Refuse a bundle replay cannot faithfully rebuild — wrong or newer
    envelope, missing fields, a source journal newer than this build, or
    fault fields this build's FaultConfig doesn't know. One-line
    SystemExit (the config-validation discipline), never a TypeError
    three layers deep in the driver."""
    fmt = bundle.get("format")
    if fmt != BUNDLE_FORMAT:
        hint = " (newer tpubench?)" if str(fmt).startswith(
            "tpubench-bundle/"
        ) else ""
        raise SystemExit(
            f"{path}: not a replay bundle (format={fmt!r}; expected "
            f"{BUNDLE_FORMAT!r}){hint}"
        )
    missing = [k for k in _REQUIRED if k not in bundle]
    if missing:
        raise SystemExit(
            f"{path}: replay bundle missing fields: {', '.join(missing)}"
        )
    if bundle.get("workload") not in ("serve", "drill"):
        raise SystemExit(
            f"{path}: bundle workload {bundle.get('workload')!r} is not "
            "replayable (serve and drill only)"
        )
    from tpubench.obs.flight import JOURNAL_SCHEMA

    js = bundle.get("journal_schema", 1)
    if isinstance(js, int) and js > JOURNAL_SCHEMA:
        raise SystemExit(
            f"{path}: bundle was recorded from journal_schema {js}; this "
            f"build understands <= {JOURNAL_SCHEMA} — refusing an "
            "unfaithful rebuild (upgrade tpubench)"
        )
    from tpubench.config import FaultConfig

    try:
        FaultConfig(**(bundle.get("fault") or {}))
    except TypeError as e:
        raise SystemExit(
            f"{path}: bundle fault plan has fields this build's "
            f"FaultConfig doesn't know ({e}) — newer bundle?"
        ) from None


def record_bundle(
    paths: Iterable[str], out_path: str, name: str = "",
) -> dict:
    """``tpubench record``: distill journals into a bundle on disk.
    Multiple paths must all stamp the SAME scenario (the per-host
    journals of one run); journals without a replay stamp (pre-replay
    builds, non-serve workloads) or newer than this build refuse loudly
    rather than fabricate a scenario."""
    from tpubench.obs.flight import JOURNAL_SCHEMA, load_journals

    paths = list(paths)
    docs = load_journals(paths)
    if not docs:
        raise SystemExit(
            "record: no readable flight journals among: "
            + ", ".join(paths)
        )
    stamp = None
    schema = 1
    for p, doc in zip(paths, docs):
        js = doc.get("journal_schema", 1)
        if isinstance(js, int) and js > JOURNAL_SCHEMA:
            raise SystemExit(
                f"record: {p}: journal_schema {js} is newer than this "
                f"build understands (<= {JOURNAL_SCHEMA}) — refusing to "
                "rebuild a scenario it can't be faithful to"
            )
        st = doc.get("replay")
        if st is None:
            raise SystemExit(
                f"record: {p}: no replay stamp in this journal (recorded "
                "by a pre-replay tpubench, or a workload the replay "
                "plane doesn't cover — serve runs stamp one)"
            )
        if st.get("bundle_schema", 1) > BUNDLE_SCHEMA:
            raise SystemExit(
                f"record: {p}: replay stamp bundle_schema "
                f"{st.get('bundle_schema')} is newer than this build's "
                f"{BUNDLE_SCHEMA} — upgrade tpubench"
            )
        if stamp is None:
            stamp, schema = st, js if isinstance(js, int) else 1
        elif st.get("scenario") != stamp.get("scenario"):
            raise SystemExit(
                f"record: {p}: journal stamps a DIFFERENT scenario than "
                f"{paths[0]} — one bundle per run (sweep points are "
                "separate runs; record them separately)"
            )
    bundle = bundle_from_stamp(stamp, name=name, journal_schema=schema)
    if bundle["name"] == "unnamed":
        # Explicit --name wins, then a replay journal's source bundle
        # name (so re-recording a replay is byte-identical to the
        # original bundle wherever it's written), then the filename.
        bundle["name"] = _derive_name(out_path)
    write_bundle(bundle, out_path)
    return bundle


# ------------------------------------------------------------------ diff --


def _ratio(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None or b <= 0:
        return None
    return a / b


def scorecard_diff(baseline: dict, replayed: dict) -> dict:
    """Replay-vs-original deltas, None-safe: what drifted and by how
    much, in the units the ``--fail-on`` grammar gates on (points of
    SLO, ratios of goodput/p99, raw count deltas)."""
    b, r = baseline or {}, replayed or {}
    gold_delta = None
    if b.get("gold_slo") is not None and r.get("gold_slo") is not None:
        gold_delta = (r["gold_slo"] - b["gold_slo"]) * 100.0
    rewarm_delta = None
    if b.get("rewarm_s") is not None and r.get("rewarm_s") is not None:
        rewarm_delta = r["rewarm_s"] - b["rewarm_s"]
    return {
        "gold_slo_delta_pts": gold_delta,
        "goodput_retention": _ratio(
            r.get("goodput_gbps"), b.get("goodput_gbps")
        ),
        "p99_ratio": _ratio(r.get("p99_ms"), b.get("p99_ms")),
        "gold_p99_ratio": _ratio(
            r.get("gold_p99_ms"), b.get("gold_p99_ms")
        ),
        "completed_delta": (
            r["completed"] - b["completed"]
            if r.get("completed") is not None
            and b.get("completed") is not None else None
        ),
        "shed_delta": (
            r["shed"] - b["shed"]
            if r.get("shed") is not None and b.get("shed") is not None
            else None
        ),
        "errors_delta": (
            r["errors"] - b["errors"]
            if r.get("errors") is not None and b.get("errors") is not None
            else None
        ),
        "rewarm_delta_s": rewarm_delta,
    }


# ------------------------------------------------------------- rendering --


def _pct(v: Optional[float]) -> str:
    return f"{v:.1%}" if v is not None else "n/a"


def format_replay_block(rp: dict) -> str:
    """Human rendering of ``extra["replay"]`` (CLI + ``tpubench
    report``) — original vs replayed side by side, then the diff."""
    b = rp.get("baseline") or {}
    r = rp.get("replayed") or {}
    d = rp.get("diff") or {}
    match = rp.get("config_match")
    lines = [
        f"== replay vs original ({rp.get('bundle', '?')}) ==",
        (
            "  config: "
            + (
                "IDENTICAL (fingerprint "
                f"{rp.get('fingerprint', '?')})" if match else
                f"A/B — original {rp.get('original_fingerprint', '?')} "
                f"vs replay {rp.get('fingerprint', '?')}"
            )
        ),
        (
            f"  arrivals: original={b.get('arrivals')} "
            f"replayed={r.get('arrivals')}"
            + ("" if rp.get("arrivals_match") else "  (MISMATCH)")
        ),
        (
            f"  gold SLO: {_pct(b.get('gold_slo'))} -> "
            f"{_pct(r.get('gold_slo'))}"
            + (
                f"  ({d['gold_slo_delta_pts']:+.1f} pts)"
                if d.get("gold_slo_delta_pts") is not None else ""
            )
        ),
        (
            f"  goodput:  {b.get('goodput_gbps') or 0:.4f} -> "
            f"{r.get('goodput_gbps') or 0:.4f} GB/s"
            + (
                f"  (retention {d['goodput_retention']:.1%})"
                if d.get("goodput_retention") is not None else ""
            )
        ),
        (
            f"  p99:      "
            + (
                f"{b.get('p99_ms'):.1f}ms" if b.get("p99_ms") is not None
                else "n/a"
            )
            + " -> "
            + (
                f"{r.get('p99_ms'):.1f}ms" if r.get("p99_ms") is not None
                else "n/a"
            )
            + (
                f"  ({d['p99_ratio']:.2f}x)"
                if d.get("p99_ratio") is not None else ""
            )
        ),
        (
            f"  completed={b.get('completed')}->{r.get('completed')} "
            f"shed={b.get('shed')}->{r.get('shed')} "
            f"errors={b.get('errors')}->{r.get('errors')}"
        ),
    ]
    if b.get("rewarm_s") is not None or r.get("rewarm_s") is not None:
        lines.append(
            f"  rewarm:   {b.get('rewarm_s')} -> {r.get('rewarm_s')} s"
        )
    return "\n".join(lines)
